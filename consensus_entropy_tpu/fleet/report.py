"""Fleet throughput reporting: users/sec, device-batch occupancy, phases.

The per-user surfaces (text report, ``metrics.jsonl``, ``timings.jsonl``)
are unchanged — each session writes its own, exactly as a sequential run
would.  This module adds the COHORT-level view a serving operator needs:

- one ``metrics.jsonl`` event stream for the fleet itself (dispatches,
  evictions, resumes, per-user completions — and, under the serve layer,
  enqueue/admit events with queue depth and admission wait) at the users
  root,
- an end-of-run summary with users/sec, device-batch occupancy (how full
  the stacked scoring dispatches ran relative to the sessions that could
  have joined them), per-bucket occupancy for bucketed admission, and
  summed per-phase wall-clock across sessions,
- a BENCH-compatible one-line JSON (``bench.py --suite fleet`` writes the
  ``BENCH_fleet_*.json`` artifact from it; ``--suite serve`` the
  ``BENCH_serve_*.json`` one).

Occupancy accounting: every dispatch records the number of ACTIVE slots —
sessions currently holding a seat in the engine (scoring, retraining, or
between steps), with finished, evicted and terminally-failed sessions
excluded from the moment their generator returned.  A cohort that loses a
user therefore stops being graded against the dead slot for the remainder
of the run (``test_fleet_occupancy_excludes_finished_and_evicted`` pins
this), and under bucketed admission the denominator is the active
sessions of that dispatch's OWN bucket.
"""

from __future__ import annotations

import threading
import time

from consensus_entropy_tpu.obs.metrics import EventWriter, MetricsRegistry

#: fn keys of the CNN device-plan dispatches (stored-committee / qbdc
#: probs producers and the cohort retrain) — rolled up separately in the
#: summary so the CNN cohort's ``mean_device_batch`` / occupancy are
#: regression-pinned exactly like the sklearn stacked path's
CNN_DISPATCH_FNS = ("cnn_probs", "qbdc_probs", "cnn_retrain", "cnn_eval")


def _dispatch_rollup(ds: list[dict]) -> dict:
    """The shared per-group dispatch aggregation (used for per-bucket,
    per-CNN-fn and combined roll-ups alike): dispatch count, mean batch,
    and occupancy against the slots active at each dispatch."""
    per = [d["batch"] / d["active"] for d in ds if d["active"]]
    return {
        "dispatches": len(ds),
        "mean_batch": round(sum(d["batch"] for d in ds) / len(ds), 2)
        if ds else None,
        "occupancy": round(sum(per) / len(per), 3) if per else None,
    }


class FleetReport:
    """Collects fleet-run telemetry; optionally streams events to JSONL.

    ``jsonl_path``: fleet-level ``metrics.jsonl`` (the per-user files live
    in the user workspaces).  Engine-side methods run on the scheduler's
    main thread; :meth:`enqueued` may ALSO run on producer threads
    (``FleetServer.submit``), so the event stream and the admission stats
    are guarded by one small lock.
    """

    def __init__(self, jsonl_path: str | None = None):
        self.jsonl_path = jsonl_path
        self.dispatches: list[dict] = []
        self.events: list[dict] = []
        self.phase_totals: dict[str, float] = {}
        self.users_done = 0
        self.users_failed = 0
        #: the obs metrics registry this report's stats live in — every
        #: fleet_metrics.jsonl line now flows through ONE schema-tagged
        #: writer (obs.metrics.EventWriter, schema: 2) instead of
        #: per-append file opens
        self.metrics = MetricsRegistry()
        self.writer = EventWriter(jsonl_path)
        #: serve-layer admission telemetry (empty outside serve mode)
        self.queue_depth = self.metrics.rolling("queue_depth")
        self.admission_wait = self.metrics.rolling("admission_wait_s")
        #: per-user admission-flow latency (FIRST ENQUEUE → user_done /
        #: terminal failure — queue wait included, the user-observed
        #: quantity a latency SLO targets and the quantity priority
        #: classes differentiate; through PR 9 the clock started at
        #: first admit) — log-bucketed histogram with exact p50/p95/p99
        self.admission_latency = self.metrics.histogram(
            "admission_to_finish_s")
        #: per-PRIORITY-CLASS admission→finish histograms (the SLO
        #: planner's acceptance surface: interactive p95 vs batch p95
        #: under load) — created lazily per class seen at admission
        self._class_latency: dict[str, object] = {}
        self._class_of: dict[str, str] = {}
        #: the serve layer's SLO planner (``serve.planner``), installed
        #: by ``FleetServer`` so summaries carry its ``planner`` section
        #: (derived edges, hold activity); None outside planner-enabled
        #: serve runs — fleet summaries stay byte-stable
        self.planner = None
        self._admit_t: dict[str, float] = {}
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()

    # -- recording ---------------------------------------------------------

    def _emit(self, rec: dict) -> None:
        with self._lock:
            self.events.append(rec)
            self.writer.emit(rec)

    def dispatch(self, fn_key: str, batch: int, active: int,
                 wall_s: float, width: int | None = None,
                 h2d_bytes: int | None = None,
                 h2d_ops: int | None = None) -> None:
        """One device scoring dispatch: ``batch`` sessions scored together
        out of ``active`` live slots (cohort-wide, or this bucket's when
        ``width`` identifies a bucketed dispatch).  ``h2d_bytes`` /
        ``h2d_ops``: bytes and discrete uploads this dispatch staged from
        host memory (the fused serve step's target metrics —
        device-resident inputs upload nothing; every host operand is its
        own transfer dispatch on a real accelerator)."""
        rec = {"fn": fn_key, "batch": batch, "active": active,
               "wall_s": wall_s}
        if width is not None:
            rec["width"] = width
        if h2d_bytes is not None:
            rec["h2d_bytes"] = h2d_bytes
        if h2d_ops is not None:
            rec["h2d_ops"] = h2d_ops
        self.dispatches.append(rec)

    def event(self, kind: str, /, **fields) -> None:
        """Cohort-level event (evict / resume / user_done / user_failed /
        enqueue / admit / drain / compile / alert).  ``kind`` is
        positional-ONLY so a payload field may itself be named ``kind``
        (the ``alert`` events carry one)."""
        self._emit({"event": kind, "t_s": round(self.elapsed_s(), 3),
                    **fields})

    def enqueued(self, user, depth: int, cls: str = "batch") -> None:
        """A user entered the serve-layer waiting queue (depth AFTER),
        in priority class ``cls``.  May be called from producer threads
        (``FleetServer.submit``).  The FIRST enqueue starts the user's
        admission-flow latency clock (queue wait counts — it is what
        priority buys); backoff re-enqueues continue the original one."""
        with self._lock:
            self.queue_depth.add(depth)
            self._admit_t.setdefault(str(user), time.perf_counter())
        self.event("enqueue", user=str(user), depth=depth, cls=cls)

    def admitted(self, user, *, width: int, wait_s: float, depth: int,
                 live: int, cls: str = "batch") -> None:
        """A queued user was admitted into the engine: its bucket width,
        priority class, how long it waited in the queue, the queue depth
        left behind and the live-session count after admission."""
        with self._lock:
            self.admission_wait.add(wait_s)
            self.queue_depth.add(depth)
            # normally the first ENQUEUE already started the latency
            # clock; the setdefault covers drivers that admit without
            # enqueueing (backoff re-admissions continue the original
            # clock either way — the user-observed latency includes its
            # failures)
            self._admit_t.setdefault(str(user), time.perf_counter())
            self._class_of.setdefault(str(user), cls)
            if cls not in self._class_latency:
                self._class_latency[cls] = self.metrics.histogram(
                    f"admission_to_finish_s.{cls}")
        self.event("admit", user=str(user), width=width,
                   wait_s=round(wait_s, 4), depth=depth, live=live,
                   cls=cls)

    def _finish_latency(self, user) -> None:
        with self._lock:
            t = self._admit_t.pop(str(user), None)
            if t is not None:
                latency = time.perf_counter() - t
                self.admission_latency.add(latency)
                cls = self._class_of.get(str(user))
                if cls in self._class_latency:
                    self._class_latency[cls].add(latency)

    def user_done(self, user, result: dict, phases: dict) -> None:
        """A session finished; ``phases`` are its summed ``{phase}_s``
        durations (from the session's ``StepTimer`` records)."""
        self.users_done += 1
        self._finish_latency(user)
        for k, v in phases.items():
            self.phase_totals[k] = self.phase_totals.get(k, 0.0) + v
        self.event("user_done", user=str(user),
                   final_mean_f1=result.get("final_mean_f1"),
                   epochs=len(result.get("trajectory", [])))

    def user_failed(self, user, error: str,
                    attempts: int | None = None) -> None:
        """A user failed TERMINALLY (every in-engine resume and — under
        the serve layer — every backoff re-admission exhausted).  The
        reason and the attempt count land in the metrics stream, not just
        the result record, so an operator tailing ``fleet_metrics.jsonl``
        sees WHY a user dropped."""
        self.users_failed += 1
        self._finish_latency(user)
        rec = {"user": str(user), "error": error}
        if attempts is not None:
            rec["attempts"] = attempts
        self.event("user_failed", **rec)

    def class_p95s(self) -> dict:
        """``{class: observed p95 admission→finish latency}`` (``None``
        before a class resolved anyone) — the SLO burn-rate alert
        kernel's input.  Thread-safe."""
        with self._lock:
            return {cls: h.percentile(95)
                    for cls, h in self._class_latency.items()}

    def elapsed_s(self) -> float:
        return time.perf_counter() - self._t0

    # -- summaries ---------------------------------------------------------

    @property
    def occupancy(self) -> float | None:
        """Mean scored-sessions per dispatch over the slots ACTIVE at that
        moment: 1.0 = every dispatch scored every active session at once
        (perfect phase alignment); 1/active = fully serialized (the
        sequential shape).  Finished/evicted sessions stopped counting
        when their generator returned (see module docstring)."""
        return _dispatch_rollup(self.dispatches)["occupancy"]

    @property
    def per_bucket_occupancy(self) -> dict | None:
        """``{width: {"occupancy", "dispatches", "mean_batch"}}`` for
        bucketed (width-tagged) dispatches; ``None`` when none were."""
        buckets: dict[int, list[dict]] = {}
        for d in self.dispatches:
            if "width" in d:
                buckets.setdefault(d["width"], []).append(d)
        if not buckets:
            return None
        return {w: _dispatch_rollup(ds) for w, ds in sorted(buckets.items())}

    @property
    def transfer_summary(self) -> dict | None:
        """Host↔device traffic roll-up of the run's dispatches — the
        overhead the fused serve step removes, pinned here (and in every
        BENCH artifact via :func:`bench_line`) the way parity is, because
        bytes-per-iteration and dispatches-per-iteration are
        capacity-INDEPENDENT on a throttled CI box whose users/sec drifts
        ~2x run to run.

        - ``h2d_bytes`` / ``h2d_bytes_per_select``: host-memory bytes
          uploaded by device dispatches, total and per session-iteration
          (fused runs upload only each iteration's probs delta; unfused
          runs re-ship probs tables and masks every select).
        - ``h2d_ops`` / ``h2d_ops_per_select``: discrete host→device
          uploads — each is its own transfer dispatch on a real
          accelerator.
        - ``selects``: session-iterations serviced (sum of reduction
          dispatch batches); ``device_calls_per_select``: device
          dispatches per session-iteration — jit executions (the
          reduction dispatch, amortized by stacking) PLUS the transfer
          ops, the figure the fused step shrinks.

        ``None`` when no dispatch carried transfer accounting (records
        replayed from pre-metric artifacts), so old summaries stay
        byte-stable."""
        graded = [d for d in self.dispatches if "h2d_bytes" in d]
        if not graded:
            return None
        red = [d for d in self.dispatches
               if d["fn"] not in CNN_DISPATCH_FNS]
        selects = sum(d["batch"] for d in red)
        h2d = sum(d.get("h2d_bytes") or 0 for d in self.dispatches)
        ops = sum(d.get("h2d_ops") or 0 for d in self.dispatches)
        out = {"h2d_bytes": h2d, "h2d_ops": ops, "selects": selects}
        if selects:
            out["h2d_bytes_per_select"] = round(h2d / selects)
            out["h2d_ops_per_select"] = round(ops / selects, 3)
            out["device_calls_per_select"] = round(
                (len(red) + ops) / selects, 3)
        return out

    @property
    def cnn_dispatch_summary(self) -> dict | None:
        """Roll-up of the CNN device-plan dispatches (:data:`CNN_DISPATCH_FNS`)
        — per fn: dispatch count, mean users per dispatch, occupancy
        against the active slots — plus the combined ``mean_device_batch``.
        ``None`` when the run had no CNN dispatches, so host-only fleet
        summaries (and committed BENCH artifacts) stay byte-stable."""
        cnn = [d for d in self.dispatches if d["fn"] in CNN_DISPATCH_FNS]
        if not cnn:
            return None
        combined = _dispatch_rollup(cnn)
        out = {"dispatches": combined["dispatches"],
               "mean_device_batch": combined["mean_batch"]}
        if combined["occupancy"] is not None:
            out["occupancy"] = combined["occupancy"]
        for fn in CNN_DISPATCH_FNS:
            ds = [d for d in cnn if d["fn"] == fn]
            if ds:
                out[fn] = _dispatch_rollup(ds)
        return out

    def summary(self, *, cohort: int, wall_s: float | None = None) -> dict:
        """Cohort roll-up.  ``phase_wall_s`` sums the sessions' OWN timers
        — session-observed latency, so in fleet mode a phase that spans a
        scheduler hand-off (notably ``select_s``, which covers staging →
        batched dispatch → id mapping) includes scheduling/batch-window
        wait.  ``dispatch_wall_s`` is the scheduler-side device dispatch
        time alone — compare the two to attribute queueing vs compute."""
        wall = self.elapsed_s() if wall_s is None else wall_s
        batches = [d["batch"] for d in self.dispatches]
        out = {
            "cohort": cohort,
            "users_done": self.users_done,
            "users_failed": self.users_failed,
            "wall_s": round(wall, 3),
            "users_per_sec": round(self.users_done / wall, 4) if wall
            else None,
            "score_dispatches": len(batches),
            "dispatch_wall_s": round(sum(d["wall_s"]
                                         for d in self.dispatches), 3),
            "mean_device_batch": round(sum(batches) / len(batches), 2)
            if batches else None,
            "occupancy": round(self.occupancy, 3)
            if self.occupancy is not None else None,
            "phase_wall_s": {k: round(v, 3)
                             for k, v in sorted(self.phase_totals.items())},
            "evictions": sum(e["event"] == "evict" for e in self.events),
            "resumes": sum(e["event"] == "resume" for e in self.events),
        }
        # serve-layer fault-domain counters, present only when the run
        # exercised them — pre-existing fleet/serve summaries (and the
        # committed BENCH artifacts) stay byte-stable
        for key, event in (("watchdog_evictions", "watchdog_evict"),
                           ("breaker_trips", "breaker_open"),
                           ("breaker_giveups", "breaker_giveup"),
                           ("dispatch_failures", "dispatch_failed"),
                           ("requeues", "requeue"),
                           ("users_poisoned", "poison")):
            n = sum(e["event"] == event for e in self.events)
            if n:
                out[key] = n
        compiles = [e for e in self.events if e.get("event") == "compile"]
        if compiles:
            # jit-compile telemetry (obs.jit_telemetry → the scheduler's
            # compile events): family builds, dispatch-attributed XLA
            # compiles and their summed wall — the cost feed the SLO
            # planner's cost-aware-edges follow-on reads; absent when no
            # family was built this run, so warm-cache summaries (and
            # committed BENCH artifacts) stay byte-stable
            out["jit"] = {
                "events": len(compiles),
                "builds": sum(1 for e in compiles
                              if e.get("phase") == "build"),
                "xla_compiles": sum(1 for e in compiles
                                    if e.get("phase") == "xla"),
                "compile_wall_s": round(sum(e.get("build_s") or 0.0
                                            for e in compiles), 4),
                "resident": max((e.get("resident") or 0
                                 for e in compiles), default=0),
            }
        per_bucket = self.per_bucket_occupancy
        if per_bucket is not None:
            out["per_bucket"] = per_bucket
        cnn = self.cnn_dispatch_summary
        if cnn is not None:
            out["cnn"] = cnn
        transfer = self.transfer_summary
        if transfer is not None:
            out["transfer"] = transfer
        if self.admission_wait.n:
            out["admissions"] = self.admission_wait.n
            out["admission_wait_s"] = self.admission_wait.snapshot()
            out["queue_depth"] = self.queue_depth.snapshot()
        if self.admission_latency.n:
            # per-user admission→finish latency (exact p50/p95/p99 while
            # the reservoir holds) — the SLO planner's input; absent
            # outside serve mode so fleet summaries stay byte-stable
            out["admission_to_finish_s"] = self.admission_latency.snapshot()
        if self._class_latency:
            # the per-PRIORITY-CLASS shape of the same histogram — the
            # SLO acceptance surface (interactive p95 <= batch p95 under
            # load); absent outside class-aware serve runs
            out["per_class"] = {}
            for cls, h in sorted(self._class_latency.items()):
                snap = h.snapshot()
                # "users" counts RESOLVED users (finished or terminally
                # failed — the histogram's population), matching its n;
                # successes alone are the top-level users_done
                out["per_class"][cls] = {
                    "users": snap["n"] if snap else 0,
                    "admission_to_finish_s": snap}
        if self.planner is not None:
            # the SLO planner's own section: derived edges, epoch count,
            # hold activity (serve.planner.AdmissionPlanner.summary)
            out["planner"] = self.planner.summary()
        return out

    def write_summary(self, *, cohort: int, wall_s: float | None = None) -> dict:
        """Emit the summary as the final JSONL event and return it."""
        s = self.summary(cohort=cohort, wall_s=wall_s)
        self._emit({"event": "fleet_summary", **s})
        return s

    def close(self) -> None:
        """Release the event writer's file handle (flushed per record
        throughout, so closing is hygiene, not durability)."""
        self.writer.close()


def bench_line(summary: dict, *, baseline_users_per_sec: float | None = None,
               extra: dict | None = None) -> dict:
    """Shape a fleet summary into the repo's BENCH JSON-line schema
    (``{"metric", "value", "unit", "vs_baseline", ...}``) so
    ``BENCH_fleet_*.json`` artifacts sit beside the scoring/retrain ones."""
    ups = summary.get("users_per_sec")
    line = {
        "metric": f"fleet_users_per_sec_n{summary.get('cohort')}",
        "value": ups,
        "unit": "users/s",
        "vs_baseline": (round(ups / baseline_users_per_sec, 2)
                        if ups and baseline_users_per_sec else None),
        "occupancy": summary.get("occupancy"),
        "users_done": summary.get("users_done"),
        "evictions": summary.get("evictions"),
        "phase_wall_s": summary.get("phase_wall_s"),
    }
    if summary.get("per_bucket") is not None:
        line["per_bucket"] = summary["per_bucket"]
    if summary.get("cnn") is not None:
        line["cnn"] = summary["cnn"]
    if summary.get("transfer") is not None:
        line["transfer"] = summary["transfer"]
    if summary.get("admission_to_finish_s") is not None:
        line["admission_to_finish_s"] = summary["admission_to_finish_s"]
    if summary.get("per_class") is not None:
        line["per_class"] = summary["per_class"]
    if summary.get("planner") is not None:
        line["planner"] = summary["planner"]
    for key in ("watchdog_evictions", "breaker_trips", "dispatch_failures",
                "requeues", "users_poisoned"):
        if summary.get(key):
            line[key] = summary[key]
    if extra:
        line.update(extra)
    return line
