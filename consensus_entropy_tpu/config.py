"""Typed configuration for the framework.

Replaces the reference's flat star-imported constants module (``settings.py``,
star-imported at ``amg_test.py:38`` / ``deam_classifier.py:38``) with frozen
dataclasses.  Every default mirrors the reference value and cites its source.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Literal

#: The paper's four acquisition modes plus the framework's registry
#: extensions (``consensus_entropy_tpu.acquire``): ``qbdc`` = query-by-
#: dropout-committee (one CNN + K seeded dropout masks, arxiv 1511.06412),
#: ``wmc`` = weighted machine consensus (per-member reliability weights in
#: the renormalized entropy reduction, arxiv 2011.06086).
AcquisitionMode = Literal["mc", "hc", "mix", "rand", "qbdc", "wmc"]

#: Quadrant label codec — ``amg_test.py:54`` (``{'Q1': 0, ... 'Q4': 3}``).
QUADRANT_TO_CLASS = {"Q1": 0, "Q2": 1, "Q3": 2, "Q4": 3}
CLASS_TO_QUADRANT = {v: k for k, v in QUADRANT_TO_CLASS.items()}
NUM_CLASSES = 4


def stft_frame_count(length: int, n_fft: int, hop: int) -> int:
    """Frame count of the centered STFT (torchaudio-default geometry):
    ``(length + 2*(n_fft//2)) // hop - 1`` — 231 for the canonical
    59049-sample crop.  Canonical definition; ``ops.mel.n_frames_for``
    delegates here (config must not import ops.mel: its module-level
    ``CNNConfig()`` defaults would recurse into this file mid-import)."""
    return (length + 2 * (n_fft // 2)) // hop - 1

#: Feature-column slice bounds used for both DEAM and AMG openSMILE features
#: (``amg_test.py:64``, ``deam_classifier.py:182-185``).
FEATURE_SLICE_START = "F0final_sma_stddev"
FEATURE_SLICE_STOP = "mfcc_sma_de[14]_amean"
FEATURE_SLICE_STOP_FFTMAG = "pcm_fftMag_mfcc_sma_de[14]_amean"
NUM_FEATURES = 260  # verified from the shipped GNB pickle (n_features_in_=260)


def feature_slice(df):
    """The 260-column openSMILE feature slice of a DEAM/AMG frame table.

    openSMILE emitted two column-name vintages for the same features — the
    newer prefixes the mfcc block with ``pcm_fftMag_`` — so the stop column
    is dispatched on whichever is present (shared by ``data/amg.py`` and
    ``data/deam.py``; the reference hardcodes one vintage per script,
    ``amg_test.py:64`` / ``deam_classifier.py:182-185``).
    """
    if FEATURE_SLICE_STOP_FFTMAG in df.columns:
        return df.loc[:, FEATURE_SLICE_START:FEATURE_SLICE_STOP_FFTMAG]
    if FEATURE_SLICE_STOP in df.columns:
        return df.loc[:, FEATURE_SLICE_START:FEATURE_SLICE_STOP]
    raise ValueError("unrecognized feature columns (expected the openSMILE "
                     f"slice to end at {FEATURE_SLICE_STOP!r} or "
                     f"{FEATURE_SLICE_STOP_FFTMAG!r})")


@dataclasses.dataclass(frozen=True)
class PathsConfig:
    """Dataset / model-store locations (``settings.py:11-33``)."""

    models_root: str = "./models"
    deam_root: str = "./data/deam"
    amg_root: str = "./data/amg1608"

    @property
    def pretrained_dir(self) -> str:
        return os.path.join(self.models_root, "pretrained")

    @property
    def users_dir(self) -> str:
        return os.path.join(self.models_root, "users")

    @property
    def deam_features_dir(self) -> str:
        return os.path.join(self.deam_root, "features")

    @property
    def deam_dataset_csv(self) -> str:
        return os.path.join(self.deam_root, "dataset_quads.csv")

    @property
    def deam_npy_dir(self) -> str:
        return os.path.join(self.deam_root, "npy")

    @property
    def amg_features_dir(self) -> str:
        return os.path.join(self.amg_root, "feats")

    @property
    def amg_dataset_csv(self) -> str:
        return os.path.join(self.amg_root, "dataset_feats.csv")

    @property
    def amg_npy_dir(self) -> str:
        return os.path.join(self.amg_root, "npy")

    @property
    def amg_annotations_mat(self) -> str:
        return os.path.join(self.amg_root, "anno", "AMG1608.mat")

    @property
    def amg_mapping_mat(self) -> str:
        return os.path.join(self.amg_root, "anno", "1608_song_id.mat")


@dataclasses.dataclass(frozen=True)
class CNNConfig:
    """ShortChunkCNN architecture hyperparameters.

    Mirrors ``short_cnn.py:284-291`` (constructor defaults) and
    ``settings.py:36`` (``input_length``).  ``n_layers`` is configurable here
    (the reference hard-codes 7) so tests can use tiny inputs.
    """

    n_channels: int = 128
    sample_rate: int = 16000
    n_fft: int = 512
    hop_length: int = 256  # torchaudio default: n_fft // 2
    f_min: float = 0.0
    f_max: float = 8000.0
    n_mels: int = 128
    n_class: int = NUM_CLASSES
    n_layers: int = 7
    input_length: int = 59049  # ~3.69 s @ 16 kHz
    dropout_rate: float = 0.5
    #: Compute dtype for conv/dense (MXU-friendly); params stay float32.
    compute_dtype: str = "float32"
    #: Device CNN family: ``vgg`` = conv→BN→ReLU→maxpool blocks (the paper's
    #: ShortChunkCNN, ``short_cnn.py:278-349``); ``res`` = residual blocks
    #: with stride-2 downsampling (the ShortChunkCNN_Res family whose
    #: ``Res_2d`` block the reference vendors unused, ``short_cnn.py:40-66``);
    #: ``harm`` = the vgg trunk over a LEARNABLE harmonic-filterbank frontend
    #: (the vendored ``HarmonicSTFT``, ``short_cnn.py:166-275``) instead of
    #: log-mel — harmonics become the trunk's input channels; ``se1d`` =
    #: sample-level squeeze-excitation residual 1-D trunk on the RAW
    #: waveform (the vendored ``ResSE_1d``, ``short_cnn.py:85-125``; the
    #: 59049-sample crop is 3^10, built for its /3-per-stage geometry).
    arch: str = "vgg"
    #: ``harm`` frontend geometry (``short_cnn.py:199-210`` defaults).
    n_harmonic: int = 6
    semitone_scale: int = 2
    bw_q_init: float = 1.0

    def __post_init__(self):
        if self.arch not in ("vgg", "res", "harm", "se1d", "musicnn"):
            raise ValueError(f"arch must be one of 'vgg', 'res', 'harm', "
                             f"'se1d', 'musicnn'; got {self.arch!r}")
        if self.arch == "res":
            return  # stride-2 convs ceil-halve dims; they never hit zero
        if self.arch == "musicnn":
            # multi-shape front-end keeps time; the mid-end halves it per
            # layer (frequency is fully pooled by the front-end)
            t = self._n_frames
            for layer in range(self.n_layers):
                t //= 2
                if t == 0:
                    raise ValueError(
                        f"musicnn geometry collapses at mid-end layer "
                        f"{layer + 1}: input_length={self.input_length} "
                        f"survives only {layer} of {self.n_layers} 2x pools")
            return
        if self.arch == "se1d":
            # stem (stride 3) + n_layers 3x max-pools each divide time by 3
            t = self.input_length // 3
            for layer in range(self.n_layers):
                t //= 3
                if t == 0:
                    raise ValueError(
                        f"se1d geometry collapses at block {layer + 1}: "
                        f"input_length={self.input_length} survives only "
                        f"{layer} of {self.n_layers} 3x pools after the "
                        f"stride-3 stem")
            return
        # Fail fast if the pooling pyramid collapses a spatial dim to zero
        # (the reference hard-codes a geometry where this can't happen:
        # 128 mels × 231 frames through 7 2×2 pools → 1×1).  The harm
        # frontend's frequency axis is its note-grid level, not n_mels.
        f = self.n_mels if self.arch == "vgg" else self.harm_level
        t = self._n_frames
        for layer in range(self.n_layers):
            f, t = f // 2, t // 2
            if f == 0 or t == 0:
                raise ValueError(
                    f"CNN geometry collapses at layer {layer + 1}: "
                    f"freq={self.n_mels if self.arch == 'vgg' else self.harm_level}, "
                    f"input_length={self.input_length} "
                    f"survive only {layer} of {self.n_layers} 2x2 pools")

    @property
    def _n_frames(self) -> int:
        """Spectrogram frame count (single source: :func:`stft_frame_count`;
        ``ops.mel.n_frames_for`` delegates here)."""
        return stft_frame_count(self.input_length, self.n_fft,
                                self.hop_length)

    @property
    def harm_level(self) -> int:
        """Frequency-axis height of the ``harm`` frontend (note-grid size;
        128 at the default sr/harmonics/scale — same as n_mels)."""
        from consensus_entropy_tpu.ops.harmonic import harmonic_center_freqs

        return harmonic_center_freqs(self.sample_rate, self.n_harmonic,
                                     self.semitone_scale)[1]

    @property
    def channel_widths(self) -> tuple[int, ...]:
        """Per-layer output channels: 128,128,256,256,256,256,512 for the
        default config (``short_cnn.py:304-310``)."""
        widths = []
        for i in range(self.n_layers):
            if i < 2:
                widths.append(self.n_channels)
            elif i < self.n_layers - 1:
                widths.append(self.n_channels * 2)
            else:
                widths.append(self.n_channels * 4)
        return tuple(widths)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """CNN training hyperparameters (``settings.py:36-42``)."""

    n_epochs: int = 200  # pre-training (n_epochs_cnn)
    n_epochs_retrain: int = 100  # AL incremental retraining
    batch_size: int = 5
    lr: float = 1e-4
    weight_decay: float = 1e-4  # Adam weight_decay (amg_test.py:281)
    log_step: int = 20
    #: Epochs-since-last-transition before each optimizer transition (the
    #: reference's ``drop_counter`` resets only at transitions, never on
    #: improvement — ``amg_test.py:203-231``).  Pre-training uses 40 for the
    #: adam→sgd step (``deam_classifier.py:150``); retraining uses 20
    #: (``amg_test.py:205``).  Subsequent lr drops are always 20 epochs.
    adam_patience: int = 20
    sgd_patience: int = 20
    sgd_momentum: float = 0.9
    sgd_weight_decay: float = 1e-4
    sgd_lrs: tuple[float, ...] = (1e-3, 1e-4, 1e-5)
    #: Run the member-sharded MESH retrain with one scanned jit per schedule
    #: phase (like the single-chip fast path) instead of one jit per epoch.
    #: Off by default: the virtual-CPU mesh backend — the multichip
    #: validation gate — is unstable compiling scan(vmap(epoch)) with member
    #: shardings under full-suite executable accumulation (see
    #: tests/conftest.py), so the CPU-mesh suite keeps per-epoch dispatch.
    #: On real TPU meshes flip this on to collapse ~n_epochs dispatch
    #: round-trips to <=4 per retrain; numerics are equivalent to per-epoch
    #: within rtol 1e-5 (parity pinned on a 1-device mesh by
    #: tests/test_cnn_trainer.py::test_fit_many_scanned_mesh_matches_per_epoch).
    scan_mesh_phases: bool = False


@dataclasses.dataclass(frozen=True)
class ALConfig:
    """Active-learning experiment parameters (CLI surface of
    ``amg_test.py:545-573``)."""

    queries: int = 10  # -q
    epochs: int = 10  # -e
    mode: AcquisitionMode = "mc"  # -m
    num_anno: int = 150  # -n: min annotations per user
    train_size: float = 0.85  # GroupShuffleSplit (amg_test.py:363)
    seed: int = 1987  # amg_test.py:55 (global numpy seed in the reference)
    #: On-disk dtype of the per-iteration CNN checkpoint fetch.  The
    #: reference persists f32 torch weights every iteration
    #: (``amg_test.py:511``); here the deferred device→host fetch is the
    #: dominant warm-iteration cost on thin links, and bf16 halves the
    #: bytes.  Restore casts back to f32; a crash-resume therefore rounds
    #: member weights to bf16 (probability error ~2e-4 at the measured
    #: gate — BENCH_cnn bf16_gate), while an uninterrupted run is
    #: unaffected.  Set "float32" for bit-exact resume.
    ckpt_dtype: str = "bfloat16"
    #: Survivor floor for member quarantine: a member whose retrain/predict
    #: raises (or emits non-finite probabilities) is quarantined for the
    #: rest of the user's run and the consensus renormalizes over the
    #: survivors; the run aborts (CommitteeExhaustedError) only when fewer
    #: than this many members remain.  The committee-ensemble argument for
    #: tolerating member loss is "Wisdom of Committees" (PAPERS.md).
    min_members: int = 1
    #: Bounded retry for transient device/RPC errors at the (pure) scoring
    #: and CNN-retrain call sites: attempts and base backoff delay; the
    #: exponential backoff is jittered and seeded (resilience.retry).
    retry_attempts: int = 3
    retry_base_delay: float = 0.05
    #: ``qbdc`` mode: committee width K — the number of seeded dropout
    #: masks the single personalized CNN is forwarded under (the committee
    #: axis of the consensus entropy; the paper's stored committee is 20
    #: models, so 20 is the like-for-like default).  Storage/compute shape:
    #: one set of CNN weights regardless of K — K only widens a vmap over
    #: dropout heads (``short_cnn.qbdc_infer``).
    qbdc_k: int = 20
    #: ``wmc`` mode: how per-member reliability weights evolve.
    #: ``agreement`` — after each reveal, member m's weight moves toward
    #: its fraction of correctly-predicted queried songs by an EMA with
    #: ``consensus_weight_alpha`` (weights start at 1.0 = plain mc);
    #: ``uniform`` — weights stay 1.0 forever, so wmc is exactly mc
    #: (the equal-weights reduction is pinned bit-identical by tests).
    consensus_weighting: Literal["agreement", "uniform"] = "agreement"
    #: EMA step for the ``agreement`` weight update (0 freezes weights).
    consensus_weight_alpha: float = 0.5
    #: Validation-gate the host members' incremental updates (keep an
    #: update only if the member's weighted F1 on the user's test split
    #: does not drop) — the host analogue of the reference's CNN
    #: best-checkpoint gate (``amg_test.py:267-273``, which scores on the
    #: same split).  Off by default: the reference applies every
    #: partial_fit/boost unconditionally (``amg_test.py:503-509``), and
    #: the round-5 evidence measures what that costs under
    #: uncertainty-dense batches (EVIDENCE_r05 mechanism_study).
    gate_host_updates: bool = False

    def __post_init__(self):
        if self.consensus_weighting not in ("agreement", "uniform"):
            # a typo here would silently freeze wmc weights at uniform
            # (the update hook no-ops on anything but "agreement")
            raise ValueError(
                f"consensus_weighting must be 'agreement' or 'uniform'; "
                f"got {self.consensus_weighting!r}")
        if self.qbdc_k < 1:
            raise ValueError(
                f"qbdc_k (dropout committee width) must be >= 1; "
                f"got {self.qbdc_k}")
        if not 0.0 <= self.consensus_weight_alpha <= 1.0:
            # >1 can drive weights negative (negative/zero normalizer)
            raise ValueError(
                f"consensus_weight_alpha must be in [0, 1]; "
                f"got {self.consensus_weight_alpha}")


@dataclasses.dataclass(frozen=True)
class ScoringConfig:
    """Configuration of the fused pool-scoring graph (the north-star kernel).

    ``pad_pool_to`` fixes the pool axis so the jit graph never recompiles as
    the pool shrinks by ``queries`` songs per AL iteration — invalidated songs
    are masked instead (SURVEY.md §7 hard part 1).  Consumed by
    ``Acquirer(pad_to=...)`` / the AL CLI's ``--pad-pool-to``: padding every
    user's pool to this one width makes the scoring graph compile once
    across users.
    """

    pad_pool_to: int = 2048
    #: Tie policy for the ``np.argsort(ent)[::-1]`` ranking (``amg_test.py:445``;
    #: the reference's own tie order is implementation-defined introsort).
    #: 'numpy' = reversed stable sort (highest index wins ties); 'fast' =
    #: ``lax.top_k`` (lowest index wins).  Entropy values identical either way.
    tie_break: Literal["numpy", "fast"] = "fast"
    compute_dtype: str = "float32"


@dataclasses.dataclass(frozen=True)
class Config:
    """Top-level aggregate."""

    paths: PathsConfig = dataclasses.field(default_factory=PathsConfig)
    cnn: CNNConfig = dataclasses.field(default_factory=CNNConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    al: ALConfig = dataclasses.field(default_factory=ALConfig)
    scoring: ScoringConfig = dataclasses.field(default_factory=ScoringConfig)
