"""Mesh construction.

Axis vocabulary (used consistently across the framework):

- ``pool``   — the unlabeled-pool axis (N songs).  This is where scale lives
  in this problem (SURVEY.md §5: "Scale in this problem is along the pool
  axis, not sequence"); sharded across chips for scoring.
- ``member`` — the committee axis (M models).  CNN members are stacked
  pytrees ``vmap``'d over this axis; sharding it parallelizes committee
  retraining (each chip trains a subset of members).
- ``dp``     — batch data-parallel axis for CNN (re)training.

Sequence/context parallelism (ring attention, Ulysses) is genuinely N/A —
there is no attention anywhere in the model family (largest member is a
~10M-param CNN on 3.69 s audio crops); documented rather than silently
omitted, per SURVEY.md §2.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh

POOL_AXIS = "pool"
MEMBER_AXIS = "member"
DP_AXIS = "dp"
SEQ_AXIS = "seq"


def make_pool_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, pool axis only.

    Used by the scoring path: committee probs ``(M, N, C)`` are sharded on
    ``N``; the consensus mean and entropy are row-local (zero communication),
    and only the final top-k gathers ``k`` candidates per chip over ICI.
    """
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (POOL_AXIS,))


def make_seq_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or the given) devices, sequence axis only.

    Used by the long-audio path (``parallel.sequence``): a full song's
    analysis windows are distributed contiguously across chips, with the
    window-overlap halo exchanged between ring neighbors over ICI.
    """
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (SEQ_AXIS,))


def make_training_mesh(dp: int | None = None, member: int | None = None,
                       devices=None) -> Mesh:
    """2-D ``(dp, member)`` mesh for committee training.

    Default factorization: put as many chips as divide the committee on the
    ``member`` axis and the rest on ``dp``.
    """
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None and member is None:
        member = _largest_divisor_at_most(n, 4)
        dp = n // member
    elif dp is None:
        dp = n // member  # type: ignore[operator]
    elif member is None:
        member = n // dp
    if dp * member != n:
        raise ValueError(f"dp*member = {dp}*{member} != {n} devices")
    return Mesh(np.asarray(devices).reshape(dp, member), (DP_AXIS, MEMBER_AXIS))


def _largest_divisor_at_most(n: int, cap: int) -> int:
    for d in range(min(cap, n), 0, -1):
        if n % d == 0:
            return d
    return 1
