"""Sharded variants of the fused scoring graph.

Two execution styles, same semantics as ``ops.scoring``:

1. :func:`make_sharded_scoring_fns` — idiomatic ``jit`` + ``NamedSharding``
   annotations; XLA propagates shardings through mean/entropy (row-local, no
   communication) and inserts the gather that top-k needs.

2. :func:`make_shardmap_mc_scorer` — explicit ``shard_map`` two-stage top-k
   for the hot mc path: each chip top-k's its own pool shard (k candidates),
   ``all_gather`` of ``k × n_chips`` candidates over ICI, then a final
   replicated top-k.  Communication is ``O(k · D)`` instead of ``O(N)``, which
   matters at the 100k-excerpt benchmark scale (BASELINE.json configs[4]).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_entropy_tpu.parallel._compat import shard_map

from consensus_entropy_tpu.ops.entropy import masked_entropy
from consensus_entropy_tpu.ops.scoring import (
    ScoreResult,
    consensus_mean,
    score_hc,
    score_hc_precomputed,
    score_mc,
    score_mix,
    score_qbdc,
    score_rand,
    score_wmc,
)
from consensus_entropy_tpu.parallel.mesh import POOL_AXIS


def make_sharded_scoring_fns(mesh: Mesh, *, k: int, tie_break: str = "fast"):
    """Jit the four acquisition scorers with pool-axis sharding constraints.

    Input layout: ``member_probs (M, N, C)`` sharded on N; masks ``(N,)``
    sharded; hc table ``(N, C)`` sharded on N.  Results replicate (they are
    ``k``-sized or consumed host-side).  ``N`` must be divisible by the mesh's
    pool-axis size (the pad-to-fixed-shape step guarantees this).

    ``lru_cache`` (``Mesh`` hashes by value, so an equal mesh rebuilt per
    user still hits): a fresh jit per ``Acquirer`` would recompile the
    sharded scoring graphs once per user of the 46-user AL run.  Callers
    must not mutate the returned dict.  The wrapper normalizes the call
    signature before the cache (see :func:`ops.scoring.make_scoring_fns`).
    """
    return _make_sharded_scoring_fns_cached(mesh, k, tie_break)


@functools.lru_cache(maxsize=None)
def _make_sharded_scoring_fns_cached(mesh: Mesh, k: int, tie_break: str):
    probs_s = NamedSharding(mesh, P(None, POOL_AXIS, None))
    vec_s = NamedSharding(mesh, P(POOL_AXIS))
    table_s = NamedSharding(mesh, P(POOL_AXIS, None))
    repl = NamedSharding(mesh, P())
    out_s = ScoreResult(entropy=vec_s, values=repl, indices=repl)
    mix_out_s = ScoreResult(entropy=repl, values=repl, indices=repl)

    mc = jax.jit(
        functools.partial(score_mc, k=k, tie_break=tie_break),
        in_shardings=(probs_s, vec_s), out_shardings=out_s)
    hc = jax.jit(
        functools.partial(score_hc, k=k, tie_break=tie_break),
        in_shardings=(table_s, vec_s), out_shardings=out_s)
    hc_pre = jax.jit(
        functools.partial(score_hc_precomputed, k=k, tie_break=tie_break),
        in_shardings=(vec_s, vec_s), out_shardings=out_s)
    # mix concatenates the mc block and hc block along the row axis; the
    # concatenated entropy is left replicated (its layout is irregular).
    mix = jax.jit(
        functools.partial(score_mix, k=k, tie_break=tie_break),
        in_shardings=(probs_s, vec_s, table_s, vec_s),
        out_shardings=mix_out_s)
    rand = jax.jit(functools.partial(score_rand, k=k),
                   in_shardings=(repl, vec_s), out_shardings=out_s)
    # registry extensions: qbdc shards exactly like mc (the committee axis
    # holds K dropout forwards); wmc adds a tiny replicated weights vector
    qbdc = jax.jit(
        functools.partial(score_qbdc, k=k, tie_break=tie_break),
        in_shardings=(probs_s, vec_s), out_shardings=out_s)
    wmc = jax.jit(
        functools.partial(score_wmc, k=k, tie_break=tie_break),
        in_shardings=(probs_s, vec_s, repl), out_shardings=out_s)
    return {"mc": mc, "hc": hc, "hc_pre": hc_pre, "mix": mix,
            "rand": rand, "qbdc": qbdc, "wmc": wmc}


def _merge_local_topk(v, i, local_n: int, k: int):
    """Shared candidate merge: globalize local indices, all_gather the k
    candidates per chip over ICI (O(k·D) traffic), final replicated top-k.
    Tiles/rows are gathered in shard order and ``lax.top_k`` is index-stable,
    so ties resolve to the lowest global index."""
    gi = i + lax.axis_index(POOL_AXIS) * local_n
    vg = lax.all_gather(v, POOL_AXIS, tiled=True)
    ig = lax.all_gather(gi, POOL_AXIS, tiled=True)
    vv, j = lax.top_k(vg, k)
    return vv, jnp.take(ig, j)


def make_shardmap_mc_scorer(mesh: Mesh, *, k: int):
    """Explicit-collective mc scorer: local top-k → all_gather → global top-k.

    Tie semantics are 'fast' (lowest global index wins): candidates are
    gathered in shard order and ``lax.top_k`` is index-stable, so the global
    winner among equal values is the lowest global index — matching the
    single-device 'fast' path.
    """
    n_shards = mesh.shape[POOL_AXIS]

    def _local(probs_local, mask_local):
        consensus = consensus_mean(probs_local)
        ent_local = masked_entropy(consensus, mask_local)
        v, i = lax.top_k(ent_local, k)
        vv, gi = _merge_local_topk(v, i, ent_local.shape[0], k)
        return ent_local, vv, gi

    smapped = shard_map(
        _local, mesh=mesh,
        in_specs=(P(None, POOL_AXIS, None), P(POOL_AXIS)),
        out_specs=(P(POOL_AXIS), P(), P()),
        check_vma=False)

    @jax.jit
    def scorer(member_probs, pool_mask) -> ScoreResult:
        ent, values, indices = smapped(member_probs, pool_mask)
        return ScoreResult(ent, values, indices)

    del n_shards
    return scorer


def make_shardmap_pallas_mc_scorer(mesh: Mesh, *, n_members: int, k: int,
                                   fuse_topk: bool = True,
                                   interpret: bool = False):
    """Multi-chip variant of the hand-fused Pallas scorer
    (``experimental.pallas_scoring``): each chip runs the Mosaic kernel on its own
    contiguous block of pool tiles, ranks its local candidates (in-kernel
    when ``fuse_topk``, else one local XLA ``lax.top_k`` — relative speed is
    pool-size dependent, see ``experimental.pallas_scoring``), then the ``k``
    per-chip candidates merge via ``all_gather`` + a tiny replicated top-k —
    identical O(k·D) ICI pattern to :func:`make_shardmap_mc_scorer`, with
    the member forward fused too.

    Returns ``scorer(x_tiles, w_packed, b_packed, pool_mask) -> ScoreResult``
    for a ``pack_pool``-packed pool whose tile count divides the mesh's pool
    axis.  Tie semantics are 'fast' (lowest global index wins).  ``interpret``
    runs the kernel in the Pallas interpreter (CPU-mesh tests).
    """
    from consensus_entropy_tpu.experimental import pallas_scoring

    def _local(x_tiles_local, w_packed, b_packed, mask_local):
        ent, v, i = pallas_scoring.packed_score_mc(
            x_tiles_local, w_packed, b_packed, mask_local,
            n_members=n_members, k=k, fuse_topk=fuse_topk,
            interpret=interpret)
        vv, gi = _merge_local_topk(v, i, mask_local.shape[0], k)
        return ent, vv, gi

    smapped = shard_map(
        _local, mesh=mesh,
        in_specs=(P(POOL_AXIS, None, None, None), P(None, None), P(None),
                  P(POOL_AXIS)),
        out_specs=(P(POOL_AXIS), P(), P()),
        check_vma=False)

    @jax.jit
    def scorer(x_tiles, w_packed, b_packed, pool_mask) -> ScoreResult:
        ent, values, indices = smapped(x_tiles, w_packed, b_packed,
                                       pool_mask)
        return ScoreResult(ent, values, indices)

    return scorer


def pad_pool(arrays, n_valid: int, n_pad: int, *, axis: int = 0):
    """Pad each array's pool axis from ``n_valid`` to ``n_pad`` and build the
    validity mask.  Returns ``(padded_arrays, mask)``.

    This is the host-side half of the fixed-shape contract: called once per
    user (not per iteration); thereafter only the mask changes on device.
    """
    import numpy as np

    if n_pad < n_valid:
        raise ValueError(f"pad target {n_pad} < pool size {n_valid}")
    out = []
    for a in arrays:
        a = np.asarray(a)
        widths = [(0, 0)] * a.ndim
        widths[axis] = (0, n_pad - a.shape[axis])
        out.append(np.pad(a, widths))
    mask = np.zeros(n_pad, dtype=bool)
    mask[:n_valid] = True
    return out, mask
