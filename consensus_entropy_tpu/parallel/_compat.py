"""JAX version compatibility shims for the parallel package.

The framework targets the modern ``jax.shard_map`` surface (top-level
export, ``check_vma`` keyword).  Older runtimes (this container ships jax
0.4.37) only have ``jax.experimental.shard_map.shard_map`` with the
``check_rep`` spelling of the same knob.  One adapter here keeps every
call site on the modern signature instead of sprinkling try/except through
the scoring/sequence modules.
"""

from __future__ import annotations

try:  # modern jax: top-level export with check_vma
    from jax import shard_map as _modern

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _modern(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_vma=check_vma)

except ImportError:  # pre-export jax: experimental module, check_rep
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True):
        return _legacy(f, mesh=mesh, in_specs=in_specs,
                       out_specs=out_specs, check_rep=check_vma)
