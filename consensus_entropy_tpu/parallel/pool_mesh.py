"""Pool-axis mesh serving: the sharded jit families the serve stack runs.

``parallel.sharding`` proved the sharding rules (pool-axis
``NamedSharding`` over the unfused scorers, shard_map top-k); this module
turns them into the PRODUCTION families the rest of the stack composes
through:

- **all six acquisition modes, fused included** — the ``*_fused``
  select→reveal→mask graphs run sharded with their donation intact: the
  pool/hc mask twins and the probs buffer live sharded across the mesh,
  the reveal scatter updates them in place, and only the 2·k selection
  scalars cross to host (``ops.scoring.selection_scalars``).
- **mesh × users composition** — :func:`sharded_fleet_fns_for_width`
  wraps the fleet's vmapped per-bucket scorers with pool-axis shardings
  on the trailing pool dim, so one multichip worker stacks a whole
  admission bucket AND splits every user's pool across its chips in the
  same dispatch.
- **jit families keyed per (fn, width, n_devices)** — every build and
  lookup lands in ``obs.jit_telemetry`` under the mesh size, the key the
  compile-telemetry feed already records, so cost-aware edge derivation
  can see what each (width, n_devices) geometry pays.

Sharding rules (the partition-rule table, matched by operand name):
probs ``(M, N, C)`` split on N; pool/hc masks ``(N,)`` and hoisted hc
entropies split on N; the hc table ``(N, C)`` split on rows; PRNG keys,
reliability weights and member masks replicate.  Every reduction axis
(member mean, class entropy) is row-local — never the sharded axis — so
sharded results are BIT-IDENTICAL to the single-device graphs, not merely
close (pinned by ``tests/test_pool_mesh.py``).  ``mix`` concatenates the
mc and hc blocks along the row axis; its full entropy vector replicates
(irregular layout), matching ``parallel.sharding``.

Single-controller contract: buffers are placed with ``jax.device_put``
onto the process-local mesh (the virtual-device CI shape and one-host
multichip serving).  Multi-controller pool feeding stays in
``parallel.multihost`` / ``Acquirer._feed``.
"""

from __future__ import annotations

import functools
import re

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_entropy_tpu.obs import jit_telemetry
from consensus_entropy_tpu.ops.scoring import (
    FUSED_DONATE,
    FusedStepResult,
    ScoreResult,
    _fleet_base_fns,
    _fused_partial,
    _POOL_MASK_POS,
    score_hc,
    score_hc_precomputed,
    score_mc,
    score_mix,
    score_qbdc,
    score_rand,
    score_wmc,
)
from consensus_entropy_tpu.parallel.mesh import POOL_AXIS, make_pool_mesh


@functools.lru_cache(maxsize=None)
def make_pool_mesh_for(n_devices: int) -> Mesh:
    """A 1-D pool-axis mesh over the first ``n_devices`` local devices.

    Validated here (not at first dispatch) so CLI/serve configuration
    errors surface as one clean message: ``n_devices`` must be >= 1 and
    must not exceed what the process actually has.
    """
    n_devices = int(n_devices)
    if n_devices < 1:
        raise ValueError(
            f"pool mesh needs at least 1 device, got {n_devices}")
    devs = jax.devices()
    if n_devices > len(devs):
        raise ValueError(
            f"pool mesh wants {n_devices} device(s) but this process has "
            f"{len(devs)} — lower --mesh / mesh_devices or run with more "
            f"chips (CI simulates them via "
            f"--xla_force_host_platform_device_count)")
    return make_pool_mesh(devs[:n_devices])


#: operand-name regex → PartitionSpec (the SNIPPETS.md [2] partition-rule
#: idiom, applied to scoring operands instead of parameter trees).  First
#: match wins; every scoring operand name must match exactly one row.
PARTITION_RULES = (
    (r"probs$", P(None, POOL_AXIS, None)),
    (r"(pool_mask|hc_mask|hc_ent)$", P(POOL_AXIS)),
    (r"hc_freq$", P(POOL_AXIS, None)),
    (r"(key|weights|member_mask)$", P()),
)


def match_partition_rules(names) -> tuple:
    """Resolve each operand name through :data:`PARTITION_RULES`."""
    specs = []
    for name in names:
        for pat, spec in PARTITION_RULES:
            if re.search(pat, name):
                specs.append(spec)
                break
        else:
            raise ValueError(f"no partition rule matches operand {name!r}")
    return tuple(specs)


#: fn key → its positional operand names (the partition-rule lookup keys);
#: the ``*_masked`` variants exist only in the vmapped fleet families
_OPERANDS = {
    "mc": ("probs", "pool_mask"),
    "mc_masked": ("probs", "pool_mask", "member_mask"),
    "hc": ("hc_freq", "hc_mask"),
    "hc_pre": ("hc_ent", "hc_mask"),
    "mix": ("probs", "pool_mask", "hc_freq", "hc_mask"),
    "mix_masked": ("probs", "pool_mask", "hc_freq", "hc_mask",
                   "member_mask"),
    "rand": ("key", "pool_mask"),
    "qbdc": ("probs", "pool_mask"),
    "wmc": ("probs", "pool_mask", "weights"),
    "wmc_masked": ("probs", "pool_mask", "weights", "member_mask"),
    "mc_fused": ("probs", "pool_mask"),
    "qbdc_fused": ("probs", "pool_mask"),
    "wmc_fused": ("probs", "pool_mask", "weights"),
    "rand_fused": ("key", "pool_mask"),
    "hc_pre_fused": ("hc_ent", "hc_mask", "pool_mask"),
    "mix_fused": ("probs", "pool_mask", "hc_freq", "hc_mask"),
}

#: fn keys whose ranking runs over the concatenated [mc; hc] row space —
#: their full entropy vector replicates (irregular layout after concat)
_MIX_KEYS = frozenset(
    k for k in _OPERANDS if k.startswith("mix"))


def _out_specs(key: str) -> tuple:
    """The result PartitionSpec tree for one fn key (single-user shapes;
    :func:`_batched` lifts them onto the stacked fleet shapes)."""
    vec, repl = P(POOL_AXIS), P()
    ent = repl if key in _MIX_KEYS else vec
    if key.endswith("_fused"):
        hc_mask = vec if key in ("hc_pre_fused", "mix_fused") else None
        return FusedStepResult(entropy=ent, values=repl, indices=repl,
                               pool_mask=vec, hc_mask=hc_mask)
    return ScoreResult(entropy=ent, values=repl, indices=repl)


def _batched(spec):
    """Prepend the stacked USER axis (unsharded) to one PartitionSpec —
    the mesh × users composition: every device holds every user's slice
    of its own pool shard."""
    if spec is None:
        return None
    return P(None, *spec)


def _shard(mesh, tree):
    return jax.tree_util.tree_map(
        lambda spec: NamedSharding(mesh, spec), tree,
        is_leaf=lambda x: isinstance(x, P))


def make_sharded_step_fns(mesh: Mesh, *, k: int, tie_break: str = "fast"):
    """The single-user sharded scorer family — all six modes, UNFUSED and
    FUSED, pool-axis sharded with the fused donation intact.

    Supersedes ``parallel.sharding.make_sharded_scoring_fns`` for the
    acquirer: same keys plus the ``*_fused`` entries, whose mask operands
    are donated (``ops.scoring.FUSED_DONATE``) at matching in/out
    shardings so XLA reuses the sharded buffers in place — the sharded
    ``DevicePoolState`` mutates on device and only the 2·k selection
    scalars cross to host.

    Cached per (mesh, k, tie_break); telemetry-keyed per
    ``(fn, n_devices)`` via ``obs.jit_telemetry``.
    """
    jit_telemetry.note_lookup(f"scoring:k{k}:{tie_break}",
                              n_devices=mesh.size)
    return _sharded_step_fns_cached(mesh, k, tie_break)


def _single_user_impls(k: int, tie_break: str) -> dict:
    impls = {
        "mc": functools.partial(score_mc, k=k, tie_break=tie_break),
        "hc": functools.partial(score_hc, k=k, tie_break=tie_break),
        "hc_pre": functools.partial(score_hc_precomputed, k=k,
                                    tie_break=tie_break),
        "mix": functools.partial(score_mix, k=k, tie_break=tie_break),
        "rand": functools.partial(score_rand, k=k),
        "qbdc": functools.partial(score_qbdc, k=k, tie_break=tie_break),
        "wmc": functools.partial(score_wmc, k=k, tie_break=tie_break),
    }
    for key in FUSED_DONATE:
        impls[key] = _fused_partial(key, k, tie_break)
    return impls


@functools.lru_cache(maxsize=None)
def _sharded_step_fns_cached(mesh: Mesh, k: int, tie_break: str) -> dict:
    b0 = jit_telemetry.build_timer()
    fns = {}
    for key, fn in _single_user_impls(k, tie_break).items():
        in_s = _shard(mesh, match_partition_rules(_OPERANDS[key]))
        out_s = _shard(mesh, _out_specs(key))
        fns[key] = jax.jit(fn, in_shardings=in_s, out_shardings=out_s,
                           donate_argnums=FUSED_DONATE.get(key, ()))
    jit_telemetry.note_build(f"scoring:k{k}:{tie_break}",
                             n_devices=mesh.size,
                             build_s=jit_telemetry.build_timer() - b0,
                             jit_fns=fns.values())
    return fns


def sharded_fleet_fns_for_width(mesh: Mesh, *, k: int,
                                tie_break: str = "fast",
                                width: int) -> dict:
    """Per-bucket vmapped scorers sharded on the pool axis — the mesh ×
    users composition.  Input shapes are the fleet shapes with the
    trailing pool dim split across the mesh: stacked probs
    ``(U, M, N, C)`` on N, stacked masks ``(U, N)`` on N, hc tables
    ``(U, N, C)`` on rows; keys/weights/member masks replicate.  The
    fused keys donate their stacked sharded mask operands, so a whole
    bucket's pool state updates in place per dispatch.

    Width-guarded like ``ops.scoring.fleet_scoring_fns_for_width`` (a
    mis-routed session fails loudly at dispatch) and additionally checks
    the bucket width divides evenly across the mesh.  Telemetry-keyed
    per ``(fn, width, n_devices)``.
    """
    if width % mesh.size:
        raise ValueError(
            f"bucket width {width} does not divide across the "
            f"{mesh.size}-device pool mesh — admission must pad buckets "
            f"to a multiple of the mesh size")
    jit_telemetry.note_lookup(f"fleet:k{k}:{tie_break}", width=width,
                              n_devices=mesh.size)
    return _sharded_fleet_fns_cached(mesh, k, tie_break, width)


@functools.lru_cache(maxsize=None)
def _sharded_fleet_fns_cached(mesh: Mesh, k: int, tie_break: str,
                              width: int) -> dict:
    b0 = jit_telemetry.build_timer()
    base = {}
    for key, fn in _fleet_base_fns(k, tie_break).items():
        in_s = _shard(mesh, tuple(
            _batched(s) for s in match_partition_rules(_OPERANDS[key])))
        out_s = _shard(mesh, jax.tree_util.tree_map(
            _batched, _out_specs(key),
            is_leaf=lambda x: isinstance(x, P)))
        base[key] = jax.jit(jax.vmap(fn), in_shardings=in_s,
                            out_shardings=out_s,
                            donate_argnums=FUSED_DONATE.get(key, ()))
    jit_telemetry.note_build(f"fleet:k{k}:{tie_break}", width=width,
                             n_devices=mesh.size,
                             build_s=jit_telemetry.build_timer() - b0,
                             jit_fns=base.values())

    def guarded(fn_key, fn):
        pos = _POOL_MASK_POS[fn_key]

        def call(*args):
            got = args[pos].shape[-1]
            if got != width:
                raise ValueError(
                    f"bucket routing error: {fn_key!r} mesh scorer for "
                    f"pool width {width} got inputs of width {got}")
            return fn(*args)

        return call

    return {key: guarded(key, fn) for key, fn in base.items()}


def _scatter_rows_sharded_impl(buf, rows, p):
    # mirrors al.acquisition._scatter_rows_impl (OOB staging slots are
    # dropped); duplicated rather than imported so parallel/ never
    # depends on the al/ layer
    return buf.at[:, rows].set(p, mode="drop")


def sharded_scatter_rows(mesh: Mesh):
    """The donated probs scatter for the SHARDED persistent buffer: buf
    ``(M, N, C)`` split on N and reused in place; the live-row index
    vector and the staged probs block replicate (each device writes only
    the rows landing in its shard — XLA drops the rest like the OOB
    staging slots)."""
    return _sharded_scatter_cached(mesh)


@functools.lru_cache(maxsize=None)
def _sharded_scatter_cached(mesh: Mesh):
    probs_s = NamedSharding(mesh, P(None, POOL_AXIS, None))
    repl = NamedSharding(mesh, P())
    return jax.jit(_scatter_rows_sharded_impl,
                   in_shardings=(probs_s, repl, repl),
                   out_shardings=probs_s, donate_argnums=0)


def sharded_probs_buffer(mesh: Mesh, m: int, n_pad: int,
                         n_classes: int) -> jax.Array:
    """A zeroed persistent ``(M, n_pad, C)`` probs buffer laid out for
    the sharded scatter (single-controller placement)."""
    return jax.device_put(
        np.zeros((m, n_pad, n_classes), np.float32),
        NamedSharding(mesh, P(None, POOL_AXIS, None)))
