"""Sequence (context) parallelism for long-audio committee scoring.

The reference scores each song from ONE uniform-random 59049-sample crop per
pass (``short_cnn.py:376-377``, ``amg_test.py:173-201``), so committee CNN
probabilities are stochastic and a full song (minutes of audio, millions of
samples) is never actually heard.  The TPU-native long-audio path replaces
that with deterministic full-coverage inference:

    song waveform -> sliding analysis windows (length = the reference crop,
    stride = ``hop``) -> committee CNN on every window -> per-member mean of
    the sigmoid outputs over all windows.

Scale lives on the window/time axis, so that is what gets sharded: a
``shard_map`` over the ``seq`` mesh axis gives each chip a contiguous block
of windows.  When windows overlap (``hop < window``) the first
``window - hop`` samples of each chip's chunk are also the tail of its left
neighbor's last window — that halo is exchanged over ICI with ONE
``lax.ppermute`` per pass (ring shift, the canonical halo pattern) instead
of replicating the waveform.  The final per-member reduction is a masked
``psum`` pair, so every collective rides ICI and the result replicates.

This is the framework's context-parallel story (SURVEY.md §5: the reference
has no sequence dimension at all — attention-style ring/Ulysses CP is N/A,
but long audio is real): a 10-minute 16 kHz song is ~9.6 M samples = 163
windows, which an 8-chip slice scores 8 windows-per-chip deep.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.models.short_cnn import ShortChunkCNN
from consensus_entropy_tpu.parallel.mesh import SEQ_AXIS


class WindowPlan(NamedTuple):
    """Static geometry of a sharded full-song pass.

    n_windows:       valid analysis windows (>= 1; zero-pad-tail windows
                     beyond this count are masked out of the mean).
    windows_per_shard: windows each chip evaluates (includes masked pad).
    chunk_len:       samples per chip in the base (non-halo) layout.
    halo:            samples each chip needs from its right neighbor.
    padded_len:      total padded waveform length = n_shards*chunk_len + halo.
    """

    n_windows: int
    windows_per_shard: int
    chunk_len: int
    halo: int
    padded_len: int
    window: int
    hop: int

    @property
    def n_shards(self) -> int:
        return (self.padded_len - self.halo) // self.chunk_len


def plan_windows(n_samples: int, n_shards: int, *, window: int,
                 hop: int | None = None) -> WindowPlan:
    """Window/shard geometry for a song of ``n_samples``.

    Windows start at ``0, hop, 2*hop, ...``; a window is *valid* if it fits
    entirely inside the (unpadded) song — matching the reference's crop
    domain ``start <= T - window`` (``short_cnn.py:376``).  Songs shorter
    than one window get a single zero-padded window (the audio layer pads
    short excerpts the same way).
    """
    if hop is None:
        hop = window
    if not 1 <= hop <= window:
        raise ValueError(f"need 1 <= hop ({hop}) <= window ({window})")
    if n_samples >= window:
        n_valid = (n_samples - window) // hop + 1
    else:
        n_valid = 1
    wps = math.ceil(n_valid / n_shards)
    halo = window - hop
    chunk_len = wps * hop
    if halo > chunk_len:
        # The ring exchange fetches the halo from ONE right neighbor
        # (single ppermute hop); a deeper overlap than one chunk would need
        # multi-hop gathers.  Only reachable when a short song meets a wide
        # mesh at >50% overlap — fewer shards (or a coarser hop) fixes it.
        raise ValueError(
            f"window overlap ({halo} samples) exceeds the per-shard chunk "
            f"({chunk_len} = {wps} windows x hop {hop}); use fewer shards "
            f"for this song length or hop >= window - windows_per_shard*hop")
    return WindowPlan(n_valid, wps, chunk_len, halo,
                      n_shards * chunk_len + halo, window, hop)


def pad_song(wave, plan: WindowPlan):
    """Fit a ``(T,)`` waveform to the plan's padded length (host-side, once
    per song): zero-pad the tail, or truncate it when the plan's window grid
    ends before ``T`` (at most ``hop - 1`` trailing samples fall outside the
    last full window; they are covered by no valid window either way —
    stride-grid semantics, vs the reference's uniformly-random crop starts,
    ``short_cnn.py:376``)."""
    wave = np.asarray(wave, np.float32)
    if wave.ndim != 1:
        raise ValueError(f"expected (T,) waveform, got {wave.shape}")
    wave = wave[:plan.padded_len]
    return np.pad(wave, (0, plan.padded_len - wave.shape[0]))


def _local_windows(chunk_ext, plan: WindowPlan):
    """Slice a chip's extended chunk into its ``windows_per_shard`` windows
    (static offsets — wps and hop are compile-time constants)."""
    return jnp.stack([
        lax.dynamic_slice_in_dim(chunk_ext, w * plan.hop, plan.window)
        for w in range(plan.windows_per_shard)])


def make_full_song_scorer(mesh: Mesh, plan: WindowPlan,
                          config: CNNConfig = CNNConfig()):
    """Build the jitted sequence-parallel full-song committee scorer.

    Returns ``scorer(stacked_variables, padded_wave) -> (M, C)`` replicated
    per-member mean sigmoid scores.  ``padded_wave`` is ``(padded_len,)``
    from :func:`pad_song`; member variables are a stacked pytree
    (``models.short_cnn.stack_params``), replicated across the mesh.

    Layout: the first ``n_shards * chunk_len`` samples shard contiguously on
    ``seq``; the global tail of ``halo`` samples rides along replicated (it
    is at most ``window - hop`` samples) and stands in for the missing right
    neighbor of the last chip.
    """
    if plan.window != config.input_length:
        raise ValueError(
            f"plan window {plan.window} != config.input_length "
            f"{config.input_length}")
    n_shards = mesh.shape[SEQ_AXIS]
    if plan.n_shards != n_shards:
        raise ValueError(f"plan built for {plan.n_shards} shards, mesh has "
                         f"{n_shards}")
    model = ShortChunkCNN(config)

    def _shard_fn(stacked, chunks, tail, n_windows):
        # chunks: (1, chunk_len) local block; tail: (halo,) replicated;
        # n_windows: dynamic scalar — the only per-song quantity, so every
        # song in one (windows_per_shard, chunk_len, halo) geometry bucket
        # shares this compiled program.
        chunk = chunks[0]
        idx = lax.axis_index(SEQ_AXIS)
        if plan.halo:
            # Ring halo exchange: every chip sends the head of its chunk to
            # its LEFT neighbor (one ICI hop); the last chip's "neighbor" is
            # the replicated global tail.
            recv = lax.ppermute(
                chunk[:plan.halo], SEQ_AXIS,
                perm=[(i, (i - 1) % n_shards) for i in range(n_shards)])
            recv = jnp.where(idx == n_shards - 1, tail, recv)
            chunk_ext = jnp.concatenate([chunk, recv])
        else:
            chunk_ext = chunk
        windows = _local_windows(chunk_ext, plan)        # (wps, window)
        probs = jax.vmap(
            lambda v: model.apply(v, windows, train=False))(stacked)
        # Masked mean over the global window axis: pad windows weigh 0.
        gid = idx * plan.windows_per_shard + jnp.arange(
            plan.windows_per_shard)
        weight = (gid < n_windows).astype(probs.dtype)   # (wps,)
        local_sum = jnp.einsum("mwc,w->mc", probs, weight)
        total = lax.psum(local_sum, SEQ_AXIS)
        count = lax.psum(jnp.sum(weight), SEQ_AXIS)
        return total / count

    from consensus_entropy_tpu.parallel._compat import shard_map

    sharded = shard_map(
        _shard_fn, mesh=mesh,
        in_specs=(P(), P(SEQ_AXIS), P(), P()),
        out_specs=P(),
        check_vma=False)

    body_len = n_shards * plan.chunk_len

    @jax.jit
    def _scorer(stacked_variables, padded_wave, n_windows):
        body = padded_wave[:body_len].reshape(n_shards, plan.chunk_len)
        tail = (padded_wave[body_len:] if plan.halo
                else jnp.zeros((0,), padded_wave.dtype))
        return sharded(stacked_variables, body, tail, n_windows)

    def scorer(stacked_variables, padded_wave, n_windows: int | None = None):
        return _scorer(stacked_variables, padded_wave,
                       jnp.int32(plan.n_windows if n_windows is None
                                 else n_windows))

    return scorer


def full_song_probs_reference(stacked_variables, wave, plan: WindowPlan,
                              config: CNNConfig = CNNConfig()):
    """Single-device oracle: the same windows, plain vmap, no sharding.
    Used by tests and single-chip fallback."""
    model = ShortChunkCNN(config)
    padded = jnp.asarray(pad_song(wave, plan))
    starts = [w * plan.hop for w in range(plan.n_windows)]
    windows = jnp.stack([padded[s:s + plan.window] for s in starts])
    probs = jax.vmap(
        lambda v: model.apply(v, windows, train=False))(stacked_variables)
    return jnp.mean(probs, axis=1)
