"""Device-mesh construction and sharding rules.

The reference has **no** distributed backend (SURVEY.md §2: no NCCL/MPI/Gloo;
single process, one optional CUDA GPU).  The TPU-native scale story is built
here instead: a ``jax.sharding.Mesh`` whose ``pool`` axis splits the unlabeled
pool across chips and whose ``member``/``dp`` axes parallelize committee
training — with XLA emitting the ICI collectives.
"""

from consensus_entropy_tpu.parallel.mesh import (  # noqa: F401
    POOL_AXIS,
    MEMBER_AXIS,
    DP_AXIS,
    make_pool_mesh,
    make_training_mesh,
)
from consensus_entropy_tpu.parallel.sharding import (  # noqa: F401
    make_sharded_scoring_fns,
    make_shardmap_mc_scorer,
)
from consensus_entropy_tpu.parallel.pool_mesh import (  # noqa: F401
    make_pool_mesh_for,
    make_sharded_step_fns,
    sharded_fleet_fns_for_width,
)
