"""Multi-host (DCN) execution support.

The reference has no distributed backend at all (SURVEY.md §2: no
NCCL/MPI/Gloo; single process, one optional GPU).  The TPU-native framework
scales the same workload across pod slices with JAX's built-in runtime:
inside one host collectives ride ICI; across hosts XLA routes them over DCN
— no hand-written transport.  This module is the thin rim around that:

- :func:`initialize` — `jax.distributed.initialize` from explicit arguments
  or the environment (no-op for single-process runs, so every entry point
  can call it unconditionally).
- :func:`global_pool_mesh` — the 1-D pool mesh over every chip of every
  host (`jax.devices()` orders devices process-major, so contiguous pool
  blocks land host-local and the scoring reduction's only cross-host
  traffic is the O(k·D) top-k candidate gather).
- :func:`host_pool_slice` / :func:`distribute_pool` — each host feeds only
  its own rows; `jax.make_array_from_process_local_data` assembles the
  logically-global sharded array without any host ever materializing the
  full pool.

Single-process semantics are identical (the test suite exercises this on
the 8-device virtual mesh); multi-process runs need only `initialize(...)`
first — same code after that.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from consensus_entropy_tpu.parallel.mesh import POOL_AXIS


_initialized = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Join (or skip joining) the distributed runtime.

    With no arguments this is a no-op and the process stays single-host.
    With cluster arguments it must run BEFORE any other jax API touches the
    backend (``jax.distributed.initialize``'s own contract) — so this
    function deliberately makes no jax queries on the way in; repeat calls
    are tracked module-side and ignored.
    """
    global _initialized
    if coordinator_address is None and num_processes is None:
        return  # single-process run: nothing to join
    if _initialized:
        return
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)
    _initialized = True


def is_coordinator() -> bool:
    """True on the process that owns all filesystem writes (reports,
    checkpoints, workspace mutation); single-process runs are trivially
    the coordinator."""
    return jax.process_index() == 0


def sync(name: str = "sync") -> None:
    """Cross-process barrier (no-op single-process).  Used around workspace
    mutation so non-coordinators never read a directory mid-write.  The
    fault point fires on the way in — a kill here models a host preempted
    at a barrier, the boundary where divergent control flow would deadlock
    the surviving processes."""
    from consensus_entropy_tpu.resilience import faults

    faults.fire("multihost.sync", barrier=name)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(name)


def broadcast_flag(value: bool) -> bool:
    """Coordinator's boolean, agreed by every process (no-op
    single-process).  Keeps control-flow decisions (e.g. skip-user) in
    lockstep — divergent paths would deadlock the next collective."""
    if jax.process_count() == 1:
        return bool(value)
    from jax.experimental import multihost_utils

    return bool(multihost_utils.broadcast_one_to_all(np.asarray(value)))


def global_pool_mesh() -> Mesh:
    """1-D ``pool`` mesh over every addressable chip of every host."""
    return Mesh(np.asarray(jax.devices()), (POOL_AXIS,))


def host_pool_slice(n_rows: int) -> slice:
    """The contiguous row range this host is responsible for feeding
    (depends only on process count/index — `jax.devices()` is
    process-major, so contiguous row blocks are host-local under the pool
    mesh).

    ``n_rows`` must divide evenly across hosts (the fixed-shape padding the
    scoring path already performs guarantees a device-multiple, which is a
    host-multiple too).
    """
    n_proc = jax.process_count()
    if n_rows % n_proc:
        raise ValueError(f"n_rows {n_rows} not divisible by "
                         f"{n_proc} processes")
    per = n_rows // n_proc
    pid = jax.process_index()
    return slice(pid * per, (pid + 1) * per)


def distribute_along(local_block: np.ndarray, global_shape: tuple,
                     mesh: Mesh | None = None, axis: int = 0,
                     axis_name: str = POOL_AXIS):
    """Assemble a global sharded array from per-host blocks.

    ``local_block``: this host's ``host_pool_slice``-worth of the array
    along ``axis`` (e.g. axis 1 for the ``(M, N, C)`` member-probability
    tables on the ``pool`` axis, or axis 0 of member-stacked training state
    on the ``member`` axis).  Returns a global jax.Array sharded on
    ``axis_name`` at ``axis``; on a single host this is exactly
    ``device_put`` with that sharding, so the same feed path serves both.

    The contiguous-block math assumes the named mesh axis spans all devices
    in process-major order (true for the 1-D pool/seq meshes and for
    ``make_training_mesh(dp=1, member=n)`` — the only shapes fed here).
    """
    mesh = mesh or global_pool_mesh()
    spec = [None] * len(global_shape)
    spec[axis] = axis_name
    sharding = NamedSharding(mesh, P(*spec))
    return jax.make_array_from_process_local_data(sharding, local_block,
                                                  tuple(global_shape))


def distribute_pool(local_rows: np.ndarray, n_global_rows: int,
                    mesh: Mesh | None = None):
    """Leading-axis convenience wrapper over :func:`distribute_along`."""
    return distribute_along(
        local_rows, (n_global_rows,) + tuple(local_rows.shape[1:]), mesh, 0)


def feed_pool_axis(arr, mesh: Mesh, axis: int = 0):
    """Slice this host's ``host_pool_slice`` block out of a host-complete
    array and assemble the global pool-sharded jax.Array — THE feed helper
    for every pool-sharded scoring input (Acquirer tables/masks, Committee
    crop/window batches).  Single-process this equals a ``device_put`` with
    the pool sharding."""
    return feed_axis(arr, mesh, POOL_AXIS, axis)


def feed_axis(arr, mesh: Mesh, axis_name: str, axis: int = 0):
    """Per-host feed of a host-complete array onto any 1-D process-major
    mesh axis (``feed_pool_axis`` generalized; the ``member`` axis of the
    training mesh uses this to shard identical per-process committee state
    without any host shipping members it doesn't own)."""
    arr = np.asarray(arr)
    sl = [slice(None)] * arr.ndim
    sl[axis] = host_pool_slice(arr.shape[axis])
    return distribute_along(arr[tuple(sl)], arr.shape, mesh, axis, axis_name)


def feed_replicated(tree, mesh: Mesh):
    """Replicated global feed of a pytree whose values are identical on
    every process (committed process-local arrays cannot be implicitly
    resharded onto non-addressable devices).  The shared idiom behind the
    committee's stacked-params feed, the Acquirer's rand-key feed, and the
    trainer's broadcast inputs."""
    sharding = NamedSharding(mesh, P())

    def one(a):
        a = np.asarray(a)
        return jax.make_array_from_process_local_data(sharding, a, a.shape)

    return jax.tree.map(one, tree)


def gather_to_host(out):
    """Bring a (possibly pool-sharded) jax.Array back as a host-complete
    numpy array on EVERY process.  Multi-host, a sharded output spans
    non-addressable devices and plain ``np.asarray`` raises; this routes
    through ``process_allgather``.  Single-process it is just
    ``np.asarray``."""
    if jax.process_count() == 1:
        return np.asarray(out)
    from jax.experimental import multihost_utils

    return np.asarray(multihost_utils.process_allgather(out, tiled=True))
