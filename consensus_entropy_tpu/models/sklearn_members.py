"""Host-side classic committee members: GNB, SGD-logistic, gradient boosting.

These stay on CPU by design (trees and tiny generative models don't map to
XLA — SURVEY.md §2 native-components table); their per-song probability
tables feed the on-device fused reduction.

Incremental-update semantics reproduced:

- GNB / SGD: ``partial_fit(X, y)`` on the queried batch (``amg_test.py:509``).
- XGB: continued boosting from the existing booster (``amg_test.py:507``)
  **with class preservation** — the reference vendors a patched
  ``xgboost/sklearn.py`` whose delta (lines 854-860, "added for active
  learning") skips recomputing ``classes_`` when a booster is passed, so the
  4-class softprob objective survives a query batch that lacks some classes.
  Here that semantics is a thin wrapper around ``xgboost.train`` with
  ``num_class`` pinned — no vendored library fork.  When xgboost is not
  installed, :func:`make_boosted_member` fills the slot with the first-party
  histogram GBDT (``models/gbdt.py`` — exact continued-boosting semantics,
  C++/OpenMP core); ``BoostedTreesMember`` (sklearn
  ``GradientBoostingClassifier`` warm-start with anchor-row class padding)
  remains as an opt-in comparison baseline (``impl='sklearn'``).
"""

from __future__ import annotations

import pickle

import numpy as np
from sklearn.ensemble import GradientBoostingClassifier
from sklearn.linear_model import SGDClassifier
from sklearn.naive_bayes import GaussianNB

from consensus_entropy_tpu import native
from consensus_entropy_tpu.config import NUM_CLASSES
from consensus_entropy_tpu.models.base import Member

try:  # gated: not baked into this image
    import xgboost as _xgb

    HAVE_XGBOOST = True
except ImportError:  # pragma: no cover - env without xgboost
    _xgb = None
    HAVE_XGBOOST = False

ALL_CLASSES = np.arange(NUM_CLASSES)


def _require_all_classes(y):
    """Pre-training must expose the full class universe (DEAM does; the
    reference's partial_fit/warm-start chain silently relies on it)."""
    seen = np.unique(y)
    if len(seen) != NUM_CLASSES:
        raise ValueError(
            f"pre-training data must contain all {NUM_CLASSES} classes; "
            f"got {sorted(int(c) for c in seen)}")


class _PickledSklearnMember(Member):
    """Shared persistence for members whose state is one sklearn estimator."""

    def __init__(self, name: str, estimator):
        super().__init__(name)
        self.estimator = estimator

    def predict_proba(self, X):
        # GNB/SGD route through the OpenMP C++ core (native.member_probs);
        # other estimators fall back to sklearn transparently.
        return self._full_proba(native.member_probs(self.estimator,
                                                    np.asarray(X)),
                                getattr(self.estimator, "classes_", ALL_CLASSES))

    @staticmethod
    def _full_proba(p, classes) -> np.ndarray:
        """Expand to all NUM_CLASSES columns if the estimator saw fewer."""
        if p.shape[1] == NUM_CLASSES:
            return p
        full = np.zeros((p.shape[0], NUM_CLASSES), p.dtype)
        full[:, np.asarray(classes, int)] = p
        return full

    def predict(self, X):
        # The per-iteration evaluation hot path (amg_test.py:411-413 scores
        # every member on the full test frame set every iteration): GNB/SGD
        # go through the native core's argmax fast path; estimators without
        # one (trees, SVC — whose Platt-scaled proba argmax can disagree
        # with its own predict) keep sklearn's predict untouched.
        X = np.asarray(X)
        y = native.member_predict(self.estimator, X)
        return y if y is not None else self.estimator.predict(X)

    def save(self, path):
        with open(path, "wb") as f:
            pickle.dump({"kind": self.kind, "name": self.name,
                         "estimator": self.estimator}, f)

    @classmethod
    def load(cls, path):
        with open(path, "rb") as f:
            state = pickle.load(f)
        obj = cls.__new__(cls)
        Member.__init__(obj, state["name"])
        obj.estimator = state["estimator"]
        return obj


class GenericSklearnMember(_PickledSklearnMember):
    """Registry entries beyond the paper's committee (rf/svc/knn/gpc/gbc —
    ``deam_classifier.py:201-225``).  They pre-train and score; ``update`` is
    a no-op because the reference's AL dispatch (``amg_test.py:503-509``)
    only retrains xgb/gnb/sgd/cnn and silently leaves other members frozen.
    """

    def __init__(self, name: str, kind: str, estimator):
        super().__init__(name, estimator)
        self.kind = kind

    def fit(self, X, y):
        self.estimator.fit(np.asarray(X), np.asarray(y))
        return self

    def update(self, X, y):
        pass  # frozen during AL, matching the reference dispatch

    @classmethod
    def load(cls, path):
        with open(path, "rb") as f:
            state = pickle.load(f)
        obj = cls.__new__(cls)
        Member.__init__(obj, state["name"])
        obj.estimator = state["estimator"]
        obj.kind = state["kind"]
        return obj


class GNBMember(_PickledSklearnMember):
    """GaussianNB (``deam_classifier.py:210-212``)."""

    kind = "gnb"

    def __init__(self, name: str = "gnb", estimator: GaussianNB | None = None):
        super().__init__(name, estimator or GaussianNB())

    def fit(self, X, y):
        y = np.asarray(y)
        _require_all_classes(y)
        self.estimator.fit(np.asarray(X), y)
        return self

    def update(self, X, y):
        # partial_fit needs the class universe on a cold start only.
        if not hasattr(self.estimator, "classes_"):
            self.estimator.partial_fit(X, y, classes=ALL_CLASSES)
        else:
            self.estimator.partial_fit(X, y)


class SGDMember(_PickledSklearnMember):
    """SGD logistic regression, L2 (``deam_classifier.py:213-218``;
    reference ``loss='log'`` is modern sklearn's ``'log_loss'``)."""

    kind = "sgd"

    def __init__(self, name: str = "sgd", estimator: SGDClassifier | None = None,
                 seed: int | None = None):
        super().__init__(name, estimator or SGDClassifier(
            loss="log_loss", penalty="l2", random_state=seed, warm_start=True))

    def fit(self, X, y):
        y = np.asarray(y)
        _require_all_classes(y)
        self.estimator.fit(np.asarray(X), y)
        return self

    def update(self, X, y):
        if not hasattr(self.estimator, "classes_"):
            self.estimator.partial_fit(X, y, classes=ALL_CLASSES)
        else:
            self.estimator.partial_fit(X, y)


class XGBMember(Member):
    """Gradient-boosted trees via xgboost with AL-safe continued boosting.

    Mirrors ``XGBClassifier(max_depth=5, eval_metric='auc', nthread=4)``
    (``deam_classifier.py:226-231``) but drives ``xgboost.train`` directly so
    ``num_class=4`` is pinned across warm-start updates — the semantics of
    the reference's vendored patch (``xgboost/sklearn.py:854-860``) without
    forking the library.
    """

    kind = "xgb"

    def __init__(self, name: str = "xgb", *, max_depth: int = 5,
                 n_estimators: int = 100, learning_rate: float = 0.3,
                 nthread: int = 4, seed: int = 0):
        if not HAVE_XGBOOST:
            raise ImportError("xgboost unavailable; use BoostedTreesMember")
        super().__init__(name)
        self.params = {"objective": "multi:softprob",
                       "num_class": NUM_CLASSES, "max_depth": max_depth,
                       "eta": learning_rate, "nthread": nthread,
                       "seed": seed, "eval_metric": "auc"}
        self.n_estimators = n_estimators
        self.booster = None

    def fit(self, X, y):
        d = _xgb.DMatrix(np.asarray(X), label=np.asarray(y))
        self.booster = _xgb.train(self.params, d, self.n_estimators)
        return self

    def update(self, X, y):
        """Continued boosting: adds rounds to the *existing* booster; the
        objective stays 4-class even if the batch lacks classes."""
        d = _xgb.DMatrix(np.asarray(X), label=np.asarray(y))
        self.booster = _xgb.train(self.params, d, self.n_estimators,
                                  xgb_model=self.booster)

    def predict_proba(self, X):
        return self.booster.predict(_xgb.DMatrix(np.asarray(X)))

    def save(self, path):
        raw = self.booster.save_raw() if self.booster is not None else None
        with open(path, "wb") as f:
            pickle.dump({"kind": self.kind, "name": self.name,
                         "params": self.params,
                         "n_estimators": self.n_estimators, "raw": raw}, f)

    @classmethod
    def from_state(cls, state: dict) -> "XGBMember":
        obj = cls(state["name"])
        obj.params = state["params"]
        obj.n_estimators = state["n_estimators"]
        if state["raw"] is not None:
            obj.booster = _xgb.Booster(model_file=None)
            obj.booster.load_model(bytearray(state["raw"]))
        return obj

    @classmethod
    def load(cls, path):
        with open(path, "rb") as f:
            return cls.from_state(pickle.load(f))


class BoostedTreesMember(_PickledSklearnMember):
    """Fallback boosted-trees member (xgboost absent): sklearn
    ``GradientBoostingClassifier`` with ``warm_start`` continued boosting.

    Class preservation: the estimator is always first fit with all 4 classes
    present (the pre-trainer guarantees this); warm-start updates keep
    ``classes_`` fixed, and query batches are boosted as additional stages.

    Approximation envelope vs true continued boosting (the reference's
    patched ``xgboost/sklearn.py:854-860,911-927``): sklearn's warm-start
    refuses batches missing a class, so class-deficient updates are padded
    with ONE remembered anchor row per missing class.  When the batch
    contains every class the update is exact warm-start boosting; when it
    does not, the anchors re-enter the gradient of the new stages, so stage
    weights differ slightly from xgboost's (which boosts the raw batch
    against the preserved 4-class objective).  Under many successive
    single-class updates the 1-row-per-class anchors are a weak
    counterweight: drift toward the batch's class is somewhat faster than
    xgboost's.  Both paths keep ``classes_``/the 4-column probability
    contract intact (pinned by the shared contract tests in
    ``tests/test_members.py``)."""

    kind = "xgb"  # fills the xgb committee slot

    def __init__(self, name: str = "xgb", *, max_depth: int = 5,
                 n_estimators: int = 50, update_estimators: int = 10,
                 seed: int | None = None):
        super().__init__(name, GradientBoostingClassifier(
            max_depth=max_depth, n_estimators=n_estimators,
            warm_start=True, random_state=seed))
        self.update_estimators = update_estimators

    def fit(self, X, y):
        X, y = np.asarray(X), np.asarray(y)
        _require_all_classes(y)
        self.estimator.fit(X, y)
        self._remember(X, y)
        return self

    def update(self, X, y):
        X, y = np.asarray(X), np.asarray(y)
        # warm-start boosting requires every class present in y (sklearn
        # validates); pad the batch with one nearest-feature row per missing
        # class drawn from the estimator's training memory — since AL batches
        # are small this keeps semantics close to continued boosting.
        missing = np.setdiff1d(self.estimator.classes_, np.unique(y))
        if missing.size:
            Xm, ym = self._anchor_rows(missing)
            X, y = np.vstack([X, Xm]), np.concatenate([y, ym])
        self.estimator.n_estimators += self.update_estimators
        self.estimator.fit(X, y)
        self._remember(X, y)

    # -- memory of one representative row per class ------------------------

    def _remember(self, X, y):
        mem = getattr(self, "_class_rows", {})
        for c in np.unique(y):
            mem[int(c)] = X[y == c][0]
        self._class_rows = mem

    def _anchor_rows(self, classes):
        rows = [self._class_rows[int(c)] for c in classes]
        return np.stack(rows), np.asarray(classes)

    def save(self, path):
        with open(path, "wb") as f:
            pickle.dump({"kind": self.kind, "name": self.name,
                         "estimator": self.estimator,
                         "update_estimators": self.update_estimators,
                         "class_rows": getattr(self, "_class_rows", {})}, f)

    @classmethod
    def from_state(cls, state: dict) -> "BoostedTreesMember":
        obj = cls.__new__(cls)
        Member.__init__(obj, state["name"])
        obj.estimator = state["estimator"]
        obj.update_estimators = state["update_estimators"]
        obj._class_rows = state["class_rows"]
        return obj

    @classmethod
    def load(cls, path):
        with open(path, "rb") as f:
            return cls.from_state(pickle.load(f))


def make_boosted_member(name: str = "xgb", seed: int = 0, *,
                        impl: str = "auto", **kw) -> Member:
    """The boosted-trees committee slot.

    ``impl='auto'`` prefers xgboost when installed, then the first-party
    :class:`~consensus_entropy_tpu.models.gbdt.NativeGBDTMember` (exact
    continued-boosting semantics, C++/OpenMP core with numpy fallback), and
    only uses the sklearn anchor-row approximation when forced
    (``impl='sklearn'``, kept for comparison tests).
    """
    if impl not in ("auto", "xgboost", "native", "sklearn"):
        raise ValueError(f"unknown boosted impl {impl!r}")
    if impl == "xgboost" or (impl == "auto" and HAVE_XGBOOST):
        return XGBMember(name, seed=seed, **kw)
    if impl == "sklearn":
        return BoostedTreesMember(name, seed=seed, **kw)
    from consensus_entropy_tpu.models.gbdt import NativeGBDTMember

    return NativeGBDTMember(name, seed=seed, **kw)
