"""Committee orchestration: host members + device CNN members, one reduction.

Reference hot loop #1 (``amg_test.py:425-447``) reloads every member from
disk each iteration, scores sequentially (CNN at batch_size=1), aggregates
frames with pandas groupby, and ships everything through scipy on host.

TPU-native shape of the same computation:

- CNN members live as ONE stacked pytree; scoring all of them over all pool
  songs is a single jit dispatch (``lax.map`` over the member axis — dense
  per-member convs, see ``short_cnn.committee_infer``; async — the host
  thread returns immediately).
- While the TPU chews the CNN graph, the host computes sklearn members'
  frame probabilities and segment-means them into per-song tables (numpy
  ``reduceat``, not pandas groupby).
- Host tables are concatenated onto the device probs and the fused
  mean→entropy→top-k graph runs on TPU (see ``ops.scoring``); overlap comes
  free from JAX's async dispatch (SURVEY.md §7 hard part 6).
"""

from __future__ import annotations

import dataclasses
import functools
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from consensus_entropy_tpu import native
from consensus_entropy_tpu.config import CNNConfig, NUM_CLASSES, TrainConfig
from consensus_entropy_tpu.obs import jit_telemetry
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.models.base import Member
from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer
from consensus_entropy_tpu.utils import round_up as _round_up
from consensus_entropy_tpu.utils.checkpoint import load_variables, save_variables


class CommitteeExhaustedError(RuntimeError):
    """Quarantine has eaten into the configured survivor floor
    (``Committee.min_members``): too few members remain for the consensus
    to mean anything, so the user's run aborts instead of limping on."""


class FramePool:
    """Per-song frame features in segment layout for host member scoring.

    ``X``: ``(n_frames_total, F)`` rows sorted/grouped by song; ``song_ids``
    gives the unique songs in order; ``offsets`` the start row of each song's
    segment.  ``mean_by_song(p)`` replaces the reference's
    ``DataFrame(...).groupby('s_id').mean()`` (``amg_test.py:437``).
    """

    def __init__(self, X: np.ndarray, frame_song: Sequence):
        frame_song = np.asarray(frame_song)
        order = np.argsort(frame_song, kind="stable")
        self.X = np.ascontiguousarray(np.asarray(X)[order])
        sorted_ids = frame_song[order]
        change = np.flatnonzero(
            np.r_[True, sorted_ids[1:] != sorted_ids[:-1]])
        self.offsets = change
        self.song_ids = list(sorted_ids[change])
        self.counts = np.diff(np.r_[change, len(sorted_ids)])
        self._starts = np.r_[change, len(sorted_ids)].astype(np.int64)
        self._index = {sid: i for i, sid in enumerate(self.song_ids)}

    @property
    def n_songs(self) -> int:
        return len(self.song_ids)

    def count_of(self, song) -> int:
        """Frames in ``song``'s segment (O(1))."""
        return int(self.counts[self._index[song]])

    def mean_by_song(self, frame_values: np.ndarray) -> np.ndarray:
        return self.mean_over_segments(frame_values, self._starts)

    def segment_view(self, songs: Sequence):
        """``(rows, starts)`` for a packed sub-table holding only ``songs``'
        frames, in ``songs`` order: ``rows`` indexes ``X``; ``starts`` are
        the n+1 segment boundaries of the packed table (``segment_mean``
        layout).  Lets callers score a shrinking pool without touching the
        removed songs' frames (the reference scores only the live
        ``X_train`` — ``amg_test.py:435``)."""
        counts = np.array([self.counts[self._index[s]] for s in songs],
                          np.int64)
        rows = np.concatenate(
            [np.arange(self.offsets[self._index[s]],
                       self.offsets[self._index[s]] + self.counts[self._index[s]])
             for s in songs]) if len(songs) else np.empty(0, np.int64)
        starts = np.r_[0, np.cumsum(counts)].astype(np.int64)
        return rows, starts

    def mean_over_segments(self, frame_values: np.ndarray,
                           starts: np.ndarray) -> np.ndarray:
        """Per-segment mean over n+1 boundaries (``segment_view`` layout;
        :meth:`mean_by_song` is the full-table case).  float32 2-D tables
        take the threaded C++ path (``native.segment_mean`` falls back to
        numpy when the toolchain is absent)."""
        frame_values = np.asarray(frame_values)
        if frame_values.dtype == np.float32 and frame_values.ndim == 2:
            return native.segment_mean(frame_values, starts)
        sums = np.add.reduceat(frame_values, starts[:-1], axis=0)
        return sums / np.diff(starts)[:, None]

    def rows_for_songs(self, songs: Sequence) -> np.ndarray:
        """Row indices of all frames belonging to ``songs`` (batch build)."""
        wanted = set(songs)
        keep = []
        for i, sid in enumerate(self.song_ids):
            if sid in wanted:
                start = self.offsets[i]
                keep.append(np.arange(start, start + self.counts[i]))
        return (np.concatenate(keep) if keep
                else np.empty(0, np.int64))


class CNNMember(Member):
    """Flax CNN committee member (device species of the Member protocol)."""

    kind = "cnn_jax"

    def __init__(self, name: str, variables, config: CNNConfig = CNNConfig(),
                 train_config: TrainConfig = TrainConfig()):
        super().__init__(name)
        self.variables = variables  # property setter marks ckpt_dirty
        self.config = config
        self.train_config = train_config

    @property
    def variables(self):
        return self._variables

    @variables.setter
    def variables(self, value):
        """Rebinding the variables marks the member checkpoint-dirty: the
        committee's ``begin_save`` fetches only members whose weights
        changed since the last snapshot (retraining rebinds, never mutates
        in place), so unchanged members cost zero device→host traffic on
        the per-iteration checkpoint cadence.  ``ckpt_clean_path`` records
        WHICH file a clean member's weights correspond to — clean relative
        to the registry it was loaded from is not clean relative to a
        workspace that happens to hold a same-named stale file."""
        self._variables = value
        self.ckpt_dirty = True
        self.ckpt_clean_path: str | None = None

    def predict_proba(self, X):  # feature-table API doesn't apply
        raise TypeError("CNNMember scores audio crops via Committee")

    def update(self, X, y):
        raise TypeError("CNNMember retrains via Committee.retrain_cnn")

    #: Frontend-shaping config fields that change NO parameter shape — a
    #: checkpoint restored under different values would load cleanly and
    #: score through a frontend the weights were never trained on, so they
    #: ride in checkpoint meta and loading honors them.
    FRONTEND_META = ("arch", "n_harmonic", "semitone_scale", "n_mels",
                     "n_fft", "hop_length", "f_min", "f_max", "sample_rate")

    def save(self, path, variables=None):
        """``variables`` overrides the member's own (the committee's batched
        checkpoint fetch passes pre-fetched host copies)."""
        meta = {"kind": self.kind, "name": self.name}
        meta.update({k: getattr(self.config, k) for k in self.FRONTEND_META})
        save_variables(path, self.variables if variables is None
                       else variables, meta=meta)

    @classmethod
    def load(cls, path, config: CNNConfig = CNNConfig(),
             train_config: TrainConfig = TrainConfig()):
        variables, meta = load_variables(path)
        # the checkpoint knows its trunk family AND frontend geometry
        # (FRONTEND_META); honor them over the caller's config — none of
        # them changes a parameter shape, so a mismatch would restore
        # cleanly and score through the wrong frontend
        import dataclasses

        override = {k: meta[k] for k in cls.FRONTEND_META
                    if k in meta and meta[k] != getattr(config, k)}
        if override:
            config = dataclasses.replace(config, **override)
        # Checkpoints may carry bf16 leaves (ALConfig.ckpt_dtype): restore
        # to f32 — training/optimizer state and the scoring path are f32
        # with an explicit compute_dtype gate, not mixed-storage.
        variables = jax.tree.map(
            lambda a: a.astype(np.float32)
            if a.dtype == jnp.bfloat16
            or (a.dtype.kind == "f" and a.dtype != np.float32)
            else a, variables)
        member = cls(meta.get("name", os.path.basename(path)), variables,
                     config, train_config)
        # freshly loaded == content of the file it came from: if that SAME
        # file is the checkpoint target, begin_save may skip the fetch
        # until the member retrains (a same-named file elsewhere proves
        # nothing — see ckpt_clean_path)
        member.ckpt_dirty = False
        member.ckpt_clean_path = os.path.abspath(path)
        return member


@jax.jit
def _cast_tree_bf16(tree):
    """f32 leaves → bf16 on device (checkpoint-fetch shrink; non-float and
    non-f32 leaves pass through untouched)."""
    return jax.tree.map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        tree)


def _concat_member_blocks(blocks):
    """``axis=1`` concat of ``(M, n, C)`` member-prob blocks.

    Blocks are homogeneous: all-numpy when a multi-host gather already
    brought them to host (stay there — re-uploading just to concat wastes
    a transfer), all-``jax.Array`` otherwise (concat on device)."""
    if len(blocks) == 1:
        return blocks[0]
    xp = np if isinstance(blocks[0], np.ndarray) else jnp
    return xp.concatenate(blocks, axis=1)


def _infer_fns(config: CNNConfig, mesh):
    """The telemetered cache wrapper: every lookup feeds the jit-family
    hit/miss counters (``obs.jit_telemetry``), the once-per-key build is
    timed inside the cached impl."""
    jit_telemetry.note_lookup("cnn_infer",
                              n_devices=mesh.size if mesh else 1)
    return _infer_fns_cached(config, mesh)


@functools.lru_cache(maxsize=None)
def _infer_fns_cached(config: CNNConfig, mesh):
    """Process-wide jitted committee-inference programs for ``config``.

    Returns ``(infer, infer_windows)``: the stacked-member crop forward and
    the window-grid masked-mean forward, optionally pool-sharded over
    ``mesh``.  Module-level and ``lru_cache``'d because a fresh
    :class:`Committee` is built PER USER in the AL run (the reference
    re-copies the committee per user, ``amg_test.py:146-171``) — per-
    instance ``jax.jit`` objects made every user re-trace AND re-compile
    the full-geometry forward (~15-30 s on the TPU, measured as the warm
    user's entire first-iteration ``score`` phase in ``ITERATION_r04``).
    The programs close over ``config`` only (frozen dataclass, hashes by
    value) and take the stacked params as an argument, so sharing across
    committees is sound and retraining needs no cache flush; ``Mesh``
    hashes by value, so an equal mesh rebuilt per round still hits.
    """

    b0 = jit_telemetry.build_timer()

    def infer(stacked, x):
        return short_cnn.committee_infer(stacked, x, config)

    def windows_forward(stacked, windows, valid):
        # (R, W, L) windows + (R, W) mask -> (M, R, C) masked window mean
        r, w, length = windows.shape
        flat = short_cnn.committee_infer(
            stacked, windows.reshape(r * w, length), config)
        probs = flat.reshape(flat.shape[0], r, w, flat.shape[-1])
        weight = valid.astype(probs.dtype)
        return (jnp.einsum("mrwc,rw->mrc", probs, weight)
                / jnp.sum(weight, axis=1)[None, :, None])

    if mesh is None:
        fns = (jax.jit(infer), jax.jit(windows_forward))
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from consensus_entropy_tpu.parallel.mesh import POOL_AXIS

        repl = NamedSharding(mesh, P())
        rows_sh = NamedSharding(mesh, P(POOL_AXIS))
        out_sh = NamedSharding(mesh, P(None, POOL_AXIS, None))
        fns = (jax.jit(infer, in_shardings=(repl, rows_sh),
                       out_shardings=out_sh),
               jax.jit(windows_forward,
                       in_shardings=(repl, rows_sh, rows_sh),
                       out_shardings=out_sh))
    jit_telemetry.note_build("cnn_infer",
                             n_devices=mesh.size if mesh else 1,
                             build_s=jit_telemetry.build_timer() - b0,
                             jit_fns=fns)
    return fns


def _qbdc_infer_fn(config: CNNConfig):
    jit_telemetry.note_lookup("qbdc_infer")
    return _qbdc_infer_fn_cached(config)


@functools.lru_cache(maxsize=None)
def _qbdc_infer_fn_cached(config: CNNConfig):
    """Process-wide jitted QBDC forward for ``config`` (same sharing
    rationale as :func:`_infer_fns`: committees are rebuilt per user, the
    program is pure in its operands).  One executable serves every user
    and every K — the mask-key operand's leading axis is the committee
    width, so jit specializes per K, cached like any shape."""
    b0 = jit_telemetry.build_timer()

    def infer(variables, x, mask_keys):
        return short_cnn.qbdc_infer(variables, x, mask_keys, config)

    fn = jax.jit(infer)
    jit_telemetry.note_build("qbdc_infer",
                             build_s=jit_telemetry.build_timer() - b0,
                             jit_fns=(fn,))
    return fn


def _user_infer_fn(config: CNNConfig):
    jit_telemetry.note_lookup("cnn_infer_users")
    return _user_infer_fn_cached(config)


@functools.lru_cache(maxsize=None)
def _user_infer_fn_cached(config: CNNConfig):
    """Process-wide jitted CROSS-USER committee forward for ``config``:
    ``short_cnn.committee_infer_users`` over ``(U, M, …)`` stacked user
    params and ``(U, bucket, L)`` crop batches.  One cache entry per
    config; jit specializes per (U, M, bucket) shape, so each serve
    bucket's cohort geometry owns its compiled program — the per-width
    executable-lifetime property ``fleet_scoring_fns_for_width`` gives the
    reduction scorers, inherited here through shape keying."""
    b0 = jit_telemetry.build_timer()

    def infer(user_stacked, x):
        return short_cnn.committee_infer_users(user_stacked, x, config)

    fn = jax.jit(infer)
    jit_telemetry.note_build("cnn_infer_users",
                             build_s=jit_telemetry.build_timer() - b0,
                             jit_fns=(fn,))
    return fn


def _user_qbdc_infer_fn(config: CNNConfig):
    jit_telemetry.note_lookup("qbdc_infer_users")
    return _user_qbdc_infer_fn_cached(config)


@functools.lru_cache(maxsize=None)
def _user_qbdc_infer_fn_cached(config: CNNConfig):
    """Cross-user QBDC forward (``short_cnn.qbdc_infer_users``), cached
    like :func:`_user_infer_fn`.  Takes raw mask-key DATA ``(U, K, …)``
    (typed keys re-wrapped inside the jit)."""
    b0 = jit_telemetry.build_timer()

    def infer(user_variables, x, mask_key_data):
        return short_cnn.qbdc_infer_users(user_variables, x, mask_key_data,
                                          config)

    fn = jax.jit(infer)
    jit_telemetry.note_build("qbdc_infer_users",
                             build_s=jit_telemetry.build_timer() - b0,
                             jit_fns=(fn,))
    return fn


class Committee:
    """The user's private committee: M_host sklearn + M_cnn Flax members.

    ``device_members=True`` moves GNB/SGD *inference* on device too
    (``ops.device_members``): their closed-form probability math runs as
    jnp inside one jit with the frame→song segment mean, so only boosted
    trees (and any generic registry members) remain on host.  Training
    (``partial_fit``) stays in sklearn either way.

    ``mesh``: optional pool-axis :class:`jax.sharding.Mesh`.  When set, the
    CNN member forward (the committee's heavy op) is compiled with the crop
    batch sharded across every chip — the production counterpart of the
    sharded scorers in ``parallel.sharding``.  Crop batches are padded to a
    shard-divisible width (repeating the last crop) and sliced back, so the
    random-crop stream and the returned probabilities are identical to the
    single-device path.

    ``train_mesh``: optional ``(dp, member)`` :class:`jax.sharding.Mesh` for
    *retraining* (``parallel.mesh.make_training_mesh``).  When set,
    :meth:`retrain_cnns` shards the member-stacked training state across the
    ``member`` axis, so the AL iteration's dominant cost (the reference's
    100-epoch per-member retrain, ``amg_test.py:496-502``) splits across
    chips; a non-dividing committee is member-padded inside
    ``CNNTrainer.fit_many``.  Multi-host meshes work too: each process
    feeds its own member block (``multihost.feed_axis``) and the winning
    checkpoints are replicated back to every host at the end.
    """

    def __init__(self, host_members: list[Member],
                 cnn_members: list[CNNMember],
                 config: CNNConfig = CNNConfig(),
                 train_config: TrainConfig = TrainConfig(),
                 *, device_members: bool = False,
                 full_song_hop: int | None = None,
                 mesh=None, train_mesh=None, min_members: int = 1):
        self.host_members = host_members
        self.cnn_members = cnn_members
        #: member quarantine ("Wisdom of Committees": an ensemble tolerates
        #: member loss by construction — exploit it).  A member whose
        #: retrain/predict raises, or whose probability rows go non-finite,
        #: is quarantined for the rest of the user's run: it stops scoring,
        #: updating, and checkpointing (its on-disk file keeps the last
        #: good state), and the consensus mean renormalizes over the
        #: survivors.  The run aborts (CommitteeExhaustedError) only when
        #: fewer than ``min_members`` members survive.
        self.min_members = min_members
        self.quarantined: dict[str, str] = {}   # member name → reason
        self.quarantine_log: list[dict] = []    # full audit trail
        self._pending_events: list[dict] = []   # drained by the AL loop
        #: the gray-degradation depth dial (``fleet.scheduler.
        #: FleetScheduler.set_depth``): ``None`` = full committee; an int
        #: caps how many ACTIVE members score — CNN (device-stacked,
        #: fast) members keep their seats first, the slow host-member
        #: tail is shed.  Reversible and volatile: nothing checkpointed
        #: or journaled reads it, quarantine (permanent, audited) is
        #: unaffected, and clearing it restores every survivor.  Floored
        #: at ``min_members`` so degradation can never exhaust the
        #: committee.
        self.depth_cap: int | None = None
        if cnn_members:
            # the committee scores all CNN members as ONE stacked pytree, so
            # they must share a trunk family AND frontend geometry; the
            # committee config follows the members' (checkpoints know
            # theirs — CNNMember.load)
            keys = CNNMember.FRONTEND_META
            sigs = {tuple(getattr(m.config, k) for k in keys)
                    for m in cnn_members}
            if len(sigs) > 1:
                raise ValueError(
                    f"CNN members mix trunk families/frontend geometries "
                    f"{sorted(sigs)}; a committee maps one stacked pytree "
                    f"and needs one architecture")
            sig = sigs.pop()
            if sig != tuple(getattr(config, k) for k in keys):
                import dataclasses

                config = dataclasses.replace(config, **dict(zip(keys, sig)))
        self.config = config
        self.device_members = device_members
        #: When set, CNN members score each song as the masked mean over
        #: stride-``full_song_hop`` windows covering the whole waveform
        #: (deterministic), instead of the reference's ONE random crop per
        #: pass (``short_cnn.py:376-377`` — stochastic by design).
        if full_song_hop is not None and not (
                1 <= full_song_hop <= config.input_length):
            raise ValueError(
                f"full_song_hop must be in [1, input_length="
                f"{config.input_length}], got {full_song_hop}")
        self.full_song_hop = full_song_hop
        self.trainer = CNNTrainer(config, train_config)
        self.mesh = mesh
        self.train_mesh = train_mesh
        #: compiled sequence-parallel scorers keyed by (geometry, mesh);
        #: never invalidated — safe because scorers take the stacked member
        #: params as an argument, so retraining needs no cache flush
        self._seq_scorers: dict = {}

        if mesh is None:
            self._n_pool_shards = 1
        else:
            from consensus_entropy_tpu.parallel.mesh import POOL_AXIS

            self._n_pool_shards = mesh.shape[POOL_AXIS]
        self._infer, self._infer_windows = _infer_fns(self.config, mesh)

    # -- multi-host feeds (no-ops single-process) --------------------------

    def _feed_repl(self, pytree):
        """Replicated global feed of a host-local pytree (the stacked member
        params) for jits whose in_shardings span a multi-host mesh —
        committed process-local arrays cannot be implicitly resharded onto
        non-addressable devices.  Every process holds identical values, so
        replication is consistent."""
        import jax as _jax

        if self.mesh is None or _jax.process_count() == 1:
            return pytree
        from jax.experimental import multihost_utils
        from jax.sharding import PartitionSpec as P

        return multihost_utils.host_local_array_to_global_array(
            pytree, self.mesh, P())

    def _feed_rows(self, arr):
        """Pool-row feed: each process contributes its ``host_pool_slice``
        block (crops / window batches are shard-divisible, hence
        process-divisible).  Short-circuits single-process — crops are
        already device-resident and the helper's host round-trip would cost
        a transfer for nothing (jit's in_shardings handle placement)."""
        import jax as _jax

        if self.mesh is None or _jax.process_count() == 1:
            return arr
        from consensus_entropy_tpu.parallel import multihost

        return multihost.feed_pool_axis(arr, self.mesh, 0)

    def _gather_rows(self, out):
        """Inverse of the feeds: host-complete value of a pool-sharded
        forward output on every process (multi-host ``np.asarray`` on such
        an array raises — it spans non-addressable devices)."""
        import jax as _jax

        if self.mesh is None or _jax.process_count() == 1:
            return out
        from consensus_entropy_tpu.parallel import multihost

        return multihost.gather_to_host(out)

    @property
    def size(self) -> int:
        return len(self.host_members) + len(self.cnn_members)

    @property
    def member_names(self) -> list[str]:
        return ([m.name for m in self.cnn_members]
                + [m.name for m in self.host_members])

    # -- quarantine --------------------------------------------------------

    @staticmethod
    def _member_name(m) -> str:
        """Quarantine key for a member; duck-typed scoring-only members
        without a ``name`` (allowed by ``pool_probs``) key by type."""
        return getattr(m, "name", type(m).__name__)

    def _active_pair(self) -> tuple[list, list]:
        """(cnn, host) members still participating: quarantined members
        excluded, then the ``depth_cap`` dial applied jointly — CNN
        members (the device-stacked fast stage) keep their seats first,
        host members fill what the cap leaves.  Cap ``None`` (the
        default) is behavior-identical to the pre-dial committee."""
        cnn = [m for m in self.cnn_members
               if self._member_name(m) not in self.quarantined]
        host = [m for m in self.host_members
                if self._member_name(m) not in self.quarantined]
        if self.depth_cap is None:
            return cnn, host
        cap = max(int(self.depth_cap), int(self.min_members), 1)
        if len(cnn) + len(host) <= cap:
            return cnn, host
        kept_cnn = cnn[:cap]
        return kept_cnn, host[:cap - len(kept_cnn)]

    @property
    def active_host_members(self) -> list[Member]:
        """Host members still participating (quarantined ones excluded);
        identical to ``host_members`` until a quarantine fires or the
        depth dial caps the committee, so the unfaulted full-depth path
        is behavior-identical."""
        return self._active_pair()[1]

    @property
    def active_cnn_members(self) -> list[CNNMember]:
        return self._active_pair()[0]

    @property
    def active_size(self) -> int:
        return len(self.active_host_members) + len(self.active_cnn_members)

    def quarantine(self, name: str, reason: str) -> None:
        """Remove ``name`` from the run (idempotent).  Raises
        :class:`CommitteeExhaustedError` when the survivor count drops
        below ``min_members`` — degradation has a floor."""
        if name in self.quarantined:
            return
        self.quarantined[name] = reason
        event = {"member": name, "reason": reason}
        self.quarantine_log.append(event)
        self._pending_events.append(event)
        if self.active_size < self.min_members:
            raise CommitteeExhaustedError(
                f"{self.active_size} committee member(s) survive after "
                f"quarantining {name!r} ({reason}); floor is "
                f"min_members={self.min_members}")

    def drain_quarantine_events(self) -> list[dict]:
        """Events since the last drain (the AL loop forwards them into the
        per-user report)."""
        events, self._pending_events = self._pending_events, []
        return events

    def _stacked(self):
        return short_cnn.stack_params(
            [m.variables for m in self.active_cnn_members])

    def pool_probs(self, pool: FramePool | None,
                   store: DeviceWaveformStore | None,
                   song_ids: Sequence, key,
                   pad_to: int | None = None, *,
                   cnn_block=None) -> jnp.ndarray:
        """Stacked member probabilities ``(M, N, C)`` over ``song_ids``.

        CNN rows first (committee order = member_names).  Without
        ``full_song_hop``: one random crop per song per scoring pass, as the
        reference's batch-1 loader does (``amg_test.py:378-382``) —
        committee entropy is stochastic across passes by design (SURVEY.md
        §7 hard part 4).  With ``full_song_hop`` set the CNN block is the
        deterministic window-grid mean instead.

        ``pad_to`` (≥ ``len(song_ids)``): return ``(M, pad_to, C)`` whose
        tail columns are staging padding — well-formed probability rows of
        the last song (the CNN block's tail holds extra crop draws of it),
        but CONTENTS UNSPECIFIED by contract: the acquirer's scatter drops
        them.  The point is that every device program downstream (block
        concat, acquirer scatter) compiles at ONE width across the
        shrinking pool (``Acquirer.staging_width``).

        Return-type contract: a pure-host committee (no CNN members, no
        eligible ``device_members`` slice) returns ``np.ndarray`` — the
        acquirer then pads on host and uploads one fixed-shape table,
        compile-free.  Any committee with a device block returns a
        ``jax.Array`` that never round-trips through the host (the acquirer
        scatters it into its persistent padded buffer).  Mesh committees
        return ``np.ndarray`` (blocks carry different placements; the
        sharded scoring fns re-shard on upload).

        ``cnn_block``: a precomputed ``(M_cnn, width, C)`` CNN member block
        (the fleet scheduler's cross-user stacked dispatch hands each
        session its own rows) — used in place of
        :meth:`predict_songs_cnn`, which the single-user path still calls;
        the host-member block and the merge are identical either way.
        """
        n_live = len(song_ids)
        if pad_to is not None and pad_to < n_live:
            raise ValueError(f"pad_to={pad_to} < n={n_live}")
        active_host = self.active_host_members
        active_cnn = self.active_cnn_members
        if pad_to is not None and n_live == 0 and active_host:
            # the host block has no live row to stage from; the AL loop
            # breaks before scoring an empty pool, so fail loud here
            raise ValueError("pad_to requires at least one live song")
        blocks = []
        if active_cnn:
            if cnn_block is not None:
                # the cohort-stacked dispatch already produced this user's
                # rows (still an async device array; the host members below
                # compute while it resolves)
                blocks.append(cnn_block)
            else:
                assert store is not None
                # async dispatch either way; full_song_hop swaps the
                # reference's stochastic single crop for the deterministic
                # window grid
                blocks.append(self.predict_songs_cnn(store, song_ids, key,
                                                     pad_to=pad_to))
        width = n_live if pad_to is None else pad_to
        if active_host:
            assert pool is not None
            rowmap = {s: i for i, s in enumerate(pool.song_ids)}
            sel = np.array([rowmap[s] for s in song_ids])
            if width > n_live:  # fixed-width tail: repeat the last live row
                sel = np.concatenate([sel, np.repeat(sel[-1:],
                                                     width - n_live)])
            on_device, on_host = self._split_members()
            dev_block = None
            if on_device["gnb"] or on_device["sgd"]:
                # Dispatch the device slice FIRST (async) so the remaining
                # host members compute while the TPU runs.
                dev_block = self._device_member_probs(pool, on_device)[:, sel]
            host_np = np.empty((len(on_host), width, NUM_CLASSES),
                               np.float32)
            if on_host:
                # host members score ONLY the live songs' frames — the
                # serial host cost shrinks with the pool, as the reference's
                # does (amg_test.py:435 scores the live X_train)
                live_rows, seg_starts = pool.segment_view(song_ids)
                X_live = pool.X[live_rows]
                for slot, (_, m) in enumerate(on_host):
                    # A member whose predict raises or whose rows go
                    # non-finite is quarantined for the rest of the user's
                    # run; its slot is NaN'd so the acquirer's sanitizer
                    # renormalizes this iteration's consensus over the
                    # survivors (next iteration it isn't scored at all).
                    mname = self._member_name(m)
                    row = None
                    try:
                        frame_p = faults.fire(
                            "member.predict",
                            payload=m.predict_proba(X_live), member=mname)
                        row = pool.mean_over_segments(frame_p, seg_starts)
                    except Exception as e:
                        self.quarantine(mname, f"predict failed: {e!r}")
                    if row is not None and not np.all(np.isfinite(row)):
                        self.quarantine(mname,
                                        "non-finite probability rows")
                        row = None
                    if row is None:
                        host_np[slot] = np.nan
                    else:
                        host_np[slot, :n_live] = row
                host_np[:, n_live:] = host_np[:, n_live - 1: n_live]
            if dev_block is None:
                # pure-host slice stays NUMPY: for host-only committees the
                # acquirer then pads on host and uploads one fixed-shape
                # table (compile-free across the shrinking pool); committees
                # WITH a CNN block concatenate on device below
                blocks.append(host_np if not active_cnn else
                              jnp.asarray(host_np))
            else:
                # Merge device slice + one host buffer back into committee
                # member order via a permutation gather on device.
                combined = jnp.concatenate(
                    [dev_block, jnp.asarray(host_np)], axis=0)
                order = np.empty(len(active_host), np.int32)
                for slot, (i, _) in enumerate(on_device["gnb"]
                                              + on_device["sgd"]):
                    order[i] = slot
                n_dev = len(on_device["gnb"]) + len(on_device["sgd"])
                for slot, (i, _) in enumerate(on_host):
                    order[i] = n_dev + slot
                blocks.append(jnp.take(combined, jnp.asarray(order), axis=0))
        if len(blocks) == 1:
            return blocks[0]
        if self.mesh is not None:
            # Blocks carry different placements (mesh-sharded CNN block,
            # host/default-device tables); merge on host — the probs table is
            # tiny next to the CNN forward, and the sharded scoring fns
            # re-shard it on upload anyway.
            return np.concatenate([np.asarray(b) for b in blocks], axis=0)
        return jnp.concatenate(blocks, axis=0)

    def qbdc_pool_probs(self, store: DeviceWaveformStore | None, song_ids,
                        key, *, k: int, pad_to: int | None = None):
        """Query-by-dropout-committee probabilities ``(K, N, C)`` over
        ``song_ids`` — or ``(K, pad_to, C)`` with the same staging-tail
        contract as :meth:`pool_probs`.

        ONE personalized CNN (the committee's first active CNN member — the
        single network QBDC personalizes per user) forwarded under ``k``
        seeded dropout masks (``short_cnn.qbdc_infer``): the committee axis
        of the consensus entropy becomes a vmap width instead of stored
        models.  Crop sampling reuses :meth:`predict_songs_cnn`'s
        compile-bucket discipline (prefix-stable threefry, 256-wide
        slices), so the crop stream and compile behavior match the stored-
        committee path.

        Determinism contract: ``key`` is the AL iteration's PRNG key; it
        splits into a crop key and a mask key, and the K member keys fold
        deterministically from the latter — so the dropout committee is
        bit-identical across checkpoint resume, fleet eviction/resume and
        serve-journal restart (the ``acquire.qbdc.masks`` fault point fires
        at the sampler so kill drills land exactly there).  Masks are
        unit-level per member (see ``qbdc_infer``), hence independent of
        pool width and staging padding.
        """
        active = self.active_cnn_members
        if not active:
            raise ValueError(
                "qbdc acquisition needs a committee with at least one "
                "(active) CNN member — the dropout committee is K masked "
                "forwards of that network")
        if self.mesh is not None:
            raise NotImplementedError(
                "qbdc scoring is single-mesh only (stack users via "
                "--fleet/--serve instead of sharding one pool)")
        if k < 1:
            raise ValueError(f"qbdc committee width must be >= 1, got {k}")
        if store is None:
            # fail loud like pool_probs: a zeros return would sanitize to
            # uniform rows and silently degrade selection to a tie-break
            raise ValueError(
                "qbdc scoring needs the device waveform store (the masked "
                "forwards run on raw crops); build UserData with a "
                "DeviceWaveformStore")
        rows = store.row_of(song_ids)
        if pad_to is not None and pad_to < len(rows):
            raise ValueError(f"pad_to={pad_to} < n={len(rows)}")
        if len(rows) == 0:
            return jnp.zeros((k, pad_to or 0, self.config.n_class),
                             jnp.float32)
        crops, mask_keys = self._qbdc_stage(store, rows, key, k)
        infer = _qbdc_infer_fn(self.config)
        variables = self.active_cnn_members[0].variables
        # bucket-wide sub-dispatches bound the trunk's activation
        # transient for any pool size (see predict_songs_cnn); the mask
        # keys are unit-level so every slice sees the same K subnetworks
        bucket = self.CROP_BUCKET
        sub = [infer(variables,
                     jax.lax.dynamic_slice_in_dim(crops, lo, bucket),
                     mask_keys)
               for lo in range(0, crops.shape[0], bucket)]
        out = _concat_member_blocks(sub)
        return self._keep_columns(
            out, len(rows) if pad_to is None else pad_to)

    #: crop compile-bucket width — matches ``Acquirer.STAGING_BUCKET`` so
    #: the whole scoring chain quantizes to the same shapes
    CROP_BUCKET = 256

    def _qbdc_stage(self, store: DeviceWaveformStore, rows, key, k: int):
        """Stage one qbdc scoring pass: split the iteration key into crop
        and mask streams, fire the ``acquire.qbdc.masks`` fault point, and
        sample the bucket-padded crop batch.  Shared VERBATIM by the
        single-user forward above and the cross-user stacked dispatch
        (:func:`run_device_plans`), so the crop/mask streams — and the
        fault-point hit counts kill drills key on — are identical on both
        paths.  Returns ``(crops, mask_keys)``."""
        crop_key, mask_key = jax.random.split(jnp.asarray(key))
        faults.fire("acquire.qbdc.masks", k=int(k))
        mask_keys = jax.random.split(mask_key, k)
        return self._bucketed_crops(store, rows, crop_key), mask_keys

    def _bucketed_crops(self, store: DeviceWaveformStore, rows, key):
        """Bucket-padded crop batch for ``rows`` (the 256-crop compile
        discipline of :meth:`predict_songs_cnn`, factored so the stacked
        cross-user path samples the identical stream).  Requires
        prefix-stable threefry — checked at the point of reliance, not at
        import (see the inline rationale at :meth:`predict_songs_cnn`)."""
        import math

        if not jax.config.jax_threefry_partitionable:
            raise RuntimeError(
                "jax_threefry_partitionable is off; crop compile-buckets "
                "require prefix-stable threefry — enable the flag (the "
                "modern JAX default) to use the CNN scoring path")
        bucket = math.lcm(self.CROP_BUCKET, self._n_pool_shards)
        pad = -len(rows) % bucket
        rows_in = np.concatenate([rows, np.repeat(rows[-1:], pad)]) \
            if pad else rows
        return store.sample_crops(key, rows_in)

    @staticmethod
    def _keep_columns(out, keep: int):
        """Slice a bucket-wide member/mask block to the staging width,
        extending with repeats of the last column for an out-of-contract
        ``pad_to`` beyond the compile bucket (``Acquirer.staging_width``
        never requests this; the shape contract is honored anyway)."""
        if keep > out.shape[1]:
            out = jnp.concatenate(
                [out, jnp.repeat(out[:, -1:], keep - out.shape[1],
                                 axis=1)], axis=1)
        return out[:, :keep] if keep != out.shape[1] else out

    # -- device-side GNB/SGD inference (ops.device_members) ----------------

    def _split_members(self):
        """Partition host members into device-representable GNB/SGD slices
        and the host remainder (trees, generic registry members, anything
        not fitted on the full class universe)."""
        from sklearn.linear_model import SGDClassifier
        from sklearn.naive_bayes import GaussianNB

        out = {"gnb": [], "sgd": []}
        rest = []
        active = self.active_host_members
        if not self.device_members:
            return out, list(enumerate(active))
        for i, m in enumerate(active):
            est = getattr(m, "estimator", None)
            full = (est is not None
                    and np.array_equal(getattr(est, "classes_", ()),
                                       np.arange(NUM_CLASSES)))
            if full and isinstance(est, GaussianNB):
                out["gnb"].append((i, est))
            elif (full and isinstance(est, SGDClassifier)
                  and est.loss == "log_loss"
                  and est.coef_.shape[0] == NUM_CLASSES):
                out["sgd"].append((i, est))
            else:
                rest.append((i, m))
        return out, rest

    def _device_member_probs(self, pool: FramePool, on_device) -> jnp.ndarray:
        """(G+S, n_songs, C) per-song means for the device slice, one jit.

        The compiled scorer AND the device-resident float32 copy of the
        (static) pool features are cached ON the pool object, so their
        lifetime is the pool's (no id-reuse aliasing) and the per-iteration
        cost is just the few-KB parameter transfer.

        Deliberate static-graph trade: this path scores the FULL pool every
        iteration and column-slices the live songs after, while the host
        path scores live rows only.  Under XLA's static shapes a live-row
        variant would either recompile per pool width (10 compiles/user) or
        gather rows into a fixed-width buffer (same FLOPs as scoring them).
        The whole-table cost is ~1.4 ms at the 100k benchmark scale and
        microseconds at AMG scale — the "wasted" late-iteration math is
        cheaper than either alternative, so the fixed shape wins.
        """
        from consensus_entropy_tpu.ops.device_members import (
            make_device_committee_scorer,
        )

        cache = getattr(pool, "_ce_device_cache", None)
        if cache is None:
            frame_song = np.repeat(np.arange(pool.n_songs), pool.counts)
            cache = {
                "scorer": make_device_committee_scorer(frame_song,
                                                       pool.n_songs),
                "x_dev": jnp.asarray(
                    np.asarray(pool.X, dtype=np.float32)),
            }
            pool._ce_device_cache = cache
        scorer, x_dev = cache["scorer"], cache["x_dev"]
        n_feat = pool.X.shape[1]
        gnb = [e for _, e in on_device["gnb"]]
        sgd = [e for _, e in on_device["sgd"]]
        gnb_theta = np.stack([e.theta_ for e in gnb]) if gnb else \
            np.zeros((0, NUM_CLASSES, n_feat))
        gnb_var = np.stack([e.var_ for e in gnb]) if gnb else \
            np.zeros((0, NUM_CLASSES, n_feat))
        gnb_lp = np.stack([np.log(e.class_prior_) for e in gnb]) if gnb else \
            np.zeros((0, NUM_CLASSES))
        sgd_coef = np.stack([e.coef_ for e in sgd]) if sgd else \
            np.zeros((0, NUM_CLASSES, n_feat))
        sgd_int = np.stack([e.intercept_ for e in sgd]) if sgd else \
            np.zeros((0, NUM_CLASSES))
        return scorer(x_dev,
                      gnb_theta.astype(np.float32),
                      gnb_var.astype(np.float32),
                      gnb_lp.astype(np.float32),
                      sgd_coef.astype(np.float32),
                      sgd_int.astype(np.float32))

    def update_host(self, X_batch: np.ndarray, y_batch: np.ndarray):
        """Incremental update of every active host member
        (``amg_test.py:503-509``).  A member whose update raises is
        quarantined (its checkpoint file keeps the last good state — the
        member is skipped by ``begin_save`` from here on) instead of one
        failing ``partial_fit`` killing the whole user sweep."""
        for m in self.active_host_members:
            mname = self._member_name(m)
            try:
                faults.fire("member.retrain", member=mname)
                m.update(X_batch, y_batch)
            except Exception as e:
                self.quarantine(mname, f"retrain failed: {e!r}")

    def update_host_gated(self, X_batch: np.ndarray, y_batch: np.ndarray,
                          X_val: np.ndarray, y_val,
                          before_scores=None) -> dict:
        """Validation-gated incremental update: each host member's update
        is KEPT only if its weighted F1 on ``(X_val, y_val)`` does not
        drop; otherwise the member's pre-update state is restored.

        This is the host-member analogue of the best-checkpoint gate the
        reference already applies to its CNN members (``amg_test.py:
        267-273`` refuses to keep a worse epoch, scored on the same test
        split this gate uses) — extended to ``partial_fit``/boosting
        members, whose corruption by uncertainty-dense query batches the
        round-5 evidence measured directly (``EVIDENCE_r05.json``
        mechanism_study: sgd Δ down to −0.26 under mc).  An extension the
        reference lacks, opt-in via ``ALConfig.gate_host_updates``; both
        acquisition arms of any comparison get the identical gate, so
        matched-budget statistics stay matched.

        ``before_scores``: optional per-member pre-update F1s on the SAME
        (X_val, y_val) in ``host_members`` order — the AL loop passes the
        previous iteration's evaluation scores (identical split, identical
        metric, member state unchanged in between), saving one full
        test-split predict per member per iteration.

        Returns ``{member name: kept}``."""
        import copy

        from consensus_entropy_tpu.al.reporting import weighted_f1

        active = [(i, m) for i, m in enumerate(self.host_members)
                  if self._member_name(m) not in self.quarantined]
        if before_scores is not None and len(before_scores) != len(active):
            # a quarantine between the evaluation that produced the scores
            # and this update shifted the member list; recompute rather
            # than pair scores with the wrong members
            before_scores = None
        kept: dict = {}
        for pos, (i, m) in enumerate(active):
            before = copy.deepcopy(m)
            try:
                f1_before = (before_scores[pos]
                             if before_scores is not None
                             else weighted_f1(y_val, m.predict(X_val)))
                faults.fire("member.retrain", member=m.name)
                m.update(X_batch, y_batch)
                worse = weighted_f1(y_val, m.predict(X_val)) < f1_before
            except Exception as e:
                # restore the pre-update state so the quarantined member's
                # next checkpoint (none — begin_save skips it) and any
                # in-memory reads see the last good weights
                self.host_members[i] = before
                self.quarantine(m.name, f"retrain failed: {e!r}")
                continue
            if worse:
                self.host_members[i] = before
                kept[m.name] = False
            else:
                kept[m.name] = True
        return kept

    def retrain_cnns(self, store: DeviceWaveformStore, train_ids, train_y,
                     test_ids, test_y, key, *, n_epochs: int | None = None):
        """Retrain every CNN member on the queried songs (hot loop #2,
        ``amg_test.py:496-502``); members get distinct crop/dropout streams
        (member ``i`` under ``fold_in(key, i)``).

        All members train in lockstep as ONE jit per epoch
        (``CNNTrainer.fit_many``) — the schedule is epoch-indexed, so this
        is exact, and retrain wall-clock stops scaling linearly in M.  With
        ``train_mesh`` set the member-stacked state is additionally sharded
        across chips on the ``member`` axis."""
        faults.fire("member.retrain", member="__cnn_stack__")
        active_cnn = self.active_cnn_members
        best, histories = self.trainer.fit_many(
            [m.variables for m in active_cnn], store, train_ids,
            train_y, test_ids, test_y, key,
            n_epochs=(self.trainer.train_config.n_epochs_retrain
                      if n_epochs is None else n_epochs),
            mesh=self.train_mesh)
        for m, b, h in zip(active_cnn, best, histories):
            # A member with no improved epoch returns its incoming weights
            # (best-checkpoint gate starts at score 0, amg_test.py:295):
            # keep the old tree so the member stays checkpoint-clean and
            # the next begin_save skips its device→host fetch entirely.
            if any(e["improved"] for e in h):
                m.variables = b
        return histories

    def predict_songs_cnn(self, store: DeviceWaveformStore, song_ids, key,
                          *, chunk: int = 8, pad_to: int | None = None):
        """Per-song CNN scores ``(M_cnn, n, C)`` — or ``(M_cnn, pad_to, C)``.

        Default: one random crop per song (reference parity).  With
        ``full_song_hop`` set: deterministic masked mean over the stride
        grid, processed ``chunk`` songs at a time so the ``(chunk, W, L)``
        window tensor bounds device memory.  Every batch (including the
        last and any n < chunk call) is padded to exactly ``chunk`` rows,
        so ONE program compiles per (chunk, W) shape.

        ``pad_to`` (≥ n): return a fixed-width block whose columns
        ``[n, pad_to)`` are the internal compile-bucket padding un-sliced
        (extra crop draws of song ``n-1``; dropped by the acquirer's
        scatter).  The acquirer requests its staging width here
        so the scoring chain — CNN forward, block concat, probs scatter —
        runs at ONE device shape across the shrinking pool instead of
        recompiling per live-width (see ``Acquirer.staging_width``).
        """
        rows = store.row_of(song_ids)
        if pad_to is not None and pad_to < len(rows):
            raise ValueError(f"pad_to={pad_to} < n={len(rows)}")
        if self.full_song_hop is None:
            if len(rows) == 0:
                return jnp.zeros((len(self.active_cnn_members), pad_to or 0,
                                  self.config.n_class), jnp.float32)
            # The row batch is padded (repeating the last row, sliced back
            # off) to a shard-divisible COMPILE BUCKET before sampling: the
            # AL pool shrinks by q songs per iteration, and without
            # bucketing every iteration's new width recompiled the
            # full-geometry committee forward (~30 s/compile on the TPU —
            # measured as the dominant `score` cost in the production
            # loop) plus the crop-sampling gather.  The real rows' crop
            # stream is unchanged: threefry draws are prefix-stable in the
            # batch width (pinned by tests).  A 256-wide bucket bounds a
            # whole reference run (10 iterations x q=10 = 100 songs
            # retired) to at most one bucket transition; the waste ceiling
            # is ~255 crops ≈ 90 ms of forward math per pass — noise next
            # to one avoided compile.
            import math

            # The bucket padding (_bucketed_crops) is only sound when
            # threefry draws are prefix-stable across batch widths (the
            # modern JAX default).  Checked there, at the point of
            # reliance — NOT a package import-time config mutation, which
            # would silently change an embedding application's unrelated
            # jax.random streams on a JAX defaulting the flag off — so a
            # config flip fails loudly instead of silently diverging the
            # crop stream.
            bucket = math.lcm(self.CROP_BUCKET, self._n_pool_shards)
            crops = self._bucketed_crops(store, rows, key)
            stacked = self._feed_repl(self._stacked())
            # Forward in BUCKET-wide sub-dispatches, not one batch: at full
            # geometry the first conv block materializes ~15 MB/member-crop,
            # so a single dispatch over a >=1536-crop pool (a user with
            # ~1300+ annotated train songs) exceeds the 16 GB HBM and fails
            # to COMPILE (measured: f32[1536,128,231,128] = 23.3 GB
            # allocation rejected on v5e).  Bucket-wide slices bound the
            # transient to ~3.9 GB for ANY pool size, compile ONE forward
            # program ever (every slice is exactly `bucket` wide), and cost
            # ~3% vs the fused batch at 512 crops (measured 306 vs 298 ms).
            # Crops are SAMPLED at the full width first, so the random
            # stream is identical to the unsliced batch.
            sub = [self._gather_rows(self._infer(stacked, self._feed_rows(
                jax.lax.dynamic_slice_in_dim(crops, lo, bucket))))
                   for lo in range(0, crops.shape[0], bucket)]
            out = _concat_member_blocks(sub)
            # slice to the STAGING width, not the live width: the bucket
            # quantizes the slice program to ~n_pad/256 shapes per run
            return self._keep_columns(
                out, len(rows) if pad_to is None else pad_to)
        n = len(rows)
        # each window chunk is one sharded dispatch; keep it shard-divisible
        chunk = _round_up(chunk, self._n_pool_shards)
        stacked = self._feed_repl(self._stacked())
        if n == 0:
            m = len(self.active_cnn_members)
            return jnp.zeros((m, pad_to or 0, self.config.n_class),
                             jnp.float32)
        blocks = []
        for lo in range(0, n, chunk):
            sel = rows[lo: lo + chunk]
            pad = chunk - len(sel)
            if pad:
                sel = np.concatenate([sel, np.repeat(sel[-1:], pad)])
            windows, valid = store.window_batch(sel, self.full_song_hop)
            out = self._gather_rows(self._infer_windows(
                stacked, self._feed_rows(windows), self._feed_rows(valid)))
            blocks.append(out[:, : out.shape[1] - pad])
        out = _concat_member_blocks(blocks)
        if pad_to is not None and pad_to > out.shape[1]:
            # window-grid path: extend with repeats of the last real column
            # (same tail contract as the crop path's bucket padding)
            xp = np if isinstance(out, np.ndarray) else jnp
            out = xp.concatenate(
                [out, xp.repeat(out[:, -1:], pad_to - out.shape[1], axis=1)],
                axis=1)
        return out

    def predict_song_sequence(self, wave, seq_mesh, *, hop: int | None = None):
        """Sequence-parallel full-song CNN scoring: ``(M_cnn, C)``.

        The long-audio production path (``parallel.sequence``): the song's
        window axis is sharded over ``seq_mesh``'s ``seq`` axis with a ring
        halo exchange, so minutes-long waveforms score without replicating
        the audio per chip.  Compiled scorers are cached per (plan, mesh):
        songs that fall on the same padded geometry reuse one XLA program.
        Use :meth:`predict_songs_cnn` for pools of short excerpts — this
        method is for waveforms that dwarf ``config.input_length``.
        """
        from consensus_entropy_tpu.parallel.mesh import SEQ_AXIS
        from consensus_entropy_tpu.parallel.sequence import (
            make_full_song_scorer,
            pad_song,
            plan_windows,
        )

        if not self.active_cnn_members:
            raise ValueError("committee has no CNN members to score with")
        if jax.process_count() > 1:
            # the seq scorers take host-local stacked params / padded waves;
            # multi-host would need global feeds (_feed_repl + a seq-axis
            # feed) that are deliberately not wired — fail loud rather than
            # crash inside jit with a resharding error
            raise NotImplementedError(
                "predict_song_sequence is single-host-only (shard long "
                "audio over one host's chips; multi-host pools use "
                "predict_songs_cnn)")
        wave = np.asarray(wave, np.float32)
        plan = plan_windows(wave.shape[0], seq_mesh.shape[SEQ_AXIS],
                            window=self.config.input_length,
                            hop=self.full_song_hop if hop is None else hop)
        # Key by compiled geometry (n_windows is a dynamic operand of the
        # scorer) and by mesh VALUE — Mesh hashes by devices+axes, so
        # per-call make_seq_mesh() constructions still hit the cache.
        key = (plan.windows_per_shard, plan.chunk_len, plan.halo,
               plan.window, plan.hop, seq_mesh)
        scorer = self._seq_scorers.get(key)
        if scorer is None:
            scorer = self._seq_scorers[key] = make_full_song_scorer(
                seq_mesh, plan, self.config)
        return scorer(self._stacked(), jnp.asarray(pad_song(wave, plan)),
                      plan.n_windows)

    # -- cross-user device plans (fleet stacked dispatch) ------------------

    def cnn_score_plan(self, store: DeviceWaveformStore | None, song_ids,
                       key, *, pad_to: int) -> "CNNScorePlan | None":
        """Stage this committee's CNN scoring pass as a batchable plan.

        The fleet scheduler groups same-signature plans from a cohort and
        runs them as ONE stacked device dispatch
        (:func:`run_device_plans`); the sequential driver and any
        batch-of-one falls back to :meth:`predict_songs_cnn` unchanged.
        Returns ``None`` when this committee can't ride the stacked path
        (no active CNN members, pool-sharded mesh, window-grid scoring, no
        device store) — the caller then uses the inline path."""
        if (not self.active_cnn_members or self.mesh is not None
                or self.full_song_hop is not None or store is None
                or not len(song_ids)):
            return None
        return CNNScorePlan(self, store, tuple(song_ids), key, pad_to,
                            len(self.active_cnn_members))

    def eval_plan(self, store: DeviceWaveformStore | None, song_ids,
                  key) -> "CNNEvalPlan | None":
        """Stage the per-epoch EVAL forward (``predict_songs_cnn`` over the
        test split, no staging pad — the eval consumes exactly ``n`` rows)
        as a batchable plan, so a cohort's evaluations ride ONE stacked
        dispatch instead of hiding a full 256-crop forward inside each
        user's host eval block.  Same eligibility rules as
        :meth:`cnn_score_plan`."""
        if (not self.active_cnn_members or self.mesh is not None
                or self.full_song_hop is not None or store is None
                or not len(song_ids)):
            return None
        return CNNEvalPlan(self, store, tuple(song_ids), key, len(song_ids),
                           len(self.active_cnn_members))

    def qbdc_score_plan(self, store: DeviceWaveformStore | None, song_ids,
                        key, *, k: int, pad_to: int) -> "QBDCScorePlan | None":
        """qbdc sibling of :meth:`cnn_score_plan`: one personalized CNN ×
        ``k`` dropout masks, stacked ``(U, K)`` across the cohort.
        ``None`` routes the caller to :meth:`qbdc_pool_probs`, whose
        upfront validation raises the proper errors."""
        if (not self.active_cnn_members or self.mesh is not None
                or store is None or k < 1 or not len(song_ids)):
            return None
        return QBDCScorePlan(self, store, tuple(song_ids), key, int(k),
                             pad_to)

    def retrain_plan(self, store: DeviceWaveformStore, train_ids, train_y,
                     test_ids, test_y, key, *,
                     n_epochs: int | None = None) -> "CNNRetrainPlan | None":
        """Stage :meth:`retrain_cnns` as a batchable plan: same-signature
        cohorts train in user-lockstep through
        ``CNNTrainer.fit_many_users`` — one jit dispatch per schedule
        phase for the WHOLE cohort instead of per user.  ``None`` (mesh
        retraining, host store, no active members, empty splits) falls
        back to the per-user path."""
        if (not self.active_cnn_members or self.train_mesh is not None
                or self.mesh is not None or store is None
                or not hasattr(store, "data")
                or not len(train_ids) or not len(test_ids)):
            return None
        members = tuple(self.active_cnn_members)
        return CNNRetrainPlan(
            self, members, store, tuple(train_ids), np.asarray(train_y),
            tuple(test_ids), np.asarray(test_y), key,
            (self.trainer.train_config.n_epochs_retrain
             if n_epochs is None else int(n_epochs)))

    # -- persistence -------------------------------------------------------

    def save(self, directory: str):
        self.begin_save(directory)()

    def begin_save(self, directory: str, *, reuse_dir: str | None = None,
                   dtype: str | None = None):
        """Split checkpointing into a synchronous SNAPSHOT and a deferred
        WRITE: host members (KB pickles, mutated in place by the next
        ``partial_fit``) are written immediately; CNN members only need
        their variable REFERENCES captured — retraining rebinds
        ``m.variables`` to new arrays, never mutates the old ones — so the
        expensive device→host fetch rides the deferred callable too.  The
        callable does ONE batched ``device_get`` (per-member, let alone
        per-leaf, fetches serialize ~90 ms tunnel round-trips) and is safe
        to run on another thread while the committee keeps training — the
        AL loop overlaps it with the next iteration's compute
        (``al.loop``).

        ``reuse_dir``: the directory whose files this checkpoint's promote
        will leave in place for anything not written here — i.e. the live
        workspace the committee was loaded from / last checkpointed into.
        Members whose variables have not been rebound since their last
        snapshot (``ckpt_dirty`` false) AND whose recorded
        ``ckpt_clean_path`` is exactly ``reuse_dir``'s file are SKIPPED:
        that file provably holds their current content.  A clean member
        loaded from a DIFFERENT directory (e.g. a pretrain registry) is
        still written — a same-named file already in the workspace could
        be a stale leftover, and adopting it would silently commit the
        wrong weights.  Callers persisting to a fresh directory (pretrain
        registry ``save``) leave ``reuse_dir`` ``None`` and every member
        is written.

        ``dtype="bfloat16"``: cast the fetch on device before the
        device→host copy — halves checkpoint traffic; restore casts back
        to f32 (see ``ALConfig.ckpt_dtype`` for the resume-rounding
        contract)."""
        os.makedirs(directory, exist_ok=True)
        # quarantined members are skipped: their in-memory state may be
        # mid-failure, and skipping leaves their last-good file live
        for m in self.active_host_members:
            p = os.path.join(directory, f"classifier_{m.kind}.{m.name}.pkl")
            m.save(p)
            faults.fire("checkpoint.write", payload=p, member=m.name)

        def fname(m):
            return f"classifier_cnn.{m.name}.msgpack"

        def provably_current(m):
            if reuse_dir is None or m.ckpt_dirty:
                return False
            target = os.path.abspath(os.path.join(reuse_dir, fname(m)))
            return (getattr(m, "ckpt_clean_path", None) == target
                    and os.path.exists(target))

        to_write = [m for m in self.active_cnn_members
                    if not provably_current(m)]
        if dtype in (None, "float32"):
            snapshot = [(m, m.variables) for m in to_write]
        elif dtype == "bfloat16":
            # one tiny async dispatch per member; the halved bytes are
            # what the deferred device_get moves over the link
            snapshot = [(m, _cast_tree_bf16(m.variables)) for m in to_write]
        else:
            raise ValueError(f"unsupported checkpoint dtype {dtype!r}")
        for m in to_write:
            # synchronous clear (single-threaded with retrain_cnns): the
            # submitted job's failure is surfaced by the checkpointer's
            # next wait(), which aborts the run — so a cleared flag never
            # silently outlives a lost write.  The clean provenance is the
            # POST-PROMOTE location (reuse_dir) when known; a direct save
            # (no staging) is clean against the directory written.
            m.ckpt_dirty = False
            m.ckpt_clean_path = os.path.abspath(os.path.join(
                reuse_dir if reuse_dir is not None else directory,
                fname(m)))

        def finish():
            import time

            t0 = time.perf_counter()
            fetched = jax.device_get([v for _, v in snapshot])
            t1 = time.perf_counter()
            for (m, _), v in zip(snapshot, fetched):
                m.save(os.path.join(directory, fname(m)), variables=v)
            # self-timed so the AL loop can surface the background fetch
            # (tunnel-bound d2h) separately from foreground phase time
            return {"fetch_s": t1 - t0,
                    "write_s": time.perf_counter() - t1,
                    "n_members_fetched": len(snapshot)}

        return finish


# -- cross-user device plans ------------------------------------------------
#
# The fleet scheduler's batching seam for the CNN device path: a session
# whose committee can stack yields a plan instead of running its forward /
# retrain inline; the scheduler groups plans by ``group_key()`` (one entry
# per architecture × member-count × crop-bucket × staging-width cohort) and
# services each multi-session group with ONE stacked dispatch
# (:func:`run_device_plans` → ``lax.map`` over the users axis — bit-identical
# per-user rows, see ``short_cnn.committee_infer_users``).  Groups of one —
# and the sequential driver — use the session's own single-user closure, so
# the per-user jitted path stays the ground truth.


@dataclasses.dataclass
class CNNScorePlan:
    """One user's staged stored-committee CNN scoring pass (mc/mix/wmc
    probs producer).  ``pad_to`` is the acquirer's staging width; crops are
    sampled lazily at dispatch with the SAME helper the single-user path
    uses (``Committee._bucketed_crops``), so the crop stream is identical
    regardless of which path runs."""

    committee: Committee
    store: DeviceWaveformStore
    song_ids: tuple
    key: object
    pad_to: int
    n_members: int

    fn_key = "cnn_probs"
    #: fault point fired per plan on the stacked path — mirrors the
    #: single-user closure's wrapping (the scoring pass fires
    #: ``pool.score``; the eval forward fires none), so fault-injection
    #: hit counts are identical on both paths
    fault_point = "pool.score"

    def group_key(self):
        bucket = Committee.CROP_BUCKET
        n_pad = -(-len(self.song_ids) // bucket) * bucket
        return (self.fn_key, self.committee.config, self.n_members, n_pad,
                self.pad_to)

    @staticmethod
    def run_many(plans: list["CNNScorePlan"]):
        config = plans[0].committee.config
        bucket = Committee.CROP_BUCKET
        crops = jnp.stack([
            p.committee._bucketed_crops(p.store, p.store.row_of(p.song_ids),
                                        p.key)
            for p in plans])
        user_stacked = short_cnn.stack_user_params(
            [p.committee._stacked() for p in plans])
        infer = _user_infer_fn(config)
        # same bucket-wide sub-dispatch discipline as predict_songs_cnn:
        # the mapped body bounds the activation transient per user, and the
        # (U, M, bucket) program compiles once per cohort geometry
        sub = [infer(user_stacked,
                     jax.lax.dynamic_slice_in_dim(crops, lo, bucket, axis=1))
               for lo in range(0, crops.shape[1], bucket)]
        out = jnp.concatenate(sub, axis=2) if len(sub) > 1 else sub[0]
        res = [Committee._keep_columns(out[i], p.pad_to)
               for i, p in enumerate(plans)]
        if plans[0].fault_point:
            res = [faults.fire(plans[0].fault_point, payload=r)
                   for r in res]
        return res


class CNNEvalPlan(CNNScorePlan):
    """One user's staged EVAL forward: ``predict_songs_cnn`` over the test
    split, batchable exactly like the scoring pass (same crop helper, same
    stacked infer body) so a cohort's per-epoch evaluations ride ONE
    device dispatch and the eval's remainder (sklearn predicts + metrics)
    stays a pure-host block on the worker pool.  No ``pool.score`` fault
    point: the single-user eval path fires none."""

    fn_key = "cnn_eval"
    fault_point = None


@dataclasses.dataclass
class QBDCScorePlan:
    """One user's staged qbdc scoring pass: ONE personalized CNN × ``k``
    seeded dropout masks.  Key split / mask derivation / the
    ``acquire.qbdc.masks`` fault point run per user through the same
    ``Committee._qbdc_stage`` the single-user forward uses, so the dropout
    committee is bit-identical on both paths."""

    committee: Committee
    store: DeviceWaveformStore
    song_ids: tuple
    key: object
    k: int
    pad_to: int

    fn_key = "qbdc_probs"

    def group_key(self):
        bucket = Committee.CROP_BUCKET
        n_pad = -(-len(self.song_ids) // bucket) * bucket
        return (self.fn_key, self.committee.config, self.k, n_pad,
                self.pad_to)

    @staticmethod
    def run_many(plans: list["QBDCScorePlan"]):
        config = plans[0].committee.config
        bucket = Committee.CROP_BUCKET
        staged = [p.committee._qbdc_stage(
                      p.store, p.store.row_of(p.song_ids), p.key, p.k)
                  for p in plans]
        crops = jnp.stack([c for c, _ in staged])
        # typed keys don't jnp.stack portably: ship raw key data, re-wrap
        # inside the mapped body (short_cnn.qbdc_infer_users)
        mask_data = jnp.stack([jax.random.key_data(mk) for _, mk in staged])
        variables = short_cnn.stack_user_params(
            [p.committee.active_cnn_members[0].variables for p in plans])
        infer = _user_qbdc_infer_fn(config)
        sub = [infer(variables,
                     jax.lax.dynamic_slice_in_dim(crops, lo, bucket, axis=1),
                     mask_data)
               for lo in range(0, crops.shape[1], bucket)]
        out = jnp.concatenate(sub, axis=2) if len(sub) > 1 else sub[0]
        return [faults.fire(
                    "pool.score",
                    payload=Committee._keep_columns(out[i], p.pad_to))
                for i, p in enumerate(plans)]


@dataclasses.dataclass
class CNNRetrainPlan:
    """One user's staged committee retrain (``Committee.retrain_cnns``
    semantics).  Same-signature cohorts train in USER lockstep — the
    epoch-indexed schedule makes this exact, just as member lockstep is
    (``CNNTrainer.fit_many``) — and each member's best-checkpoint gate /
    rebinding applies per user exactly as the single path does."""

    committee: Committee
    members: tuple
    store: DeviceWaveformStore
    train_ids: tuple
    train_y: np.ndarray
    test_ids: tuple
    test_y: np.ndarray
    key: object
    n_epochs: int

    fn_key = "cnn_retrain"

    def group_key(self):
        return (self.fn_key, self.committee.config,
                self.committee.trainer.train_config, len(self.members),
                len(self.train_ids), len(self.test_ids), self.n_epochs,
                tuple(self.store.data.shape))

    @staticmethod
    def run_many(plans: list["CNNRetrainPlan"]):
        # PURE compute: fit the cohort and return the raw ``fit_many_users``
        # result — member rebinding lives in :meth:`apply_many` so a
        # watchdog-abandoned stacked dispatch (a zombie thread the
        # scheduler has already fallen back from) can never mutate live
        # committees when it eventually finishes.  The per-user fault
        # point fires for every cohort member, exactly once per retrain,
        # as retrain_cnns does on the single path.
        for _ in plans:
            faults.fire("member.retrain", member="__cnn_stack__")
        trainer = plans[0].committee.trainer
        return trainer.fit_many_users(
            [dict(variables_list=[m.variables for m in p.members],
                  store=p.store, train_ids=list(p.train_ids),
                  train_y=p.train_y, test_ids=list(p.test_ids),
                  test_y=p.test_y, key=p.key)
             for p in plans],
            n_epochs=plans[0].n_epochs)

    @staticmethod
    def apply_many(plans: list["CNNRetrainPlan"], fitted):
        """COMMIT the pure :meth:`run_many` result: the best-checkpoint
        gate + member rebinding of ``retrain_cnns``, run by the caller
        AFTER the (possibly watchdog-bounded) dispatch returned — never
        inside it."""
        out = []
        for p, (best, histories) in zip(plans, fitted):
            for m, b, h in zip(p.members, best, histories):
                # the best-checkpoint gate of retrain_cnns: a member with
                # no improved epoch keeps its incoming tree (and stays
                # checkpoint-clean)
                if any(e["improved"] for e in h):
                    m.variables = b
            out.append(histories)
        return out


def _check_plan_group(plans: list) -> type:
    kind = type(plans[0])
    keys = {p.group_key() for p in plans}
    if any(type(p) is not kind for p in plans) or len(keys) != 1:
        raise ValueError(
            f"device-plan group is not homogeneous: {sorted(map(str, keys))}")
    return kind


def stage_device_plans(plans: list):
    """PURE half of a stacked plan dispatch: run the group's compute and
    return the raw result, mutating nothing.  This is the piece a
    scheduler may run under a watchdog — if the deadline expires and the
    thread is abandoned, the zombie's eventual completion is inert.  The
    scheduler guarantees homogeneous groups (it groups by
    ``group_key()``); the check here turns a grouping bug into a loud
    error instead of a shape explosion inside jit."""
    return _check_plan_group(plans).run_many(plans)


def commit_device_plans(plans: list, computed):
    """COMMIT half: apply any member-state side effects of the computed
    result (today only ``CNNRetrainPlan`` has them) and return per-plan
    results in order.  Callers run this on their own thread AFTER
    :func:`stage_device_plans` returned in time."""
    apply = getattr(_check_plan_group(plans), "apply_many", None)
    return apply(plans, computed) if apply is not None else computed


def run_device_plans(plans: list):
    """Service one GROUP of same-signature device plans as a single
    stacked dispatch; returns per-plan results in order.  One-shot
    compute+commit — the watchdog-aware scheduler calls the
    :func:`stage_device_plans` / :func:`commit_device_plans` halves
    separately so an abandoned dispatch can never rebind live members."""
    return commit_device_plans(plans, stage_device_plans(plans))
