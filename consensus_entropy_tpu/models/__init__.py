"""Committee members: Flax ShortChunkCNN (device) + sklearn members (host)."""

from consensus_entropy_tpu.models.short_cnn import ShortChunkCNN  # noqa: F401
