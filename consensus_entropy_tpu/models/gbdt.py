"""First-party gradient-boosted trees with TRUE continued boosting.

The reference's boosted committee slot is ``XGBClassifier(max_depth=5)``
continued per AL iteration via ``fit(X, y, xgb_model=booster)`` under its
vendored class-preservation patch (``amg_test.py:507``,
``xgboost/sklearn.py:854-860``): new boosting rounds are fit on the RAW
query batch against the preserved 4-class softprob objective, even when the
batch lacks classes.  xgboost is not shipped in every deployment and
sklearn's ``GradientBoostingClassifier`` warm start refuses class-deficient
batches (see ``BoostedTreesMember``'s anchor-row approximation), so this
module implements the needed capability first-party:

- :class:`QuantileBinner` — per-feature quantile bins (fit once at
  pre-training; AL updates reuse the same edges, the histogram-GBDT
  analogue of xgboost's per-DMatrix sketch on a fixed feature space).
- :class:`GBDT` — K-class softmax boosting: per round, softmax the
  current margins, take g = p − y / h = p(1−p) per class, and build one
  depth-limited histogram tree per class.  ``K`` is pinned at construction
  — gradients are computed for every class no matter which appear in the
  batch, which IS the reference patch's semantics (not an approximation).
- :class:`NativeGBDTMember` — the ``Member`` wrapper filling the ``xgb``
  committee slot.

The tree build / forest predict hot loops run in the OpenMP C++ core
(``native/ce_gbdt.cpp``) with a numpy fallback that produces identical
trees (same double accumulation order).
"""

from __future__ import annotations

import pickle

import numpy as np

from consensus_entropy_tpu import native
from consensus_entropy_tpu.config import NUM_CLASSES
from consensus_entropy_tpu.models.base import Member
from consensus_entropy_tpu.models.sklearn_members import _require_all_classes


class QuantileBinner:
    """Per-feature quantile binning to uint8 codes.

    ``fit`` computes up to ``n_bins − 1`` interior edges per feature from the
    pre-training data; ``transform`` maps a value to the count of edges
    strictly below it (``searchsorted`` side='left': a raw value exactly
    equal to an edge lands in the LOWER bin, i.e. bins are left-open /
    right-closed ``(lo, hi]``), so codes are monotone in the raw value and a
    tree split ``bin <= t`` equals a raw-value threshold.
    """

    def __init__(self, n_bins: int = 256):
        if not 2 <= n_bins <= 256:
            raise ValueError(f"n_bins must be in [2, 256], got {n_bins}")
        self.n_bins = n_bins
        self.edges: list[np.ndarray] | None = None

    def fit(self, X) -> "QuantileBinner":
        X = np.asarray(X, np.float64)
        qs = np.linspace(0.0, 1.0, self.n_bins + 1)[1:-1]
        self.edges = []
        for j in range(X.shape[1]):
            e = np.unique(np.quantile(X[:, j], qs))
            self.edges.append(e.astype(np.float64))
        return self

    def transform(self, X) -> np.ndarray:
        if self.edges is None:
            raise RuntimeError("binner not fitted")
        X = np.asarray(X, np.float64)
        if X.shape[1] != len(self.edges):
            raise ValueError(f"expected {len(self.edges)} features, "
                             f"got {X.shape[1]}")
        out = np.empty(X.shape, np.uint8)
        for j, e in enumerate(self.edges):
            out[:, j] = np.searchsorted(e, X[:, j], side="left")
        return np.ascontiguousarray(out)


class GBDT:
    """K-class softmax gradient boosting over binned features.

    One tree per class per round (xgboost's multi:softprob layout); leaf
    weights are second-order Newton steps ``−G/(H+λ)`` scaled by
    ``learning_rate``.  ``boost`` continues from the margins of the existing
    forest evaluated on the given batch — call it again with new data for
    continued boosting.
    """

    def __init__(self, n_class: int, *, max_depth: int = 5,
                 learning_rate: float = 0.3, lam: float = 1.0,
                 min_child_weight: float = 1.0, min_gain: float = 0.0,
                 n_bins: int = 256):
        self.n_class = n_class
        self.max_depth = max_depth
        self.learning_rate = learning_rate
        self.lam = lam
        self.min_child_weight = min_child_weight
        self.min_gain = min_gain
        self.n_bins = n_bins
        n_nodes = 2 ** (max_depth + 1) - 1
        self._feature = np.empty((0, n_nodes), np.int32)
        self._threshold = np.empty((0, n_nodes), np.int32)
        self._value = np.empty((0, n_nodes), np.float64)
        self._tree_class = np.empty(0, np.int32)

    @property
    def n_trees(self) -> int:
        return self._feature.shape[0]

    def margins(self, Xb) -> np.ndarray:
        """Raw (pre-softmax) scores ``(n, K)`` of the current forest."""
        return native.gbdt_predict_margins(
            Xb, self._feature, self._threshold, self._value,
            self._tree_class, self.n_class, self.learning_rate)

    def predict_proba(self, Xb) -> np.ndarray:
        m = self.margins(Xb)
        m -= m.max(axis=1, keepdims=True)
        p = np.exp(m)
        return (p / p.sum(axis=1, keepdims=True)).astype(np.float32)

    def boost(self, Xb, y, n_rounds: int) -> "GBDT":
        """Add ``n_rounds`` × K trees fit on ``(Xb, y)``.

        Starts from the existing forest's margins on ``Xb`` — with a
        non-empty forest this is continued boosting on the new batch, the
        ``xgboost.train(..., xgb_model=booster)`` semantics.  ``y`` may
        lack classes: the objective stays K-class (one-hot targets are
        zero columns for absent classes).
        """
        if n_rounds <= 0:
            raise ValueError(f"n_rounds must be >= 1, got {n_rounds}")
        Xb = np.ascontiguousarray(Xb, np.uint8)
        y = np.asarray(y, np.int64)
        if len(y) and (y.min() < 0 or y.max() >= self.n_class):
            # negative ints would silently wrap via numpy indexing; the
            # sibling members (sklearn/xgboost) raise on unseen labels too
            raise ValueError(f"labels must be in [0, {self.n_class}); got "
                             f"range [{y.min()}, {y.max()}]")
        onehot = np.zeros((len(y), self.n_class), np.float64)
        onehot[np.arange(len(y)), y] = 1.0
        m = self.margins(Xb)
        new_f, new_t, new_v, new_c = [], [], [], []
        for _ in range(n_rounds):
            z = m - m.max(axis=1, keepdims=True)
            p = np.exp(z)
            p /= p.sum(axis=1, keepdims=True)
            for k in range(self.n_class):
                g = (p[:, k] - onehot[:, k]).astype(np.float32)
                h = np.maximum(p[:, k] * (1.0 - p[:, k]),
                               1e-16).astype(np.float32)
                f_, t_, v_ = native.gbdt_build_tree(
                    Xb, g, h, max_depth=self.max_depth, n_bins=self.n_bins,
                    lam=self.lam, min_child_weight=self.min_child_weight,
                    min_gain=self.min_gain)
                new_f.append(f_)
                new_t.append(t_)
                new_v.append(v_)
                new_c.append(k)
                m[:, k] += self.learning_rate * native.gbdt_predict_margins(
                    Xb, f_[None], t_[None], v_[None],
                    np.zeros(1, np.int32), 1, 1.0)[:, 0]
        self._feature = np.concatenate([self._feature, np.stack(new_f)])
        self._threshold = np.concatenate([self._threshold, np.stack(new_t)])
        self._value = np.concatenate([self._value, np.stack(new_v)])
        self._tree_class = np.concatenate(
            [self._tree_class, np.asarray(new_c, np.int32)])
        return self

    # -- persistence (plain arrays; no code objects in the pickle) ---------

    def state(self) -> dict:
        return {"n_class": self.n_class, "max_depth": self.max_depth,
                "learning_rate": self.learning_rate, "lam": self.lam,
                "min_child_weight": self.min_child_weight,
                "min_gain": self.min_gain, "n_bins": self.n_bins,
                "feature": self._feature, "threshold": self._threshold,
                "value": self._value, "tree_class": self._tree_class}

    @classmethod
    def from_state(cls, st: dict) -> "GBDT":
        obj = cls(st["n_class"], max_depth=st["max_depth"],
                  learning_rate=st["learning_rate"], lam=st["lam"],
                  min_child_weight=st["min_child_weight"],
                  min_gain=st["min_gain"], n_bins=st["n_bins"])
        obj._feature = st["feature"]
        obj._threshold = st["threshold"]
        obj._value = st["value"]
        obj._tree_class = st["tree_class"]
        return obj


class NativeGBDTMember(Member):
    """Boosted-trees committee member with exact continued-boosting AL
    updates (the vendored-patch semantics — see module docstring).

    Hyperparameters mirror the reference's committee slot
    (``deam_classifier.py:226-231``: max_depth=5; xgboost defaults
    n_estimators=100, eta=0.3), and ``update`` adds the same
    ``n_estimators`` rounds per AL iteration that the reference's
    ``fit(xgb_model=...)`` call does.
    """

    kind = "xgb"  # fills the boosted committee slot

    def __init__(self, name: str = "xgb", *, max_depth: int = 5,
                 n_estimators: int = 100, update_estimators: int | None = None,
                 learning_rate: float = 0.3, n_bins: int = 256,
                 seed: int | None = None):
        super().__init__(name)
        del seed  # deterministic by construction; kept for registry parity
        self.n_estimators = n_estimators
        self.update_estimators = (n_estimators if update_estimators is None
                                  else update_estimators)
        self.binner = QuantileBinner(n_bins)
        self.model = GBDT(NUM_CLASSES, max_depth=max_depth,
                          learning_rate=learning_rate, n_bins=n_bins)

    def fit(self, X, y):
        y = np.asarray(y)
        _require_all_classes(y)
        X = np.asarray(X)
        # fit() retrains from scratch (like every other member's fit): a
        # fresh forest under fresh bin edges — stale trees would be
        # evaluated against mismatched codes otherwise.
        self.binner = QuantileBinner(self.binner.n_bins)
        self.model = GBDT(NUM_CLASSES, max_depth=self.model.max_depth,
                          learning_rate=self.model.learning_rate,
                          n_bins=self.model.n_bins)
        self.binner.fit(X)
        self.model.boost(self.binner.transform(X), y, self.n_estimators)
        return self

    def update(self, X, y):
        """Continued boosting on the RAW query batch — no class padding;
        the K-class objective is pinned by the model."""
        self.model.boost(self.binner.transform(np.asarray(X)),
                         np.asarray(y), self.update_estimators)

    def predict_proba(self, X):
        return self.model.predict_proba(self.binner.transform(np.asarray(X)))

    def predict(self, X):
        return np.argmax(self.predict_proba(X), axis=1)

    def save(self, path):
        with open(path, "wb") as f:
            pickle.dump({"kind": self.kind, "name": self.name,
                         "fmt": "native_gbdt",
                         "n_estimators": self.n_estimators,
                         "update_estimators": self.update_estimators,
                         "edges": self.binner.edges,
                         "n_bins": self.binner.n_bins,
                         "model": self.model.state()}, f)

    @classmethod
    def from_state(cls, st: dict) -> "NativeGBDTMember":
        obj = cls.__new__(cls)
        Member.__init__(obj, st["name"])
        obj.n_estimators = st["n_estimators"]
        obj.update_estimators = st["update_estimators"]
        obj.binner = QuantileBinner(st["n_bins"])
        obj.binner.edges = st["edges"]
        obj.model = GBDT.from_state(st["model"])
        return obj

    @classmethod
    def load(cls, path):
        with open(path, "rb") as f:
            return cls.from_state(pickle.load(f))
