"""Flax ShortChunkCNN — the TPU-native CNN committee member families.

Architecture parity with the reference's torch model (``short_cnn.py:278-349``):
log-mel frontend → BatchNorm over the 1-channel spectrogram → 7× [3×3 conv →
BN → ReLU → 2×2 maxpool] with widths (128,128,256,256,256,256,512) → global
max pool → Dense(512) → BN → ReLU → Dropout(0.5) → Dense(4) → **sigmoid**
(the reference trains with BCELoss on one-hot targets, ``amg_test.py:294`` —
outputs are per-class Bernoullis, not a softmax simplex; the downstream
entropy renormalizes, matching ``scipy.stats.entropy`` semantics).

A second trunk family, ``config.arch='res'``, swaps the pool blocks for
stride-2 residual blocks (:class:`ResBlock` — the semantics of the
``Res_2d`` module the reference vendors from the sota-music-tagging model
zoo but never wires up, ``short_cnn.py:40-66``); frontend, head, trainer,
and committee machinery are shared between families.

TPU-first choices (vs a line-for-line port):

- NHWC layout throughout (XLA's native conv layout on TPU).
- The mel frontend is jnp matmuls (see ``ops/mel.py``) fused into the same
  jit graph — no torchaudio buffer shipped in checkpoints.
- BatchNorm uses running statistics for *all* inference (the reference
  evaluates with batch_size=1 where train-mode BN would be degenerate —
  SURVEY.md §7 hard part 3).
- Committee inference/training runs over stacked parameter pytrees
  (``stack_params``) rather than a Python loop that reloads each member from
  disk per iteration (``amg_test.py:434``) — ``lax.map`` on one chip (dense
  per-member convs), ``vmap`` where the member axis shards across chips.
- Optional bfloat16 compute (params/stats stay float32).

Torch-default hyperparameters preserved: BN eps=1e-5, BN momentum 0.1 (flax
``momentum=0.9``), conv/pool geometry identical.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.ops.mel import log_mel_spectrogram


class ConvBlock(nn.Module):
    """3×3 conv (pad 1) → BN → ReLU → 2×2 max pool (``short_cnn.py:28-37``)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(self.features, (3, 3), padding=1, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        x = nn.relu(x)
        return nn.max_pool(x, (2, 2), strides=(2, 2))


class ResBlock(nn.Module):
    """Residual block with stride-2 downsampling: conv(s2) → BN → ReLU →
    conv → BN, plus a projected shortcut (conv(s2) → BN) whenever shape or
    width changes; sum → ReLU.  Semantics of the vendored ``Res_2d``
    (``short_cnn.py:40-66``; reference default stride=2)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        def bn(name):
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                epsilon=1e-5, dtype=self.dtype, name=name)

        out = nn.Conv(self.features, (3, 3), strides=(2, 2), padding=1,
                      dtype=self.dtype, name="conv1")(x)
        out = nn.relu(bn("bn1")(out))
        out = nn.Conv(self.features, (3, 3), padding=1, dtype=self.dtype,
                      name="conv2")(out)
        out = bn("bn2")(out)
        # stride 2 always changes shape -> the projection is always needed
        # (the reference's `diff` flag; short_cnn.py:50-54)
        short = nn.Conv(self.features, (3, 3), strides=(2, 2), padding=1,
                        dtype=self.dtype, name="conv_proj")(x)
        short = bn("bn_proj")(short)
        return nn.relu(short + out)


class SEBlock1d(nn.Module):
    """Squeeze-excitation residual 1-D block, sample-level: conv → BN →
    ReLU → conv → BN, channel SE gate (global-average → dense → ReLU →
    dense → sigmoid), projected shortcut on width change, then ReLU →
    3× max-pool.  Semantics of the vendored ``ResSE_1d``
    (``short_cnn.py:85-125``); laid out NHWC with W=1 so the trunk plugs
    into the same head as the 2-D families."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool):
        def bn(name):
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                epsilon=1e-5, dtype=self.dtype, name=name)

        out = nn.Conv(self.features, (3, 1), padding=((1, 1), (0, 0)),
                      dtype=self.dtype, name="conv1")(x)
        out = nn.relu(bn("bn1")(out))
        out = nn.Conv(self.features, (3, 1), padding=((1, 1), (0, 0)),
                      dtype=self.dtype, name="conv2")(out)
        out = bn("bn2")(out)
        # squeeze & excitation: global average over time -> channel gate
        se = jnp.mean(out, axis=(1, 2))
        se = nn.relu(nn.Dense(self.features, dtype=self.dtype,
                              name="se_dense1")(se))
        se = nn.sigmoid(nn.Dense(self.features, dtype=self.dtype,
                                 name="se_dense2")(se))
        out = out * se[:, None, None, :]
        if x.shape[-1] != self.features:  # projected shortcut (`diff`)
            x = nn.Conv(self.features, (3, 1), padding=((1, 1), (0, 0)),
                        dtype=self.dtype, name="conv_proj")(x)
            x = bn("bn_proj")(x)
        out = nn.relu(x + out)
        return nn.max_pool(out, (3, 1), strides=(3, 1))


class MusicnnFrontEnd(nn.Module):
    """Multi-shape timbral/temporal front-end over the log-mel image.

    Vertical branches (the vendored ``Conv_V``, ``short_cnn.py:128-143``):
    filters spanning a FRACTION of the mel axis (0.4 and 0.7 here, the
    MusiCNN design the blocks come from), max-pooled over remaining
    frequency — pitch-invariant timbre detectors.  Horizontal branches
    (``Conv_H``, ``short_cnn.py:146-160``): frequency-average first, then
    long 1-D convs over time (lengths 32/64) — tempo/rhythm detectors.
    Branch outputs concatenate on channels into a ``(B, T, 1, C_total)``
    map for the mid-end.  The reference vendors only the blocks, not their
    composition; the composition here follows the MusiCNN front-end they
    were written for.
    """

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, s, train: bool):
        def bn(name):
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                epsilon=1e-5, dtype=self.dtype, name=name)

        n_mels = s.shape[1]
        branches = []
        for i, frac in enumerate((0.4, 0.7)):  # Conv_V semantics
            h = max(1, int(n_mels * frac))
            v = nn.Conv(self.features, (h, 7), padding=((0, 0), (3, 3)),
                        dtype=self.dtype, name=f"v{i}_conv")(s)
            v = nn.relu(bn(f"v{i}_bn")(v))
            branches.append(jnp.max(v, axis=1))  # freq max-pool -> (B,T,C)
        avg = jnp.mean(s, axis=1)  # Conv_H: freq average -> (B, T, 1)
        for i, length in enumerate((32, 64)):
            pad = length // 2
            hbr = nn.Conv(self.features, (length,),
                          padding=((pad, pad - (length + 1) % 2),),
                          dtype=self.dtype, name=f"h{i}_conv")(avg)
            branches.append(nn.relu(bn(f"h{i}_bn")(hbr)))
        t = min(b.shape[1] for b in branches)
        out = jnp.concatenate([b[:, :t] for b in branches], axis=-1)
        return out[:, :, None, :]  # (B, T, 1, C_total) for the mid-end


class ShortChunkCNN(nn.Module):
    """Short-chunk CNN over ~3.69 s mel spectrograms.

    ``config.arch`` picks the trunk: ``vgg`` = conv/BN/ReLU/maxpool blocks
    (the paper's committee member), ``res`` = stride-2 residual blocks
    (the ShortChunkCNN_Res family).  Frontend and classifier head are
    shared — and keep identical parameter paths — so both families plug
    into the same trainer/committee/checkpoint machinery.
    """

    config: CNNConfig = CNNConfig()

    @nn.compact
    def __call__(self, x, train: bool = False,
                 return_features: bool = False):
        """x: waveform ``(B, L)`` float — returns sigmoid scores ``(B, C)``.

        ``return_features``: stop after the penultimate ReLU (the dropout
        layer's input) and return the ``(B, D)`` feature map instead — the
        split point the QBDC head (:func:`qbdc_infer`) resamples K dropout
        masks over without re-running the trunk."""
        cfg = self.config
        dtype = jnp.dtype(cfg.compute_dtype)

        def input_bn(s):
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                epsilon=1e-5, dtype=dtype, name="spec_bn")(s)

        if cfg.arch == "se1d":
            # sample-level trunk on the RAW waveform — no spectrogram
            # frontend at all (the 59049-sample reference crop is 3^10,
            # built for exactly this /3-per-stage geometry).  NHWC, W=1.
            s = input_bn(x[..., None, None].astype(dtype))  # (B, L, 1, 1)
            s = nn.Conv(cfg.channel_widths[0], (3, 1), strides=(3, 1),
                        padding="VALID", dtype=dtype, name="stem")(s)
            s = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-5, dtype=dtype, name="stem_bn")(s)
            s = nn.relu(s)
            for width in cfg.channel_widths:
                s = SEBlock1d(width, dtype=dtype)(s, train)
        elif cfg.arch == "musicnn":
            s = input_bn(log_mel_spectrogram(x, cfg)[..., None].astype(dtype))
            s = MusicnnFrontEnd(cfg.n_channels, dtype=dtype)(s, train)
            for i in range(cfg.n_layers):  # temporal mid-end, /2 per stage
                s = nn.Conv(cfg.channel_widths[i], (3, 1),
                            padding=((1, 1), (0, 0)), dtype=dtype,
                            name=f"mid{i}_conv")(s)
                s = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                 epsilon=1e-5, dtype=dtype,
                                 name=f"mid{i}_bn")(s)
                s = nn.relu(s)
                s = nn.max_pool(s, (2, 1), strides=(2, 1))
        else:
            if cfg.arch == "harm":
                from consensus_entropy_tpu.ops.harmonic import (
                    harmonic_spectrogram,
                )

                # learnable frontend: gradients flow into the band Q factor
                # (the reference's learn_bw='only_Q', short_cnn.py:227-231)
                bw_q = self.param(
                    "bw_q",
                    lambda _: jnp.asarray([cfg.bw_q_init], jnp.float32))
                s = harmonic_spectrogram(
                    x, bw_q, sample_rate=cfg.sample_rate, n_fft=cfg.n_fft,
                    hop_length=cfg.hop_length, n_harmonic=cfg.n_harmonic,
                    semitone_scale=cfg.semitone_scale)  # (B, H, level, T)
                s = jnp.transpose(s, (0, 2, 3, 1)).astype(dtype)  # NHWC
            else:
                s = log_mel_spectrogram(x, cfg)  # (B, n_mels, T)
                s = s[..., None].astype(dtype)  # NHWC: (B, n_mels, T, 1)
            s = input_bn(s)
            block = ResBlock if cfg.arch == "res" else ConvBlock
            for width in cfg.channel_widths:
                s = block(width, dtype=dtype)(s, train)
        # Global max pool over remaining (freq, time) — the reference squeezes
        # freq (==1 after 7 pools) then MaxPool1d's time (short_cnn.py:334-339).
        s = jnp.max(s, axis=(1, 2))
        s = nn.Dense(cfg.channel_widths[-1], dtype=dtype, name="dense1")(s)
        s = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=dtype, name="head_bn")(s)
        s = nn.relu(s)
        if return_features:
            return s
        s = nn.Dropout(cfg.dropout_rate, deterministic=not train)(s)
        s = nn.Dense(cfg.n_class, dtype=dtype, name="dense2")(s)
        return nn.sigmoid(s.astype(jnp.float32))


def init_variables(key, config: CNNConfig = CNNConfig(), batch_size: int = 2):
    """Initialize ``{'params', 'batch_stats'}`` for a single member."""
    model = ShortChunkCNN(config)
    x = jnp.zeros((batch_size, config.input_length), jnp.float32)
    return model.init({"params": key}, x, train=False)


def apply_infer(variables, x, config: CNNConfig = CNNConfig()):
    """Inference forward pass (running-stats BN, no dropout)."""
    return ShortChunkCNN(config).apply(variables, x, train=False)


def apply_train(variables, x, dropout_key, config: CNNConfig = CNNConfig()):
    """Training forward pass; returns ``(scores, new_batch_stats)``."""
    out, mutated = ShortChunkCNN(config).apply(
        variables, x, train=True, rngs={"dropout": dropout_key},
        mutable=["batch_stats"])
    return out, mutated["batch_stats"]


def apply_features(variables, x, config: CNNConfig = CNNConfig()):
    """Penultimate features ``(B, D)``: the inference forward (running-
    stats BN, no dropout) stopped at the dropout layer's input."""
    return ShortChunkCNN(config).apply(variables, x, train=False,
                                       return_features=True)


def qbdc_infer(variables, x, mask_keys, config: CNNConfig = CNNConfig()):
    """Query-by-dropout-committee forward: ``(K, B, C)`` sigmoid scores of
    ONE member under K seeded dropout masks (arxiv 1511.06412).

    The committee members share every parameter — member ``j`` is the
    FIXED thinned subnetwork drawn by ``mask_keys[j]``: a unit-level
    Bernoulli mask over the ``D`` penultimate features, broadcast over the
    batch, so each member scores the whole pool through one consistent
    subnetwork (and the mask is independent of batch width, compile
    bucketing and staging padding — a member's identity never drifts as
    the pool shrinks).  The expensive trunk runs ONCE and only the
    dropout→dense2→sigmoid head is vmapped over ``mask_keys``: committee
    width K costs K tiny ``(B, D)×(D, C)`` matmuls and NO extra weights —
    the storage/compute shape that replaces the paper's 20 stored models
    per user.  Masks use inverted-dropout scaling (keep-probability
    ``1 - dropout_rate``; ``dropout_rate == 0`` degenerates to K identical
    members).  BN runs in inference mode (running stats), matching
    :func:`apply_infer`.
    """
    feats = apply_features(variables, x, config)
    dense2 = variables["params"]["dense2"]
    dtype = jnp.dtype(config.compute_dtype)
    kernel = dense2["kernel"].astype(dtype)
    bias = dense2["bias"].astype(dtype)
    keep = 1.0 - config.dropout_rate

    def head(key):
        m = jax.random.bernoulli(key, keep, (feats.shape[-1],))
        h = jnp.where(m[None, :], feats / keep, 0.0).astype(dtype)
        return nn.sigmoid((h @ kernel + bias).astype(jnp.float32))

    return jax.vmap(head)(mask_keys)


def stack_params(member_variables: list):
    """Stack per-member variable pytrees along a leading committee axis.

    The stacked pytree is what ``lax.map``/``vmap``/``shard_map`` consume:
    committee inference is one fused graph for all M members instead of M
    sequential model loads (``amg_test.py:428-438``).
    """
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *member_variables)


def stack_user_params(user_stacked: list):
    """Stack per-USER member-stacked pytrees along a leading users axis.

    Input: one ``stack_params`` result per user (each ``(M, …)``); output
    ``(U, M, …)`` — the operand of :func:`committee_infer_users`, the
    cross-user device batch the fleet scheduler dispatches for a cohort of
    same-bucket CNN sessions.  All users must share one architecture /
    member count (the scheduler's group key guarantees it).
    """
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *user_stacked)


def unstack_params(stacked, index: int):
    """Extract member ``index`` from a stacked pytree."""
    return jax.tree.map(lambda leaf: leaf[index], stacked)


def num_members(stacked) -> int:
    return jax.tree.leaves(stacked)[0].shape[0]


def committee_infer(stacked_variables, x, config: CNNConfig = CNNConfig()):
    """All members score the same crops: ``(M, B, C)`` sigmoid outputs.

    ``lax.map`` over the member axis, NOT ``vmap``: vmapping convolutions
    over a batched *kernel* lowers to feature-group convs, which the TPU
    runs ~2.5x slower than the same math as per-member dense convs
    (measured at the bench geometry, 5 members x 48 reference crops:
    41.2 ms vmapped vs 16.0 ms mapped — identical outputs; the per-member
    fwd is HBM-bound, so sequencing members costs nothing on one chip).
    Under a pool-sharded mesh the map body is itself SPMD over the crop
    axis, so multi-chip scoring keeps working unchanged.
    """
    return jax.lax.map(lambda v: apply_infer(v, x, config),
                       stacked_variables)


def committee_infer_users(user_stacked, x, config: CNNConfig = CNNConfig()):
    """Cross-user committee forward: ``(U, M, B, C)`` sigmoid outputs.

    ``user_stacked``: ``(U, M, …)`` per-user member-stacked variables
    (:func:`stack_user_params`); ``x``: ``(U, B, L)`` per-user crop
    batches.  A whole same-bucket cohort of CNN sessions scores as ONE
    device dispatch — the users axis of the fleet scheduler's stacked
    scoring calls, extended to the probs *producer*.

    ``lax.map`` over the user axis, NOT ``vmap``, for the same reason
    :func:`committee_infer` maps the member axis: vmapping convolutions
    over batched kernels lowers to feature-group convs (slower on TPU,
    and NOT bit-identical — measured 1e-7-level drift on this backend),
    while the mapped body runs the exact single-user program, so each
    user's rows are bit-identical to its own jitted
    ``committee_infer`` call (pinned by ``tests/test_cnn_fleet.py``).
    The win is dispatch-granularity: one compile, one dispatch, one
    host round-trip for the cohort.
    """
    return jax.lax.map(
        lambda uv: committee_infer(uv[0], uv[1], config),
        (user_stacked, x))


def qbdc_infer_users(user_variables, x, mask_key_data,
                     config: CNNConfig = CNNConfig()):
    """Cross-user QBDC forward: ``(U, K, B, C)`` — one trunk pass per user
    plus K vmapped dropout heads, all users in ONE device dispatch.

    ``user_variables``: ``(U, …)`` stacked single-member variables (the
    network QBDC personalizes per user); ``x``: ``(U, B, L)`` crops;
    ``mask_key_data``: ``(U, K, …)`` RAW key data of each user's mask keys
    (``jax.random.key_data`` — typed key arrays don't ``jnp.stack``
    portably; the keys are re-wrapped inside the mapped body).  Same
    ``lax.map``-over-users bit-identity contract as
    :func:`committee_infer_users`, against :func:`qbdc_infer`.
    """
    return jax.lax.map(
        lambda a: qbdc_infer(a[0], a[1], jax.random.wrap_key_data(a[2]),
                             config),
        (user_variables, x, mask_key_data))
