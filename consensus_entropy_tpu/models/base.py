"""The committee-member contract.

The reference's committee is duck-typed sklearn objects plus a torch model
dispatched by filename substring checks (``amg_test.py:404-413,496-509``).
Here the contract is explicit (SURVEY.md §7 step 4): every member can score
the pool, incrementally absorb a labeled batch, and round-trip to disk.

Two member species exist:

- **Host members** (GNB/SGD/boosting) — stay on CPU; their per-song
  probability tables are fed into the on-device fused scoring graph.
- **Device members** (Flax CNN) — stacked-params pytrees scored via ``vmap``
  on TPU; they implement the same protocol through ``CNNMember``.
"""

from __future__ import annotations

import abc
import numpy as np


class Member(abc.ABC):
    """One committee member."""

    #: short algorithm tag, e.g. 'gnb', 'sgd', 'xgb', 'cnn_jax'
    kind: str = "?"

    def __init__(self, name: str):
        self.name = name

    @abc.abstractmethod
    def predict_proba(self, X: np.ndarray) -> np.ndarray:
        """Class probabilities ``(n, C)`` for feature rows ``X``."""

    def predict(self, X: np.ndarray) -> np.ndarray:
        """Hard labels; default argmax of probabilities."""
        return np.argmax(self.predict_proba(X), axis=1)

    @abc.abstractmethod
    def update(self, X: np.ndarray, y: np.ndarray) -> None:
        """Incrementally absorb a labeled batch (the AL query step):
        ``partial_fit`` for GNB/SGD (``amg_test.py:509``), continued boosting
        for XGB (``amg_test.py:507``), retraining for the CNN."""

    @abc.abstractmethod
    def save(self, path: str) -> None: ...

    @classmethod
    @abc.abstractmethod
    def load(cls, path: str) -> "Member": ...
