"""CNN training: optax loops with the reference's optimizer schedule.

Reference semantics reproduced (``amg_test.py:203-341``, ``deam_classifier.py:
106-176,249-316``):

- BCE loss on sigmoid outputs vs one-hot targets, log clamped at −100
  (torch ``BCELoss`` semantics), mean reduction.
- Adam(lr=1e-4, L2 weight_decay=1e-4) → after ``patience`` stale epochs,
  SGD(momentum .9, nesterov, wd 1e-4) at 1e-3 → 1e-4 → 1e-5, **reloading the
  best checkpoint at every transition** (``amg_test.py:205-217``).
  torch-style *coupled* weight decay (added to the gradient before the
  optimizer transform), not AdamW-style decoupled.
- Per-epoch validation on the (randomly re-cropped) test set; best model
  kept by ``score = 1 − val_loss`` (``amg_test.py:267-273``).

TPU-first shape of the loop: each epoch is ONE jit'd function — crop
sampling (device RNG), ``lax.scan`` over fixed-shape batches, forward/backward
on the MXU, validation pass, and best-params update via ``tree_map(where)``
all fused; the host only advances the epoch counter and switches the optax
transform at phase transitions (≤4 compilations total, cached afterwards).
The reference instead runs a Python batch loop with a DataLoader worker
process and per-batch host↔device transfers.
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax

from consensus_entropy_tpu.config import CNNConfig, TrainConfig
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.models.short_cnn import ShortChunkCNN

PHASES = ("adam", "sgd_1", "sgd_2", "sgd_3")  # amg_test.py:203-231


def bce_per_sample(preds, targets):
    """Per-sample BCE (mean over the class axis), torch clamp semantics."""
    p = jnp.clip(preds, 0.0, 1.0)
    log_p = jnp.maximum(jnp.log(jnp.maximum(p, 1e-44)), -100.0)
    log_1p = jnp.maximum(jnp.log(jnp.maximum(1.0 - p, 1e-44)), -100.0)
    return -jnp.mean(targets * log_p + (1.0 - targets) * log_1p, axis=-1)


def bce_loss(preds, targets):
    """torch.nn.BCELoss parity: mean over all elements, log clamped at −100."""
    return jnp.mean(bce_per_sample(preds, targets))


def weighted_f1_in_graph(preds, targets_onehot):
    """``sklearn.f1_score(average='weighted', zero_division=0)`` on argmax
    predictions, computed in-graph over the fixed class axis so per-epoch
    validation never forces a host readback (the reference's per-epoch F1,
    ``amg_test.py:264`` / ``deam_classifier.py:137-138``, runs on host —
    here it rides the epoch jit; sklearn parity is pinned by
    ``tests/test_cnn_trainer.py::test_weighted_f1_in_graph_matches_sklearn``)."""
    c = targets_onehot.shape[-1]
    pred_oh = jax.nn.one_hot(jnp.argmax(preds, axis=-1), c,
                             dtype=targets_onehot.dtype)
    tp = jnp.sum(targets_onehot * pred_oh, axis=0)
    pred_n = jnp.sum(pred_oh, axis=0)
    true_n = jnp.sum(targets_onehot, axis=0)
    precision = jnp.where(pred_n > 0, tp / jnp.maximum(pred_n, 1.0), 0.0)
    recall = jnp.where(true_n > 0, tp / jnp.maximum(true_n, 1.0), 0.0)
    pr = precision + recall
    f1 = jnp.where(pr > 0, 2.0 * precision * recall / jnp.maximum(pr, 1e-30),
                   0.0)
    return jnp.sum(true_n * f1) / jnp.maximum(jnp.sum(true_n), 1.0)


_HISTORY_DEVICE_KEYS = ("train_loss", "val_loss", "val_f1", "improved")


def _materialize_history(history: list[dict]) -> list[dict]:
    """Resolve deferred device scalars in epoch-info dicts to Python values
    in ONE bulk transfer.  ``fit``/``fit_many`` queue the whole optimizer
    schedule asynchronously and only sync here (or per epoch when a caller
    passed a ``callback``)."""
    pending = [h for h in history if not isinstance(h["train_loss"], float)]
    if pending:
        vals = jax.device_get(
            [tuple(h[k] for k in _HISTORY_DEVICE_KEYS) for h in pending])
        for h, v in zip(pending, vals):
            h["train_loss"] = float(v[0])
            h["val_loss"] = float(v[1])
            h["val_f1"] = float(v[2])
            h["improved"] = bool(v[3])
    return history


def make_tx(phase: str, cfg: TrainConfig) -> optax.GradientTransformation:
    """Optimizer for a schedule phase, torch-coupled weight decay."""
    if phase == "adam":
        return optax.chain(optax.add_decayed_weights(cfg.weight_decay),
                           optax.adam(cfg.lr))
    idx = PHASES.index(phase) - 1
    return optax.chain(
        optax.add_decayed_weights(cfg.sgd_weight_decay),
        optax.sgd(cfg.sgd_lrs[idx], momentum=cfg.sgd_momentum, nesterov=True))


@dataclasses.dataclass
class EpochResult:
    train_loss: float
    val_loss: float
    val_f1_pairs: tuple  # (y_true, y_pred) for host-side metrics
    improved: bool


#: Process-wide jitted epoch programs, keyed by
#: (config, train_config, phase, shapes[, mesh]).  Module-level, NOT per
#: trainer: a fresh :class:`CNNTrainer` is built per user (each user's
#: committee is a new object, ``amg_test.py:146-171`` semantics), and a
#: per-instance cache made every user re-trace and re-compile the full
#: retrain program — measured as ~104 s of the warm user's first
#: ``retrain_cnn`` phase in ``ITERATION_r04``.  The epoch closures are
#: fully determined by the two frozen configs + shape key (the captured
#: ``ShortChunkCNN``/optax tx are pure functions of them), so sharing
#: across trainer instances is sound.
#: Bounded LRU: in a production AL run ``n_train`` grows every iteration, so
#: (phase, n_train)-keyed programs would otherwise accumulate for the process
#: lifetime (a slow leak, and the same executable-accumulation mode that
#: destabilises the virtual-CPU test backend — see tests/conftest.py).  One
#: retrain touches <=4 phase programs per (n_train, n_epochs) key, so 128
#: entries hold the full working set of a 46-user run with headroom; evicting
#: an entry drops only the Python jit wrapper — in-flight executions keep
#: their executable alive through the runtime, and a re-visited key simply
#: re-traces.
_EPOCH_FNS: collections.OrderedDict[tuple, Callable] = collections.OrderedDict()
_EPOCH_FNS_MAX = 128


def _split_member_keys(ks):
    """Advance the stacked member key carry exactly as ``fit_many``'s
    per-epoch ``run_epoch`` does (``vmap(split)``), so the scanned and
    per-epoch paths share one random stream."""
    splits = jax.vmap(jax.random.split)(ks)
    return splits[:, 0], splits[:, 1]


def _split_user_member_keys(ks):
    """``(U, M)`` key-carry advance: each user's member keys split exactly
    as :func:`_split_member_keys` splits them in a single-user ``fit_many``
    (vmap only batches the identical per-key threefry derivation), so the
    user-lockstep schedule reproduces every user's own random stream."""
    splits = jax.vmap(jax.vmap(jax.random.split))(ks)
    return splits[:, :, 0], splits[:, :, 1]


def _epoch_fns_cached(key_: tuple, build: Callable[[], Callable]) -> Callable:
    fn = _EPOCH_FNS.get(key_)
    if fn is None:
        fn = build()
        _EPOCH_FNS[key_] = fn
        while len(_EPOCH_FNS) > _EPOCH_FNS_MAX:
            _EPOCH_FNS.popitem(last=False)
    else:
        _EPOCH_FNS.move_to_end(key_)
    return fn


class CNNTrainer:
    """Drives pre-training and AL retraining of one CNN member."""

    def __init__(self, config: CNNConfig = CNNConfig(),
                 train_config: TrainConfig = TrainConfig()):
        self.config = config
        self.train_config = train_config
        self.model = ShortChunkCNN(config)

    # -- jitted epoch step (built per phase, cached) -----------------------

    def _build_epoch(self, phase: str, n_train: int, n_test: int,
                     batch_size: int) -> Callable:
        """The raw (unjitted) one-epoch function for a schedule phase —
        shared by the single-member jit and the vmapped multi-member jit."""
        tx = make_tx(phase, self.train_config)
        model = self.model
        n_batches = -(-n_train // batch_size)
        used = n_batches * batch_size
        pad = used - n_train  # < batch_size <= n_train

        def epoch(params, batch_stats, opt_state, best_params, best_stats,
                  best_score, data, lengths, train_rows, train_y, test_rows,
                  test_y, key):
            kperm, kcrop, ktest, kdrop = jax.random.split(key, 4)
            # shuffle + crop the training pool (epoch-fresh random crops,
            # matching the reference's shuffling DataLoader).
            perm = jax.random.permutation(kperm, n_train)
            perm = jnp.concatenate([perm, perm[:pad]])  # zero-weight tail
            rows = train_rows[perm]
            u = jax.random.uniform(kcrop, (used,))
            starts = jnp.floor(
                u * (lengths[rows] - model.config.input_length)).astype(jnp.int32)

            def crop(row, start):
                return jax.lax.dynamic_slice_in_dim(
                    data[row], start, model.config.input_length)

            xs = jax.vmap(crop)(rows, starts).reshape(
                n_batches, batch_size, model.config.input_length)
            ys = train_y[perm].reshape(n_batches, batch_size, -1)
            ws = jnp.concatenate(
                [jnp.ones(n_train), jnp.zeros(pad)]).reshape(
                    n_batches, batch_size)
            dkeys = jax.random.split(kdrop, n_batches)

            def loss_fn(p, stats, x, y, w, dk):
                out, mutated = model.apply(
                    {"params": p, "batch_stats": stats}, x, train=True,
                    rngs={"dropout": dk}, mutable=["batch_stats"])
                loss = (jnp.sum(bce_per_sample(out, y) * w)
                        / jnp.sum(w))
                return loss, mutated["batch_stats"]

            def step(carry, batch):
                p, stats, opt = carry
                x, y, w, dk = batch
                (loss, new_stats), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(p, stats, x, y, w, dk)
                updates, opt = tx.update(grads, opt, p)
                p = optax.apply_updates(p, updates)
                return (p, new_stats, opt), loss

            (params, batch_stats, opt_state), losses = jax.lax.scan(
                step, (params, batch_stats, opt_state), (xs, ys, ws, dkeys))

            # validation with fresh random test crops (the reference's test
            # loader also crops randomly every pass — short_cnn.py:376).
            ut = jax.random.uniform(ktest, (n_test,))
            tstarts = jnp.floor(
                ut * (lengths[test_rows] - model.config.input_length)
            ).astype(jnp.int32)
            xt = jax.vmap(crop)(test_rows, tstarts)
            preds = model.apply({"params": params, "batch_stats": batch_stats},
                                xt, train=False)
            val_loss = bce_loss(preds, test_y)
            val_f1 = weighted_f1_in_graph(preds, test_y)

            # best-checkpoint update on device: score = 1 - val_loss
            # (amg_test.py:267-273).
            score = 1.0 - val_loss
            improved = score > best_score
            best_params = jax.tree.map(
                lambda new, old: jnp.where(improved, new, old),
                params, best_params)
            best_stats = jax.tree.map(
                lambda new, old: jnp.where(improved, new, old),
                batch_stats, best_stats)
            best_score = jnp.where(improved, score, best_score)
            return (params, batch_stats, opt_state, best_params, best_stats,
                    best_score, jnp.mean(losses), val_loss, val_f1, preds,
                    improved)

        return epoch

    def _epoch_fn(self, phase: str, n_train: int, n_test: int,
                  batch_size: int) -> Callable:
        # The reference's DataLoader has drop_last=False (short final batch,
        # every song trains every epoch).  Fixed-shape equivalent: clamp the
        # batch size to the pool, round batches UP, and pad the tail with
        # repeated rows at loss weight 0 — all songs contribute gradient
        # each epoch (padding rows still enter train-mode BatchNorm stats,
        # the one unavoidable deviation from a genuinely shorter batch).
        batch_size = max(1, min(batch_size, n_train))
        key_ = (self.config, self.train_config, phase, n_train, n_test,
                batch_size)
        return _epoch_fns_cached(key_, lambda: jax.jit(
            self._build_epoch(phase, n_train, n_test, batch_size),
            donate_argnums=(0, 1, 2, 3, 4)))

    def _build_epoch_many(self, phase: str, n_train: int, n_test: int,
                          batch_size: int, mesh=None) -> Callable:
        """The raw (unjitted) lockstep multi-member epoch — shared by the
        per-epoch jit (:meth:`_epoch_fn_many`) and the scanned phase jit
        (:meth:`_phase_fn_many`).

        args: params, stats, opt, best_p, best_s, best_score are
        member-stacked; data, lengths, rows, y broadcast; key per member."""
        epoch = self._build_epoch(phase, n_train, n_test, batch_size)
        if mesh is None:
            # Single chip: run members as a lax.map, not vmap — vmapping
            # convs over batched kernels lowers to feature-group convs the
            # TPU runs measurably slower (fwd+bwd at bench geometry:
            # 60.6 ms vmapped vs 51.1 ms mapped; identical math).  On a
            # member-sharded mesh the vmap IS the cross-chip parallelism,
            # so that branch keeps it.
            def mapped(params, stats, opt, best_p, best_s, best_score,
                       data, lengths, train_rows, train_y, test_rows,
                       test_y, keys):
                return jax.lax.map(
                    lambda ms: epoch(*ms[:6], data, lengths, train_rows,
                                     train_y, test_rows, test_y, ms[6]),
                    (params, stats, opt, best_p, best_s, best_score, keys))

            return mapped
        return jax.vmap(
            epoch,
            in_axes=(0, 0, 0, 0, 0, 0, None, None, None, None, None,
                     None, 0))

    @staticmethod
    def _member_shardings(mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from consensus_entropy_tpu.parallel.mesh import MEMBER_AXIS

        return (NamedSharding(mesh, P(MEMBER_AXIS)),
                NamedSharding(mesh, P()))

    def _epoch_fn_many(self, phase: str, n_train: int, n_test: int,
                       batch_size: int, mesh=None) -> Callable:
        """Lockstep multi-member epoch: the single-member epoch ``vmap``'d
        over the stacked member axis (per-member params/opt/best/keys; the
        waveform store and id tables broadcast), one jit dispatch for the
        whole committee.  With ``mesh``, member-stacked state is sharded on
        the ``member`` axis (each chip trains its member slice)."""
        batch_size = max(1, min(batch_size, n_train))
        # Mesh hashes by value: an equal mesh rebuilt per AL round still hits
        key_ = (self.config, self.train_config, "many", phase, n_train,
                n_test, batch_size, mesh)

        def build():
            mapped = self._build_epoch_many(phase, n_train, n_test,
                                            batch_size, mesh)
            if mesh is None:
                return jax.jit(mapped, donate_argnums=(0, 1, 2, 3, 4))
            member, repl = self._member_shardings(mesh)
            # metric outputs come back REPLICATED: they are tiny (M,)
            # vectors / (M, n_test, C) preds, and replication makes them
            # host-readable on every process of a multi-host mesh (a
            # member-sharded output would span non-addressable devices)
            return jax.jit(
                mapped,
                in_shardings=(member,) * 6 + (repl,) * 6 + (member,),
                out_shardings=(member,) * 6 + (repl,) * 5,
                donate_argnums=(0, 1, 2, 3, 4))

        return _epoch_fns_cached(key_, build)

    def _build_epoch_users(self, phase: str, n_train: int, n_test: int,
                           batch_size: int) -> Callable:
        """Cross-USER lockstep epoch: ``lax.map`` over the users axis of
        the member-lockstep epoch body, every argument (including the
        waveform store and id tables, which ``_build_epoch_many``
        broadcasts within one user) carried per user.  ``lax.map`` rather
        than ``vmap`` for the same two reasons as the member axis: batched
        conv kernels lower to slower feature-group convs, and the mapped
        body runs the IDENTICAL per-user program — so each user's
        trajectory is bit-identical to its own ``fit_many``
        (pinned by ``tests/test_cnn_fleet.py``)."""
        epoch_m = self._build_epoch_many(phase, n_train, n_test, batch_size)

        def mapped(params, stats, opt, best_p, best_s, best_score,
                   data, lengths, train_rows, train_y, test_rows, test_y,
                   keys):
            return jax.lax.map(
                lambda a: epoch_m(*a),
                (params, stats, opt, best_p, best_s, best_score,
                 data, lengths, train_rows, train_y, test_rows, test_y,
                 keys))

        return mapped

    def _phase_fn_users(self, phase: str, n_ep: int, n_train: int,
                        n_test: int, batch_size: int) -> Callable:
        """A whole schedule phase of the user-lockstep epoch as ONE
        scanned jit (the ``_phase_fn_many`` shape, one users axis up):
        ≤4 dispatches retrain a whole cohort.  Cached like every epoch
        program; jit specializes per (U, M) cohort shape."""
        batch_size = max(1, min(batch_size, n_train))
        key_ = (self.config, self.train_config, "phase_users", phase, n_ep,
                n_train, n_test, batch_size)

        def build():
            mapped = self._build_epoch_users(phase, n_train, n_test,
                                             batch_size)
            return jax.jit(
                self._make_phase_run(mapped, n_ep, _split_user_member_keys),
                donate_argnums=(0, 1, 2, 3, 4))

        return _epoch_fns_cached(key_, build)

    @staticmethod
    def _make_phase_run(epoch_fn, n_ep: int, split_keys) -> Callable:
        """The scanned whole-phase program, shared by the single-member and
        lockstep fast paths (they differ only in the epoch body and how the
        key carry advances).  ``split_keys`` must reproduce the
        corresponding per-epoch ``run_epoch``'s key chain exactly, so the
        scanned and per-epoch paths compute identical trajectories."""

        def phase_run(params, stats, opt, best_p, best_s, best_score,
                      data, lengths, train_rows, train_y, test_rows,
                      test_y, keys):
            def body(carry, _):
                p, st, op, bp, bs, bsc, ks = carry
                ks, subs = split_keys(ks)
                (p, st, op, bp, bs, bsc, tl, vl, f1, _preds,
                 imp) = epoch_fn(p, st, op, bp, bs, bsc, data, lengths,
                                 train_rows, train_y, test_rows, test_y,
                                 subs)
                return (p, st, op, bp, bs, bsc, ks), (tl, vl, f1, imp)

            carry, metrics = jax.lax.scan(
                body, (params, stats, opt, best_p, best_s, best_score,
                       keys), None, length=n_ep)
            return carry + metrics

        return phase_run

    def _phase_fn(self, phase: str, n_ep: int, n_train: int, n_test: int,
                  batch_size: int) -> Callable:
        """Single-member analogue of :meth:`_phase_fn_many`: a whole
        schedule phase as one scanned jit.  Used by ``fit``'s callback-free
        fast path (the 200-epoch CNN pre-training calls ``fit`` with no
        callback — TensorBoard scalars are written from the returned
        history — so per-epoch dispatch there was pure round-trip latency
        too)."""
        batch_size = max(1, min(batch_size, n_train))
        key_ = (self.config, self.train_config, "phase1", phase, n_ep,
                n_train, n_test, batch_size)

        def build():
            epoch = self._build_epoch(phase, n_train, n_test, batch_size)

            def split_one(k):
                k, sub = jax.random.split(k)
                return k, sub

            return jax.jit(self._make_phase_run(epoch, n_ep, split_one),
                           donate_argnums=(0, 1, 2, 3, 4))

        return _epoch_fns_cached(key_, build)

    def _phase_fn_many(self, phase: str, n_ep: int, n_train: int,
                       n_test: int, batch_size: int, mesh=None) -> Callable:
        """A whole schedule phase (``n_ep`` lockstep epochs) as ONE jitted
        ``lax.scan`` program.  Default single-chip; with ``mesh`` (opt-in
        via ``TrainConfig.scan_mesh_phases`` — see ``fit_many`` for why the
        mesh path defaults to per-epoch) the scanned program carries the
        same member shardings as the per-epoch mesh jit.

        The schedule is epoch-indexed (transitions never depend on data —
        ``amg_test.py:203-231``), so a phase's epoch count is known on the
        host and the per-epoch host loop is pure dispatch overhead: on the
        tunneled chip each of the retrain path's 100 epoch dispatches costs
        ~90 ms of round-trip latency (~10 s/retrain measured in
        ``ITERATION_r04``); the scan collapses that to one dispatch per
        phase (<=4 per retrain).  The scan body reproduces
        ``fit_many.run_epoch``'s key chain exactly — ``vmap(split)`` the
        member keys, feed the subkeys to the epoch — so the random stream
        is identical to the per-epoch path.  Per-epoch prediction tensors
        are not stacked (callers that need them — per-epoch callbacks —
        use the per-epoch path); metrics come back as ``(n_ep, M)`` stacks.
        """
        batch_size = max(1, min(batch_size, n_train))
        key_ = (self.config, self.train_config, "phase", phase, n_ep,
                n_train, n_test, batch_size, mesh)

        def build():
            mapped = self._build_epoch_many(phase, n_train, n_test,
                                            batch_size, mesh)
            phase_run = self._make_phase_run(mapped, n_ep,
                                             _split_member_keys)
            if mesh is None:
                return jax.jit(phase_run, donate_argnums=(0, 1, 2, 3, 4))
            member, repl = self._member_shardings(mesh)
            # carry (params..keys) keeps the member sharding; the (n_ep, M)
            # metric stacks come back replicated like the per-epoch mesh
            # jit's scalar metrics (host-readable on every process)
            return jax.jit(
                phase_run,
                in_shardings=(member,) * 6 + (repl,) * 6 + (member,),
                out_shardings=(member,) * 7 + (repl,) * 4,
                donate_argnums=(0, 1, 2, 3, 4))

        return _epoch_fns_cached(key_, build)

    def _run_scanned_schedule(self, n_epochs: int, adam_patience: int,
                              get_fn, reload_best, state, key_field: str,
                              fixed_args: tuple) -> list[tuple]:
        """Execute the schedule as one scanned jit per phase (the
        callback-free fast path shared by ``fit`` and ``fit_many``).
        Returns host-side per-epoch rows ``[(epoch, phase, tl, vl, f1,
        imp), ...]``.  Metric stacks stay DEVICE arrays until the single
        bulk ``device_get`` at the end — slicing them per epoch while the
        schedule runs would queue ~4 x n_epochs tiny gather dispatches."""
        seg_records: list[tuple] = []
        for si, (phase, start, end) in enumerate(
                self._phase_segments(n_epochs, adam_patience)):
            if si:
                reload_best(phase)
            fn = get_fn(phase, end - start)
            (state["params"], state["batch_stats"], state["opt_state"],
             state["best_params"], state["best_stats"],
             state["best_score"], state[key_field], tl, vl, f1, imp) = fn(
                state["params"], state["batch_stats"], state["opt_state"],
                state["best_params"], state["best_stats"],
                state["best_score"], *fixed_args, state[key_field])
            seg_records.append((phase, start, end, tl, vl, f1, imp))
        rows: list[tuple] = []
        for (phase, start, end, *_), (tl, vl, f1, imp) in zip(
                seg_records, jax.device_get([s[3:] for s in seg_records])):
            for j in range(end - start):
                rows.append((start + j, phase, tl[j], vl[j], f1[j],
                             imp[j]))
        return rows

    # -- host-level loop ---------------------------------------------------

    def _phase_segments(self, n_epochs: int,
                        adam_patience: int) -> list[tuple]:
        """``[(phase, start_epoch, end_epoch), ...]`` — the exact epoch
        ranges :meth:`_run_schedule` executes, computed up front.  Legal
        because the schedule is epoch-indexed: ``drop_counter`` resets only
        at transitions, never on improvement, so phase boundaries are
        data-independent (``amg_test.py:203-231``).  Derived by REPLAYING
        ``_run_schedule`` with recording closures — one source of truth, so
        a future schedule-semantics change cannot desync the scanned fast
        path from the per-epoch path."""
        eps: list[tuple] = []
        self._run_schedule(n_epochs, adam_patience,
                           lambda e, p: eps.append((e, p)), lambda p: None)
        segs: list[tuple] = []
        for e, p in eps:
            if segs and segs[-1][0] == p:
                segs[-1] = (p, segs[-1][1], e + 1)
            else:
                segs.append((p, e, e + 1))
        return segs

    def _run_schedule(self, n_epochs: int, adam_patience: int,
                      run_epoch, reload_best) -> None:
        """The epoch-indexed adam→sgd schedule controller, shared by ``fit``
        and ``fit_many`` (``amg_test.py:203-231``): ``run_epoch(epoch,
        phase)`` executes one epoch; at each transition ``reload_best(phase)``
        must restore the best checkpoint and re-init the optimizer.
        ``drop_counter`` resets only at transitions, never on improvement."""
        cfg = self.train_config
        phase_i = 0
        drop_counter = 0
        for epoch in range(n_epochs):
            drop_counter += 1
            run_epoch(epoch, PHASES[phase_i])
            patience = adam_patience if PHASES[phase_i] == "adam" \
                else cfg.sgd_patience
            if phase_i < len(PHASES) - 1 and drop_counter >= patience:
                phase_i += 1
                reload_best(PHASES[phase_i])
                drop_counter = 0

    def fit(self, variables, store: DeviceWaveformStore, train_ids, train_y,
            test_ids, test_y, key, *, n_epochs: int | None = None,
            batch_size: int | None = None, adam_patience: int | None = None,
            callback=None):
        """Train with the adam→sgd best-reload schedule; returns
        ``(best_variables, history)``.

        ``train_y`` / ``test_y``: one-hot float arrays aligned with the id
        lists.  ``callback(epoch, info_dict)`` is invoked per epoch (metrics /
        reporting hook).

        The caller's ``variables`` tree is COPIED before the first (donated)
        epoch call — like ``fit_many`` — so the input buffers are never
        invalidated.  This keeps a pending async checkpoint's deferred
        ``device_get`` of a live committee member's variables safe even if
        ``fit`` runs concurrently on the same tree.
        """
        cfg = self.train_config
        n_epochs = cfg.n_epochs if n_epochs is None else n_epochs
        batch_size = batch_size or cfg.batch_size
        adam_patience = adam_patience or cfg.adam_patience

        train_rows = jnp.asarray(store.row_of(train_ids))
        test_rows = jnp.asarray(store.row_of(test_ids))
        train_y = jnp.asarray(train_y)
        test_y = jnp.asarray(test_y)

        params = jax.tree.map(jnp.copy, variables["params"])
        batch_stats = jax.tree.map(jnp.copy, variables["batch_stats"])
        best_params = jax.tree.map(jnp.copy, params)
        best_stats = jax.tree.map(jnp.copy, batch_stats)
        # The reference starts best_metric at 0 (amg_test.py:295,
        # deam_classifier.py:249): an epoch only becomes the checkpoint when
        # its score = 1 − val_loss beats 0, so a training run whose every
        # epoch has val_loss >= 1 keeps the INCOMING weights.
        best_score = jnp.asarray(0.0)

        opt_state = make_tx(PHASES[0], cfg).init(params)
        history = []
        # mutable epoch state shared by the schedule-controller closures
        state = {"params": params, "batch_stats": batch_stats,
                 "opt_state": opt_state, "best_params": best_params,
                 "best_stats": best_stats, "best_score": best_score,
                 "key": key}

        def run_epoch(epoch, phase):
            fn = self._epoch_fn(phase, len(train_ids), len(test_ids),
                                batch_size)
            state["key"], sub = jax.random.split(state["key"])
            (state["params"], state["batch_stats"], state["opt_state"],
             state["best_params"], state["best_stats"], state["best_score"],
             train_loss, val_loss, val_f1, preds, improved) = fn(
                state["params"], state["batch_stats"], state["opt_state"],
                state["best_params"], state["best_stats"],
                state["best_score"], store.data, store.lengths, train_rows,
                train_y, test_rows, test_y, sub)
            # history holds DEVICE scalars until the end of the schedule —
            # per-epoch float() would block the dispatch pipeline (a full
            # host sync per epoch; the retrain hot loop runs 100 of them)
            info = {"epoch": epoch, "phase": phase, "train_loss": train_loss,
                    "val_loss": val_loss, "val_f1": val_f1,
                    "improved": improved}
            history.append(info)
            if callback is not None:
                _materialize_history([info])
                callback(epoch, info, np.asarray(preds))

        def reload_best(phase):
            # reload best at each transition (amg_test.py:205-229)
            state["params"] = jax.tree.map(jnp.copy, state["best_params"])
            state["batch_stats"] = jax.tree.map(jnp.copy,
                                                state["best_stats"])
            state["opt_state"] = make_tx(phase, cfg).init(state["params"])

        if callback is None:
            # Scanned fast path — one jit per schedule phase instead of one
            # per epoch; same contract as fit_many's (key chain identical
            # to run_epoch, parity pinned by
            # test_fit_scanned_matches_per_epoch)
            for epoch, phase, tl, vl, f1, imp in self._run_scanned_schedule(
                    n_epochs, adam_patience,
                    lambda phase, n_ep: self._phase_fn(
                        phase, n_ep, len(train_ids), len(test_ids),
                        batch_size),
                    reload_best, state, "key",
                    (store.data, store.lengths, train_rows, train_y,
                     test_rows, test_y)):
                history.append(
                    {"epoch": epoch, "phase": phase,
                     "train_loss": float(tl), "val_loss": float(vl),
                     "val_f1": float(f1), "improved": bool(imp)})
        else:
            self._run_schedule(n_epochs, adam_patience, run_epoch,
                               reload_best)
        return ({"params": state["best_params"],
                 "batch_stats": state["best_stats"]},
                _materialize_history(history))

    def fit_many(self, variables_list, store: DeviceWaveformStore, train_ids,
                 train_y, test_ids, test_y, key, *, n_epochs: int | None = None,
                 batch_size: int | None = None, adam_patience: int | None = None,
                 mesh=None, callback=None):
        """Train M members in lockstep: ONE vmapped jit per epoch instead of
        M sequential ``fit`` loops (reference hot loop #2 runs its members
        one by one — ``amg_test.py:496-502``).

        Exactness: the optimizer schedule is epoch-indexed (transitions never
        depend on data — ``amg_test.py:203-231``), so every member switches
        phase at the same epoch and lockstep vmap computes the same math as
        M independent loops.  Member ``i`` trains under
        ``jax.random.fold_in(key, i)``, the same stream the sequential
        committee path used.  With ``mesh`` (a ``(dp, member)`` training
        mesh), member state is sharded across chips on the ``member`` axis;
        a committee that doesn't divide the axis is padded with copies of
        the last member (trained redundantly, never returned), so the
        reference's 5-member committee runs unchanged on 4- or 8-wide
        meshes.  Multi-host meshes are supported: every process holds the
        identical committee, contributes only its member block, trains in
        lockstep SPMD, and receives the replicated winning checkpoints.

        Returns ``(best_variables_list, histories)`` with per-member
        histories in ``fit``'s format.  ``callback(epoch, infos)`` gets the
        per-member info list each epoch.
        """
        from consensus_entropy_tpu.models.short_cnn import stack_params

        cfg = self.train_config
        n_epochs = cfg.n_epochs if n_epochs is None else n_epochs
        batch_size = batch_size or cfg.batch_size
        adam_patience = adam_patience or cfg.adam_patience
        n_members = len(variables_list)

        train_rows = jnp.asarray(store.row_of(train_ids))
        test_rows = jnp.asarray(store.row_of(test_ids))
        train_y = jnp.asarray(train_y)
        test_y = jnp.asarray(test_y)

        # A sharded member axis must divide the mesh's member dimension: pad
        # the committee by repeating the last member (trained redundantly,
        # sliced off below) so e.g. 5 reference members run on a 4- or
        # 8-wide member axis.  Padded slots get distinct key streams but
        # never surface in the returned best/histories.
        n_total = n_members
        if mesh is not None:
            from consensus_entropy_tpu.parallel.mesh import MEMBER_AXIS

            shards = mesh.shape[MEMBER_AXIS]
            n_total = -(-n_members // shards) * shards
        padded = list(variables_list) + \
            [variables_list[-1]] * (n_total - n_members)

        stacked = stack_params(padded)
        params = stacked["params"]
        batch_stats = stacked["batch_stats"]
        best_params = jax.tree.map(jnp.copy, params)
        best_stats = jax.tree.map(jnp.copy, batch_stats)
        # per-member best gate, same 0-init parity as ``fit``
        best_score = jnp.zeros(n_total)
        keys = jax.vmap(lambda i: jax.random.fold_in(key, i))(
            jnp.arange(n_total))

        opt_state = jax.vmap(make_tx(PHASES[0], cfg).init)(params)

        member_sh = repl_sh = None
        multi_host = False
        data_arg, lengths_arg = store.data, store.lengths
        if mesh is not None:
            # COMMIT the member-stacked state to the member sharding up
            # front: incoming variables may carry other committed shardings
            # (e.g. replicated slices of a previous retrain's best params),
            # and jit raises on a committed-sharding/in_shardings mismatch
            # rather than resharding.
            from jax.sharding import NamedSharding, PartitionSpec as P

            from consensus_entropy_tpu.parallel.mesh import MEMBER_AXIS

            member_sh = NamedSharding(mesh, P(MEMBER_AXIS))
            repl_sh = NamedSharding(mesh, P())
            multi_host = jax.process_count() > 1
            if multi_host:
                # every process holds the identical member-stacked state
                # (the committee is loaded from the shared workspace in
                # lockstep); each contributes only its own member block —
                # typed PRNG keys ride as raw key data
                from consensus_entropy_tpu.parallel import multihost

                def feed(tree):
                    return jax.tree.map(
                        lambda a: multihost.feed_axis(
                            np.asarray(a), mesh, MEMBER_AXIS, 0), tree)

                (params, batch_stats, opt_state, best_params, best_stats,
                 best_score) = feed((params, batch_stats, opt_state,
                                     best_params, best_stats, best_score))
                keys = jax.random.wrap_key_data(
                    feed(jax.random.key_data(keys)))
                # broadcast inputs: process-local device arrays can't be
                # implicitly resharded onto non-addressable devices, so
                # feed them as replicated globals (every process holds the
                # identical store/ids/labels).  The waveform store is
                # static for the whole run and potentially HBM-sized, so
                # its replicated feed is cached ON the store (one
                # D2H+H2D round-trip per run, not per retrain call).
                cache = getattr(store, "_ce_repl_cache", None)
                if cache is None or cache[0] is not mesh:
                    store._ce_repl_cache = (mesh, multihost.feed_replicated(
                        (store.data, store.lengths), mesh))
                data_arg, lengths_arg = store._ce_repl_cache[1]
                train_rows, train_y, test_rows, test_y = \
                    multihost.feed_replicated(
                        (train_rows, train_y, test_rows, test_y), mesh)
            else:
                (params, batch_stats, opt_state, best_params, best_stats,
                 best_score, keys) = jax.device_put(
                    (params, batch_stats, opt_state, best_params,
                     best_stats, best_score, keys), member_sh)
        #: (epoch, phase, train_loss, val_loss, val_f1, improved).  On the
        #: per-epoch (callback / mesh) path the metric entries are DEVICE
        #: member-vectors — the whole schedule is queued asynchronously and
        #: synced in one bulk transfer at the end (per-epoch np.asarray
        #: here was the retrain path's pipeline stall: a blocking readback
        #: x n_epochs).  The scanned fast path appends already
        #: host-materialized rows (its own single bulk get); the final
        #: device_get passes those through untouched.
        records: list[tuple] = []
        state = {"params": params, "batch_stats": batch_stats,
                 "opt_state": opt_state, "best_params": best_params,
                 "best_stats": best_stats, "best_score": best_score,
                 "keys": keys}

        def run_epoch(epoch, phase):
            fn = self._epoch_fn_many(phase, len(train_ids), len(test_ids),
                                     batch_size, mesh)
            splits = jax.vmap(jax.random.split)(state["keys"])
            state["keys"], subs = splits[:, 0], splits[:, 1]
            (state["params"], state["batch_stats"], state["opt_state"],
             state["best_params"], state["best_stats"], state["best_score"],
             train_loss, val_loss, val_f1, _preds, improved) = fn(
                state["params"], state["batch_stats"], state["opt_state"],
                state["best_params"], state["best_stats"],
                state["best_score"], data_arg, lengths_arg, train_rows,
                train_y, test_rows, test_y, subs)
            records.append((epoch, phase, train_loss, val_loss, val_f1,
                            improved))
            if callback is not None:
                tl, vl, f1, imp = jax.device_get(
                    (train_loss, val_loss, val_f1, improved))
                callback(epoch, [
                    {"epoch": epoch, "phase": phase,
                     "train_loss": float(tl[m]), "val_loss": float(vl[m]),
                     "val_f1": float(f1[m]), "improved": bool(imp[m])}
                    for m in range(n_members)])

        def reload_best(phase):
            state["params"] = jax.tree.map(jnp.copy, state["best_params"])
            state["batch_stats"] = jax.tree.map(jnp.copy,
                                                state["best_stats"])
            opt = jax.vmap(make_tx(phase, cfg).init)(state["params"])
            if member_sh is not None:
                # jit identity re-commits to the member sharding (works on
                # multi-host global arrays, where device_put would not)
                opt = jax.jit(lambda o: o, out_shardings=member_sh)(opt)
            state["opt_state"] = opt

        if callback is None and (mesh is None or cfg.scan_mesh_phases):
            # Fast path (the production single-chip retrain): each schedule
            # phase is ONE scanned jit dispatch — <=len(PHASES) device
            # round-trips for the whole schedule instead of one per epoch
            # (the per-epoch host loop was pure dispatch latency, ~90 ms x
            # 100 epochs on the tunneled chip; measured 2.4x warm retrain).
            # The scan body chains the same vmap(split) key stream as
            # run_epoch, so both paths compute identical trajectories
            # (pinned by test_fit_many_scanned_matches_per_epoch).
            #
            # The MESH path defaults to per-epoch and takes the scanned
            # program only when ``TrainConfig.scan_mesh_phases`` opts in:
            # compiling scan(vmap(epoch)) with member shardings + donation
            # segfaulted the virtual-CPU XLA backend (SIGSEGV inside
            # backend_compile_and_load) deterministically in full-suite
            # process state — and that backend is exactly what validates
            # multi-chip correctness without hardware, so the default mesh
            # construct must never be the fragile one.  Real TPU meshes
            # don't share that bug; production multi-chip retrains should
            # set the flag and get <=4 dispatches instead of ~n_epochs
            # (1-device-mesh numeric parity pinned by
            # test_fit_many_scanned_mesh_matches_per_epoch).
            records.extend(self._run_scanned_schedule(
                n_epochs, adam_patience,
                lambda phase, n_ep: self._phase_fn_many(
                    phase, n_ep, len(train_ids), len(test_ids),
                    batch_size, mesh),
                reload_best, state, "keys",
                (data_arg, lengths_arg, train_rows, train_y, test_rows,
                 test_y)))
        else:
            self._run_schedule(n_epochs, adam_patience, run_epoch,
                               reload_best)
        if multi_host:
            # replicate the winning checkpoints (one all-gather over the
            # member axis) and land them as host numpy so downstream
            # consumers (scoring feeds, checkpoint writers) see ordinary
            # process-local values on every host
            bp, bs = jax.jit(lambda p, s: (p, s),
                             out_shardings=(repl_sh, repl_sh))(
                state["best_params"], state["best_stats"])
            state["best_params"] = jax.device_get(bp)
            state["best_stats"] = jax.device_get(bs)
        histories = [[] for _ in range(n_members)]
        metric_vals = jax.device_get([r[2:] for r in records])
        for (epoch, phase, *_), (tl, vl, f1, imp) in zip(records, metric_vals):
            for m in range(n_members):
                histories[m].append(
                    {"epoch": epoch, "phase": phase,
                     "train_loss": float(tl[m]), "val_loss": float(vl[m]),
                     "val_f1": float(f1[m]), "improved": bool(imp[m])})
        best = [{"params": jax.tree.map(lambda a, m=m: a[m],
                                        state["best_params"]),
                 "batch_stats": jax.tree.map(lambda a, m=m: a[m],
                                             state["best_stats"])}
                for m in range(n_members)]
        return best, histories

    def fit_many_users(self, users: list[dict], *,
                       n_epochs: int | None = None,
                       batch_size: int | None = None,
                       adam_patience: int | None = None) -> list[tuple]:
        """Train U users' committees in USER-AND-MEMBER lockstep: one
        scanned jit per schedule phase for the whole cohort — the
        cross-user extension of :meth:`fit_many`, and the device half of
        the fleet scheduler's ``cnn_retrain`` stacked dispatch
        (``committee.CNNRetrainPlan``).

        ``users``: one dict per user with ``variables_list`` (member
        variable trees), ``store`` (:class:`DeviceWaveformStore`),
        ``train_ids`` / ``train_y`` / ``test_ids`` / ``test_y`` and the
        user's retrain ``key``.  The cohort must be homogeneous in member
        count, split sizes and store geometry (the scheduler's plan
        group key guarantees it; checked loudly here).

        Exactness: lockstep across users is exact for the same reason it
        is across members — the optimizer schedule is epoch-indexed, so
        every user switches phase at the same epoch, and the user axis is
        a ``lax.map`` whose body is the member-lockstep epoch itself
        (``_build_epoch_users``), fed each user's own data/keys.  Member
        ``i`` of user ``u`` trains under ``fold_in(users[u].key, i)`` —
        the exact stream its own ``fit_many`` call would use — so
        per-user results are bit-identical to U sequential ``fit_many``
        calls (pinned by ``tests/test_cnn_fleet.py``).

        Returns ``[(best_variables_list, histories), ...]`` per user, each
        element exactly :meth:`fit_many`'s return shape.  Mesh sharding
        and per-epoch callbacks are the per-user path's business — cohort
        retraining is the callback-free production path.
        """
        from consensus_entropy_tpu.models.short_cnn import (
            stack_params,
            stack_user_params,
        )

        cfg = self.train_config
        n_epochs = cfg.n_epochs if n_epochs is None else n_epochs
        batch_size = batch_size or cfg.batch_size
        adam_patience = adam_patience or cfg.adam_patience
        u0 = users[0]
        n_users = len(users)
        n_members = len(u0["variables_list"])
        n_train, n_test = len(u0["train_ids"]), len(u0["test_ids"])
        for u in users:
            if (len(u["variables_list"]) != n_members
                    or len(u["train_ids"]) != n_train
                    or len(u["test_ids"]) != n_test
                    or u["store"].data.shape != u0["store"].data.shape):
                raise ValueError(
                    "fit_many_users cohort is not homogeneous (member "
                    "count / split sizes / store geometry must match; "
                    "group plans by their group_key)")

        stacked = stack_user_params(
            [stack_params(u["variables_list"]) for u in users])
        params = stacked["params"]
        batch_stats = stacked["batch_stats"]
        best_params = jax.tree.map(jnp.copy, params)
        best_stats = jax.tree.map(jnp.copy, batch_stats)
        best_score = jnp.zeros((n_users, n_members))
        # member i of user u: fold_in(key_u, i) — fit_many's exact stream;
        # typed keys ride as raw key data across the user stack
        keys = jax.random.wrap_key_data(jnp.stack([
            jax.random.key_data(jax.vmap(
                lambda i, k=u["key"]: jax.random.fold_in(k, i))(
                    jnp.arange(n_members)))
            for u in users]))
        opt_state = jax.vmap(jax.vmap(make_tx(PHASES[0], cfg).init))(params)

        data = jnp.stack([u["store"].data for u in users])
        lengths = jnp.stack([u["store"].lengths for u in users])
        train_rows = jnp.stack([jnp.asarray(u["store"].row_of(u["train_ids"]))
                                for u in users])
        train_y = jnp.stack([jnp.asarray(u["train_y"]) for u in users])
        test_rows = jnp.stack([jnp.asarray(u["store"].row_of(u["test_ids"]))
                               for u in users])
        test_y = jnp.stack([jnp.asarray(u["test_y"]) for u in users])

        state = {"params": params, "batch_stats": batch_stats,
                 "opt_state": opt_state, "best_params": best_params,
                 "best_stats": best_stats, "best_score": best_score,
                 "keys": keys}

        def reload_best(phase):
            state["params"] = jax.tree.map(jnp.copy, state["best_params"])
            state["batch_stats"] = jax.tree.map(jnp.copy,
                                                state["best_stats"])
            state["opt_state"] = jax.vmap(jax.vmap(
                make_tx(phase, cfg).init))(state["params"])

        rows = self._run_scanned_schedule(
            n_epochs, adam_patience,
            lambda phase, n_ep: self._phase_fn_users(
                phase, n_ep, n_train, n_test, batch_size),
            reload_best, state, "keys",
            (data, lengths, train_rows, train_y, test_rows, test_y))

        out = []
        for ui in range(n_users):
            histories = [
                [{"epoch": epoch, "phase": phase,
                  "train_loss": float(tl[ui, m]), "val_loss": float(vl[ui, m]),
                  "val_f1": float(f1[ui, m]), "improved": bool(imp[ui, m])}
                 for epoch, phase, tl, vl, f1, imp in rows]
                for m in range(n_members)]
            best = [{"params": jax.tree.map(
                         lambda a, ui=ui, m=m: a[ui, m],
                         state["best_params"]),
                     "batch_stats": jax.tree.map(
                         lambda a, ui=ui, m=m: a[ui, m],
                         state["best_stats"])}
                    for m in range(n_members)]
            out.append((best, histories))
        return out
