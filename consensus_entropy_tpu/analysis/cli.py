"""The ``cetpu-lint`` console entry point.

Pure host (no jax import anywhere on this path): parses the tree, loads
the contract tables from source, prints text or JSON findings, and exits
nonzero on any unsuppressed finding — the CI gate ``scripts/
lint_check.sh`` wraps exactly this.

Examples::

    cetpu-lint                          # whole repo, text report
    cetpu-lint consensus_entropy_tpu/serve --format json
    cetpu-lint --list-rules
    cetpu-lint --select fault-point-literal,event-schema tests
    cetpu-lint --write-baseline         # grandfather current findings
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from consensus_entropy_tpu.analysis import (  # noqa: F401 (rules register)
    available_rules,
    lint_paths,
    load_baseline,
)
from consensus_entropy_tpu.analysis.engine import baseline_from
from consensus_entropy_tpu.analysis.model import ModelError, ProjectModel

#: what "the whole repo" means when no paths are given
DEFAULT_PATHS = ("consensus_entropy_tpu", "tests", "scripts", "bench.py",
                 "__graft_entry__.py", "native")
BASELINE_FILE = "lint_baseline.json"


def _find_root(start: str) -> str:
    """Walk up from ``start`` to the directory holding the package —
    lets ``cetpu-lint`` run from anywhere inside the repo."""
    cur = os.path.abspath(start)
    while True:
        if os.path.isdir(os.path.join(cur, "consensus_entropy_tpu")):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            raise SystemExit(
                f"cetpu-lint: no consensus_entropy_tpu package found "
                f"above {start!r}; pass --root")
        cur = parent


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cetpu-lint",
        description="repo-specific static analysis: donation, PRNG, "
                    "replay-determinism and schema discipline "
                    "(see README 'Static analysis')")
    p.add_argument("paths", nargs="*",
                   help=f"files/directories to lint, relative to the "
                        f"repo root (default: {' '.join(DEFAULT_PATHS)})")
    p.add_argument("--root", default=None,
                   help="repository root (default: walk up from cwd to "
                        "the directory holding consensus_entropy_tpu)")
    p.add_argument("--format", choices=("text", "json"), default="text",
                   help="finding report format (default text)")
    p.add_argument("--select", default=None, metavar="RULE[,RULE...]",
                   help="run only these rules")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help=f"baseline file (default <root>/{BASELINE_FILE} "
                        "when it exists)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline file (report everything)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current findings to the baseline "
                        "file and exit 0 (the grandfathering ratchet)")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for name, doc in available_rules().items():
            print(f"{name:24} {doc}")
        return 0
    root = args.root or _find_root(os.getcwd())
    paths = args.paths or [p for p in DEFAULT_PATHS
                           if os.path.exists(os.path.join(root, p))]
    select = [s.strip() for s in args.select.split(",")] \
        if args.select else None
    baseline_path = args.baseline or os.path.join(root, BASELINE_FILE)
    try:
        model = ProjectModel.from_repo(root)
        baseline = {} if (args.no_baseline or args.write_baseline) \
            else load_baseline(baseline_path)
        result = lint_paths(paths, root=root, model=model, select=select,
                            baseline=baseline)
    except (ModelError, ValueError) as e:
        print(f"cetpu-lint: {e}", file=sys.stderr)
        return 2
    if args.write_baseline:
        if result.errors:
            # a baseline computed while files failed to parse is
            # incomplete — refuse rather than grandfather a lie
            for e in result.errors:
                print(f"cetpu-lint: ERROR: {e}", file=sys.stderr)
            print("cetpu-lint: refusing to write a baseline while "
                  f"{len(result.errors)} file(s) are unparseable",
                  file=sys.stderr)
            return 2
        payload = baseline_from(result.findings)
        with open(baseline_path, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"cetpu-lint: wrote {len(payload)} baseline bucket(s) "
              f"({len(result.findings)} finding(s)) to {baseline_path}",
              file=sys.stderr)
        return 0
    if args.format == "json":
        print(json.dumps({
            "findings": [f.to_dict() for f in result.findings],
            "errors": result.errors,
            "files": result.files,
            "suppressed": result.suppressed,
            "baselined": result.baselined,
            "wall_s": result.wall_s,
        }))
    else:
        for f in result.findings:
            print(str(f))
        for e in result.errors:
            print(f"ERROR: {e}")
        status = "clean" if result.clean else (
            f"{len(result.findings)} finding(s)"
            + (f", {len(result.errors)} parse error(s)"
               if result.errors else ""))
        print(f"cetpu-lint: {result.files} file(s) in {result.wall_s}s "
              f"— {status} ({result.suppressed} noqa'd, "
              f"{result.baselined} baselined)", file=sys.stderr)
    return 0 if result.clean else 1


if __name__ == "__main__":
    raise SystemExit(main())
