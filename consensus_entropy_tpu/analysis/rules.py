"""The lint rules — one per load-bearing convention in the stack.

Each rule documents the CONTRACT it enforces, the scope it applies to,
and what the accepted escape hatch is (``# cetpu: noqa[rule] <why>``).
All checks are pure-AST heuristics: linear over branches where real
dataflow would need a solver, conservative where types are unknowable.
A false positive is one visible noqa with a justification — the price
of machine-checking conventions that otherwise only fail at 3am in a
replay drill.

Scoping tables (kept here, next to the rules that read them):

- :data:`REPLAY_PREFIXES` / :data:`REPLAY_FILES` — the replay-critical
  surface: everything journaled, checkpointed or replayed must be a
  pure function of journal/seed state, never of wall clock or unseeded
  RNG (serve journal replay, fleet eviction+resume, resilience
  recovery, ALState).
- :data:`HOT_PATH_FUNCS` — the scheduler's dispatch hot path, where the
  PR 8 h2d/d2h accounting assumes every transfer goes through
  ``Acquirer.take_h2d`` and an implicit ``float()``/``.item()`` sync
  would both stall the pipeline and escape the accounting.
- :data:`LOCK_ORDER` — the documented lock-acquisition order table for
  the ``lock-discipline`` rule.  EMPTY by design: the stack's threading
  convention is single-lock critical sections (``with self._lock:``),
  never nested locks — a nested acquisition is a latent deadlock the
  moment a second code path takes the pair in the other order.  Adding
  a pair here is the sanctioned way to introduce an ordering (and the
  review surface for it).
"""

from __future__ import annotations

import ast

from consensus_entropy_tpu.analysis.engine import register

PKG = "consensus_entropy_tpu/"

#: replay-critical modules (directory prefixes + exact files)
REPLAY_PREFIXES = (
    PKG + "serve/",
    PKG + "fleet/",
    PKG + "resilience/",
    PKG + "workload/",
)
REPLAY_FILES = (
    PKG + "al/state.py",
    PKG + "al/workspace.py",
)

#: dispatch hot paths: file -> function names whose whole subtree
#: (nested closures included) must not host-sync implicitly.  Beyond
#: the scheduler's dispatch core this now covers the serve loop's
#: per-round admission/collection paths and the acquirer's staging +
#: select-finish path (cetpu-lint follow-on (c)) — made possible by
#: the one sanctioned pull below.
HOT_PATH_FUNCS = {
    PKG + "fleet/scheduler.py": {
        "pump", "_dispatch_scores", "_stacked_call", "_plan_call",
        "_single_call", "_result_rows", "_hold_partial_plans",
        "_h2d", "_stack", "_sig",
    },
    PKG + "serve/server.py": {
        "serve", "_refill", "_admit_up_to_target", "_collect",
        "_admit_due_requeues", "_apply_fences",
    },
    PKG + "al/acquisition.py": {
        "finish_select", "_ids", "scoring_inputs", "run_scoring",
        "take_h2d", "device_masks",
    },
    PKG + "acquire/builtin.py": {"extract_queries", "fused_inputs",
                                 "scoring_inputs"},
}

#: the ONE sanctioned hot-path device→host pull: the 2·k selection
#: scalars ``finish_select`` maps back to song ids each iteration
#: (``ops.scoring.selection_scalars``).  Spelled through a named helper
#: so the rule can whitelist the INTENT, not a line — any other
#: ``np.asarray``/``float()`` in a hot-path function stays a finding.
_SANCTIONED_PULLS = {"selection_scalars"}

#: wall-clock reads replay can never reproduce.  ``time.perf_counter``
#: is deliberately ABSENT: it is the stack's sanctioned duration-
#: telemetry clock (StepTimer, wait_s, span durations) — process-local
#: deltas that never feed a journaled decision; listing it would bury
#: the real signal under telemetry noqas.
_WALLCLOCKS = {
    "time.time", "time.monotonic", "time.time_ns", "time.monotonic_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
    "datetime.now", "datetime.utcnow",  # `from datetime import datetime`
}

#: jax.random fns that CONSUME the key passed in first position (using
#: the same key at a second sink yields correlated — or identical —
#: streams; ``split`` consumes too: the parent key must not outlive it)
_KEY_CONSUMERS = {
    "split", "uniform", "normal", "bernoulli", "permutation", "randint",
    "choice", "categorical", "gumbel", "exponential", "truncated_normal",
    "shuffle", "bits", "dirichlet", "beta", "gamma", "poisson", "laplace",
    "rademacher", "multivariate_normal",
}

#: order-independent consumers a set may feed directly
_ORDER_FREE = {"sorted", "sum", "min", "max", "any", "all", "len",
               "set", "frozenset"}

#: order-CAPTURING conversions of an iterable
_ORDER_CAPTURE = {"list", "tuple", "enumerate", "iter", "reversed"}

#: documented lock-acquisition order: ``(outer_path, inner_path)`` pairs
#: a nested ``with`` acquisition is allowed to take.  Empty — the stack
#: has no sanctioned nested-lock pair today (see the module docstring);
#: the coordinator/worker planes stay deadlock-free by construction
#: because every critical section holds exactly one lock.
LOCK_ORDER: tuple = ()


def _in_pkg(path: str) -> bool:
    return path.startswith(PKG)


def _in_replay_scope(path: str) -> bool:
    return path.startswith(REPLAY_PREFIXES) or path in REPLAY_FILES


def _dotted(node) -> str | None:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _iter_scopes(tree):
    """Yield ``(scope_node, body)`` for the module and every function —
    each analyzed independently (nested defs get their own scope AND
    appear, unanalyzed, in their parent's)."""
    yield tree, tree.body
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node, node.body


def _calls_in_order(stmt):
    """Call nodes within one statement, source order (nested defs and
    lambdas excluded — separate control flow)."""
    skip: set[int] = set()
    for node in ast.walk(stmt):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not stmt:
            for sub in ast.walk(node):
                skip.add(id(sub))
    calls = [n for n in ast.walk(stmt)
             if isinstance(n, ast.Call) and id(n) not in skip]
    return sorted(calls, key=lambda n: (n.lineno, n.col_offset))


def _store_paths(stmt) -> list[str]:
    """Dotted paths assigned by this statement (tuple targets unpacked)."""
    targets = []
    if isinstance(stmt, ast.Assign):
        targets = list(stmt.targets)
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        targets = [stmt.target]
    elif isinstance(stmt, ast.For):
        targets = [stmt.target]
    elif isinstance(stmt, ast.withitem) and stmt.optional_vars:
        targets = [stmt.optional_vars]
    out = []
    stack = list(targets)
    while stack:
        t = stack.pop()
        if isinstance(t, (ast.Tuple, ast.List)):
            stack.extend(t.elts)
        else:
            path = _dotted(t)
            if path:
                out.append(path)
    return out


# -- rule 1: donation-after-use ---------------------------------------------


def _local_donated_fns(tree) -> dict[str, tuple]:
    """Module-level ``X = jax.jit(fn, donate_argnums=<literal>)``
    assignments: ``{X: positions}`` — the in-module siblings of the
    ``FUSED_DONATE`` table (e.g. ``al.acquisition._scatter_rows``)."""
    out: dict[str, tuple] = {}
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            continue
        callee = _dotted(node.value.func)
        if callee is None or callee.split(".")[-1] != "jit":
            continue
        for kw in node.value.keywords:
            if kw.arg != "donate_argnums":
                continue
            try:
                pos = ast.literal_eval(kw.value)
            except ValueError:
                continue
            out[node.targets[0].id] = (pos,) if isinstance(pos, int) \
                else tuple(pos)
    return out


def _donated_positions(call, model, local) -> tuple | None:
    """Which positional args of ``call`` are donated, or None."""
    f = call.func
    if isinstance(f, ast.Subscript):  # fns["mc_fused"](...)
        sl = f.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            return model.fused_donate.get(sl.value)
        return None
    name = _dotted(f)
    if name is None:
        return None
    last = name.split(".")[-1]
    return model.fused_donate.get(last) or local.get(last)


@register(
    "donation-after-use",
    doc="no read of a buffer after it was passed in a donated argument "
        "position of a *_fused / donate_argnums-jitted call",
    applies=_in_pkg)
def check_donation_after_use(tree, ctx):
    """The fused serve step's contract (PR 8): the jitted ``*_fused``
    families donate their mask operands (``ops.scoring.FUSED_DONATE``),
    so the caller's reference is SPENT the moment the call is staged —
    XLA reuses the buffer in place.  Reading it afterwards returns
    whatever the dispatch scribbled there (or raises on a deleted
    buffer), and the failure is timing-dependent: it survives unit runs
    and dies under serve load.  The only valid continuation is the
    RETURNED buffer (``finish_select`` adopts ``FusedStepResult``
    masks).  Linear over branches — a donate in one branch and a read
    in the other flags conservatively.

    FLOW-SENSITIVE over local rebinds: a pure alias assignment
    (``m = mask`` / ``m = self.device.probs``) links the names, so
    donating EITHER spends both — a read through the other spelling
    still flags — while rebinding a name to the returned buffer (or
    anything else) breaks only ITS link.  Rebinding the alias TARGET
    carries the pending consumption onto the surviving alias: the old
    name's buffer is gone, but the alias still holds the spent one."""
    findings = []
    local = _local_donated_fns(tree)
    for _scope, body in _iter_scopes(tree):
        consumed: dict[str, int] = {}  # canonical path -> donating line
        aliases: dict[str, str] = {}   # name -> canonical dotted path

        def canon(path):
            """Resolve a path's leading name through the alias table
            (alias values are stored pre-canonicalized, so one hop)."""
            head, _, rest = path.partition(".")
            head = aliases.get(head, head)
            return head + ("." + rest) if rest else head

        def flat(node, store_paths=()):
            """Process one straight-line node: register donations, flag
            loads of already-donated paths, then clear stores and
            update the alias links the node's assignments create."""
            donated_args: set[int] = set()
            for call in _calls_in_order(node):
                pos = _donated_positions(call, ctx.model, local)
                if not pos:
                    continue
                for p in pos:
                    if p < len(call.args):
                        path = _dotted(call.args[p])
                        if path:
                            donated_args.add(id(call.args[p]))
                            consumed[canon(path)] = call.lineno
            if consumed:
                flagged: set[tuple] = set()  # one per (path, line)
                for sub in ast.walk(node):
                    if id(sub) in donated_args:
                        continue
                    if not isinstance(sub, (ast.Name, ast.Attribute)):
                        continue
                    if not isinstance(getattr(sub, "ctx", None),
                                      ast.Load):
                        continue
                    path = _dotted(sub)
                    if path is None:
                        continue
                    cpath0 = canon(path)
                    for cpath, at in consumed.items():
                        if cpath0 != cpath \
                                and not cpath0.startswith(cpath + "."):
                            continue
                        if (cpath, sub.lineno) in flagged:
                            continue  # mask and mask.sum are ONE read
                        flagged.add((cpath, sub.lineno))
                        label = repr(path) if cpath0 == path else \
                            f"{path!r} (an alias of {cpath!r})"
                        findings.append(ctx.finding(
                            "donation-after-use", sub,
                            f"{label} was donated to a fused call "
                            f"on line {at} and is read here; use "
                            "the returned buffer instead (the "
                            "donated operand is spent)"))
            for spath in store_paths:
                aliases.pop(spath, None)  # the rebind breaks ITS link
                for a, v in list(aliases.items()):
                    if v != spath and not v.startswith(spath + "."):
                        continue
                    # the alias outlives its rebound target: it still
                    # references the OLD buffer, so a pending
                    # consumption survives under the alias's own name
                    at = consumed.get(v)
                    if at is not None:
                        consumed[a] = at
                    del aliases[a]
                for cpath in list(consumed):
                    if cpath == spath or cpath.startswith(spath + "."):
                        del consumed[cpath]
            # pure alias assigns (no call on the value side) link AFTER
            # the store cleared the target's previous state
            value = getattr(node, "value", None) \
                if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                else None
            vpath = _dotted(value) if value is not None else None
            if vpath:
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Name):
                        cv = canon(vpath)
                        if cv != t.id:
                            aliases[t.id] = cv

        def scan(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef, ast.ClassDef)):
                    continue
                if isinstance(stmt, ast.If):
                    flat(stmt.test)
                    scan(stmt.body)
                    scan(stmt.orelse)
                elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                    flat(stmt.iter, _store_paths(stmt))
                    scan(stmt.body)
                    scan(stmt.orelse)
                elif isinstance(stmt, ast.While):
                    flat(stmt.test)
                    scan(stmt.body)
                    scan(stmt.orelse)
                elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                    for item in stmt.items:
                        flat(item.context_expr, _store_paths(item))
                    scan(stmt.body)
                elif isinstance(stmt, ast.Try):
                    scan(stmt.body)
                    for handler in stmt.handlers:
                        scan(handler.body)
                    scan(stmt.orelse)
                    scan(stmt.finalbody)
                else:
                    flat(stmt, _store_paths(stmt))

        scan(body)
    return findings


# -- rule 2a: literal PRNG seeds --------------------------------------------


@register(
    "prng-literal-key",
    doc="no jax.random.key / PRNGKey with a literal seed in library "
        "code (derive from the run seed; tests/bench are exempt)",
    applies=_in_pkg)
def check_prng_literal(tree, ctx):
    """Replay, failover and the qbdc mask discipline all assume every
    key in the system derives from the ONE run seed (fold_in/split
    chains from ``ALConfig.seed``).  A literal ``key(0)`` buried in
    library code silently decouples that stream: two users collide, or
    a resume replays a different committee than the original run."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        name = _dotted(node.func)
        if name is None:
            continue
        parts = name.split(".")
        is_key_ctor = parts[-1] == "PRNGKey" or (
            len(parts) >= 2 and parts[-2:] == ["random", "key"])
        if not is_key_ctor:
            continue
        seed = node.args[0]
        if isinstance(seed, ast.Constant) \
                and isinstance(seed.value, (int, float)):
            findings.append(ctx.finding(
                "prng-literal-key", node,
                f"literal PRNG seed {seed.value!r} in library code; "
                "derive the key from the run seed (config/CLI) so "
                "replay and failover reproduce the stream"))
    return findings


# -- rule 2b: key reuse ------------------------------------------------------


def _key_consumer_operand(call):
    """``(path, fn)`` when ``call`` is a jax.random consumer taking a
    trackable key in first position, else None."""
    name = _dotted(call.func)
    if name is None or not call.args:
        return None
    parts = name.split(".")
    if len(parts) < 2 or parts[-2] != "random" \
            or parts[-1] not in _KEY_CONSUMERS:
        return None
    path = _dotted(call.args[0])
    return (path, parts[-1]) if path else None


@register(
    "prng-key-reuse",
    doc="no key consumed by two jax.random sinks without an "
        "interleaving split/fold_in",
    applies=_in_pkg)
def check_prng_key_reuse(tree, ctx):
    """QBDC committees, dropout schedules and the rand acquisition mode
    are bit-replayable because every sink gets its OWN key: ``k, sub =
    split(k)`` before each use, or ``fold_in(k, i)`` per member.
    Feeding one key to two sinks yields identical (not independent)
    draws — a committee whose members agree by construction, an AL run
    whose "random" arm repeats its first batch.  ``If`` branches fork
    the tracking state and re-merge (union of consumed); loop bodies
    are scanned twice so loop-carried reuse is caught."""
    findings = []

    def flag(call, path, fn, first):
        findings.append(ctx.finding(
            "prng-key-reuse", call,
            f"key {path!r} already consumed on line {first} is fed to "
            f"jax.random.{fn} again; split/fold_in between sinks"))

    def consume_calls(node, state, seen):
        for call in _calls_in_order(node):
            op = _key_consumer_operand(call)
            if op is None:
                continue
            path, fn = op
            if path in state:
                if id(call) not in seen:
                    seen.add(id(call))
                    flag(call, path, fn, state[path])
            else:
                state[path] = call.lineno

    def clear_stores(paths, state):
        for spath in paths:
            for kpath in list(state):
                if kpath == spath or kpath.startswith(spath + "."):
                    del state[kpath]

    def scan(stmts, state, seen) -> bool:
        """Scan a block; returns True when it TERMINATES (every path
        returns/raises), so an If whose taken branch exits never leaks
        its consumed keys into the fall-through code."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.Return, ast.Raise, ast.Break,
                                 ast.Continue)):
                consume_calls(stmt, state, seen)
                return True
            if isinstance(stmt, ast.If):
                consume_calls(stmt.test, state, seen)
                b, o = dict(state), dict(state)
                b_done = scan(stmt.body, b, seen)
                o_done = scan(stmt.orelse, o, seen)
                if b_done and o_done:
                    return True
                state.clear()  # re-merge: consumed in EITHER live branch
                if not b_done:
                    state.update(b)
                if not o_done:
                    state.update(o)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                consume_calls(stmt.iter, state, seen)
                clear_stores(_store_paths(stmt), state)
                for _ in range(2):  # twice: loop-carried reuse
                    scan(stmt.body, state, seen)
                scan(stmt.orelse, state, seen)
            elif isinstance(stmt, ast.While):
                consume_calls(stmt.test, state, seen)
                for _ in range(2):
                    scan(stmt.body, state, seen)
                scan(stmt.orelse, state, seen)
            elif isinstance(stmt, ast.Try):
                scan(stmt.body, state, seen)
                for h in stmt.handlers:
                    scan(h.body, state, seen)
                scan(stmt.orelse, state, seen)
                scan(stmt.finalbody, state, seen)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    consume_calls(item.context_expr, state, seen)
                    clear_stores(_store_paths(item), state)
                scan(stmt.body, state, seen)
            else:
                consume_calls(stmt, state, seen)
                clear_stores(_store_paths(stmt), state)
        return False

    for _scope, body in _iter_scopes(tree):
        scan(body, {}, set())
    return findings


# -- rule 3a: wall clocks in replay-critical code ---------------------------


@register(
    "replay-wallclock",
    doc="no time.time()/time.monotonic() CALLS in replay-critical "
        "modules outside the injected-clock seams",
    applies=_in_replay_scope)
def check_replay_wallclock(tree, ctx):
    """Crash-replay parity (journal replay, eviction+resume, planner
    edge re-derivation) holds because no journaled DECISION reads the
    wall clock.  The sanctioned pattern is the injected-clock seam — a
    ``clock=time.monotonic`` parameter default (watchdog, breaker,
    planner) the caller can pin in tests and drills.  Only CALLS are
    flagged, so the uncalled seam reference is clean by construction —
    and a CALL in a parameter default (``def f(t=time.time())``) flags
    like any other: that is a timestamp frozen at import, reused for
    every invocation.  Wall-stamping telemetry fields that replay
    provably ignores is the legitimate exemption — say so in a
    ``# cetpu: noqa`` justification."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name in _WALLCLOCKS:
            findings.append(ctx.finding(
                "replay-wallclock", node,
                f"{name}() in a replay-critical module; route through "
                "an injected-clock seam (clock= parameter) or justify "
                "via noqa that replay never reads this value"))
    return findings


# -- rule 3b: unseeded RNG in replay-critical code --------------------------


@register(
    "replay-unseeded-rng",
    doc="no stdlib random / os.urandom / unseeded numpy RNG in "
        "replay-critical modules",
    applies=_in_replay_scope)
def check_replay_unseeded_rng(tree, ctx):
    """Every random draw on the replay surface is seeded (backoff
    jitter, fault corruption, session keys) so a journal replay or a
    kill-matrix drill reproduces the run bit-for-bit.  The stdlib
    ``random`` module, ``os.urandom``, ``uuid.uuid4`` and numpy's
    GLOBAL sampler state (``np.random.<sampler>()``, or
    ``default_rng()`` with no seed) all break that."""
    findings = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "random":
                    findings.append(ctx.finding(
                        "replay-unseeded-rng", node,
                        "stdlib random imported in a replay-critical "
                        "module; use a seeded np.random.default_rng or "
                        "a jax key derived from the run seed"))
        elif isinstance(node, ast.ImportFrom):
            if node.module == "random":
                findings.append(ctx.finding(
                    "replay-unseeded-rng", node,
                    "stdlib random imported in a replay-critical "
                    "module; use a seeded RNG"))
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name is None:
                continue
            if name in ("os.urandom", "uuid.uuid4"):
                findings.append(ctx.finding(
                    "replay-unseeded-rng", node,
                    f"{name}() is entropy the journal cannot replay; "
                    "derive from the run seed"))
                continue
            parts = name.split(".")
            if len(parts) >= 3 and parts[-2] == "random" \
                    and parts[-3] in ("np", "numpy"):
                sampler = parts[-1]
                if sampler == "default_rng":
                    if not node.args and not node.keywords:
                        findings.append(ctx.finding(
                            "replay-unseeded-rng", node,
                            "np.random.default_rng() without a seed in "
                            "a replay-critical module"))
                elif sampler not in ("Generator", "SeedSequence",
                                     "BitGenerator", "PCG64"):
                    findings.append(ctx.finding(
                        "replay-unseeded-rng", node,
                        f"np.random.{sampler} uses numpy's global RNG "
                        "state; use a seeded default_rng instance"))
    return findings


# -- rule 3c: set-iteration order in replay-critical code -------------------


def _is_set_valued(node) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        return _dotted(node.func) in ("set", "frozenset")
    return False


def _is_set_annotation(node) -> bool:
    if isinstance(node, ast.Subscript):
        node = node.value
    return _dotted(node) in ("set", "frozenset", "Set", "FrozenSet",
                             "typing.Set", "typing.FrozenSet")


def _typed_paths(tree, is_value, is_annotation) -> dict[int, set[str]]:
    """Per-scope dotted paths whose assigned value satisfies ``is_value``
    (or annotation ``is_annotation``), keyed by scope node id:

    - module scope: top-level names (direct statements only — a
      function-local of the same name must not taint the module);
    - each ClassDef: ``self.x`` attributes assigned/annotated anywhere
      in the class body (methods included);
    - each FunctionDef: ITS OWN locals (no descent into nested defs —
      they scope separately)."""

    out: dict[int, set[str]] = {}

    def direct_stmts(body):
        """Statements reachable without crossing a def/class boundary."""
        stack = list(body)
        while stack:
            stmt = stack.pop()
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            yield stmt
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    stack.extend(s for s in sub
                                 if isinstance(s, ast.stmt))
            for handler in getattr(stmt, "handlers", []) or []:
                stack.extend(handler.body)

    def collect(body, paths, *, attrs_only=False):
        for stmt in direct_stmts(body):
            if isinstance(stmt, ast.Assign) and is_value(stmt.value):
                for t in stmt.targets:
                    p = _dotted(t)
                    if p and (not attrs_only or p.startswith("self.")):
                        paths.add(p)
            elif isinstance(stmt, ast.AnnAssign) \
                    and is_annotation(stmt.annotation):
                p = _dotted(stmt.target)
                if p and (not attrs_only or p.startswith("self.")):
                    paths.add(p)

    module_paths: set[str] = set()
    collect(tree.body, module_paths)
    out[id(tree)] = module_paths
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            paths: set[str] = set()
            for sub in ast.walk(node):
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    collect(sub.body, paths, attrs_only=True)
            out[id(node)] = paths
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            paths = set()
            collect(node.body, paths)
            out[id(node)] = paths
    return out


def _set_typed_paths(tree) -> dict[int, set[str]]:
    """Per-scope set-typed dotted paths (see :func:`_typed_paths`)."""
    return _typed_paths(tree, _is_set_valued, _is_set_annotation)


def _annotate_active(tree, by_scope) -> dict[int, set[str]]:
    """node id -> typed paths visible there (module names, enclosing
    class self-attrs, enclosing function locals)."""
    active_at: dict[int, set[str]] = {}
    root = by_scope.get(id(tree), set())

    def annotate(node, active: set[str]):
        for child in ast.iter_child_nodes(node):
            cur = active
            if isinstance(child, (ast.ClassDef, ast.FunctionDef,
                                  ast.AsyncFunctionDef)):
                cur = active | by_scope.get(id(child), set())
            active_at[id(child)] = cur
            annotate(child, cur)

    active_at[id(tree)] = root
    annotate(tree, root)
    return active_at


@register(
    "replay-set-iteration",
    doc="no order-dependent iteration over sets in replay-critical "
        "modules (sorted() or an insertion-ordered dict instead)",
    applies=_in_replay_scope)
def check_replay_set_iteration(tree, ctx):
    """Python set iteration order varies with insertion history and hash
    seeds — two processes replaying the same journal can walk the same
    set differently.  Anything that feeds journaled or emitted output
    (finish records, assignment feeds, metrics lines) from a set walk
    is therefore nondeterministic across restarts.  Flags: ``for``
    loops and comprehensions iterating a set expression or a set-typed
    attribute, and order-capturing conversions (``list``/``tuple``/
    ``enumerate``/``iter``/``reversed``).  Order-independent reducers
    (``sorted``/``sum``/``min``/``max``/``any``/``all``/``len``) and
    membership tests stay silent."""
    findings = []
    by_scope = _set_typed_paths(tree)
    set_paths_global = by_scope.get(id(tree), set())
    active_at = _annotate_active(tree, by_scope)

    def is_set_expr(node) -> bool:
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in ("set", "frozenset"):
                return True
        path = _dotted(node)
        if path is None:
            return False
        return path in active_at.get(id(node), set_paths_global)

    # comprehensions that feed an order-free reducer directly
    allowed_comps: set[int] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and node.args:
            name = _dotted(node.func)
            if name in _ORDER_FREE and isinstance(
                    node.args[0], (ast.GeneratorExp, ast.ListComp,
                                   ast.SetComp, ast.DictComp)):
                allowed_comps.add(id(node.args[0]))

    def flag(node, what):
        findings.append(ctx.finding(
            "replay-set-iteration", node,
            f"{what} over a set in a replay-critical module is "
            "order-nondeterministic across processes; sorted(...) it, "
            "or keep an insertion-ordered dict"))

    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.AsyncFor)):
            if is_set_expr(node.iter):
                flag(node.iter, "for-loop iteration")
        elif isinstance(node, (ast.GeneratorExp, ast.ListComp,
                               ast.SetComp, ast.DictComp)):
            if id(node) in allowed_comps:
                continue
            for gen in node.generators:
                if is_set_expr(gen.iter):
                    flag(gen.iter, "comprehension iteration")
        elif isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name in _ORDER_CAPTURE and len(node.args) == 1 \
                    and is_set_expr(node.args[0]):
                flag(node, f"{name}() conversion")
    return findings


# -- rule 4: implicit host sync in dispatch hot paths -----------------------


@register(
    "implicit-host-sync",
    doc="no float()/bool()/.item()/np.asarray in the scheduler "
        "dispatch hot path (transfers go through Acquirer.take_h2d)",
    applies=lambda path: path in HOT_PATH_FUNCS)
def check_implicit_host_sync(tree, ctx):
    """The stacked-dispatch pipeline (PR 8) stays asynchronous because
    no result row is pulled before every bucket's dispatch is in
    flight, and every host→device byte is graded through
    ``Acquirer.take_h2d``.  A ``float(x)``/``bool(x)``/``x.item()``/
    ``np.asarray(x)`` on a jax value inside the hot path is a hidden
    blocking d2h sync — it serializes the pipeline AND escapes the
    transfer accounting the BENCH artifacts pin."""
    findings = []
    hot = HOT_PATH_FUNCS.get(ctx.path, set())
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if node.name not in hot:
            continue
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _dotted(sub.func)
            if name is not None \
                    and name.split(".")[-1] in _SANCTIONED_PULLS:
                continue  # the one sanctioned selection-scalar pull
            msg = None
            if name in ("float", "bool") and len(sub.args) == 1:
                msg = (f"{name}() forces a blocking device→host sync "
                       "in the dispatch hot path")
            elif isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "item" and not sub.args:
                msg = (".item() forces a blocking device→host sync in "
                       "the dispatch hot path")
            elif name in ("np.asarray", "numpy.asarray", "np.array",
                          "numpy.array"):
                msg = (f"{name}() pulls a device buffer to host outside "
                       "the Acquirer.take_h2d transfer accounting")
            if msg:
                findings.append(ctx.finding(
                    "implicit-host-sync", sub,
                    msg + "; keep rows device-resident (lazy slices) "
                          "or stage through the acquirer"))
    return findings


# -- rule 5: fault-point literals -------------------------------------------


def _is_fire_call(call) -> bool:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id == "fire"
    if isinstance(f, ast.Attribute) and f.attr == "fire":
        base = _dotted(f.value)
        return base is not None and base.split(".")[-1] == "faults"
    return False


@register(
    "fault-point-literal",
    doc="every faults.fire / FaultRule / fault_point string literal "
        "must name a registered resilience.faults.FAULT_POINTS member")
def check_fault_point_literal(tree, ctx):
    """The fault matrix only drills boundaries that EXIST: a typo'd
    ``faults.fire("serve.dipatch")`` never fires (the injector matches
    nothing) and its recovery path silently stops being exercised.
    ``FaultRule.__post_init__`` validates at RUNTIME — i.e. only when
    the drill runs (``faults.py``); this check resolves every literal
    statically: ``faults.fire("…")`` calls, ``FaultRule(point=…)``
    constructions, ``fault_point = "…"`` plan attributes, and
    ``parse_spec("point:action…")`` specs."""
    model = ctx.model
    if not model.fault_points:
        return []
    findings = []

    def check_point(node, value: str):
        if value not in model.fault_points:
            findings.append(ctx.finding(
                "fault-point-literal", node,
                f"fault point {value!r} is not in resilience.faults."
                f"FAULT_POINTS; register it there or fix the literal"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            if _is_fire_call(node) and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    check_point(arg, arg.value)
            name = _dotted(node.func)
            last = name.split(".")[-1] if name else None
            if last == "FaultRule":
                point = None
                if node.args and isinstance(node.args[0], ast.Constant):
                    point = node.args[0]
                for kw in node.keywords:
                    if kw.arg == "point" \
                            and isinstance(kw.value, ast.Constant):
                        point = kw.value
                if point is not None and isinstance(point.value, str):
                    check_point(point, point.value)
            elif last == "parse_spec" and node.args:
                spec = node.args[0]
                if isinstance(spec, ast.Constant) \
                        and isinstance(spec.value, str):
                    for part in spec.value.split(","):
                        part = part.strip()
                        if ":" in part:
                            check_point(spec, part.split(":", 1)[0])
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, (ast.Name, ast.Attribute)) \
                        and (t.id if isinstance(t, ast.Name) else t.attr) \
                        == "fault_point" \
                        and isinstance(node.value, ast.Constant) \
                        and isinstance(node.value.value, str):
                    check_point(node.value, node.value.value)
    return findings


# -- rule 6: event-schema conformance ---------------------------------------


#: what a LITERAL argument node must look like per schema field kind.
#: Only literals are judged — a Name/Attribute/Call argument's runtime
#: type is unknowable to a pure-AST pass, so those always pass here and
#: ``obs.export.validate_metrics`` catches them at read time instead.
def _literal_kind_ok(kind, node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        v = node.value
        if kind == "str":
            return isinstance(v, str)
        if kind == "int":
            return isinstance(v, int) and not isinstance(v, bool)
        if kind == "float":
            return (isinstance(v, (int, float))
                    and not isinstance(v, bool))
        if kind == "list":
            return False  # a Constant is never a list literal
        return True
    if isinstance(node, (ast.List, ast.Tuple, ast.ListComp)):
        return kind == "list"
    if isinstance(node, (ast.Dict, ast.Set, ast.DictComp, ast.SetComp)):
        return False  # literally the wrong container for every kind
    # Name/Call/Attribute/...: runtime type unknowable to a pure-AST
    # pass — obs.export.validate_metrics judges it at read time
    return True


@register(
    "event-schema",
    doc="every report.event(...) / EventWriter.emit({...}) literal "
        "emit site must match obs.export.EVENT_FIELDS (fields AND "
        "literal argument types)")
def check_event_schema(tree, ctx):
    """``obs.export.validate_metrics`` rejects malformed records at READ
    time — after the run already emitted them.  This check moves the
    contract to the emit site: a literal event kind must be registered
    in ``EVENT_FIELDS``, the call's keyword set must cover the kind's
    required fields (a ``**kwargs`` splat defeats the field check but
    the kind is still verified), and a required field passed as a
    LITERAL must hold the field's registered type kind — the v2.1
    schema's str/int/float/list table (lint follow-on (d); non-literal
    arguments are left to the runtime validator).  Extra fields are
    fine — the schema lists the floor, not the ceiling."""
    model = ctx.model
    if not model.event_fields:
        return []
    findings = []

    def check_kind(node, kind, present, has_splat):
        if kind not in model.event_fields:
            findings.append(ctx.finding(
                "event-schema", node,
                f"event kind {kind!r} is not in obs.export."
                f"EVENT_FIELDS; register it (with its required fields) "
                "or fix the literal"))
            return
        fields = model.event_fields[kind]
        if not has_splat:
            missing = [f for f in fields if f not in present]
            if missing:
                findings.append(ctx.finding(
                    "event-schema", node,
                    f"event {kind!r} emit site lacks required field(s) "
                    f"{missing}; EVENT_FIELDS requires "
                    f"{list(fields)}"))
        for field, value in present.items():
            want = fields.get(field) if isinstance(fields, dict) else None
            if want and value is not None \
                    and not _literal_kind_ok(want, value):
                findings.append(ctx.finding(
                    "event-schema", node,
                    f"event {kind!r} field {field!r} must be {want} "
                    f"(EVENT_FIELDS v2.1 kind table); this literal "
                    "argument is not"))

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Attribute):
            continue
        if node.func.attr == "event":
            if not node.args:
                continue
            kind = node.args[0]
            if not (isinstance(kind, ast.Constant)
                    and isinstance(kind.value, str)):
                continue
            present = {kw.arg: kw.value for kw in node.keywords
                       if kw.arg is not None}
            has_splat = any(kw.arg is None for kw in node.keywords)
            check_kind(node, kind.value, present, has_splat)
        elif node.func.attr == "emit" and len(node.args) == 1 \
                and isinstance(node.args[0], ast.Dict):
            d = node.args[0]
            keys = {}
            has_splat = False
            for k, v in zip(d.keys, d.values):
                if k is None:
                    has_splat = True  # {**rec} merge: keys unknowable
                elif isinstance(k, ast.Constant):
                    keys[k.value] = v
            kind = keys.get("event")
            if isinstance(kind, ast.Constant) \
                    and isinstance(kind.value, str):
                check_kind(node, kind.value, keys, has_splat)
    return findings


# -- rule 7: raw durable IO --------------------------------------------------


#: the durability-critical surface: every byte written here is either a
#: ledger (journal / WAL / feed), a lease, a checkpoint marker or a
#:  quarantine sidecar — all must route through ``resilience.io`` so the
#: ``io.*`` fault points cover them and the CRC framing discipline is
#: uniform
DURABLE_PREFIXES = (
    PKG + "serve/",
    PKG + "resilience/",
)
DURABLE_FILES = (
    PKG + "al/workspace.py",
)


def _in_durable_scope(path: str) -> bool:
    return path.startswith(DURABLE_PREFIXES) or path in DURABLE_FILES


@register(
    "raw-durable-io",
    doc="no direct open(w/a/x) / os.replace / os.fsync in "
        "durability-critical modules (route through resilience.io so "
        "the io.* fault points and CRC framing cover the write)",
    applies=_in_durable_scope)
def check_raw_durable_io(tree, ctx):
    """The storage-integrity guarantees (PR 19) hold only if every
    durable byte flows through ONE seam: ``resilience.io`` is where the
    ``io.write.*`` / ``io.fsync`` / ``io.rename`` fault points fire,
    where short writes and silent fsync drops are injected in the kill
    matrix, and where the CRC frame discipline lives.  A raw
    ``open(path, "w")`` in serve/ or resilience/ is a write the fault
    matrix cannot drill and fsck cannot reason about — it reintroduces
    exactly the torn-write blind spot the seam closed.  Flags literal
    write/append/exclusive open modes (positional or ``mode=``),
    ``os.replace`` and ``os.fsync``.  Read opens, ``r+b`` byte-surgery
    (the fault injector's corrupt action) and non-literal modes pass.
    The sanctioned escapes — the seam's own primitives, zero-byte lock
    siblings that carry no data — say so in a ``# cetpu: noqa`` why."""
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name in ("os.replace", "os.fsync"):
            findings.append(ctx.finding(
                "raw-durable-io", node,
                f"direct {name}() in a durability-critical module; use "
                "resilience.io.replace/fsync (or atomic_write) so the "
                "io.* fault points cover the commit"))
            continue
        if name not in ("open", "io.open", "builtins.open"):
            continue
        mode = None
        if len(node.args) >= 2:
            mode = node.args[1]
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if not (isinstance(mode, ast.Constant)
                and isinstance(mode.value, str)):
            continue  # no/unknowable mode: a read, or runtime-chosen
        if any(c in mode.value for c in "wax"):
            findings.append(ctx.finding(
                "raw-durable-io", node,
                f"raw open(..., {mode.value!r}) in a durability-"
                "critical module; route the write through "
                "resilience.io (open_append/atomic_write/write) so "
                "fault drills and CRC framing cover it"))
    return findings


# -- rule 8: lock discipline -------------------------------------------------


_LOCK_CTORS = ("threading.Lock", "threading.RLock", "Lock", "RLock")


def _is_lock_valued(node) -> bool:
    return isinstance(node, ast.Call) and _dotted(node.func) in _LOCK_CTORS


def _is_lock_annotation(node) -> bool:
    return _dotted(node) in _LOCK_CTORS


def _lock_typed_paths(tree) -> dict[int, set[str]]:
    """Per-scope lock-typed dotted paths (see :func:`_typed_paths`):
    names/attributes assigned ``threading.Lock()`` / ``RLock()`` or
    annotated as such.  ``Condition``/``Semaphore`` are deliberately NOT
    tracked — their wait/notify protocols have their own shapes and the
    queue's ``with self._cond:`` idiom is already the sanctioned form."""
    return _typed_paths(tree, _is_lock_valued, _is_lock_annotation)


@register(
    "lock-discipline",
    doc="locks are held via `with` only (no bare .acquire()), and a "
        "second lock is never taken while one is held unless the pair "
        "is in the documented LOCK_ORDER table",
    applies=_in_pkg)
def check_lock_discipline(tree, ctx):
    """The fabric's threading model survives SIGKILL drills because its
    critical sections are trivially correct: every lock is taken with
    ``with`` (released on ANY exit — an exception inside a bare
    ``acquire()``/``release()`` pair leaks the lock and wedges the
    worker's intake or fence queue forever), and no code path holds two
    locks at once (two paths nesting the same pair in opposite orders is
    a deadlock that only fires under load, i.e. in the chaos soak, not
    in unit runs).  Flags: (a) any ``.acquire()`` call on a lock-typed
    path — ``with`` never spells it, so a bare acquire is always a
    hand-rolled critical section; (b) a ``with`` acquiring a lock-typed
    path while an enclosing ``with`` in the same function already holds
    one, unless that exact ``(outer, inner)`` pair is documented in
    :data:`LOCK_ORDER`.  Nested defs are separate control flow (they
    run later, maybe on another thread) and are scanned as their own
    scopes."""
    findings = []
    by_scope = _lock_typed_paths(tree)
    if not any(by_scope.values()):
        return findings
    active_at = _annotate_active(tree, by_scope)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "acquire":
            path = _dotted(node.func.value)
            if path and path in active_at.get(id(node), set()):
                findings.append(ctx.finding(
                    "lock-discipline", node,
                    f"bare {path}.acquire() — hold locks via `with "
                    f"{path}:` so every exit path (including "
                    "exceptions) releases"))

    def with_lock_paths(stmt) -> list[str]:
        out = []
        for item in stmt.items:
            p = _dotted(item.context_expr)
            if p and p in active_at.get(id(stmt), set()):
                out.append(p)
        return out

    def scan(stmts, held: list[str]):
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = with_lock_paths(stmt)
                # a multi-item `with a, b:` acquires left-to-right — the
                # earlier items are held while the later ones acquire
                for i, p in enumerate(acquired):
                    for h in held + acquired[:i]:
                        if (h, p) not in LOCK_ORDER:
                            findings.append(ctx.finding(
                                "lock-discipline", stmt,
                                f"lock {p!r} acquired while {h!r} is "
                                "held and the pair is not in the "
                                "documented LOCK_ORDER table; nested "
                                "locks deadlock the first time two "
                                "paths disagree on the order"))
                scan(stmt.body, held + acquired)
                continue
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if isinstance(sub, list):
                    scan(sub, held)
            for handler in getattr(stmt, "handlers", []) or []:
                scan(handler.body, held)

    for _scope, body in _iter_scopes(tree):
        scan(body, [])
    return findings


# -- rule: deadline-discipline ----------------------------------------------


def _in_serve(path: str) -> bool:
    return path.startswith(PKG + "serve/")


def _sleep_calls(loop) -> list:
    """Sleep calls inside ``loop`` (nested defs excluded — separate
    control flow, scanned as their own loops if they have any)."""
    skip: set[int] = set()
    for node in ast.walk(loop):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for sub in ast.walk(node):
                skip.add(id(sub))
    out = []
    for node in ast.walk(loop):
        if id(node) in skip or not isinstance(node, ast.Call):
            continue
        name = _dotted(node.func)
        if name and name.split(".")[-1] == "sleep":
            out.append(node)
    return out


def _has_deadline_seam(loop) -> bool:
    """True when the loop's subtree references a bounding seam: a name
    or attribute whose spelling carries ``deadline``/``timeout``, or a
    clock read through the injected seam (``clock``/``_clock``) — the
    shapes every bounded poll loop in serve/ already uses."""
    for node in ast.walk(loop):
        if isinstance(node, ast.Name):
            ident = node.id
        elif isinstance(node, ast.Attribute):
            ident = node.attr
        else:
            continue
        low = ident.lower()
        if "deadline" in low or "timeout" in low or "clock" in low:
            return True
    return False


@register(
    "deadline-discipline",
    doc="serve/ never waits unboundedly: thread/process .join() calls "
        "carry a timeout, and a constant-condition poll loop that "
        "sleeps must read a deadline or the injected clock seam",
    applies=_in_serve)
def check_deadline_discipline(tree, ctx):
    """The gray-failure lesson, machine-checked: a wedged peer doesn't
    crash, it STALLS — and any unbounded wait in the serve plane turns
    one gray host into a wedged coordinator (the exact failure the
    stall/slow fault actions inject).  Two shapes are flagged:

    (a) a zero-argument ``.join()`` call — joining a thread or process
        with no timeout waits forever on a stalled peer (string
        ``sep.join(parts)`` always takes an argument, so a bare join is
        never the str method);
    (b) a ``while`` loop with a CONSTANT-truthy test whose body sleeps
        (``time.sleep`` et al.) but never references a bounding seam —
        no ``deadline``/``timeout`` name, no injected ``clock`` read —
        so nothing inside it can ever decide "too long".  Loops with a
        real exit condition (``while self._clock() < deadline``, the
        run loop's work-remaining test) are bounded by construction
        and stay clean.

    The escape hatch is the usual ``# cetpu: noqa[deadline-discipline]
    <why>`` — e.g. a loop whose bound lives one call down."""
    findings = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "join" \
                and not node.args and not node.keywords:
            findings.append(ctx.finding(
                "deadline-discipline", node,
                "bare .join() — pass timeout= (and handle the still-"
                "alive case) so a stalled peer can't hold this plane "
                "forever"))
        if isinstance(node, ast.While):
            test = node.test
            constant_truthy = (isinstance(test, ast.Constant)
                               and bool(test.value))
            if not constant_truthy:
                continue
            if _sleep_calls(node) and not _has_deadline_seam(node):
                findings.append(ctx.finding(
                    "deadline-discipline", node,
                    "unbounded poll loop: `while True` + sleep with no "
                    "deadline/timeout/injected-clock reference — give "
                    "it a deadline (or route the bound through the "
                    "clock seam) so a gray peer can't wedge it"))
    return findings
