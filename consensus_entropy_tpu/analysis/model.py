"""The project model: the stack's contract tables, loaded WITHOUT imports.

The lint rules check source against three registries that live in runtime
modules the linter must not import (``ops.scoring`` pulls in jax at
import time; ``resilience.faults`` activates ``CETPU_FAULTS`` on import):

- ``resilience.faults.FAULT_POINTS`` — the named fault-injection points,
- ``obs.export.EVENT_FIELDS`` — the schema-v2 event table,
- ``ops.scoring.FUSED_DONATE`` — donated argument positions per fused fn.

This module re-derives them by PARSING the defining files and
``ast.literal_eval``-ing the assigned literals — pure host, no project or
jax imports, millisecond cost.  ``tests/test_lint.py`` pins the parsed
tables EQUAL to the runtime objects, so the two can never drift silently:
a table edit that breaks the literal shape fails the loader loudly, and a
loader bug that drops entries fails the equality pin.
"""

from __future__ import annotations

import ast
import dataclasses
import os

#: (module-relative source file, assigned name) per table
_TABLE_SOURCES = {
    "fault_points": ("resilience/faults.py", "FAULT_POINTS"),
    "event_fields": ("obs/export.py", "EVENT_FIELDS"),
    "fused_donate": ("ops/scoring.py", "FUSED_DONATE"),
}


class ModelError(RuntimeError):
    """A contract table could not be statically recovered from source —
    its defining assignment moved, or stopped being a literal the loader
    can evaluate.  Update ``analysis.model`` alongside such a change."""


def _extract_assignment(path: str, name: str):
    """Evaluate the module-level ``name = <literal>`` assignment in
    ``path``.  ``frozenset({...})`` / ``set({...})`` / ``dict({...})``
    wrappers around a literal are unwrapped (``FAULT_POINTS`` is a
    ``frozenset`` call, which ``ast.literal_eval`` alone rejects)."""
    with open(path, "r", encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in targets):
            continue
        value = node.value
        if (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id in ("frozenset", "set", "dict", "tuple")
                and len(value.args) == 1 and not value.keywords):
            value = value.args[0]
        try:
            return ast.literal_eval(value)
        except ValueError as e:
            raise ModelError(
                f"{path}: {name} is no longer a literal the lint model "
                f"can evaluate ({e}); keep the table a plain literal or "
                "teach analysis.model its new shape") from e
    raise ModelError(f"{path}: no module-level assignment to {name} "
                     "found (did the table move?)")


@dataclasses.dataclass(frozen=True)
class ProjectModel:
    """The statically recovered contract tables (see module docstring)."""

    fault_points: frozenset
    event_fields: dict
    fused_donate: dict

    @classmethod
    def load(cls, package_root: str) -> "ProjectModel":
        """``package_root``: the ``consensus_entropy_tpu`` directory."""
        values = {}
        for key, (rel, name) in _TABLE_SOURCES.items():
            values[key] = _extract_assignment(
                os.path.join(package_root, rel), name)
        return cls(fault_points=frozenset(values["fault_points"]),
                   # v2.1 table: {kind: {field: type-kind}} — dicts kept
                   # whole so the event-schema rule can check literal
                   # argument TYPES, not just field presence
                   event_fields={k: dict(v) for k, v
                                 in values["event_fields"].items()},
                   fused_donate={k: tuple(v) for k, v
                                 in values["fused_donate"].items()})

    @classmethod
    def from_repo(cls, root: str) -> "ProjectModel":
        """``root``: the repository root (holds ``consensus_entropy_tpu``)."""
        return cls.load(os.path.join(root, "consensus_entropy_tpu"))

    @classmethod
    def empty(cls) -> "ProjectModel":
        """A model with no registered contracts — fixture tests use it to
        prove a rule stays silent without project tables."""
        return cls(fault_points=frozenset(), event_fields={},
                   fused_donate={})
