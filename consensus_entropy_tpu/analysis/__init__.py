"""cetpu-lint: repo-specific static analysis over Python ``ast``.

Every load-bearing guarantee in this stack is a *convention*: fused fns
donate their mask buffers (``ops.scoring.FUSED_DONATE``), qbdc dropout
keys fold from the AL-iteration seed, replay-critical code must never
consult a wall clock or an unseeded RNG, every ``faults.fire`` literal
must name a registered fault point, and every schema-v2 emit site must
match ``obs.export.EVENT_FIELDS``.  Tests enforce these only on the
paths they happen to exercise; this package enforces them at the SOURCE
level, before any run happens.

Design constraints (see README "Static analysis"):

- **pure host**: the pass imports nothing from jax.  The project model
  (:mod:`analysis.model`) reads the ``FAULT_POINTS`` / ``EVENT_FIELDS``
  / ``FUSED_DONATE`` tables straight out of the source files via
  ``ast.literal_eval``, so ``cetpu-lint`` runs in seconds anywhere the
  tree was copied to — no backend, no imports of the linted code.
- **suppressions are visible**: a finding is silenced per line with
  ``# cetpu: noqa[rule]`` (justify it in the same comment) or
  grandfathered in the checked-in baseline file (``lint_baseline.json``
  — kept EMPTY: fix it or noqa it with a reason).
- **registry**: rules self-register (:func:`analysis.engine.register`);
  ``cetpu-lint --list-rules`` prints the live table.
"""

from consensus_entropy_tpu.analysis.engine import (
    Finding,
    LintResult,
    available_rules,
    lint_paths,
    lint_source,
    load_baseline,
    register,
)
from consensus_entropy_tpu.analysis.model import ProjectModel

# importing the rules module populates the registry
from consensus_entropy_tpu.analysis import rules as _rules  # noqa: F401

__all__ = [
    "Finding",
    "LintResult",
    "ProjectModel",
    "available_rules",
    "lint_paths",
    "lint_source",
    "load_baseline",
    "register",
]
