"""The lint engine: rule registry, suppression semantics, file walking.

A rule is a callable ``(tree, ctx) -> iterable[Finding]`` registered
under a kebab-case name with a scope predicate over repo-relative paths.
The engine owns everything around the rules:

- **noqa**: a finding whose anchor line carries ``# cetpu: noqa[rule]``
  (or a bare ``# cetpu: noqa`` — all rules) is suppressed.  The bracket
  list is comma-separated rule names; anything after the bracket is the
  justification the satellite workflow requires.
- **baseline**: grandfathered findings live in a checked-in JSON file
  mapping ``"<rule>:<path>"`` to a COUNT (counts, not line numbers, so
  unrelated edits don't invalidate entries).  Up to that many findings
  of the rule in the file are suppressed, lowest line first; new
  findings past the count still fail.  The ratchet direction: the
  repo's committed baseline stays empty, fixtures exercise the format.
- **walking**: ``lint_paths`` expands directories to ``*.py`` files
  (skipping ``__pycache__``/hidden dirs), parses each once, and runs
  every in-scope rule over the shared tree.  ``lint_source`` is the
  test surface: lint a source string AS IF it lived at a given
  repo-relative path, so fixtures exercise path-scoped rules without
  touching the real tree.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
import time

from consensus_entropy_tpu.analysis.model import ProjectModel

_NOQA_RE = re.compile(
    r"#\s*cetpu:\s*noqa(?:\[(?P<rules>[a-z0-9_,\- ]+)\])?", re.IGNORECASE)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str      # repo-relative, forward slashes
    line: int      # 1-based anchor line
    col: int
    message: str

    def key(self) -> str:
        """The baseline bucket this finding counts against."""
        return f"{self.rule}:{self.path}"

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"[{self.rule}] {self.message}")


@dataclasses.dataclass
class LintContext:
    """Per-file state handed to every rule."""

    path: str                 # repo-relative
    source: str
    lines: list[str]
    model: ProjectModel

    def finding(self, rule: str, node, message: str) -> Finding:
        return Finding(rule=rule, path=self.path,
                       line=getattr(node, "lineno", 1),
                       col=getattr(node, "col_offset", 0),
                       message=message)


@dataclasses.dataclass
class _Rule:
    name: str
    doc: str
    check: object                      # (tree, ctx) -> iterable[Finding]
    applies: object                    # (rel_path) -> bool


_REGISTRY: dict[str, _Rule] = {}


def register(name: str, *, doc: str, applies=None):
    """Decorator: add a rule to the registry.  ``applies(rel_path)``
    scopes the rule (default: every linted file)."""
    if not re.fullmatch(r"[a-z0-9][a-z0-9\-]*", name):
        raise ValueError(f"rule names are kebab-case, got {name!r}")

    def deco(fn):
        if name in _REGISTRY:
            raise ValueError(f"duplicate rule {name!r}")
        _REGISTRY[name] = _Rule(name=name, doc=doc, check=fn,
                                applies=applies or (lambda path: True))
        return fn

    return deco


def available_rules() -> dict[str, str]:
    """``{name: one-line doc}`` for the live registry."""
    return {name: rule.doc for name, rule in sorted(_REGISTRY.items())}


# -- suppression semantics ---------------------------------------------------


def _noqa_rules(line: str) -> set[str] | None:
    """Rules suppressed by this physical line: ``None`` when no noqa
    comment, the empty set for a bare ``# cetpu: noqa`` (ALL rules),
    otherwise the named set."""
    m = _NOQA_RE.search(line)
    if m is None:
        return None
    rules = m.group("rules")
    if rules is None:
        return set()
    return {r.strip() for r in rules.split(",") if r.strip()}


def apply_noqa(findings: list[Finding], lines: list[str]) -> list[Finding]:
    out = []
    for f in findings:
        line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        suppressed = _noqa_rules(line)
        if suppressed is not None and (not suppressed
                                       or f.rule in suppressed):
            continue
        out.append(f)
    return out


def load_baseline(path: str | None) -> dict[str, int]:
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    if not isinstance(raw, dict):
        raise ValueError(f"{path}: baseline must be a JSON object "
                         "mapping 'rule:path' to a count")
    return {str(k): int(v) for k, v in raw.items()}


def apply_baseline(findings: list[Finding],
                   baseline: dict[str, int]) -> list[Finding]:
    """Suppress up to ``baseline[key]`` findings per (rule, path) bucket,
    lowest line first — count-based, so unrelated edits in the file
    don't invalidate the grandfathering."""
    if not baseline:
        return list(findings)
    budget = dict(baseline)
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.rule, f.line,
                                             f.col)):
        if budget.get(f.key(), 0) > 0:
            budget[f.key()] -= 1
            continue
        out.append(f)
    return out


def baseline_from(findings: list[Finding]) -> dict[str, int]:
    """The ``--write-baseline`` payload for the current findings."""
    out: dict[str, int] = {}
    for f in findings:
        out[f.key()] = out.get(f.key(), 0) + 1
    return dict(sorted(out.items()))


# -- running -----------------------------------------------------------------


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]      # after noqa + baseline
    suppressed: int              # noqa'd findings
    baselined: int               # baseline-absorbed findings
    files: int
    errors: list[str]            # unparseable files
    wall_s: float

    @property
    def clean(self) -> bool:
        return not self.findings and not self.errors


def _select(select) -> list[_Rule]:
    if select is None:
        return list(_REGISTRY.values())
    unknown = set(select) - set(_REGISTRY)
    if unknown:
        raise ValueError(f"unknown rule(s) {sorted(unknown)} "
                         f"(have {sorted(_REGISTRY)})")
    return [_REGISTRY[name] for name in select]


def lint_source(source: str, rel_path: str, *, model: ProjectModel,
                select=None) -> list[Finding]:
    """Lint one source string as if it lived at ``rel_path`` (the test
    surface — path-scoped rules see the virtual location).  Returns
    noqa-filtered findings; baseline is the caller's concern."""
    lines = source.splitlines()
    tree = ast.parse(source, filename=rel_path)
    ctx = LintContext(path=rel_path, source=source, lines=lines,
                      model=model)
    findings: list[Finding] = []
    for rule in _select(select):
        if rule.applies(rel_path):
            findings.extend(rule.check(tree, ctx))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return apply_noqa(findings, lines)


def _iter_py_files(paths: list[str], root: str):
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(full):
            # a typo'd path must FAIL, not lint zero files and pass —
            # a CI gate pointed at a missing directory would otherwise
            # stay green forever
            raise ValueError(f"lint path does not exist: {p!r} "
                             f"(resolved {full!r})")
        if os.path.isfile(full):
            yield full
            continue
        for dirpath, dirnames, filenames in os.walk(full):
            dirnames[:] = sorted(
                d for d in dirnames
                if not d.startswith(".") and d != "__pycache__")
            for fname in sorted(filenames):
                if fname.endswith(".py"):
                    yield os.path.join(dirpath, fname)


def lint_paths(paths: list[str], *, root: str,
               model: ProjectModel | None = None, select=None,
               baseline: dict[str, int] | None = None) -> LintResult:
    """Lint files/directories under ``root``; see :class:`LintResult`."""
    t0 = time.perf_counter()
    model = model or ProjectModel.from_repo(root)
    rules = _select(select)
    raw: list[Finding] = []
    kept: list[Finding] = []
    errors: list[str] = []
    files = 0
    for full in _iter_py_files(paths, root):
        rel = os.path.relpath(full, root).replace(os.sep, "/")
        try:
            with open(full, "r", encoding="utf-8") as f:
                source = f.read()
            tree = ast.parse(source, filename=rel)
        except (SyntaxError, ValueError, OSError) as e:
            errors.append(f"{rel}: unparseable ({e})")
            continue
        files += 1
        lines = source.splitlines()
        ctx = LintContext(path=rel, source=source, lines=lines,
                          model=model)
        file_findings: list[Finding] = []
        for rule in rules:
            if rule.applies(rel):
                file_findings.extend(rule.check(tree, ctx))
        file_findings.sort(key=lambda f: (f.line, f.col, f.rule))
        raw.extend(file_findings)
        kept.extend(apply_noqa(file_findings, lines))
    suppressed = len(raw) - len(kept)
    final = apply_baseline(kept, baseline or {})
    return LintResult(findings=final, suppressed=suppressed,
                      baselined=len(kept) - len(final), files=files,
                      errors=errors,
                      wall_s=round(time.perf_counter() - t0, 3))
