"""Unified observability: span tracing, metrics registry, export.

One subsystem for the telemetry the serving stack grew piecemeal
(``RollingStat``/``StepTimer`` in ``utils.profiling``, hand-rolled
dispatch records in ``fleet.scheduler``, per-host ``fleet_metrics.jsonl``
files nobody merged):

- :mod:`obs.metrics` — counters/gauges/log-bucketed histograms behind a
  named registry, the ``StepTimer``/``RollingStat`` primitives (moved
  here; ``utils.profiling`` keeps thin aliases), and the single
  schema-tagged JSONL event writer every metrics stream goes through.
- :mod:`obs.trace` — a :class:`~obs.trace.Tracer` with explicit span
  contexts (``run → user → al_iter → {score_dispatch, host_step,
  retrain, checkpoint, admission_wait}``); trace/span ids derive
  deterministically from ``(run_id, user, iteration)`` so a resumed or
  failed-over user CONTINUES its trace instead of starting a new one.
- :mod:`obs.export` — torn-tail-tolerant readers, schema-v2 validation
  (field presence AND per-field kinds), the multi-host spans+metrics
  merge, Chrome trace-event export (Perfetto-loadable, one lane per
  host/worker/bucket plus the ``control-plane`` decision lane with flow
  links into user traces) and the text report behind ``python -m
  consensus_entropy_tpu.cli.report``.

The LIVE introspection plane (ISSUE 15) rides on top:

- :mod:`obs.jit_telemetry` — process-wide jit-family build/lookup/compile
  counters with resident-executable polling, fed by the ``ops.scoring``
  and ``models.committee`` family caches and attributed per dispatch by
  the fleet scheduler; the cost feed the SLO planner's cost-aware-edges
  follow-on needs.
- :mod:`obs.status` — atomic-rename per-host ``status_<h>.json``
  snapshots (torn-read tolerant by construction) that ``cetpu-top``
  renders into a live fleet view.
- :mod:`obs.alerts` — pure-function SLO burn-rate watchers over existing
  planner/queue/breaker/lease telemetry, surfaced as edge-triggered
  schema-registered ``alert`` events.
"""

from consensus_entropy_tpu.obs.metrics import (  # noqa: F401
    SCHEMA_VERSION,
    Counter,
    EventWriter,
    Gauge,
    Histogram,
    MetricsRegistry,
    QuantileSketch,
    RollingStat,
    StepTimer,
)
from consensus_entropy_tpu.obs.trace import (  # noqa: F401
    NULL_TRACER,
    SpanContext,
    Tracer,
)
