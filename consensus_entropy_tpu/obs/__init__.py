"""Unified observability: span tracing, metrics registry, export.

One subsystem for the telemetry the serving stack grew piecemeal
(``RollingStat``/``StepTimer`` in ``utils.profiling``, hand-rolled
dispatch records in ``fleet.scheduler``, per-host ``fleet_metrics.jsonl``
files nobody merged):

- :mod:`obs.metrics` — counters/gauges/log-bucketed histograms behind a
  named registry, the ``StepTimer``/``RollingStat`` primitives (moved
  here; ``utils.profiling`` keeps thin aliases), and the single
  schema-tagged JSONL event writer every metrics stream goes through.
- :mod:`obs.trace` — a :class:`~obs.trace.Tracer` with explicit span
  contexts (``run → user → al_iter → {score_dispatch, host_step,
  retrain, checkpoint, admission_wait}``); trace/span ids derive
  deterministically from ``(run_id, user, iteration)`` so a resumed or
  failed-over user CONTINUES its trace instead of starting a new one.
- :mod:`obs.export` — torn-tail-tolerant readers, schema-v2 validation,
  the multi-host spans+metrics merge, Chrome trace-event export
  (Perfetto-loadable, one lane per host/worker/bucket) and the text
  report behind ``python -m consensus_entropy_tpu.cli.report``.
"""

from consensus_entropy_tpu.obs.metrics import (  # noqa: F401
    SCHEMA_VERSION,
    Counter,
    EventWriter,
    Gauge,
    Histogram,
    MetricsRegistry,
    QuantileSketch,
    RollingStat,
    StepTimer,
)
from consensus_entropy_tpu.obs.trace import (  # noqa: F401
    NULL_TRACER,
    SpanContext,
    Tracer,
)
