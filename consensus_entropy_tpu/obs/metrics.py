"""Metrics primitives + the named registry + the one JSONL event writer.

Everything here is pure host code (no jax): importable from CLI tooling,
report scripts and fabric workers alike.

- :class:`StepTimer` / :class:`RollingStat` moved here verbatim from
  ``utils.profiling`` (which keeps thin aliases so existing imports and
  ``tests/test_profiling.py`` stay valid).
- :class:`Counter` / :class:`Gauge` / :class:`Histogram` are the new
  registry metrics.  The histogram is LOG-bucketed for bounded state on
  unbounded streams, but keeps an exact sample reservoir up to
  ``max_samples`` — while the reservoir holds, ``percentile`` is exact
  (numpy ``linear`` interpolation, pinned against numpy in
  ``tests/test_obs.py``); past it, percentiles fall back to bucket upper
  edges (conservative for latency reporting, flagged by ``exact=False``
  in the snapshot).
- :class:`MetricsRegistry` name-keys metric instances so the serving
  stack's telemetry is declared in one place and snapshots as one dict.
- :class:`EventWriter` is the single writer every ``fleet_metrics.jsonl``
  line now goes through: thread-safe, line-buffered (flush per record,
  no fsync — telemetry, not a WAL; readers tolerate a torn tail, see
  ``obs.export.read_jsonl_tolerant``), and tags each record with
  ``schema: 2``.
"""

from __future__ import annotations

import contextlib
import json
import math
import os
import threading
import time

#: the fleet_metrics.jsonl / spans.jsonl line-format version.  v1 was the
#: untagged PR 2-8 stream; v2 adds the tag itself, the admission→finish
#: latency histogram in summaries, and the span records (see README
#: "Observability" for the event table).
SCHEMA_VERSION = 2


def ema(prev: float | None, x: float, alpha: float = 0.3) -> float:
    """One exponential-moving-average step, ``None``-seeded: the shared
    smoothing kernel behind the serving stack's telemetry predictors
    (the planner's inter-arrival and host-step EMAs, the fabric's
    finish-interval EMA) — one alpha, one spelling."""
    return x if prev is None else alpha * x + (1.0 - alpha) * prev


class StepTimer:
    """Accumulates named phase durations; one JSONL record per flush.

    Usage::

        timer = StepTimer(path)           # or StepTimer(None): in-memory
        with timer.phase("score"):
            ...
        timer.flush(epoch=3)              # writes {"epoch": 3, "score_s": ...}
    """

    def __init__(self, jsonl_path: str | None = None):
        self.jsonl_path = jsonl_path
        self._acc: dict[str, float] = {}
        self.records: list[dict] = []

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._acc[name] = (self._acc.get(name, 0.0)
                               + time.perf_counter() - t0)

    def add(self, name: str, seconds: float) -> None:
        """Accumulate an externally measured duration into the current
        record (e.g. a background thread's self-timed work — such phases
        OVERLAP the foreground ones and must not be summed into iteration
        wall-clock)."""
        self._acc[name] = self._acc.get(name, 0.0) + seconds

    def flush(self, **labels) -> dict:
        """Close the current record: labels + ``{phase}_s`` durations."""
        rec = dict(labels)
        rec.update({f"{k}_s": round(v, 6) for k, v in self._acc.items()})
        self._acc = {}
        self.records.append(rec)
        if self.jsonl_path:
            with open(self.jsonl_path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        return rec


class RollingStat:
    """Streaming count/mean/min/max/last aggregator for unbounded event
    streams (serve-layer queue depth, admission wait): a long-running
    admission service cannot keep every sample the way :class:`StepTimer`
    keeps per-iteration records, so this folds each observation into O(1)
    state and snapshots to a compact dict for the metrics stream."""

    __slots__ = ("n", "total", "min", "max", "last")

    def __init__(self):
        self.n = 0
        self.total = 0.0
        self.min = None
        self.max = None
        self.last = None

    def add(self, value: float) -> None:
        v = float(value)
        self.n += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        self.last = v

    @property
    def mean(self) -> float | None:
        return self.total / self.n if self.n else None

    def snapshot(self, ndigits: int = 4) -> dict | None:
        """``{"n", "mean", "min", "max", "last"}``, or ``None`` before the
        first observation (absent beats a row of nulls in JSONL)."""
        if not self.n:
            return None
        return {"n": self.n, "mean": round(self.mean, ndigits),
                "min": round(self.min, ndigits),
                "max": round(self.max, ndigits),
                "last": round(self.last, ndigits)}


class Counter:
    """Monotonic event count."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def snapshot(self):
        return self.value


class Gauge:
    """Last-observed value (queue depth, live sessions)."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = None

    def set(self, v: float) -> None:
        self.value = v

    def snapshot(self):
        return self.value


class Histogram:
    """Log-bucketed histogram with an exact reservoir (see module doc).

    ``growth``: geometric bucket ratio (default ``2**0.25`` — 4 buckets
    per doubling, <= 19% worst-case edge error past the reservoir).
    ``max_samples``: exact-percentile reservoir bound; the log buckets
    keep accumulating forever either way, so the fallback path loses
    resolution, never observations."""

    def __init__(self, *, growth: float = 2 ** 0.25,
                 max_samples: int = 4096):
        if growth <= 1.0:
            raise ValueError(f"growth must be > 1, got {growth}")
        self.growth = growth
        self.max_samples = max_samples
        self.n = 0
        self.total = 0.0
        self.min: float | None = None
        self.max: float | None = None
        self._log_g = math.log(growth)
        self._buckets: dict[int, int] = {}
        self._samples: list[float] | None = []

    #: bucket index for values <= 0 (latencies shouldn't produce them,
    #: but a clock hiccup must not crash the metrics path)
    _NONPOS = -(10 ** 9)

    def _index(self, v: float) -> int:
        if v <= 0.0:
            return self._NONPOS
        return math.floor(math.log(v) / self._log_g + 1e-9)

    def add(self, value: float) -> None:
        v = float(value)
        self.n += 1
        self.total += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        i = self._index(v)
        self._buckets[i] = self._buckets.get(i, 0) + 1
        if self._samples is not None:
            if len(self._samples) < self.max_samples:
                self._samples.append(v)
            else:
                self._samples = None  # reservoir spent: buckets only

    @property
    def exact(self) -> bool:
        """True while every observation is still in the reservoir."""
        return self._samples is not None

    @property
    def mean(self) -> float | None:
        return self.total / self.n if self.n else None

    def percentile(self, q: float) -> float | None:
        """The ``q``-th percentile (0..100).  Exact (numpy ``linear``
        interpolation) while the reservoir holds; otherwise the upper
        edge of the log bucket containing the rank — an upper bound on
        the true quantile, the conservative direction for latency SLOs.
        """
        if not self.n:
            return None
        if self._samples is not None:
            s = sorted(self._samples)
            rank = (q / 100.0) * (len(s) - 1)
            lo = math.floor(rank)
            hi = math.ceil(rank)
            frac = rank - lo
            # numpy's "linear" lerp, branch included (t >= 0.5 computes
            # from the upper point), so the result is BIT-identical to
            # np.percentile — pinned in tests/test_obs.py
            diff = s[hi] - s[lo]
            if frac >= 0.5:
                return s[hi] - diff * (1.0 - frac)
            return s[lo] + diff * frac
        rank = math.ceil((q / 100.0) * self.n)
        cum = 0
        for i in sorted(self._buckets):
            cum += self._buckets[i]
            if cum >= max(rank, 1):
                if i == self._NONPOS:
                    return float(self.min)
                return min(self.growth ** (i + 1), float(self.max))
        return float(self.max)

    def snapshot(self, ndigits: int = 4) -> dict | None:
        """Compact summary for the metrics stream: ``n``/``mean``/``min``/
        ``max`` plus p50/p95/p99 (``None`` before the first observation).
        ``exact`` is flagged only when False — the common in-reservoir
        case stays byte-lean."""
        if not self.n:
            return None
        out = {"n": self.n, "mean": round(self.mean, ndigits),
               "min": round(self.min, ndigits),
               "max": round(self.max, ndigits),
               "p50": round(self.percentile(50), ndigits),
               "p95": round(self.percentile(95), ndigits),
               "p99": round(self.percentile(99), ndigits)}
        if not self.exact:
            out["exact"] = False
        return out


class QuantileSketch(Histogram):
    """A MERGEABLE :class:`Histogram`: the SLO admission planner's view of
    the enqueue-time pool-size distribution (``serve.planner``).

    Same accounting as the parent — numpy-exact percentiles while the
    reservoir holds (``n <= max_samples``), log-bucket upper edges after —
    plus the two capabilities the planner needs:

    - :meth:`merge` folds another sketch in (fabric hosts each sketch
      their own admission stream; a merged view is one ``merge`` chain).
      Merging is ASSOCIATIVE: bucket counts add, and the exact reservoir
      survives iff the combined count still fits the bound — a decision
      that depends only on the total, not the merge order (pinned in
      ``tests/test_slo.py``).
    - :meth:`to_dict` / :meth:`from_dict` round-trip the full state, so
      the admission journal's planner records can carry the sketch and a
      restarted server re-derives IDENTICAL bucket edges from replay.
    """

    def merge(self, other: "QuantileSketch") -> "QuantileSketch":
        if (other.growth != self.growth
                or other.max_samples != self.max_samples):
            raise ValueError("cannot merge sketches with different "
                             "growth/max_samples geometry")
        if not other.n:
            return self
        self.n += other.n
        self.total += other.total
        self.min = other.min if self.min is None \
            else min(self.min, other.min)
        self.max = other.max if self.max is None \
            else max(self.max, other.max)
        for i, c in other._buckets.items():
            self._buckets[i] = self._buckets.get(i, 0) + c
        if (self._samples is not None and other._samples is not None
                and self.n <= self.max_samples):
            self._samples = self._samples + other._samples
        else:
            self._samples = None  # combined stream past the exact bound
        return self

    def to_dict(self) -> dict:
        return {"growth": self.growth, "max_samples": self.max_samples,
                "n": self.n, "total": self.total, "min": self.min,
                "max": self.max,
                "buckets": {str(i): c for i, c in self._buckets.items()},
                "samples": (list(self._samples)
                            if self._samples is not None else None)}

    @classmethod
    def merge_all(cls, sketches) -> "QuantileSketch":
        """Fold an iterable of sketch DICTS (the journaled wire form —
        per-host planner records) into one fresh sketch.  Associativity
        makes the fold order irrelevant; the fabric coordinator's fleet
        planner feeds this sorted by host id so the chain is canonical
        anyway."""
        out = None
        for d in sketches:
            sk = cls.from_dict(d)
            out = sk if out is None else out.merge(sk)
        return out if out is not None else cls()

    @classmethod
    def from_dict(cls, d: dict) -> "QuantileSketch":
        sk = cls(growth=float(d.get("growth", 2 ** 0.25)),
                 max_samples=int(d.get("max_samples", 4096)))
        sk.n = int(d.get("n", 0))
        sk.total = float(d.get("total", 0.0))
        sk.min = d.get("min")
        sk.max = d.get("max")
        sk._buckets = {int(i): int(c)
                       for i, c in (d.get("buckets") or {}).items()}
        samples = d.get("samples")
        sk._samples = [float(v) for v in samples] \
            if samples is not None else None
        return sk


class MetricsRegistry:
    """Name-keyed metric instances; get-or-create, type-checked.

    One registry per report/driver — the names are the declaration
    surface (``registry.snapshot()`` is the whole telemetry state), and
    getting an existing name with a different kind fails loudly instead
    of silently forking the stream."""

    def __init__(self):
        self._metrics: dict[str, object] = {}

    def _get(self, name: str, cls, **kw):
        m = self._metrics.get(name)
        if m is None:
            m = cls(**kw)
            self._metrics[name] = m
        elif type(m) is not cls:
            raise TypeError(f"metric {name!r} is {type(m).__name__}, "
                            f"not {cls.__name__}")
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, **kw) -> Histogram:
        return self._get(name, Histogram, **kw)

    def rolling(self, name: str) -> RollingStat:
        return self._get(name, RollingStat)

    def snapshot(self) -> dict:
        return {name: m.snapshot() for name, m in
                sorted(self._metrics.items())}


class EventWriter:
    """The one JSONL event writer (thread-safe, schema-tagged).

    ``path=None`` keeps the interface with no I/O.  The handle opens
    lazily and stays open (flush per record, NO fsync: this is telemetry
    — a torn tail after SIGKILL is an expected artifact the readers skip,
    ``obs.export.read_jsonl_tolerant``)."""

    def __init__(self, path: str | None, schema: int = SCHEMA_VERSION):
        self.path = path
        self.schema = schema
        self._f = None
        self._lock = threading.Lock()
        if path:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)

    def emit(self, rec: dict) -> dict:
        """Write one record (``schema`` prepended unless already present);
        returns the record as written."""
        if "schema" not in rec:
            rec = {"schema": self.schema, **rec}
        if self.path is not None:
            line = (json.dumps(rec) + "\n").encode("utf-8")
            with self._lock:
                if self._f is None:
                    self._f = open(self.path, "ab")
                self._f.write(line)
                self._f.flush()
        return rec

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None
