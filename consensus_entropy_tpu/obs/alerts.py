"""SLO burn-rate alerts: pure-function watchers over telemetry the
stack already records.

The serving stack KNOWS when it is in trouble — the planner tracks
per-class SLO headroom, the queue knows how long its batch head has
aged, the breaker knows which widths are degraded, the coordinator knows
which leases are about to expire — but until this module nothing TOLD
anyone: an operator discovered a breaker-open bucket by reading
``fleet_metrics.jsonl`` after the run.  Each kernel here is a pure
function of observed telemetry (injected ``now``, unit-testable to the
boundary), and :class:`AlertWatcher` edge-triggers the schema-registered
``alert`` event (``obs.export.EVENT_FIELDS``) when an alert RISES —
re-evaluations while it stays active are silent, so a wedged fleet
doesn't flood its own metrics stream.

Alerts change WHEN operators look, never results: nothing journaled or
replayed reads an alert, and ``--no-introspection`` removes the watcher
wholesale (the PR 14 arm — bit-exact parity pinned by the obs bench).

Alert kinds (the README "Observability" table renders these):

- ``slo_headroom`` — a priority class's p95 admission→finish latency has
  burned past ``burn_frac`` of its SLO target: the tail is about to
  breach, before it actually does.
- ``batch_aging`` — the queue's batch-class head has waited past the
  aging bound: strict priority is starving throughput work and the aging
  guard is doing real work.
- ``breaker_open`` — a bucket width is degraded to per-user dispatch
  (open or spent breaker): stacked throughput is gone on that width.
- ``lease_expiry`` — a worker's lease age has burned past ``burn_frac``
  of the lease: the host is about to be declared dead and failed over.
- ``placement_skew`` — a live host's unresolved load sits more than
  ``max_skew`` above the fleet's floor: the placement invariant is being
  violated by attrition or degradation, and the remediation plane's
  drain-for-rebalance (``serve.remedy``) is the journaled response.
- ``gray_suspect`` — a host is SLOW relative to its peers without being
  dead: one or more gray signals (journal-append age, feed-ack lag,
  lease-age skew, step-wall EMA) sit at ``gray_ratio`` times the peer
  median AND past an absolute floor.  Peer-RELATIVE on purpose: a
  constant threshold either fires on every cold start or sleeps through
  a 10x-slow host on a fast fleet.  The coordinator's gray ladder
  (``serve.remedy``) is the journaled response.

Alerts can also ROUTE: :class:`AlertWatcher` takes a tuple of SINKS
(:class:`ConsoleSink` — operator log line, :class:`JsonlSink` —
append-only ``alerts.jsonl`` for ``tail -f``, :class:`CommandSink` —
webhook-shaped command invocation per alert; build from a CLI spec with
:func:`make_sink`), each fed every RISEN alert.  Sinks are telemetry
delivery, never control flow: a raising sink is counted
(``sink_errors``) and skipped, and no journaled decision reads one.
"""

from __future__ import annotations

ALERT_KINDS = ("slo_headroom", "batch_aging", "breaker_open",
               "lease_expiry", "placement_skew", "gray_suspect")

#: default fraction of a bound an observation may burn before alerting
BURN_FRAC = 0.8

#: gray-failure outlier gates: a host is suspect when its signal is at
#: least ``GRAY_RATIO`` times the PEER MEDIAN (the median of the OTHER
#: hosts — a fleet-wide slowdown is load, not a gray failure) AND at
#: least ``GRAY_MIN_ABS_S`` in absolute terms (ratio alone would flag
#: microsecond noise on an idle fleet)
GRAY_RATIO = 3.0
GRAY_MIN_ABS_S = 1.0


def slo_headroom_alerts(per_class_p95: dict, slo_s: dict, *,
                        burn_frac: float = BURN_FRAC) -> list[dict]:
    """``per_class_p95``: observed p95 admission→finish latency per
    priority class; ``slo_s``: the per-class targets.  Fires per class
    whose p95 burned past ``burn_frac`` of its target."""
    out = []
    for cls in sorted(per_class_p95):
        p95, target = per_class_p95[cls], slo_s.get(cls)
        if p95 is None or not target or target <= 0:
            continue
        if p95 >= burn_frac * target:
            out.append({"kind": "slo_headroom", "key": cls, "cls": cls,
                        "p95_s": round(float(p95), 4),
                        "slo_s": float(target),
                        "burn": round(float(p95) / target, 4)})
    return out


def batch_aging_alerts(head_waits: dict, aging_s: float) -> list[dict]:
    """``head_waits``: seconds each non-empty queue class's head entry
    has waited (``AdmissionQueue.head_waits``).  Fires per non-top class
    whose head aged past the bound (aging 0 = guard off, never fires)."""
    if not aging_s or aging_s <= 0:
        return []
    out = []
    for cls in sorted(head_waits):
        if cls == "interactive":
            continue  # the top class never ages past itself
        wait = head_waits[cls]
        if wait is not None and wait >= aging_s:
            out.append({"kind": "batch_aging", "key": cls, "cls": cls,
                        "head_wait_s": round(float(wait), 4),
                        "aging_s": float(aging_s)})
    return out


def breaker_alerts(breaker_states: dict | None) -> list[dict]:
    """``breaker_states``: ``{width: state}`` from
    ``DispatchBreaker.summary`` — which also lists CLOSED widths that
    merely have recent failures, so closed entries are skipped here:
    only a width actually degraded to per-user dispatch (open /
    half_open probing / given up) alerts."""
    out = []
    for width, state in sorted((breaker_states or {}).items()):
        if str(state) == "closed":
            continue  # failures counted, but stacked dispatch intact
        out.append({"kind": "breaker_open", "key": str(width),
                    "width": int(width), "state": str(state)})
    return out


def lease_alerts(lease_ages: dict, lease_s: float, *,
                 burn_frac: float = BURN_FRAC) -> list[dict]:
    """``lease_ages``: seconds since each live host's last heartbeat
    (``None`` = never beat yet, not alertable — spawn grace owns that).
    Fires per host whose age burned past ``burn_frac`` of the lease."""
    if not lease_s or lease_s <= 0:
        return []
    out = []
    for host in sorted(lease_ages):
        age = lease_ages[host]
        if age is not None and age >= burn_frac * lease_s:
            out.append({"kind": "lease_expiry", "key": str(host),
                        "host": str(host),
                        "age_s": round(float(age), 4),
                        "lease_s": float(lease_s)})
    return out


def skew_alerts(loads: dict, *, max_skew: int) -> list[dict]:
    """``loads``: unresolved-user count per live, non-draining host
    (journal-replayed — the same view ``serve.placement`` places by).
    Fires per host whose load sits MORE than ``max_skew`` above the
    fleet's floor (the least-loaded host) — the exact complement of the
    placement rule, which only admits onto hosts within the skew bound,
    so a firing alert means attrition or degradation broke an invariant
    placement alone cannot restore.  A one-host fleet has no skew."""
    if len(loads) < 2:
        return []
    floor = min(loads.values())
    out = []
    for host in sorted(loads):
        load = loads[host]
        if load - floor > max_skew:
            out.append({"kind": "placement_skew", "key": str(host),
                        "host": str(host), "load": int(load),
                        "floor": int(floor), "max_skew": int(max_skew)})
    return out


def _median(vals: list) -> float:
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return float(s[mid]) if n % 2 else (s[mid - 1] + s[mid]) / 2.0


def _gray_outliers(values: dict, *, ratio: float,
                   min_abs_s: float) -> list[tuple]:
    """The peer-relative outlier kernel shared by every gray signal:
    ``values`` maps host -> observed seconds (``None`` = no observation,
    excluded from both sides).  For each host the PEER baseline is the
    median of the OTHER hosts' values — excluding self, so one sick host
    cannot drag the baseline toward itself on a small fleet.  Fires
    ``(host, value, peer_median)`` when the value clears BOTH gates (see
    ``GRAY_RATIO`` / ``GRAY_MIN_ABS_S``) and strictly exceeds its peers
    (a fleet that is uniformly slow is load, not gray).  Fewer than two
    observed hosts → no peers → no outliers."""
    obs = {h: float(v) for h, v in values.items() if v is not None}
    if len(obs) < 2:
        return []
    out = []
    for host in sorted(obs):
        peers = [v for h, v in obs.items() if h != host]
        peer = _median(peers)
        v = obs[host]
        if v >= min_abs_s and v >= ratio * max(peer, 0.0) and v > peer:
            out.append((host, v, peer))
    return out


def gray_suspect_alerts(*, append_ages: dict | None = None,
                        ack_lags: dict | None = None,
                        lease_ages: dict | None = None,
                        step_walls: dict | None = None,
                        ratio: float = GRAY_RATIO,
                        min_abs_s: float = GRAY_MIN_ABS_S) -> list[dict]:
    """The gray-failure detector: four peer-relative signals, one alert
    per suspect host with the evidence attached.

    - ``append_ages``: seconds since each LOADED host's event journal
      last grew (an idle host legitimately appends nothing — callers
      must pass only hosts with unresolved users).
    - ``ack_lags``: age of each host's oldest unacked fence/drop
      (``0.0`` — not ``None`` — for hosts with nothing pending, so only
      a genuinely lagging host skews against its peers).
    - ``lease_ages``: seconds since each host's last heartbeat (the
      same view ``lease_alerts`` reads — gray catches the host whose
      beats land LATE but never late enough to expire the lease).
    - ``step_walls``: each host's self-advertised dispatch step-wall
      EMA (``step_ema_s`` on its lease record).

    Each signal runs :func:`_gray_outliers` independently; a host
    flagged by ANY signal gets one ``gray_suspect`` alert listing every
    firing signal plus its value/peer pair — the evidence the ladder
    journals and the operator reads."""
    signals = (("append_age", append_ages), ("ack_lag", ack_lags),
               ("lease_age", lease_ages), ("step_wall", step_walls))
    by_host: dict[str, dict] = {}
    for name, values in signals:
        if not values:
            continue
        for host, v, peer in _gray_outliers(values, ratio=ratio,
                                            min_abs_s=min_abs_s):
            alert = by_host.setdefault(
                str(host), {"kind": "gray_suspect", "key": str(host),
                            "host": str(host), "signals": []})
            alert["signals"].append(name)
            alert[f"{name}_s"] = round(float(v), 4)
            alert[f"{name}_peer_s"] = round(float(peer), 4)
    return [by_host[h] for h in sorted(by_host)]


class ConsoleSink:
    """Operator console delivery: one human log line per risen alert.
    ``write`` defaults to ``print`` (the CLI passes its own logger)."""

    def __init__(self, write=None):
        self._write = write if write is not None else print

    def emit(self, alert: dict) -> None:
        detail = " ".join(f"{k}={v}" for k, v in sorted(alert.items())
                          if k not in ("kind", "key"))
        self._write(f"ALERT [{alert.get('kind')}] {detail}")


class JsonlSink:
    """Append-only JSONL alert log (the ``tail -f`` surface): one JSON
    line per risen alert, flushed per emit so a follower sees it
    promptly.  Telemetry, not a ledger — no fsync, no lock."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def emit(self, alert: dict) -> None:
        import json
        import os

        if self._f is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._f = open(self.path, "ab")
        self._f.write((json.dumps(alert) + "\n").encode("utf-8"))
        self._f.flush()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class CommandSink:
    """Webhook-shaped delivery without a network dependency: run
    ``argv + [json-encoded alert]`` per risen alert (a curl wrapper, a
    pager script, a chat-post hook).  Bounded by ``timeout_s`` and
    fire-and-forget — a failing or hanging command is the WATCHER's
    problem to count, never the serve loop's to wait on."""

    def __init__(self, argv: list, *, timeout_s: float = 5.0):
        if not argv:
            raise ValueError("CommandSink needs a non-empty argv")
        self.argv = [str(a) for a in argv]
        self.timeout_s = timeout_s

    def emit(self, alert: dict) -> None:
        import json
        import subprocess

        subprocess.run(self.argv + [json.dumps(alert)],
                       check=True, timeout=self.timeout_s,
                       stdout=subprocess.DEVNULL,
                       stderr=subprocess.DEVNULL)


def make_sink(spec: str, *, log=None):
    """Build one sink from its CLI spec (``--alert-sink``, repeatable):
    ``console`` | ``jsonl:<path>`` | ``cmd:<shell-words>``.  Unknown
    kinds and missing arguments fail HERE at construction (the
    validate-at-the-edge precedent), not as a silently-dropped alert."""
    kind, _, arg = str(spec).partition(":")
    if kind == "console":
        return ConsoleSink(log)
    if kind == "jsonl":
        if not arg:
            raise ValueError("jsonl sink needs a path: jsonl:<path>")
        return JsonlSink(arg)
    if kind == "cmd":
        if not arg:
            raise ValueError("cmd sink needs a command: cmd:<command>")
        import shlex

        return CommandSink(shlex.split(arg))
    raise ValueError(f"unknown alert sink {spec!r} "
                     "(choose console | jsonl:<path> | cmd:<command>)")


class AlertWatcher:
    """Edge-triggered alert surface: :meth:`update` takes the round's
    full evaluated alert list, emits a schema ``alert`` event (plus an
    operator log line via ``log``) for each NEWLY-risen ``(kind, key)``,
    and keeps the active set for snapshots.  An alert that stops holding
    simply leaves the active set — re-rising re-emits.

    ``sinks``: delivery fan-out (see :func:`make_sink`) — each risen
    alert goes to every sink; a raising sink increments ``sink_errors``
    and is skipped for that alert (delivery is telemetry, never control
    flow).

    Edge-triggering is SNAPSHOT-based, so a condition that clears and
    re-rises BETWEEN two :meth:`update` calls looks continuously active
    and the second rise would be silently coalesced into the first.
    Whoever CLEARS a condition mid-interval (the remediation plane,
    after acting on an alert) must call :meth:`rearm` so the next
    evaluation re-fires if the condition still — or again — holds."""

    def __init__(self, report=None, *, log=None, sinks=()):
        self.report = report
        self.log = log
        self.sinks = tuple(sinks)
        self.fired = 0
        self.sink_errors = 0
        #: (kind, key) -> the alert dict, as currently active
        self._active: dict[tuple, dict] = {}

    def update(self, alerts: list[dict]) -> list[dict]:
        """Fold one evaluation round; returns the alerts that ROSE."""
        now_keys = set()
        rose = []
        for alert in alerts:
            key = (alert.get("kind"), alert.get("key"))
            now_keys.add(key)
            if key not in self._active:
                rose.append(alert)
            self._active[key] = alert
        for key in list(self._active):
            if key not in now_keys:
                del self._active[key]
        for alert in rose:
            self.fired += 1
            if self.report is not None:
                fields = {k: v for k, v in alert.items() if k != "key"}
                self.report.event("alert", **fields)
            if self.log is not None:
                detail = " ".join(f"{k}={v}" for k, v in
                                  sorted(alert.items())
                                  if k not in ("kind", "key"))
                self.log(f"ALERT [{alert.get('kind')}] {detail}")
            for sink in self.sinks:
                try:
                    sink.emit(alert)
                except Exception:
                    # a broken pager script must never wedge the serve
                    # loop — count it and keep the round going
                    self.sink_errors += 1
        return rose

    def rearm(self, kind: str, key=None) -> None:
        """Drop ``(kind, key)`` — or every key of ``kind`` when ``key``
        is ``None`` — from the active set, so the NEXT evaluation round
        re-emits the alert if its condition still (or again) holds.

        The edge-trigger REARM (this PR's watcher bugfix): a remediation
        that clears a condition mid-poll-interval would otherwise leave
        the stale entry active, and a re-risen condition inside the same
        interval would be coalesced into the original edge — the second
        ``alert`` event never fired.  Acting on an alert consumes it."""
        if key is None:
            for k in list(self._active):
                if k[0] == kind:
                    del self._active[k]
        else:
            self._active.pop((kind, key), None)

    @property
    def active(self) -> list[dict]:
        """The currently-active alerts (snapshot surface), stable
        order."""
        return [self._active[k] for k in sorted(self._active,
                                                key=lambda kv: (str(kv[0]),
                                                                str(kv[1])))]
