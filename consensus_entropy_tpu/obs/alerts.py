"""SLO burn-rate alerts: pure-function watchers over telemetry the
stack already records.

The serving stack KNOWS when it is in trouble — the planner tracks
per-class SLO headroom, the queue knows how long its batch head has
aged, the breaker knows which widths are degraded, the coordinator knows
which leases are about to expire — but until this module nothing TOLD
anyone: an operator discovered a breaker-open bucket by reading
``fleet_metrics.jsonl`` after the run.  Each kernel here is a pure
function of observed telemetry (injected ``now``, unit-testable to the
boundary), and :class:`AlertWatcher` edge-triggers the schema-registered
``alert`` event (``obs.export.EVENT_FIELDS``) when an alert RISES —
re-evaluations while it stays active are silent, so a wedged fleet
doesn't flood its own metrics stream.

Alerts change WHEN operators look, never results: nothing journaled or
replayed reads an alert, and ``--no-introspection`` removes the watcher
wholesale (the PR 14 arm — bit-exact parity pinned by the obs bench).

Alert kinds (the README "Observability" table renders these):

- ``slo_headroom`` — a priority class's p95 admission→finish latency has
  burned past ``burn_frac`` of its SLO target: the tail is about to
  breach, before it actually does.
- ``batch_aging`` — the queue's batch-class head has waited past the
  aging bound: strict priority is starving throughput work and the aging
  guard is doing real work.
- ``breaker_open`` — a bucket width is degraded to per-user dispatch
  (open or spent breaker): stacked throughput is gone on that width.
- ``lease_expiry`` — a worker's lease age has burned past ``burn_frac``
  of the lease: the host is about to be declared dead and failed over.
"""

from __future__ import annotations

ALERT_KINDS = ("slo_headroom", "batch_aging", "breaker_open",
               "lease_expiry")

#: default fraction of a bound an observation may burn before alerting
BURN_FRAC = 0.8


def slo_headroom_alerts(per_class_p95: dict, slo_s: dict, *,
                        burn_frac: float = BURN_FRAC) -> list[dict]:
    """``per_class_p95``: observed p95 admission→finish latency per
    priority class; ``slo_s``: the per-class targets.  Fires per class
    whose p95 burned past ``burn_frac`` of its target."""
    out = []
    for cls in sorted(per_class_p95):
        p95, target = per_class_p95[cls], slo_s.get(cls)
        if p95 is None or not target or target <= 0:
            continue
        if p95 >= burn_frac * target:
            out.append({"kind": "slo_headroom", "key": cls, "cls": cls,
                        "p95_s": round(float(p95), 4),
                        "slo_s": float(target),
                        "burn": round(float(p95) / target, 4)})
    return out


def batch_aging_alerts(head_waits: dict, aging_s: float) -> list[dict]:
    """``head_waits``: seconds each non-empty queue class's head entry
    has waited (``AdmissionQueue.head_waits``).  Fires per non-top class
    whose head aged past the bound (aging 0 = guard off, never fires)."""
    if not aging_s or aging_s <= 0:
        return []
    out = []
    for cls in sorted(head_waits):
        if cls == "interactive":
            continue  # the top class never ages past itself
        wait = head_waits[cls]
        if wait is not None and wait >= aging_s:
            out.append({"kind": "batch_aging", "key": cls, "cls": cls,
                        "head_wait_s": round(float(wait), 4),
                        "aging_s": float(aging_s)})
    return out


def breaker_alerts(breaker_states: dict | None) -> list[dict]:
    """``breaker_states``: ``{width: state}`` from
    ``DispatchBreaker.summary`` — which also lists CLOSED widths that
    merely have recent failures, so closed entries are skipped here:
    only a width actually degraded to per-user dispatch (open /
    half_open probing / given up) alerts."""
    out = []
    for width, state in sorted((breaker_states or {}).items()):
        if str(state) == "closed":
            continue  # failures counted, but stacked dispatch intact
        out.append({"kind": "breaker_open", "key": str(width),
                    "width": int(width), "state": str(state)})
    return out


def lease_alerts(lease_ages: dict, lease_s: float, *,
                 burn_frac: float = BURN_FRAC) -> list[dict]:
    """``lease_ages``: seconds since each live host's last heartbeat
    (``None`` = never beat yet, not alertable — spawn grace owns that).
    Fires per host whose age burned past ``burn_frac`` of the lease."""
    if not lease_s or lease_s <= 0:
        return []
    out = []
    for host in sorted(lease_ages):
        age = lease_ages[host]
        if age is not None and age >= burn_frac * lease_s:
            out.append({"kind": "lease_expiry", "key": str(host),
                        "host": str(host),
                        "age_s": round(float(age), 4),
                        "lease_s": float(lease_s)})
    return out


class AlertWatcher:
    """Edge-triggered alert surface: :meth:`update` takes the round's
    full evaluated alert list, emits a schema ``alert`` event (plus an
    operator log line via ``log``) for each NEWLY-risen ``(kind, key)``,
    and keeps the active set for snapshots.  An alert that stops holding
    simply leaves the active set — re-rising re-emits."""

    def __init__(self, report=None, *, log=None):
        self.report = report
        self.log = log
        self.fired = 0
        #: (kind, key) -> the alert dict, as currently active
        self._active: dict[tuple, dict] = {}

    def update(self, alerts: list[dict]) -> list[dict]:
        """Fold one evaluation round; returns the alerts that ROSE."""
        now_keys = set()
        rose = []
        for alert in alerts:
            key = (alert.get("kind"), alert.get("key"))
            now_keys.add(key)
            if key not in self._active:
                rose.append(alert)
            self._active[key] = alert
        for key in list(self._active):
            if key not in now_keys:
                del self._active[key]
        for alert in rose:
            self.fired += 1
            if self.report is not None:
                fields = {k: v for k, v in alert.items() if k != "key"}
                self.report.event("alert", **fields)
            if self.log is not None:
                detail = " ".join(f"{k}={v}" for k, v in
                                  sorted(alert.items())
                                  if k not in ("kind", "key"))
                self.log(f"ALERT [{alert.get('kind')}] {detail}")
        return rose

    @property
    def active(self) -> list[dict]:
        """The currently-active alerts (snapshot surface), stable
        order."""
        return [self._active[k] for k in sorted(self._active,
                                                key=lambda kv: (str(kv[0]),
                                                                str(kv[1])))]
