"""Per-host live status snapshots: the introspection plane's "what is
the fleet doing RIGHT NOW" surface.

Every metrics stream this stack writes is append-only and post-hoc: an
operator can replay ``fleet_metrics.jsonl`` after the run, but cannot see
a live serve process's queue depths, bucket occupancy or drain state
without attaching a debugger.  This module closes that gap with the
cheapest possible mechanism — each worker (and the fabric coordinator)
periodically rewrites ONE small ``status_<host>.json`` via the
write-tmp-then-``os.replace`` discipline the lease heartbeats already
use, so a reader sees the previous snapshot or the current one, never a
torn file.  ``cetpu-top`` (``cli/top.py``) renders the snapshot
directory as a live fleet view.

Torn-read tolerance is layered anyway (:func:`read_status` returns
``None`` on any parse failure) because operators copy these files around
and network filesystems break rename atomicity; the reader must never
crash on a half-copied snapshot.

The writer takes an injected ``clock=`` seam (the same discipline as
every liveness surface — cetpu-lint's replay rules stay clean because
callers in ``serve/`` never read a wall clock themselves), and snapshots
are TELEMETRY: nothing journaled or replayed ever reads one back, so the
introspection plane cannot change results.
"""

from __future__ import annotations

import glob
import json
import os
import time

#: snapshot schema floor: every status file must carry these at these
#: kinds (the same str/int/float vocabulary as the event table)
STATUS_FIELDS = {"kind": "str", "host": "str", "t": "float",
                 "schema": "int"}

#: the snapshot-file schema version (independent of the event stream's)
STATUS_SCHEMA = 1


def status_path(status_dir: str, host: str) -> str:
    return os.path.join(status_dir, f"status_{host}.json")


class StatusWriter:
    """Atomic-rename snapshot writer for one host, rate-limited.

    ``interval_s``: minimum seconds between writes (:meth:`maybe_write`
    is called every loop round; most rounds return without I/O).
    ``clock``: the injected wall clock — snapshots cross processes, so
    wall time is the right axis, and the seam keeps callers clock-free.
    """

    def __init__(self, status_dir: str, host: str, *,
                 interval_s: float = 1.0, clock=time.time):
        if interval_s < 0:
            raise ValueError(f"interval_s must be >= 0, got {interval_s}")
        self.path = status_path(status_dir, host)
        self.host = host
        self.interval_s = interval_s
        self.writes = 0
        #: swallowed best-effort failures (see :meth:`maybe_write`)
        self.errors = 0
        self._clock = clock
        self._last_write: float | None = None

    def maybe_write(self, build) -> bool:
        """Write a fresh snapshot when the interval elapsed; ``build()``
        (a nullary callable returning the payload dict) only runs when a
        write actually happens, so idle rounds cost one clock read.

        BEST-EFFORT: any failure (disk full, network-FS rename error, a
        payload-builder bug) is swallowed and counted — the serve loop
        and the fabric coordinator call this inline, and the
        introspection plane must never take down the fleet it observes
        (:meth:`write` itself still raises, for callers that want the
        error)."""
        now = self._clock()
        if self._last_write is not None \
                and now - self._last_write < self.interval_s:
            return False
        try:
            self.write(build())
        except Exception:
            self.errors += 1
            self._last_write = now  # don't retry at poll rate
            return False
        return True

    def write(self, payload: dict) -> dict:
        """One snapshot: payload + the schema floor (kind/host/t) +
        this writer's ``interval_s`` (so a READER can judge staleness
        in units of the writer's own cadence — ``cetpu-top`` flags a
        snapshot older than a few write intervals without the operator
        re-deriving the fleet's ``--status-interval``), then tmp-write
        + ``os.replace`` so readers never see a torn file."""
        now = self._clock()
        snap = {"schema": STATUS_SCHEMA, "kind": "status",
                "host": self.host, "t": round(now, 3),
                "interval_s": self.interval_s, **payload}
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = f"{self.path}.tmp"
        with open(tmp, "wb") as f:
            f.write(json.dumps(snap).encode("utf-8"))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)
        self._last_write = now
        self.writes += 1
        return snap


def read_status(path: str) -> dict | None:
    """One snapshot, or ``None`` for missing/torn/non-dict files — the
    reader half of the torn-read tolerance contract (the atomic rename
    makes tears rare; copies and network filesystems make them
    possible)."""
    try:
        with open(path, "rb") as f:
            rec = json.loads(f.read().decode("utf-8"))
    except (OSError, ValueError, UnicodeDecodeError):
        return None
    return rec if isinstance(rec, dict) else None


def read_status_dir(status_dir: str) -> dict[str, dict]:
    """``{host: snapshot}`` over every readable ``status_*.json`` in the
    directory (unreadable ones skipped, per the tolerance contract)."""
    out: dict[str, dict] = {}
    for path in sorted(glob.glob(status_path(status_dir, "*"))):
        snap = read_status(path)
        if snap is None:
            continue
        base = os.path.basename(path)
        host = base[len("status_"):-len(".json")]
        out[snap.get("host") or host] = snap
    return out


class HistoryRing:
    """The last-N snapshots per host (ROADMAP introspection follow-on
    (d)): ``cetpu-top``'s watch loop pushes each poll's snapshots here
    and renders depth/occupancy DELTAS against the ring, so a soak is
    watchable as movement — queue draining or building, users
    finishing — not just absolute numbers.  Pure in-memory bookkeeping:
    snapshots are telemetry, nothing replayed reads them.

    A host's snapshot only enters the ring when its ``t`` advanced (the
    writer is rate-limited; re-reading an unchanged file must not
    flatten the deltas to zero)."""

    def __init__(self, depth: int = 60):
        if depth < 2:
            raise ValueError(f"depth must be >= 2, got {depth}")
        self.depth = depth
        self._ring: dict[str, list] = {}

    def push(self, snaps: dict) -> None:
        """Fold one ``read_status_dir`` result in (stale/unchanged
        snapshots — same ``t`` as the host's newest entry — are
        skipped)."""
        for host, snap in snaps.items():
            dq = self._ring.setdefault(host, [])
            if dq and dq[-1].get("t") == snap.get("t"):
                continue
            dq.append(snap)
            del dq[:-self.depth]

    def history(self, host: str) -> list:
        """Oldest → newest retained snapshots for one host."""
        return list(self._ring.get(host, ()))

    def deltas(self, host: str, fields: tuple) -> dict:
        """``{field: newest - oldest}`` over the retained window for
        the numeric ``fields`` present at both ends (missing or
        non-numeric at either end → field omitted), plus ``span_s`` —
        the window's wall span.  One entry in the ring → empty dict (no
        movement measurable yet)."""
        hist = self._ring.get(host, ())
        if len(hist) < 2:
            return {}
        lo, hi = hist[0], hist[-1]
        out = {}
        for f in fields:
            a, b = lo.get(f), hi.get(f)
            if isinstance(a, (int, float)) and not isinstance(a, bool) \
                    and isinstance(b, (int, float)) \
                    and not isinstance(b, bool):
                out[f] = b - a
        if out and isinstance(lo.get("t"), (int, float)) \
                and isinstance(hi.get("t"), (int, float)):
            out["span_s"] = round(hi["t"] - lo["t"], 3)
        return out


def validate_status(snap: dict) -> list[str]:
    """Schema-floor validation for one snapshot (``scripts/obs_check.sh``
    asserts this on MID-RUN snapshots); returns error strings, empty =
    valid."""
    from consensus_entropy_tpu.obs.export import FIELD_KINDS

    errors = []
    for field, kind in STATUS_FIELDS.items():
        if field not in snap:
            errors.append(f"status snapshot lacks {field!r}")
        elif not FIELD_KINDS[kind](snap[field]):
            errors.append(f"status field {field!r} must be {kind}, "
                          f"got {snap[field]!r}")
    if not errors and snap.get("kind") != "status":
        errors.append(f"kind must be 'status', got {snap.get('kind')!r}")
    alerts = snap.get("alerts")
    if alerts is not None and not (
            isinstance(alerts, list)
            and all(isinstance(a, dict) and isinstance(a.get("kind"), str)
                    for a in alerts)):
        errors.append("alerts must be a list of {kind: str, ...} dicts")
    return errors
