"""Jit-family compile telemetry: the process-wide view of what the
stack has compiled, is compiling, and keeps resident.

The serving stack's device work flows through a small set of PROCESS-WIDE
jit-family caches — ``ops.scoring.make_scoring_fns`` /
``make_fleet_scoring_fns`` / ``fleet_scoring_fns_for_width`` (one wrapper
family per (k, tie_break[, width])) and ``models.committee``'s per-config
infer programs — each of which owns jit objects whose per-shape
executables compile lazily at first dispatch.  Until this module nobody
RECORDED any of it: the SLO planner's cost-aware-edges follow-on (ROADMAP
SLO (a)) needs compile wall × resident executables per family to trade
padding waste against jit-cache pressure, and an operator watching a
serve process grow has no way to see which bucket geometry is paying.

Three feeds, all cheap:

- :func:`note_build` — called INSIDE each lru-cached family builder (runs
  exactly once per key per process): family registered, wrapper-build
  wall recorded, the family's jit objects kept for resident-executable
  counts (``_cache_size()``; gone executables decrement naturally).
- :func:`note_lookup` — called by the public cache wrappers on every
  lookup; ``hits = lookups - builds`` is the cache-pressure counter.
- :func:`dispatch_scope` — the scheduler wraps each device dispatch in
  this thread-local scope; a ``jax.monitoring`` backend-compile duration
  landing inside it is attributed to that (fn, width) family and fired to
  subscribers as a first-class ``compile`` event (schema-registered in
  ``obs.export.EVENT_FIELDS``).  Without ``jax.monitoring`` (older jax)
  the build/lookup feeds still flow — the listener install is best-effort.

Subscribers (``FleetScheduler`` forwards to its ``FleetReport``) receive
plain dicts shaped for ``report.event("compile", ...)``.  Everything here
is pure host bookkeeping behind one lock; no jax import happens at module
load (the monitoring hook imports lazily), so CLI tooling can import the
snapshot surface backend-free.
"""

from __future__ import annotations

import contextlib
import threading
import time

_LOCK = threading.RLock()
_FAMILIES: dict[tuple, dict] = {}
_LISTENERS: list = []
_SCOPE = threading.local()
_MONITOR = {"installed": False}

#: the family key XLA compile walls land in when no dispatch scope is
#: active (a compile triggered outside the scheduler's dispatch path)
_UNATTRIBUTED = ("unattributed", None, None)


def family_key(fn: str, width=None, n_devices=None) -> tuple:
    return (str(fn), width, n_devices)


def _new_family(key: tuple) -> dict:
    return {"fn": key[0], "width": key[1], "n_devices": key[2],
            "builds": 0, "lookups": 0, "build_s": 0.0,
            "compiles": 0, "compile_s": 0.0, "jit_fns": ()}


def subscribe(listener) -> None:
    """Register a listener for build/compile events (idempotent)."""
    with _LOCK:
        if listener not in _LISTENERS:
            _LISTENERS.append(listener)


def unsubscribe(listener) -> None:
    with _LOCK:
        if listener in _LISTENERS:
            _LISTENERS.remove(listener)


def _fire(event: dict) -> None:
    with _LOCK:
        listeners = list(_LISTENERS)
    for listener in listeners:
        try:
            listener(dict(event))
        except Exception:
            pass  # telemetry must never take down a dispatch


def _family_resident(fam: dict) -> int:
    n = 0
    for fn in fam["jit_fns"]:
        try:
            n += int(fn._cache_size())
        except Exception:
            pass  # older jax without _cache_size: resident reads 0
    return n


def note_build(fn: str, *, width=None, n_devices=None,
               build_s: float = 0.0, jit_fns=()) -> None:
    """One jit-family BUILD (the lru-cache miss path: tracing wrappers
    constructed, nothing XLA-compiled yet).  ``jit_fns``: the family's
    jit objects, retained for resident-executable counts."""
    key = family_key(fn, width, n_devices)
    with _LOCK:
        fam = _FAMILIES.setdefault(key, _new_family(key))
        fam["builds"] += 1
        fam["build_s"] += build_s
        fam["jit_fns"] = tuple(jit_fns)
    event = {"fn": key[0], "build_s": round(build_s, 6),
             "phase": "build"}
    if width is not None:
        event["width"] = width
    if n_devices is not None:
        event["n_devices"] = n_devices
    _fire(event)


def note_lookup(fn: str, width=None, n_devices=None) -> None:
    """One cache lookup of the family (hit or the miss that built it:
    ``hits = lookups - builds``)."""
    key = family_key(fn, width, n_devices)
    with _LOCK:
        fam = _FAMILIES.setdefault(key, _new_family(key))
        fam["lookups"] += 1


@contextlib.contextmanager
def dispatch_scope(fn: str, width=None, n_devices=None):
    """Attribute XLA backend-compile walls observed during this dispatch
    to the (fn, width, n_devices) family — the scheduler wraps each
    stacked/plan/single device call in one.  Thread-local: concurrent
    dispatch threads attribute independently."""
    _install_monitor()
    prev = getattr(_SCOPE, "key", None)
    _SCOPE.key = family_key(fn, width, n_devices)
    try:
        yield
    finally:
        _SCOPE.key = prev


def _install_monitor() -> None:
    """Best-effort, once: hook ``jax.monitoring``'s duration events so
    real backend-compile walls (not just wrapper builds) reach the
    stream.  Missing API → the build/lookup feeds still flow."""
    if _MONITOR["installed"]:
        return
    with _LOCK:
        if _MONITOR["installed"]:
            return
        _MONITOR["installed"] = True
        try:
            from jax import monitoring
            monitoring.register_event_duration_secs_listener(
                _on_jax_duration)
        except Exception:
            pass


def _on_jax_duration(name: str, dur: float, **_kw) -> None:
    if not str(name).endswith("/backend_compile_duration"):
        return
    key = getattr(_SCOPE, "key", None) or _UNATTRIBUTED
    with _LOCK:
        fam = _FAMILIES.setdefault(key, _new_family(key))
        fam["compiles"] += 1
        fam["compile_s"] += float(dur)
        resident = _family_resident(fam)
    event = {"fn": key[0], "build_s": round(float(dur), 6),
             "phase": "xla", "resident": resident}
    if key[1] is not None:
        event["width"] = key[1]
    if key[2] is not None:
        event["n_devices"] = key[2]
    _fire(event)


def _label(key: tuple) -> str:
    label = key[0]
    if key[1] is not None:
        label += f"@w{key[1]}"
    if key[2] is not None:
        label += f"/d{key[2]}"
    return label


def family_labels() -> list[str]:
    """Sorted labels of every family this process has touched — the
    determinism pin (same workload → same families, restart included)."""
    with _LOCK:
        return sorted(_label(k) for k in _FAMILIES)


def snapshot() -> dict:
    """The process-wide roll-up (status snapshots and ``cetpu-top`` read
    this): totals plus a per-family table with resident-executable
    counts polled live."""
    with _LOCK:
        fams = {k: dict(f) for k, f in _FAMILIES.items()}
    per_family = {}
    totals = {"families": len(fams), "lookups": 0, "builds": 0,
              "hits": 0, "build_s": 0.0, "compiles": 0,
              "compile_s": 0.0, "resident": 0}
    for key, fam in sorted(fams.items(),
                           key=lambda kv: _label(kv[0])):
        resident = _family_resident(fam)
        hits = max(fam["lookups"] - fam["builds"], 0)
        per_family[_label(key)] = {
            "lookups": fam["lookups"], "builds": fam["builds"],
            "hits": hits, "build_s": round(fam["build_s"], 6),
            "compiles": fam["compiles"],
            "compile_s": round(fam["compile_s"], 6),
            "resident": resident,
        }
        totals["lookups"] += fam["lookups"]
        totals["builds"] += fam["builds"]
        totals["hits"] += hits
        totals["build_s"] += fam["build_s"]
        totals["compiles"] += fam["compiles"]
        totals["compile_s"] += fam["compile_s"]
        totals["resident"] += resident
    totals["build_s"] = round(totals["build_s"], 6)
    totals["compile_s"] = round(totals["compile_s"], 6)
    totals["per_family"] = per_family
    return totals


def build_timer() -> float:
    """The builders' wall source (one spelling, mockable)."""
    return time.perf_counter()


def _reset_for_tests() -> None:
    """Drop family state and listeners (the jit caches themselves are
    process-wide and stay warm — tests pin LOOKUP growth, not rebuild)."""
    with _LOCK:
        _FAMILIES.clear()
        _LISTENERS.clear()
