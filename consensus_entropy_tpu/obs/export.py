"""Export + report: torn-tail-safe readers, schema validation, the
multi-host merge, Chrome trace-event export and the text report.

All pure host code (json + os only): the ``report`` CLI subcommand and
``scripts/obs_check.sh`` run it without touching a jax backend.

Chrome trace output loads in Perfetto (or ``chrome://tracing``): one
process lane per HOST, one thread lane per user / bucket / run within it.
Span records come from ``spans.jsonl`` (single-host / coordinator-
transcribed) and ``fabric/spans_<h>.jsonl`` (per-worker WALs); the merge
dedupes by deterministic span id — a resumed user's re-run iteration
keeps its completed attempt, a transcribed duplicate collapses — keeping
the LONGEST duration per id (a partially-written eviction span loses to
the completed re-run).
"""

from __future__ import annotations

import glob
import json
import os

#: schema-v2 event table: event kind -> ``{field: kind}`` — the fields
#: every record of that kind must carry (beyond ``schema``/``event``;
#: ``t_s`` is required for all but the summary records, which close a
#: stream rather than timestamp a transition) AND the value kind each
#: must hold.  Kinds: ``str`` / ``int`` (bools excluded) / ``float``
#: (ints accepted — JSON round-trips may narrow) / ``list``.  v2 of the
#: table listed field names only; the per-field kinds are what keeps the
#: ``compile``/``alert``/snapshot events honest at the emit site (the
#: ``event-schema`` lint rule checks literal argument types) and at read
#: time (:func:`validate_metrics`).  README "Observability" renders this
#: as the docs table.
EVENT_FIELDS = {
    # admission flow (enqueue/admit also carry a ``cls`` priority-class
    # field since the SLO planner — OPTIONAL here so pre-planner v2
    # streams keep validating; scripts/slo_check.sh asserts it on
    # planner runs)
    "enqueue": {"user": "str", "depth": "int"},
    "admit": {"user": "str", "width": "int", "wait_s": "float",
              "depth": "int", "live": "int"},
    "user_done": {"user": "str"},
    "user_failed": {"user": "str", "error": "str"},
    "skip_done": {"user": "str"},
    "skip_poisoned": {"user": "str"},
    # engine lifecycle
    "evict": {"user": "str", "error": "str"},
    "resume": {"user": "str", "attempt": "int"},
    "watchdog_evict": {"user": "str"},
    "dispatch_failed": {"fn": "str", "width": "int"},
    "dispatch_session_error": {"user": "str", "fn": "str"},
    # fault domain
    "breaker_open": {"width": "int"},
    "breaker_close": {"width": "int"},
    "breaker_probe": {"width": "int"},
    "breaker_giveup": {"width": "int"},
    "requeue": {"user": "str", "attempt": "int"},
    "requeue_reload_failed": {"user": "str"},
    "poison": {"user": "str"},
    "drain": {},
    "journal_recover": {},
    # SLO planner decisions (serve.planner)
    "planner_edges": {"edges": "list"},
    "admission_hold": {"window_s": "float"},
    # jit-compile telemetry (obs.jit_telemetry): one event per jit-family
    # build / per observed XLA compile — the feed the planner's
    # cost-aware-edges follow-on needs to trade padding waste against
    # jit-cache pressure (width/n_devices/compile_s/resident ride along)
    "compile": {"fn": "str", "build_s": "float"},
    # SLO burn-rate alerts (obs.alerts): edge-triggered operator signals
    "alert": {"kind": "str"},
    # fabric
    "assign": {"user": "str", "host": "str"},
    "host_up": {"host": "str"},
    "host_down": {"host": "str"},
    "orphan_reaped": {"host": "str"},
    "drain_kill": {"host": "str"},
    "user_finished": {"user": "str"},
    "user_poisoned": {"user": "str"},
    "user_failed_final": {"user": "str"},
    # elastic control plane (serve.elastic / serve.placement)
    "host_spawn": {"host": "str"},
    "host_join": {"host": "str"},
    "host_adopt": {"host": "str"},
    "host_adopt_refused": {"host": "str"},
    "migrate_request": {"user": "str", "host": "str"},
    "migrate": {"user": "str", "host": "str"},
    "migrate_refused": {"user": "str"},
    "withdraw": {"user": "str"},
    "fleet_edges": {"edges": "list"},
    # graceful scale-down + checkpoint-fenced live migration
    "host_drain": {"host": "str"},
    "drain_done": {"host": "str"},
    "migrate_fence": {"user": "str", "host": "str"},
    "migrate_inflight": {"user": "str", "host": "str"},
    "fence_release": {"user": "str"},
    # the remediation plane (serve.remedy): a journaled self-healing
    # decision (drain-for-rebalance / deadline fallback) and the fence
    # that burned past --fence-deadline-s into evict+resume
    "remedy": {"host": "str", "action": "str"},
    "fence_timeout": {"user": "str", "host": "str"},
    # the gray-failure ladder (serve.remedy gray kernels): a host placed
    # on / lifted from probation (placement stops/resumes routing NEW
    # users to it — journaled, so the rung survives a coordinator kill),
    # and a probation host's committee scoring depth dialed between
    # ``full`` and ``cheap`` under sustained SLO burn
    "probation": {"host": "str"},
    "depth_change": {"host": "str", "depth": "str"},
    # live intake churn (workload traces): a producer disconnected a
    # user mid-run (parked; workspace kept) / reconnected it (resumes
    # from the workspace over the journal re-admission path)
    "disconnect": {"user": "str"},
    "reconnect": {"user": "str"},
    # storage integrity (resilience.io + fencing epochs): an injected or
    # real disk fault surfaced through the io seam; a corrupt WAL record
    # CRC-quarantined to its sidecar; a coordinator incarnation claiming
    # its fencing epoch; a stale incarnation's feed line or ack refused
    # (epoch_fenced also carries ``user`` when the line named one)
    "io_fault": {"kind": "str", "path": "str"},
    "record_quarantined": {"host": "str", "path": "str"},
    "epoch_claim": {"epoch": "int"},
    "epoch_fenced": {"host": "str", "epoch": "int"},
    # stream-closing summaries (no t_s)
    "fleet_summary": {},
    "fabric_summary": {},
}

#: the value check per field kind.  ``float`` accepts ints (a JSON
#: round-trip of ``1.0`` may come back ``1``); bools are never ints
#: here (``json.dumps(True)`` is not a count).
FIELD_KINDS = {
    "str": lambda v: isinstance(v, str),
    "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
    "float": lambda v: (isinstance(v, (int, float))
                        and not isinstance(v, bool)),
    "list": lambda v: isinstance(v, list),
}

#: events that close a stream instead of timestamping a transition
_SUMMARY_EVENTS = ("fleet_summary", "fabric_summary")


def read_jsonl_tolerant(path: str) -> list[dict]:
    """Read a JSONL telemetry file, SKIPPING a torn tail line (the
    expected SIGKILL artifact — the same discipline ``serve.journal``
    applies to its WALs) and any other unparseable line, instead of
    raising.  Non-dict lines are dropped too.  CRC-framed journal lines
    (``w1 <crc> {...}``, the storage-integrity format) are unframed
    transparently — a frame failing its CRC is skipped like any other
    corrupt line, because these readers OBSERVE; only replay halts."""
    from consensus_entropy_tpu.resilience import io as dio
    out: list[dict] = []
    if not os.path.exists(path):
        return out
    with open(path, "rb") as f:
        for raw in f:
            status, rec = dio.parse_frame(raw)
            if status == "corrupt":
                continue  # torn/corrupt line: telemetry, not a ledger
            if isinstance(rec, dict) and not dio.is_header(rec):
                out.append(rec)
    return out


def find_metrics_files(users_dir: str) -> list[str]:
    """``fleet_metrics.jsonl`` plus the per-host
    ``fleet_metrics_<h>.jsonl`` files a fabric run leaves."""
    return sorted(glob.glob(os.path.join(users_dir,
                                         "fleet_metrics*.jsonl")))


def find_span_files(users_dir: str) -> list[str]:
    """``spans.jsonl`` (single-host, or the coordinator's transcription)
    plus any per-worker ``fabric/spans_<h>.jsonl`` WALs."""
    return sorted(
        glob.glob(os.path.join(users_dir, "spans*.jsonl"))
        + glob.glob(os.path.join(users_dir, "fabric", "spans_*.jsonl")))


def validate_metrics(records: list[dict], *, path: str = "") -> list[str]:
    """Schema-v2 validation; returns human-readable error strings (empty
    = valid).  Every line must be a tagged dict with a known event, that
    event's required fields AT their registered kinds (the per-field
    type check the v2.1 table added), and — for non-summary events — a
    numeric ``t_s``.
    """
    errors = []
    where = f"{path}:" if path else "line "
    for i, rec in enumerate(records, 1):
        ev = rec.get("event")
        if rec.get("schema") != 2:
            errors.append(f"{where}{i}: missing/wrong schema tag "
                          f"(want 2, got {rec.get('schema')!r})")
            continue
        if ev not in EVENT_FIELDS:
            errors.append(f"{where}{i}: unknown event {ev!r}")
            continue
        if ev not in _SUMMARY_EVENTS \
                and not isinstance(rec.get("t_s"), (int, float)):
            errors.append(f"{where}{i}: event {ev!r} lacks numeric t_s")
        for field, kind in EVENT_FIELDS[ev].items():
            if field not in rec:
                errors.append(f"{where}{i}: event {ev!r} lacks {field!r}")
            elif not FIELD_KINDS[kind](rec[field]):
                errors.append(
                    f"{where}{i}: event {ev!r} field {field!r} must be "
                    f"{kind}, got {rec[field]!r}")
    return errors


def validate_metrics_file(path: str) -> list[str]:
    return validate_metrics(read_jsonl_tolerant(path), path=path)


def load_spans(paths: list[str]) -> list[dict]:
    """Merge span files into one deduped timeline, sorted by ``t0``.
    Dedupe key is the deterministic ``(trace, span)`` id; the longest
    duration wins (see module docstring)."""
    best: dict[tuple, dict] = {}
    for path in paths:
        for rec in read_jsonl_tolerant(path):
            if rec.get("ev") != "span":
                continue
            key = (rec.get("trace"), rec.get("span"))
            prev = best.get(key)
            if prev is None or (rec.get("dur_s") or 0) \
                    > (prev.get("dur_s") or 0):
                best[key] = rec
    return sorted(best.values(), key=lambda r: (r.get("t0") or 0))


def orphan_spans(spans: list[dict]) -> list[dict]:
    """Spans whose ``parent`` id is absent from the merged set — the
    determinism contract says a healthy (resumed-to-completion) run has
    none."""
    ids = {r.get("span") for r in spans}
    return [r for r in spans
            if r.get("parent") is not None and r["parent"] not in ids]


def _lane_of(rec: dict) -> str:
    """The Chrome-trace thread lane: users own their session spans,
    stacked device work rides per-bucket lanes, the run span its own."""
    name = rec.get("name")
    if name == "run":
        return "run"
    if rec.get("user") is not None:
        return f"user {rec['user']}"
    if name in ("score_dispatch", "retrain"):
        width = rec.get("width")
        return f"bucket {width}" if width is not None else "dispatch"
    return "dispatch"


def _flow_id(rec: dict) -> int:
    """Deterministic Chrome flow-event id for a control span (derived
    from the span's own deterministic id, so re-exports and kill+replay
    merges draw the same arrows)."""
    import hashlib

    h = hashlib.sha1(f"flow:{rec.get('trace')}:{rec.get('span')}"
                     .encode("utf-8"))
    return int.from_bytes(h.digest()[:6], "big")


def chrome_trace(spans: list[dict]) -> dict:
    """Render merged spans as Chrome trace-event JSON (Perfetto-loadable):
    complete (``ph: "X"``) events on one process per host — plus a
    dedicated ``control-plane`` process whose thread lanes are the
    ``ctl.*`` decision kinds — and one thread per user/bucket/run lane,
    with metadata naming events.  Control spans carrying ``flow_user``
    additionally emit a Chrome flow pair (``ph: "s"`` at the decision,
    ``ph: "f"`` binding into the user's root span), so a fence/migrate
    decision visibly threads into the session it moved."""
    pids: dict[str, int] = {}
    tids: dict[tuple, int] = {}
    events = []
    #: user -> that user's root-span placement (filled as lanes are
    #: assigned; flow arrows bind to it)
    user_slice: dict[str, dict] = {}
    flows = []

    def lane_for(pkey: str, pname: str, lane: str) -> tuple:
        if pkey not in pids:
            pids[pkey] = len(pids) + 1
            events.append({"name": "process_name", "ph": "M",
                           "pid": pids[pkey], "tid": 0,
                           "args": {"name": pname}})
        tkey = (pkey, lane)
        if tkey not in tids:
            tids[tkey] = len(tids) + 1
            events.append({"name": "thread_name", "ph": "M",
                           "pid": pids[pkey], "tid": tids[tkey],
                           "args": {"name": lane}})
        return pids[pkey], tids[tkey]

    for rec in spans:
        host = rec.get("host") or "local"
        if rec.get("ctl"):
            # the control-plane lane: one process, one thread per
            # decision kind (instant spans of one kind never nest)
            pid, tid = lane_for("__ctl__", "control-plane",
                                rec.get("name") or "ctl")
        else:
            pid, tid = lane_for(host, f"host {host}", _lane_of(rec))
        args = {k: v for k, v in rec.items()
                if k not in ("ev", "name", "t0", "dur_s", "host")}
        ts = int(round((rec.get("t0") or 0) * 1e6))
        dur = max(int(round((rec.get("dur_s") or 0) * 1e6)), 1)
        events.append({
            "name": rec.get("name") or "span", "cat": "obs", "ph": "X",
            "ts": ts, "dur": dur, "pid": pid, "tid": tid, "args": args,
        })
        user = rec.get("user")
        if user is not None and not rec.get("ctl"):
            best = user_slice.get(str(user))
            # the user ROOT span is the flow anchor; any other span of
            # the user's stands in when the root never closed
            if best is None or (rec.get("name") == "user"
                                and best["name"] != "user"):
                user_slice[str(user)] = {"name": rec.get("name"),
                                         "pid": pid, "tid": tid,
                                         "ts": ts, "dur": dur}
        if rec.get("flow_user") is not None:
            flows.append((rec, pid, tid, ts))
    for rec, pid, tid, ts in flows:
        target = user_slice.get(str(rec["flow_user"]))
        if target is None:
            continue  # the user never traced (e.g. --no-trace worker)
        fid = _flow_id(rec)
        name = f"{rec.get('name') or 'ctl'} → {rec['flow_user']}"
        events.append({"name": name, "cat": "obs.flow", "ph": "s",
                       "id": fid, "pid": pid, "tid": tid, "ts": ts})
        # bind the arrow INSIDE the user slice (Chrome attaches flow
        # ends to the enclosing slice at that instant)
        t_end = min(max(ts + 1, target["ts"]),
                    target["ts"] + target["dur"])
        events.append({"name": name, "cat": "obs.flow", "ph": "f",
                       "bp": "e", "id": fid, "pid": target["pid"],
                       "tid": target["tid"], "ts": t_end})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def _host_of_metrics_path(path: str) -> str:
    base = os.path.basename(path)
    if base == "fleet_metrics.jsonl":
        return "main"
    return base[len("fleet_metrics_"):-len(".jsonl")] or "main"


def merged_summary(users_dir: str) -> dict:
    """One fleet view over every host's metrics stream: the last
    ``fleet_summary`` per file, keyed by host, plus fleet-wide roll-ups
    (users done/failed, admission→finish latency per host — the fabric
    shape of the SLO telemetry)."""
    per_host = {}
    for path in find_metrics_files(users_dir):
        recs = read_jsonl_tolerant(path)
        summaries = [r for r in recs if r.get("event") == "fleet_summary"]
        if not summaries:
            continue
        per_host[_host_of_metrics_path(path)] = summaries[-1]
    out = {
        "hosts": sorted(per_host),
        "users_done": sum(s.get("users_done") or 0
                          for s in per_host.values()),
        "users_failed": sum(s.get("users_failed") or 0
                            for s in per_host.values()),
        "per_host": per_host,
        "admission_to_finish_s": {
            h: s["admission_to_finish_s"] for h, s in per_host.items()
            if s.get("admission_to_finish_s") is not None},
        "per_class": {
            h: s["per_class"] for h, s in per_host.items()
            if s.get("per_class") is not None},
    }
    return out


def planner_timeline(users_dir: str) -> dict:
    """The SLO planner's decision history: per-host ``planner_edges``
    events (locally derived edges over time), per-host ``fleet_edges``
    events (coordinator broadcasts the host ADOPTED), the
    ``admission_hold`` counts, and — the piece the per-worker streams
    cannot carry — the main journal's own ``planner`` epochs, which in
    fabric mode are the coordinator ``FleetPlanner``'s derivations over
    the MERGED per-host sketches (PR 13): the edges workers actually
    routed by.  Fired ``alert`` events ride along in the same pass
    (one read per metrics file, not one per report section).  Returns
    ``{"per_host": {host: {...}}, "journal_epochs": [...],
    "alerts": [...]}`` — the ``cetpu-report`` planner/alert sections'
    data."""
    per_host: dict[str, dict] = {}
    alert_events: list[dict] = []
    for path in find_metrics_files(users_dir):
        host = _host_of_metrics_path(path)
        edges, fleet_edges, holds = [], [], 0
        for rec in read_jsonl_tolerant(path):
            ev = rec.get("event")
            if ev == "alert":
                alert_events.append({"host": host, **rec})
            elif ev == "planner_edges":
                edges.append({"t_s": rec.get("t_s"),
                              "edges": rec.get("edges"),
                              "observations": rec.get("observations")})
            elif ev == "fleet_edges":
                # coordinator-broadcast fabric-level edges (the elastic
                # fleet planner) as this host adopted them — rendered
                # alongside the local epochs
                fleet_edges.append({"t_s": rec.get("t_s"),
                                    "edges": rec.get("edges"),
                                    "observations":
                                        rec.get("observations")})
            elif ev == "admission_hold":
                holds += 1
        if edges or fleet_edges or holds:
            per_host[host] = {"edges": edges, "admission_holds": holds}
            if fleet_edges:
                per_host[host]["fleet_edges"] = fleet_edges
    epochs = []
    for rec in read_jsonl_tolerant(os.path.join(users_dir,
                                                "serve_journal.jsonl")):
        if rec.get("event") == "planner":
            epochs.append({"seq": rec.get("seq"),
                           "edges": rec.get("edges"),
                           "observations":
                               (rec.get("sketch") or {}).get("n"),
                           "fleet": bool(rec.get("fleet"))})
    return {"per_host": per_host, "journal_epochs": epochs,
            "alerts": alert_events}


def alert_counts(users_dir: str) -> dict:
    """Fired-alert counts by kind across every host's metrics stream —
    the soak grader's "did the control plane notice" column (and the
    quick health read: a clean steady-state soak fires few; a saturated
    one burns slo_headroom/batch_aging continuously)."""
    counts: dict = {}
    for rec in planner_timeline(users_dir)["alerts"]:
        kind = rec.get("kind")
        if isinstance(kind, str):
            counts[kind] = counts.get(kind, 0) + 1
    return dict(sorted(counts.items()))


def text_report(users_dir: str) -> str:
    """The operator text report: per-phase wall-clock breakdown, dispatch
    occupancy, h2d traffic and admission→finish latency percentiles, per
    host, from the merged metrics + spans."""
    lines = [f"observability report — {users_dir}"]
    merged = merged_summary(users_dir)
    if not merged["per_host"]:
        lines.append("  (no fleet_summary found in any "
                     "fleet_metrics*.jsonl)")
    for host in merged["hosts"]:
        s = merged["per_host"][host]
        lines.append(f"[{host}] users_done={s.get('users_done')} "
                     f"failed={s.get('users_failed')} "
                     f"wall_s={s.get('wall_s')} "
                     f"users/s={s.get('users_per_sec')}")
        phases = s.get("phase_wall_s") or {}
        total = sum(phases.values()) or 1.0
        for k, v in sorted(phases.items(), key=lambda kv: -kv[1]):
            lines.append(f"    {k:<16} {v:>9.3f}s "
                         f"({100.0 * v / total:5.1f}%)")
        lines.append(f"    dispatches={s.get('score_dispatches')} "
                     f"occupancy={s.get('occupancy')} "
                     f"mean_batch={s.get('mean_device_batch')}")
        if s.get("transfer") is not None:
            t = s["transfer"]
            lines.append(f"    h2d_bytes={t.get('h2d_bytes')} "
                         f"({t.get('h2d_bytes_per_select')}/select), "
                         f"h2d_ops={t.get('h2d_ops')}, "
                         f"device_calls/select="
                         f"{t.get('device_calls_per_select')}")
        lat = s.get("admission_to_finish_s")
        if lat is not None:
            lines.append(f"    admission→finish p50={lat.get('p50')}s "
                         f"p95={lat.get('p95')}s p99={lat.get('p99')}s "
                         f"(n={lat.get('n')})")
        per_class = s.get("per_class") or {}
        for cls, c in sorted(per_class.items()):
            clat = c.get("admission_to_finish_s") or {}
            lines.append(f"      [{cls}] users={c.get('users')} "
                         f"p50={clat.get('p50')}s p95={clat.get('p95')}s "
                         f"p99={clat.get('p99')}s")
        planner = s.get("planner")
        if planner is not None:
            lines.append(f"    planner: edges={planner.get('edges')} "
                         f"({planner.get('edge_updates')} update(s) over "
                         f"{planner.get('observations')} obs), holds: "
                         f"admission={planner.get('admission_hold_rounds')}"
                         f" dispatch={planner.get('dispatch_hold_rounds')}")
        per_bucket = s.get("per_bucket") or {}
        for width, b in sorted(per_bucket.items(),
                               key=lambda kv: int(kv[0])):
            lines.append(f"      bucket {width}: occupancy="
                         f"{b.get('occupancy')} mean_batch="
                         f"{b.get('mean_batch')} "
                         f"dispatches={b.get('dispatches')}")
    timeline = planner_timeline(users_dir)
    for host, t in sorted(timeline["per_host"].items()):
        if t["edges"]:
            lines.append(f"planner edges over time [{host}]:")
            for e in t["edges"]:
                lines.append(f"    t={e.get('t_s')}s -> {e.get('edges')} "
                             f"(after {e.get('observations')} obs)")
        if t.get("fleet_edges"):
            lines.append(f"fleet edges adopted [{host}]:")
            for e in t["fleet_edges"]:
                lines.append(f"    t={e.get('t_s')}s -> {e.get('edges')} "
                             f"(after {e.get('observations')} merged "
                             "obs)")
    if timeline["journal_epochs"]:
        # the journal's own planner epochs — in fabric mode the
        # coordinator FleetPlanner's merged-sketch derivations (the
        # edges broadcast to every worker), single-host the local
        # planner's (the PR 15 report bugfix: these never showed)
        lines.append("journal planner epochs:")
        for e in timeline["journal_epochs"]:
            tag = " [fleet-adopt]" if e.get("fleet") else ""
            lines.append(f"    seq={e.get('seq')} -> {e.get('edges')} "
                         f"(sketch n={e.get('observations')}){tag}")
    if timeline["alerts"]:
        lines.append(f"alerts fired: {len(timeline['alerts'])}")
        for r in timeline["alerts"]:
            detail = " ".join(
                f"{k}={v}" for k, v in sorted(r.items())
                if k not in ("schema", "event", "t_s", "kind"))
            lines.append(f"    t={r.get('t_s')}s [{r.get('kind')}] "
                         f"{detail}")
    spans = load_spans(find_span_files(users_dir))
    if spans:
        by_name: dict[str, list[float]] = {}
        hosts = set()
        for r in spans:
            by_name.setdefault(r.get("name") or "span", []).append(
                r.get("dur_s") or 0.0)
            hosts.add(r.get("host") or "local")
        lines.append(f"spans: {len(spans)} across {len(hosts)} host(s)")
        for name, durs in sorted(by_name.items(),
                                 key=lambda kv: -sum(kv[1])):
            lines.append(f"    {name:<16} n={len(durs):<5} "
                         f"total={sum(durs):9.3f}s "
                         f"mean={sum(durs) / len(durs):8.4f}s")
        orphans = orphan_spans(spans)
        if orphans:
            lines.append(f"    WARNING: {len(orphans)} orphan span(s) "
                         "(parent id never written)")
    return "\n".join(lines)
