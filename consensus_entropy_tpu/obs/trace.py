"""Span tracing with explicit contexts and deterministic ids.

The span hierarchy mirrors the serving stack::

    run ── user ── al_iter ── {host_step, checkpoint}
     │      └──── admission_wait            (serve mode: enqueue→admit)
     ├──── {score_dispatch, retrain}        (stacked: one span, N users)
     └──── ctl.*                            (control-plane decisions:
            spawn/join/drain/fence/migrate/failover/planner_epoch — the
            fabric coordinator's lane, see :meth:`Tracer.control_event`)

**Determinism is the recovery story.**  Trace ids derive from
``(run_id, user)`` and the user/iteration span ids from
``(run_id, user, iteration)``, so a session rebuilt after eviction,
serve-journal restart or fabric worker-SIGKILL failover CONTINUES its
trace: the resumed attempt re-emits the SAME span ids for the re-run
iteration, and the merge (``obs.export.load_spans``) dedupes by id,
keeping the completed attempt.  An iteration interrupted mid-flight
leaves its span unwritten — never torn — and its already-written children
reference a parent id the resumed attempt is guaranteed to write, so the
merged trace has no orphans (pinned in ``tests/test_obs.py``).

**Threading.**  Contexts are EXPLICIT (passed as ``parent=``), never
ambient: the fleet scheduler services one session's steps on worker
threads while the session generator is suspended, so thread-local context
propagation would attribute spans to whichever session last ran on the
thread.  The writer is the shared :class:`~obs.metrics.EventWriter`
(thread-safe, flush per record, torn tails tolerated by readers).

**Cost.**  A span is one dict + one buffered JSON line; the serving
stack emits a handful per user-iteration.  ``enabled=False`` (the
``--no-trace`` arm) short-circuits every call — the overhead bound is
measured by ``bench.py --suite obs``.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time

from consensus_entropy_tpu.obs.metrics import EventWriter


def _digest(*parts) -> str:
    h = hashlib.sha1("\x1f".join(str(p) for p in parts).encode("utf-8"))
    return h.hexdigest()[:16]


def trace_id(run_id: str, user=None) -> str:
    """The deterministic trace id: one per (run, user), or the run's own
    when ``user`` is None."""
    return _digest("trace", run_id) if user is None \
        else _digest("trace", run_id, str(user))


class SpanContext:
    """An addressable span: ``(trace, span)`` id pair, passed explicitly
    as ``parent=`` to child spans.  Hashable/immutable."""

    __slots__ = ("trace", "span")

    def __init__(self, trace: str, span: str):
        self.trace = trace
        self.span = span

    def __repr__(self):
        return f"SpanContext({self.trace}/{self.span})"


class _OpenSpan:
    """Handle returned by :meth:`Tracer.begin`; usable as ``parent=``
    directly (it carries its context)."""

    __slots__ = ("ctx", "name", "t0", "attrs")

    def __init__(self, ctx: SpanContext, name: str, t0: float, attrs: dict):
        self.ctx = ctx
        self.name = name
        self.t0 = t0
        self.attrs = attrs


def _ctx_of(parent) -> SpanContext | None:
    if parent is None:
        return None
    return parent.ctx if isinstance(parent, _OpenSpan) else parent


class Tracer:
    """Emit spans to a JSONL sink (``spans.jsonl`` / ``spans_<h>.jsonl``).

    ``run_id``: the deterministic run identity — the CLI derives it from
    ``(mode, seed)`` so a restarted run (and every fabric worker of one)
    continues the same traces.  ``host``: tag for multi-host lanes.
    ``path=None`` keeps spans in memory only (``records``); ``enabled=
    False`` is the zero-cost ``--no-trace`` arm.
    """

    def __init__(self, path: str | None = None, *, run_id: str = "run",
                 host: str | None = None, enabled: bool = True):
        self.enabled = enabled
        self.run_id = run_id
        self.host = host
        #: in-memory span mirror, kept ONLY for path=None tracers (unit
        #: tests, embedded drivers): a file-backed tracer on a long-lived
        #: server must not grow an unbounded list beside its sink
        self.records: list[dict] = []
        self._keep_records = path is None
        #: approximate seconds spent INSIDE the tracer (id derivation +
        #: record build + buffered write), summed across threads — the
        #: capacity-independent overhead pin ``bench.py --suite obs``
        #: reports, since this box's wall-clock noise floor (±3-8%
        #: run-to-run) swamps a sub-1% true cost.  Non-atomic
        #: accumulation: concurrent updates may drop a few µs.
        self.cost_s = 0.0
        self._writer = EventWriter(path if enabled else None)
        self._lock = threading.Lock()
        self._auto = 0
        #: open user root spans: span id -> (ctx, t0, attrs); idempotent
        #: open keeps the EARLIEST t0 (serve mode opens at first enqueue)
        self._open_users: dict[str, tuple] = {}
        self.run_ctx = SpanContext(trace_id(run_id),
                                   _digest("span", run_id, "run"))
        self._run_t0 = time.time()

    # -- id derivation (pure) ---------------------------------------------

    def user_ctx(self, user) -> SpanContext | None:
        """The deterministic user-root context — derivable WITHOUT the
        session (the serve layer parents ``admission_wait`` spans under
        it before any session exists)."""
        if not self.enabled:
            return None
        return SpanContext(trace_id(self.run_id, user),
                           _digest("span", self.run_id, "user", str(user)))

    def _child_ctx(self, name: str, parent: SpanContext | None,
                   key) -> SpanContext:
        trace = parent.trace if parent is not None else self.run_ctx.trace
        if key is None:
            # run-scoped, non-replayable span (a stacked dispatch): unique
            # within and across (possibly restarted) runs — host + the
            # tracer's own start instant salt the counter
            with self._lock:
                self._auto += 1
                key = f"auto:{self.host}:{self._run_t0:.6f}:{self._auto}"
        return SpanContext(trace, _digest("span", self.run_id, name, key))

    # -- emission ----------------------------------------------------------

    def _emit(self, rec: dict) -> None:
        if self._keep_records:
            self.records.append(rec)
        self._writer.emit(rec)

    def _span_rec(self, ctx: SpanContext, parent: SpanContext | None,
                  name: str, t0: float, t1: float, attrs: dict) -> dict:
        rec = {"ev": "span", "trace": ctx.trace, "span": ctx.span,
               "parent": parent.span if parent is not None else None,
               "name": name, "t0": round(t0, 6),
               "dur_s": round(max(t1 - t0, 0.0), 6)}
        if self.host is not None:
            rec["host"] = self.host
        rec.update(attrs)
        return rec

    # -- span lifecycle ----------------------------------------------------

    def begin(self, name: str, *, parent=None, key=None,
              **attrs) -> _OpenSpan | None:
        """Open a span WITHOUT a context manager (generator code that
        suspends across the span's lifetime).  An opened-but-never-ended
        span is simply not written — deterministic keys make the re-run
        write it (see module docstring)."""
        if not self.enabled:
            return None
        c0 = time.perf_counter()
        parent = _ctx_of(parent)
        ctx = self._child_ctx(name, parent, key)
        sp = _OpenSpan(ctx, name, time.time(), attrs)
        sp.attrs["_parent"] = parent
        self.cost_s += time.perf_counter() - c0
        return sp

    def end(self, span: _OpenSpan | None, **attrs) -> None:
        if span is None or not self.enabled:
            return
        c0 = time.perf_counter()
        a = dict(span.attrs)
        parent = a.pop("_parent", None)
        a.update(attrs)
        self._emit(self._span_rec(span.ctx, parent, span.name, span.t0,
                                  time.time(), a))
        self.cost_s += time.perf_counter() - c0

    @contextlib.contextmanager
    def span(self, name: str, *, parent=None, key=None, **attrs):
        """Context-manager span; yields the child's :class:`SpanContext`
        for further nesting.  Written on exit (exceptions included — the
        partial duration is still telemetry)."""
        if not self.enabled:
            yield None
            return
        sp = self.begin(name, parent=parent, key=key, **attrs)
        try:
            yield sp.ctx
        finally:
            self.end(sp)

    def span_at(self, name: str, t0: float, t1: float, *, parent=None,
                key=None, **attrs) -> None:
        """Record a span retroactively from measured wall-clock endpoints
        (admission waits, already-timed dispatches)."""
        if not self.enabled:
            return
        c0 = time.perf_counter()
        parent = _ctx_of(parent)
        ctx = self._child_ctx(name, parent, key)
        self._emit(self._span_rec(ctx, parent, name, t0, t1, attrs))
        self.cost_s += time.perf_counter() - c0

    # -- user root spans ---------------------------------------------------

    def open_user(self, user, *, t0: float | None = None, **attrs) -> None:
        """Idempotently open the user's root span (keyed by its
        deterministic id): the serve layer opens it at first enqueue, the
        session constructor opens it defensively — whichever ran first
        owns ``t0``, so admission waits nest inside the user span."""
        if not self.enabled:
            return
        c0 = time.perf_counter()
        ctx = self.user_ctx(user)
        with self._lock:
            if ctx.span not in self._open_users:
                self._open_users[ctx.span] = (
                    ctx, time.time() if t0 is None else t0,
                    {"user": str(user), **attrs})
        self.cost_s += time.perf_counter() - c0

    def user_open_t0(self, user) -> float | None:
        """The open user root span's start time (None when not open) —
        lets the serve layer clamp an ``admission_wait`` span measured
        from the queue's own (earlier) timestamp inside its parent."""
        if not self.enabled:
            return None
        ctx = self.user_ctx(user)
        with self._lock:
            rec = self._open_users.get(ctx.span)
        return rec[1] if rec is not None else None

    def close_user(self, user, **attrs) -> None:
        """Write the user root span (no-op if never/no-longer open —
        a re-admitted user's span stays open across attempts)."""
        if not self.enabled:
            return
        c0 = time.perf_counter()
        ctx = self.user_ctx(user)
        with self._lock:
            open_rec = self._open_users.pop(ctx.span, None)
        if open_rec is not None:
            _ctx, t0, a = open_rec
            a.update(attrs)
            self._emit(self._span_rec(ctx, self.run_ctx, "user", t0,
                                      time.time(), a))
        self.cost_s += time.perf_counter() - c0

    # -- control-plane lane (fabric coordinator) ---------------------------

    def control_event(self, name: str, *, key, flow_user=None,
                      **attrs) -> None:
        """One control-plane DECISION as an instantaneous span in the
        coordinator's own Perfetto lane (``ctl.*`` names, ``ctl: True``
        attr — the export routes these to a ``control-plane`` process).

        ``key`` is the decision's DURABLE identity: the journal record's
        ``seq`` for coordinator-originated decisions (spawn / drain /
        drain_done / revoke / planner epochs — journaled exactly once),
        or ``(host, src_off)`` for transcribed worker acks (drop/fence —
        a restarted coordinator re-reads a stale ack and re-journals it
        under a NEW seq, but the worker-WAL byte offset it came from
        never changes).  Same discipline as the run/user/epoch ids: a
        coordinator SIGKILL + replay re-emits identical span ids and the
        merge dedupes, so the control timeline survives the kill.

        ``flow_user``: the user this decision acts on — the Chrome
        export draws a flow arrow from this span to that user's trace
        (fence/migrate decisions visibly thread into the session they
        moved)."""
        if not self.enabled:
            return
        c0 = time.perf_counter()
        key = key if isinstance(key, tuple) else (key,)
        a = {"ctl": True}
        if flow_user is not None:
            a["flow_user"] = str(flow_user)
        a.update(attrs)
        now = time.time()
        ctx = self._child_ctx(name, self.run_ctx, ("ctl", name) + key)
        self._emit(self._span_rec(ctx, self.run_ctx, name, now, now, a))
        self.cost_s += time.perf_counter() - c0

    # -- transcription (fabric coordinator) --------------------------------

    def transcribe(self, rec: dict, *, host: str | None = None) -> None:
        """Re-emit a span record tailed from another host's span WAL into
        this tracer's sink (the coordinator merging worker spans the way
        it transcribes event WALs).  At-least-once is fine: ids are
        deterministic and the merge dedupes."""
        if not self.enabled or rec.get("ev") != "span":
            return
        rec = dict(rec)
        if host is not None and "host" not in rec:
            rec["host"] = host
        self._emit(rec)

    # -- lifecycle ---------------------------------------------------------

    def close(self, **attrs) -> None:
        """Write the run span (covering the tracer's lifetime) plus any
        still-open user spans (flagged ``open``: failed users whose close
        never came), then close the sink."""
        if self.enabled:
            with self._lock:
                leftovers = list(self._open_users.items())
                self._open_users.clear()
            for _sid, (ctx, t0, a) in leftovers:
                self._emit(self._span_rec(ctx, self.run_ctx, "user", t0,
                                          time.time(),
                                          {**a, "open": True}))
            self._emit(self._span_rec(
                self.run_ctx, None, "run", self._run_t0, time.time(),
                {"run_id": self.run_id, **attrs}))
        self._writer.close()

    def __enter__(self) -> "Tracer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


#: the shared no-op tracer (``--no-trace``, sequential drivers, tests
#: that don't care) — every call short-circuits on ``enabled``
NULL_TRACER = Tracer(None, enabled=False)


@contextlib.contextmanager
def device_trace(trace_dir: str | None):
    """``jax.profiler.trace`` when a directory is given; no-op otherwise
    (moved from ``utils.profiling.trace``; that alias remains)."""
    if not trace_dir:
        yield
        return
    import jax

    with jax.profiler.trace(trace_dir):
        yield
