"""Quadrant geometry and label codecs.

The reference contains *two* subtly different arousal/valence → quadrant
mappings; both are reproduced here exactly and documented side by side
(SURVEY.md §7 step 1):

- **AMG variant** (``amg_test.py:69-78``) — boundary-asymmetric::

      Q1:  a >= 0 and v >= 0
      Q2:  a >  0 and v <  0
      Q3:  a <= 0 and v <= 0
      Q4:  a <  0 and v >  0

  Axis points resolve as: (a=0, v<0) → Q3, (a>0, v=0) → Q1, (a=0, v>0) → Q1,
  (a<0, v=0) → Q3.

- **DEAM variant** (``deam_classifier.py:90-97``) — half-open on arousal::

      Q1:  a >= 0 and v >= 0
      Q2:  a >= 0 and v <  0
      Q3:  a <  0 and v <  0
      Q4:  a <  0 and v >= 0

Note the reference's quadrant naming is nonstandard (its "valence" column is
the first ``song_label`` component and quadrants rotate clockwise from Q1);
we replicate the observed predicate order verbatim rather than re-deriving
from circumplex convention.

All functions are pure, vectorized, and jit-safe (``jnp`` ops only), with
numpy twins for host-side dataframe work.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from consensus_entropy_tpu.config import NUM_CLASSES, QUADRANT_TO_CLASS


def quadrant_amg(arousal, valence):
    """AMG-variant quadrant as int class (Q1..Q4 → 0..3), jit-safe.

    Matches ``amg_test.py:69-78`` exactly, including boundary behavior.
    """
    a = jnp.asarray(arousal)
    v = jnp.asarray(valence)
    q1 = (a >= 0) & (v >= 0)
    q2 = (a > 0) & (v < 0)
    q3 = (a <= 0) & (v <= 0)
    # Q4 = complement: a < 0 and v > 0
    return jnp.where(q1, 0, jnp.where(q2, 1, jnp.where(q3, 2, 3))).astype(jnp.int32)


def quadrant_deam(arousal, valence):
    """DEAM-variant quadrant as int class (Q1..Q4 → 0..3), jit-safe.

    Matches ``deam_classifier.py:90-97`` exactly.
    """
    a = jnp.asarray(arousal)
    v = jnp.asarray(valence)
    q1 = (a >= 0) & (v >= 0)
    q2 = (a >= 0) & (v < 0)
    q3 = (a < 0) & (v < 0)
    return jnp.where(q1, 0, jnp.where(q2, 1, jnp.where(q3, 2, 3))).astype(jnp.int32)


def quadrant_amg_np(arousal, valence) -> np.ndarray:
    """Numpy twin of :func:`quadrant_amg` for host dataframe pipelines."""
    a = np.asarray(arousal)
    v = np.asarray(valence)
    q1 = (a >= 0) & (v >= 0)
    q2 = (a > 0) & (v < 0)
    q3 = (a <= 0) & (v <= 0)
    return np.where(q1, 0, np.where(q2, 1, np.where(q3, 2, 3))).astype(np.int32)


def quadrant_deam_np(arousal, valence) -> np.ndarray:
    """Numpy twin of :func:`quadrant_deam`."""
    a = np.asarray(arousal)
    v = np.asarray(valence)
    q1 = (a >= 0) & (v >= 0)
    q2 = (a >= 0) & (v < 0)
    q3 = (a < 0) & (v < 0)
    return np.where(q1, 0, np.where(q2, 1, np.where(q3, 2, 3))).astype(np.int32)


def class_to_name(c: int) -> str:
    return f"Q{int(c) + 1}"


def names_to_classes(names) -> np.ndarray:
    """Vectorized 'Q1'..'Q4' → 0..3 (codec at ``amg_test.py:54``)."""
    return np.asarray([QUADRANT_TO_CLASS[n] for n in names], dtype=np.int32)


def one_hot(classes, num_classes: int = NUM_CLASSES):
    """One-hot targets as float32 (``short_cnn.py:356-359`` uses unit rows;
    the CNN trains with BCE on these)."""
    c = jnp.asarray(classes)
    return (c[..., None] == jnp.arange(num_classes)).astype(jnp.float32)


def one_hot_np(classes, num_classes: int = NUM_CLASSES) -> np.ndarray:
    c = np.asarray(classes)
    return (c[..., None] == np.arange(num_classes)).astype(np.float32)
