"""Deterministic fault injection at named pipeline boundaries.

Every recovery path in the framework — two-phase checkpoint recovery,
last-good rollback, member quarantine, transient retry — is exercised by
injecting faults at the exact boundaries where real runs die: checkpoint
writes, member retrain/predict calls, pool scoring, state commits, and
multihost barriers.  The injector is:

- **deterministic**: rules fire on the Nth hit of a point (a per-point
  counter, thread-safe — checkpoint writes run on the AsyncCheckpointer
  thread), and corruption flips fixed byte positions; a faulted run is
  exactly reproducible.
- **zero-overhead when inactive**: every instrumented call site costs one
  module-attribute check when no injector is installed.
- **env/config-activated**: tests install rules via the :func:`inject`
  context manager; operators can activate via ``CETPU_FAULTS`` (e.g.
  ``CETPU_FAULTS="checkpoint.write:kill@3,member.predict:corrupt@1"``)
  to drill recovery on a real deployment.

Fault actions model distinct failure species:

- ``kill`` raises :class:`InjectedKill` (a ``BaseException``) — simulated
  process death; no ``except Exception`` handler (quarantine, retry) may
  absorb it, exactly like SIGKILL at that boundary.
- ``raise`` raises :class:`InjectedFault` — a member-level error that the
  quarantine machinery is expected to absorb.
- ``transient`` raises :class:`TransientFault` — a transient device/RPC
  error that bounded backoff retry is expected to absorb.
- ``corrupt`` mutates the payload passed to :func:`fire`: a file path gets
  its last byte flipped in place (bit-rot: breaks the checkpoint CRC and
  pickle STOP opcode), an ndarray gets its first row set to NaN
  (degenerate member output).
- ``delay`` sleeps ``delay_s`` (slow-I/O / straggler simulation).
- ``stall`` holds the hit for ``stall_s`` seconds — the GRAY-failure
  species: the process is alive (heartbeats keep flowing from their own
  thread) but the guarded operation wedges.  ``stall=inf`` hangs until
  the process is killed, the hung-but-alive worker every lease-based
  failure detector is blind to.
- ``slow`` multiplies the guarded operation's WALL TIME by
  ``slow_factor`` — sticky for the rule's hit window: :func:`fire`
  records the factor and the instrumented site calls :func:`slow_hold`
  with the operation's measured elapsed time AFTER it completes, which
  sleeps ``elapsed × (factor - 1)``.  Unlike ``delay`` (a fixed sleep),
  ``slow`` scales with the real work, so a 20x-slow host stays
  proportionally slow across mixed workloads — and unlike ``stall`` it
  never blocks progress, only throughput: every journaled value is
  untouched, so parity drills bind bit-identically.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time

import numpy as np

#: The named fault points threaded through the framework.  Each maps to one
#: instrumented boundary (see README "Failure handling" for the site list).
FAULT_POINTS = frozenset({
    "checkpoint.write",   # utils.checkpoint.save_variables / host pickles
    "member.retrain",     # Committee.update_host / retrain_cnns
    "member.predict",     # Committee.pool_probs per-member scoring
    "pool.score",         # ALLoop score phase (whole-pool probs table)
    "state.save",         # al.state.ALState.save (the commit point)
    "multihost.sync",     # parallel.multihost.sync barriers
    # serve-layer boundaries (the crash-safe-serving fault domain): a kill
    # at any of these must lose no submitted user — the admission journal
    # replays the queue/in-flight set on restart (serve.journal)
    "serve.admit",           # FleetServer slot refill, pre-engine-admit
    "serve.journal.append",  # admission-journal WAL append, pre-fsync
    "serve.dispatch",        # stacked/per-user device scoring dispatch
    "serve.collect",         # completion collection, pre-finish-journal
    # multi-host fabric boundaries: a kill at any of these must lose no
    # user — the coordinator's journal replay + lease failover re-route
    # every in-flight/queued user to a surviving host (serve.fabric)
    "fabric.assign",         # coordinator routing, pre-assign-journal
    "fabric.lease",          # worker heartbeat, pre-lease-file-write
    "fabric.compact",        # journal compaction (checkpoint + truncate
                             # stages — a kill between the two renames
                             # must replay idempotently)
    "fabric.spawn",          # elastic autoscaler, pre-spawn-journal (a
                             # kill here leaves no spawn record: the
                             # restart re-decides from the same state)
    "fabric.drain",          # scale-down decision, pre-drain-journal (a
                             # kill here leaves no drain record: the
                             # restart keeps the full fleet and the
                             # low-water clock restarts)
    "fabric.migrate.fence",  # in-flight migration, pre-fence-journal (a
                             # kill here re-reads the worker's fence ack
                             # as cursor-only: the restart re-places the
                             # user from the journal alone)
    "fabric.migrate.commit", # in-flight migration, post-fence pre-assign
                             # (a kill between fence and commit replays
                             # to exactly ONE owner: the fenced user's
                             # last assignment decides, and the restart
                             # re-routes it before any worker runs it)
    "fabric.remedy",         # remediation decision, pre-remedy-journal
                             # (drain-for-rebalance / fence-deadline
                             # fallback — a kill here leaves no record:
                             # the restart re-detects the condition and
                             # re-derives the identical action sequence;
                             # every move stays ack-gated, so no user is
                             # ever double-moved)
    # acquisition-subsystem boundaries (the acquire registry's fault
    # domain): the qbdc dropout-mask sampler — mask keys fold from the AL
    # iteration seed, so a kill here must resume bit-identically (same
    # masks, same consensus) from checkpoint/journal state
    "acquire.qbdc.masks",    # Committee.qbdc_pool_probs, pre-mask-sampling
    # filesystem-seam boundaries (resilience.io): the disk-fault species
    # below the process boundary — every journal/WAL/feed/lease/ckpt
    # write routes through the seam, so these drill the BYTES themselves.
    # The seam translates a ``raise`` action into the matching OSError
    # (or drops the fsync); ``kill`` still dies at the boundary.  Seam
    # calls carry member= context (wal/compact/lease/workspace) for
    # per-family targeting.
    "io.write.short",        # half the payload lands, then the action
                             # fires (short-write-then-SIGKILL: the torn
                             # frame must replay as never-written)
    "io.write.enospc",       # raise → OSError(ENOSPC) before any byte
    "io.write.eio",          # raise → OSError(EIO) before any byte
    "io.fsync",              # raise → fsync silently DROPPED (lying
                             # disk); kill → death at the barrier
    "io.rename",             # raise → the atomic-rename commit point
                             # fails as EIO (tmp sibling left for the
                             # caller's cleanup path)
    # coordinator fencing-epoch claim (serve.fabric): fires before the
    # epoch record journals — a kill here dies unclaimed, and the
    # restart re-derives the SAME epoch (correct: no feed line stamped
    # with it ever reached a worker)
    "fabric.epoch",
    # gray-failure boundaries (the slow-not-dead fault domain): the
    # escalation-ladder decision point and the feed-read seam — the two
    # places PR 20 adds that earlier kill matrices never exercised
    "fabric.gray",           # gray-ladder rung transition, pre-probation-
                             # journal (a kill here leaves no record: the
                             # restart re-times the suspicion from the
                             # same peer-relative evidence and replays to
                             # the same rung)
    "serve.feed.poll",       # JsonlTail.poll — a stall here models a
                             # LAGGING TAIL: the reader is alive but its
                             # view of the feed/WAL goes stale, the gray
                             # symptom the append-age detector catches
})

ACTIONS = ("kill", "raise", "transient", "corrupt", "delay", "stall",
           "slow")


class InjectedFault(Exception):
    """A recoverable injected member/IO failure (quarantine paths)."""


class TransientFault(InjectedFault):
    """An injected transient device/RPC error (retry-with-backoff paths)."""


class InjectedKill(BaseException):
    """Simulated process death.  Derives from ``BaseException`` so no
    ``except Exception`` recovery handler can absorb it — the run dies at
    the boundary, exactly like SIGKILL, and only a fresh process's resume
    path may bring the workload back."""


@dataclasses.dataclass
class FaultRule:
    """Fire ``action`` at hits ``[at, at + times)`` of ``point``.

    ``at`` is 1-based over the hit counter; ``times=-1`` fires forever from
    ``at`` on.  ``member`` restricts the rule to fault-point invocations
    carrying that ``member=`` context (per-member targeting for quarantine
    and fleet-eviction tests) — and the rule then counts hits on the
    (point, member) pair, not the global point, so ``at=2`` means "that
    member's second hit" regardless of how many other members (or other
    users' committees, in a fleet cohort) hit the point in between."""

    point: str
    action: str
    at: int = 1
    times: int = 1
    delay_s: float = 0.01
    member: str | None = None
    #: ``stall`` hold in seconds; ``float("inf")`` hangs until killed
    stall_s: float = 1.0
    #: ``slow`` wall-time multiplier honored by :func:`slow_hold`
    slow_factor: float = 2.0

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {self.point!r} "
                             f"(have {sorted(FAULT_POINTS)})")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r} "
                             f"(have {ACTIONS})")
        if self.at < 1:
            raise ValueError(f"at must be >= 1 (1-based hit), got {self.at}")
        if self.stall_s < 0:
            raise ValueError(f"stall_s must be >= 0, got {self.stall_s}")
        if self.slow_factor < 1:
            raise ValueError("slow_factor must be >= 1 (a multiplier on "
                             f"the guarded op's wall), got {self.slow_factor}")

    def matches(self, hit: int, ctx: dict) -> bool:
        if self.member is not None and ctx.get("member") != self.member:
            return False
        if hit < self.at:
            return False
        return self.times < 0 or hit < self.at + self.times


def _corrupt_file(path: str) -> None:
    """Flip the last byte in place — deterministic bit-rot.  The last byte
    sits in the checkpoint payload (CRC-covered) and is a pickle's STOP
    opcode, so both formats fail loudly on the next load."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            return
        f.seek(size - 1)
        byte = f.read(1)
        f.seek(size - 1)
        f.write(bytes([byte[0] ^ 0xFF]))


class FaultInjector:
    """Rule store + per-point hit counters.  ``seed`` feeds any stochastic
    corruption (reserved; the default corruptions are position-fixed so
    faulted runs replay bit-identically)."""

    def __init__(self, rules, *, seed: int = 0):
        self.rules = [r if isinstance(r, FaultRule) else FaultRule(**r)
                      for r in rules]
        self.hits: dict[str, int] = {}
        #: (point, member) hit counters — member-filtered rules index these
        #: so their ``at`` window is stable under fleet interleaving
        self.member_hits: dict[tuple, int] = {}
        self.fired: list[dict] = []  # (point, action, hit) audit trail
        self.rng = np.random.default_rng(seed)
        self._lock = threading.Lock()
        #: (thread id, point) -> pending slow factor, armed by a matched
        #: ``slow`` rule in :meth:`fire` and consumed by the site's
        #: :meth:`slow_hold` after the guarded op completes.  Thread-keyed
        #: so one thread's slow dispatch never stretches a sibling's.
        self._slow_pending: dict[tuple, float] = {}

    def fire(self, point: str, payload=None, **ctx):
        with self._lock:
            hit = self.hits.get(point, 0) + 1
            self.hits[point] = hit
            mhit = None
            if "member" in ctx:
                mkey = (point, ctx["member"])
                mhit = self.member_hits.get(mkey, 0) + 1
                self.member_hits[mkey] = mhit
            todo = [r for r in self.rules if r.point == point
                    and r.matches(hit if r.member is None else mhit, ctx)]
            for r in todo:
                self.fired.append({"point": point, "action": r.action,
                                   "hit": hit, **ctx})
                if r.action == "slow":
                    skey = (threading.get_ident(), point)
                    self._slow_pending[skey] = max(
                        self._slow_pending.get(skey, 1.0), r.slow_factor)
        for r in todo:
            where = f"{point} hit {hit}" + (
                f" ({ctx['member']})" if "member" in ctx else "")
            if r.action == "kill":
                raise InjectedKill(f"injected kill at {where}")
            if r.action == "raise":
                raise InjectedFault(f"injected fault at {where}")
            if r.action == "transient":
                raise TransientFault(f"injected transient error at {where}")
            if r.action == "delay":
                time.sleep(r.delay_s)
            elif r.action == "stall":
                # the gray hold: the hit wedges here while the rest of
                # the process (heartbeat thread, intake thread) runs on
                while r.stall_s == float("inf"):
                    time.sleep(3600)
                time.sleep(r.stall_s)
            elif r.action == "corrupt":
                payload = self._corrupt(payload, where)
        return payload

    def slow_hold(self, point: str, elapsed_s: float) -> None:
        """Honor a pending ``slow`` factor armed by this thread's last
        :meth:`fire` of ``point``: sleep ``elapsed × (factor - 1)`` so
        the guarded operation's total wall is ``elapsed × factor``."""
        with self._lock:
            factor = self._slow_pending.pop(
                (threading.get_ident(), point), None)
        if factor is not None and factor > 1.0 and elapsed_s > 0:
            time.sleep(elapsed_s * (factor - 1.0))

    def _corrupt(self, payload, where: str):
        if isinstance(payload, (str, os.PathLike)):
            _corrupt_file(os.fspath(payload))
            return payload
        if isinstance(payload, np.ndarray):
            out = payload.astype(np.float64 if payload.dtype.kind != "f"
                                 else payload.dtype, copy=True)
            out[(0,) * max(out.ndim - 1, 0)] = np.nan  # first row → NaN
            return out
        raise InjectedFault(f"injected corruption at {where} "
                            f"(payload {type(payload).__name__} is not "
                            "corruptible; treating as a hard fault)")


_injector: FaultInjector | None = None


def install(injector: FaultInjector) -> None:
    global _injector
    _injector = injector


def uninstall() -> None:
    global _injector
    _injector = None


def active() -> FaultInjector | None:
    return _injector


def fire(point: str, payload=None, **ctx):
    """The instrumented-site hook: no-op (returns ``payload`` unchanged)
    unless an injector is installed and a rule matches this hit."""
    inj = _injector
    if inj is None:
        return payload
    return inj.fire(point, payload=payload, **ctx)


def slow_hold(point: str, elapsed_s: float) -> None:
    """The ``slow``-action honor hook: instrumented sites bracket their
    guarded operation with a perf-counter and call this with the measured
    elapsed seconds — a pending factor (armed by this thread's preceding
    :func:`fire` of the same point) stretches the op to ``elapsed ×
    factor`` total wall.  No-op (one attribute check) when no injector is
    installed or no ``slow`` rule matched the hit."""
    inj = _injector
    if inj is not None:
        inj.slow_hold(point, elapsed_s)


@contextlib.contextmanager
def inject(*rules, seed: int = 0):
    """Install an injector for the block; yields it (``.fired`` is the
    audit trail).  Nested installs are not supported — the innermost wins
    and the previous injector is restored on exit."""
    prev = _injector
    inj = FaultInjector(rules, seed=seed)
    install(inj)
    try:
        yield inj
    finally:
        install(prev) if prev is not None else uninstall()


#: ``action=value`` suffix grammar: which actions take a float value and
#: which :class:`FaultRule` field it lands in.  One table, one validated
#: parse path — adding a valued action is a row here, never a fourth
#: inline ``startswith`` branch.
_VALUED_ACTIONS = {"delay": "delay_s", "stall": "stall_s",
                   "slow": "slow_factor"}


def _parse_action(token: str) -> tuple[str, dict]:
    """Parse one ``action`` or ``action=value`` token into ``(action,
    rule-field overrides)`` with clean errors for malformed floats and
    keys that take no value.  The action NAME is still validated by
    :class:`FaultRule` (one place owns the action list)."""
    action, sep, value = token.partition("=")
    if not sep:
        return action, {}
    field = _VALUED_ACTIONS.get(action)
    if field is None:
        keys = ", ".join(f"{k}=" for k in sorted(_VALUED_ACTIONS))
        raise ValueError(f"action {action!r} takes no '=value' suffix "
                         f"(valued actions: {keys})")
    try:
        parsed = float(value)
    except ValueError:
        raise ValueError(f"malformed float {value!r} for "
                         f"{action}=") from None
    return action, {field: parsed}


def parse_spec(spec: str) -> list[FaultRule]:
    """Parse the ``CETPU_FAULTS`` grammar: comma-separated
    ``point:action[=value][@at][xTIMES]`` — e.g.
    ``checkpoint.write:kill@3,member.predict:corrupt@1x2``.  Valued
    actions (see ``_VALUED_ACTIONS``):

    - ``delay=0.5`` sleeps half a second per firing (default 0.01) —
      ``pool.score:delay=0.4@1x-1`` turns a worker into a slow host for
      straggler/drain drills without touching any journaled value.
    - ``stall=5`` holds each hit five seconds (``stall=inf`` hangs until
      killed) — the gray wedge: ``serve.dispatch:stall=5@1x-1`` is the
      hung-but-heartbeating worker.
    - ``slow=20`` multiplies the guarded op's wall 20x for the rule's
      hit window — the gray straggler, proportional to real work.
    """
    rules = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            point, rest = part.split(":", 1)
            times = 1
            if "x" in rest:
                rest, times_s = rest.rsplit("x", 1)
                times = int(times_s)
            at = 1
            if "@" in rest:
                rest, at_s = rest.split("@", 1)
                at = int(at_s)
            action, overrides = _parse_action(rest)
            rules.append(FaultRule(point=point, action=action, at=at,
                                   times=times, **overrides))
        except ValueError as e:
            raise ValueError(
                f"bad CETPU_FAULTS entry {part!r} (want "
                f"point:action[=value][@at][xTIMES]): {e}") from e
    return rules


def install_from_env(env: str = "CETPU_FAULTS") -> FaultInjector | None:
    """Activate the injector from the environment (called once at package
    import; harmless no-op when the variable is unset)."""
    spec = os.environ.get(env)
    if not spec:
        return None
    inj = FaultInjector(parse_spec(spec))
    install(inj)
    return inj


install_from_env()
