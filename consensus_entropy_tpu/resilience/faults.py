"""Deterministic fault injection at named pipeline boundaries.

Every recovery path in the framework — two-phase checkpoint recovery,
last-good rollback, member quarantine, transient retry — is exercised by
injecting faults at the exact boundaries where real runs die: checkpoint
writes, member retrain/predict calls, pool scoring, state commits, and
multihost barriers.  The injector is:

- **deterministic**: rules fire on the Nth hit of a point (a per-point
  counter, thread-safe — checkpoint writes run on the AsyncCheckpointer
  thread), and corruption flips fixed byte positions; a faulted run is
  exactly reproducible.
- **zero-overhead when inactive**: every instrumented call site costs one
  module-attribute check when no injector is installed.
- **env/config-activated**: tests install rules via the :func:`inject`
  context manager; operators can activate via ``CETPU_FAULTS`` (e.g.
  ``CETPU_FAULTS="checkpoint.write:kill@3,member.predict:corrupt@1"``)
  to drill recovery on a real deployment.

Fault actions model distinct failure species:

- ``kill`` raises :class:`InjectedKill` (a ``BaseException``) — simulated
  process death; no ``except Exception`` handler (quarantine, retry) may
  absorb it, exactly like SIGKILL at that boundary.
- ``raise`` raises :class:`InjectedFault` — a member-level error that the
  quarantine machinery is expected to absorb.
- ``transient`` raises :class:`TransientFault` — a transient device/RPC
  error that bounded backoff retry is expected to absorb.
- ``corrupt`` mutates the payload passed to :func:`fire`: a file path gets
  its last byte flipped in place (bit-rot: breaks the checkpoint CRC and
  pickle STOP opcode), an ndarray gets its first row set to NaN
  (degenerate member output).
- ``delay`` sleeps ``delay_s`` (slow-I/O / straggler simulation).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import threading
import time

import numpy as np

#: The named fault points threaded through the framework.  Each maps to one
#: instrumented boundary (see README "Failure handling" for the site list).
FAULT_POINTS = frozenset({
    "checkpoint.write",   # utils.checkpoint.save_variables / host pickles
    "member.retrain",     # Committee.update_host / retrain_cnns
    "member.predict",     # Committee.pool_probs per-member scoring
    "pool.score",         # ALLoop score phase (whole-pool probs table)
    "state.save",         # al.state.ALState.save (the commit point)
    "multihost.sync",     # parallel.multihost.sync barriers
    # serve-layer boundaries (the crash-safe-serving fault domain): a kill
    # at any of these must lose no submitted user — the admission journal
    # replays the queue/in-flight set on restart (serve.journal)
    "serve.admit",           # FleetServer slot refill, pre-engine-admit
    "serve.journal.append",  # admission-journal WAL append, pre-fsync
    "serve.dispatch",        # stacked/per-user device scoring dispatch
    "serve.collect",         # completion collection, pre-finish-journal
    # multi-host fabric boundaries: a kill at any of these must lose no
    # user — the coordinator's journal replay + lease failover re-route
    # every in-flight/queued user to a surviving host (serve.fabric)
    "fabric.assign",         # coordinator routing, pre-assign-journal
    "fabric.lease",          # worker heartbeat, pre-lease-file-write
    "fabric.compact",        # journal compaction (checkpoint + truncate
                             # stages — a kill between the two renames
                             # must replay idempotently)
    "fabric.spawn",          # elastic autoscaler, pre-spawn-journal (a
                             # kill here leaves no spawn record: the
                             # restart re-decides from the same state)
    "fabric.drain",          # scale-down decision, pre-drain-journal (a
                             # kill here leaves no drain record: the
                             # restart keeps the full fleet and the
                             # low-water clock restarts)
    "fabric.migrate.fence",  # in-flight migration, pre-fence-journal (a
                             # kill here re-reads the worker's fence ack
                             # as cursor-only: the restart re-places the
                             # user from the journal alone)
    "fabric.migrate.commit", # in-flight migration, post-fence pre-assign
                             # (a kill between fence and commit replays
                             # to exactly ONE owner: the fenced user's
                             # last assignment decides, and the restart
                             # re-routes it before any worker runs it)
    "fabric.remedy",         # remediation decision, pre-remedy-journal
                             # (drain-for-rebalance / fence-deadline
                             # fallback — a kill here leaves no record:
                             # the restart re-detects the condition and
                             # re-derives the identical action sequence;
                             # every move stays ack-gated, so no user is
                             # ever double-moved)
    # acquisition-subsystem boundaries (the acquire registry's fault
    # domain): the qbdc dropout-mask sampler — mask keys fold from the AL
    # iteration seed, so a kill here must resume bit-identically (same
    # masks, same consensus) from checkpoint/journal state
    "acquire.qbdc.masks",    # Committee.qbdc_pool_probs, pre-mask-sampling
    # filesystem-seam boundaries (resilience.io): the disk-fault species
    # below the process boundary — every journal/WAL/feed/lease/ckpt
    # write routes through the seam, so these drill the BYTES themselves.
    # The seam translates a ``raise`` action into the matching OSError
    # (or drops the fsync); ``kill`` still dies at the boundary.  Seam
    # calls carry member= context (wal/compact/lease/workspace) for
    # per-family targeting.
    "io.write.short",        # half the payload lands, then the action
                             # fires (short-write-then-SIGKILL: the torn
                             # frame must replay as never-written)
    "io.write.enospc",       # raise → OSError(ENOSPC) before any byte
    "io.write.eio",          # raise → OSError(EIO) before any byte
    "io.fsync",              # raise → fsync silently DROPPED (lying
                             # disk); kill → death at the barrier
    "io.rename",             # raise → the atomic-rename commit point
                             # fails as EIO (tmp sibling left for the
                             # caller's cleanup path)
    # coordinator fencing-epoch claim (serve.fabric): fires before the
    # epoch record journals — a kill here dies unclaimed, and the
    # restart re-derives the SAME epoch (correct: no feed line stamped
    # with it ever reached a worker)
    "fabric.epoch",
})

ACTIONS = ("kill", "raise", "transient", "corrupt", "delay")


class InjectedFault(Exception):
    """A recoverable injected member/IO failure (quarantine paths)."""


class TransientFault(InjectedFault):
    """An injected transient device/RPC error (retry-with-backoff paths)."""


class InjectedKill(BaseException):
    """Simulated process death.  Derives from ``BaseException`` so no
    ``except Exception`` recovery handler can absorb it — the run dies at
    the boundary, exactly like SIGKILL, and only a fresh process's resume
    path may bring the workload back."""


@dataclasses.dataclass
class FaultRule:
    """Fire ``action`` at hits ``[at, at + times)`` of ``point``.

    ``at`` is 1-based over the hit counter; ``times=-1`` fires forever from
    ``at`` on.  ``member`` restricts the rule to fault-point invocations
    carrying that ``member=`` context (per-member targeting for quarantine
    and fleet-eviction tests) — and the rule then counts hits on the
    (point, member) pair, not the global point, so ``at=2`` means "that
    member's second hit" regardless of how many other members (or other
    users' committees, in a fleet cohort) hit the point in between."""

    point: str
    action: str
    at: int = 1
    times: int = 1
    delay_s: float = 0.01
    member: str | None = None

    def __post_init__(self):
        if self.point not in FAULT_POINTS:
            raise ValueError(f"unknown fault point {self.point!r} "
                             f"(have {sorted(FAULT_POINTS)})")
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r} "
                             f"(have {ACTIONS})")
        if self.at < 1:
            raise ValueError(f"at must be >= 1 (1-based hit), got {self.at}")

    def matches(self, hit: int, ctx: dict) -> bool:
        if self.member is not None and ctx.get("member") != self.member:
            return False
        if hit < self.at:
            return False
        return self.times < 0 or hit < self.at + self.times


def _corrupt_file(path: str) -> None:
    """Flip the last byte in place — deterministic bit-rot.  The last byte
    sits in the checkpoint payload (CRC-covered) and is a pickle's STOP
    opcode, so both formats fail loudly on the next load."""
    with open(path, "r+b") as f:
        f.seek(0, os.SEEK_END)
        size = f.tell()
        if size == 0:
            return
        f.seek(size - 1)
        byte = f.read(1)
        f.seek(size - 1)
        f.write(bytes([byte[0] ^ 0xFF]))


class FaultInjector:
    """Rule store + per-point hit counters.  ``seed`` feeds any stochastic
    corruption (reserved; the default corruptions are position-fixed so
    faulted runs replay bit-identically)."""

    def __init__(self, rules, *, seed: int = 0):
        self.rules = [r if isinstance(r, FaultRule) else FaultRule(**r)
                      for r in rules]
        self.hits: dict[str, int] = {}
        #: (point, member) hit counters — member-filtered rules index these
        #: so their ``at`` window is stable under fleet interleaving
        self.member_hits: dict[tuple, int] = {}
        self.fired: list[dict] = []  # (point, action, hit) audit trail
        self.rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    def fire(self, point: str, payload=None, **ctx):
        with self._lock:
            hit = self.hits.get(point, 0) + 1
            self.hits[point] = hit
            mhit = None
            if "member" in ctx:
                mkey = (point, ctx["member"])
                mhit = self.member_hits.get(mkey, 0) + 1
                self.member_hits[mkey] = mhit
            todo = [r for r in self.rules if r.point == point
                    and r.matches(hit if r.member is None else mhit, ctx)]
            for r in todo:
                self.fired.append({"point": point, "action": r.action,
                                   "hit": hit, **ctx})
        for r in todo:
            where = f"{point} hit {hit}" + (
                f" ({ctx['member']})" if "member" in ctx else "")
            if r.action == "kill":
                raise InjectedKill(f"injected kill at {where}")
            if r.action == "raise":
                raise InjectedFault(f"injected fault at {where}")
            if r.action == "transient":
                raise TransientFault(f"injected transient error at {where}")
            if r.action == "delay":
                time.sleep(r.delay_s)
            elif r.action == "corrupt":
                payload = self._corrupt(payload, where)
        return payload

    def _corrupt(self, payload, where: str):
        if isinstance(payload, (str, os.PathLike)):
            _corrupt_file(os.fspath(payload))
            return payload
        if isinstance(payload, np.ndarray):
            out = payload.astype(np.float64 if payload.dtype.kind != "f"
                                 else payload.dtype, copy=True)
            out[(0,) * max(out.ndim - 1, 0)] = np.nan  # first row → NaN
            return out
        raise InjectedFault(f"injected corruption at {where} "
                            f"(payload {type(payload).__name__} is not "
                            "corruptible; treating as a hard fault)")


_injector: FaultInjector | None = None


def install(injector: FaultInjector) -> None:
    global _injector
    _injector = injector


def uninstall() -> None:
    global _injector
    _injector = None


def active() -> FaultInjector | None:
    return _injector


def fire(point: str, payload=None, **ctx):
    """The instrumented-site hook: no-op (returns ``payload`` unchanged)
    unless an injector is installed and a rule matches this hit."""
    inj = _injector
    if inj is None:
        return payload
    return inj.fire(point, payload=payload, **ctx)


@contextlib.contextmanager
def inject(*rules, seed: int = 0):
    """Install an injector for the block; yields it (``.fired`` is the
    audit trail).  Nested installs are not supported — the innermost wins
    and the previous injector is restored on exit."""
    prev = _injector
    inj = FaultInjector(rules, seed=seed)
    install(inj)
    try:
        yield inj
    finally:
        install(prev) if prev is not None else uninstall()


def parse_spec(spec: str) -> list[FaultRule]:
    """Parse the ``CETPU_FAULTS`` grammar: comma-separated
    ``point:action[@at][xTIMES]`` — e.g.
    ``checkpoint.write:kill@3,member.predict:corrupt@1x2``.  The
    ``delay`` action takes an optional duration: ``delay=0.5`` sleeps
    half a second per firing (default 0.01) — ``pool.score:delay=0.4@1x-1``
    turns a worker into a slow host for straggler/drain drills without
    touching any journaled value."""
    rules = []
    for part in filter(None, (p.strip() for p in spec.split(","))):
        try:
            point, rest = part.split(":", 1)
            times = 1
            if "x" in rest:
                rest, times_s = rest.rsplit("x", 1)
                times = int(times_s)
            at = 1
            if "@" in rest:
                rest, at_s = rest.split("@", 1)
                at = int(at_s)
            delay_s = 0.01
            if rest.startswith("delay="):
                rest, delay_s = "delay", float(rest[len("delay="):])
            rules.append(FaultRule(point=point, action=rest, at=at,
                                   times=times, delay_s=delay_s))
        except ValueError as e:
            raise ValueError(
                f"bad CETPU_FAULTS entry {part!r} (want "
                f"point:action[@at][xTIMES]): {e}") from e
    return rules


def install_from_env(env: str = "CETPU_FAULTS") -> FaultInjector | None:
    """Activate the injector from the environment (called once at package
    import; harmless no-op when the variable is unset)."""
    spec = os.environ.get(env)
    if not spec:
        return None
    inj = FaultInjector(parse_spec(spec))
    install(inj)
    return inj


install_from_env()
