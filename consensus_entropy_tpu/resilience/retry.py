"""Bounded retry with seeded, jittered exponential backoff.

Transient device/RPC errors (a TPU slice briefly unreachable over the
tunnel, a DCN hiccup mid-collective) should not kill a 46-user AL sweep
when the failed call is pure — scoring and CNN retraining both are: they
read committee state and return fresh arrays, so re-invoking them replays
the identical computation.  The AL loop wraps exactly those call sites.

The backoff is seeded (``np.random.default_rng``) so a faulted run's
timing is reproducible, and jittered so a fleet of preempted hosts does
not retry in lockstep.
"""

from __future__ import annotations

import time
from typing import Callable, TypeVar

import numpy as np

from consensus_entropy_tpu.resilience.faults import TransientFault

T = TypeVar("T")


def _transient_types() -> tuple:
    """Error types worth a bounded retry: injected transients plus the
    runtime's device/RPC error (jax.errors.JaxRuntimeError wraps
    XlaRuntimeError — what a dropped TPU tunnel or DCN RPC surfaces as)."""
    types: tuple = (TransientFault,)
    try:
        from jax.errors import JaxRuntimeError
        types += (JaxRuntimeError,)
    except ImportError:  # very old jax: fall back to the xla_client name
        try:
            from jaxlib.xla_extension import XlaRuntimeError
            types += (XlaRuntimeError,)
        except ImportError:
            pass
    return types


TRANSIENT_ERRORS: tuple = _transient_types()


def backoff_delay(attempt: int, *, base_delay: float = 0.05,
                  max_delay: float = 2.0, rng=None) -> float:
    """The backoff schedule shared by :func:`retry_transient` and the serve
    layer's re-admission queue: ``min(max_delay, base_delay * 2**attempt)``
    jittered into ``[0.5, 1.5)x`` when ``rng`` is given (seeded by the
    caller, so a faulted run's timing replays; jitter keeps a fleet of
    failures from re-admitting in lockstep)."""
    delay = min(max_delay, base_delay * (2 ** max(attempt, 0)))
    if rng is not None:
        delay *= 0.5 + rng.random()
    return delay


def retry_transient(fn: Callable[[], T], *, attempts: int = 3,
                    base_delay: float = 0.05, max_delay: float = 2.0,
                    seed: int = 0, what: str = "op",
                    on: tuple | None = None,
                    sleep: Callable[[float], None] = time.sleep) -> T:
    """Call ``fn`` up to ``attempts`` times, sleeping
    ``min(max_delay, base_delay * 2**k) * uniform(0.5, 1.5)`` between
    tries.  Only errors in ``on`` (default :data:`TRANSIENT_ERRORS`) are
    retried; anything else — including :class:`InjectedKill` — propagates
    immediately.  The final failure re-raises the last transient error.

    ``fn`` must be safe to re-invoke (pure, or idempotent up to its own
    commit point); the AL loop's scoring/retrain closures are.
    """
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    on = TRANSIENT_ERRORS if on is None else on
    rng = np.random.default_rng(seed)
    for attempt in range(attempts):
        try:
            return fn()
        except on as e:
            if attempt == attempts - 1:
                raise
            sleep(backoff_delay(attempt, base_delay=base_delay,
                                max_delay=max_delay, rng=rng))
    raise AssertionError("unreachable")  # pragma: no cover
