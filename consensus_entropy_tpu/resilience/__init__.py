"""Fault tolerance for long-running AL workloads.

The paper's committee pipeline (20 members x 46 users x 10 AL iterations)
is a long-lived stateful job; at production scale it must survive preempted
TPU slices, bit-rotted checkpoints, and degenerate committee members
without losing the run.  This package holds the three host-side pillars:

- :mod:`~consensus_entropy_tpu.resilience.faults` — a deterministic,
  seedable fault injector with named fault points threaded through the
  checkpoint / committee / scoring / multihost layers, so every recovery
  path is exercised by tier-1 tests instead of trusted on faith.
- :mod:`~consensus_entropy_tpu.resilience.retry` — bounded
  retry-with-jittered-exponential-backoff for transient device/RPC errors
  at the scoring and retrain call sites.
- :mod:`~consensus_entropy_tpu.resilience.preemption` — SIGTERM/SIGINT
  handling that finishes the in-flight iteration's two-phase commit and
  exits with a distinct, rescheduler-friendly exit code.

The fourth pillar — checkpoint integrity (CRC) with a last-good
previous-generation fallback, and committee member quarantine — lives at
its point of use (``utils.checkpoint``, ``al.state``,
``models.committee``), instrumented with this package's fault points.
"""

from consensus_entropy_tpu.resilience.faults import (  # noqa: F401
    FAULT_POINTS,
    FaultInjector,
    FaultRule,
    InjectedFault,
    InjectedKill,
    TransientFault,
    fire,
    inject,
)
from consensus_entropy_tpu.resilience.preemption import (  # noqa: F401
    EXIT_PREEMPTED,
    Preempted,
    PreemptionGuard,
)
from consensus_entropy_tpu.resilience.retry import (  # noqa: F401
    TRANSIENT_ERRORS,
    retry_transient,
)
