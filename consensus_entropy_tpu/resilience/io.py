"""The injectable filesystem seam + per-record CRC32 framing.

Every durability-critical writer in the framework — the admission
journal and per-host event WALs (:mod:`serve.journal`), the assignment
feeds, the lease heartbeats (:mod:`serve.hosts`), compaction
checkpoints, workspace DONE markers — routes its raw ``write`` /
``fsync`` / ``rename`` syscalls through this module instead of calling
them directly (enforced by the ``raw-durable-io`` lint rule).  That buys
two things:

1. **Disk-fault injection.**  Each seam call fires the matching ``io.*``
   fault point, so the existing ``CETPU_FAULTS`` grammar can drill the
   failure species real disks produce, at the exact byte boundary:

   - ``io.write.short`` — the write lands HALF the payload and then the
     fault action fires: ``kill`` models a short-write-then-SIGKILL
     (torn frame on disk), ``raise`` a short write surfaced as ``EIO``.
   - ``io.write.enospc`` / ``io.write.eio`` — a ``raise`` action is
     translated into ``OSError(ENOSPC)`` / ``OSError(EIO)`` BEFORE any
     byte lands, the errors callers must survive or die cleanly on.
   - ``io.fsync`` — a ``raise`` action silently DROPS the fsync (the
     lying-disk model: the write sits in the page cache and a power cut
     would lose it); ``kill`` dies at the barrier.
   - ``io.rename`` — a ``raise`` action fails the atomic-rename commit
     point as ``EIO``, leaving the tmp sibling for cleanup paths.

   Seam calls carry ``member=`` context (``wal`` / ``compact`` /
   ``lease`` / ``workspace``) so rules can target one write family —
   ``member``-filtered rules count hits per family, e.g. ENOSPC on the
   compaction checkpoint only, never the appends around it.

2. **Frame primitives.**  The ``w1`` record frame the journal/WAL layer
   writes (one line per record)::

       w1 <crc32 as 8 hex chars> <json payload>\\n

   The CRC covers exactly the payload bytes, so a bit flip ANYWHERE in
   a durably-written line is detected on read instead of silently
   replayed.  Files open with a framed header record ``{"wal": 2}``;
   legacy plain-JSON lines (pre-frame writers) still parse — see
   :func:`parse_frame`.  Corrupt lines are quarantined into a
   ``<path>.quarantine`` JSONL sidecar (offset + reason + raw bytes,
   base64) by the repair paths, never silently dropped.

Observability: :func:`add_listener` registers ``fn(kind, path)``
callbacks fired on every injected io fault and every quarantined
record — the fabric coordinator forwards them as ``io_fault`` /
``record_quarantined`` events.  Listener errors are swallowed: telemetry
must never turn a survivable disk fault into a new failure.
"""

from __future__ import annotations

import base64
import errno
import json
import os
import time
import zlib

from consensus_entropy_tpu.resilience import faults

try:
    import fcntl
except ImportError:  # non-POSIX: repair falls back to lock-less rewrite
    fcntl = None

#: frame version written in the header record ``{"wal": 2}`` (version 1
#: is the implicit legacy plain-JSON format, which has no header)
WAL_VERSION = 2
_MAGIC = b"w1 "
_CRC_LEN = 8  # crc32 as zero-padded hex

# -- fault/quarantine listeners (the coordinator's obs bridge) -------------

_listeners: list = []


def add_listener(fn) -> None:
    """Register ``fn(kind, path)`` for io-fault / quarantine events."""
    _listeners.append(fn)


def remove_listener(fn) -> None:
    try:
        _listeners.remove(fn)
    except ValueError:
        pass


def _notify(kind: str, path: str) -> None:
    for fn in list(_listeners):
        try:
            fn(kind, path)
        except Exception:
            pass  # observability must never amplify a disk fault


# -- the syscall seam ------------------------------------------------------


def open_append(path: str):
    """Open ``path`` for appending (the WAL writers' open)."""
    return open(path, "ab")  # cetpu: noqa[raw-durable-io] this IS the seam


def write(f, data: bytes, *, path: str, member: str = "wal") -> None:
    """Write ``data`` to handle ``f`` through the three write fault
    points (short / ENOSPC / EIO).  The short-write point flushes its
    half-payload before failing, so the torn bytes are really on disk
    for the recovery path under test to trip over."""
    try:
        faults.fire("io.write.short", member=member, path=path)
    except faults.InjectedKill:
        f.write(data[: len(data) // 2])
        f.flush()
        _notify("io.write.short", path)
        raise
    except faults.InjectedFault as e:
        f.write(data[: len(data) // 2])
        f.flush()
        _notify("io.write.short", path)
        raise OSError(errno.EIO, f"injected short write: {path}") from e
    try:
        faults.fire("io.write.enospc", member=member, path=path)
    except faults.InjectedFault as e:
        _notify("io.write.enospc", path)
        raise OSError(errno.ENOSPC,
                      f"injected ENOSPC (disk full): {path}") from e
    try:
        faults.fire("io.write.eio", member=member, path=path)
    except faults.InjectedFault as e:
        _notify("io.write.eio", path)
        raise OSError(errno.EIO, f"injected EIO: {path}") from e
    f.write(data)


def fsync(f, *, path: str, member: str = "wal") -> None:
    """The durability barrier.  An injected ``raise`` here DROPS the
    fsync silently (the lying-disk model — the caller believes the
    record is durable); everything else fsyncs for real.  A ``slow``
    rule multiplies the barrier's measured wall (the gray slow-disk
    model — every durable append pays it, so ``io.fsync:slow=F`` is the
    whole WAL path running F-times slow); a ``stall`` rule wedges the
    barrier inside :func:`~consensus_entropy_tpu.resilience.faults.fire`
    itself."""
    try:
        faults.fire("io.fsync", member=member, path=path)
    except faults.InjectedFault:
        _notify("io.fsync", path)
        return
    t0 = time.perf_counter()
    os.fsync(f.fileno())  # cetpu: noqa[raw-durable-io] this IS the seam
    faults.slow_hold("io.fsync", time.perf_counter() - t0)


def replace(src: str, dst: str, *, member: str = "wal") -> None:
    """Atomic-rename commit point (``os.replace`` through the
    ``io.rename`` fault point)."""
    try:
        faults.fire("io.rename", member=member, path=dst)
    except faults.InjectedFault as e:
        _notify("io.rename", dst)
        raise OSError(errno.EIO, f"injected rename failure: {dst}") from e
    os.replace(src, dst)  # cetpu: noqa[raw-durable-io] this IS the seam


def atomic_write(path: str, data: bytes, *, member: str = "wal") -> None:
    """Write-new-then-rename through the seam: a reader sees the old
    content or the new, never a torn file.  A surfaced ``OSError``
    (ENOSPC, EIO, rename failure) removes the tmp sibling before
    propagating — only a genuine process death (``InjectedKill`` /
    SIGKILL) can leak one, and the journal's open-time sweep reclaims
    those."""
    tmp = path + ".tmp"
    try:
        with open(tmp, "wb") as f:  # cetpu: noqa[raw-durable-io] this IS the seam
            write(f, data, path=tmp, member=member)
            f.flush()
            fsync(f, path=tmp, member=member)
        replace(tmp, path, member=member)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


# -- record framing --------------------------------------------------------


def frame_record(rec: dict) -> bytes:
    """One framed JSONL line: ``w1 <crc32:08x> <json>\\n``."""
    payload = json.dumps(rec).encode("utf-8")
    crc = zlib.crc32(payload)
    return _MAGIC + f"{crc:08x}".encode("ascii") + b" " + payload + b"\n"


def frame_header() -> bytes:
    """The framed version header a fresh WAL opens with."""
    return frame_record({"wal": WAL_VERSION})


def is_header(rec) -> bool:
    """True for the ``{"wal": N}`` version-header record (carries no
    event — readers skip it)."""
    return isinstance(rec, dict) and "wal" in rec and "event" not in rec


def parse_frame(line: bytes):
    """Parse one complete line → ``(status, rec)``.

    - ``("ok", rec)`` — a ``w1`` frame whose CRC matched.
    - ``("legacy", rec)`` — a plain-JSON line (pre-frame writer).
    - ``("corrupt", None)`` — a broken frame (bad CRC, mangled header,
      unparseable payload) or a non-JSON legacy line.  The CALLER
      decides tail-ness: a line without its newline is a torn tail
      (expected crash artifact), anything else is bit-rot.

    ``rec`` may be any JSON value; non-dict records are the caller's
    ``isinstance`` problem, exactly as before framing."""
    body = line[:-1] if line.endswith(b"\n") else line
    if body.endswith(b"\r"):
        body = body[:-1]
    if body.startswith(_MAGIC):
        crc_end = len(_MAGIC) + _CRC_LEN
        if len(body) <= crc_end or body[crc_end:crc_end + 1] != b" ":
            return ("corrupt", None)
        try:
            crc = int(body[len(_MAGIC):crc_end], 16)
        except ValueError:
            return ("corrupt", None)
        payload = body[crc_end + 1:]
        if zlib.crc32(payload) != crc:
            return ("corrupt", None)
        try:
            return ("ok", json.loads(payload.decode("utf-8")))
        except (ValueError, UnicodeDecodeError):
            return ("corrupt", None)
    try:
        return ("legacy", json.loads(body.decode("utf-8")))
    except (ValueError, UnicodeDecodeError):
        return ("corrupt", None)


# -- quarantine sidecar ----------------------------------------------------


def quarantine_path(path: str) -> str:
    return path + ".quarantine"


def quarantine_append(path: str, *, off: int, raw: bytes,
                      reason: str) -> str:
    """Append one quarantine record (offset + reason + raw bytes,
    base64) to ``<path>.quarantine``; returns the sidecar path.  One
    buffered write + fsync per record — the sidecar is an audit trail,
    never replayed, so readers AND writers of ``path`` may both append
    to it."""
    qpath = quarantine_path(path)
    rec = {"off": int(off), "len": len(raw), "reason": reason,
           "raw_b64": base64.b64encode(raw).decode("ascii")}
    with open_append(qpath) as f:
        write(f, (json.dumps(rec) + "\n").encode("utf-8"),
              path=qpath, member="quarantine")
        f.flush()
        fsync(f, path=qpath, member="quarantine")
    _notify("record_quarantined", path)
    return qpath


# -- scan / repair (the cetpu-fsck core) -----------------------------------


def scan_wal(path: str) -> dict:
    """Structural frame scan of one JSONL WAL.  Returns::

        {"path", "lines", "ok", "legacy", "corrupt": [entry...],
         "torn_tail": bool}

    where each corrupt ``entry`` is ``{"line", "off", "len", "reason"}``
    (1-based line, byte offset).  A final line missing its newline is
    reported as ``torn_tail`` (the expected crash artifact), NOT as
    corruption."""
    out = {"path": path, "lines": 0, "ok": 0, "legacy": 0,
           "corrupt": [], "torn_tail": False}
    if not os.path.exists(path):
        return out
    with open(path, "rb") as f:
        raws = f.readlines()
    off = 0
    for i, raw in enumerate(raws, 1):
        out["lines"] += 1
        if not raw.endswith(b"\n"):
            out["torn_tail"] = True  # readlines: only the last line can
            off += len(raw)
            continue
        status, _rec = parse_frame(raw)
        if status == "corrupt":
            out["corrupt"].append({"line": i, "off": off, "len": len(raw),
                                   "reason": "frame CRC/parse failure"})
        else:
            out[status if status == "legacy" else "ok"] += 1
        off += len(raw)
    return out


class WalLocked(RuntimeError):
    """The WAL's writer lock is held — a live process owns this file;
    repairing under it would race the single-writer discipline."""


def repair_wal(path: str) -> dict:
    """Drop every corrupt line (and any torn tail) out of ``path`` into
    its quarantine sidecar and rewrite the file atomically.  Refuses to
    run against a live writer (the ``<path>.lock`` flock —
    :class:`WalLocked`).  Returns ``{"dropped": n, "quarantine": path
    or None}``."""
    lockf = None
    if fcntl is not None:
        lockf = open(path + ".lock", "ab")  # cetpu: noqa[raw-durable-io] zero-byte lock sibling, never fsynced
        try:
            fcntl.flock(lockf.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
        except OSError:
            lockf.close()
            raise WalLocked(
                f"{path}: a live writer holds this WAL's lock — stop the "
                "server before repairing")
    try:
        with open(path, "rb") as f:
            raws = f.readlines()
        kept, dropped, qpath, off = [], 0, None, 0
        for raw in raws:
            torn = not raw.endswith(b"\n")
            status = parse_frame(raw)[0] if not torn else "corrupt"
            if status == "corrupt":
                qpath = quarantine_append(
                    path, off=off, raw=raw,
                    reason="torn tail" if torn else "frame CRC/parse "
                                                   "failure")
                dropped += 1
            else:
                kept.append(raw)
            off += len(raw)
        if dropped:
            atomic_write(path, b"".join(kept), member="repair")
        return {"dropped": dropped, "quarantine": qpath}
    finally:
        if lockf is not None:
            lockf.close()
