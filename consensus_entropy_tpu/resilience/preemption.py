"""Preemption-safe execution: drain the in-flight iteration, exit clean.

TPU slices are preempted with a SIGTERM and a grace window.  The AL loop's
two-phase commit already makes a SIGKILL recoverable; this module upgrades
SIGTERM/SIGINT from "recoverable crash" to "clean handoff": the handler
only sets a flag, the loop checks it at iteration boundaries (after the
iteration's checkpoint has been submitted), joins the in-flight two-phase
commit, and raises :class:`Preempted`.  Drivers catch it and exit with
:data:`EXIT_PREEMPTED` so the scheduler can tell "reschedule me" from
"this run is broken".

Multi-host: the flag is process-local (each host gets its own signal);
the loop agrees on it via ``multihost.broadcast_flag`` so every process
leaves the collective program at the same boundary — one preempted host
must not leave the others blocked in a collective.
"""

from __future__ import annotations

import signal
import threading

#: Distinct exit code for a preempted-but-cleanly-checkpointed run
#: (EX_TEMPFAIL from sysexits.h: "try again later" — rescheduler-friendly,
#: disjoint from error exits and from shells' 128+signum kill codes).
EXIT_PREEMPTED = 75


class Preempted(BaseException):
    """Raised at an iteration boundary after the in-flight two-phase
    commit has been joined.  Derives from ``BaseException`` (like
    ``KeyboardInterrupt``) so quarantine/retry handlers cannot absorb it;
    drivers catch it explicitly and exit :data:`EXIT_PREEMPTED`."""


class PreemptionGuard:
    """Context manager installing SIGTERM/SIGINT handlers that request a
    graceful stop.

    The handler is deliberately trivial (sets an ``Event``): all real work
    — finishing the iteration, joining the checkpoint commit — happens on
    the loop thread at the next boundary check.  ``request()`` triggers
    the same path programmatically (tests, external schedulers).  Signal
    installation silently degrades to programmatic-only when not on the
    main thread (``signal.signal`` raises there).
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._old: dict = {}
        self._event = threading.Event()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def request(self) -> None:
        self._event.set()

    def _handler(self, signum, frame):  # noqa: ARG002 (signal signature)
        self._event.set()

    def __enter__(self) -> "PreemptionGuard":
        for s in self._signals:
            try:
                self._old[s] = signal.signal(s, self._handler)
            except ValueError:  # not the main thread: programmatic-only
                pass
        return self

    def __exit__(self, *exc) -> None:
        for s, old in self._old.items():
            signal.signal(s, old)
        self._old.clear()
