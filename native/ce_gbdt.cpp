// Native gradient-boosted-trees core for consensus_entropy_tpu.
//
// Fills the committee's boosted slot (the reference trains
// XGBClassifier(max_depth=5) and continues boosting per AL iteration with
// its vendored class-preservation patch — amg_test.py:507,
// xgboost/sklearn.py:854-860).  xgboost is not shipped in every deployment,
// and sklearn's GradientBoostingClassifier warm-start refuses
// class-deficient batches, so this is a first-party implementation of the
// exact capability the AL loop needs: depth-limited regression trees on
// quantile-binned features, boosted under a K-class softmax objective whose
// class universe is pinned by the caller — NOT re-derived from each batch.
//
// Scope: the tree BUILD and forest PREDICT hot loops only.  Binning,
// gradients, and the boosting schedule live in Python
// (consensus_entropy_tpu/models/gbdt.py) where they are cheap and testable;
// a pure-numpy build/predict fallback exists for toolchain-less hosts.
//
// Tree layout: complete binary heap of n_nodes = 2^(max_depth+1) - 1 slots.
// feature[i] >= 0  -> internal node; rows with bin <= threshold[i] go to
//                     child 2i+1, else 2i+2.
// feature[i] == -1 -> leaf (or never-created slot); value[i] is the leaf
//                     weight (0 for never-created slots, which are
//                     unreachable by construction).
//
// Split objective (second-order, xgboost-style):
//   gain = GL^2/(HL+lambda) + GR^2/(HR+lambda) - G^2/(H+lambda)
//   leaf weight = -G/(H+lambda)
// Ties broken toward the lowest (feature, bin) pair, matching the numpy
// fallback's argmax-first semantics bit-for-bit (all accumulation in
// double, same traversal order).

#include <cmath>
#include <cstdint>
#include <cstring>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// Build one depth-limited regression tree on pre-binned features.
//   Xb:   (n, f) uint8 bin codes, row-major
//   g, h: (n,) float32 gradients / hessians
//   feature, threshold: (n_nodes,) int32 outputs (caller zero/-1 init NOT
//     required; fully written here)
//   value: (n_nodes,) double output
void ce_gbdt_build_tree(const uint8_t* Xb, int64_t n, int64_t f,
                        const float* g, const float* h, int max_depth,
                        int n_bins, double lambda, double min_child_weight,
                        double min_gain, int32_t* feature, int32_t* threshold,
                        double* value) {
  const int64_t n_nodes = ((int64_t)1 << (max_depth + 1)) - 1;
  for (int64_t i = 0; i < n_nodes; ++i) {
    feature[i] = -1;
    threshold[i] = 0;
    value[i] = 0.0;
  }
  double* G = new double[n_nodes]();
  double* H = new double[n_nodes]();
  bool* open_ = new bool[n_nodes]();
  int32_t* node_of_row = new int32_t[n];
  std::memset(node_of_row, 0, n * sizeof(int32_t));

  // Row-order scratch for the per-node histogram pass (counting sort of
  // rows by node, stable in row index).
  int64_t* order = new int64_t[n];

  {
    double sg = 0.0, sh = 0.0;
    for (int64_t i = 0; i < n; ++i) {
      sg += (double)g[i];
      sh += (double)h[i];
    }
    G[0] = sg;
    H[0] = sh;
    open_[0] = true;
  }

  // local index of each open node at the current level (-1 otherwise)
  int32_t* local = new int32_t[n_nodes];
  // previous level's histograms + local map (sibling-subtraction trick)
  double* prev_hg = nullptr;
  double* prev_hh = nullptr;
  int32_t* prev_local = new int32_t[n_nodes];

  for (int depth = 0; depth < max_depth; ++depth) {
    const int64_t lo = ((int64_t)1 << depth) - 1;
    const int64_t hi = ((int64_t)1 << (depth + 1)) - 1;
    int64_t n_act = 0;
    for (int64_t i = 0; i < n_nodes; ++i) local[i] = -1;
    for (int64_t nd = lo; nd < hi; ++nd)
      if (open_[nd]) local[nd] = (int32_t)n_act++;
    if (n_act == 0) break;

    // Histograms: (n_act, f, n_bins) of G and H, double accumulation.
    // Rows are first grouped per node (stable counting sort, so each
    // histogram cell accumulates its rows in ascending row order — the
    // exact order np.bincount uses, keeping backends bit-identical), then
    // each node's pass reads rows feature-contiguously into an
    // L2-resident (f, n_bins) slice — cache-friendly on both sides.
    //
    // Sibling subtraction: open nodes at depth >= 1 come in sibling pairs
    // (a split opens both children), and parent = left + right cell-wise,
    // so only the SMALLER child is accumulated from rows; the other is
    // derived as parent_hist - built_hist (ties build the left child).
    // Halves the expected row traffic per level; the numpy fallback does
    // the identical subtraction, keeping backends bit-identical.
    const int64_t fb = f * n_bins;
    const int64_t hsize = n_act * fb;
    double* hg = new double[hsize]();
    double* hh = new double[hsize]();
    int64_t* start = new int64_t[n_act + 1]();
    for (int64_t i = 0; i < n; ++i) {
      const int32_t lc = local[node_of_row[i]];
      if (lc >= 0) ++start[lc + 1];
    }
    for (int64_t a = 0; a < n_act; ++a) start[a + 1] += start[a];
    {
      int64_t* fill = new int64_t[n_act];
      for (int64_t a = 0; a < n_act; ++a) fill[a] = start[a];
      for (int64_t i = 0; i < n; ++i) {
        const int32_t lc = local[node_of_row[i]];
        if (lc >= 0) order[fill[lc]++] = i;
      }
      delete[] fill;
    }
    bool* direct = new bool[n_act];
    for (int64_t nd = lo; nd < hi; ++nd) {
      const int32_t lc = local[nd];
      if (lc < 0) continue;
      if (depth == 0 || prev_hg == nullptr) {
        direct[lc] = true;
        continue;
      }
      const int64_t sib = (nd & 1) ? nd + 1 : nd - 1;
      const int32_t sl = local[sib];
      const int64_t cnt = start[lc + 1] - start[lc];
      const int64_t sib_cnt = start[sl + 1] - start[sl];
      direct[lc] = cnt < sib_cnt || (cnt == sib_cnt && (nd & 1));
    }
#pragma omp parallel for schedule(dynamic)
    for (int64_t a = 0; a < n_act; ++a) {
      if (!direct[a]) continue;
      double* hga = hg + a * fb;
      double* hha = hh + a * fb;
      for (int64_t s = start[a]; s < start[a + 1]; ++s) {
        const int64_t i = order[s];
        const uint8_t* row = Xb + i * f;
        const double gi = (double)g[i], hi = (double)h[i];
        for (int64_t j = 0; j < f; ++j) {
          const int64_t at = j * n_bins + row[j];
          hga[at] += gi;
          hha[at] += hi;
        }
      }
    }
#pragma omp parallel for schedule(static)
    for (int64_t nd = lo; nd < hi; ++nd) {
      const int32_t lc = local[nd];
      if (lc < 0 || direct[lc]) continue;
      const int64_t sib = (nd & 1) ? nd + 1 : nd - 1;
      const int64_t parent = (nd - 1) / 2;
      const double* pg = prev_hg + (int64_t)prev_local[parent] * fb;
      const double* ph = prev_hh + (int64_t)prev_local[parent] * fb;
      const double* sg_ = hg + (int64_t)local[sib] * fb;
      const double* sh_ = hh + (int64_t)local[sib] * fb;
      double* dg = hg + (int64_t)lc * fb;
      double* dh = hh + (int64_t)lc * fb;
      for (int64_t k = 0; k < fb; ++k) {
        dg[k] = pg[k] - sg_[k];
        dh[k] = ph[k] - sh_[k];
      }
    }
    delete[] direct;
    delete[] start;

    // Split search per open node (first-max tie break over (feature, bin)).
#pragma omp parallel for schedule(static)
    for (int64_t nd = lo; nd < hi; ++nd) {
      const int32_t lc = local[nd];
      if (lc < 0) continue;
      const double Gt = G[nd], Ht = H[nd];
      const double parent = Gt * Gt / (Ht + lambda);
      double best_gain = -1.0 / 0.0;
      int32_t best_f = -1, best_b = 0;
      double best_gl = 0.0, best_hl = 0.0;
      for (int64_t j = 0; j < f; ++j) {
        const double* cg = hg + ((int64_t)lc * f + j) * n_bins;
        const double* ch = hh + ((int64_t)lc * f + j) * n_bins;
        double gl = 0.0, hl = 0.0;
        for (int b = 0; b < n_bins - 1; ++b) {  // last bin: all-left, skip
          gl += cg[b];
          hl += ch[b];
          const double gr = Gt - gl, hr = Ht - hl;
          if (hl < min_child_weight || hr < min_child_weight) continue;
          const double gain =
              gl * gl / (hl + lambda) + gr * gr / (hr + lambda) - parent;
          if (gain > best_gain) {
            best_gain = gain;
            best_f = (int32_t)j;
            best_b = b;
            best_gl = gl;
            best_hl = hl;
          }
        }
      }
      if (best_f >= 0 && best_gain > min_gain) {
        feature[nd] = best_f;
        threshold[nd] = best_b;
        const int64_t l = 2 * nd + 1, r = 2 * nd + 2;
        G[l] = best_gl;
        H[l] = best_hl;
        G[r] = G[nd] - best_gl;
        H[r] = H[nd] - best_hl;
        open_[l] = true;
        open_[r] = true;
      } else {
        value[nd] = -Gt / (Ht + lambda);
      }
      open_[nd] = false;
    }
    // this level's histograms become next level's parents
    delete[] prev_hg;
    delete[] prev_hh;
    prev_hg = hg;
    prev_hh = hh;
    std::memcpy(prev_local, local, n_nodes * sizeof(int32_t));

    // Partition rows of split nodes to their children.
#pragma omp parallel for schedule(static)
    for (int64_t i = 0; i < n; ++i) {
      const int32_t nd = node_of_row[i];
      if (nd >= lo && nd < hi && feature[nd] >= 0)
        node_of_row[i] = (int32_t)(
            2 * nd + 1 + (Xb[i * f + feature[nd]] > (uint8_t)threshold[nd]));
    }
  }

  // Max-depth level: every still-open node becomes a leaf.
  for (int64_t nd = 0; nd < n_nodes; ++nd) {
    if (open_[nd]) {
      value[nd] = -G[nd] / (H[nd] + lambda);
      open_[nd] = false;
    }
  }

  delete[] G;
  delete[] H;
  delete[] open_;
  delete[] node_of_row;
  delete[] local;
  delete[] order;
  delete[] prev_hg;
  delete[] prev_hh;
  delete[] prev_local;
}

// Accumulate a forest's margins:
//   margins[i, tree_class[t]] += lr * leaf_t(row i)   for every tree t.
// Trees are packed contiguously: feature/threshold (n_trees, n_nodes) int32,
// value (n_trees, n_nodes) double.  margins is (n, k) float64, caller-init.
void ce_gbdt_predict_margins(const uint8_t* Xb, int64_t n, int64_t f,
                             const int32_t* feature, const int32_t* threshold,
                             const double* value, int64_t n_trees,
                             int64_t n_nodes, const int32_t* tree_class,
                             int64_t k, double lr, double* margins) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* x = Xb + i * f;
    double* m = margins + i * k;
    for (int64_t t = 0; t < n_trees; ++t) {
      const int32_t* tf = feature + t * n_nodes;
      const int32_t* tt = threshold + t * n_nodes;
      int64_t nd = 0;
      while (tf[nd] >= 0)
        nd = 2 * nd + 1 + (x[tf[nd]] > (uint8_t)tt[nd]);
      m[tree_class[t]] += lr * value[t * n_nodes + nd];
    }
  }
}

}  // extern "C"
