// Native host-member runtime for consensus_entropy_tpu.
//
// The classic committee members (GNB, SGD-logistic) stay host-side by design
// (SURVEY.md §2: trees/tiny generative models don't map to XLA); in a real AL
// iteration their predict_proba over the pool frames (~95k rows x 260 feats
// per member) plus the frame->song groupby-mean is the host hot loop that
// runs concurrently with the TPU graph (SURVEY.md §7 hard part 6).  The
// reference leaves all of this to single-threaded sklearn inside a Python
// member loop (amg_test.py:428-438); here it is an OpenMP-threaded C++ core
// loaded via ctypes (no pybind11 in this image).
//
// Numerical contracts (validated against sklearn in tests/test_native.py):
//  - ce_linear_predict_proba mode=0: softmax over classes (multinomial).
//    mode=1: per-class sigmoid, L1-normalized rows — sklearn's
//    one-vs-all SGDClassifier(loss='log_loss') predict_proba semantics.
//  - ce_gnb_predict_proba: GaussianNB joint log-likelihood
//    (log prior + sum of Gaussian log pdfs, double accumulation) with
//    exp(jll - logsumexp(jll)) normalization.
//  - ce_segment_mean: mean over contiguous runs of equal segment ids —
//    pandas groupby('s_id').mean() on a sorted index (amg_test.py:437).
//  - ce_row_entropy: scipy.stats.entropy semantics (normalize rows, nats,
//    0*log0 = 0).
//
// Build: g++ -O3 -march=native -fopenmp -shared -fPIC (see
// consensus_entropy_tpu/native/build.py; a pure-numpy fallback exists).

#include <cmath>
#include <cstdint>

#if defined(_OPENMP)
#include <omp.h>
#endif

extern "C" {

// out (n, c) <- row-softmax / row-normalized-sigmoid of X (n, f) @ W (f, c) + b (c)
void ce_linear_predict_proba(const float* X, int64_t n, int64_t f,
                             const float* W, const float* b, int64_t c,
                             int mode, float* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const float* x = X + i * f;
    float* o = out + i * c;
    // logits, double accumulation for sklearn-grade parity
    for (int64_t k = 0; k < c; ++k) {
      double acc = b[k];
      const float* w = W + k;  // W is (f, c) row-major: stride c per feature
      for (int64_t j = 0; j < f; ++j) acc += (double)x[j] * (double)w[j * c];
      o[k] = (float)acc;
    }
    if (mode == 0) {  // multinomial softmax
      float m = o[0];
      for (int64_t k = 1; k < c; ++k) m = o[k] > m ? o[k] : m;
      double s = 0.0;
      for (int64_t k = 0; k < c; ++k) {
        double e = std::exp((double)o[k] - (double)m);
        o[k] = (float)e;
        s += e;
      }
      for (int64_t k = 0; k < c; ++k) o[k] = (float)((double)o[k] / s);
    } else {  // one-vs-all sigmoids, L1-normalized (sklearn OvA)
      double s = 0.0;
      for (int64_t k = 0; k < c; ++k) {
        double p = 1.0 / (1.0 + std::exp(-(double)o[k]));
        o[k] = (float)p;
        s += p;
      }
      if (s > 0.0)
        for (int64_t k = 0; k < c; ++k) o[k] = (float)((double)o[k] / s);
      else
        for (int64_t k = 0; k < c; ++k) o[k] = (float)(1.0 / (double)c);
    }
  }
}

// GaussianNB: out (n, c) posterior from theta/var (c, f) and log_prior (c).
void ce_gnb_predict_proba(const float* X, int64_t n, int64_t f,
                          const double* theta, const double* var,
                          const double* log_prior, int64_t c, float* out) {
  // Per-class constant: log_prior - 0.5 * sum(log(2*pi*var))
  double* cls_const = new double[c];
  for (int64_t k = 0; k < c; ++k) {
    double s = 0.0;
    for (int64_t j = 0; j < f; ++j)
      s += std::log(2.0 * M_PI * var[k * f + j]);
    cls_const[k] = log_prior[k] - 0.5 * s;
  }
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const float* x = X + i * f;
    float* o = out + i * c;
    double jll[64];  // c <= 64 enforced by the wrapper
    double m = -1e308;
    for (int64_t k = 0; k < c; ++k) {
      const double* th = theta + k * f;
      const double* va = var + k * f;
      double s = 0.0;
      for (int64_t j = 0; j < f; ++j) {
        double d = (double)x[j] - th[j];
        s += d * d / va[j];
      }
      jll[k] = cls_const[k] - 0.5 * s;
      if (jll[k] > m) m = jll[k];
    }
    double s = 0.0;
    for (int64_t k = 0; k < c; ++k) {
      jll[k] = std::exp(jll[k] - m);
      s += jll[k];
    }
    for (int64_t k = 0; k < c; ++k) o[k] = (float)(jll[k] / s);
  }
  delete[] cls_const;
}

// Mean over contiguous equal-id runs. seg_starts (n_segs + 1) gives row
// offsets of each segment (computed host-side from the sorted id column).
void ce_segment_mean(const float* X, int64_t n, int64_t c,
                     const int64_t* seg_starts, int64_t n_segs, float* out) {
#pragma omp parallel for schedule(static)
  for (int64_t s = 0; s < n_segs; ++s) {
    int64_t lo = seg_starts[s], hi = seg_starts[s + 1];
    float* o = out + s * c;
    for (int64_t k = 0; k < c; ++k) {
      double acc = 0.0;
      for (int64_t i = lo; i < hi; ++i) acc += X[i * c + k];
      o[k] = hi > lo ? (float)(acc / (double)(hi - lo)) : 0.0f;
    }
  }
  (void)n;
}

// scipy.stats.entropy per row: normalize, -sum(p log p) in nats.
void ce_row_entropy(const float* P, int64_t n, int64_t c, float* out) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const float* p = P + i * c;
    double tot = 0.0;
    for (int64_t k = 0; k < c; ++k) tot += p[k];
    double h = 0.0;
    for (int64_t k = 0; k < c; ++k) {
      if (p[k] > 0.0f) {
        double q = (double)p[k] / tot;
        h -= q * std::log(q);
      }
    }
    out[i] = (float)h;
  }
}

int ce_num_threads() {
#if defined(_OPENMP)
  return omp_get_max_threads();
#else
  return 1;
#endif
}

}  // extern "C"
