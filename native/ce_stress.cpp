// Concurrency stress driver for the native host runtime — run by
// scripts/race_check.sh (SURVEY.md §5: the reference is single-threaded so
// race detection was N/A; this framework's C++ core is OpenMP-parallel and
// gets checked).
//
// Two modes (GCC's libgomp is not TSAN-instrumented, so its barriers are
// invisible to TSAN and every post-region read would be a false positive —
// the standard GCC+TSAN caveat.  Each mode targets what it can verify
// soundly):
//
//   tsan         — OMP_NUM_THREADS=1 (no libgomp parallelism); several
//                  pthreads call every kernel CONCURRENTLY on shared
//                  read-only inputs and private outputs.  TSAN then detects
//                  any hidden shared mutable state across calls (static
//                  buffers, unprotected globals) — the reentrancy contract
//                  the AL driver relies on.
//   determinism  — oversubscribed OpenMP (threads > cores): every kernel
//                  runs twice and outputs are compared BYTEWISE; a data
//                  race in a parallel region (overlapping writes, order-
//                  dependent accumulation) shows up as nondeterminism.
//
// Exit 0 = clean.  TSAN reports flip the exit code via halt_on_error.

#include <algorithm>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>

#if defined(_OPENMP)
#include <omp.h>
#endif
#include <vector>

extern "C" {
void ce_linear_predict_proba(const float*, int64_t, int64_t, const float*,
                             const float*, int64_t, int, float*);
void ce_gnb_predict_proba(const float*, int64_t, int64_t, const double*,
                          const double*, const double*, int64_t, float*);
void ce_segment_mean(const float*, int64_t, int64_t, const int64_t*, int64_t,
                     float*);
void ce_row_entropy(const float*, int64_t, int64_t, float*);
void ce_gbdt_build_tree(const uint8_t*, int64_t, int64_t, const float*,
                        const float*, int, int, double, double, double,
                        int32_t*, int32_t*, double*);
void ce_gbdt_predict_margins(const uint8_t*, int64_t, int64_t, const int32_t*,
                             const int32_t*, const double*, int64_t, int64_t,
                             const int32_t*, int64_t, double, double*);
}

namespace {

constexpr int64_t N = 4096, F = 32, C = 4, N_BINS = 32;
constexpr int MAX_DEPTH = 5;
constexpr int64_t N_NODES = ((int64_t)1 << (MAX_DEPTH + 1)) - 1;

uint64_t rng_state = 88172645463325252ull;
double frand() {
  rng_state ^= rng_state << 13;
  rng_state ^= rng_state >> 7;
  rng_state ^= rng_state << 17;
  return (double)(rng_state % 10000) / 5000.0 - 1.0;
}

struct Inputs {
  std::vector<float> X, W, b, g, h;
  std::vector<double> theta, var, log_prior;
  std::vector<uint8_t> Xb;
  std::vector<int64_t> starts;

  Inputs() {
    X.resize(N * F);
    W.resize(F * C);
    b.resize(C);
    g.resize(N);
    h.resize(N);
    theta.resize(C * F);
    var.resize(C * F);
    log_prior.assign(C, -1.4);
    Xb.resize(N * F);
    for (auto& v : X) v = (float)frand();
    for (auto& v : W) v = (float)frand();
    for (auto& v : b) v = (float)frand();
    for (auto& v : g) v = (float)frand();
    for (auto& v : h) v = (float)(frand() * frand() + 0.1);
    for (auto& v : theta) v = frand();
    // strictly positive: log(var) feeds the GNB class constant — a
    // negative draw would NaN the whole output and make the bytewise
    // comparison vacuous for that kernel
    for (auto& v : var) v = frand() * frand() * 0.4 + 0.5;
    for (auto& v : Xb) v = (uint8_t)(rng_state % N_BINS), frand();
    for (int64_t i = 0; i <= N; i += 64) starts.push_back(i);
  }
};

struct Outputs {
  std::vector<float> probs, gnb, seg, ent;
  std::vector<int32_t> feat, thr, tree_class;
  std::vector<double> val, margins;

  Outputs()
      : probs(N * C), gnb(N * C), seg(64 * C), ent(N),
        feat(8 * N_NODES), thr(8 * N_NODES), tree_class(8),
        val(8 * N_NODES), margins(N * C, 0.0) {}
};

void run_all(const Inputs& in, Outputs& out) {
  ce_linear_predict_proba(in.X.data(), N, F, in.W.data(), in.b.data(), C, 0,
                          out.probs.data());
  ce_gnb_predict_proba(in.X.data(), N, F, in.theta.data(), in.var.data(),
                       in.log_prior.data(), C, out.gnb.data());
  ce_segment_mean(out.probs.data(), N, C, in.starts.data(),
                  (int64_t)in.starts.size() - 1, out.seg.data());
  ce_row_entropy(out.probs.data(), N, C, out.ent.data());
  for (int64_t t = 0; t < 8; ++t) {
    ce_gbdt_build_tree(in.Xb.data(), N, F, in.g.data(), in.h.data(),
                       MAX_DEPTH, (int)N_BINS, 1.0, 1.0, 0.0,
                       out.feat.data() + t * N_NODES,
                       out.thr.data() + t * N_NODES,
                       out.val.data() + t * N_NODES);
    out.tree_class[t] = (int32_t)(t % C);
  }
  std::fill(out.margins.begin(), out.margins.end(), 0.0);
  ce_gbdt_predict_margins(in.Xb.data(), N, F, out.feat.data(),
                          out.thr.data(), out.val.data(), 8, N_NODES,
                          out.tree_class.data(), C, 0.3,
                          out.margins.data());
}

template <typename T>
bool same(const std::vector<T>& a, const std::vector<T>& b) {
  return std::memcmp(a.data(), b.data(), a.size() * sizeof(T)) == 0;
}

}  // namespace

int main(int argc, char** argv) {
  const std::string mode = argc > 1 ? argv[1] : "determinism";
  Inputs in;

  if (mode == "tsan") {
    // concurrent kernel invocations: shared inputs, private outputs
    std::vector<std::thread> threads;
    std::vector<Outputs> outs(4);
    for (int t = 0; t < 4; ++t)
      threads.emplace_back([&in, &outs, t] {
#if defined(_OPENMP)
        // self-enforce the documented precondition PER WORKER (the
        // nthreads ICV is per-thread; setting it on main would not reach
        // these initial threads): libgomp's barriers are invisible to
        // TSAN, so in-region parallelism would be all noise
        omp_set_num_threads(1);
#endif
        run_all(in, outs[t]);
      });
    for (auto& th : threads) th.join();
    for (int t = 1; t < 4; ++t)
      if (!same(outs[0].probs, outs[t].probs) ||
          !same(outs[0].val, outs[t].val)) {
        std::fprintf(stderr, "cross-thread result mismatch\n");
        return 1;
      }
    std::printf("tsan stress ok\n");
    return 0;
  }

  // determinism: oversubscribed OpenMP, bytewise-equal repeat runs
  Outputs a, b;
  run_all(in, a);
  run_all(in, b);
  if (!same(a.probs, b.probs) || !same(a.gnb, b.gnb) ||
      !same(a.seg, b.seg) || !same(a.ent, b.ent) || !same(a.feat, b.feat) ||
      !same(a.thr, b.thr) || !same(a.val, b.val) ||
      !same(a.margins, b.margins)) {
    std::fprintf(stderr, "nondeterministic outputs across repeat runs\n");
    return 1;
  }
  std::printf("determinism ok\n");
  return 0;
}
