#!/usr/bin/env bash
# Elastic fabric control-plane race (ISSUE 13 acceptance: with one
# worker SIGKILLed mid-run, the autoscaler respawns a replacement and
# every user finishes bit-identical to sequential; bucket-aware
# placement beats least-loaded on mean per-host stacked-dispatch
# occupancy, with the fleet planner's merged edges identical on every
# surviving host).
#
# Runs `bench.py --suite elastic`: two arms over the IDENTICAL
# two-bucket workload (pool sizes cycling 30,30,100,100) on a 2-host
# elastic fabric (min_hosts=2, max_hosts=3), h0 SIGKILLed at its first
# admission in BOTH arms.  The arms differ only in
# FabricConfig.placement — 'bucket' (co-locate same-dispatch-bucket
# users, this PR's policy) vs 'load' (the PR 5 least-loaded baseline).
# Occupancy is mean_device_batch / target_live per surviving host (the
# in-bucket occupancy metric cannot see placement); parity vs unfaulted
# sequential runs is asserted on every rep of both arms, and reps are
# interleaved best-of per the 2-vCPU drift protocol.
#
# The JSON line goes to stdout (redirect to BENCH_elastic_r<N>.json to
# commit an artifact); the per-rep log goes to stderr.  Extra bench
# args pass through, e.g.:
#   scripts/elastic_bench.sh --users 8 --al-epochs 2 --reps 2
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite elastic "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite elastic \
        --users 8 --hosts 2 --al-epochs 3 --reps 3
fi
