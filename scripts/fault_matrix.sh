#!/usr/bin/env bash
# Kill-at-every-boundary matrix (SURVEY.md §5 failure detection; mirrors
# scripts/race_check.sh for the resilience layer).
#
# Runs EVERY fault-injection test, including the slow full matrix that
# tier-1 skips:
#
# - per-session (tests/test_resilience.py): for each named fault point
#   (checkpoint.write, member.retrain, member.predict, pool.score,
#   state.save, multihost.sync) x each acquisition mode (mc/hc/mix/rand,
#   plus the registry's wmc rows), a run killed at that boundary and
#   resumed must reproduce the unfaulted F1 trajectory bit-for-bit, and a
#   corrupted live checkpoint must roll back one generation and converge
#   to the same trajectory.  The qbdc rows add the dropout committee's
#   own boundary — the acquire.qbdc.masks mask sampler — alongside
#   pool.score/state.save/checkpoint.write, with mask keys folding from
#   the checkpointed PRNG stream so the resumed committee is bit-identical
#   (test_qbdc_kill_at_every_boundary).
# - serve-layer (tests/test_serve_faults.py): for each serve boundary
#   (serve.admit, serve.journal.append, serve.dispatch, serve.collect)
#   plus the 4-mode restart matrix, a SIGKILLed server restarted from
#   serve_journal.jsonl must finish EVERY submitted user with results
#   bit-identical to an uninterrupted run — journal recovery loses no
#   user; the watchdog/backoff/poison/breaker drills (including the
#   watchdog-expiry-counts-toward-breaker interaction) ride along.
# - fabric kill matrix (tests/test_serve_fabric.py): a REAL 2-host
#   fabric, drilled at every process boundary — SIGKILL the coordinator
#   (restart replays the journal, orphan workers self-exit and are
#   reaped), SIGKILL each worker in every acquisition mode — including
#   the registry's qbdc (dropout committee) and wmc (reliability
#   weights) rows — (in-flight users resume on the survivor, queued
#   users re-enqueue in journal order), a heartbeat-dead (hung) worker
#   failed over on lease expiry, and journal compaction killed in BOTH
#   rename windows — all asserting journal-driven recovery with per-user
#   trajectories bit-identical to uninterrupted single-host runs.
# - SLO-planner restart (tests/test_slo.py): a SIGKILLed
#   planner-enabled serve run (adaptive bucket edges + priority classes)
#   restarted from the journal must re-derive IDENTICAL bucket edges,
#   preserve every user's class assignment and admitted width, and
#   finish every user bit-identical to sequential — the planner rows of
#   the serve kill matrix (scripts/slo_check.sh is the companion
#   schema/replay gate).
# - elastic control plane (tests/test_elastic.py): a worker SIGKILLed
#   out of a 2-host ELASTIC fabric must be REPLACED by the autoscaler
#   (spawn/join journaled, users recovered bit-identical, fleet shape
#   replayable), the coordinator-kill-mid-rebalance drill must replay
#   to deterministic assignments, and the drop-ack migration protocol
#   must never run a user on two hosts.  The SCALE-DOWN rows drill the
#   drain state machine + checkpoint-fenced migration: the
#   deterministic fake-worker drain→rebalance→exit and fence drills, a
#   coordinator-kill matrix over the three new fault points
#   (fabric.drain / fabric.migrate.fence / fabric.migrate.commit —
#   single-owner invariant asserted across both incarnations), and the
#   real 3-host→2-host subprocess drill in mc (tier-1) plus hc/wmc
#   rows here.  scripts/elastic_check.sh (run at the end of this
#   matrix) is the companion gate: kill→respawn→journal-schema→
#   merged-edges (leg 1) and the drain+migrate kill matrix against
#   real workers with the exactly-one-owner check (leg 2).
# - self-healing remediation plane (tests/test_remedy.py): the pure
#   decision-kernel sweep tables (shed_count flap-freedom, hysteresis/
#   cooldown/deadline truth tables, pick_shed order+budget), alert-sink
#   grammar/delivery/isolation, the edge-trigger rearm pin, and the
#   deterministic fake-worker drills — drain-for-rebalance off an
#   overloaded live host (drop-ack + checkpoint fence, host NOT
#   retired), fence-deadline demotion to evict+resume (both the
#   evict-ack and late-fence-ack winners), and the coordinator-kill
#   matrix at the fabric.remedy decision point (fires BEFORE the
#   journal append; single-owner invariant across both incarnations).
#   scripts/remedy_check.sh (run at the end of this matrix) is the
#   companion gate against REAL workers: a slow host (pool.score
#   delay) must be rebalanced without retirement, a fence the slow
#   host cannot ack inside fence_deadline_s must demote to
#   evict+resume, and a coordinator killed at fabric.remedy must
#   replay to an exactly-once finish — every leg bit-identical to
#   sequential baselines.
# - acquisition registry (tests/test_acquire.py): the acquire.qbdc.masks
#   fault point unit and the qbdc resume drill.
# - observability (tests/test_obs.py): the traced fleet eviction+resume
#   trace-continuity pin, and the slow 2-host fabric worker-SIGKILL
#   drill — failed-over users must CONTINUE their traces on the
#   survivor (one deterministic trace id per user, spans from both
#   hosts, orphan-free merge).  scripts/obs_check.sh is the companion
#   schema/export gate.
# - pool-axis mesh serving (tests/test_pool_mesh.py): the
#   sharded-worker SIGKILL failover drill — a 2-host fabric where h0
#   scores through a 4-device mesh and h1 through one chip, h0
#   SIGKILLed at its first admission; every user must fail over to the
#   NARROWER survivor bit-identical to sequential baselines (sharded
#   and unsharded execution of the same journaled state are
#   interchangeable mid-flight).  scripts/mesh_check.sh (run at the
#   end of this matrix) is the companion gate: the 4/8-device parity
#   sweep, the jit-family telemetry determinism pin, and the
#   bench-path selection-digest parity leg.
# - workload / soak (tests/test_workload.py): the live-fabric churn
#   drill — a trace-driven keep-open soak where a user disconnects
#   mid-iteration (journaled evict, workspace kept) and reconnects
#   (journal re-admission, evict-ack gated), draining to zero loss with
#   trajectories bit-identical to sequential.  scripts/soak_check.sh
#   (run at the end of this matrix) is the companion gate: a
#   compressed deterministic soak (zero loss, schema-valid streams,
#   >= 1 slo_headroom alert fired AND graded, >= 1 journaled admission
#   hold, parity) plus a coordinator killed MID-SOAK at fabric.remedy
#   whose journal replay must finish every trace user exactly once.
# - storage integrity (tests/test_durability.py): the io.* fault-point
#   rows — corrupt-mid-file (a complete CRC-framed line that fails its
#   check HALTS replay with a file:line:byte diagnosis, never silently
#   replayed), short-write-then-SIGKILL (the torn tail is quarantine-
#   truncated on reopen and the retried append lands), ENOSPC and
#   rename-kill during journal compaction (tmp cleaned/swept, the next
#   compaction retries, no record lost), the fsync-drop listener
#   surface, plus the fencing-epoch units (EpochGate, stamped feeds,
#   monotonic claims) and the cetpu-fsck detect/repair/replay drills.
#   scripts/fsck_check.sh (run at the end of this matrix) is the
#   companion gate against a REAL fabric: a byte flipped mid-journal
#   after a full 2-host run must halt replay, be quarantined by
#   cetpu-fsck --repair and replay to exact parity; a second
#   coordinator incarnation must claim a strictly higher epoch; and a
#   split-brain zombie drop ack (stale "ep" stamp) must be fenced
#   cursor-only with the migration committed exactly once.
#
# Extra pytest args pass through, e.g.:
#   scripts/fault_matrix.sh -k kill_at_every_boundary
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py \
  tests/test_serve_faults.py tests/test_serve_fabric.py \
  tests/test_slo.py tests/test_elastic.py tests/test_remedy.py \
  tests/test_acquire.py tests/test_obs.py tests/test_workload.py \
  tests/test_pool_mesh.py tests/test_durability.py \
  tests/test_gray.py \
  -v -m faults -p no:cacheprovider "$@"
scripts/elastic_check.sh
scripts/remedy_check.sh
scripts/soak_check.sh
scripts/mesh_check.sh
scripts/fsck_check.sh
scripts/gray_check.sh
echo "fault matrix passed"
