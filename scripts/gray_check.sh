#!/usr/bin/env bash
# Gray-failure CI gate (ISSUE 20 tentpole; sits next to remedy_check.sh
# and is run by scripts/fault_matrix.sh).
#
# LEG 1 — the ladder on a live gray host: a REAL 3-host fabric where
# h0 runs with an injected ``serve.dispatch:stall=5@1x-1`` (the
# slow-not-dead wedge: EVERY dispatch on h0 holds 5 s — values
# untouched, the process alive and beating its lease).  The
# peer-relative detector must fire ``gray_suspect`` with evidence, the
# coordinator must journal PROBATION and escalate to ``gray_drain``,
# and every migrated user must finish EXACTLY ONCE, bit-identical to
# unfaulted sequential baselines — with h0 never retired from the
# fleet shape.
#
# LEG 2 — kill at the rung transition: the coordinator is killed
# (in-process InjectedKill) at ``fabric.gray`` — which fires BEFORE the
# probation record journals, so the kill leaves no half-journaled rung.
# The restarted coordinator claims a fresh fencing epoch and re-places
# every previous-incarnation in-flight user at startup (failover
# resume, old host excluded) — the users h0 was holding hostage are
# FREED by the restart itself, a strictly stronger remediation than
# re-deriving the rung (that replay determinism is pinned by the
# tier-1 fake-fleet kill matrix in tests/test_gray.py).  The gate:
# the hostages finish on healthy hosts, every user exactly once
# across every host's results file, parity bit-identical.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import glob
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from tests.fabric_workload import (
    make_cfg,
    sequential_baselines,
    sizes_arg,
    user_specs,
)

from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.obs.alerts import AlertWatcher
from consensus_entropy_tpu.resilience import faults as faults_mod
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths

cfg = make_cfg("mc", epochs=2)
specs = user_specs(8, sizes=[30, 100])
root = tempfile.mkdtemp(prefix="gray_check_")
seq = sequential_baselines(root, cfg, specs)

GRAY_FAULT = "serve.dispatch:stall=5@1x-1"


class _Rec:
    def __init__(self):
        self.events = []

    def event(self, kind, /, **kw):
        self.events.append((kind, kw))


def run_leg(slug, fcfg, *, inject_point=None):
    """One coordinator run over real workers; h0 is the gray host
    (every dispatch stalls, lease still beating).  Returns (summary or
    None, killed, fabric_dir, alert recorder)."""
    fdir = os.path.join(root, "fabric_" + slug)
    ws = os.path.join(root, "ws_" + slug)
    os.makedirs(fdir, exist_ok=True)
    os.makedirs(ws, exist_ok=True)

    def spawn(host_id, fdir=fdir, ws=ws):
        log = open(fabric_paths(fdir, host_id)["log"], "ab")
        env = {**os.environ, "PYTHONPATH": "."}
        if host_id == "h0":
            env["CETPU_FAULTS"] = GRAY_FAULT
        try:
            return subprocess.Popen(
                [sys.executable, "tests/fabric_worker.py", fdir,
                 host_id, ws, cfg.mode, str(cfg.epochs), str(len(specs)),
                 "5.0", "2", sizes_arg(specs)],
                stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()

    jp = os.path.join(fdir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp)
    rep = _Rec()
    killed = False
    summary = None
    try:
        if inject_point is None:
            summary = FabricCoordinator(journal, fdir, fcfg,
                                        alerts=AlertWatcher(rep)).run(
                [u for _, u, _ in specs], spawn,
                pools={u: n for _, u, n in specs})
        else:
            try:
                with faults_mod.inject(FaultRule(inject_point, "kill",
                                                 at=1)):
                    FabricCoordinator(journal, fdir, fcfg,
                                      alerts=AlertWatcher(rep)).run(
                        [u for _, u, _ in specs], spawn,
                        pools={u: n for _, u, n in specs})
            except InjectedKill:
                killed = True
    finally:
        journal.close()
    return summary, killed, fdir, rep


def check_parity_and_owners(fdir, label):
    """Schema-validate every journal/WAL, then the EXACTLY-ONE-OWNER +
    parity gate: each user has exactly one result row across every
    host's results file, bit-identical to sequential."""
    jp = os.path.join(fdir, "serve_journal.jsonl")
    bad = validate_journal_file(jp)
    for wal in sorted(glob.glob(os.path.join(fdir, "events_*.jsonl"))):
        bad += validate_journal_file(wal)
    assert bad == [], "journal violations:\n" + "\n".join(bad[:10])
    rows = {}
    for fname in sorted(os.listdir(fdir)):
        if fname.startswith("results_") and fname.endswith(".jsonl"):
            for rec in export.read_jsonl_tolerant(
                    os.path.join(fdir, fname)):
                rows.setdefault(rec["user"], []).append(rec)
    for _, uid, _ in specs:
        assert len(rows[uid]) == 1, (label, uid, rows.get(uid))
        assert rows[uid][0]["error"] is None, (label, uid)
        assert rows[uid][0]["result"]["trajectory"] \
            == seq[uid]["trajectory"], (label, uid)


def journal_events(fdir, event):
    out = []
    for rec in export.read_jsonl_tolerant(
            os.path.join(fdir, "serve_journal.jsonl")):
        if rec.get("event") == event:
            out.append(rec)
    return out


# the ladder knobs: an absolute floor ABOVE the lease beat cadence and
# normal CPU step walls (so only the 5 s stall qualifies) but inside
# the stall window, short sustained-evidence gates so the drill
# escalates inside the wedge, and a clear gate long enough that nothing
# lifts mid-run (the recovery path is tier-1's fake-fleet drill)
fcfg = FabricConfig(hosts=3, min_hosts=3, max_hosts=3, placement="load",
                    gray=True, gray_ratio=3.0, gray_min_s=2.0,
                    gray_hold_s=0.5, gray_drain_s=1.0,
                    gray_clear_s=600.0)

# ---- LEG 1: the full ladder on a live stalled host --------------------
summary1, _, fdir1, rep1 = run_leg("ladder", fcfg)
assert sorted(summary1["finished"]) == sorted(u for _, u, _ in specs)
assert summary1["probations"] >= 1, summary1
assert summary1["gray_drains"] >= 1, summary1
assert summary1["migrations"] >= 1, summary1
assert summary1["drains"] == 0 and summary1["revocations"] == 0, summary1
gray_alerts = [kw for k, kw in rep1.events
               if k == "alert" and kw.get("kind") == "gray_suspect"]
# the ALERT stream is advisory and edge-triggered: under CPU
# contention a busy peer mid-step can transiently look quiet, and the
# hysteresis ladder is what filters that — so the gate pins the
# STALLED host's evidence, not the absence of peer noise
h0_alerts = [a for a in gray_alerts if a["host"] == "h0"]
assert h0_alerts, "gray_suspect never fired for the stalled host"
assert any(a["signals"] for a in h0_alerts), h0_alerts
probs1 = [(r["host"], r["on"]) for r in journal_events(fdir1,
                                                       "probation")]
assert ("h0", True) in probs1, probs1
assert any(r["action"] == "gray_drain" and r["host"] == "h0"
           for r in journal_events(fdir1, "remedy"))
st1 = AdmissionJournal(os.path.join(fdir1, "serve_journal.jsonl")).state
assert sorted(st1.fleet_hosts()) == ["h0", "h1", "h2"]  # never retired
assert "h0" in st1.probation, st1.probation
check_parity_and_owners(fdir1, "ladder")
print(f"gray_check: ladder climbed suspect->probation->drain on the "
      f"stalled host (probations={summary1['probations']}, "
      f"gray_drains={summary1['gray_drains']}, "
      f"migrations={summary1['migrations']}), host kept, parity exact")

# ---- LEG 2: coordinator killed at the rung transition -----------------
_, killed, fdir2, _ = run_leg("kill", fcfg, inject_point="fabric.gray")
assert killed, "fabric.gray never fired (no gray evidence developed?)"
# fired-before-append: the killed rung decision never journaled
assert journal_events(fdir2, "probation") == [], \
    journal_events(fdir2, "probation")
pend2 = AdmissionJournal(
    os.path.join(fdir2, "serve_journal.jsonl")).state.pending
last_host = {r["user"]: r["host"]
             for r in journal_events(fdir2, "assign")}
hostages = sorted(u for u in pend2 if last_host.get(u) == "h0")
assert hostages, "the kill left nothing pending on the stalled host?"
summary2, _, _, _ = run_leg("kill", fcfg)
st2 = AdmissionJournal(os.path.join(fdir2, "serve_journal.jsonl")).state
assert st2.finished == {u for _, u, _ in specs} and not st2.pending
# the restart freed the hostages: each finished off the wedged host
fin = {r["user"]: r["host"]
       for r in journal_events(fdir2, "finish") if r.get("host")}
for u in hostages:
    assert fin.get(u) != "h0", (u, fin.get(u))
check_parity_and_owners(fdir2, "kill")
print(f"gray_check: kill@fabric.gray replayed clean — {len(specs)} "
      f"users finished exactly once, the restart freed "
      f"{len(hostages)} hostage(s) off the wedged host, parity exact")
PY
echo "gray check passed"
