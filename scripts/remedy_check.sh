#!/usr/bin/env bash
# Self-healing fabric CI gate (ISSUE 16 tentpole; sits next to
# elastic_check.sh and is run by scripts/fault_matrix.sh).
#
# LEG 1 — drain-for-rebalance: a REAL 3-host fabric where h0 runs with
# an injected pool.score delay (the slow-host simulation — values
# untouched, iterations slow), so its unresolved load holds while the
# fast hosts drain to zero: the sustained placement-skew alert must
# trigger a journaled ``remedy`` rebalance that moves h0's queued users
# over the drop-ack path and its in-flight users over the checkpoint
# fence WITHOUT retiring the host — every user bit-identical to
# unfaulted sequential baselines, no drains, no revocations, and the
# main journal + every per-host WAL schema-valid.
#
# LEG 2 — deadline-fenced degradation: same geometry, but the slow
# host's iterations (~0.5 s) cannot ack a checkpoint fence inside
# ``fence_deadline_s`` (0.01 s): the coordinator must journal the
# ``fence_timeout`` remedy and demote to evict+resume — the session
# force-releases at its next step boundary and resumes on a fast host,
# still bit-identical.
#
# LEG 3 — kill at the decision point: the coordinator is killed
# (in-process InjectedKill) at ``fabric.remedy`` — which fires BEFORE
# the decision journals, so the kill leaves no half-journaled remedy —
# and rerun; the rerun must replay the journal, finish every user
# EXACTLY ONCE across every host's results file, and keep parity.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import glob
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from tests.fabric_workload import (
    make_cfg,
    sequential_baselines,
    sizes_arg,
    user_specs,
)

from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.resilience import faults as faults_mod
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths

cfg = make_cfg("mc", epochs=2)
specs = user_specs(8, sizes=[30, 100])
root = tempfile.mkdtemp(prefix="remedy_check_")
seq = sequential_baselines(root, cfg, specs)


def run_leg(slug, fcfg, *, slow_s, inject_point=None, on_poll=None):
    """One coordinator run over real workers; h0 is the slow host
    (pool.score delay, every scan).  Returns (summary_or_None, killed,
    fabric_dir) — summary is None when the injected kill fired."""
    fdir = os.path.join(root, "fabric_" + slug)
    ws = os.path.join(root, "ws_" + slug)
    os.makedirs(fdir, exist_ok=True)
    os.makedirs(ws, exist_ok=True)

    def spawn(host_id, fdir=fdir, ws=ws):
        log = open(fabric_paths(fdir, host_id)["log"], "ab")
        env = {**os.environ, "PYTHONPATH": "."}
        if host_id == "h0":
            env["CETPU_FAULTS"] = f"pool.score:delay={slow_s}@1x-1"
        try:
            return subprocess.Popen(
                [sys.executable, "tests/fabric_worker.py", fdir,
                 host_id, ws, cfg.mode, str(cfg.epochs), str(len(specs)),
                 "5.0", "2", sizes_arg(specs)],
                stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()

    jp = os.path.join(fdir, "serve_journal.jsonl")
    journal = AdmissionJournal(jp)
    killed = False
    summary = None
    try:
        if inject_point is None:
            summary = FabricCoordinator(journal, fdir, fcfg,
                                        on_poll=on_poll).run(
                [u for _, u, _ in specs], spawn,
                pools={u: n for _, u, n in specs})
        else:
            try:
                with faults_mod.inject(FaultRule(inject_point, "kill",
                                                 at=1)):
                    FabricCoordinator(journal, fdir, fcfg,
                                      on_poll=on_poll).run(
                        [u for _, u, _ in specs], spawn,
                        pools={u: n for _, u, n in specs})
            except InjectedKill:
                killed = True
    finally:
        journal.close()
    return summary, killed, fdir


def check_parity_and_owners(fdir, label):
    """Schema-validate every journal/WAL, then the EXACTLY-ONE-OWNER +
    parity gate: each user has exactly one result row across every
    host's results file, bit-identical to sequential."""
    jp = os.path.join(fdir, "serve_journal.jsonl")
    bad = validate_journal_file(jp)
    for wal in sorted(glob.glob(os.path.join(fdir, "events_*.jsonl"))):
        bad += validate_journal_file(wal)
    assert bad == [], "journal violations:\n" + "\n".join(bad[:10])
    rows = {}
    for fname in sorted(os.listdir(fdir)):
        if fname.startswith("results_") and fname.endswith(".jsonl"):
            for rec in export.read_jsonl_tolerant(
                    os.path.join(fdir, fname)):
                rows.setdefault(rec["user"], []).append(rec)
    for _, uid, _ in specs:
        assert len(rows[uid]) == 1, (label, uid, rows.get(uid))
        assert rows[uid][0]["error"] is None, (label, uid)
        assert rows[uid][0]["result"]["trajectory"] \
            == seq[uid]["trajectory"], (label, uid)


def remedy_actions(fdir):
    st_recs = []
    for rec in export.read_jsonl_tolerant(
            os.path.join(fdir, "serve_journal.jsonl")):
        if rec.get("event") == "remedy":
            st_recs.append(rec.get("action"))
    return st_recs


# ---- LEG 1: drain-for-rebalance on a live slow host -------------------
# placement="load" gives the even 3/3/2 initial split (inside the
# remedy_skew=1 bound), so the ONLY sustained skew is the slow host
# holding its share while the fast hosts drain to zero
fcfg1 = FabricConfig(hosts=3, min_hosts=3, max_hosts=3, remedy=True,
                     remedy_hold_s=0.2, remedy_cooldown_s=600.0,
                     remedy_skew=1, placement="load")
summary1, _, fdir1 = run_leg("rebalance", fcfg1, slow_s=0.3)
assert sorted(summary1["finished"]) == sorted(u for _, u, _ in specs)
assert summary1["remedies"] >= 1, summary1
assert summary1["migrations"] >= 1, summary1
assert summary1["fence_timeouts"] == 0, summary1  # deadline disabled
assert summary1["drains"] == 0 and summary1["revocations"] == 0, summary1
assert "rebalance" in remedy_actions(fdir1)
st1 = AdmissionJournal(os.path.join(fdir1, "serve_journal.jsonl")).state
assert sorted(st1.fleet_hosts()) == ["h0", "h1", "h2"]  # nobody retired
check_parity_and_owners(fdir1, "rebalance")
print(f"remedy_check: drain-for-rebalance moved load off the slow host "
      f"(remedies={summary1['remedies']}, "
      f"migrations={summary1['migrations']}, "
      f"fences={summary1['fences']}), host kept, parity exact")

# ---- LEG 2: deadline-fenced degradation -------------------------------
fcfg2 = FabricConfig(hosts=3, min_hosts=3, max_hosts=3, remedy=True,
                     remedy_hold_s=0.2, remedy_cooldown_s=600.0,
                     remedy_skew=1, placement="load",
                     fence_deadline_s=0.01)
summary2, _, fdir2 = run_leg("deadline", fcfg2, slow_s=0.5)
assert sorted(summary2["finished"]) == sorted(u for _, u, _ in specs)
assert summary2["remedies"] >= 1, summary2
assert summary2["fence_timeouts"] >= 1, summary2
assert "fence_timeout" in remedy_actions(fdir2)
check_parity_and_owners(fdir2, "deadline")
print(f"remedy_check: fence deadline demoted to evict+resume "
      f"(fence_timeouts={summary2['fence_timeouts']}), parity exact")

# ---- LEG 3: coordinator killed at the decision point ------------------
_, killed, fdir3 = run_leg("kill", fcfg1, slow_s=0.3,
                           inject_point="fabric.remedy")
assert killed, "fabric.remedy never fired (no skew developed?)"
# fired-before-append: the killed decision never reached the journal
assert remedy_actions(fdir3) == [], remedy_actions(fdir3)
summary3, _, _ = run_leg("kill", fcfg1, slow_s=0.3)
st3 = AdmissionJournal(os.path.join(fdir3, "serve_journal.jsonl")).state
assert st3.finished == {u for _, u, _ in specs} and not st3.pending
check_parity_and_owners(fdir3, "kill")
print(f"remedy_check: kill@fabric.remedy replayed clean — "
      f"{len(specs)} users finished exactly once, parity exact "
      f"(rerun remedies={summary3['remedies']})")
PY
echo "remedy check passed"
