#!/usr/bin/env bash
# Storage-integrity CI gate (ISSUE 19 tentpole; sits next to
# remedy_check.sh and is run by scripts/fault_matrix.sh).
#
# LEG 1 — byte-flip under a REAL fabric: a 2-host fabric runs a full
# workload to completion (parity vs unfaulted sequential baselines
# asserted first), then one byte of a mid-file CRC-framed journal
# record is flipped.  Replay must HALT with ``JournalCorruption`` (a
# complete-but-damaged line is bit-rot, NEVER silently replayed),
# ``cetpu-fsck`` must detect it (exit 1) and ``--repair`` must
# quarantine the damaged line into the ``.quarantine`` sidecar, sweep a
# planted stale ``.tmp``, and re-verify clean (exit 0) — after which
# the journal replays with every committed disposition intact and the
# per-user results still bit-identical.
#
# LEG 2 — double-coordinator fencing: a SECOND coordinator incarnation
# over the repaired journal (real workers again) must claim a STRICTLY
# HIGHER fencing epoch — the journal's epoch events read [1, 2] — and
# finish with nothing re-run (every user skip_done).  Then the
# deterministic split-brain drill: a fake-worker fleet whose journal a
# dead incarnation stamped at epoch 7, where the migration drop ack
# arrives TWICE — once carrying the dead incarnation's ``"ep": 7`` (the
# zombie's ack) and once live.  The live coordinator (epoch 8) must
# journal the stale ack CURSOR-ONLY (report ``epoch_fenced``, commit
# nothing from it) and commit the migration exactly once off the live
# ack — no user runs on two hosts, and every feed line it wrote is
# stamped ``"ep": 8``.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import glob
import os
import pathlib
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from tests.fabric_workload import (
    make_cfg,
    sequential_baselines,
    sizes_arg,
    user_specs,
)

from consensus_entropy_tpu.cli.fsck import main as fsck_main
from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.resilience import io as dio
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths
from consensus_entropy_tpu.serve.journal import JournalCorruption

cfg = make_cfg("mc", epochs=2)
specs = user_specs(6, sizes=[30, 100])
users = [u for _, u, _ in specs]
pools = {u: n for _, u, n in specs}
root = tempfile.mkdtemp(prefix="fsck_check_")
seq = sequential_baselines(root, cfg, specs)

fdir = os.path.join(root, "fabric")
ws = os.path.join(root, "ws")
os.makedirs(fdir, exist_ok=True)
os.makedirs(ws, exist_ok=True)
jp = os.path.join(fdir, "serve_journal.jsonl")


def spawn(host_id):
    log = open(fabric_paths(fdir, host_id)["log"], "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "tests/fabric_worker.py", fdir, host_id,
             ws, cfg.mode, str(cfg.epochs), str(len(specs)), "5.0", "2",
             sizes_arg(specs)],
            stdout=log, stderr=subprocess.STDOUT,
            env={**os.environ, "PYTHONPATH": "."})
    finally:
        log.close()


def run_coordinator():
    fcfg = FabricConfig(hosts=2, min_hosts=2, max_hosts=2)
    journal = AdmissionJournal(jp)
    coord = FabricCoordinator(journal, fdir, fcfg)
    try:
        return coord.run(users, spawn, pools=pools), coord.epoch
    finally:
        journal.close()


def check_parity(label):
    rows = {}
    for fname in sorted(os.listdir(fdir)):
        if fname.startswith("results_") and fname.endswith(".jsonl"):
            for rec in export.read_jsonl_tolerant(
                    os.path.join(fdir, fname)):
                rows.setdefault(rec["user"], []).append(rec)
    for uid in users:
        assert len(rows[uid]) == 1, (label, uid, rows.get(uid))
        assert rows[uid][0]["error"] is None, (label, uid)
        assert rows[uid][0]["result"]["trajectory"] \
            == seq[uid]["trajectory"], (label, uid)


# ---- LEG 1: byte-flip under a real fabric -----------------------------
summary1, epoch1 = run_coordinator()
assert sorted(summary1["finished"]) == sorted(users), summary1
assert epoch1 == 1, epoch1
check_parity("pre-flip")
bad = validate_journal_file(jp)
for wal in sorted(glob.glob(os.path.join(fdir, "events_*.jsonl"))):
    bad += validate_journal_file(wal)
assert bad == [], "journal violations:\n" + "\n".join(bad[:10])
pre = AdmissionJournal(jp).state.to_dict()

# flip one byte of a mid-file framed ``enqueue`` record (disposition-
# neutral damage: the user's assign/admit/finish records all survive)
with open(jp, "rb") as f:
    lines = f.read().split(b"\n")
target = next(i for i, ln in enumerate(lines)
              if i >= 2 and dio.parse_frame(ln + b"\n")[0] == "ok"
              and dio.parse_frame(ln + b"\n")[1].get("event")
              == "enqueue")
mut = bytearray(lines[target])
mut[len(mut) // 2] ^= 0xFF
lines[target] = bytes(mut)
with open(jp, "wb") as f:
    f.write(b"\n".join(lines))

try:
    AdmissionJournal(jp)
except JournalCorruption as e:
    assert "cetpu-fsck" in str(e), e
else:
    raise AssertionError("bit-rot was silently replayed")

# plant a killed compaction's stray AFTER the replay probe (opening the
# journal sweeps its OWN .tmp siblings, corrupt or not)
open(jp + ".tmp", "wb").close()

assert fsck_main([fdir]) == 1, "fsck missed the flipped byte"
assert fsck_main([fdir, "--repair"]) == 0, "repair did not re-verify"
assert fsck_main([fdir]) == 0, "repaired dir not clean"
assert os.path.exists(dio.quarantine_path(jp))
assert not os.path.exists(jp + ".tmp")
st = AdmissionJournal(jp).state
assert st.finished == set(users), st.finished
assert st.seq == pre["seq"], (st.seq, pre["seq"])
check_parity("post-repair")
print(f"fsck_check: byte-flip at line {target + 1} halted replay, "
      "detected, quarantined, replayed to parity")

# ---- LEG 2a: second incarnation claims a strictly higher epoch --------
summary2, epoch2 = run_coordinator()
assert epoch2 == 2, epoch2
# every user was already finished: skip_done filters them out before
# submission, so the incarnation resolves with nothing to run
assert summary2["users"] == 0 and summary2["finished"] == [], summary2
claims = [rec["epoch"] for rec in export.read_jsonl_tolerant(jp)
          if rec.get("event") == "epoch"]
assert claims == [1, 2], claims
assert AdmissionJournal(jp).state.coordinator_epoch == 2
# the parity check is the re-run detector: a second incarnation that
# RE-RAN a finished user would append a second results row
check_parity("double-coordinator")
print("fsck_check: double-coordinator claimed epochs [1, 2], "
      "every finished user skip_done, parity intact")

# ---- LEG 2b: the split-brain zombie ack is fenced out -----------------
import tests.test_elastic as te

_BaseWorker = te._FakeWorker


class _ZombieAckWorker(_BaseWorker):
    """Answers every successful drop request TWICE: once stamped with
    the DEAD incarnation's epoch (the split-brain zombie's ack), then
    the live ack — the coordinator must treat the stale-stamped ack as
    cursor-only and commit the migration exactly once."""

    def _event(self, rec):
        if rec.get("event") == "drop" and rec.get("ok"):
            _BaseWorker._event(self, {**rec, "ep": 7})
        _BaseWorker._event(self, rec)


te._FakeWorker = _ZombieAckWorker
leg2 = os.path.join(root, "leg2")
os.makedirs(os.path.join(leg2, "fabric"), exist_ok=True)
with AdmissionJournal(os.path.join(leg2, "fabric",
                                   "serve_journal.jsonl")) as j:
    j.append("epoch", epoch=7)  # the dead incarnation's claim

fusers = [f"u{i}" for i in range(6)]
fpools = {u: (30 if i % 2 == 0 else 100) for i, u in enumerate(fusers)}
fcfg = FabricConfig(hosts=1, min_hosts=1, max_hosts=2, scale_backlog=2,
                    poll_s=0.01, lease_s=5.0, drain_timeout_s=0.2)


def script(rnd, coord, workers):
    h0 = workers.get("h0")
    if rnd == 2 and h0 and not h0.admitted and h0.queued:
        h0.admit(h0.queued[0])  # one in-flight: must never migrate
    if rnd > 6:
        for w in workers.values():
            for uid in list(w.admitted):
                w.finish(uid)
            for uid in list(w.queued):
                w.admit(uid)


fsum, coord, workers, fab2 = te._fake_fleet(
    pathlib.Path(leg2), fcfg, fusers, fpools, script)
assert coord.epoch == 8, coord.epoch
assert sorted(fsum["finished"]) == fusers, fsum
assert fsum["migrations"] >= 1, fsum
ran = sorted(u for w in workers.values() for u in w.finished)
assert ran == fusers, ran  # exactly one owner despite the double ack
fenced = [e for e in coord.report.events
          if e.get("event") == "epoch_fenced"]
assert fenced and all(e["epoch"] == 7 for e in fenced), fenced
jp2 = os.path.join(fab2, "serve_journal.jsonl")
stale_acks = [rec for rec in export.read_jsonl_tolerant(jp2)
              if rec.get("event") == "drop" and rec.get("ep") == 7]
assert stale_acks, "the zombie ack never reached the journal cursor"
for ap in sorted(glob.glob(os.path.join(fab2, "assign_*.jsonl"))):
    for rec in export.read_jsonl_tolerant(ap):
        assert rec.get("ep") == 8, (ap, rec)
assert validate_journal_file(jp2) == []
print(f"fsck_check: zombie ack (ep=7) fenced {len(fenced)} time(s) by "
      f"the epoch-8 incarnation, migration committed exactly once")
PY
echo "fsck check passed"
