#!/usr/bin/env bash
# Crash-safe-serving throughput under faults (ISSUE 4 CI drill; the
# resilience sibling of scripts/serve_bench.sh).
#
# Runs `bench.py --suite serve-faults`: the serve layer over a flaky user
# mix — every 3rd user's victim member raises on its first two retrains
# (burning the session AND its in-engine resume, so recovery goes through
# serve-layer backoff re-admission), a straggler pool.score delay trips
# the session watchdog, and a transient stacked-dispatch fault exercises
# the per-bucket circuit breaker.  Sequential UNFAULTED runs are the
# ground truth: parity is asserted per user on every rep (reps are
# interleaved best-of per the 2-vCPU drift protocol), then the JSON line
# reports recovered-users/sec plus eviction/resume/requeue/watchdog/
# breaker trip counts.
#
# The JSON line goes to stdout (redirect to BENCH_serve_faults_r<N>.json
# to commit an artifact); the per-rep log goes to stderr.  Extra bench
# args pass through, e.g.:
#   scripts/serve_fault_bench.sh --users 6 --reps 2
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
        --suite serve-faults "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
        --suite serve-faults --users 8 --pool 120 --fleet 4
fi
