#!/usr/bin/env bash
# Fleet engine throughput sweep (ISSUE 2 acceptance: >1.5x users/sec vs
# the sequential loop on the synthetic workload).
#
# Runs `bench.py --suite fleet`: N concurrent AL sessions through
# fleet.FleetScheduler — one vmapped scoring dispatch per phase-aligned
# cohort, host sklearn retraining on a bounded worker pool — against the
# sequential ALLoop.run_user baseline over the identical users and seeds.
# Parity with the sequential trajectories is asserted inside the suite, so
# the reported speedup is for bit-identical results.
#
# The JSON line goes to stdout (redirect to BENCH_fleet_r<N>.json to
# commit an artifact); the per-cohort log goes to stderr.  Extra bench
# args pass through, e.g.:
#   scripts/fleet_bench.sh --users 8 --pool 600 --fleet 2 4 8
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite fleet "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite fleet \
        --users 6 --pool 400 --fleet 2 6
fi
