#!/usr/bin/env bash
# Serve-layer throughput sweep (ISSUE 3 acceptance: users/sec >= the fleet
# cohort mode on a skewed-pool workload, with per-bucket occupancy).
#
# Runs `bench.py --suite serve`: continuous-batching admission
# (serve.FleetServer — freed slots refilled from the waiting queue the
# moment a session finishes, each user padded to its bucket edge instead
# of the cohort max) against BOTH the fixed-cohort fleet scheduler and the
# sequential ALLoop over identical tail-heavy users (every 4th pool is 4x
# the rest).  Per the 2-vCPU drift protocol the reps are INTERLEAVED
# (sequential, fleet-N, serve-N per rep) and each side reports its best
# (min-wall) rep; per-user trajectory parity with the sequential loop is
# asserted on every rep before any users/sec number is reported.
#
# The JSON line goes to stdout (redirect to BENCH_serve_r<N>.json to
# commit an artifact); the per-rep log goes to stderr.  Extra bench args
# pass through, e.g.:
#   scripts/serve_bench.sh --users 8 --pool 150 --fleet 2 4
#
# `scripts/serve_bench.sh fused [...]` runs the FUSED-STEP race instead
# (`bench.py --suite serve-fused`, ISSUE 8): the fused serve step
# (device-resident pool state, donated stacks, in-graph
# select→reveal→mask) vs `--no-fuse-step` over identical users, parity
# asserted on every rep, reporting host↔device bytes + device calls per
# iteration alongside users/sec (redirect to BENCH_serve_fused_r<N>.json).
#
# `scripts/serve_bench.sh mesh [...]` runs the pool-axis mesh K-sweep
# instead (`bench.py --suite mesh`, ISSUE 18): one worker, K simulated
# devices (each K in its own subprocess with
# --xla_force_host_platform_device_count=K), all six fused serve-step
# modes over a >=100k pool through the NamedSharding families — donated
# masks, sharded reveal scatter — with the per-iteration selection
# digest asserted BIT-EQUAL to the unsharded K=1 arm on every rep
# before any steps/sec is reported (redirect to BENCH_mesh_r<N>.json).
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "${1:-}" = "mesh" ]; then
    shift
    if [ "$#" -gt 0 ]; then
        JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
            --suite mesh "$@"
    else
        JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
            --suite mesh --mesh-sweep 1 2 4 8 --reps 3
    fi
elif [ "${1:-}" = "fused" ]; then
    shift
    if [ "$#" -gt 0 ]; then
        JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
            --suite serve-fused "$@"
    else
        JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
            --suite serve-fused --users 6 --pool 280 --fleet 3 --reps 3
    fi
elif [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite serve "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite serve \
        --users 8 --pool 120 --fleet 4
fi
