"""Maximal real-data artifact: pretrain the committee on the REAL DEAM
dynamic annotations (round-4 VERDICT #8).

This image mounts exactly one piece of the reference's real data:
``/root/reference/deam_annotations/{arousal,valence}.csv`` (1802 songs of
per-500ms dynamic annotations — consumed by the reference at
``deam_classifier.py:64-87``).  Per-song openSMILE feature CSVs, AMG1608
``.mat`` annotations, and audio are NOT mounted, so full quality parity
with the paper's Table (BASELINE.md: CNN mu=0.48, SGD mu=0.457,
XGB mu=0.39, GNB mu=0.238 over 46 users) is environment-blocked.

What this script commits instead — the closest attainable artifact:

- REAL labels, real pipeline: the arousal/valence rows drive per-frame
  quadrant labels through the exact reference rules (dropna per row, keep
  the shorter annotation when lengths disagree, quadrant geometry,
  lexicographic-max song label — ``data/deam.py`` / ``labels.py``).
- SYNTHETIC features/audio, schema-exact: per-frame 260-column openSMILE-
  schema features from a class-conditional generative model (10
  informative columns, per-song offsets, frame noise), and full-length
  class-tone waveforms from the experiment family's SINE timbre
  (``al.evidence.synth_tone``) — the same family the EVIDENCE_r05 sweep
  pools draw from, so the full-geometry CNN fold-members this run
  produces are the sweep's pretrained committee.
- The full pretraining surface: gnb / sgd / xgb / cnn_jax through the
  production ``deam_classifier`` pipeline (5 grouped CV folds each, every
  fold estimator kept — the committee registry).

Usage:
  python scripts/realdata_run.py [--root DIR] [--cnn-epochs 100]
      [--out REALDATA_r05.json] [--skip-cnn] [--songs N]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

REF_ANNO = "/root/reference/deam_annotations"

#: class-conditional feature model: 10 informative columns out of the
#: 260-column openSMILE schema; per-song offset comparable to the class
#: separation and per-frame noise on top, so fold F1 lands in a
#: mid-range, non-saturated band (still NOT real-data difficulty — the
#: caveat in the committed artifact is explicit)
N_INFORMATIVE = 10
CLASS_SEP = 2.2
SONG_OFF = 1.3
FRAME_NOISE = 1.2


def set_feature_model(*, n_informative=None, class_sep=None, song_off=None,
                      frame_noise=None):
    """Override the generative constants (CLI --class-sep etc.): the
    default band SATURATES the classic models (F1 ~0.96 — committed in
    REALDATA_r05's main block); a harder variant puts the fold F1s in a
    band where the juxtaposition with the paper's numbers reads as more
    than a ceiling check."""
    global N_INFORMATIVE, CLASS_SEP, SONG_OFF, FRAME_NOISE
    if n_informative is not None:
        N_INFORMATIVE = n_informative
    if class_sep is not None:
        CLASS_SEP = class_sep
    if song_off is not None:
        SONG_OFF = song_off
    if frame_noise is not None:
        FRAME_NOISE = frame_noise


def build_tree(root: str, n_songs: int | None, rng) -> tuple[dict, dict]:
    """Synthesize the DEAM tree from the REAL annotation CSVs; returns
    (paths dict, stats dict)."""
    import pandas as pd

    from consensus_entropy_tpu.al.evidence import synth_tone
    from consensus_entropy_tpu.config import CNNConfig
    from consensus_entropy_tpu.config import (
        FEATURE_SLICE_START,
        FEATURE_SLICE_STOP_FFTMAG,
    )
    from consensus_entropy_tpu.labels import quadrant_deam_np

    # 260-column openSMILE slice at the REAL width: sentinel start/stop
    # column names exact (config.feature_slice pins them); the 258
    # interior names are synthetic — the real openSMILE CSVs (and hence
    # their column names) are not mounted in this image
    FEATURE_COLS_FFTMAG = ([FEATURE_SLICE_START]
                           + [f"synth_col_{i}" for i in range(258)]
                           + [FEATURE_SLICE_STOP_FFTMAG])

    cfg = CNNConfig()  # full reference geometry (sample_rate for tones)
    deam = os.path.join(root, "deam")
    for sub in ("features", "annotations", "npy"):
        os.makedirs(os.path.join(deam, sub), exist_ok=True)
    # the REAL annotation tables, verbatim
    for f in ("arousal.csv", "valence.csv"):
        shutil.copy(os.path.join(REF_ANNO, f),
                    os.path.join(deam, "annotations", f))
    arousal = pd.read_csv(os.path.join(deam, "annotations", "arousal.csv"))
    valence = pd.read_csv(os.path.join(deam, "annotations", "valence.csv"))
    valence_ids = set(int(s) for s in valence.song_id)

    centers = np.zeros((4, len(FEATURE_COLS_FFTMAG)), np.float32)
    centers[:, :N_INFORMATIVE] = (
        rng.standard_normal((4, N_INFORMATIVE)) * CLASS_SEP)

    n_frames_total = 0
    song_labels: dict[int, int] = {}
    song_ids = [int(s) for s in arousal.song_id]
    if n_songs:
        song_ids = song_ids[:n_songs]
    for sid in song_ids:
        if sid not in valence_ids:
            continue
        a_row = arousal[arousal.song_id == sid].dropna(axis=1)
        v_row = valence[valence.song_id == sid].dropna(axis=1)
        t_a = [int("".join(filter(str.isdigit, c))) / 1000.0
               for c in a_row.columns[1:]]
        t_v = [int("".join(filter(str.isdigit, c))) / 1000.0
               for c in v_row.columns[1:]]
        # keep the shorter annotation (deam_classifier.py:75-83)
        t_common = t_a if len(t_a) <= len(t_v) else t_v
        if not t_common:
            continue
        cols = [f"sample_{int(t * 1000)}ms" for t in t_common]
        a_vals = a_row.loc[:, cols].values[0].astype(np.float64)
        v_vals = v_row.loc[:, cols].values[0].astype(np.float64)
        q = quadrant_deam_np(a_vals, v_vals)  # per-frame class 0..3
        # song-level label: lexicographic MAX quadrant — the reference's
        # groupby('song_id')['quadrants'].max() rule (deam_classifier.py:253)
        song_labels[sid] = int(q.max())
        song_off = (rng.standard_normal(len(FEATURE_COLS_FFTMAG))
                    .astype(np.float32) * SONG_OFF)
        feats = (centers[q] + song_off
                 + rng.standard_normal(
                     (len(q), len(FEATURE_COLS_FFTMAG))).astype(np.float32)
                 * FRAME_NOISE)
        df = pd.DataFrame(feats, columns=FEATURE_COLS_FFTMAG)
        df.insert(0, "frameTime", t_common)
        df.to_csv(os.path.join(deam, "features", f"{sid}.csv"), sep=";",
                  index=False)
        n = cfg.input_length + 10000 + int(rng.integers(0, 2000))
        np.save(os.path.join(deam, "npy", f"{sid}.npy"),
                synth_tone(song_labels[sid], n, rng,
                           sample_rate=cfg.sample_rate, timbre="sine"))
        n_frames_total += len(q)
    stats = {
        "songs": len(song_labels),
        "frames": n_frames_total,
        "song_class_counts": {int(c): int(n) for c, n in zip(
            *np.unique(list(song_labels.values()), return_counts=True))},
    }
    return ({"deam": deam, "models": os.path.join(root, "models")}, stats)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default="/tmp/ce_realdata")
    ap.add_argument("--out", default="REALDATA_r05.json")
    ap.add_argument("--songs", type=int, default=None,
                    help="limit songs (smoke); default: all 1802")
    ap.add_argument("--cnn-epochs", type=int, default=100,
                    help="CNN pretrain epochs per fold.  The reference "
                         "default is 200 (settings n_epochs_cnn); the "
                         "adam(40)->sgd schedule completes all transitions "
                         "at epoch 100, and the last 100 sgd_3 epochs at "
                         "lr=1e-5 move validation loss marginally — 100 is "
                         "the wall-clock-bounded choice, recorded in the "
                         "artifact")
    ap.add_argument("--skip-cnn", action="store_true")
    ap.add_argument("--skip-classic", action="store_true")
    ap.add_argument("--class-sep", type=float, default=None)
    ap.add_argument("--song-off", type=float, default=None)
    ap.add_argument("--frame-noise", type=float, default=None)
    ap.add_argument("--n-informative", type=int, default=None)
    args = ap.parse_args(argv)
    set_feature_model(n_informative=args.n_informative,
                      class_sep=args.class_sep, song_off=args.song_off,
                      frame_noise=args.frame_noise)

    t_start = time.time()
    rng = np.random.default_rng(1987)
    os.makedirs(args.root, exist_ok=True)
    stats_path = os.path.join(args.root, "tree_stats.json")
    #: everything that determines the generated tree's content — a cached
    #: tree is only reusable when ALL of it matches (existence alone is
    #: not freshness: a --songs 12 smoke tree must never be silently
    #: pretrained into a full-scale artifact, nor vice versa)
    fingerprint = {"songs_arg": args.songs, "seed": 1987,
                   "n_informative": N_INFORMATIVE, "class_sep": CLASS_SEP,
                   "song_off": SONG_OFF, "frame_noise": FRAME_NOISE}
    stats = None
    if os.path.exists(stats_path):
        with open(stats_path) as fh:
            stats = json.load(fh)
        if stats.get("fingerprint") != fingerprint:
            raise SystemExit(
                f"{args.root} holds a tree built with "
                f"{stats.get('fingerprint')}, but this run wants "
                f"{fingerprint} — pass a fresh --root or delete the old "
                "tree")
        print(f"reusing existing tree under {args.root}", flush=True)
        roots = {"deam": os.path.join(args.root, "deam"),
                 "models": os.path.join(args.root, "models")}
    else:
        print(f"building DEAM tree from REAL annotations under "
              f"{args.root} ...", flush=True)
        roots, stats = build_tree(args.root, args.songs, rng)
        stats["fingerprint"] = fingerprint
        with open(stats_path, "w") as fh:
            json.dump(stats, fh)
    print(f"  {stats['songs']} songs, {stats['frames']} frames, "
          f"class counts {stats['song_class_counts']}", flush=True)

    from consensus_entropy_tpu.config import PathsConfig, TrainConfig
    from consensus_entropy_tpu.data import deam
    from consensus_entropy_tpu.train import pretrain

    paths = PathsConfig(models_root=roots["models"],
                        deam_root=roots["deam"], amg_root=roots["deam"])
    out_dir = paths.pretrained_dir
    df = deam.load_dataset(paths.deam_features_dir,
                           os.path.join(roots["deam"], "annotations",
                                        "arousal.csv"),
                           os.path.join(roots["deam"], "annotations",
                                        "valence.csv"),
                           cache_csv=paths.deam_dataset_csv)
    print(f"joined frame table: {len(df)} rows", flush=True)

    results: dict = {}
    if not args.skip_classic:
        X, y, song_ids = deam.training_arrays(df)
        for model in ("gnb", "sgd", "xgb"):
            t0 = time.time()
            print(f"pretraining {model} (5 folds) ...", flush=True)
            results[model] = pretrain.pretrain_classic(
                model, X, y, song_ids, cv=5, out_dir=out_dir, seed=1987)
            results[model]["wall_s"] = round(time.time() - t0, 1)
    if not args.skip_cnn:
        from consensus_entropy_tpu.data.audio import device_store_from_npy

        per_song = df.groupby("song_id")["quadrants"].max()
        labels = {sid: int(q[1]) - 1 for sid, q in per_song.items()}
        store = device_store_from_npy(paths.deam_npy_dir, list(labels),
                                      59049)
        t0 = time.time()
        print(f"pretraining cnn_jax (5 folds x {args.cnn_epochs} epochs, "
              f"full geometry) ...", flush=True)
        results["cnn_jax"] = pretrain.pretrain_cnn(
            labels, store, cv=5, out_dir=out_dir,
            train_config=TrainConfig(), n_epochs=args.cnn_epochs,
            seed=1987, resume=True)
        results["cnn_jax"]["wall_s"] = round(time.time() - t0, 1)

    # per-fold detail from the pretrainer's own jsonl
    fold_detail = {}
    jsonl = os.path.join(out_dir, "pretrain_metrics.jsonl")
    if os.path.exists(jsonl):
        for line in open(jsonl):
            rec = json.loads(line)
            fold_detail[rec["model"]] = rec

    report = {
        "metric": "realdata_pretrain_f1",
        "what": "committee pretraining on the REAL DEAM dynamic "
                "annotations (the only real reference data mounted in "
                "this image) joined to schema-exact SYNTHETIC features "
                "and class-tone audio",
        "real": {
            "files": [os.path.join(REF_ANNO, "arousal.csv"),
                      os.path.join(REF_ANNO, "valence.csv")],
            "label_pipeline": "dropna per row; shorter annotation kept on "
                              "length mismatch (deam_classifier.py:75-83); "
                              "quadrant geometry (labels.py); "
                              "lexicographic-max song label "
                              "(deam_classifier.py:253)",
            **stats,
        },
        "synthetic": {
            "features": f"260-col openSMILE schema, {N_INFORMATIVE} "
                        f"informative cols, class sep {CLASS_SEP}, song "
                        f"offset {SONG_OFF}, frame noise {FRAME_NOISE}",
            "audio": "full-length class tones, sine timbre "
                     "(al.evidence.synth_tone family)",
            "caveat": "F1 here measures the synthetic features'/audio's "
                      "class separability under the REAL label structure "
                      "(incl. genuine frame-level label dynamics and the "
                      "real class imbalance) — NOT real-data difficulty. "
                      "Only the openSMILE/audio mounts block the "
                      "remaining gap.",
        },
        "results": results,
        "fold_detail": fold_detail,
        "paper_reference_f1": {
            "note": "BASELINE.md paper §5 final F1 after AL over 46 real "
                    "users — different data AND different stage (post-AL "
                    "vs pretrain CV); juxtaposed for orientation only",
            "cnn": 0.48, "sgd": 0.457, "xgb": 0.39, "gnb": 0.238,
        },
        "registry_dir": out_dir,
        "cnn_epochs": args.cnn_epochs,
        "wall_s_total": round(time.time() - t_start, 1),
    }
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2)
    print(json.dumps({"metric": report["metric"],
                      "value": {m: results[m]["f1"]["mean"]
                                for m in results},
                      "unit": "weighted F1 (5-fold CV mean)"}))
    print(f"wrote {args.out}; registry at {out_dir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
