#!/usr/bin/env bash
# Elastic fabric CI gate (ISSUE 13 satellite; sits next to slo_check.sh
# and is run by scripts/fault_matrix.sh).
#
# Runs a REAL 2-host ELASTIC fabric (worker subprocesses over the
# synthetic tests/fabric_workload users, two pool-size buckets),
# SIGKILLs h0 at its first admission, then:
#   1. asserts the autoscaler RESPAWNED a replacement (spawn journaled,
#      fresh host id in the replayed fleet shape) and every user
#      finished bit-identical to unfaulted sequential baselines,
#   2. schema-validates the main admission journal AND every per-host
#      event WAL (structural: known events, required fields, monotone
#      seq, torn tails tolerated),
#   3. asserts the fleet planner's MERGED edges ended identical on
#      every surviving host (each worker's last fleet-adopted planner
#      record) and match the main journal's restored edges.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import glob
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from tests.fabric_workload import (
    make_cfg,
    read_results,
    sequential_baselines,
    sizes_arg,
    user_specs,
)

from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths

cfg = make_cfg("mc", epochs=2)
specs = user_specs(6, sizes=[30, 100])
root = tempfile.mkdtemp(prefix="elastic_check_")
seq = sequential_baselines(root, cfg, specs)
fabric_dir = os.path.join(root, "fabric")
os.makedirs(fabric_dir)
jp = os.path.join(fabric_dir, "serve_journal.jsonl")
journal = AdmissionJournal(jp)


def spawn(host_id):
    log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "tests/fabric_worker.py", fabric_dir,
             host_id, root, cfg.mode, str(cfg.epochs), str(len(specs)),
             "5.0", "3", sizes_arg(specs)],
            stdout=log, stderr=subprocess.STDOUT,
            env={**os.environ, "PYTHONPATH": "."})
    finally:
        log.close()


state = {"killed": False}


def chaos(coord):
    st = coord.journal.state
    if not state["killed"] and any(
            h == "h0" and st.last.get(u) == "admit"
            for u, h in st.assigned.items()):
        coord.hosts["h0"].proc.kill()
        state["killed"] = True


coord = FabricCoordinator(
    journal, fabric_dir,
    FabricConfig(hosts=2, min_hosts=2, max_hosts=3, planner_epoch=4),
    on_poll=chaos)
summary = coord.run([u for _, u, _ in specs], spawn,
                    pools={u: n for _, u, n in specs})
journal.close()

# 1. kill exercised, replacement respawned, all users bit-identical
assert state["killed"], "h0 was never killed"
assert summary["revocations"] == 1 and summary["spawns"] >= 1, summary
assert sorted(summary["finished"]) == sorted(u for _, u, _ in specs)
results = read_results(fabric_dir)
for _, uid, _ in specs:
    assert results[uid]["error"] is None
    assert results[uid]["result"]["trajectory"] == seq[uid]["trajectory"]
st = AdmissionJournal(jp).state
assert st.hosts["h0"] == "revoke"
assert "h2" in st.fleet_hosts(), st.fleet_hosts()
print(f"elastic_check: kill+respawn recovered {len(specs)} users "
      f"bit-identical (spawns={summary['spawns']}, "
      f"joins={summary['joins']}, migrations={summary['migrations']})")

# 2. every journal/WAL validates structurally
bad = validate_journal_file(jp)
for wal in sorted(glob.glob(os.path.join(fabric_dir, "events_*.jsonl"))):
    bad += validate_journal_file(wal)
assert bad == [], "journal violations:\n" + "\n".join(bad[:10])
print("elastic_check: main journal + per-host WALs schema-valid")

# 3. merged planner edges identical on every surviving host
per_host = {}
for hid, status in summary["hosts"].items():
    if status == "revoked":
        continue
    last = None
    for rec in export.read_jsonl_tolerant(
            os.path.join(fabric_dir, f"events_{hid}.jsonl")):
        if rec.get("event") == "planner" and rec.get("fleet"):
            last = tuple(rec.get("edges") or ())
    if last is not None:
        per_host[hid] = last
assert per_host, "no host ever adopted fleet edges"
assert len(set(per_host.values())) == 1, per_host
fleet = summary.get("fleet_planner") or {}
assert list(next(iter(per_host.values()))) == fleet.get("edges"), \
    (per_host, fleet)
assert st.planner_edges == fleet.get("edges")
print(f"elastic_check: merged edges identical on every host "
      f"{sorted(per_host)} -> {fleet.get('edges')}")
PY
echo "elastic check passed"
