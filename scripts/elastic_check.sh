#!/usr/bin/env bash
# Elastic fabric CI gate (ISSUE 13 satellite + the ISSUE 14 drain and
# migrate leg; sits next to slo_check.sh and is run by
# scripts/fault_matrix.sh).
#
# LEG 1 — kill + respawn: a REAL 2-host ELASTIC fabric (worker
# subprocesses over the synthetic tests/fabric_workload users, two
# pool-size buckets), h0 SIGKILLed at its first admission, then:
#   1. asserts the autoscaler RESPAWNED a replacement (spawn journaled,
#      fresh host id in the replayed fleet shape) and every user
#      finished bit-identical to unfaulted sequential baselines,
#   2. schema-validates the main admission journal AND every per-host
#      event WAL (structural: known events, required fields, monotone
#      seq, torn tails tolerated),
#   3. asserts the fleet planner's MERGED edges ended identical on
#      every surviving host (each worker's last fleet-adopted planner
#      record) and match the main journal's restored edges.
#
# LEG 2 — drain + migrate: a REAL 3-host elastic fabric whose
# low-water mark holds from the start, so it SCALES DOWN mid-run; the
# coordinator is killed (in-process InjectedKill) at EACH new fault
# point — fabric.drain, fabric.migrate.fence, fabric.migrate.commit —
# and rerun; after each rerun the journal must validate, every user
# must finish bit-identical to sequential, and the EXACTLY-ONE-OWNER
# invariant must hold (each user has exactly one result row across
# every host's results file — no user ever ran to completion twice).
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import glob
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from tests.fabric_workload import (
    force_low_water,
    make_cfg,
    read_results,
    sequential_baselines,
    sizes_arg,
    user_specs,
)

from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths

cfg = make_cfg("mc", epochs=2)
specs = user_specs(6, sizes=[30, 100])
root = tempfile.mkdtemp(prefix="elastic_check_")
seq = sequential_baselines(root, cfg, specs)
fabric_dir = os.path.join(root, "fabric")
os.makedirs(fabric_dir)
jp = os.path.join(fabric_dir, "serve_journal.jsonl")
journal = AdmissionJournal(jp)


def spawn(host_id):
    log = open(fabric_paths(fabric_dir, host_id)["log"], "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "tests/fabric_worker.py", fabric_dir,
             host_id, root, cfg.mode, str(cfg.epochs), str(len(specs)),
             "5.0", "3", sizes_arg(specs)],
            stdout=log, stderr=subprocess.STDOUT,
            env={**os.environ, "PYTHONPATH": "."})
    finally:
        log.close()


state = {"killed": False}


def chaos(coord):
    st = coord.journal.state
    if not state["killed"] and any(
            h == "h0" and st.last.get(u) == "admit"
            for u, h in st.assigned.items()):
        coord.hosts["h0"].proc.kill()
        state["killed"] = True


coord = FabricCoordinator(
    journal, fabric_dir,
    FabricConfig(hosts=2, min_hosts=2, max_hosts=3, planner_epoch=4),
    on_poll=chaos)
summary = coord.run([u for _, u, _ in specs], spawn,
                    pools={u: n for _, u, n in specs})
journal.close()

# 1. kill exercised, replacement respawned, all users bit-identical
assert state["killed"], "h0 was never killed"
assert summary["revocations"] == 1 and summary["spawns"] >= 1, summary
assert sorted(summary["finished"]) == sorted(u for _, u, _ in specs)
results = read_results(fabric_dir)
for _, uid, _ in specs:
    assert results[uid]["error"] is None
    assert results[uid]["result"]["trajectory"] == seq[uid]["trajectory"]
st = AdmissionJournal(jp).state
assert st.hosts["h0"] == "revoke"
assert "h2" in st.fleet_hosts(), st.fleet_hosts()
print(f"elastic_check: kill+respawn recovered {len(specs)} users "
      f"bit-identical (spawns={summary['spawns']}, "
      f"joins={summary['joins']}, migrations={summary['migrations']})")

# 2. every journal/WAL validates structurally
bad = validate_journal_file(jp)
for wal in sorted(glob.glob(os.path.join(fabric_dir, "events_*.jsonl"))):
    bad += validate_journal_file(wal)
assert bad == [], "journal violations:\n" + "\n".join(bad[:10])
print("elastic_check: main journal + per-host WALs schema-valid")

# 3. merged planner edges identical on every surviving host
per_host = {}
for hid, status in summary["hosts"].items():
    if status == "revoked":
        continue
    last = None
    for rec in export.read_jsonl_tolerant(
            os.path.join(fabric_dir, f"events_{hid}.jsonl")):
        if rec.get("event") == "planner" and rec.get("fleet"):
            last = tuple(rec.get("edges") or ())
    if last is not None:
        per_host[hid] = last
assert per_host, "no host ever adopted fleet edges"
assert len(set(per_host.values())) == 1, per_host
fleet = summary.get("fleet_planner") or {}
assert list(next(iter(per_host.values()))) == fleet.get("edges"), \
    (per_host, fleet)
assert st.planner_edges == fleet.get("edges")
print(f"elastic_check: merged edges identical on every host "
      f"{sorted(per_host)} -> {fleet.get('edges')}")

# ---- LEG 2: drain + migrate, killed at every new fault point ----------

from consensus_entropy_tpu.resilience import faults as faults_mod
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import FabricError  # noqa: F401

# slow-host workers (pool.score delay rule, injected via CETPU_FAULTS
# below) keep in-flight sessions alive through the drain decision, so
# the fence window reliably opens
cfg2 = make_cfg("mc", epochs=3)
specs2 = user_specs(6, sizes=[30, 100])
root2 = tempfile.mkdtemp(prefix="elastic_check_drain_")
seq2 = sequential_baselines(root2, cfg2, specs2)

for point in ("fabric.drain", "fabric.migrate.fence",
              "fabric.migrate.commit"):
    slug = point.replace(".", "_")
    fdir = os.path.join(root2, "fabric_" + slug)
    # each leg gets its OWN workspace root: a shared one would hand the
    # later legs already-complete fab_* workspaces (users resolve
    # instantly, nothing in flight, no fence to kill at)
    ws2 = os.path.join(root2, "ws_" + slug)
    os.makedirs(fdir)
    os.makedirs(ws2)
    jp2 = os.path.join(fdir, "serve_journal.jsonl")

    def spawn2(host_id, fdir=fdir, ws2=ws2):
        log = open(fabric_paths(fdir, host_id)["log"], "ab")
        try:
            return subprocess.Popen(
                [sys.executable, "tests/fabric_worker.py", fdir,
                 host_id, ws2, cfg2.mode, str(cfg2.epochs),
                 str(len(specs2)), "5.0", "2", sizes_arg(specs2)],
                stdout=log, stderr=subprocess.STDOUT,
                # the pool.score delay rule = slow-host simulation:
                # sessions outlive the fence round-trip, values untouched
                env={**os.environ, "PYTHONPATH": ".",
                     "CETPU_FAULTS": "pool.score:delay=0.3@1x-1"})
        finally:
            log.close()

    # the low-water TIMER is forced (tests.fabric_workload.
    # force_low_water, via on_poll) the moment every joined host holds
    # an in-flight user, so the drain victim always has sessions to
    # fence — the kill lands at a deterministic state instead of racing
    # worker start-up on a loaded CI box
    fcfg = FabricConfig(hosts=3, min_hosts=2, max_hosts=3,
                        scale_down_s=600.0, drain_timeout_s=30.0)
    killed = False
    journal2 = AdmissionJournal(jp2)
    try:
        with faults_mod.inject(FaultRule(point, "kill", at=1)):
            FabricCoordinator(journal2, fdir, fcfg,
                              on_poll=force_low_water).run(
                [u for _, u, _ in specs2], spawn2,
                pools={u: n for _, u, n in specs2})
    except InjectedKill:
        killed = True
    finally:
        journal2.close()
    assert killed, f"{point} never fired (no drain/fence reached?)"

    # the rerun replays the journal and finishes everything (the
    # drain-kill leg re-decides its drain through the same forced
    # low-water hook; the fence/commit legs already journaled theirs,
    # so the hook's 3-joined-hosts guard never fires there)
    journal2 = AdmissionJournal(jp2)
    try:
        summary2 = FabricCoordinator(journal2, fdir, fcfg,
                                     on_poll=force_low_water).run(
            [u for _, u, _ in specs2], spawn2,
            pools={u: n for _, u, n in specs2})
    finally:
        journal2.close()
    st2 = AdmissionJournal(jp2).state
    assert st2.finished == {u for _, u, _ in specs2} and not st2.pending
    assert len(st2.fleet_hosts()) == 2, st2.hosts  # scaled down
    bad2 = validate_journal_file(jp2)
    for wal in sorted(glob.glob(os.path.join(fdir, "events_*.jsonl"))):
        bad2 += validate_journal_file(wal)
    assert bad2 == [], "journal violations:\n" + "\n".join(bad2[:10])
    # EXACTLY-ONE-OWNER: each user has exactly one result row across
    # every host's results file, bit-identical to sequential
    rows = {}
    for fname in sorted(os.listdir(fdir)):
        if fname.startswith("results_") and fname.endswith(".jsonl"):
            for rec in export.read_jsonl_tolerant(
                    os.path.join(fdir, fname)):
                rows.setdefault(rec["user"], []).append(rec)
    for _, uid, _ in specs2:
        assert len(rows[uid]) == 1, (uid, rows[uid])
        assert rows[uid][0]["error"] is None
        assert rows[uid][0]["result"]["trajectory"] \
            == seq2[uid]["trajectory"]
    print(f"elastic_check: kill@{point} -> replayed to "
          f"{len(st2.fleet_hosts())} hosts, {len(specs2)} users "
          f"finished exactly once, parity exact "
          f"(drains={summary2['drains']}, fences={summary2['fences']})")
PY
echo "elastic check passed"
