#!/usr/bin/env bash
# Soak / load-generation CI gate (ISSUE 17 tentpole; sits next to
# remedy_check.sh and is run by scripts/fault_matrix.sh).
#
# LEG 1 — compressed deterministic soak: a seeded trace (poisson
# arrivals, interactive/batch mix, cycled pool sizes, churn) is
# generated, saved, LOADED BACK and played at 0.1x against a REAL
# 2-host keep-open fabric with the burn-rate admission hold armed on a
# deliberately tight interactive SLO.  Asserted:
#   1. zero user loss (journal dispositions) + schema-valid journal and
#      metrics streams, graded through workload.grade,
#   2. at least one slo_headroom alert FIRED (schema-valid `alert`
#      event in a metrics stream) and GRADED (alert counts),
#   3. at least one journaled admission hold (`remedy` record, action
#      admission_hold) and at least one churn disconnect,
#   4. per-user parity vs unfaulted sequential baselines,
#   5. the cetpu-soak CLI round-trips: `digest` pins the trace file,
#      `grade` exits 0 over the finished run directory.
#
# LEG 2 — coordinator killed MID-SOAK at the ``fabric.remedy`` fault
# point (which fires BEFORE the hold decision journals, so the kill
# leaves no half-journaled remedy): the driver is stopped, the journal
# replayed by a fresh coordinator which re-admits every trace user, and
# the rerun must finish EVERY user EXACTLY ONCE across every host's
# results file — still bit-identical to sequential.
#
# LEG 3 — the adversarial SKEW pool distribution (ISSUE 18 satellite):
# a second compressed soak whose trace piles 80% of users onto ONE
# seeded hot pool size (workload.trace SKEW_FRAC) — the single-bucket
# stampede the planner sketch and bucketed admission must absorb.
# Asserted: the drawn shape is actually skewed (hot size holds a
# strict majority, the cold size still drawn), zero loss, schema-valid
# streams, per-class p50/p95/p99 percentile rows graded for BOTH
# classes, and per-user parity vs sequential baselines over the
# trace-drawn sizes.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import glob
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from tests.fabric_workload import (
    make_cfg,
    sequential_baselines,
    sizes_arg,
    user_specs,
)

from consensus_entropy_tpu.cli.soak import main as soak_main
from consensus_entropy_tpu.fleet import FleetReport
from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.obs.alerts import AlertWatcher
from consensus_entropy_tpu.obs.status import StatusWriter
from consensus_entropy_tpu.resilience import faults as faults_mod
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    validate_journal_file,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths
from consensus_entropy_tpu.workload import (
    FabricTarget,
    TraceDriver,
    TraceSpec,
    generate,
    grade_run,
    load,
    save,
)

N_USERS = 6
cfg = make_cfg("mc", epochs=2)
# cycled 30/100 pools: the known-trainable sizing every fabric gate
# uses, and the skewed two-bucket shape the planner sketch sees
specs = user_specs(N_USERS, sizes=[30, 100])
root = tempfile.mkdtemp(prefix="soak_check_")
seq = sequential_baselines(root, cfg, specs)

# a 60-virtual-second trace played at 0.1x (the compressed clock); the
# seed scan (deterministic — first hit wins) guarantees the class mix
# actually drew both classes, so the tight interactive SLO has users to
# burn on and the batch lane stays populated
spec = None
for seed in range(5, 105):
    cand = TraceSpec(
        seed=seed, n_users=N_USERS, arrival="poisson", rate=1.0,
        class_mix=(("interactive", 0.5), ("batch", 0.5)),
        pool_dist="cycle", pool_sizes=(30, 100),
        churn_frac=0.34, churn_delay_s=10.0, reconnect_s=20.0,
        horizon_s=60.0)
    classes = {e["cls"] for e in generate(cand).events
               if e["kind"] == "arrive"}
    if classes == {"interactive", "batch"}:
        spec = cand
        break
assert spec is not None, "no two-class trace seed in the scan range"
trace_path = os.path.join(root, "trace.jsonl")
save(generate(spec), trace_path)
tr = load(trace_path)
pools = {e["user"]: e["pool"] for e in tr.events
         if e["kind"] == "arrive"}
cls_of = {e["user"]: e["cls"] for e in tr.events
          if e["kind"] == "arrive"}
SLO = {"interactive": 0.5, "batch": 600.0}


def fabric_cfg():
    # the tight interactive SLO: real AL users take seconds end to
    # end, so the burn detector MUST arm and the hold MUST fire
    return FabricConfig(hosts=2, lease_s=5.0, hold_on_burn=True,
                        admission_hold_s=0.5, remedy_hold_s=0.3,
                        remedy_cooldown_s=3.0,
                        slo_interactive_s=SLO["interactive"],
                        slo_batch_s=SLO["batch"])


def make_spawn(fdir, ws, specs_=specs):
    def spawn(host_id):
        log = open(fabric_paths(fdir, host_id)["log"], "ab")
        env = {**os.environ, "PYTHONPATH": ".",
               "CETPU_FABRIC_METRICS": "1"}
        env.pop("CETPU_FAULTS", None)
        try:
            return subprocess.Popen(
                [sys.executable, "tests/fabric_worker.py", fdir,
                 host_id, ws, cfg.mode, str(cfg.epochs), str(N_USERS),
                 "5.0", "2", sizes_arg(specs_)],
                stdout=log, stderr=subprocess.STDOUT, env=env)
        finally:
            log.close()
    return spawn


def check_parity_and_owners(fdir, label, specs_=specs, seq_=seq):
    jp = os.path.join(fdir, "serve_journal.jsonl")
    bad = validate_journal_file(jp)
    for wal in sorted(glob.glob(os.path.join(fdir, "events_*.jsonl"))):
        bad += validate_journal_file(wal)
    assert bad == [], "journal violations:\n" + "\n".join(bad[:10])
    rows = {}
    for fname in sorted(os.listdir(fdir)):
        if fname.startswith("results_") and fname.endswith(".jsonl"):
            for rec in export.read_jsonl_tolerant(
                    os.path.join(fdir, fname)):
                rows.setdefault(rec["user"], []).append(rec)
    for _, uid, _ in specs_:
        assert len(rows.get(uid, [])) == 1, (label, uid, rows.get(uid))
        assert rows[uid][0]["error"] is None, (label, uid)
        assert rows[uid][0]["result"]["trajectory"] \
            == seq_[uid]["trajectory"], (label, uid)


# ---- LEG 1: the compressed soak ---------------------------------------
fdir1 = os.path.join(root, "fabric_soak")
ws1 = os.path.join(root, "ws_soak")
os.makedirs(fdir1)
os.makedirs(ws1)
jp1 = os.path.join(fdir1, "serve_journal.jsonl")
journal = AdmissionJournal(jp1)
report = FleetReport(os.path.join(fdir1, "fleet_metrics_fleet.jsonl"))
# the StatusWriter matters: alert evaluation runs on the status-write
# path, so without it the watcher never emits the slo_headroom event
coord = FabricCoordinator(
    journal, fdir1, fabric_cfg(), report=report,
    alerts=AlertWatcher(report),
    status=StatusWriter(os.path.join(fdir1, "status"), "coordinator",
                        interval_s=0.2))
driver = TraceDriver(tr, FabricTarget(coord), time_scale=0.1,
                     backoff_seed=3)
driver.start()
try:
    summary = coord.run([], make_spawn(fdir1, ws1), keep_open=True)
finally:
    assert driver.join(timeout=120.0), "trace driver wedged"
    journal.close()
    report.close()

g = grade_run(fdir1, journal_path=jp1, trace=tr, slo_s=SLO,
              driver_stats=driver.stats.as_dict())
det = g["deterministic"]
assert det["zero_loss"], det["lost_users"]
assert det["journal_ok"], g["measured"]["journal_errors"]
assert det["stream_ok"], g["measured"]["stream_errors"]
assert summary["holds"] >= 1, summary
assert summary["disconnects"] >= 1, summary
assert g["measured"]["alerts"].get("slo_headroom", 0) >= 1, \
    g["measured"]["alerts"]
hold_recs = [r for r in export.read_jsonl_tolerant(jp1)
             if r.get("event") == "remedy"
             and r.get("action") == "admission_hold"]
assert hold_recs, "no journaled admission hold"
check_parity_and_owners(fdir1, "soak")
print(f"soak_check: compressed soak drained clean — "
      f"{det['finished']}/{N_USERS} finished, holds={summary['holds']}, "
      f"disconnects={summary['disconnects']}, "
      f"alerts={g['measured']['alerts']}, parity exact")

# the CLI round-trip: digest pins the trace, grade gates the run dir
assert soak_main(["digest", trace_path]) == 0
assert soak_main(["grade", fdir1, "--journal", jp1, "--trace",
                  trace_path, "--slo",
                  "interactive=0.5,batch=600"]) == 0
print("soak_check: cetpu-soak digest + grade ok")

# ---- LEG 2: coordinator killed mid-soak at fabric.remedy --------------
fdir2 = os.path.join(root, "fabric_kill")
ws2 = os.path.join(root, "ws_kill")
os.makedirs(fdir2)
os.makedirs(ws2)
jp2 = os.path.join(fdir2, "serve_journal.jsonl")
journal2 = AdmissionJournal(jp2)
coord2 = FabricCoordinator(journal2, fdir2, fabric_cfg(),
                           report=FleetReport())
driver2 = TraceDriver(tr, FabricTarget(coord2), time_scale=0.1,
                      backoff_seed=3)
killed = False
driver2.start()
try:
    try:
        with faults_mod.inject(FaultRule("fabric.remedy", "kill", at=1)):
            coord2.run([], make_spawn(fdir2, ws2), keep_open=True)
    except InjectedKill:
        killed = True
finally:
    driver2.stop()
    driver2.join(timeout=30.0)
    journal2.close()
assert killed, "fabric.remedy never fired mid-soak"
# the in-process kill leaves the dead coordinator's per-host WAL
# handles (and their single-writer flocks) open — release them so the
# rerun coordinator can take the locks, then drop the object
for h in coord2.hosts.values():
    h.assign.close()
    h.tail.close()
    if h.span_tail is not None:
        h.span_tail.close()
del coord2
# fired-before-append: the killed hold never reached the journal
assert [r for r in export.read_jsonl_tolerant(jp2)
        if r.get("event") == "remedy"] == []

# the rerun: replay the journal AND re-admit every trace user (arrivals
# the dead intake swallowed were never journaled).  Users the killed
# incarnation already finished replay as terminal — the rerun must
# finish EXACTLY the complement, and the ownership check below proves
# nobody ran twice across the two incarnations.
done_before = {
    u for u, d in grade_run(fdir2, journal_path=jp2, trace=tr)
    ["deterministic"]["dispositions"].items() if d == "finish"}
journal3 = AdmissionJournal(jp2)
try:
    summary3 = FabricCoordinator(journal3, fdir2, fabric_cfg(),
                                 report=FleetReport()).run(
        tr.users, make_spawn(fdir2, ws2),
        classes=cls_of, pools=pools)
finally:
    journal3.close()
assert sorted(summary3["finished"]) \
    == sorted(set(tr.users) - done_before), (summary3, done_before)
check_parity_and_owners(fdir2, "kill")
g2 = grade_run(fdir2, journal_path=jp2, trace=tr)
assert g2["deterministic"]["zero_loss"], g2["deterministic"]
print(f"soak_check: kill@fabric.remedy mid-soak replayed clean — "
      f"{N_USERS} users finished exactly once, parity exact")

# ---- LEG 3: the adversarial SKEW pool distribution --------------------
# 80% of users pile onto ONE seeded hot size (workload.trace
# SKEW_FRAC): the single-bucket stampede.  The seed scan (first hit
# wins) requires both classes, a STRICT hot-size majority with the
# cold size still drawn, and every user's trace-drawn pool trainable
# (all 4 classes present in its pre-training labels).
from tests.fabric_workload import make_data

spec3 = sizes3 = None
for seed in range(11, 211):
    cand = TraceSpec(
        seed=seed, n_users=N_USERS, arrival="poisson", rate=1.0,
        class_mix=(("interactive", 0.5), ("batch", 0.5)),
        pool_dist="skew", pool_sizes=(30, 100),
        churn_frac=0.34, churn_delay_s=10.0, reconnect_s=20.0,
        horizon_s=60.0)
    ev = [e for e in generate(cand).events if e["kind"] == "arrive"]
    pool_of = {e["user"]: e["pool"] for e in ev}
    sizes = [pool_of[f"u{i}"] for i in range(N_USERS)]
    hot_n = max(sizes.count(s) for s in set(sizes))
    if ({e["cls"] for e in ev} == {"interactive", "batch"}
            and len(set(sizes)) == 2 and hot_n > N_USERS // 2 + 1
            and all(len(set(make_data(100 + i, f"u{i}", n_songs=n)
                            .labels.values())) == 4
                    for i, n in enumerate(sizes))):
        spec3, sizes3 = cand, sizes
        break
assert spec3 is not None, "no skewed trace seed in the scan range"
hot = max(set(sizes3), key=sizes3.count)
trace3_path = os.path.join(root, "trace_skew.jsonl")
save(generate(spec3), trace3_path)
tr3 = load(trace3_path)
specs3 = user_specs(N_USERS, sizes=sizes3)
root3 = os.path.join(root, "seq_skew")
os.makedirs(root3)
seq3 = sequential_baselines(root3, cfg, specs3)

fdir3 = os.path.join(root, "fabric_skew")
ws3 = os.path.join(root, "ws_skew")
os.makedirs(fdir3)
os.makedirs(ws3)
jp3 = os.path.join(fdir3, "serve_journal.jsonl")
journal3b = AdmissionJournal(jp3)
report3 = FleetReport(os.path.join(fdir3, "fleet_metrics_fleet.jsonl"))
coord3 = FabricCoordinator(
    journal3b, fdir3, fabric_cfg(), report=report3,
    alerts=AlertWatcher(report3),
    status=StatusWriter(os.path.join(fdir3, "status"), "coordinator",
                        interval_s=0.2))
driver3 = TraceDriver(tr3, FabricTarget(coord3), time_scale=0.1,
                      backoff_seed=3)
driver3.start()
try:
    summary_skew = coord3.run([], make_spawn(fdir3, ws3, specs3),
                              keep_open=True)
finally:
    assert driver3.join(timeout=120.0), "skew trace driver wedged"
    journal3b.close()
    report3.close()

g3 = grade_run(fdir3, journal_path=jp3, trace=tr3, slo_s=SLO,
               driver_stats=driver3.stats.as_dict())
det3 = g3["deterministic"]
assert det3["zero_loss"], det3["lost_users"]
assert det3["journal_ok"], g3["measured"]["journal_errors"]
assert det3["stream_ok"], g3["measured"]["stream_errors"]
# per-class percentile rows graded for BOTH classes of the stampede
per_cls = g3["measured"]["per_class"]
for cls in ("interactive", "batch"):
    row = per_cls.get(cls)
    assert row and row["n"] >= 1, (cls, per_cls)
    assert all(k in row for k in ("p50_s", "p95_s", "p99_s")), row
check_parity_and_owners(fdir3, "skew", specs3, seq3)
print(f"soak_check: skew soak drained clean — hot pool {hot} held "
      f"{sizes3.count(hot)}/{N_USERS} users (sizes={sizes3}), "
      f"per-class percentiles graded "
      f"{ {c: round(r['p95_s'], 2) for c, r in per_cls.items()} }, "
      f"parity exact")
PY
echo "soak check passed"
