#!/usr/bin/env bash
# Tracing-overhead race (ISSUE 9 acceptance: traced-vs-untraced serve
# throughput overhead <= 3%, parity asserted every rep).
#
# Runs `bench.py --suite obs`: a serve run with the obs span tracer
# writing a real spans.jsonl vs the --no-trace arm over IDENTICAL users
# and seeds, interleaved with alternating order per rep.  The headline
# is the MEDIAN of per-rep paired wall ratios (pairing cancels the
# throttled box's slow drift); the identical-arm noise floor and the
# deterministic per-span emit cost ride along in the artifact so the
# number reads in context.  Every traced rep also schema-validates its
# fleet_metrics.jsonl and asserts the merged span set is orphan-free
# with a loadable Chrome export.
#
# The JSON line goes to stdout (redirect to BENCH_obs_r<N>.json to
# commit an artifact); the per-rep log goes to stderr.  Extra bench
# args pass through, e.g.:
#   scripts/obs_bench.sh --users 8 --reps 7
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite obs "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite obs \
        --users 6 --pool 100 --fleet 3 --reps 5 --al-epochs 2
fi
