#!/usr/bin/env bash
# Introspection-overhead race (ISSUE 9 acceptance: traced-vs-untraced
# serve throughput overhead <= 3%, parity asserted every rep; the
# ISSUE 15 plane rides the same arms).
#
# Runs `bench.py --suite obs`: a serve run with the WHOLE introspection
# plane live (span tracer writing a real spans.jsonl, compile events,
# status snapshots refreshing, alert watcher evaluating) vs the
# everything-off arm over IDENTICAL users and seeds, interleaved with
# alternating order per rep.  The headline is the MEDIAN of per-rep
# paired wall ratios (pairing cancels the throttled box's slow drift);
# the identical-arm noise floor and the deterministic per-span emit
# cost ride along in the artifact so the number reads in context.
# Every plane-on rep also schema-validates its fleet_metrics.jsonl,
# asserts the merged span set is orphan-free with a loadable Chrome
# export, and validates its final status snapshot.
#
# The JSON line goes to stdout (redirect to BENCH_obs_r<N>.json to
# commit an artifact); the per-rep log goes to stderr.  Extra bench
# args pass through, e.g.:
#   scripts/obs_bench.sh --users 8 --reps 7
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite obs "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite obs \
        --users 6 --pool 100 --fleet 3 --reps 5 --al-epochs 2
fi
