#!/usr/bin/env bash
# SLO-admission CI gate (ISSUE 11 satellite; sits next to obs_check.sh).
#
# Runs a REAL planner-enabled 2-class serve cohort over the synthetic
# workload, then:
#   1. schema-validates EVERY fleet_metrics.jsonl line (the v2 table now
#      includes the cls fields and the planner_edges/admission_hold
#      events),
#   2. asserts the per-class admission→finish histograms and the
#      planner-decision events are present,
#   3. asserts the journal REPLAYS to identical bucket edges — a fresh
#      AdmissionPlanner restored from the replayed journal derives the
#      same routing the live run used.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import os
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import bench
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    AdmissionPlanner,
    BucketRouter,
    FleetServer,
    ServeConfig,
)

cfg = ALConfig(queries=8, epochs=2, mode="mc", seed=1987,
               ckpt_dtype="float32")
users = bench._fleet_workload(4, 80, 96, cfg.seed)
root = tempfile.mkdtemp(prefix="slo_check_")
users_dir = os.path.join(root, "users")
metrics_path = os.path.join(users_dir, "fleet_metrics.jsonl")
journal_path = os.path.join(users_dir, "serve_journal.jsonl")

report = FleetReport(metrics_path)
journal = AdmissionJournal(journal_path)
sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                       user_timings=False)
serve_cfg = ServeConfig(target_live=2, planner_epoch=2)
server = FleetServer(sched, serve_cfg, journal=journal)
entries = [FleetUser(d.user_id, f(), d, bench._mkdir(root, f"u{i}"),
                     seed=cfg.seed,
                     priority="interactive" if i % 2 else "batch")
           for i, (d, f) in enumerate(users)]
for e in entries:
    server.submit(e)
server.close_intake()
recs = server.serve(())
assert len(recs) == 4 and all(r["error"] is None for r in recs), recs
summary = report.write_summary(cohort=2)
report.close()
live_edges = server.planner.edges
journal.close()
assert live_edges, "planner derived no edges"

# 1. every metrics line validates against the v2 schema
errors = export.validate_metrics_file(metrics_path)
assert errors == [], "schema violations:\n" + "\n".join(errors[:10])
n_lines = len(export.read_jsonl_tolerant(metrics_path))
print(f"slo_check: {n_lines} metrics lines schema-valid")

# 2. per-class histograms + planner-decision events are present
per_class = summary.get("per_class") or {}
assert set(per_class) == {"batch", "interactive"}, per_class
for cls, c in per_class.items():
    snap = c["admission_to_finish_s"]
    assert snap and snap["n"] == 2, (cls, snap)
assert summary.get("planner", {}).get("edges") == list(live_edges)
events = export.read_jsonl_tolerant(metrics_path)
assert any(e.get("event") == "planner_edges" for e in events), \
    "no planner-decision events in the metrics stream"
assert all(e.get("cls") for e in events
           if e.get("event") in ("enqueue", "admit"))
print(f"slo_check: per-class histograms + planner events present "
      f"(edges {list(live_edges)})")

# 3. the journal replays to identical edges
with AdmissionJournal(journal_path) as replayed:
    assert replayed.recovered
    router = BucketRouter()
    restored = AdmissionPlanner(serve_cfg, router=router,
                                journal=replayed)
    assert restored.edges == live_edges, (restored.edges, live_edges)
    assert router.widths == live_edges
    assert set(replayed.state.classes.values()) \
        == {"batch", "interactive"}
print(f"slo_check: journal replays to identical edges {list(live_edges)}")
PY
echo "slo check passed"
