#!/usr/bin/env bash
# Pool-axis mesh serving CI gate (ISSUE 18 satellite; sits next to
# elastic_check.sh and is run by scripts/fault_matrix.sh).
#
# LEG 1 — multi-device parity + telemetry: every mesh-marked test
# EXCEPT the fault drill — the 2-device parity pin over all thirteen
# scoring/fused families, the slow 4/8-device sweep of the sharded
# fleet families + donated scatter, the (fn, width, n_devices)
# jit-family telemetry determinism pin (family set identical across an
# in-process restart), the mesh/composition config validation units,
# the devices-aware placement units, and the mesh-arm serve run whose
# compile events must carry the real device count.  The tests run
# under tests/conftest.py's 8 virtual CPU devices — the same code path
# XLA uses on a TPU slice, minus ICI.
#
# LEG 2 — sharded-worker SIGKILL failover: a REAL 2-host fabric where
# h0 serves through a 4-device mesh (CETPU_MESH_DEVICES=4 in the
# worker) and h1 through a single chip; h0 is SIGKILLed at its first
# admission and every user must fail over to the NARROWER survivor and
# finish bit-identical to unfaulted sequential baselines — pinning
# that sharded and unsharded execution of the same journaled state are
# interchangeable mid-flight.
#
# LEG 3 — bench-path digest parity: a compressed `bench.py --suite
# mesh` run (small pool, K in {1,2,4}) whose per-iteration selection
# digests must be bit-equal across every arm — the same gate the full
# BENCH_mesh artifact asserts, exercised cheaply on every CI run.
#
# Extra pytest args pass through to LEG 1, e.g.:
#   scripts/mesh_check.sh -k parity
set -euo pipefail

cd "$(dirname "$0")/.."

echo "mesh_check leg 1/3: multi-device parity sweep + telemetry pins"
JAX_PLATFORMS=cpu python -m pytest tests/test_pool_mesh.py \
  -v -m "mesh and not faults" -p no:cacheprovider "$@"

echo "mesh_check leg 2/3: sharded-worker SIGKILL failover drill"
JAX_PLATFORMS=cpu python -m pytest \
  "tests/test_pool_mesh.py::test_mesh_worker_sigkill_fails_over_to_narrow_survivor" \
  -v -p no:cacheprovider

echo "mesh_check leg 3/3: bench-path selection-digest parity (K=1,2,4)"
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite mesh \
  --mesh-sweep 1 2 4 --pool 20000 --mesh-iters 5 --reps 1 > /dev/null

echo "mesh check passed"
