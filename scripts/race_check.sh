#!/usr/bin/env bash
# Race/sanitizer sweep of the native OpenMP runtime (SURVEY.md §5 race
# detection: N/A in the single-threaded reference; this framework's C++
# core is parallel and gets checked).
#
# Two passes (GCC's libgomp is not TSAN-instrumented, so its barriers are
# invisible to TSAN — post-region reads would all be false positives; each
# pass verifies what it can soundly):
#   1. TSAN reentrancy: OMP_NUM_THREADS=1, four pthreads invoke every
#      kernel concurrently on shared inputs — detects hidden shared
#      mutable state across calls.
#   2. Determinism: oversubscribed OpenMP (threads > cores), repeat runs
#      must be BYTEWISE identical — parallel-region races (overlapping
#      writes, order-dependent accumulation) surface as nondeterminism.
set -euo pipefail

cd "$(dirname "$0")/.."
BUILD="${TMPDIR:-/tmp}/ce_tsan_build"
mkdir -p "$BUILD"
SRC="native/ce_host.cpp native/ce_gbdt.cpp native/ce_stress.cpp"

# shellcheck disable=SC2086
g++ -O1 -g -fsanitize=thread -fopenmp -std=c++17 $SRC -o "$BUILD/ce_tsan"
echo "== TSAN reentrancy (4 concurrent callers, OMP threads pinned to 1) =="
TSAN_OPTIONS="halt_on_error=1" OMP_NUM_THREADS=1 "$BUILD/ce_tsan" tsan

# shellcheck disable=SC2086
g++ -O2 -fopenmp -std=c++17 $SRC -o "$BUILD/ce_det"
CORES="$(nproc)"
for threads in 2 "$CORES" "$((CORES * 2))" "$((CORES * 4))"; do
  [ "$threads" -lt 2 ] && continue
  echo "== determinism, OMP_NUM_THREADS=$threads (x3) =="
  for rep in 1 2 3; do
    OMP_NUM_THREADS="$threads" "$BUILD/ce_det" determinism
  done
done
echo "race check passed"
