#!/usr/bin/env bash
# Full reproduction pipeline — the TPU-native counterpart of the reference's
# README workflow (reference README.md:43-60): pre-train the committee on
# DEAM, then run per-user consensus-entropy AL on AMG1608 in all four
# acquisition modes.
#
# Data layout (see README "Data layout"): --deam-root / --amg-root must hold
# the DEAM features+annotations(+npy) and AMG1608 feats+anno(+npy) trees.
#
# Usage:
#   scripts/reproduce.sh [MODELS_ROOT] [DEAM_ROOT] [AMG_ROOT] [DEVICE]
#
# The paper's experiment constants can be overridden via env for smoke runs:
#   CV (5-fold), QUERIES (q=10), EPOCHS (10 AL iterations), NUM_ANNO (150),
#   MODELS_LIST, MODES, EXTRA (extra amg_test flags, e.g. "--max-users 2").
set -euo pipefail

MODELS="${1:-./models}"
DEAM="${2:-./data/deam}"
AMG="${3:-./data/amg1608}"
DEVICE="${4:-tpu}"
FLAGS=(--models-root "$MODELS" --deam-root "$DEAM" --amg-root "$AMG"
       --device "$DEVICE")

# 1. Pre-train the paper's committee: 5-fold CV per algorithm
#    (gnb/sgd/xgb classic members + the Flax CNN — 20 members total).
for model in ${MODELS_LIST:-gnb sgd xgb cnn_jax}; do
  python -m consensus_entropy_tpu.cli.deam_classifier -cv "${CV:-5}" \
      -m "$model" "${FLAGS[@]}"
done

# 2. Personalize per user: 10 AL iterations x q=10 on users with >=150
#    annotations, one run per acquisition mode (mc = machine consensus,
#    hc = human consensus, mix = hybrid, rand = control).
#    --mesh auto shards the scoring path over every visible chip.
for mode in ${MODES:-mc hc mix rand}; do
  # shellcheck disable=SC2086
  python -m consensus_entropy_tpu.cli.amg_test -q "${QUERIES:-10}" \
      -e "${EPOCHS:-10}" -n "${NUM_ANNO:-150}" -m "$mode" --mesh auto \
      ${EXTRA:-} "${FLAGS[@]}"
done

echo "done: per-user reports under $MODELS/users/<uid>/<mode>/"
