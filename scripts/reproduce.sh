#!/usr/bin/env bash
# Full reproduction pipeline — the TPU-native counterpart of the reference's
# README workflow (reference README.md:43-60): pre-train the committee on
# DEAM, then run per-user consensus-entropy AL on AMG1608 in all four
# acquisition modes.
#
# Data layout (see README "Data layout"): --deam-root / --amg-root must hold
# the DEAM features+annotations(+npy) and AMG1608 feats+anno(+npy) trees.
#
# Usage:
#   scripts/reproduce.sh [MODELS_ROOT] [DEAM_ROOT] [AMG_ROOT] [DEVICE]
#
# The paper's experiment constants can be overridden via env for smoke runs:
#   CV (5-fold), QUERIES (q=10), EPOCHS (10 AL iterations), NUM_ANNO (150),
#   MODELS_LIST, MODES, EXTRA (extra amg_test flags, e.g. "--max-users 2").
# `--smoke` (as the only argument) proves the FULL pipeline from a pristine
# tree: it generates a synthetic DEAM+AMG layout in a temp dir (the same
# builder the CLI integration tests use) and runs pre-train + all-mode AL
# with tiny budgets on cpu.  Takes ~2 minutes; exits nonzero on any failure.
set -euo pipefail

if [ "${1:-}" = "--smoke" ]; then
  SMOKE_ROOT="$(mktemp -d)"
  trap 'rm -rf "$SMOKE_ROOT"' EXIT
  REPO="$(cd "$(dirname "$0")/.." && pwd)"
  PYTHONPATH="$REPO/tests${PYTHONPATH:+:$PYTHONPATH}" python - "$SMOKE_ROOT" <<'PYEOF'
import sys
import numpy as np
from synth_data import build_synth_roots
from pathlib import Path
roots = build_synth_roots(Path(sys.argv[1]), np.random.default_rng(0))
print(f"synthetic tree: deam={roots['deam']} amg={roots['amg']}")
PYEOF
  CV=2 QUERIES=2 EPOCHS=2 NUM_ANNO=4 MODELS_LIST="gnb sgd" \
    MODES="mc rand" EXTRA="--max-users 1" \
    "$0" "$SMOKE_ROOT/models" "$SMOKE_ROOT/deam" "$SMOKE_ROOT/amg1608" cpu
  exit $?
fi

MODELS="${1:-./models}"
DEAM="${2:-./data/deam}"
AMG="${3:-./data/amg1608}"
DEVICE="${4:-tpu}"
FLAGS=(--models-root "$MODELS" --deam-root "$DEAM" --amg-root "$AMG"
       --device "$DEVICE")

# 1. Pre-train the paper's committee: 5-fold CV per algorithm
#    (gnb/sgd/xgb classic members + the Flax CNN — 20 members total).
for model in ${MODELS_LIST:-gnb sgd xgb cnn_jax}; do
  python -m consensus_entropy_tpu.cli.deam_classifier -cv "${CV:-5}" \
      -m "$model" "${FLAGS[@]}"
done

# 2. Personalize per user: 10 AL iterations x q=10 on users with >=150
#    annotations, one run per acquisition mode (mc = machine consensus,
#    hc = human consensus, mix = hybrid, rand = control).
#    --mesh auto shards the scoring path over every visible chip.
for mode in ${MODES:-mc hc mix rand}; do
  # shellcheck disable=SC2086
  python -m consensus_entropy_tpu.cli.amg_test -q "${QUERIES:-10}" \
      -e "${EPOCHS:-10}" -n "${NUM_ANNO:-150}" -m "$mode" --mesh auto \
      ${EXTRA:-} "${FLAGS[@]}"
done

echo "done: per-user reports under $MODELS/users/<uid>/<mode>/"
