#!/usr/bin/env bash
# QBDC (query-by-dropout-committee) vs stored-committee mc (ISSUE 6
# acceptance: the K-sweep artifact with per-user device memory at K=64
# below the 20-model stored-committee footprint).
#
# Runs `bench.py --suite qbdc`: ONE personalized CNN forwarded under K
# seeded dropout masks (Committee.qbdc_pool_probs -> the fused
# consensus->entropy->top-k graph) against the paper's 20-stored-model mc
# committee on an identical synthetic waveform workload.  Reports
# per-pass scoring throughput across K in {8, 20, 64}, top-k overlap vs
# the stored ensemble, per-user device parameter bytes, and end-to-end
# AL users/sec — interleaved best-of-reps windows (throttled-image
# discipline).
#
# The JSON line goes to stdout (redirect to BENCH_qbdc_r<N>.json to
# commit an artifact); the per-window log goes to stderr.  Extra bench
# args pass through, e.g.:
#   scripts/qbdc_bench.sh --qbdc-sweep 8 20 64 128 --pool 96
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite qbdc \
    --al-epochs 2 --k 5 "$@"
