"""Measure the PRODUCTION per-iteration AL wall-clock at reference parity.

Builds a real-shape synthetic AMG/DEAM tree (1608-song feature cache, .mat
annotations, waveforms), pre-trains a gnb+sgd+cnn committee at the FULL
reference CNN geometry, runs the production AL CLI for TWO identically
shaped users at the paper's settings (``-q 10 -e 10 -m mc -n 150``,
100-epoch CNN retrains — ``settings.py`` n_epochs_retrain parity), and
summarizes the loop's own per-user ``timings.jsonl`` into one JSON
artifact.

Two users, one process, identical shapes = compile attribution for free:
jit caches are process-global, so the FIRST user pays every compilation
(cold) and the SECOND hits the caches (warm).  The per-phase cold−warm
delta IS the compile cost; the warm user is the steady-state production
iteration.  Both users annotate the same 400 songs and run under the same
seed, so every device program (scoring pad, staging bucket, crop bucket,
retrain batches, eval batch) has identical shapes across the two runs.

This is not a micro-benchmark: every number comes from the real
`al/loop.py` phases on whatever device JAX resolves (the TPU chip under the
driver).  Waveforms are synthetic 70k-sample tones (enough for the
59049-sample crop geometry; real 30-s songs would only enlarge the
device-resident store, not the compute per crop).

Usage: python scripts/measure_iteration.py [--out ITERATION.json]
       [--retrain-epochs N] [--songs N] [--keep WORKDIR]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))


def build_tree(root: str, n_songs: int, rng) -> dict:
    """Real-shape synthetic AMG + minimal DEAM tree under ``root``."""
    import pandas as pd
    from scipy.io import savemat

    from synth_data import FEATURE_COLS_FFTMAG, amg_dataset_frame

    amg = os.path.join(root, "amg1608")
    deam = os.path.join(root, "deam")
    os.makedirs(os.path.join(amg, "anno"))
    os.makedirs(os.path.join(amg, "npy"))
    os.makedirs(os.path.join(deam, "features"))
    os.makedirs(os.path.join(deam, "annotations"))
    os.makedirs(os.path.join(deam, "npy"))

    # AMG feature cache at the real 1608-song shape (fftMag column vintage)
    df = amg_dataset_frame(rng, n_songs=n_songs,
                           feature_cols=FEATURE_COLS_FFTMAG)
    df.to_csv(os.path.join(amg, "dataset_feats.csv"), sep=";", index=False)
    song_ids = sorted(df["s_id"].unique())

    # TWO heavy annotators over the SAME songs (identical device shapes →
    # cold/warm compile attribution) + a few sparse ones
    n_users = 4
    lab = np.full((len(song_ids), n_users, 2), np.nan)
    for i in range(len(song_ids)):
        c = int(rng.integers(0, 4))
        v_sign = 1.0 if c in (0, 3) else -1.0
        a_sign = 1.0 if c in (0, 1) else -1.0
        if i < min(400, len(song_ids)):  # users 0+1 annotated these songs
            for u in (0, 1):
                lab[i, u] = [v_sign * rng.uniform(0.3, 1),
                             a_sign * rng.uniform(0.3, 1)]
        for u in range(2, n_users):
            if rng.uniform() < 0.02:
                lab[i, u] = [v_sign * rng.uniform(0.3, 1),
                             a_sign * rng.uniform(0.3, 1)]
    savemat(os.path.join(amg, "anno", "AMG1608.mat"), {"song_label": lab})
    savemat(os.path.join(amg, "anno", "1608_song_id.mat"),
            {"mat_id2song_id": np.asarray(song_ids).reshape(-1, 1)})

    # waveforms: class-correlated tones, 70k samples (> one 59049 crop);
    # the CLI's device store loads EVERY pool song's audio, so all songs
    # need a file (~280 KB each)
    for sid in song_ids:
        n = 70000 + int(rng.integers(0, 2000))
        t = np.arange(n) / 16000.0
        w = (np.sin(2 * np.pi * float(rng.uniform(200, 1000)) * t)
             + 0.1 * rng.standard_normal(n))
        np.save(os.path.join(amg, "npy", f"{sid}.npy"),
                w.astype(np.float32))

    # minimal DEAM tree (pre-training data): 24 songs
    times = np.arange(15.0, 25.0, 0.5)
    cols_ms = [f"sample_{int(t * 1000)}ms" for t in times]
    a_rows, v_rows = [], []
    for sid in range(1, 25):
        c = sid % 4
        a_sign = 1.0 if c in (0, 1) else -1.0
        v_sign = 1.0 if c in (0, 3) else -1.0
        feats = rng.standard_normal((len(times), len(FEATURE_COLS_FFTMAG)))
        fdf = pd.DataFrame(feats.astype(np.float32),
                           columns=FEATURE_COLS_FFTMAG)
        fdf.insert(0, "frameTime", times)
        fdf.to_csv(os.path.join(deam, "features", f"{sid}.csv"), sep=";",
                   index=False)
        a_rows.append({"song_id": sid, **dict(
            zip(cols_ms, a_sign * rng.uniform(0.2, 1, len(times))))})
        v_rows.append({"song_id": sid, **dict(
            zip(cols_ms, v_sign * rng.uniform(0.2, 1, len(times))))})
        n = 70000
        t = np.arange(n) / 16000.0
        w = np.sin(2 * np.pi * 400.0 * (c + 1) * t) + \
            0.05 * rng.standard_normal(n)
        np.save(os.path.join(deam, "npy", f"{sid}.npy"),
                w.astype(np.float32))
    pd.DataFrame(a_rows).to_csv(
        os.path.join(deam, "annotations", "arousal.csv"), index=False)
    pd.DataFrame(v_rows).to_csv(
        os.path.join(deam, "annotations", "valence.csv"), index=False)
    return {"amg": amg, "deam": deam,
            "models": os.path.join(root, "models")}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--out", default="ITERATION.json")
    ap.add_argument("--retrain-epochs", type=int, default=None,
                    help="override n_epochs_retrain (default: reference "
                         "parity, 100)")
    ap.add_argument("--songs", type=int, default=1608)
    ap.add_argument("--queries", type=int, default=10)
    ap.add_argument("--epochs", type=int, default=10)
    ap.add_argument("--keep", default=None,
                    help="build/run in this dir and keep it")
    ap.add_argument("--device", choices=("cpu", "tpu"), default="tpu",
                    help="forwarded to the CLIs (cpu = plumbing smoke; "
                         "the committed artifact must come from tpu)")
    args = ap.parse_args(argv)

    cleanup = None
    if args.keep:
        root = args.keep
        os.makedirs(root, exist_ok=True)
    else:
        cleanup = tempfile.TemporaryDirectory(prefix="ce_iter_")
        root = cleanup.name
    rng = np.random.default_rng(1987)
    print(f"building real-shape tree ({args.songs} songs) under {root} ...")
    roots = build_tree(root, args.songs, rng)

    env = {**os.environ}
    flags = ["--models-root", roots["models"], "--deam-root", roots["deam"],
             "--amg-root", roots["amg"], "--device", args.device]

    # pre-train the committee: 5 gnb + 5 sgd folds + 5 FULL-geometry CNNs
    # (2 pretrain epochs — model quality is irrelevant to loop timing)
    for model, extra in (("gnb", []), ("sgd", []),
                         ("cnn_jax", ["--epochs", "2"])):
        print(f"pretraining {model} ...")
        rc = subprocess.run(
            [sys.executable, "-m", "consensus_entropy_tpu.cli."
             "deam_classifier", "-cv", "5", "-m", model] + extra + flags,
            env=env).returncode
        if rc:
            return rc

    num_anno = min(150, max(1, args.songs // 2))  # paper's -n 150 at scale
    al_args = [sys.executable, "-m", "consensus_entropy_tpu.cli.amg_test",
               "-q", str(args.queries), "-e", str(args.epochs), "-m", "mc",
               "-n", str(num_anno), "--max-users", "2"] + flags
    if args.retrain_epochs:
        al_args += ["--retrain-epochs", str(args.retrain_epochs)]
    print("running the production AL loop (two same-shape users, mc; "
          "user 0 = cold/compiling, user 1 = warm/steady-state) ...")
    rc = subprocess.run(al_args, env=env).returncode
    if rc:
        return rc

    # summarize the loop's own per-phase timings, per user
    users = os.path.join(roots["models"], "users")
    uids = sorted(os.listdir(users))[:2]

    def phase_times(uid):
        """(foreground phases, background phases): ``ckpt_bg_*`` entries
        are the checkpointer thread's self-timed work — it OVERLAPS the
        foreground compute (and on a thin d2h link contends with it), so
        it is reported separately and never summed into wall-clock."""
        tpath = os.path.join(users, uid, "mc", "timings.jsonl")
        phases: dict[str, list] = {}
        bg: dict[str, list] = {}
        for line in open(tpath):
            r = json.loads(line)
            if r.get("epoch", -1) < 0:
                continue  # epoch0 baseline evaluation, no acquisition
            for k, v in r.items():
                if k.endswith("_s"):  # StepTimer phase durations
                    (bg if k.startswith("ckpt_bg_")
                     else phases).setdefault(k, []).append(float(v))
        return phases, bg

    cold, cold_bg = phase_times(uids[0])
    warm, warm_bg = (phase_times(uids[1]) if len(uids) > 1 else ({}, {}))
    summary = {}
    for k in sorted(cold):
        c, w = cold[k], warm.get(k, [])
        entry = {
            "median_s": round(float(np.median(c)), 4),
            "mean_s": round(float(np.mean(c)), 4),
            "total_s": round(float(np.sum(c)), 2),
            # raw per-iteration series: lets a reader attribute any
            # mean>median gap to a SPECIFIC iteration (first-touch setup,
            # bucket transition, tunnel hiccup) instead of guessing from
            # aggregates
            "per_iteration_s": [round(float(v), 3) for v in c],
        }
        if w:
            delta = float(np.sum(c) - np.sum(w))
            entry.update({
                "warm_median_s": round(float(np.median(w)), 4),
                "warm_mean_s": round(float(np.mean(w)), 4),
                "warm_total_s": round(float(np.sum(w)), 2),
                "warm_per_iteration_s": [round(float(v), 3) for v in w],
                # same shapes + same process ⇒ the cold run's excess over
                # the warm run is (almost entirely) XLA compilation.
                # Non-negative by construction: a warm phase can only
                # exceed its cold twin through non-compile effects
                # (tunnel bandwidth contention with the background
                # checkpoint fetch, run-to-run wall-clock drift) — that
                # excess is reported as warm_excess_s, not as negative
                # compile time.
                "compile_s": round(max(delta, 0.0), 2),
            })
            if delta < 0:
                entry["warm_excess_s"] = round(-delta, 2)
                entry["warm_excess_note"] = (
                    "warm > cold: overlap/contention (background "
                    "checkpoint d2h riding this phase's device syncs) "
                    "and tunnel drift, not compilation")
        summary[k] = entry
    background = {}
    for k in sorted(set(cold_bg) | set(warm_bg)):
        background[k] = {
            "cold_total_s": round(float(np.sum(cold_bg.get(k, []))), 2),
            "warm_total_s": round(float(np.sum(warm_bg.get(k, []))), 2),
            "warm_per_iteration_s": [round(float(v), 3)
                                     for v in warm_bg.get(k, [])],
        }

    cold_total = float(np.sum([np.sum(v) for v in cold.values()]))
    warm_total = float(np.sum([np.sum(v) for v in warm.values()])) \
        if warm else None
    n_iter = max(len(v) for v in cold.values())
    warm_mean_iter = (warm_total / n_iter) if warm_total else None

    from consensus_entropy_tpu.cli.common import configure_device

    configure_device(args.device)  # report the device the CLIs actually used
    import jax
    import jax.numpy as jnp
    import time as _time

    devs = jax.devices()

    # Device->host bandwidth probe: the per-iteration checkpoint defers a
    # ~(members x params) device_get to a background thread, so on a
    # tunneled chip with slow d2h that traffic surfaces inside the NEXT
    # iteration's first device sync (select/retrain) — measured at
    # ~9 MB/s on the axon loopback relay vs GB/s on a real TPU host.
    # Committing the measured bandwidth lets a reader subtract the
    # environment from the phase numbers.  A fresh buffer per rep: jax
    # caches the host copy of a fetched array, so re-fetching one array
    # measures nothing.
    d2h = []
    if devs[0].platform != "cpu":  # on cpu the "link" is host memcpy —
        for rep in range(3):       # recording it would mislead a reader
            buf = jnp.full((16, 1 << 20), float(rep), jnp.float32)  # 64 MB
            buf.block_until_ready()
            t0 = _time.perf_counter()
            jax.device_get(buf)
            d2h.append(buf.nbytes / (_time.perf_counter() - t0) / 1e6)
            del buf
        d2h = d2h[1:]  # rep 0 pays one-time transfer-path setup
    report = {
        "metric": "al_iteration_wall_clock_production",
        "value": round(warm_mean_iter if warm_mean_iter is not None
                       else cold_total / n_iter, 3),
        "unit": "s/iteration (MEAN over the warm steady-state user)",
        "note": "two identically shaped users share one process: user 0 "
                "pays every XLA compile (cold), user 1 reuses the caches "
                "(warm = steady state); compile_s per phase is "
                "max(cold-warm, 0) — warm>cold excess is attributed in "
                "warm_excess_s, never as negative compile.  'score' only "
                "DISPATCHES the async CNN pool forward; 'select' drains "
                "it at its first device sync, so the forward's execute "
                "time lands in select by design (the async overlap is "
                "the point).  The per-iteration checkpoint runs on a "
                "background thread: ckpt_join is the foreground blocking "
                "wait (usually ~0 when the job finished in time); the "
                "'background' section carries the job's self-timed "
                "fetch/write/commit, which OVERLAP the next iteration's "
                "foreground phases (one-record offset: a record's "
                "ckpt_bg_* describe the job submitted by the PREVIOUS "
                "record) and are excluded from all totals.  This chip's "
                "wall-clock drifts up to ~2x run-to-run (tunnel), so "
                "compare phase STRUCTURE across artifacts, not absolute "
                "seconds",
        "settings": {"queries": args.queries, "epochs": args.epochs,
                     "mode": "mc", "songs": args.songs,
                     "retrain_epochs": args.retrain_epochs or "default(100)",
                     "committee": "5 gnb + 5 sgd + 5 cnn (full geometry)"},
        "phases": summary,
        "background": background,
        "iterations": {
            "n_per_user": n_iter,
            "cold_user_total_s": round(cold_total, 2),
            "cold_user_mean_iteration_s": round(cold_total / n_iter, 3),
            "warm_user_total_s": round(warm_total, 2) if warm_total
            else None,
            "warm_user_mean_iteration_s": round(warm_mean_iter, 3)
            if warm_mean_iter else None,
            "compile_total_s": round(max(cold_total - warm_total, 0.0), 2)
            if warm_total else None,
            "compile_share_of_cold": round(
                max(cold_total - warm_total, 0.0) / cold_total, 3)
            if warm_total else None,
        },
        "platform": devs[0].platform, "device_kind": devs[0].device_kind,
        # median of the post-warmup fresh-buffer reps; the async checkpoint
        # ships the retrained members' variables per iteration over this
        # path (bf16-cast by default — ALConfig.ckpt_dtype — so ~37 MB for
        # 5 full-geometry members, half the f32 bytes; members that did
        # not improve are skipped entirely), hidden behind the next
        # iteration's compute — at GB/s (real host) invisible, at ~9 MB/s
        # (tunnel) it contends with the foreground device syncs; the
        # 'background' section carries its measured duration.
        # null on --device cpu (no device link to measure).
        "d2h_bandwidth_MB_s": round(float(np.median(d2h)), 1) if d2h
        else None,
    }
    with open(args.out, "w") as fh:
        json.dump(report, fh, indent=2)
    print(json.dumps({"metric": report["metric"], "value": report["value"],
                      "unit": report["unit"]}))
    print(f"wrote {args.out}")
    if cleanup is not None:
        cleanup.cleanup()
    return 0


if __name__ == "__main__":
    sys.exit(main())
