#!/usr/bin/env bash
# Cross-user stacked CNN device path (ISSUE 7 acceptance: CNN-cohort
# mean_device_batch > 1.5 and >= 1.3x users/sec over the per-user CNN
# dispatch path on a >= 4-user same-bucket cohort, parity bit-identical
# to the sequential loop in mc and qbdc modes).  The users/sec ratio is
# capacity-bound: both arms run equal FLOPs (bit-identity pins the
# kernels), so the stacked win is host/device overlap, bounded by the
# box's measured parallel capacity — recorded per run as
# host_parallel_speedup in the JSON (observed ~1.1x on this throttled
# image, i.e. the 1.3x arm ratio needs a box where two workers actually
# run in parallel; mean_device_batch and dispatch counts are the
# capacity-independent metrics).
#
# Runs `bench.py --suite cnn-fleet`: a same-bucket cohort of CNN AL
# sessions through fleet.FleetScheduler with the cross-user stacked
# device path (one lax.map-over-users dispatch per round for the CNN
# probs forward, the qbdc dropout committee, and the lockstep retrain)
# against the identical engine with `stack_cnn=False` — per-user CNN
# dispatch, the pre-PR shape.  Reps are interleaved (best-of per arm;
# this image's cpu shares are throttled) and per-user parity with the
# sequential ALLoop trajectories is asserted on every rep of both arms,
# so the reported speedup is for bit-identical results.
#
# The JSON line goes to stdout (redirect to BENCH_cnn_fleet_r<N>.json to
# commit an artifact); the per-arm log goes to stderr.  Extra bench args
# pass through, e.g.:
#   scripts/cnn_fleet_bench.sh --users 8 --pool 32 --reps 5
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite cnn-fleet "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite cnn-fleet \
        --users 6 --pool 120 --k 10 --al-epochs 2 --reps 5
fi
