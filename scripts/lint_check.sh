#!/usr/bin/env bash
# Static-analysis CI gate (ISSUE 12 tentpole; sits next to obs_check.sh
# and slo_check.sh in the verify chain).
#
# Runs cetpu-lint over the whole tree — the donation / PRNG /
# replay-determinism / host-sync / fault-point / event-schema invariant
# rules (see README "Static analysis") — and fails on:
#   1. any unbaselined, un-noqa'd finding (exit 1 from the linter),
#   2. a parse error anywhere in the tree,
#   3. a wall-clock blowout: the pass is pure AST and must stay
#      interactive (<10 s on the CI box) so it runs on every change.
#
# The checked-in baseline (lint_baseline.json) is EMPTY by policy: a new
# finding is either fixed or carries a per-line
#   # cetpu: noqa[rule] <one-line justification>
# — grandfathering via the baseline is for migrations only.
#
# Pure host: no jax import anywhere on this path (JAX_PLATFORMS unset is
# fine); safe on a box with no accelerator.
#
# Extra args are passed through to cetpu-lint (e.g. --format json).
set -euo pipefail

cd "$(dirname "$0")/.."

start=$(date +%s)
python -m consensus_entropy_tpu.analysis.cli "$@"
end=$(date +%s)

elapsed=$((end - start))
if [ "$elapsed" -ge 10 ]; then
  echo "lint check FAILED: full-tree lint took ${elapsed}s (>= 10s budget)" >&2
  exit 1
fi
echo "lint check passed (${elapsed}s)"
