#!/usr/bin/env bash
# Observability CI gate (ISSUE 9 satellite; sits next to fault_matrix.sh).
#
# Runs a REAL traced 2-user serve cohort over the synthetic workload,
# then:
#   1. validates EVERY fleet_metrics.jsonl line against the schema-v2
#      event table (obs.export.validate_metrics_file),
#   2. asserts the span WAL merges orphan-free and the Chrome trace
#      export loads as JSON with complete events,
#   3. round-trips the `report` CLI subcommand (--validate --out) over
#      the run's users dir.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import json
import os
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import bench
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.obs.trace import Tracer
from consensus_entropy_tpu.serve import FleetServer, ServeConfig

cfg = ALConfig(queries=8, epochs=2, mode="mc", seed=1987,
               ckpt_dtype="float32")
users = bench._fleet_workload(2, 80, 96, cfg.seed)
root = tempfile.mkdtemp(prefix="obs_check_")
users_dir = os.path.join(root, "users")
metrics_path = os.path.join(users_dir, "fleet_metrics.jsonl")
spans_path = os.path.join(users_dir, "spans.jsonl")

tracer = Tracer(spans_path, run_id=f"{cfg.mode}-{cfg.seed}")
report = FleetReport(metrics_path)
sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                       user_timings=False, tracer=tracer)
server = FleetServer(sched, ServeConfig(target_live=2))
entries = [FleetUser(d.user_id, f(), d, bench._mkdir(root, f"u{i}"),
                     seed=cfg.seed)
           for i, (d, f) in enumerate(users)]
recs = server.serve(iter(entries))
tracer.close()
report.write_summary(cohort=2)
report.close()
assert len(recs) == 2 and all(r["error"] is None for r in recs), recs

# 1. every metrics line validates against the v2 schema
errors = export.validate_metrics_file(metrics_path)
assert errors == [], "schema violations:\n" + "\n".join(errors[:10])
n_lines = len(export.read_jsonl_tolerant(metrics_path))
print(f"obs_check: {n_lines} metrics lines schema-valid")

# 2. spans merge orphan-free; the Chrome export loads
spans = export.load_spans([spans_path])
assert spans, "no spans written by a traced run"
assert export.orphan_spans(spans) == []
trace = json.loads(json.dumps(export.chrome_trace(spans)))
xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
assert len(xs) == len(spans)
print(f"obs_check: {len(spans)} spans merged, export loads "
      f"({len(trace['traceEvents'])} events)")

# 3. the report CLI round-trips over the same dir
from consensus_entropy_tpu.cli.report import main as report_main

out = os.path.join(root, "trace.json")
assert report_main([users_dir, "--validate", "--out", out,
                    "--no-text"]) == 0
assert json.load(open(out))["traceEvents"]
print("obs_check: report CLI validate+export ok")
PY
echo "obs check passed"
