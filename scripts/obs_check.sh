#!/usr/bin/env bash
# Observability CI gate (ISSUE 9 satellite; sits next to fault_matrix.sh;
# the ISSUE 15 live-introspection leg rides below).
#
# LEG 1 — runs a REAL traced 2-user serve cohort over the synthetic
# workload, then:
#   1. validates EVERY fleet_metrics.jsonl line against the schema-v2
#      event table (obs.export.validate_metrics_file),
#   2. asserts the span WAL merges orphan-free and the Chrome trace
#      export loads as JSON with complete events,
#   3. round-trips the `report` CLI subcommand (--validate --out) over
#      the run's users dir.
#
# LEG 2 — the LIVE leg: a REAL traced 3-host elastic drain+migrate run
# (worker subprocesses slowed by a pool.score:delay= rule so sessions
# outlive the drain decision), introspection plane ON, and:
#   1. MID-RUN status snapshots (coordinator + workers) schema-validate
#      while the fabric is still serving,
#   2. at least one SLO burn-rate alert fires (batch aging under the
#      tiny aging bound) as a schema-valid `alert` event,
#   3. the exported Chrome trace carries the control-plane lane with
#      drain→fence→migrate spans FLOW-LINKED into the migrated user's
#      trace, and `cetpu-top --once` renders the snapshot directory.
#
# Extra args are NOT accepted: this is a pass/fail gate, not a bench.
set -euo pipefail

cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import json
import os
import sys
import tempfile

sys.path.insert(0, ".")
import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

import bench
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.obs.trace import Tracer
from consensus_entropy_tpu.serve import FleetServer, ServeConfig

cfg = ALConfig(queries=8, epochs=2, mode="mc", seed=1987,
               ckpt_dtype="float32")
users = bench._fleet_workload(2, 80, 96, cfg.seed)
root = tempfile.mkdtemp(prefix="obs_check_")
users_dir = os.path.join(root, "users")
metrics_path = os.path.join(users_dir, "fleet_metrics.jsonl")
spans_path = os.path.join(users_dir, "spans.jsonl")

tracer = Tracer(spans_path, run_id=f"{cfg.mode}-{cfg.seed}")
report = FleetReport(metrics_path)
sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                       user_timings=False, tracer=tracer)
server = FleetServer(sched, ServeConfig(target_live=2))
entries = [FleetUser(d.user_id, f(), d, bench._mkdir(root, f"u{i}"),
                     seed=cfg.seed)
           for i, (d, f) in enumerate(users)]
recs = server.serve(iter(entries))
tracer.close()
report.write_summary(cohort=2)
report.close()
assert len(recs) == 2 and all(r["error"] is None for r in recs), recs

# 1. every metrics line validates against the v2 schema
errors = export.validate_metrics_file(metrics_path)
assert errors == [], "schema violations:\n" + "\n".join(errors[:10])
n_lines = len(export.read_jsonl_tolerant(metrics_path))
print(f"obs_check: {n_lines} metrics lines schema-valid")

# 2. spans merge orphan-free; the Chrome export loads
spans = export.load_spans([spans_path])
assert spans, "no spans written by a traced run"
assert export.orphan_spans(spans) == []
trace = json.loads(json.dumps(export.chrome_trace(spans)))
xs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
assert len(xs) == len(spans)
print(f"obs_check: {len(spans)} spans merged, export loads "
      f"({len(trace['traceEvents'])} events)")

# 3. the report CLI round-trips over the same dir
from consensus_entropy_tpu.cli.report import main as report_main

out = os.path.join(root, "trace.json")
assert report_main([users_dir, "--validate", "--out", out,
                    "--no-text"]) == 0
assert json.load(open(out))["traceEvents"]
print("obs_check: report CLI validate+export ok")

# ---- LEG 2: the live introspection leg (ISSUE 15) ---------------------

import glob as glob_mod
import subprocess

from consensus_entropy_tpu.obs.alerts import AlertWatcher
from consensus_entropy_tpu.obs.status import (
    StatusWriter,
    read_status_dir,
    validate_status,
)
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
)
from consensus_entropy_tpu.serve.hosts import fabric_paths
from tests.fabric_workload import (
    force_low_water,
    make_cfg,
    read_results,
    sizes_arg,
    user_specs,
)

cfg2 = make_cfg("mc", epochs=3)
specs2 = user_specs(6, sizes=[30, 100])
root2 = tempfile.mkdtemp(prefix="obs_check_live_")
fdir = os.path.join(root2, "fabric")
status_dir = os.path.join(root2, "status")
os.makedirs(fdir)
jp = os.path.join(fdir, "serve_journal.jsonl")


def spawn(host_id):
    log = open(fabric_paths(fdir, host_id)["log"], "ab")
    try:
        return subprocess.Popen(
            [sys.executable, "tests/fabric_worker.py", fdir, host_id,
             root2, cfg2.mode, str(cfg2.epochs), str(len(specs2)),
             "5.0", "1", sizes_arg(specs2)],
            stdout=log, stderr=subprocess.STDOUT,
            # the pool.score delay rule is the SLOW-HOST simulation:
            # sessions outlive the drain decision so the fence window
            # opens; target_live=1 queues the second user per host so
            # the tiny aging bound fires a batch_aging alert
            env={**os.environ, "PYTHONPATH": ".",
                 "CETPU_FAULTS": "pool.score:delay=0.2@1x-1",
                 "CETPU_OBS_TRACE": "1", "CETPU_FABRIC_METRICS": "1",
                 "CETPU_OBS_STATUS": status_dir,
                 "CETPU_OBS_AGING": "0.2"})
    finally:
        log.close()


from consensus_entropy_tpu.fleet import FleetReport
from consensus_entropy_tpu.obs.trace import Tracer

spans_path = os.path.join(root2, "spans.jsonl")
coord_metrics = os.path.join(root2, "fleet_metrics.jsonl")
tracer = Tracer(spans_path, run_id=f"{cfg2.mode}-{cfg2.seed}",
                host="coordinator")
report2 = FleetReport(coord_metrics)
mid_run = {"snaps": {}, "checked": 0}


def on_poll(coord):
    force_low_water(coord)
    # the MID-RUN snapshot gate: while users are still unresolved, every
    # snapshot present must already schema-validate
    if coord._unresolved and mid_run["checked"] < 200:
        mid_run["checked"] += 1
        for host, snap in read_status_dir(status_dir).items():
            errs = validate_status(snap)
            assert errs == [], (host, errs)
            mid_run["snaps"][host] = snap


journal = AdmissionJournal(jp)
status = StatusWriter(status_dir, "coordinator", interval_s=0.2)
alerts = AlertWatcher(report2, log=print)
coord = FabricCoordinator(
    journal, fdir,
    FabricConfig(hosts=3, min_hosts=2, max_hosts=3, scale_down_s=600.0,
                 drain_timeout_s=30.0),
    report=report2, tracer=tracer, status=status, alerts=alerts,
    on_poll=on_poll)
try:
    summary2 = coord.run([u for _, u, _ in specs2], spawn,
                         pools={u: n for _, u, n in specs2})
finally:
    tracer.close()
    journal.close()
    report2.write_summary(cohort=len(specs2))
    report2.close()

assert sorted(summary2["finished"]) == sorted(u for _, u, _ in specs2)
assert summary2["drains"] == 1 and summary2["fences"] >= 1, summary2
results2 = read_results(fdir)
assert all(results2[u]["error"] is None for _, u, _ in specs2)

# 1. mid-run snapshots were seen (coordinator + at least one worker)
# and validated while the fabric was serving
assert "coordinator" in mid_run["snaps"], sorted(mid_run["snaps"])
assert any(h.startswith("h") for h in mid_run["snaps"]), \
    sorted(mid_run["snaps"])
print(f"obs_check live: {len(mid_run['snaps'])} mid-run snapshots "
      f"schema-valid ({sorted(mid_run['snaps'])})")

# 2. at least one burn-rate alert fired, schema-valid in a metrics
# stream (the workers' batch_aging under the tiny bound)
alert_events = []
for path in [coord_metrics] + sorted(
        glob_mod.glob(os.path.join(fdir, "fleet_metrics_*.jsonl"))):
    recs = export.read_jsonl_tolerant(path)
    assert export.validate_metrics(recs) == [], path
    alert_events += [r for r in recs if r.get("event") == "alert"]
assert alert_events, "no alert fired in the live leg"
print(f"obs_check live: {len(alert_events)} alert event(s) "
      f"({sorted({a.get('kind') for a in alert_events})})")

# 3. the export carries the control-plane lane, drain→fence→migrate
# spans, and flow links into the migrated user's trace
spans2 = export.load_spans([spans_path])
ctl = [s for s in spans2 if s.get("ctl")]
names = {s["name"] for s in ctl}
assert {"ctl.drain", "ctl.fence", "ctl.migrate",
        "ctl.drain_done"} <= names, sorted(names)
trace2 = export.chrome_trace(spans2)
procs = {e["args"]["name"] for e in trace2["traceEvents"]
         if e.get("name") == "process_name"}
assert "control-plane" in procs, procs
starts = [e for e in trace2["traceEvents"] if e.get("ph") == "s"]
ends = {e["id"] for e in trace2["traceEvents"] if e.get("ph") == "f"}
assert starts and all(e["id"] in ends for e in starts), \
    (len(starts), len(ends))
json.dumps(trace2)
print(f"obs_check live: control lane {sorted(names)} with "
      f"{len(starts)} flow link(s) into user traces")

# 4. cetpu-top renders the final snapshot directory
from consensus_entropy_tpu.cli.top import main as top_main

assert top_main([root2, "--once"]) == 0
print("obs_check live: cetpu-top rendered the fleet view")
PY
echo "obs check passed"
