#!/usr/bin/env bash
# Multi-host fabric resilience throughput (ISSUE 5 CI drill; the
# multi-host sibling of scripts/serve_fault_bench.sh).
#
# Runs `bench.py --suite fabric`: a 2-host fabric (coordinator
# in-process, worker subprocesses over the shared synthetic workload)
# serves --users users; the moment the journal shows host h0 admitted a
# user, h0 is SIGKILLed — its in-flight users must resume on the
# survivor from their durable workspaces and its queued users re-enqueue
# in journal order, while journal compaction runs live at a small bound.
# Sequential UNFAULTED runs are the ground truth: per-user trajectory
# parity is asserted on every rep (reps are interleaved best-of per the
# 2-vCPU drift protocol), then the JSON line reports recovered-users/sec
# plus revocation/reassignment/compaction counts.
#
# The JSON line goes to stdout (redirect to BENCH_fabric_r<N>.json to
# commit an artifact); the per-rep log goes to stderr.  Extra bench args
# pass through, e.g.:
#   scripts/fabric_bench.sh --users 6 --al-epochs 2 --reps 2
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
        --suite fabric "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py \
        --suite fabric --users 8 --al-epochs 3 --hosts 2 --reps 2
fi
