#!/usr/bin/env bash
# Graceful scale-down race (ISSUE 14 acceptance: a 3-host elastic
# fabric sheds one host mid-run with zero user loss and parity
# bit-identical to sequential; checkpoint-fenced in-flight migration
# retires the surplus host faster than waiting out its sessions).
#
# Runs `bench.py --suite drain`: two arms over the IDENTICAL slowed
# workload (a pool.score delay rule stretches every worker iteration —
# values untouched) on a 3-host fabric (min_hosts=2) whose low-water
# timer is forced once every host is mid-run.  The arms differ only in
# FabricConfig.migrate_inflight — 'fence' (in-flight users checkpoint
# at their next iteration boundary and migrate on the journaled fence
# ack) vs 'wait' (the PR 13-shaped baseline: only queued users move,
# in-flight users finish where they are).  Recovered-users/sec plus the
# journal-derived drain->drain_done latency; parity vs unfaulted
# sequential runs is asserted on every rep of both arms, and the fence
# arm must fence >= 1 user while the wait arm fences exactly 0.
#
# The JSON line goes to stdout (redirect to BENCH_drain_r<N>.json to
# commit an artifact); the per-rep log goes to stderr.  Extra bench
# args pass through, e.g.:
#   scripts/drain_bench.sh --users 6 --al-epochs 3 --reps 2
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite drain "$@"
else
    # 8 users over 3 hosts: the survivors outlast the drain victim, so
    # the wait arm's retirement (drain_done) lands inside the run and
    # both arms report a COMPLETED drain latency
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite drain \
        --users 8 --hosts 3 --al-epochs 4 --reps 3
fi
