#!/usr/bin/env bash
# SLO-aware admission race (ISSUE 11 acceptance: the planner arm raises
# MEAN BUCKET OCCUPANCY vs the fixed-window arm on the tail-heavy serve
# workload, with per-user parity exact on every rep and per-class
# admission→finish p95 reported for both arms).
#
# Runs `bench.py --suite slo`: the SLO admission planner (bucket edges
# derived online from a quantile sketch of enqueue-time pool sizes,
# priority classes interactive/batch with strict-priority+aging
# admission, predictive dispatch holds bounded by per-class SLO
# headroom) against the PR 3 fixed-window arm (`slo_planner=False`) over
# IDENTICAL tail-heavy users (every 4th pool 4x, every 3rd user
# interactive).  Per the 2-vCPU drift protocol the reps are INTERLEAVED
# (sequential, fixed, planner per rep); occupancy is reported as the
# mean over reps (capacity-independent on this box — the same role h2d
# bytes played for the fused-step suite), users/sec as each arm's best.
#
# The JSON line goes to stdout (redirect to BENCH_slo_r<N>.json to
# commit an artifact); the per-rep log goes to stderr.  Extra bench args
# pass through, e.g.:
#   scripts/slo_bench.sh --users 8 --pool 120 --fleet 4 --reps 3
set -euo pipefail

cd "$(dirname "$0")/.."
if [ "$#" -gt 0 ]; then
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite slo "$@"
else
    JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python bench.py --suite slo \
        --users 8 --pool 120 --fleet 4 --reps 3
fi
