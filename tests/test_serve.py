"""Serve layer: continuous-batching admission vs sequential ground truth.

Tier-1 (un-marked) keeps only the 3-user admission smoke, the bucket-
parity test and the pure-host units, per the tier-1 budget; the full mode
matrix, the eviction+resume drill, the drain drill and the threaded-
producer test are ``slow`` (``scripts/serve_bench.sh`` exercises
throughput).

Parity is exact (``==`` on float lists): the server drives the SAME
engine over the SAME session generators as the fleet/sequential paths,
and padding (bucket edges included) never changes selections — so there
is no tolerance to grant.
"""

import json
import os

import pytest

from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.al.loop import ALLoop
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.ops import scoring
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule
from consensus_entropy_tpu.serve import (
    AdmissionQueue,
    BucketRouter,
    FleetServer,
    QueueClosed,
    QueueFull,
    ServeConfig,
)
from tests.test_fleet import _cfg, _committee, _user_data

pytestmark = pytest.mark.serve


def _baselines_and_entries(tmp_path, cfg, specs, *, committee_fn=_committee,
                           run_seq=True):
    """Sequential ground-truth runs + fresh serve entries over identical
    inputs.  ``specs``: list of (seed, uid, n_songs)."""
    seq, entries = [], []
    for seed, uid, n_songs in specs:
        data = _user_data(seed, uid, n_songs=n_songs)
        if run_seq:
            p = tmp_path / f"seq_{uid}"
            p.mkdir()
            seq.append(ALLoop(cfg).run_user(committee_fn(data), data,
                                            str(p)))
        fp = tmp_path / f"serve_{uid}"
        fp.mkdir()
        entries.append(FleetUser(
            uid, committee_fn(data), data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp))))
    return seq, entries


def _serve(cfg, entries, *, serve_cfg=None, preemption=None, report=None,
           scheduler_kw=None):
    sched = FleetScheduler(cfg, report=report or FleetReport(),
                           scoring_by_width=True, **(scheduler_kw or {}))
    server = FleetServer(sched, serve_cfg or ServeConfig(target_live=2),
                         preemption=preemption)
    recs = server.serve(iter(entries))
    return recs, server


# -- pure-host units (no jax) ---------------------------------------------


def test_bucket_router_pow2_and_explicit_edges():
    pow2 = BucketRouter()
    assert [pow2.width_for(n) for n in (1, 8, 9, 100, 257)] == \
        [8, 8, 16, 128, 512]
    r = BucketRouter(widths=(30, 100))  # edges round up to multiples of 8
    assert r.widths == (32, 104)
    assert r.width_for(20) == 32
    assert r.width_for(33) == 104
    assert r.width_for(200) == 256  # overflow falls through to pow2
    with pytest.raises(ValueError):
        BucketRouter(widths=(0,))


def test_admission_queue_backpressure_and_fifo():
    q = AdmissionQueue(2)
    assert q.put("a") == 1
    assert q.put("b") == 2
    with pytest.raises(QueueFull):
        q.put("c")  # the bound IS the backpressure surface
    assert q.pop()[0] == "a"  # FIFO
    assert q.put("c") == 2  # a pop frees room
    assert [q.pop()[0], q.pop()[0]] == ["b", "c"]
    assert q.pop() is None


def test_admission_queue_try_put_and_wait_at_least():
    import threading

    q = AdmissionQueue(2)
    assert q.try_put("a") == 1
    assert q.try_put("b") == 2
    assert q.try_put("c") is None  # full: the serve loop holds, not raises
    assert q.wait_at_least(2, timeout=0.01) is True
    q.pop()
    assert q.wait_at_least(2, timeout=0.05) is False  # window elapses
    t = threading.Timer(0.05, lambda: q.put("c"))
    t.start()
    try:
        assert q.wait_at_least(2, timeout=2.0) is True  # arrival wakes it
    finally:
        t.join()


def test_admission_queue_close_wakes_waiters_and_producers():
    """The drain sentinel: close() makes put raise QueueClosed (ending
    producer retry loops promptly), wakes wait_* early, and leaves queued
    entries readable for the rerun."""
    import threading
    import time as _time

    q = AdmissionQueue(2)
    q.put("a")
    q.put("b")
    woke = {}

    def waiter():
        t0 = _time.perf_counter()
        woke["result"] = q.wait_at_least(5, timeout=30.0)
        woke["s"] = _time.perf_counter() - t0

    def producer():
        # the put-retry loop every threaded producer runs: QueueFull →
        # back off and retry; QueueClosed must END the loop, not retry
        t0 = _time.perf_counter()
        while True:
            try:
                q.put("c")
                break
            except QueueFull:
                _time.sleep(0.005)
            except QueueClosed:
                woke["producer"] = "closed"
                break
        woke["producer_s"] = _time.perf_counter() - t0

    tw = threading.Thread(target=waiter)
    tp = threading.Thread(target=producer)
    tw.start(), tp.start()
    _time.sleep(0.05)
    q.close()
    tw.join(timeout=5.0), tp.join(timeout=5.0)
    assert not tw.is_alive() and not tp.is_alive()
    assert woke["result"] is False and woke["s"] < 5.0  # not the full 30s
    assert woke["producer"] == "closed" and woke["producer_s"] < 5.0
    assert q.closed
    with pytest.raises(QueueClosed):
        q.put("d")
    assert q.pop()[0] == "a" and q.pop()[0] == "b"  # drain leaves entries
    assert q.wait_nonempty(0.01) is False


def test_serve_config_validation():
    with pytest.raises(ValueError):
        ServeConfig(target_live=0)
    with pytest.raises(ValueError):
        ServeConfig(max_queue=0)
    with pytest.raises(ValueError):
        ServeConfig(watchdog_s=-1.0)
    with pytest.raises(ValueError):
        ServeConfig(failure_budget=0)
    with pytest.raises(ValueError):
        ServeConfig(breaker_threshold=-1)
    with pytest.raises(ValueError, match="owns preemption"):
        FleetServer(FleetScheduler(ALConfig(queries=2, epochs=1, mode="mc"),
                                   preemption=object()),
                    ServeConfig())
    with pytest.raises(ValueError, match="on_terminal"):
        FleetServer(FleetScheduler(ALConfig(queries=2, epochs=1, mode="mc"),
                                   on_terminal=lambda *a: False),
                    ServeConfig())


def test_per_width_scoring_fns_cached_and_guarded():
    """One jit family per (k, tie_break, width) — and a mis-routed batch
    fails loudly instead of silently compiling an off-bucket program."""
    import numpy as np

    a = scoring.fleet_scoring_fns_for_width(k=3, width=32)
    b = scoring.fleet_scoring_fns_for_width(k=3, width=32)
    c = scoring.fleet_scoring_fns_for_width(k=3, width=64)
    assert a is b and a is not c  # cached per width, distinct across
    probs = np.full((2, 2, 32, 4), 0.25, np.float32)
    mask = np.ones((2, 32), bool)
    res = a["mc"](probs, mask)
    assert res.indices.shape == (2, 3)
    with pytest.raises(ValueError, match="bucket routing"):
        c["mc"](probs, mask)  # width-64 family fed width-32 inputs


# -- tier-1 admission smoke + bucket parity -------------------------------


def test_serve_three_user_admission_smoke(tmp_path):
    """3 users through a target-occupancy-2 server: the third user is
    admitted the moment a slot frees (continuous batching — never more
    than 2 live), every trajectory matches its sequential run, and the
    admission telemetry (enqueue/admit events, queue depth, admission
    wait) lands in the fleet metrics stream."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100 + i, f"u{i}", 30) for i in range(3)]
    seq, entries = _baselines_and_entries(tmp_path, cfg, specs)
    jsonl = tmp_path / "fleet_metrics.jsonl"
    recs, server = _serve(cfg, entries, report=FleetReport(str(jsonl)))
    assert [r["error"] for r in recs] == [None] * 3
    for s, r in zip(seq, recs):
        assert r["result"]["trajectory"] == s["trajectory"]
    events = [json.loads(l) for l in open(jsonl)]
    admits = [e for e in events if e["event"] == "admit"]
    assert [a["user"] for a in admits] == ["u0", "u1", "u2"]  # FIFO
    # occupancy target respected: never more than target_live live slots
    assert max(a["live"] for a in admits) <= 2
    # the third admission happened AFTER a completion freed its slot
    done_t = min(e["t_s"] for e in events if e["event"] == "user_done")
    assert admits[2]["t_s"] >= done_t
    summary = server.report.write_summary(cohort=2)
    assert summary["users_done"] == 3 and summary["users_failed"] == 0
    assert summary["admissions"] == 3
    assert summary["admission_wait_s"]["n"] == 3
    assert summary["queue_depth"]["max"] >= 1  # u2 actually waited
    assert 0 < summary["occupancy"] <= 1.0
    # per-user surfaces unchanged: workspace state + reports exist
    for i in range(3):
        d = str(tmp_path / f"serve_u{i}")
        assert os.path.exists(os.path.join(d, "al_state.json"))
        assert os.path.exists(os.path.join(d, "metrics.jsonl"))


def test_serve_bucket_parity_across_skewed_pools(tmp_path):
    """Users of different pool sizes pad to DIFFERENT bucket edges (not a
    shared max), dispatch as separate width-tagged stacked groups, and
    still reproduce their sequential trajectories bit-for-bit."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100, "small0", 20), (101, "small1", 24), (102, "big0", 70),
             (103, "big1", 65)]
    seq, entries = _baselines_and_entries(tmp_path, cfg, specs)
    # a generous batch window makes the phase alignment deterministic:
    # the engine waits out in-flight host work before dispatching a
    # partial batch, so same-bucket sessions stack
    recs, server = _serve(
        cfg, entries,
        serve_cfg=ServeConfig(target_live=4, bucket_widths=(32, 80)),
        scheduler_kw={"batch_window_s": 5.0})
    assert [r["error"] for r in recs] == [None] * 4
    for s, r in zip(seq, recs):
        assert r["result"]["trajectory"] == s["trajectory"]
    widths = {d.get("width") for d in server.report.dispatches}
    assert widths == {32, 80}  # both buckets dispatched, no cohort max
    # same-bucket sessions stacked into shared dispatches
    assert any(d["batch"] > 1 for d in server.report.dispatches)
    per_bucket = server.report.per_bucket_occupancy
    assert set(per_bucket) == {32, 80}
    for stats in per_bucket.values():
        assert 0 < stats["occupancy"] <= 1.0
        assert stats["dispatches"] >= cfg.epochs


# -- slow drills ----------------------------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["mc", "hc", "mix", "rand"])
def test_serve_matches_sequential_all_modes(tmp_path, mode):
    """Acceptance parity: per-user selections and final metrics under the
    serve layer are bit-identical to the sequential loop in all four
    acquisition modes, across mixed bucket widths."""
    cfg = _cfg(mode=mode, epochs=3)
    specs = [(100, "u0", 30), (101, "u1", 30), (102, "u2", 55)]
    seq, entries = _baselines_and_entries(tmp_path, cfg, specs)
    recs, _ = _serve(
        cfg, entries,
        serve_cfg=ServeConfig(target_live=2, bucket_widths=(32, 64)))
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]
        # final metrics, not just the curve
        assert r["result"]["final_mean_f1"] == s["final_mean_f1"]
        assert r["result"]["mode"] == mode


@pytest.mark.slow
@pytest.mark.faults
def test_serve_eviction_resume_keeps_bucket_and_parity(tmp_path):
    """A faulted user is evicted, resumed from its workspace AT ITS PINNED
    BUCKET WIDTH, and finishes with the sequential unfaulted trajectory;
    admission never stalls on the fault."""
    cfg = _cfg(mode="mc", epochs=3)

    def committee_fn(data):
        if data.user_id == "u1":  # the victim: uniquely-named member
            return _committee(data, sgd_name="sgd.victim", min_members=2)
        return _committee(data)

    specs = [(100 + i, f"u{i}", 30) for i in range(3)]
    # sequential baselines run OUTSIDE the injection window (the rule
    # would fire on the baseline's victim retrain instead)
    seq, entries = _baselines_and_entries(tmp_path, cfg, specs,
                                          committee_fn=committee_fn)
    jsonl = tmp_path / "fleet_metrics.jsonl"
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="sgd.victim")) as inj:
        recs, server = _serve(
            cfg, entries,
            serve_cfg=ServeConfig(target_live=2, bucket_widths=(32,)),
            report=FleetReport(str(jsonl)))
    assert inj.fired, "the victim member's retrain fault never fired"
    events = [json.loads(l) for l in open(jsonl)]
    assert [e["user"] for e in events if e["event"] == "evict"] == ["u1"]
    assert [e["user"] for e in events if e["event"] == "resume"] == ["u1"]
    for s, r in zip(seq, recs):
        assert r["error"] is None, r
        assert r["result"]["trajectory"] == s["trajectory"]
    assert {d["width"] for d in server.report.dispatches} == {32}
    assert server.report.users_failed == 0


@pytest.mark.slow
@pytest.mark.faults
def test_serve_terminal_failure_never_stalls_admission(tmp_path):
    """A user that fails terminally (no committee_factory, committee
    exhausted) releases its slot like a completion: later queued users
    are still admitted, and the failure is recorded in the results."""
    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100 + i, f"u{i}", 30) for i in range(3)]
    seq, _ = _baselines_and_entries(tmp_path, cfg, specs)
    entries = []
    for i, (seed, uid, n_songs) in enumerate(specs):
        data = _user_data(seed, uid, n_songs=n_songs)
        committee = (_committee(data, sgd_name="sgd.victim", min_members=2)
                     if i == 0 else _committee(data))
        fp = tmp_path / f"serve2_{uid}"
        fp.mkdir()
        entries.append(FleetUser(uid, committee, data, str(fp),
                                 seed=cfg.seed))  # no committee_factory
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="sgd.victim")) as inj:
        recs, server = _serve(
            cfg, entries, serve_cfg=ServeConfig(target_live=1))
    assert inj.fired
    by_user = {r["user"]: r for r in recs}
    assert by_user["u0"]["error"] is not None
    for i in (1, 2):  # admitted AFTER the failure freed the only slot
        assert by_user[f"u{i}"]["error"] is None
        assert by_user[f"u{i}"]["result"]["trajectory"] \
            == seq[i]["trajectory"]
    assert server.report.users_failed == 1


@pytest.mark.slow
def test_serve_drain_finishes_in_flight_and_leaves_queue(tmp_path):
    """Drain semantics: when the guard trips, in-flight sessions FINISH
    (durable, final, sequential-identical), queued users are never
    admitted (workspaces untouched for the rerun), and ``Preempted``
    surfaces so the CLI exits 75."""
    from consensus_entropy_tpu.resilience.preemption import Preempted

    class TripAfter:
        def __init__(self, after):
            self.checks, self.after = 0, after

        @property
        def requested(self):
            self.checks += 1
            return self.checks > self.after

    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100 + i, f"u{i}", 30) for i in range(4)]
    seq, entries = _baselines_and_entries(tmp_path, cfg, specs)
    jsonl = tmp_path / "fleet_metrics.jsonl"
    sched = FleetScheduler(cfg, report=FleetReport(str(jsonl)),
                           scoring_by_width=True)
    server = FleetServer(sched, ServeConfig(target_live=2),
                         preemption=TripAfter(1))
    with pytest.raises(Preempted, match="drained"):
        server.serve(iter(entries))
    # the drain closed the queue: producers blocked in put-retry loops or
    # wait_* see it promptly instead of spinning out their timeouts
    assert server.queue.closed
    with pytest.raises(QueueClosed):
        server.queue.put(entries[-1])
    # the first admissions ran to completion with sequential results
    assert 1 <= len(server.results) < 4
    for rec in server.results:
        assert rec["error"] is None
        i = int(rec["user"][1:])
        assert rec["result"]["trajectory"] == seq[i]["trajectory"]
    done_users = {r["user"] for r in server.results}
    events = [json.loads(l) for l in open(jsonl)]
    assert any(e["event"] == "drain" for e in events)
    admits = {e["user"] for e in events if e["event"] == "admit"}
    assert admits == done_users  # every admitted session finished
    # queued users were never touched: no workspace state written
    for _, uid, _ in specs:
        touched = os.path.exists(tmp_path / f"serve_{uid}" / "al_state.json")
        assert touched == (uid in done_users)
    # a rerun (no guard) serves the leftovers to the same trajectories
    leftovers = [e for e in entries if e.user_id not in done_users]
    recs2, _ = _serve(cfg, leftovers)
    for rec in recs2:
        i = int(rec["user"][1:])
        assert rec["error"] is None
        assert rec["result"]["trajectory"] == seq[i]["trajectory"]


@pytest.mark.slow
def test_serve_admission_window_gangs_arrivals(tmp_path):
    """With ``admit_window_s`` set, an arrival landing on an idle server
    holds the window open so later arrivals GANG into one admission
    (phase-aligned into one bucket dispatch) instead of trickling in."""
    import threading
    import time as _time

    cfg = _cfg(mode="mc", epochs=1)
    specs = [(100, "u0", 20), (101, "u1", 20)]
    seq, entries = _baselines_and_entries(tmp_path, cfg, specs)
    sched = FleetScheduler(cfg, scoring_by_width=True)
    server = FleetServer(sched, ServeConfig(target_live=2,
                                            admit_window_s=2.0))

    def producer():
        server.submit(entries[0])
        _time.sleep(0.15)  # well inside the window
        server.submit(entries[1])
        server.close_intake()

    t = threading.Thread(target=producer)
    t.start()
    try:
        recs = server.serve((), keep_open=True)
    finally:
        t.join()
    kinds = [(e["event"], e.get("user")) for e in server.report.events]
    # u1's enqueue precedes u0's admission: the window held the gang open
    assert kinds.index(("enqueue", "u1")) < kinds.index(("admit", "u0"))
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]


@pytest.mark.slow
def test_serve_threaded_producer_backpressure(tmp_path):
    """External producers submit() from another thread against the bounded
    queue (retrying on QueueFull — real backpressure); close_intake()
    ends the run once the engine drains."""
    import threading
    import time as _time

    cfg = _cfg(mode="mc", epochs=1)
    specs = [(100 + i, f"u{i}", 20) for i in range(3)]
    seq, entries = _baselines_and_entries(tmp_path, cfg, specs)
    sched = FleetScheduler(cfg, scoring_by_width=True)
    server = FleetServer(sched, ServeConfig(target_live=2, max_queue=2,
                                            admit_window_s=0.02))
    done = {}

    def producer():
        for e in entries:
            while True:
                try:
                    server.submit(e)
                    break
                except QueueFull:  # backpressure: retry as slots drain
                    _time.sleep(0.01)
        server.close_intake()

    t = threading.Thread(target=producer)
    t.start()
    try:
        recs = server.serve((), on_result=lambda r: done.update(
            {r["user"]: r}), keep_open=True)
    finally:
        t.join()
    assert len(recs) == 3
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]
