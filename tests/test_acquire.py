"""Acquisition registry: strategy round-trip, qbdc, wmc.

Tier-1 keeps the registry units, the wmc==mc exact-equality pins, the
weights-before-mask ordering pin, the fleet-scoring parity rows and the
host-mode registry round-trip (a 2-user fleet smoke per registered mode);
the qbdc fleet round and the qbdc resume drill are ``slow`` (the serve
journal-restart qbdc acceptance case in ``tests/test_serve_faults.py`` is
the tier-1 qbdc pin).
"""

import os

import jax
import numpy as np
import pytest

from consensus_entropy_tpu import acquire
from consensus_entropy_tpu.acquire.base import AcquisitionStrategy
from consensus_entropy_tpu.al import state as al_state
from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.al.loop import ALLoop
from consensus_entropy_tpu.config import ALConfig, CNNConfig, TrainConfig
from consensus_entropy_tpu.fleet import FleetScheduler, FleetUser
from consensus_entropy_tpu.ops import scoring
from tests.test_fleet import _cfg, _committee, _user_data

pytestmark = pytest.mark.acquire

TINY_CNN = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)
TINY_TC = TrainConfig(batch_size=2)


# -- registry units --------------------------------------------------------


def test_registry_lists_all_modes_and_rejects_unknown():
    modes = acquire.available_modes()
    assert ("mc", "hc", "mix", "rand") == modes[:4]  # the paper's four
    assert {"qbdc", "wmc"} <= set(modes)
    with pytest.raises(ValueError, match="unknown mode"):
        acquire.get("zzz")
    for m in modes:
        assert acquire.get(m).name == m


def test_registry_rejects_conflicting_reregistration():
    class Imposter(AcquisitionStrategy):
        name = "mc"

    with pytest.raises(ValueError, match="already registered"):
        acquire.register(Imposter())
    # same-class re-registration is an idempotent no-op
    acquire.register(acquire.MachineConsensus())
    assert type(acquire.get("mc")) is acquire.MachineConsensus

    class Nameless(AcquisitionStrategy):
        pass

    with pytest.raises(ValueError, match="no name"):
        acquire.register(Nameless())


def test_strategy_flags_drive_the_machinery():
    """The attributes the loop/acquirer branch on, per mode."""
    flags = {m: acquire.get(m) for m in acquire.available_modes()}
    assert [flags[m].needs_probs for m in ("mc", "mix", "qbdc", "wmc")] \
        == [True] * 4
    assert not flags["hc"].needs_probs and not flags["rand"].needs_probs
    assert flags["qbdc"].probs_source == "qbdc"
    assert flags["wmc"].uses_weights
    assert flags["hc"].uses_hc_table and flags["hc"].uses_hc_entropy
    assert flags["mix"].uses_hc_table and not flags["mix"].uses_hc_entropy


# -- wmc scoring pins ------------------------------------------------------


def _probs(rng, m, n, c=4):
    p = rng.uniform(0.01, 1.0, size=(m, n, c)).astype(np.float32)
    return p / p.sum(axis=-1, keepdims=True)


def test_wmc_equal_weights_is_exactly_mc(rng):
    """THE degradation pin: uniform reliability weights reduce wmc to mc
    BIT-IDENTICALLY (entropies, values, indices), through the jitted
    production fns — wmc runs can be compared against mc baselines with
    ``==``, no tolerance."""
    p = _probs(rng, 5, 96)
    mask = np.zeros(96, bool)
    mask[:80] = True
    fns = scoring.make_scoring_fns(k=7)
    mc = fns["mc"](p, mask)
    wmc = fns["wmc"](p, mask, np.ones(5, np.float32))
    np.testing.assert_array_equal(np.asarray(mc.entropy),
                                  np.asarray(wmc.entropy))
    np.testing.assert_array_equal(np.asarray(mc.values),
                                  np.asarray(wmc.values))
    np.testing.assert_array_equal(np.asarray(mc.indices),
                                  np.asarray(wmc.indices))
    # qbdc shares mc's graph outright (distinct key, same scorer)
    qb = fns["qbdc"](p, mask)
    np.testing.assert_array_equal(np.asarray(mc.entropy),
                                  np.asarray(qb.entropy))


def test_wmc_weights_reorder_the_ranking(rng):
    """Non-uniform weights actually change the consensus: an all-certain
    committee outvoted by one up-weighted uncertain member flips the
    ranking toward the member the weights trust."""
    n = 16
    p = np.zeros((2, n, 4), np.float32)
    p[:, :, 0] = 1.0            # member 0+1 baseline: everything certain
    p[1, 3, :] = 0.25           # member 1 is uncertain about song 3
    mask = np.ones(n, bool)
    fns = scoring.make_scoring_fns(k=1)
    lo = fns["wmc"](p, mask, np.array([1.0, 0.01], np.float32))
    hi = fns["wmc"](p, mask, np.array([0.01, 1.0], np.float32))
    assert int(np.asarray(hi.indices)[0]) == 3
    assert float(np.asarray(hi.values)[0]) \
        > float(np.asarray(lo.values)[0])


def test_wmc_quarantine_mask_zeroes_weight_before_renormalization(rng):
    """The ordering fix: a quarantined member with a stale (huge) weight
    contributes NOTHING — masked wmc equals wmc with that weight set to
    zero, bit-for-bit, and equals scoring the surviving members alone."""
    p = _probs(rng, 4, 48)
    mask = np.zeros(48, bool)
    mask[:40] = True
    stale = np.array([1.0, 1e6, 1.0, 1.0], np.float32)  # member 1 stale
    mmask = np.array([True, False, True, True])
    a = scoring.score_wmc(p, mask, stale, k=5, member_mask=mmask)
    zeroed = stale.copy()
    zeroed[1] = 0.0
    b = scoring.score_wmc(p, mask, zeroed, k=5, member_mask=mmask)
    np.testing.assert_array_equal(np.asarray(a.entropy),
                                  np.asarray(b.entropy))
    np.testing.assert_array_equal(np.asarray(a.indices),
                                  np.asarray(b.indices))
    # and the ranking is the survivors': the stale weight never re-enters
    survivors = scoring.score_wmc(p[[0, 2, 3]], mask,
                                  np.ones(3, np.float32), k=5)
    np.testing.assert_allclose(np.asarray(a.entropy)[mask],
                               np.asarray(survivors.entropy)[mask],
                               rtol=1e-6)


# -- fleet batched parity for the new fn keys ------------------------------


def test_fleet_wmc_and_qbdc_match_single(rng):
    """Every row of the vmapped wmc/qbdc fleet scorers is bit-identical
    to the single-user jitted fn — the same contract the four paper modes
    are pinned to in tests/test_fleet_scoring.py."""
    u, m, n, k = 3, 6, 64, 5
    p = np.stack([_probs(rng, m, n) for _ in range(u)])
    mask = np.zeros((u, n), bool)
    mask[:, :56] = True
    w = rng.uniform(0.1, 2.0, size=(u, m)).astype(np.float32)
    fleet = scoring.make_fleet_scoring_fns(k=k)
    single = scoring.make_scoring_fns(k=k)
    res_w = fleet["wmc"](p, mask, w)
    res_q = fleet["qbdc"](p, mask)
    for i in range(u):
        sw = single["wmc"](p[i], mask[i], w[i])
        sq = single["qbdc"](p[i], mask[i])
        for batched, s in ((res_w, sw), (res_q, sq)):
            np.testing.assert_array_equal(np.asarray(batched.values[i]),
                                          np.asarray(s.values))
            np.testing.assert_array_equal(np.asarray(batched.indices[i]),
                                          np.asarray(s.indices))
            np.testing.assert_array_equal(np.asarray(batched.entropy[i]),
                                          np.asarray(s.entropy))

    mm = np.ones((u, m), bool)
    mm[0, 2] = mm[2, 5] = False

    def one(pp, pm, ww, mmm):
        return scoring.score_wmc(pp, pm, ww, k=k, member_mask=mmm,
                                 tie_break="fast")

    jone = jax.jit(one)
    res_m = fleet["wmc_masked"](p, mask, w, mm)
    for i in range(u):
        s = jone(p[i], mask[i], w[i], mm[i])
        np.testing.assert_array_equal(np.asarray(res_m.entropy[i]),
                                      np.asarray(s.entropy))
        np.testing.assert_array_equal(np.asarray(res_m.indices[i]),
                                      np.asarray(s.indices))


def test_bucket_families_carry_registry_modes():
    """Per-width serve families expose every registered probs mode and
    keep the width guard on the new keys."""
    fns = scoring.fleet_scoring_fns_for_width(k=4, width=32)
    assert {"qbdc", "wmc", "wmc_masked"} <= set(fns)
    bad = np.ones((2, 5, 48, 4), np.float32)
    with pytest.raises(ValueError, match="bucket routing"):
        fns["wmc"](bad, np.ones((2, 48), bool), np.ones((2, 5), np.float32))


# -- qbdc probs producer ---------------------------------------------------


def _cnn_data(seed, uid, n_songs=8):
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore

    data = _user_data(seed, uid, n_songs=n_songs)
    wrng = np.random.default_rng(seed + 7)
    waves = {s: wrng.standard_normal(9000).astype(np.float32)
             for s in data.pool.song_ids}
    data.store = DeviceWaveformStore(waves, TINY_CNN.input_length)
    return data


def _cnn_committee(data, *, seed=5):
    from consensus_entropy_tpu.models import short_cnn
    from consensus_entropy_tpu.models.committee import CNNMember, Committee

    member = CNNMember(
        "cnn0", short_cnn.init_variables(jax.random.key(seed), TINY_CNN),
        TINY_CNN, TINY_TC)
    return Committee([], [member], TINY_CNN, TINY_TC)


def test_qbdc_pool_probs_shape_determinism_and_mask_diversity():
    data = _cnn_data(300, "u0")
    committee = _cnn_committee(data)
    key = jax.random.key(42)
    songs = data.pool.song_ids
    p1 = np.asarray(committee.qbdc_pool_probs(data.store, songs, key, k=5))
    p2 = np.asarray(committee.qbdc_pool_probs(data.store, songs, key, k=5))
    assert p1.shape == (5, len(songs), 4)
    np.testing.assert_array_equal(p1, p2)  # same key -> bit-identical
    # distinct masks actually disagree (a committee, not 5 copies)
    assert np.abs(p1[0] - p1[1]).max() > 0
    # rows are probabilities of a sigmoid head: in (0, 1), finite
    assert np.all(np.isfinite(p1)) and p1.min() > 0 and p1.max() < 1
    # the staging-pad contract mirrors pool_probs: live columns identical
    padded = np.asarray(committee.qbdc_pool_probs(data.store, songs, key,
                                                  k=5, pad_to=300))
    assert padded.shape == (5, 300, 4)
    np.testing.assert_array_equal(padded[:, :len(songs)], p1)
    with pytest.raises(ValueError, match="pad_to"):
        committee.qbdc_pool_probs(data.store, songs, key, k=5, pad_to=2)
    with pytest.raises(ValueError, match=">= 1"):
        committee.qbdc_pool_probs(data.store, songs, key, k=0)


def test_qbdc_requires_a_cnn_member():
    data = _user_data(301, "u0", n_songs=6)
    committee = _committee(data)  # host-only
    with pytest.raises(ValueError, match="CNN member"):
        committee.qbdc_pool_probs(None, data.pool.song_ids,
                                  jax.random.key(0), k=4)


@pytest.mark.faults
def test_qbdc_mask_sampler_is_a_fault_point():
    from consensus_entropy_tpu.resilience import faults
    from consensus_entropy_tpu.resilience.faults import (
        FaultRule,
        InjectedKill,
    )

    data = _cnn_data(302, "u0", n_songs=4)
    committee = _cnn_committee(data)
    with faults.inject(FaultRule("acquire.qbdc.masks", "kill")) as inj:
        with pytest.raises(InjectedKill):
            committee.qbdc_pool_probs(data.store, data.pool.song_ids,
                                      jax.random.key(1), k=3)
    assert inj.fired and inj.fired[0]["point"] == "acquire.qbdc.masks"


# -- registry round-trip: every mode through the 2-user fleet --------------


HOST_MODES = ("mc", "hc", "mix", "rand", "wmc")


@pytest.mark.fleet
@pytest.mark.parametrize("mode", HOST_MODES)
def test_registry_roundtrip_fleet_smoke(tmp_path, mode):
    """Every registered host-committee mode runs a 2-user fleet cohort
    with per-user trajectories identical to sequential runs — new modes
    inherit the engine by registration, not by plumbing."""
    cfg = _cfg(mode=mode, epochs=2)
    seq, entries = [], []
    for i in range(2):
        data = _user_data(100 + i, f"u{i}")
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg).run_user(_committee(data), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(f"u{i}", _committee(data), data, str(fp),
                                 seed=cfg.seed))
    recs = FleetScheduler(cfg).run(entries)
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]


@pytest.mark.fleet
@pytest.mark.slow
def test_registry_roundtrip_fleet_smoke_qbdc(tmp_path):
    """The qbdc round of the registry round-trip: a 2-user dropout-
    committee cohort matches sequential bit-for-bit (the tier-1 qbdc pin
    is the serve journal-restart case in tests/test_serve_faults.py)."""
    cfg = ALConfig(queries=3, epochs=2, mode="qbdc", seed=7,
                   ckpt_dtype="float32", qbdc_k=6)
    seq, entries = [], []
    for i in range(2):
        data = _cnn_data(100 + i, f"u{i}")
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=1).run_user(
            _cnn_committee(data), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(f"u{i}", _cnn_committee(data), data,
                                 str(fp), seed=cfg.seed))
    recs = FleetScheduler(cfg, retrain_epochs=1).run(entries)
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]


# -- wmc end-to-end --------------------------------------------------------


def test_wmc_uniform_weighting_matches_mc_run(tmp_path, rng):
    """End-to-end degradation pin: a wmc run under 'uniform' weighting
    queries the same songs and lands the same trajectory as mc."""
    data = _user_data(400, "u0")
    mc = ALConfig(queries=4, epochs=3, mode="mc", seed=7,
                  ckpt_dtype="float32")
    wu = ALConfig(queries=4, epochs=3, mode="wmc", seed=7,
                  ckpt_dtype="float32", consensus_weighting="uniform")
    res = {}
    for name, cfg in (("mc", mc), ("wmc", wu)):
        p = tmp_path / name
        p.mkdir()
        res[name] = (ALLoop(cfg).run_user(_committee(data), data, str(p)),
                     al_state.ALState.load(str(p)))
    assert res["mc"][0]["trajectory"] == res["wmc"][0]["trajectory"]
    assert res["mc"][1].queried == res["wmc"][1].queried
    # uniform weighting persists no drifting weights: all exactly 1.0
    assert set((res["wmc"][1].member_weights or {}).values()) <= {1.0}


def test_wmc_agreement_updates_and_resumes_bit_identically(tmp_path, rng):
    """The agreement EMA moves weights after each reveal, the weights
    ride ALState, and a mid-run resume replays the straight run exactly
    (weights restored, not re-derived)."""
    data = _user_data(401, "u0")
    full_cfg = ALConfig(queries=4, epochs=4, mode="wmc", seed=11,
                        ckpt_dtype="float32")
    d_full = tmp_path / "full"
    d_full.mkdir()
    res_full = ALLoop(full_cfg).run_user(_committee(data), data,
                                         str(d_full), seed=11)
    st_full = al_state.ALState.load(str(d_full))
    assert st_full.member_weights  # populated, name-keyed
    assert set(st_full.member_weights) == {"gnb.it_0", "sgd.it_0"}
    for w in st_full.member_weights.values():
        assert 0.0 <= w <= 1.0  # EMA of agreements from a 1.0 start

    d_part = tmp_path / "part"
    d_part.mkdir()
    part_cfg = ALConfig(queries=4, epochs=2, mode="wmc", seed=11,
                        ckpt_dtype="float32")
    ALLoop(part_cfg).run_user(_committee(data), data, str(d_part), seed=11)
    committee2 = workspace.load_committee(str(d_part))
    res_resumed = ALLoop(full_cfg).run_user(committee2, data, str(d_part),
                                            seed=11)
    assert res_resumed["trajectory"] == res_full["trajectory"]
    st_part = al_state.ALState.load(str(d_part))
    assert st_part.queried == st_full.queried
    assert st_part.member_weights == st_full.member_weights


# -- qbdc resume determinism (slow; serve-restart is the tier-1 pin) -------


@pytest.mark.slow
@pytest.mark.faults
def test_qbdc_resume_matches_straight_run(tmp_path):
    """A qbdc run killed at the iteration boundary resumes with identical
    queries and trajectory: mask keys fold from the checkpointed PRNG
    stream, so the dropout committee is bit-identical across the cut."""
    data = _cnn_data(500, "u0", n_songs=10)
    full_cfg = ALConfig(queries=3, epochs=3, mode="qbdc", seed=11,
                        ckpt_dtype="float32", qbdc_k=6)
    d_full = tmp_path / "full"
    d_full.mkdir()
    res_full = ALLoop(full_cfg, retrain_epochs=1).run_user(
        _cnn_committee(data), data, str(d_full), seed=11)

    d_part = tmp_path / "part"
    d_part.mkdir()
    part_cfg = ALConfig(queries=3, epochs=1, mode="qbdc", seed=11,
                        ckpt_dtype="float32", qbdc_k=6)
    ALLoop(part_cfg, retrain_epochs=1).run_user(
        _cnn_committee(data), data, str(d_part), seed=11)
    committee2 = workspace.load_committee(str(d_part), TINY_CNN, TINY_TC)
    res_resumed = ALLoop(full_cfg, retrain_epochs=1).run_user(
        committee2, data, str(d_part), seed=11)
    assert res_resumed["trajectory"] == res_full["trajectory"]
    assert al_state.ALState.load(str(d_part)).queried \
        == al_state.ALState.load(str(d_full)).queried
    assert os.path.exists(d_part / "classifier_cnn.cnn0.msgpack")
