"""Torch .pth checkpoint import: the converted Flax model must reproduce the
reference ShortChunkCNN forward (``/root/reference/short_cnn.py:278-349``)
numerically.  The oracle below runs the torch side with plain functional ops
on the same state dict, fed with OUR mel output so the frontend is held
common (mel-vs-torchaudio parity is pinned separately in test_mel.py)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402

from consensus_entropy_tpu.config import CNNConfig  # noqa: E402
from consensus_entropy_tpu.models import short_cnn  # noqa: E402
from consensus_entropy_tpu.ops.mel import log_mel_spectrogram  # noqa: E402
from consensus_entropy_tpu.utils.torch_import import (  # noqa: E402
    import_torch_shortchunk,
)

# 32 mels / 5 pools -> the freq axis collapses to 1, matching the
# reference's squeeze(2) + MaxPool1d global-time pooling exactly.
CFG = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)


def _random_state_dict(rng, cfg: CNNConfig) -> dict:
    """A reference-shaped state dict with random weights and realistic
    (non-trivial) BN running stats."""

    def t(*shape, scale=0.3):
        return torch.tensor(
            rng.standard_normal(shape).astype(np.float32) * scale)

    def bn(prefix, n):
        return {
            f"{prefix}.weight": t(n) + 1.0,
            f"{prefix}.bias": t(n),
            f"{prefix}.running_mean": t(n),
            f"{prefix}.running_var": torch.abs(t(n)) + 0.5,
            f"{prefix}.num_batches_tracked": torch.tensor(7),
        }

    state = {"spec.mel_scale.fb": t(cfg.n_fft // 2 + 1, cfg.n_mels),
             **bn("spec_bn", 1)}
    in_ch = 1
    for i, width in enumerate(cfg.channel_widths):
        state[f"layer{i + 1}.conv.weight"] = t(width, in_ch, 3, 3)
        state[f"layer{i + 1}.conv.bias"] = t(width)
        state.update(bn(f"layer{i + 1}.bn", width))
        in_ch = width
    top = cfg.channel_widths[-1]
    state["dense1.weight"] = t(top, top)
    state["dense1.bias"] = t(top)
    state.update(bn("bn", top))
    state["dense2.weight"] = t(cfg.n_class, top)
    state["dense2.bias"] = t(cfg.n_class)
    return state


def _torch_forward(state: dict, spec: torch.Tensor, cfg: CNNConfig):
    """The reference forward from the spectrogram down (eval mode),
    expressed with torch functional ops over the raw state dict."""
    import torch.nn.functional as F

    def bn(x, prefix):
        return F.batch_norm(x, state[f"{prefix}.running_mean"],
                            state[f"{prefix}.running_var"],
                            state[f"{prefix}.weight"],
                            state[f"{prefix}.bias"], training=False,
                            eps=1e-5)

    x = spec.unsqueeze(1)  # (B, 1, n_mels, T)
    x = bn(x, "spec_bn")
    for i in range(cfg.n_layers):
        x = F.conv2d(x, state[f"layer{i + 1}.conv.weight"],
                     state[f"layer{i + 1}.conv.bias"], padding=1)
        x = F.relu(bn(x, f"layer{i + 1}.bn"))
        x = F.max_pool2d(x, 2)
    x = x.squeeze(2)  # freq axis == 1 by construction
    if x.size(-1) != 1:
        x = F.max_pool1d(x, x.size(-1))
    x = x.squeeze(2)
    x = F.linear(x, state["dense1.weight"], state["dense1.bias"])
    x = F.relu(bn(x, "bn"))
    x = F.linear(x, state["dense2.weight"], state["dense2.bias"])
    return torch.sigmoid(x)


def test_imported_checkpoint_matches_torch_forward(rng):
    state = _random_state_dict(rng, CFG)
    variables = import_torch_shortchunk(state, CFG)
    x = rng.standard_normal((3, CFG.input_length)).astype(np.float32) * 0.1

    ours = np.asarray(short_cnn.apply_infer(variables, x, CFG))

    spec = torch.tensor(np.asarray(log_mel_spectrogram(x, CFG)))
    want = _torch_forward(state, spec, CFG).numpy()
    np.testing.assert_allclose(ours, want, rtol=1e-4, atol=1e-5)


def test_import_validates_geometry(rng):
    state = _random_state_dict(rng, CFG)
    with pytest.raises(ValueError, match="conv layers"):
        import_torch_shortchunk(state, CNNConfig(
            n_channels=4, n_mels=32, n_layers=3, input_length=8192))
    with pytest.raises(ValueError, match="output channels"):
        import_torch_shortchunk(state, CNNConfig(
            n_channels=8, n_mels=32, n_layers=5, input_length=8192))
    with pytest.raises(ValueError, match="vgg"):
        import_torch_shortchunk(state, CNNConfig(
            n_channels=4, n_layers=5, input_length=8192, arch="res"))


def test_mel_geometry_validated_via_fb_shape(rng):
    """The dropped filterbank buffer still certifies the checkpoint's mel
    geometry: a wrong-shape fb must refuse to convert."""
    state = _random_state_dict(rng, CFG)
    state["spec.mel_scale.fb"] = torch.zeros(CFG.n_fft // 2 + 1, 96)
    with pytest.raises(ValueError, match="mel filterbank"):
        import_torch_shortchunk(state, CFG)


def test_import_cli_roundtrip(rng, tmp_path):
    """.pth file -> converter CLI (main()) -> workspace-loadable member."""
    from consensus_entropy_tpu.models.committee import CNNMember
    from consensus_entropy_tpu.utils import torch_import

    # main() converts at the DEFAULT reference geometry
    default_cfg = CNNConfig()
    state = _random_state_dict(rng, default_cfg)
    src = str(tmp_path / "best_model.pth")
    torch.save(state, src)
    dst = str(tmp_path / "classifier_cnn.it_3.msgpack")
    assert torch_import.main([src, dst]) == 0

    m = CNNMember.load(dst)
    assert m.name == "it_3"  # workspace-convention name derivation
    assert m.config.arch == "vgg" and m.config.n_mels == default_cfg.n_mels

    # non-convention filename falls back to the extensionless stem
    dst2 = str(tmp_path / "imported.msgpack")
    assert torch_import.main([src, dst2, "--name", "legacy"]) == 0
    assert CNNMember.load(dst2).name == "legacy"


def test_library_roundtrip_preserves_forward(rng, tmp_path):
    from consensus_entropy_tpu.models.committee import CNNMember

    state = _random_state_dict(rng, CFG)
    variables = import_torch_shortchunk(state, CFG)
    dst = str(tmp_path / "classifier_cnn.it_0.msgpack")
    CNNMember("it_0", variables, CFG).save(dst)
    m2 = CNNMember.load(dst, CFG)
    x = rng.standard_normal((2, CFG.input_length)).astype(np.float32) * 0.1
    np.testing.assert_array_equal(
        np.asarray(short_cnn.apply_infer(m2.variables, x, CFG)),
        np.asarray(short_cnn.apply_infer(variables, x, CFG)))
