"""Cross-user stacked CNN device path vs the single-user production paths.

The contract the CNN cohort batching rests on: every per-user slice of a
stacked device-plan dispatch (``models.committee.run_device_plans`` — a
``lax.map`` over the users axis) is BIT-IDENTICAL to that user's own
single-user jitted path — ``predict_songs_cnn`` for the stored committee,
``qbdc_pool_probs`` for the dropout committee, ``fit_many`` for
retraining — because the mapped body IS the single-user program (vmap
over batched conv kernels is NOT bitwise and is deliberately not used;
see ``short_cnn.committee_infer_users``).

Tier-1 keeps one fast mc-forward parity case; the matrix (qbdc,
quarantine, retrain lockstep, end-to-end cohorts, eviction+resume at the
pinned pad) is ``slow``, per the tier-1 budget.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from consensus_entropy_tpu.config import ALConfig, CNNConfig, TrainConfig
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.labels import one_hot_np
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.models.committee import (
    CNNMember,
    Committee,
    FramePool,
    run_device_plans,
)

pytestmark = pytest.mark.fleet

TINY = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)
TC = TrainConfig(batch_size=2)


def _store(seed, n_songs=8):
    w = np.random.default_rng(seed)
    sids = [f"s{i:02d}" for i in range(n_songs)]
    waves = {s: w.standard_normal(9000).astype(np.float32) for s in sids}
    return DeviceWaveformStore(waves, TINY.input_length), sids


def _cnn_committee(seed, n_members=2, host_members=()):
    cnns = [CNNMember(f"cnn{i}",
                      short_cnn.init_variables(jax.random.key(seed + i),
                                               TINY), TINY, TC)
            for i in range(n_members)]
    return Committee(list(host_members), cnns, TINY, TC)


def test_stacked_cnn_forward_rows_bit_identical():
    """The tier-1 pin: a 3-user stacked ``cnn_probs`` dispatch returns
    each user's ``(M, pad_to, C)`` block bit-identical to that user's own
    ``predict_songs_cnn`` (same crop stream, same 256-crop compile-bucket
    discipline, same staging-width slice)."""
    users = [( _cnn_committee(100 + 10 * i), *_store(200 + i))
             for i in range(3)]
    keys = [jax.random.key(300 + i) for i in range(3)]
    plans = [c.cnn_score_plan(st, sids, k, pad_to=16)
             for (c, st, sids), k in zip(users, keys)]
    assert all(p is not None for p in plans)
    # one cohort geometry -> one dispatch group
    assert len({p.group_key() for p in plans}) == 1
    blocks = run_device_plans(plans)
    for (c, st, sids), k, b in zip(users, keys, blocks):
        single = c.predict_songs_cnn(st, sids, k, pad_to=16)
        assert b.shape == (2, 16, TINY.n_class)
        np.testing.assert_array_equal(np.asarray(b), np.asarray(single))


@pytest.mark.slow
def test_stacked_qbdc_rows_bit_identical():
    """qbdc: the stacked ``(U, K)`` dropout-committee dispatch matches
    each user's own ``qbdc_pool_probs`` bitwise — same crop/mask key
    derivation (``Committee._qbdc_stage`` is shared verbatim)."""
    users = [( _cnn_committee(400 + 10 * i, n_members=1), *_store(500 + i))
             for i in range(3)]
    keys = [jax.random.key(600 + i) for i in range(3)]
    plans = [c.qbdc_score_plan(st, sids, k, k=6, pad_to=8)
             for (c, st, sids), k in zip(users, keys)]
    assert len({p.group_key() for p in plans}) == 1
    blocks = run_device_plans(plans)
    for (c, st, sids), k, b in zip(users, keys, blocks):
        single = c.qbdc_pool_probs(st, sids, k, k=6, pad_to=8)
        assert b.shape == (6, 8, TINY.n_class)
        np.testing.assert_array_equal(np.asarray(b), np.asarray(single))
        # the K masks are genuinely distinct subnetworks
        assert len({np.asarray(b[j]).tobytes() for j in range(6)}) > 1


@pytest.mark.slow
def test_stacked_forward_with_quarantined_member():
    """A quarantined CNN member changes the user's stacked-member axis:
    that user's plan groups SEPARATELY (different member count) and its
    rows still match its own single-user path over the surviving
    members; an intact peer in the same round is unaffected."""
    com_a = _cnn_committee(700, n_members=2)
    com_b = _cnn_committee(710, n_members=2)
    com_b.quarantine("cnn0", "injected mid-pass failure")
    (st_a, sids_a), (st_b, sids_b) = _store(701), _store(711)
    ka, kb = jax.random.key(702), jax.random.key(712)
    plan_a = com_a.cnn_score_plan(st_a, sids_a, ka, pad_to=8)
    plan_b = com_b.cnn_score_plan(st_b, sids_b, kb, pad_to=8)
    assert plan_a.group_key() != plan_b.group_key()  # M=2 vs M=1
    (block_a,), (block_b,) = run_device_plans([plan_a]), \
        run_device_plans([plan_b])
    np.testing.assert_array_equal(
        np.asarray(block_a),
        np.asarray(com_a.predict_songs_cnn(st_a, sids_a, ka, pad_to=8)))
    single_b = com_b.predict_songs_cnn(st_b, sids_b, kb, pad_to=8)
    assert block_b.shape[0] == 1  # the survivor only
    np.testing.assert_array_equal(np.asarray(block_b),
                                  np.asarray(single_b))


@pytest.mark.slow
def test_fit_many_users_matches_per_user_fit_many():
    """User-lockstep retraining: each user's best checkpoints and history
    rows from one ``fit_many_users`` cohort equal its own sequential
    ``fit_many`` call bitwise (same fold_in key streams, same epoch
    schedule)."""
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

    trainer = CNNTrainer(TINY, TC)
    users = []
    for i in range(2):
        store, sids = _store(800 + i)
        w = np.random.default_rng(810 + i)
        users.append(dict(
            variables_list=[short_cnn.init_variables(
                jax.random.key(820 + 10 * i + j), TINY) for j in range(2)],
            store=store, train_ids=sids[:5],
            train_y=one_hot_np(w.integers(0, 4, 5)), test_ids=sids[5:],
            test_y=one_hot_np(w.integers(0, 4, 3)),
            key=jax.random.key(830 + i)))
    fitted = trainer.fit_many_users(users, n_epochs=3)
    for u, (best, hists) in zip(users, fitted):
        ref_best, ref_hists = trainer.fit_many(
            u["variables_list"], u["store"], u["train_ids"], u["train_y"],
            u["test_ids"], u["test_y"], u["key"], n_epochs=3)
        assert hists == ref_hists
        for b, rb in zip(best, ref_best):
            for a, r in zip(jax.tree.leaves(b), jax.tree.leaves(rb)):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(r))


def test_fit_many_users_rejects_ragged_cohort():
    store_a, sids_a = _store(840, n_songs=8)
    store_b, sids_b = _store(841, n_songs=8)
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

    def entry(store, sids, n_train):
        w = np.random.default_rng(0)
        return dict(
            variables_list=[short_cnn.init_variables(jax.random.key(1),
                                                     TINY)],
            store=store, train_ids=sids[:n_train],
            train_y=one_hot_np(w.integers(0, 4, n_train)),
            test_ids=sids[6:], test_y=one_hot_np(w.integers(0, 4, 2)),
            key=jax.random.key(2))

    with pytest.raises(ValueError, match="not homogeneous"):
        CNNTrainer(TINY, TC).fit_many_users(
            [entry(store_a, sids_a, 5), entry(store_b, sids_b, 6)],
            n_epochs=1)


@pytest.mark.slow
def test_retrain_plan_compute_is_pure_commit_rebinds():
    """The stacked retrain's watchdog-safety split: ``stage_device_plans``
    (the half a scheduler may run under a watchdog and abandon) must NOT
    rebind member variables — a zombie dispatch finishing late would
    otherwise overwrite committees that already took the per-user
    fallback.  ``commit_device_plans`` applies the best-checkpoint gate,
    exactly as ``retrain_cnns`` does."""
    from consensus_entropy_tpu.models.committee import (
        commit_device_plans,
        stage_device_plans,
    )

    store, sids = _store(860)
    coms = [_cnn_committee(870 + u, n_members=1) for u in range(2)]
    w = np.random.default_rng(3)
    y_q = one_hot_np(w.integers(0, 4, 4))
    y_t = one_hot_np(w.integers(0, 4, 2))
    plans = [c.retrain_plan(store, sids[:4], y_q, sids[6:], y_t,
                            jax.random.key(5), n_epochs=8) for c in coms]
    before = [c.cnn_members[0].variables for c in coms]
    computed = stage_device_plans(plans)
    for c, b in zip(coms, before):
        assert c.cnn_members[0].variables is b  # pure: nothing rebound
    hists = commit_device_plans(plans, computed)
    for c, b, h in zip(coms, before, hists):
        if any(e["improved"] for e in h[0]):
            assert c.cnn_members[0].variables is not b
        else:
            assert c.cnn_members[0].variables is b


# -- end-to-end cohorts ----------------------------------------------------


def _user_data(seed, uid, n_songs=10, f=10):
    from consensus_entropy_tpu.al.loop import UserData

    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((4, f)).astype(np.float32) * 2.5
    rows, sids, labels = [], [], {}
    for i in range(n_songs):
        sid = f"song{i:03d}"
        c = int(rng.integers(0, 4))
        labels[sid] = c
        k = int(rng.integers(3, 7))
        rows.append(centers[c]
                    + rng.standard_normal((k, f)).astype(np.float32))
        sids += [sid] * k
    pool = FramePool(np.vstack(rows), sids)
    data = UserData(uid, pool, labels, hc_rows=None)
    wrng = np.random.default_rng(seed + 7)
    waves = {s: wrng.standard_normal(9000).astype(np.float32)
             for s in pool.song_ids}
    data.store = DeviceWaveformStore(waves, TINY.input_length)
    return data


def _mixed_committee(data, seed):
    from consensus_entropy_tpu.models.sklearn_members import GNBMember

    X = data.pool.X
    y = np.array([data.labels[s] for s in np.repeat(
        data.pool.song_ids, data.pool.counts)], np.int32)
    return _cnn_committee(seed,
                          host_members=[GNBMember("gnb.it_0").fit(X, y)])


@pytest.mark.slow
@pytest.mark.parametrize("mode,qbdc_k", [("mc", None), ("qbdc", 4)])
def test_cnn_cohort_stacked_matches_sequential(tmp_path, mode, qbdc_k):
    """End to end: a 3-user CNN cohort under the stacked device path
    reproduces the sequential per-user trajectories exactly, and the
    fleet summary grades the CNN dispatches (mean_device_batch > 1 —
    cross-user batching genuinely engaged, for scoring AND retraining)."""
    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.fleet import (
        FleetReport,
        FleetScheduler,
        FleetUser,
    )

    kw = dict(qbdc_k=qbdc_k) if qbdc_k else {}
    cfg = ALConfig(queries=3, epochs=2, mode=mode, seed=7,
                   ckpt_dtype="float32", **kw)
    n_members = 1 if mode == "qbdc" else 2

    def committee_fn(seed):
        return (_cnn_committee(seed, n_members=1) if mode == "qbdc"
                else _mixed_committee(data_by_seed[seed], seed))

    data_by_seed = {}
    seq, entries = [], []
    for i in range(3):
        data = _user_data(100 + i, f"u{i}")
        data_by_seed[100 + i] = data
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=2).run_user(
            committee_fn(100 + i), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(f"u{i}", committee_fn(100 + i), data,
                                 str(fp), seed=cfg.seed))
    # the batch window phase-aligns the cohort's pooled host steps
    # (baseline/eval/select staging) so plan groups form full — the
    # batch-forming config the fleet/serve drivers and the cnn-fleet
    # bench run; window=0 stays the latency-eager default
    sched = FleetScheduler(cfg, retrain_epochs=2, report=FleetReport(),
                           batch_window_s=0.2)
    recs = sched.run(entries)
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]
    cnn = sched.report.cnn_dispatch_summary
    assert cnn is not None and cnn["mean_device_batch"] > 1.0
    probs_fn = "qbdc_probs" if mode == "qbdc" else "cnn_probs"
    assert cnn[probs_fn]["mean_batch"] > 1.0
    assert cnn["cnn_retrain"]["mean_batch"] > 1.0
    assert n_members  # silence unused warning paths


@pytest.mark.slow
def test_cnn_cohort_chunked_matches_sequential(tmp_path):
    """``plan_chunk`` end to end: a 3-user cohort serviced in chunk-2
    dispatch quanta (2+1 per plan group) still reproduces the sequential
    trajectories exactly — the chunked rounds, the partial-group hold,
    and the batch-of-one fallback through ``step.single`` all preserve
    per-user bit-identity."""
    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.fleet import (
        FleetReport,
        FleetScheduler,
        FleetUser,
    )

    cfg = ALConfig(queries=3, epochs=2, mode="mc", seed=7,
                   ckpt_dtype="float32")
    data_by_seed = {}
    seq, entries = [], []
    for i in range(3):
        data = _user_data(100 + i, f"u{i}")
        data_by_seed[100 + i] = data
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=2).run_user(
            _mixed_committee(data, 100 + i), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(f"u{i}", _mixed_committee(data, 100 + i),
                                 data, str(fp), seed=cfg.seed))
    sched = FleetScheduler(cfg, retrain_epochs=2, report=FleetReport(),
                           batch_window_s=0.2, plan_chunk=2)
    recs = sched.run(entries)
    for s, r in zip(seq, recs):
        assert r["error"] is None, r
        assert r["result"]["trajectory"] == s["trajectory"]
    cnn = sched.report.cnn_dispatch_summary
    assert cnn is not None
    # chunk=2 over a 3-user cohort: dispatch quanta of at most 2, and at
    # least one genuine multi-user dispatch went through
    batches = [d["batch"] for d in sched.report.dispatches
               if d["fn"] in ("cnn_probs", "cnn_retrain", "cnn_eval")]
    assert batches and max(batches) == 2


@pytest.mark.slow
@pytest.mark.faults
def test_cnn_cohort_eviction_resume_at_pinned_pad(tmp_path):
    """A CNN session evicted mid-cohort (injected retrain failure on its
    sklearn member under a min_members floor) resumes from its workspace
    AT THE PINNED PAD WIDTH, rejoins the stacked dispatches, and finishes
    with the sequential unfaulted trajectory."""
    from consensus_entropy_tpu.al import workspace
    from consensus_entropy_tpu.al.loop import ALLoop
    from consensus_entropy_tpu.fleet import (
        FleetReport,
        FleetScheduler,
        FleetUser,
    )
    from consensus_entropy_tpu.models.sklearn_members import GNBMember
    from consensus_entropy_tpu.resilience import faults
    from consensus_entropy_tpu.resilience.faults import FaultRule

    cfg = ALConfig(queries=3, epochs=2, mode="mc", seed=7,
                   ckpt_dtype="float32")

    def committee_fn(data, victim):
        X = data.pool.X
        y = np.array([data.labels[s] for s in np.repeat(
            data.pool.song_ids, data.pool.counts)], np.int32)
        name = "gnb.victim" if victim else "gnb.it_0"
        com = _cnn_committee(900, host_members=[GNBMember(name).fit(X, y)])
        com.min_members = 3 if victim else 1
        return com

    seq, entries = [], []
    for i in range(2):
        data = _user_data(100 + i, f"u{i}")
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=2).run_user(
            committee_fn(data, victim=False), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()

        def factory(fp=fp, data=data):
            com = workspace.load_committee(str(fp), TINY)
            com.trainer.train_config = TC
            for m in com.cnn_members:
                m.train_config = TC
            return com

        entries.append(FleetUser(
            f"u{i}", committee_fn(data, victim=(i == 0)), data, str(fp),
            seed=cfg.seed, committee_factory=factory))
    jsonl = tmp_path / "fleet_metrics.jsonl"
    sched = FleetScheduler(cfg, retrain_epochs=2,
                           report=FleetReport(str(jsonl)))
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="gnb.victim")) as inj:
        recs = sched.run(entries)
    assert inj.fired
    evicts = [e for e in sched.report.events if e["event"] == "evict"]
    resumes = [e for e in sched.report.events if e["event"] == "resume"]
    assert [e["user"] for e in evicts] == ["u0"]
    assert [e["user"] for e in resumes] == ["u0"]
    for s, r in zip(seq, recs):
        assert r["error"] is None, r
        assert r["result"]["trajectory"] == s["trajectory"]


def test_session_step_flags():
    """The per-step offload split (the ``host_offloadable`` fix): a CNN
    committee no longer opts the whole session out of the worker pool —
    its jax-free sklearn blocks stay offloadable and its device work
    routes through DeviceSteps; ``cnn_steps=False`` restores the legacy
    inline shape."""
    import os

    from consensus_entropy_tpu.fleet.session import UserSession

    data = _user_data(950, "u0")
    cfg = ALConfig(queries=3, epochs=1, mode="mc", seed=7)

    def session(com, **kw):
        p = f"/tmp/_flags_{os.getpid()}_{id(com)}"
        os.makedirs(p, exist_ok=True)
        return UserSession(cfg, com, data, p, resume=False, **kw)

    s = session(_mixed_committee(data, 960))
    assert not s.host_offloadable and s.cnn_steps and s.sklearn_offloadable
    s2 = session(_mixed_committee(data, 961), cnn_steps=False)
    assert not s2.cnn_steps and not s2.sklearn_offloadable

    from consensus_entropy_tpu.models.sklearn_members import GNBMember

    X = data.pool.X
    y = np.array([data.labels[s] for s in np.repeat(
        data.pool.song_ids, data.pool.counts)], np.int32)
    host_only = Committee([GNBMember("gnb.it_0").fit(X, y)], [])
    s3 = session(host_only)
    assert s3.host_offloadable and s3.sklearn_offloadable
    assert not s3.cnn_steps  # nothing to stack


def test_hold_partial_plans_releases_chunk_quanta():
    """``plan_chunk`` batch-forming (``_hold_partial_plans``): full chunk
    quanta of a same-key plan group dispatch now, the sub-chunk remainder
    is held back into ``_score_wait`` to be joined by the plans the
    outstanding host steps are about to produce; reduction ScoreSteps
    always pass through; a different-key group holds independently."""
    import dataclasses

    from consensus_entropy_tpu.fleet.scheduler import FleetScheduler
    from consensus_entropy_tpu.fleet.session import DeviceStep, ScoreStep

    @dataclasses.dataclass
    class FakePlan:
        sig: str

        def group_key(self):
            return ("cnn_probs", self.sig)

    cfg = ALConfig(queries=3, epochs=1, mode="mc", seed=7)
    sched = FleetScheduler(cfg, plan_chunk=2)
    sched.open(capacity=2)
    try:
        def dstep(sig):
            return DeviceStep(None, FakePlan(sig), lambda: None, "cnn_probs")

        a = [(f"stA{i}", dstep("a")) for i in range(5)]
        b = [(f"stB{i}", dstep("b")) for i in range(1)]
        r = [("stR", ScoreStep(None, "mc", ()))]
        out = sched._hold_partial_plans(list(a) + list(b) + list(r))
        # 5 same-key 'a' plans -> 4 dispatch (2 chunk quanta), 1 held;
        # the lone 'b' plan is all-remainder -> held; ScoreStep passes
        assert [s for s, _ in out if s.startswith("stA")] == \
            ["stA0", "stA1", "stA2", "stA3"]
        assert ("stR", r[0][1]) in out and len(out) == 5
        held = {s for s, _ in sched._score_wait}
        assert held == {"stA4", "stB0"}
        # with the pool quiet the caller skips the hold entirely (pump
        # only calls this while _host_wait is non-empty), so a full
        # flush needs no special casing here — but a re-offered batch
        # must release whole quanta again, not re-hold forever
        sched._score_wait.clear()
        out2 = sched._hold_partial_plans([("stA4", dstep("a")),
                                          ("stB0", dstep("b")),
                                          ("stB1", dstep("b"))])
        assert [s for s, _ in out2] == ["stB0", "stB1"]
        assert {s for s, _ in sched._score_wait} == {"stA4"}
    finally:
        sched._host_pool.shutdown(wait=False)
        sched._ckpt_pool.shutdown(wait=False)
