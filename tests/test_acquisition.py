"""Acquirer: mode semantics, pool shrinkage, hc removal, fixed shapes."""

import numpy as np
import pytest

from consensus_entropy_tpu.al.acquisition import Acquirer


def _probs(rng, m, n):
    p = rng.uniform(0.01, 1, size=(m, n, 4)).astype(np.float32)
    return p / p.sum(-1, keepdims=True)


def _hc(rng, n):
    c = rng.integers(1, 20, size=(n, 4))
    return np.round(c / c.sum(1, keepdims=True), 3).astype(np.float32)


SONGS = [f"s{i:03d}" for i in range(37)]


def test_mc_shrinks_pool(rng):
    acq = Acquirer(SONGS, None, queries=5, mode="mc", seed=0)
    assert acq.n_pad % 8 == 0 and acq.n_pad >= 37
    total = set()
    for _ in range(4):
        live = acq.remaining_songs
        q = acq.select(_probs(rng, 3, len(live)))
        assert len(q) == 5
        assert not set(q) & total  # never re-queried
        total |= set(q)
    assert len(acq.remaining_songs) == 37 - 20


def test_mc_picks_max_entropy(rng):
    acq = Acquirer(SONGS, None, queries=3, mode="mc")
    live = acq.remaining_songs
    p = np.zeros((2, len(live), 4), np.float32)
    p[:, :, 0] = 1.0  # everything certain → entropy 0
    for j, hot in enumerate([5, 11, 20]):  # three uniform (max-entropy) songs
        p[:, hot, :] = 0.25
    q = acq.select(p)
    assert set(q) == {SONGS[5], SONGS[11], SONGS[20]}


def test_hc_mode_removes_rows(rng):
    hc = _hc(rng, 37)
    acq = Acquirer(SONGS, hc, queries=6, mode="hc")
    q1 = acq.select()
    q2 = acq.select()
    assert not set(q1) & set(q2)
    # and pool also shrank (amg_test.py:520-523 applies in every mode)
    assert len(acq.remaining_songs) == 37 - len(q1) - len(q2)


def test_mix_mode_dedups_and_removes(rng):
    hc = _hc(rng, 37)
    acq = Acquirer(SONGS, hc, queries=6, mode="mix")
    live = acq.remaining_songs
    q = acq.select(_probs(rng, 4, len(live)))
    assert 1 <= len(q) <= 6
    assert len(set(q)) == len(q)
    for s in q:
        r = acq._song_row[s]
        assert not acq.pool_mask[r] and not acq.hc_mask[r]


def test_rand_mode_unique_and_seeded():
    a1 = Acquirer(SONGS, None, queries=8, mode="rand", seed=3)
    a2 = Acquirer(SONGS, None, queries=8, mode="rand", seed=3)
    a3 = Acquirer(SONGS, None, queries=8, mode="rand", seed=4)
    q1, q2, q3 = a1.select(), a2.select(), a3.select()
    assert q1 == q2
    assert q1 != q3
    assert len(set(q1)) == 8


def test_exhausting_pool(rng):
    songs = SONGS[:7]
    acq = Acquirer(songs, None, queries=5, mode="mc")
    q1 = acq.select(_probs(rng, 2, 7))
    assert len(q1) == 5
    q2 = acq.select(_probs(rng, 2, 2))
    assert len(q2) == 2  # only 2 valid left; -inf slots trimmed
    assert acq.remaining_songs == []


def test_unknown_mode():
    with pytest.raises(ValueError):
        Acquirer(SONGS, None, queries=3, mode="zzz").select()


def test_staged_device_probs_match_host_numpy(rng):
    """The persistent device probs buffer (live rows scattered in place,
    stale rows behind the mask) must select identically to host-numpy
    feeds, across shrinking iterations and for both mc and mix."""
    import jax.numpy as jnp

    for mode in ("mc", "mix"):
        hc = _hc(rng, 37) if mode == "mix" else None
        # fuse_step=False pins the legacy host-pad arm (the fused arm
        # routes numpy probs through the scatter too; its own parity is
        # pinned in tests/test_fused_step.py)
        a = Acquirer(SONGS, hc, queries=4, mode=mode, seed=1,
                     fuse_step=False)
        b = Acquirer(SONGS, hc, queries=4, mode=mode, seed=1,
                     fuse_step=False)
        for _ in range(3):
            live = a.remaining_songs
            assert live == b.remaining_songs
            p = _probs(rng, 3, len(live))
            qa = a.select(np.asarray(p))      # host numpy feed
            qb = b.select(jnp.asarray(p))     # device-array feed
            assert qa == qb
        # device-fed path: the staged buffer never reallocates across
        # iterations; numpy-fed path: compile-free host pad, no buffer
        assert b._probs_buf.shape == (3, b.n_pad, 4)
        assert a._probs_buf is None


def _stage_pad(p, w):
    """Pad probs to the staging width.  The tail is GARBAGE (uniform rows
    scaled oddly) on purpose: the pool_probs ``pad_to`` contract leaves the
    staging columns unspecified and the acquirer's scatter must drop them."""
    n = p.shape[1]
    if w == n:
        return p
    tail = np.full((p.shape[0], w - n, p.shape[2]), 0.125, p.dtype)
    return np.concatenate([p, tail], axis=1)


def test_staging_width_selects_identically(rng):
    """Probs staged at ``staging_width`` (fixed-bucket, unspecified tail —
    the pool_probs ``pad_to`` contract) must select exactly as exact-width
    probs, and the width must stay constant across the shrinking pool."""
    import jax.numpy as jnp

    for mode in ("mc", "mix"):
        hc = _hc(rng, 37) if mode == "mix" else None
        a = Acquirer(SONGS, hc, queries=4, mode=mode, seed=5)
        b = Acquirer(SONGS, hc, queries=4, mode=mode, seed=5)
        widths = set()
        for _ in range(3):
            live = a.remaining_songs
            p = _probs(rng, 3, len(live))
            w = a.staging_width(len(live))
            assert len(live) <= w <= a.n_pad
            qa = a.select(jnp.asarray(_stage_pad(p, w)))
            qb = b.select(jnp.asarray(p))
            assert qa == qb
            widths.add(w)
        assert len(widths) == 1  # one scatter shape across the whole run


def test_staging_width_scatter_compiles_once(rng):
    """At the staging width the scatter program is hit from cache on every
    iteration after the first — the round-3 per-live-width recompile
    (VERDICT r3 weak #2) is gone."""
    from consensus_entropy_tpu.al import acquisition

    import jax.numpy as jnp

    acq = Acquirer([f"t{i:03d}" for i in range(53)], None, queries=4,
                   mode="mc", seed=6)
    live = acq.remaining_songs
    w = acq.staging_width(len(live))
    acq.select(jnp.asarray(_stage_pad(_probs(rng, 7, len(live)), w)))
    size0 = acquisition._scatter_rows._cache_size()
    for _ in range(3):
        live = acq.remaining_songs
        assert acq.staging_width(len(live)) == w
        acq.select(jnp.asarray(_stage_pad(_probs(rng, 7, len(live)), w)))
    assert acquisition._scatter_rows._cache_size() == size0


def test_staging_width_rejects_narrow_probs(rng):
    import jax.numpy as jnp

    acq = Acquirer(SONGS, None, queries=4, mode="mc", seed=7)
    n_live = len(acq.remaining_songs)
    with pytest.raises(ValueError, match="width"):
        acq.select(jnp.asarray(_probs(rng, 3, n_live - 2)))
