"""bench.py plumbing on the CPU mesh — this script produces the recorded
benchmark artifact, so its non-TPU-specific paths are pinned here (the
Pallas/Mosaic impls are TPU-only and covered by ops/parallel tests)."""

import json

import numpy as np
import pytest

import bench  # repo root is on sys.path via tests/conftest.py


def test_cpu_reference_matches_independent_oracle():
    from scipy.stats import entropy as scipy_entropy

    x, w, b = bench.make_inputs(3, 40, 2, 8, 4)
    ent, idx = bench.cpu_reference_iteration(x, w, b, 5)
    # independent float64 recomputation of the whole chain
    frames = x.reshape(-1, 8).astype(np.float64)
    per_member = []
    for m in range(3):
        lg = frames @ w[m] + b[m]
        lg -= lg.max(axis=1, keepdims=True)
        p = np.exp(lg)
        p /= p.sum(axis=1, keepdims=True)
        per_member.append(p.reshape(40, 2, -1).mean(axis=1))
    want = scipy_entropy(np.mean(per_member, axis=0), axis=1)
    np.testing.assert_allclose(ent, want, rtol=1e-6)
    assert set(idx) == set(np.argsort(want)[::-1][:5])


@pytest.fixture(scope="module")
def xla_impl():
    x, w, b = bench.make_inputs(3, 64, 2, 8, 4)
    args, itfn = bench.build_xla_impl(x, w, b, 5)
    return x, w, b, args, itfn


def test_xla_impl_passes_parity_gate(xla_impl):
    x, w, b, args, itfn = xla_impl
    ent_cpu, idx_cpu = bench.cpu_reference_iteration(x, w, b, 5)
    assert bench.check_parity("xla", args, itfn, ent_cpu, idx_cpu, 5)


def test_parity_gate_rejects_wrong_entropy(xla_impl):
    x, w, b, args, itfn = xla_impl
    ent_cpu, idx_cpu = bench.cpu_reference_iteration(x, w, b, 5)
    assert not bench.check_parity("xla", args, itfn, ent_cpu + 0.01,
                                  idx_cpu, 5)


def test_timing_window_runs_on_cpu(xla_impl):
    _, _, _, args, itfn = xla_impl
    ms = bench.time_device_impl("xla", args, itfn, chain=3, trials=2)
    assert ms > 0


def test_main_emits_single_json_line(capsys):
    rc = bench.main(["--impl", "xla", "--pool", "64", "--members", "3",
                     "--frames", "2", "--features", "8", "--chain", "3",
                     "--trials", "1", "--cpu-reps", "1"])
    assert rc == 0
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l]
    assert len(out_lines) == 1  # the driver contract: ONE json line
    rec = json.loads(out_lines[0])
    assert rec["unit"] == "ms" and rec["value"] > 0
    assert rec["metric"] == "al_pool_scoring_latency_3m_64"
    assert rec["vs_baseline"] > 0


def test_hc_mode_matches_scipy():
    from scipy.stats import entropy as scipy_entropy

    hc = bench.make_hc_table(50, 4)
    ent, idx = bench.cpu_reference_iteration(None, None, None, 5, "hc", hc)
    want = scipy_entropy(hc.astype(np.float64), axis=1)
    np.testing.assert_allclose(ent, want, rtol=1e-6)
    args, itfn = bench.build_xla_impl(
        np.zeros((50, 1, 4), np.float32), np.zeros((1, 4, 4), np.float32),
        np.zeros((1, 4), np.float32), 5, "hc", hc)
    assert bench.check_parity("hc", args, itfn, ent, idx, 5, n_valid=50)


def test_mix_mode_stacked_rows_parity():
    """mix = [mc consensus rows; hc rows] with top-k over the stacked
    space; parity remapping must reconcile the padded device layout."""
    x, w, b = bench.make_inputs(3, 60, 2, 8, 4)
    hc = bench.make_hc_table(60, 4)
    ent, idx = bench.cpu_reference_iteration(x, w, b, 6, "mix", hc)
    assert ent.shape == (120,)  # stacked rows
    args, itfn = bench.build_xla_impl(x, w, b, 6, "mix", hc)
    assert bench.check_parity("mix", args, itfn, ent, idx, 6, n_valid=60)


@pytest.mark.parametrize("mode", ["hc", "mix"])
def test_main_mode_flag_emits_tagged_metric(mode, capsys):
    rc = bench.main(["--impl", "xla", "--mode", mode, "--pool", "64",
                     "--members", "3", "--frames", "2", "--features", "8",
                     "--chain", "3", "--trials", "1", "--cpu-reps", "1"])
    assert rc == 0
    out_lines = [l for l in capsys.readouterr().out.splitlines() if l]
    rec = json.loads(out_lines[0])
    assert rec["metric"] == f"al_pool_scoring_latency_{mode}_3m_64"


def test_flat_gemm_variant_passes_parity():
    x, w, b = bench.make_inputs(3, 64, 2, 8, 4)
    ent, idx = bench.cpu_reference_iteration(x, w, b, 5)
    args, itfn = bench.build_xla_impl(x, w, b, 5, "mc", None, flat_gemm=True)
    assert bench.check_parity("xla-flat", args, itfn, ent, idx, 5, n_valid=64)


def test_pallas_suite_skips_cleanly_off_tpu(capsys):
    # --impl pallas on a CPU host must exit 1 with a clear skip, not crash.
    rc = bench.main(["--impl", "pallas", "--pool", "64", "--members", "3",
                     "--frames", "2", "--features", "8", "--cpu-reps", "1"])
    assert rc == 1
    assert "Mosaic" in capsys.readouterr().err


def test_failure_message_keeps_first_and_last_lines():
    """Committed impl_failures entries must carry the ROOT CAUSE, not just
    the transport wrapper (the axon tunnel fronts server-side compile
    errors with an opaque HTTP-500 line)."""
    import bench

    e = RuntimeError("INTERNAL: http 500 wrapper\n\nstack frame\n"
                     "Scoped allocation with size 20.05M exceeded limit")
    msg = bench.failure_message(e)
    assert msg.startswith("INTERNAL: http 500 wrapper")
    assert msg.endswith("Scoped allocation with size 20.05M exceeded limit")
    assert bench.failure_message(RuntimeError("one line")) == "one line"
    assert len(bench.failure_message(RuntimeError("x" * 900))) == 250


def test_committed_r05_evidence_claims_hold():
    """EVIDENCE_r05.json must actually contain the claims README/ROUND5
    state: committee-pooled null with the species decomposition — gnb
    significantly positive, cnn exactly zero, sgd negative."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "EVIDENCE_r05.json")
    with open(path) as fh:
        r = json.load(fh)
    sp = r["species_tests"]
    assert sp["gnb:mc>rand"]["p"] < 0.05
    assert sp["gnb:mc>rand"]["mean_diff"] > 0
    assert sp["cnn:mc>rand"]["mean_diff"] == 0.0
    assert sp["sgd:mc>rand"]["mean_diff"] < 0
    pooled = r["tests"]["mc>rand"]["per_member_final"]
    assert abs(pooled["mean_diff"]) < 0.01  # the committed null
    # the mechanism run measures the mapping-novelty corruption
    mech = r["mechanism_study"]["committed_mapping_novelty_run"]
    assert mech["species_tests"]["cnn:mc>rand"]["mean_diff"] < 0
