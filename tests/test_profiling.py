"""StepTimer / RollingStat units (utils.profiling).

The timers are the metrics surface every bench and fleet/serve report is
built on; these pin the accumulation semantics (phase nesting, re-entry,
flush schema, the ``None``-path no-op) that the AL loop and the serve
telemetry rely on.
"""

import json
import time

from consensus_entropy_tpu.utils.profiling import RollingStat, StepTimer


def test_step_timer_accumulates_reentrant_phases(tmp_path):
    t = StepTimer(str(tmp_path / "t.jsonl"))
    for _ in range(3):
        with t.phase("score"):
            time.sleep(0.002)
    rec = t.flush(epoch=0)
    assert rec["epoch"] == 0
    assert rec["score_s"] >= 3 * 0.002  # three entries summed into one key


def test_step_timer_nested_phases_time_independently(tmp_path):
    """An inner phase's wall-clock is ALSO inside the outer's (phases are
    plain wall windows, not exclusive self-time) — the AL loop nests
    ``checkpoint`` inside iteration boundaries and sums them knowingly."""
    t = StepTimer(None)
    with t.phase("outer"):
        time.sleep(0.002)
        with t.phase("inner"):
            time.sleep(0.004)
    rec = t.flush()
    assert rec["inner_s"] >= 0.004
    assert rec["outer_s"] >= rec["inner_s"]


def test_step_timer_phase_records_on_exception(tmp_path):
    t = StepTimer(None)
    try:
        with t.phase("boom"):
            time.sleep(0.002)
            raise RuntimeError("x")
    except RuntimeError:
        pass
    assert t.flush()["boom_s"] >= 0.002  # finally-path accumulation


def test_step_timer_flush_schema_and_reset(tmp_path):
    path = tmp_path / "t.jsonl"
    t = StepTimer(str(path))
    with t.phase("a"):
        pass
    t.add("bg", 1.5)
    rec1 = t.flush(user="u0", epoch=3, queried=10)
    # labels verbatim, durations suffixed _s and rounded to 6 places
    assert set(rec1) == {"user", "epoch", "queried", "a_s", "bg_s"}
    assert rec1["bg_s"] == 1.5
    assert rec1["a_s"] == round(rec1["a_s"], 6)
    # the accumulator resets per flush; records list keeps history
    rec2 = t.flush(epoch=4)
    assert "a_s" not in rec2 and "bg_s" not in rec2
    assert t.records == [rec1, rec2]
    lines = [json.loads(l) for l in open(path)]
    assert lines == [rec1, rec2]


def test_step_timer_none_path_writes_nothing(tmp_path, monkeypatch):
    """StepTimer(None) is the in-memory no-op sink: no file I/O at all
    (fleet sessions run with user_timings=False on every bench rep)."""
    monkeypatch.chdir(tmp_path)
    t = StepTimer(None)
    with t.phase("a"):
        pass
    rec = t.flush(epoch=0)
    assert rec["a_s"] >= 0
    assert t.records == [rec]
    assert list(tmp_path.iterdir()) == []  # nothing written anywhere


def test_rolling_stat_folds_and_snapshots():
    s = RollingStat()
    assert s.snapshot() is None and s.mean is None  # pre-observation
    for v in (3.0, 1.0, 2.0):
        s.add(v)
    snap = s.snapshot()
    assert snap == {"n": 3, "mean": 2.0, "min": 1.0, "max": 3.0,
                    "last": 2.0}
