"""Waveform stores: crop geometry, determinism, host/device agreement."""

import jax
import numpy as np
import pytest

from consensus_entropy_tpu.data.audio import DeviceWaveformStore, HostWaveformStore


def _waves(rng, n=6, base=2000, var=500):
    return {f"s{i}": rng.standard_normal(base + int(rng.integers(0, var)))
            .astype(np.float32) for i in range(n)}


def test_crops_shape_and_content(rng):
    waves = _waves(rng)
    store = DeviceWaveformStore(waves, input_length=1024)
    rows = store.row_of(["s0", "s3", "s5"])
    crops = np.asarray(store.sample_crops(jax.random.key(0), rows))
    assert crops.shape == (3, 1024)
    # each crop is a contiguous slice of its source waveform
    for c, sid in zip(crops, ["s0", "s3", "s5"]):
        w = waves[sid]
        starts = np.flatnonzero(np.isclose(w, c[0]))
        assert any(np.allclose(w[s: s + 1024], c) for s in starts
                   if s + 1024 <= len(w))


def test_crops_deterministic_and_keyed(rng):
    store = DeviceWaveformStore(_waves(rng), input_length=512)
    rows = store.row_of(store.ids)
    a = np.asarray(store.sample_crops(jax.random.key(7), rows))
    b = np.asarray(store.sample_crops(jax.random.key(7), rows))
    c = np.asarray(store.sample_crops(jax.random.key(8), rows))
    np.testing.assert_array_equal(a, b)
    assert not np.allclose(a, c)


def test_exact_length_song(rng):
    w = {"x": rng.standard_normal(1024).astype(np.float32)}
    store = DeviceWaveformStore(w, input_length=1024)
    crops = np.asarray(store.sample_crops(jax.random.key(0), store.row_of(["x"])))
    np.testing.assert_array_equal(crops[0], w["x"])


def test_too_short_rejected(rng):
    with pytest.raises(ValueError, match="shorter"):
        DeviceWaveformStore({"x": np.zeros(10, np.float32)}, input_length=100)


def test_host_store_matches_api(rng, tmp_path):
    waves = _waves(rng, n=4)
    for sid, w in waves.items():
        np.save(tmp_path / f"{sid}.npy", w)
    store = HostWaveformStore(str(tmp_path), list(waves), input_length=700)
    rows = store.row_of(["s1", "s2"])
    crops = np.asarray(store.sample_crops(jax.random.key(3), rows))
    assert crops.shape == (2, 700)
    for c, sid in zip(crops, ["s1", "s2"]):
        w = waves[sid]
        starts = np.flatnonzero(np.isclose(w, c[0]))
        assert any(np.allclose(w[s: s + 700], c) for s in starts
                   if s + 700 <= len(w))


def test_sample_crops_prefix_stable_in_batch_width(rng, tmp_path):
    """Padding the row batch must not change the real rows' crops: the
    committee pads row batches to a compile bucket before sampling
    (committee.predict_songs_cnn), which is only sound because threefry
    draws are prefix-stable in the batch width."""
    import jax

    from consensus_entropy_tpu.data.audio import (
        DeviceWaveformStore,
        HostWaveformStore,
    )

    waves = {f"s{i}": (rng.standard_normal(3000) * 0.1).astype(np.float32)
             for i in range(6)}
    for sid, w in waves.items():
        np.save(tmp_path / f"{sid}.npy", w)
    key = jax.random.key(42)
    for store in (DeviceWaveformStore(waves, 1024),
                  HostWaveformStore(str(tmp_path), list(waves), 1024)):
        rows = store.row_of([f"s{i}" for i in range(4)])
        rows_padded = np.concatenate([rows, np.repeat(rows[-1:], 12)])
        a = np.asarray(store.sample_crops(key, rows))
        b = np.asarray(store.sample_crops(key, rows_padded))[:4]
        np.testing.assert_array_equal(a, b)
