"""Sharded scoring on an 8-device virtual CPU mesh: parity with the
single-device graph, and the explicit shard_map two-stage top-k."""

import jax
import numpy as np
import pytest
from scipy.stats import entropy as scipy_entropy

from consensus_entropy_tpu.ops import scoring
from consensus_entropy_tpu.parallel import (
    make_pool_mesh,
    make_sharded_scoring_fns,
    make_shardmap_mc_scorer,
    make_training_mesh,
)
from consensus_entropy_tpu.parallel.sharding import pad_pool


def _probs(rng, m, n, c=4):
    p = rng.uniform(0.01, 1.0, size=(m, n, c)).astype(np.float32)
    return p / p.sum(axis=-1, keepdims=True)


def test_eight_devices_present():
    assert len(jax.devices()) == 8


def test_pool_mesh_shape():
    mesh = make_pool_mesh()
    assert mesh.shape == {"pool": 8}


def test_training_mesh_factorization():
    mesh = make_training_mesh()
    assert mesh.shape["dp"] * mesh.shape["member"] == 8
    mesh2 = make_training_mesh(dp=8, member=1)
    assert mesh2.shape == {"dp": 8, "member": 1}
    with pytest.raises(ValueError):
        make_training_mesh(dp=3, member=3)


def test_sharded_mc_matches_single_device(rng):
    mesh = make_pool_mesh()
    fns = make_sharded_scoring_fns(mesh, k=10)
    p = _probs(rng, 16, 512)
    mask = np.ones(512, dtype=bool)
    mask[400:] = False
    res = fns["mc"](p, mask)
    ref = scoring.score_mc(p, mask, k=10, tie_break="fast")
    np.testing.assert_allclose(np.asarray(res.entropy)[:400],
                               np.asarray(ref.entropy)[:400], rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.asarray(ref.indices))


def test_sharded_hc_and_mix(rng):
    mesh = make_pool_mesh()
    fns = make_sharded_scoring_fns(mesh, k=6)
    counts = rng.integers(1, 30, size=(256, 4))
    hc = (counts / counts.sum(axis=1, keepdims=True)).astype(np.float32)
    hc_mask = np.ones(256, dtype=bool)
    res = fns["hc"](hc, hc_mask)
    ent_ref = scipy_entropy(hc, axis=1)
    np.testing.assert_array_equal(
        np.sort(np.asarray(res.indices)),
        np.sort(np.argsort(ent_ref)[::-1][:6]))

    p = _probs(rng, 4, 256)
    pool_mask = np.ones(256, dtype=bool)
    res_mix = fns["mix"](p, pool_mask, hc, hc_mask)
    ref_mix = scoring.score_mix(p, pool_mask, hc, hc_mask, k=6,
                                tie_break="fast")
    np.testing.assert_array_equal(np.asarray(res_mix.indices),
                                  np.asarray(ref_mix.indices))


def test_shardmap_two_stage_topk(rng):
    mesh = make_pool_mesh()
    scorer = make_shardmap_mc_scorer(mesh, k=12)
    p = _probs(rng, 8, 1024)
    mask = np.ones(1024, dtype=bool)
    mask[1000:] = False
    res = scorer(p, mask)
    ref = scoring.score_mc(p, mask, k=12, tie_break="fast")
    np.testing.assert_allclose(np.asarray(res.values), np.asarray(ref.values),
                               rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.asarray(ref.indices))


def test_pad_pool_helper(rng):
    x = rng.uniform(size=(100, 4))
    (xp,), mask = pad_pool([x], 100, 256)
    assert xp.shape == (256, 4)
    assert mask.sum() == 100
    np.testing.assert_array_equal(xp[:100], x)
    with pytest.raises(ValueError):
        pad_pool([x], 100, 64)
