"""Iteration-level resume: a run killed mid-user must continue at the next
AL iteration with identical queries, masks, and final state as an
uninterrupted run (SURVEY.md §5 failure detection — the reference can only
skip-or-redo whole users)."""

import json
import os

import numpy as np
import pytest

from consensus_entropy_tpu.al import state as al_state
from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.al.loop import ALLoop, UserData
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.models.committee import Committee, FramePool
from consensus_entropy_tpu.models.sklearn_members import GNBMember, SGDMember
from consensus_entropy_tpu.utils.profiling import StepTimer


def _make_user(rng, n_songs=30, frames_per_song=3, n_feat=8):
    centers = rng.standard_normal((4, n_feat)) * 3.0
    labels = {}
    X, frame_song = [], []
    for s in range(n_songs):
        c = int(rng.integers(0, 4))
        sid = f"song{s:03d}"
        labels[sid] = c
        X.append(centers[c] + rng.standard_normal((frames_per_song, n_feat)))
        frame_song += [sid] * frames_per_song
    pool = FramePool(np.concatenate(X).astype(np.float32), frame_song)
    hc = rng.uniform(0.1, 1.0, (pool.n_songs, 4)).astype(np.float32)
    hc /= hc.sum(axis=1, keepdims=True)
    return UserData("u0", pool, labels, hc_rows=hc)


def _committee(rng, data):
    X = data.pool.X
    y = np.array([data.labels[s] for s in np.repeat(
        data.pool.song_ids, data.pool.counts)], np.int32)
    gnb = GNBMember("gnb.it_0").fit(X, y)
    sgd = SGDMember("sgd.it_0", seed=0).fit(X, y)
    return Committee([gnb, sgd], [])


#: tier-1 keeps the probs-path (mc) and key-path (rand) rows; hc/mix ride
#: the slow matrix (ISSUE 6 budget rebalance — the acquire/qbdc tier-1
#: additions displace the redundant mode rows here and in
#: test_al_loop/test_sharded_loop)
@pytest.mark.parametrize("mode", [
    "mc",
    pytest.param("hc", marks=pytest.mark.slow),
    pytest.param("mix", marks=pytest.mark.slow),
    "rand",
])
def test_interrupted_run_matches_straight_run(tmp_path, rng, mode):
    data = _make_user(rng)

    # Straight run: 4 iterations in one go.
    d_full = tmp_path / "full"
    d_full.mkdir()
    rng_a = np.random.default_rng(0)
    loop4 = ALLoop(ALConfig(queries=3, epochs=4, mode=mode, seed=11))
    res_full = loop4.run_user(_committee(rng_a, data), data, str(d_full),
                              seed=11)

    # Interrupted run: 2 iterations, then resume for the remaining 2 with a
    # committee reloaded from the per-iteration persistence.
    d_part = tmp_path / "part"
    d_part.mkdir()
    rng_b = np.random.default_rng(0)
    loop2 = ALLoop(ALConfig(queries=3, epochs=2, mode=mode, seed=11))
    loop2.run_user(_committee(rng_b, data), data, str(d_part), seed=11)
    st = al_state.ALState.load(str(d_part))
    assert st is not None and st.next_epoch == 2

    committee2 = workspace.load_committee(str(d_part))
    res_resumed = loop4.run_user(committee2, data, str(d_part), seed=11)

    assert res_resumed["trajectory"] == pytest.approx(res_full["trajectory"])
    full_q = al_state.ALState.load(str(d_full)).queried
    part_q = al_state.ALState.load(str(d_part)).queried
    assert full_q == part_q  # identical query sequence across the cut


def test_state_mismatch_fails_loud(tmp_path, rng):
    # run_user must not silently "start clean" on top of a committee that
    # was trained under a different experiment; the workspace layer is the
    # one that wipes mismatched directories back to pristine models.
    data = _make_user(rng)
    d = tmp_path / "u"
    d.mkdir()
    loop = ALLoop(ALConfig(queries=3, epochs=1, mode="mc", seed=11))
    loop.run_user(_committee(np.random.default_rng(0), data), data, str(d),
                  seed=11)
    for bad in (ALConfig(queries=3, epochs=1, mode="hc", seed=11),
                ALConfig(queries=3, epochs=1, mode="mc", seed=12),
                ALConfig(queries=5, epochs=1, mode="mc", seed=11)):
        with pytest.raises(ValueError, match="different experiment"):
            ALLoop(bad).run_user(
                _committee(np.random.default_rng(0), data), data, str(d),
                seed=bad.seed)


def test_workspace_wipes_mismatched_experiment(tmp_path, rng):
    pre = tmp_path / "pretrained"
    pre.mkdir()
    (pre / "classifier_gnb.it_0.pkl").write_bytes(b"x")
    users = str(tmp_path / "users")
    exp = {"seed": 11, "queries": 3, "train_size": 0.85}
    path, _ = workspace.create_user(users, str(pre), "u1", "mc", exp)
    al_state.ALState(1, [0.5], [], [], [[]], [0, 0], "uint32", "mc", 11,
                     queries=3, train_size=0.85).save(path)
    (tmp_path / "users" / "u1" / "mc" / "trained").write_text("x")
    # Same experiment: kept.
    path2, skip2 = workspace.create_user(users, str(pre), "u1", "mc", exp)
    assert not skip2 and os.path.exists(os.path.join(path2, "trained"))
    # Different queries: wiped back to pristine.
    path3, skip3 = workspace.create_user(users, str(pre), "u1", "mc",
                                         {**exp, "queries": 7})
    assert not skip3 and not os.path.exists(os.path.join(path3, "trained"))


def test_torn_checkpoint_recovery(tmp_path):
    # Crash between the staged committee write and the state write: the
    # stage must be discarded.  Crash after the state write: promoted.
    d = tmp_path / "u"
    d.mkdir()
    (d / "classifier_gnb.m.pkl").write_text("old")
    al_state.ALState(2, [0.5], [], [], [["s"]], [0, 0], "uint32",
                     "mc", 11).save(str(d))
    stale = al_state.staging_dir(str(d), 3)   # pre-commit (state says 2)
    os.makedirs(stale)
    with open(os.path.join(stale, "classifier_gnb.m.pkl"), "w") as f:
        f.write("newer-uncommitted")
    al_state.recover_workspace(str(d))
    assert not os.path.exists(stale)
    assert open(d / "classifier_gnb.m.pkl").read() == "old"

    committed = al_state.staging_dir(str(d), 2)  # matches state: promote
    os.makedirs(committed)
    with open(os.path.join(committed, "classifier_gnb.m.pkl"), "w") as f:
        f.write("committed")
    al_state.recover_workspace(str(d))
    assert not os.path.exists(committed)
    assert open(d / "classifier_gnb.m.pkl").read() == "committed"
    al_state.recover_workspace(str(d))  # idempotent


def test_workspace_keeps_resumable_dirs(tmp_path):
    pre = tmp_path / "pretrained"
    pre.mkdir()
    (pre / "classifier_gnb.it_0.pkl").write_bytes(b"x")
    users = str(tmp_path / "users")

    path, skip = workspace.create_user(users, str(pre), "u1", "mc")
    assert not skip
    # Crash before any state: directory is wiped and recreated.
    (tmp_path / "users" / "u1" / "mc" / "junk").write_text("partial")
    path2, skip2 = workspace.create_user(users, str(pre), "u1", "mc")
    assert not skip2 and not os.path.exists(os.path.join(path2, "junk"))
    # Crash with state: directory survives for the loop to resume.
    al_state.ALState(1, [0.5], [], [], [[]], [0, 0], "uint32",
                     "mc", 11).save(path2)
    (tmp_path / "users" / "u1" / "mc" / "keepme").write_text("x")
    path3, skip3 = workspace.create_user(users, str(pre), "u1", "mc")
    assert not skip3 and os.path.exists(os.path.join(path3, "keepme"))
    # DONE still short-circuits.
    workspace.mark_done(path3)
    _, skip4 = workspace.create_user(users, str(pre), "u1", "mc")
    assert skip4


def test_step_timer_records_phases(tmp_path, rng):
    data = _make_user(rng, n_songs=16)
    d = tmp_path / "u"
    d.mkdir()
    timer = StepTimer(str(tmp_path / "timings.jsonl"))
    loop = ALLoop(ALConfig(queries=3, epochs=2, mode="mc", seed=11))
    loop.run_user(_committee(np.random.default_rng(0), data), data, str(d),
                  seed=11, timer=timer)
    recs = [json.loads(l) for l in open(tmp_path / "timings.jsonl")]
    assert len(recs) == 3  # epoch -1, 0, 1
    assert recs[0]["epoch"] == -1 and "evaluate_s" in recs[0]
    for r in recs[1:]:
        for phase in ("score_s", "select_s", "update_host_s", "evaluate_s",
                      "ckpt_join_s", "checkpoint_s"):
            assert phase in r, r
    # the background checkpoint job self-times; its durations surface on
    # the NEXT record (one-record offset), tagged ckpt_bg_* so artifact
    # consumers can exclude them from wall-clock totals
    for r in recs[1:]:
        assert "ckpt_bg_fetch_s" in r and "ckpt_bg_commit_s" in r, r
        assert "ckpt_members_fetched" in r  # 0: host-only committee
        assert r["ckpt_members_fetched"] == 0


def test_async_checkpointer_orders_and_raises():
    """Jobs never overlap (submit joins the previous), and a failed write
    surfaces on the loop thread at the next wait/submit instead of being
    swallowed on the writer thread."""
    import time

    from consensus_entropy_tpu.al.loop import AsyncCheckpointer

    ck = AsyncCheckpointer()
    order = []

    def slow():
        time.sleep(0.2)
        order.append("first")

    ck.submit(slow)
    ck.submit(lambda: order.append("second"))  # must join `slow` first
    ck.wait()
    assert order == ["first", "second"]

    def boom():
        raise RuntimeError("disk full")

    ck.submit(boom)
    with pytest.raises(RuntimeError, match="disk full"):
        ck.wait()
    ck.wait()  # exception is surfaced once, then cleared
