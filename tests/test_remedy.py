"""Self-healing fabric: the remediation plane (``serve.remedy`` +
``FabricCoordinator._pump_remedy`` / ``_check_fence_deadlines``) and the
alert delivery surface it consumes (``obs.alerts`` sinks + the
edge-trigger REARM).

Tier-1 keeps the pure decision kernels (the flap-free shed-count sweep,
hysteresis/cooldown/deadline tables, the shed-pick ordering contract),
the config/CLI validation edges, the alert-watcher rearm regression and
sink registry, and the DETERMINISTIC fake-worker drills: a sustained
placement-skew alert triggers exactly one journaled drain-for-rebalance
(queued users over the drop-ack path, in-flight over the checkpoint
fence, the host NEVER retired), an unacked fence past the operator
deadline demotes to evict+resume (whichever ack lands first commits the
move, the loser is cursor-only), and a coordinator SIGKILL at the
``fabric.remedy`` fault point — before the rebalance decision or inside
the deadline expiry window — replays from the journal to exactly one
owner per user.  The real-subprocess acceptance drill is
``scripts/remedy_check.sh`` (fault-matrix tier)."""

import json
import os
import sys

import pytest

from consensus_entropy_tpu.obs.alerts import (
    AlertWatcher,
    CommandSink,
    ConsoleSink,
    JsonlSink,
    make_sink,
    skew_alerts,
)
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule, InjectedKill
from consensus_entropy_tpu.serve import (
    AdmissionJournal,
    FabricConfig,
    FabricCoordinator,
    cooldown_ok,
    fence_expired,
    pick_shed,
    remedy_due,
    shed_count,
    validate_journal_file,
)
from tests.test_elastic import _fake_fleet, _FakeWorker

pytestmark = [pytest.mark.serve, pytest.mark.faults]


# -- pure decision kernels -------------------------------------------------


def test_shed_count_sweep_is_flap_free():
    """The arithmetic contract: shedding lands the host at EXACTLY
    ``floor + max_skew`` — the highest load that does not alert — so one
    remediation clears its own trigger and can never re-fire from the
    same imbalance."""
    for load in range(0, 16):
        for floor in range(0, load + 1):
            for skew in (1, 2, 4):
                n = shed_count(load, floor, max_skew=skew)
                assert n >= 0
                if load - floor <= skew:
                    assert n == 0  # at or below the line: shed nothing
                else:
                    assert load - n == floor + skew
                    # cross-check against the alert kernel itself: the
                    # pre-shed load alerts, the post-shed load does not
                    # (floor can only RISE as shed users land elsewhere)
                    before = skew_alerts({"hot": load, "cold": floor},
                                         max_skew=skew)
                    after = skew_alerts({"hot": load - n, "cold": floor},
                                        max_skew=skew)
                    assert [a["host"] for a in before] == ["hot"]
                    assert after == []


def test_hysteresis_cooldown_and_deadline_tables():
    # hold: acts only on a CONTINUOUSLY held condition
    assert not remedy_due(None, 10.0, hold_s=1.0)
    assert not remedy_due(9.5, 10.0, hold_s=1.0)
    assert remedy_due(9.0, 10.0, hold_s=1.0)
    assert remedy_due(10.0, 10.0, hold_s=0.0)  # hold 0: immediate
    # cooldown: never-remediated always passes
    assert cooldown_ok(None, 0.0, cooldown_s=5.0)
    assert not cooldown_ok(8.0, 10.0, cooldown_s=5.0)
    assert cooldown_ok(5.0, 10.0, cooldown_s=5.0)
    # fence deadline: <= 0 disables (PR 14 wait-forever semantics)
    assert not fence_expired(None, 10.0, deadline_s=1.0)
    assert not fence_expired(9.5, 10.0, deadline_s=1.0)
    assert fence_expired(9.0, 10.0, deadline_s=1.0)
    assert not fence_expired(0.0, 1e9, deadline_s=0.0)
    assert not fence_expired(0.0, 1e9, deadline_s=-1.0)


def test_pick_shed_order_and_budget():
    """Queued users shed first (latest-enqueued first — the
    plan_rebalance contract), in-flight users fill the remainder from
    the END of the first-admit-ordered list (most sunk work sheds
    last)."""
    q, f = ["a", "b", "c"], ["x", "y", "z"]
    assert pick_shed(q, f, 0) == ([], [])
    assert pick_shed(q, f, -3) == ([], [])
    assert pick_shed(q, f, 2) == (["c", "b"], [])
    assert pick_shed(q, f, 4) == (["c", "b", "a"], ["z"])
    assert pick_shed(q, f, 99) == (["c", "b", "a"], ["z", "y", "x"])
    assert pick_shed([], f, 2) == ([], ["z", "y"])
    # the drain-by-waiting arm: queued users only
    assert pick_shed(q, f, 5, migrate_inflight=False) == (["c", "b", "a"],
                                                          [])
    # selection never mutates its inputs
    assert q == ["a", "b", "c"] and f == ["x", "y", "z"]


def test_skew_alerts_fire_per_offender():
    assert skew_alerts({}, max_skew=1) == []
    assert skew_alerts({"h0": 99}, max_skew=1) == []  # one host: no skew
    assert skew_alerts({"h0": 5, "h1": 2}, max_skew=3) == []  # at bound
    out = skew_alerts({"h0": 9, "h1": 2, "h2": 8}, max_skew=4)
    assert [(a["host"], a["load"], a["floor"]) for a in out] == \
        [("h0", 9, 2), ("h2", 8, 2)]
    assert all(a["kind"] == "placement_skew" and a["key"] == a["host"]
               for a in out)


# -- config + CLI validation edges -----------------------------------------


def test_remedy_config_validation():
    c = FabricConfig(hosts=2, min_hosts=2, max_hosts=2, remedy=True,
                     fence_deadline_s=2.0, remedy_hold_s=0.0,
                     remedy_cooldown_s=0.0, remedy_skew=1)
    assert c.remedy and c.fence_deadline_s == 2.0
    with pytest.raises(ValueError, match="elastic"):
        FabricConfig(hosts=2, remedy=True)
    with pytest.raises(ValueError, match="elastic"):
        FabricConfig(hosts=2, fence_deadline_s=1.0)
    with pytest.raises(ValueError, match="fence_deadline_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2,
                     fence_deadline_s=-0.1)
    with pytest.raises(ValueError, match="remedy_hold_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, remedy_hold_s=-1)
    with pytest.raises(ValueError, match="remedy_cooldown_s"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2,
                     remedy_cooldown_s=-1)
    with pytest.raises(ValueError, match="remedy_skew"):
        FabricConfig(hosts=2, min_hosts=2, max_hosts=2, remedy_skew=0)


def test_remedy_cli_flag_validation(tmp_path):
    """Clean CLI errors for remediation knobs without their gates, and
    sink specs that fail at the edge — before any data or backend
    work."""
    from consensus_entropy_tpu.cli.amg_test import main

    base = ["-q", "1", "-e", "1", "-n", "1", "-m", "mc",
            "--models-root", str(tmp_path)]
    # remediation needs the elastic gate (--hosts)
    assert main(base + ["--serve", "1", "--remedy"]) == 1
    assert main(base + ["--serve", "1", "--fence-deadline-s", "2"]) == 1
    # sink grammar validates at construction
    assert main(base + ["--serve", "1", "--hosts", "2",
                        "--alert-sink", "nope"]) == 1
    assert main(base + ["--serve", "1", "--hosts", "2",
                        "--alert-sink", "jsonl"]) == 1
    # sinks ride the introspection plane
    assert main(base + ["--serve", "1", "--hosts", "2",
                        "--alert-sink", "console",
                        "--no-introspection"]) == 1


# -- alert watcher: edge-trigger rearm + sink registry ---------------------


class _Rec:
    def __init__(self):
        self.events = []

    def event(self, kind, /, **kw):
        self.events.append((kind, kw))


def test_alert_watcher_rearm_refires_within_interval():
    """The edge-trigger REARM regression: snapshot-based edge triggering
    coalesces a condition that clears and re-rises between two updates —
    whoever consumes an alert (the remediation plane) must rearm it so
    the next evaluation re-fires."""
    rep = _Rec()
    w = AlertWatcher(rep)
    alert = skew_alerts({"h0": 9, "h1": 0}, max_skew=4)
    assert w.update(alert) == alert and w.fired == 1
    # still-active re-evaluation: silent (no event flood)
    assert w.update(alert) == [] and w.fired == 1
    # the remediation plane acted on it: consume the edge
    w.rearm("placement_skew", "h0")
    assert w.update(alert) == alert and w.fired == 2
    kinds = [kw["kind"] for k, kw in rep.events if k == "alert"]
    assert kinds == ["placement_skew", "placement_skew"]
    # kind-wide rearm (no key) drops every key of that kind
    w.rearm("placement_skew")
    assert w.update(alert) == alert and w.fired == 3
    # rearming an inactive key is a no-op, and a cleared condition
    # leaves the active set on its own
    w.rearm("placement_skew", "h9")
    assert w.update([]) == [] and w.active == []


def test_make_sink_grammar_and_delivery(tmp_path):
    lines = []
    console = make_sink("console", log=lines.append)
    assert isinstance(console, ConsoleSink)
    console.emit({"kind": "placement_skew", "key": "h0", "host": "h0",
                  "load": 9})
    assert lines == ["ALERT [placement_skew] host=h0 load=9"]

    jp = str(tmp_path / "alerts.jsonl")
    sink = make_sink(f"jsonl:{jp}")
    assert isinstance(sink, JsonlSink) and not os.path.exists(jp)  # lazy
    sink.emit({"kind": "lease_expiry", "key": "h1"})
    sink.emit({"kind": "lease_expiry", "key": "h2"})
    sink.close()
    rows = [json.loads(ln) for ln in open(jp, "rb").read().splitlines()]
    assert [r["key"] for r in rows] == ["h1", "h2"]

    out = str(tmp_path / "cmd_out.txt")
    hook = tmp_path / "hook.py"  # webhook-shaped: record arrives as argv
    hook.write_text("import sys\n"
                    "open(sys.argv[1], 'a').write(sys.argv[-1])\n")
    cmd = make_sink(f"cmd:{sys.executable} {hook} {out}")
    assert isinstance(cmd, CommandSink)
    cmd.emit({"kind": "breaker_open", "key": "64", "width": 64})
    assert json.loads(open(out, "rb").read())["width"] == 64

    for bad in ("jsonl", "cmd", "pager", "jsonl:", ""):
        with pytest.raises(ValueError):
            make_sink(bad)


def test_alert_sinks_never_wedge_the_watcher(tmp_path):
    """Delivery is telemetry, never control flow: a raising sink (or a
    failing command) is counted and skipped; the round still fires every
    other sink."""
    jp = str(tmp_path / "alerts.jsonl")

    class _Boom:
        def emit(self, alert):
            raise RuntimeError("pager down")

    w = AlertWatcher(sinks=(_Boom(), JsonlSink(jp),
                            CommandSink([sys.executable, "-c",
                                         "import sys; sys.exit(1)"])))
    rose = w.update(skew_alerts({"h0": 9, "h1": 0}, max_skew=4))
    assert len(rose) == 1 and w.fired == 1
    assert w.sink_errors == 2  # _Boom + the exit-1 command
    assert len(open(jp, "rb").read().splitlines()) == 1  # jsonl delivered


# -- deterministic fake-worker remediation drills --------------------------


class _RemedyWorker(_FakeWorker):
    """``_FakeWorker`` plus the deadline-fallback EVICT verb: a ``drop``
    carrying ``evict`` on an in-flight user defers to the next step
    boundary (the script calls :meth:`force_release` to model it) —
    the real worker's ``server.evict()`` semantics."""

    def __init__(self, fabric_dir, host_id):
        super().__init__(fabric_dir, host_id)
        #: evict requests deferred to the next step boundary
        self.evict_pending: list = []

    def pump(self):
        if self.dead:
            return
        self.beat()
        for rec, _off in self.feed.poll():
            if rec.get("close"):
                self._rc = 0
                continue
            if isinstance(rec.get("edges"), list):
                self.edges.append(tuple(rec["edges"]))
                continue
            if rec.get("drain"):
                self.draining = True
                continue
            if rec.get("fence") is not None:
                uid = str(rec["fence"])
                if uid in self.queued:
                    self.queued.remove(uid)
                    self._event({"event": "fence", "user": uid,
                                 "ok": True})
                elif uid in self.admitted:
                    self.fence_pending.append(uid)
                else:
                    self._event({"event": "fence", "user": uid,
                                 "ok": False})
                continue
            if rec.get("drop") is not None:
                uid = str(rec["drop"])
                if rec.get("evict") and uid in self.admitted:
                    self.evict_pending.append(uid)  # next step boundary
                    continue
                ok = uid in self.queued
                if ok:
                    self.queued.remove(uid)
                self._event({"event": "drop", "user": uid, "ok": ok})
                continue
            if rec.get("user") is not None:
                self.queued.append(str(rec["user"]))
        if self.draining and not self.queued and not self.admitted \
                and not self.fence_pending and self._rc is None:
            self._rc = 0

    def force_release(self, uid, gen=2):
        """The step boundary the evict fallback waits on: the session
        leaves the engine mid-run, acked as a deferred ``drop`` with the
        last committed checkpoint generation."""
        self.admitted.remove(uid)
        self.evict_pending.remove(uid)
        self._event({"event": "drop", "user": uid, "ok": True,
                     "gen": gen})


def _remedy_fleet(tmp_path, config, users, pools, script, workers=None):
    """``_fake_fleet`` with evict-capable workers (the caller may pass
    the ``workers`` dict to keep a killed incarnation's hosts for
    exactly-once accounting across reruns)."""
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir, exist_ok=True)
    journal = AdmissionJournal(
        os.path.join(fabric_dir, "serve_journal.jsonl"))
    workers = {} if workers is None else workers

    def spawn(host_id):
        workers[host_id] = _RemedyWorker(fabric_dir, host_id)
        return workers[host_id]

    state = {"round": 0}

    def on_poll(coord):
        state["round"] += 1
        if state["round"] > 2000:
            raise AssertionError("remedy drill wedged: "
                                 f"unresolved={sorted(coord._unresolved)}")
        for w in list(workers.values()):
            w.pump()
        script(state["round"], coord, workers)

    coord = FabricCoordinator(journal, fabric_dir, config,
                              on_poll=on_poll)
    try:
        summary = coord.run(users, spawn, pools=pools)
    finally:
        journal.close()
    return summary, coord, workers, fabric_dir


def _journal_records(fabric_dir):
    from consensus_entropy_tpu.resilience import io as dio

    path = os.path.join(fabric_dir, "serve_journal.jsonl")
    out = []
    with open(path, "rb") as f:
        for ln in f.read().splitlines():
            if not ln:
                continue
            status, rec = dio.parse_frame(ln + b"\n")
            assert status != "corrupt", ln
            if not dio.is_header(rec):
                out.append(rec)
    return out


def _setup_skew(state, users, workers):
    """Build the canonical imbalance: once routing has delivered every
    user (balanced 4/4 by the placement policy), h0 admits all but ONE
    of its users (3 in-flight + 1 queued) while h1 starts working —
    h1 draining to zero opens a skew of 4 over the floor."""
    if state["setup"]:
        return True
    h0, h1 = workers.get("h0"), workers.get("h1")
    if not (h0 and h1):
        return False
    if len(h0.queued) + len(h1.queued) == len(users):
        state["setup"] = True
        assert len(h0.queued) == 4  # the placement policy balances 8/2
        for uid in list(h0.queued)[:-1]:
            h0.admit(uid)
        for uid in list(h1.queued):
            h1.admit(uid)
    return state["setup"]


def _work(w):
    """One normal worker round: finish in-flight, admit queued."""
    for uid in list(w.admitted):
        w.finish(uid)
    for uid in list(w.queued):
        w.admit(uid)


def test_remedy_drill_rebalances_overloaded_host(tmp_path):
    """The drain-for-rebalance drill: a sustained placement-skew alert
    on h0 (4 unresolved vs 0) triggers exactly ONE journaled ``remedy``
    decision — its queued user moves over the drop-ack path, one
    in-flight user over the checkpoint fence — and h0 is NEVER drained
    or retired: it keeps its remaining sessions and finishes them."""
    users = [f"u{i}" for i in range(8)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=2, min_hosts=2, max_hosts=2, poll_s=0.01,
                       drain_timeout_s=0.2, placement="load",
                       remedy=True,
                       remedy_hold_s=0.0, remedy_cooldown_s=0.0,
                       remedy_skew=2)
    state = {"setup": False}
    rep = _Rec()

    def script(rnd, coord, workers):
        if not _setup_skew(state, users, workers):
            return
        h0, h1 = workers["h0"], workers["h1"]
        # fences release at their next checkpoint boundary
        for w in workers.values():
            for uid in list(w.fence_pending):
                w.release(uid, gen=1)
        _work(h1)  # h1 drains to zero -> skew 4 > remedy_skew 2
        # the victim holds its load until the remediation wave commits,
        # then finishes what it kept
        if coord.remedies and not coord._migrating and not coord._fencing:
            _work(h0)

    summary, coord, workers, fabric_dir = _fake_fleet(
        tmp_path, cfg, users, pools, script, alerts=AlertWatcher(rep))
    assert sorted(summary["finished"]) == users
    # shed_count(4, 0, max_skew=2) == 2: one queued drop + one fence
    assert summary["remedies"] == 1
    assert summary["migrations"] == 2 and summary["fences"] == 1
    assert summary["fence_timeouts"] == 0
    # drain-for-rebalance retires NOTHING
    assert summary["drains"] == 0 and summary["revocations"] == 0
    # exactly one owner per user across both hosts
    ran = [u for w in workers.values() for u in w.finished]
    assert sorted(ran) == users
    assert workers["h0"].finished  # the victim kept working
    # the decision is journaled (replayable) and the skew alert fired
    recs = _journal_records(fabric_dir)
    remedies = [r for r in recs if r["event"] == "remedy"]
    assert [(r["host"], r["action"]) for r in remedies] == \
        [("h0", "rebalance")]
    alerts_seen = [kw for k, kw in rep.events if k == "alert"]
    assert any(a["kind"] == "placement_skew" and a["host"] == "h0"
               for a in alerts_seen)
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    assert validate_journal_file(jp) == []
    st = AdmissionJournal(jp).state
    assert st.fleet_hosts() == ["h0", "h1"]  # both hosts still in shape
    assert st.draining_hosts() == []
    # replay determinism: independent replays agree on every assignment
    assert AdmissionJournal(jp).state.assigned == st.assigned


@pytest.mark.parametrize("winner", ["evict_ack", "late_fence_ack"])
def test_fence_deadline_demotes_to_evict_resume(tmp_path, winner):
    """Deadline-fenced degradation: h0 withholds its checkpoint fence
    past ``fence_deadline_s`` — the coordinator journals the timeout
    (``remedy``, action ``fence_timeout``) and demotes to evict+resume.
    Whichever ack lands first commits the move EXACTLY ONCE; the loser
    is cursor-only (``evict_ack``: the forced release moves the user,
    the late checkpoint ack is stale; ``late_fence_ack``: the boundary
    beats the evict, the fence-fallback path commits)."""
    users = [f"u{i}" for i in range(8)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=2, min_hosts=2, max_hosts=2, poll_s=0.01,
                       drain_timeout_s=0.2, placement="load",
                       remedy=True,
                       remedy_hold_s=0.0, remedy_cooldown_s=600.0,
                       remedy_skew=2, fence_deadline_s=0.05)
    state = {"setup": False, "late_acked": False}

    def script(rnd, coord, workers):
        if not _setup_skew(state, users, workers):
            return
        h0, h1 = workers["h0"], workers["h1"]
        _work(h1)
        # h0 WITHHOLDS its fence: the boundary never comes in time
        if winner == "evict_ack":
            for uid in list(h0.evict_pending):
                h0.force_release(uid, gen=2)
            if coord.fences_timed_out and not coord._migrating \
                    and not state["late_acked"] and h0.fence_pending:
                # the checkpoint boundary finally commits AFTER the
                # eviction already moved the user: stale, cursor-only
                state["late_acked"] = True
                for uid in list(h0.fence_pending):
                    h0.fence_pending.remove(uid)
                    h0._event({"event": "fence", "user": uid,
                               "ok": True, "gen": 3})
        elif coord.fences_timed_out and h0.evict_pending:
            # the boundary wins the race with the pending evict: the
            # fence-fallback path must still commit the move
            for uid in list(h0.evict_pending):
                h0.evict_pending.remove(uid)
                h0.release(uid, gen=1)
        if coord.fences_timed_out and not coord._migrating \
                and not coord._fencing:
            _work(h0)

    summary, coord, workers, fabric_dir = _remedy_fleet(
        tmp_path, cfg, users, pools, script)
    assert sorted(summary["finished"]) == users
    assert summary["remedies"] == 1 and summary["fence_timeouts"] == 1
    # one queued drop + one demoted fence = two committed moves; the
    # fence counter records only a COMMITTED checkpoint migration
    assert summary["migrations"] == 2
    assert summary["fences"] == (0 if winner == "evict_ack" else 1)
    assert summary["drains"] == 0 and summary["revocations"] == 0
    ran = [u for w in workers.values() for u in w.finished]
    assert sorted(ran) == users
    recs = _journal_records(fabric_dir)
    remedies = [r for r in recs if r["event"] == "remedy"]
    assert [r["action"] for r in remedies] == ["rebalance",
                                               "fence_timeout"]
    assert remedies[1]["host"] == "h0"
    moved = remedies[1]["user"]
    # the demoted user was assigned exactly twice: the initial routing
    # and the single committed move — the losing ack was cursor-only
    assigns = [r for r in recs
               if r["event"] == "assign" and r.get("user") == moved]
    assert len(assigns) == 2 and assigns[-1]["host"] == "h1"
    assert validate_journal_file(
        os.path.join(fabric_dir, "serve_journal.jsonl")) == []


@pytest.mark.parametrize("at,actions_before",
                         [(1, []), (2, ["rebalance"])])
def test_remedy_kill_matrix_single_owner(tmp_path, at, actions_before):
    """Coordinator SIGKILL at ``fabric.remedy`` — before the rebalance
    decision journals (``at=1``) and inside the fence-deadline expiry
    window (``at=2``, the fault fires again at the timeout): the fault
    point fires BEFORE the append, so a kill leaves no half-journaled
    decision, and the rerun re-derives everything from the journal —
    every user finishes on exactly one host across both incarnations."""
    users = [f"u{i}" for i in range(8)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=2, min_hosts=2, max_hosts=2, poll_s=0.01,
                       drain_timeout_s=0.2, placement="load",
                       remedy=True,
                       remedy_hold_s=0.0, remedy_cooldown_s=600.0,
                       remedy_skew=2, fence_deadline_s=0.05)
    state = {"setup": False}

    def script1(rnd, coord, workers):
        if not _setup_skew(state, users, workers):
            return
        _work(workers["h1"])
        # h0 withholds its fence: at=2 reaches the deadline fire

    jp = str(tmp_path / "fabric" / "serve_journal.jsonl")
    w1 = {}
    with faults.inject(FaultRule("fabric.remedy", "kill", at=at)):
        with pytest.raises(InjectedKill):
            _remedy_fleet(tmp_path, cfg, users, pools, script1,
                          workers=w1)
    # fired-before-append: the killed decision never reached the journal
    recs_mid = _journal_records(str(tmp_path / "fabric"))
    assert [r["action"] for r in recs_mid
            if r["event"] == "remedy"] == actions_before
    done1 = set(AdmissionJournal(jp).state.finished)
    assert done1  # h1 finished its share before the kill

    def script2(rnd, coord, workers):
        for w in workers.values():
            if w.dead:
                continue
            # the fresh worker re-reads stale feed lines: users the
            # first incarnation already finished resolve from their
            # complete workspaces (build_entry -> None), modeled here
            # by dropping them from the queue without running
            for uid in list(w.queued):
                if uid in done1:
                    w.queued.remove(uid)
            for uid in list(w.fence_pending):
                w.release(uid, gen=1)
            for uid in list(getattr(w, "evict_pending", ())):
                w.force_release(uid, gen=2)
            _work(w)

    w2 = {}
    summary, coord, workers, fabric_dir = _remedy_fleet(
        tmp_path, cfg, users, pools, script2, workers=w2)
    assert sorted(list(done1) + summary["finished"]) == users
    # exactly one owner per user ACROSS BOTH incarnations
    ran = [u for w in list(w1.values()) + list(w2.values())
           for u in w.finished]
    assert sorted(ran) == users
    assert validate_journal_file(jp) == []
    # replay determinism: independent replays agree on every assignment
    assert AdmissionJournal(jp).state.assigned == \
        AdmissionJournal(jp).state.assigned
