"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

This substitutes for multi-chip hardware (SURVEY.md §4d): every sharding /
collective test runs against a real 8-way mesh of host devices, which is the
same code path XLA uses on a TPU slice (minus ICI).
"""

import os
import sys

# Must happen before the first backend initialization anywhere in the test
# session.  This environment's JAX build hard-defaults jax_platforms to the
# TPU plugin and ignores JAX_PLATFORMS/XLA_FLAGS env vars, so the config API
# is the only reliable override.
os.environ["JAX_PLATFORMS"] = "cpu"
# Older jax builds (this container ships 0.4.37) have no jax_num_cpu_devices
# config option; the XLA flag is the portable spelling and must be in the
# environment before the backend initializes.
_FLAGS = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _FLAGS:
    os.environ["XLA_FLAGS"] = \
        (_FLAGS + " --xla_force_host_platform_device_count=8").strip()

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:  # pre-0.4.38 jax: the XLA_FLAGS fallback above applies
    pass
# The crop compile-buckets rely on prefix-stable threefry draws
# (committee.predict_songs_cnn checks at the point of reliance); newer jax
# defaults this on, this build defaults it off.
jax.config.update("jax_threefry_partitionable", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1987)


@pytest.fixture(autouse=True, scope="module")
def _clear_process_wide_jit_caches():
    """Drop the framework's process-wide jit program caches after each test
    MODULE.

    Production deliberately shares compiled programs process-wide
    (``cnn_trainer._EPOCH_FNS``, ``committee._infer_fns``, the cached
    scoring-fn factories) so per-user objects never recompile.  Under the
    352-test suite that sharing keeps EVERY compiled executable of every
    module alive at once — an accumulation the pre-r04 per-instance caches
    never produced — and the virtual-CPU XLA backend then segfaults
    (SIGSEGV inside ``backend_compile_and_load``) compiling the
    member-sharded retrain epoch late in the run (deterministic at
    ``test_sharded_loop`` across three full-suite runs; the same compile
    succeeds standalone and in every file-subset probe).  Clearing between
    modules restores bounded compiler state while keeping the sharing
    semantics intact WITHIN each module, which is what the sharing tests
    pin.
    """
    yield
    from consensus_entropy_tpu.models import cnn_trainer, committee
    from consensus_entropy_tpu.ops import scoring
    from consensus_entropy_tpu.parallel import pool_mesh, sharding

    cnn_trainer._EPOCH_FNS.clear()
    committee._infer_fns_cached.cache_clear()
    committee._qbdc_infer_fn_cached.cache_clear()
    committee._user_infer_fn_cached.cache_clear()
    committee._user_qbdc_infer_fn_cached.cache_clear()
    scoring._make_scoring_fns_cached.cache_clear()
    scoring._make_fleet_scoring_fns_cached.cache_clear()
    scoring._fleet_fns_for_width_cached.cache_clear()
    sharding._make_sharded_scoring_fns_cached.cache_clear()
    pool_mesh._sharded_step_fns_cached.cache_clear()
    pool_mesh._sharded_fleet_fns_cached.cache_clear()
    pool_mesh._sharded_scatter_cached.cache_clear()
    jax.clear_caches()
