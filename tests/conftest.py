"""Test harness: force an 8-device virtual CPU platform before JAX initializes.

This substitutes for multi-chip hardware (SURVEY.md §4d): every sharding /
collective test runs against a real 8-way mesh of host devices, which is the
same code path XLA uses on a TPU slice (minus ICI).
"""

import os
import sys

# Must happen before the first backend initialization anywhere in the test
# session.  This environment's JAX build hard-defaults jax_platforms to the
# TPU plugin and ignores JAX_PLATFORMS/XLA_FLAGS env vars, so the config API
# is the only reliable override.
os.environ["JAX_PLATFORMS"] = "cpu"

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1987)
