"""Pallas fused linear-member scoring vs a numpy/scipy oracle of the
reference chain predict_proba -> groupby.mean -> consensus -> entropy
(amg_test.py:428-447), run through the Pallas interpreter on CPU."""

import numpy as np
import pytest
from scipy.stats import entropy as scipy_entropy

from consensus_entropy_tpu.experimental import pallas_scoring


def _make_problem(rng, m=3, n=50, k_frames=2, f=12, c=4):
    x = rng.standard_normal((n, k_frames, f)).astype(np.float32)
    w = (rng.standard_normal((m, f, c)) / np.sqrt(f)).astype(np.float32)
    b = (rng.standard_normal((m, c)) * 0.1).astype(np.float32)
    return x, w, b


def _oracle_entropy(x, w, b):
    """Straight-line float64 oracle: per-frame softmax, frame mean, member
    mean, scipy entropy — the reference's mc chain for linear members."""
    n, k_frames, f = x.shape
    frames = x.reshape(n * k_frames, f).astype(np.float64)
    per_member = []
    for m in range(w.shape[0]):
        logits = frames @ w[m] + b[m]
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        per_member.append(p.reshape(n, k_frames, -1).mean(axis=1))
    consensus = np.mean(per_member, axis=0)
    return scipy_entropy(consensus, axis=1)


def test_entropy_parity(rng):
    x, w, b = _make_problem(rng, n=48)
    ent = pallas_scoring.linear_consensus_entropy(
        x, w, b, tile_n=16, interpret=True)
    np.testing.assert_allclose(np.asarray(ent), _oracle_entropy(x, w, b),
                               rtol=1e-5, atol=1e-6)


def test_entropy_parity_uneven_tiles(rng):
    # N=50 with tile_n=32 exercises the internal zero-pad + trim.
    x, w, b = _make_problem(rng, n=50)
    ent = pallas_scoring.linear_consensus_entropy(
        x, w, b, tile_n=32, interpret=True)
    assert ent.shape == (50,)
    np.testing.assert_allclose(np.asarray(ent), _oracle_entropy(x, w, b),
                               rtol=1e-5, atol=1e-6)


def test_pack_roundtrip(rng):
    x, w, b = _make_problem(rng, m=2, n=8, k_frames=3, f=5)
    x_tiles, n_valid = pallas_scoring.pack_pool(x, tile_n=8)
    assert n_valid == 8 and x_tiles.shape == (1, 3, 8, 5)
    np.testing.assert_array_equal(
        np.asarray(x_tiles)[0, 1], x[:, 1, :])
    w_p, b_p = pallas_scoring.pack_weights(w, b)
    # Column block m of the packed matrix is member m's weight matrix.
    np.testing.assert_array_equal(np.asarray(w_p)[:, 4:8], w[1])
    np.testing.assert_array_equal(np.asarray(b_p)[4:8], b[1])


def test_fused_score_matches_unfused(rng):
    # The fused kernel and the XLA scoring graph must pick identical queries.
    from consensus_entropy_tpu.ops import scoring

    x, w, b = _make_problem(rng, m=4, n=64, k_frames=3)
    mask = np.ones(64, dtype=bool)
    mask[60:] = False

    x_tiles, _ = pallas_scoring.pack_pool(x, tile_n=16)
    w_p, b_p = pallas_scoring.pack_weights(w, b)
    ent, values, idx = pallas_scoring.packed_score_mc(
        x_tiles, w_p, b_p, mask, n_members=4, k=8, interpret=True)

    frames = x.reshape(-1, x.shape[-1])
    probs = []
    for m in range(w.shape[0]):
        logits = frames @ w[m] + b[m]
        logits -= logits.max(axis=1, keepdims=True)
        p = np.exp(logits)
        p /= p.sum(axis=1, keepdims=True)
        probs.append(p.reshape(64, 3, -1).mean(axis=1))
    res = scoring.score_mc(np.asarray(probs, np.float32), mask, k=8)

    np.testing.assert_array_equal(np.asarray(idx), np.asarray(res.indices))
    ent_np = np.asarray(ent)
    assert np.all(np.isneginf(ent_np[~mask]))
    np.testing.assert_allclose(ent_np[mask], np.asarray(res.entropy)[mask],
                               rtol=1e-5, atol=1e-6)


def test_shape_validation(rng):
    x, w, b = _make_problem(rng)
    x_tiles, _ = pallas_scoring.pack_pool(x, tile_n=16)
    w_p, b_p = pallas_scoring.pack_weights(w, b)
    with pytest.raises(ValueError):
        pallas_scoring.packed_consensus_entropy(
            x_tiles[..., :-1], w_p, b_p, n_members=3, interpret=True)


def test_fused_topk_ties_and_masked_tile(rng):
    # Duplicate rows create exact entropy ties; reference semantics ('fast')
    # = lax.top_k on the masked entropy vector: lowest index wins.
    x, w, b = _make_problem(rng, m=3, n=40, k_frames=2)
    x[7] = x[3]          # tie pair across tiles
    x[25] = x[3]
    x_tiles, _ = pallas_scoring.pack_pool(x, tile_n=8)
    w_p, b_p = pallas_scoring.pack_weights(w, b)
    mask = np.ones(40, bool)
    mask[8:16] = False   # a fully-masked tile
    ent, values, idx = pallas_scoring.packed_score_mc(
        x_tiles, w_p, b_p, mask, n_members=3, k=6, interpret=True)
    from consensus_entropy_tpu.ops.topk import masked_top_k
    v_ref, i_ref = masked_top_k(np.asarray(ent), mask, 6, "fast")
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(i_ref))
    np.testing.assert_allclose(np.asarray(values), np.asarray(v_ref))


def test_fused_topk_fewer_valid_than_k(rng):
    x, w, b = _make_problem(rng, m=2, n=16, k_frames=1)
    x_tiles, _ = pallas_scoring.pack_pool(x, tile_n=8)
    w_p, b_p = pallas_scoring.pack_weights(w, b)
    mask = np.zeros(16, bool)
    mask[[2, 5, 9]] = True
    ent, values, idx = pallas_scoring.packed_score_mc(
        x_tiles, w_p, b_p, mask, n_members=2, k=5, interpret=True)
    v = np.asarray(values)
    assert np.sum(v > -np.inf) == 3
    assert set(np.asarray(idx)[:3].tolist()) == {2, 5, 9}


def test_frame_packing_parity(rng):
    # pack=2: frames become extra member copies; entropy must be identical.
    x, w, b = _make_problem(rng, m=3, n=32, k_frames=4, f=10)
    assert pallas_scoring.auto_pack(4, 3, 4) == 4  # 4*3*4=48 <= 128
    for pack in (1, 2, 4):
        x_tiles, _ = pallas_scoring.pack_pool(x, tile_n=16, pack=pack)
        w_p, b_p = pallas_scoring.pack_weights(w, b, pack=pack)
        ent = pallas_scoring.packed_consensus_entropy(
            x_tiles, w_p, b_p, n_members=3 * pack, interpret=True)
        np.testing.assert_allclose(np.asarray(ent), _oracle_entropy(x, w, b),
                                   rtol=1e-5, atol=1e-6,
                                   err_msg=f"pack={pack}")


def test_pack_pool_rejects_non_divisor(rng):
    x, _, _ = _make_problem(rng, n=8, k_frames=3)
    with pytest.raises(ValueError):
        pallas_scoring.pack_pool(x, tile_n=8, pack=2)


def test_member_far_below_committee_max(rng):
    # A member whose logits sit far below another member's max must still
    # contribute its own (sharp) softmax to the consensus — a global-row-max
    # stability shift would flatten it to a uniform vote.
    f = 8
    x = np.zeros((16, 1, f), np.float32)
    x[:, 0, 0] = 1.0
    w = np.zeros((2, f, 4), np.float32)
    w[0, 0] = [0.0, 0.0, 0.0, 80.0]    # member A: sharp, huge logits
    w[1, 0] = [0.0, 0.0, 0.0, 5.0]     # member B: sharp, tiny logits
    b = np.zeros((2, 4), np.float32)
    ent = pallas_scoring.linear_consensus_entropy(x, w, b, tile_n=16,
                                                  interpret=True)
    np.testing.assert_allclose(np.asarray(ent), _oracle_entropy(x, w, b),
                               rtol=1e-5, atol=1e-6)


def test_shardmap_pallas_scorer_matches_single_device(rng):
    # The multi-chip Pallas path (kernel per pool shard + all_gather merge)
    # must reproduce the single-device fused scorer on an 8-way mesh.
    from consensus_entropy_tpu.parallel.mesh import make_pool_mesh
    from consensus_entropy_tpu.parallel.sharding import (
        make_shardmap_pallas_mc_scorer,
    )

    x, w, b = _make_problem(rng, m=3, n=128, k_frames=2)
    x_tiles, _ = pallas_scoring.pack_pool(x, tile_n=8)   # 16 tiles / 8 chips
    w_p, b_p = pallas_scoring.pack_weights(w, b)
    mask = np.ones(128, bool)
    mask[100:] = False

    mesh = make_pool_mesh()
    ent1, v1, i1 = pallas_scoring.packed_score_mc(
        x_tiles, w_p, b_p, mask, n_members=3, k=6, fuse_topk=True,
        interpret=True)
    for fuse in (True, False):
        scorer = make_shardmap_pallas_mc_scorer(mesh, n_members=3, k=6,
                                                fuse_topk=fuse,
                                                interpret=True)
        res = scorer(x_tiles, w_p, b_p, mask)
        np.testing.assert_allclose(np.asarray(res.entropy), np.asarray(ent1),
                                   rtol=1e-6)
        np.testing.assert_array_equal(np.asarray(res.indices),
                                      np.asarray(i1))
        np.testing.assert_allclose(np.asarray(res.values), np.asarray(v1),
                                   rtol=1e-6)
