"""Fused scoring graph vs a straight-line numpy/scipy oracle of the
reference's acquisition code (amg_test.py:425-489)."""

import jax
import numpy as np
from scipy.stats import entropy as scipy_entropy

from consensus_entropy_tpu.ops import scoring


def _oracle_mc(member_probs, q):
    consensus = np.mean(member_probs, axis=0)  # amg_test.py:441
    ent = scipy_entropy(consensus, axis=1)  # :443
    return ent, np.argsort(ent)[::-1][:q]  # :445


def _probs(rng, m, n, c=4):
    p = rng.uniform(0.01, 1.0, size=(m, n, c))
    return p / p.sum(axis=-1, keepdims=True)


def test_mc_parity(rng):
    p = _probs(rng, 20, 120)
    mask = np.ones(120, dtype=bool)
    res = scoring.score_mc(p, mask, k=10, tie_break="numpy")
    ent_ref, _ = _oracle_mc(p, 10)
    got_ent = np.asarray(res.entropy)
    np.testing.assert_allclose(got_ent, ent_ref, rtol=1e-4)
    # Rank oracle over the kernel's own entropies: float64-vs-float32 near-ties
    # may legitimately reorder vs scipy, but ranking must match numpy exactly.
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.argsort(got_ent)[::-1][:10])


def test_mc_with_padding(rng):
    # Padding the pool axis must not change which real songs are selected.
    p = _probs(rng, 6, 100)
    padded = np.zeros((6, 256, 4), dtype=p.dtype)
    padded[:, :100] = p
    mask = np.zeros(256, dtype=bool)
    mask[:100] = True
    res = scoring.score_mc(padded, mask, k=7, tie_break="numpy")
    unpadded = scoring.score_mc(p, np.ones(100, dtype=bool), k=7,
                                tie_break="numpy")
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.asarray(unpadded.indices))
    _, idx_ref = _oracle_mc(p, 7)
    assert set(np.asarray(res.indices)) == set(idx_ref)


def test_mc_member_mask(rng):
    # A padded member slot must contribute nothing to the consensus.
    p = _probs(rng, 5, 40)
    padded = np.concatenate([p, np.zeros((3, 40, 4))], axis=0)
    mmask = np.array([True] * 5 + [False] * 3)
    pool_mask = np.ones(40, dtype=bool)
    res = scoring.score_mc(padded, pool_mask, k=5, member_mask=mmask,
                           tie_break="numpy")
    unmasked = scoring.score_mc(p, pool_mask, k=5, tie_break="numpy")
    np.testing.assert_allclose(np.asarray(res.entropy),
                               np.asarray(unmasked.entropy), rtol=1e-5)
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.asarray(unmasked.indices))


def test_hc_parity(rng):
    counts = rng.integers(0, 30, size=(80, 4)) + 1
    freq = np.round(counts / counts.sum(axis=1, keepdims=True), 3)
    mask = np.ones(80, dtype=bool)
    res = scoring.score_hc(freq, mask, k=10, tie_break="numpy")
    ent_ref = scipy_entropy(freq, axis=1)  # amg_test.py:451
    got_ent = np.asarray(res.entropy)
    np.testing.assert_allclose(got_ent, ent_ref, rtol=1e-4)
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.argsort(got_ent)[::-1][:10])


def test_hc_precomputed_matches_score_hc(rng):
    """The production hc path (entropy hoisted out of the loop,
    ``score_hc_precomputed``) must produce identical entropies/selections
    to the full per-iteration chain across shrinking masks — including
    all-zero padding rows sitting behind the mask."""
    from consensus_entropy_tpu.ops.entropy import shannon_entropy

    counts = rng.integers(0, 30, size=(64, 4)) + 1
    freq = np.zeros((80, 4), np.float32)  # rows 64.. are all-zero padding
    freq[:64] = np.round(counts / counts.sum(axis=1, keepdims=True), 3)
    mask = np.zeros(80, bool)
    mask[:64] = True
    ent_once = np.asarray(shannon_entropy(freq))
    # zero padding rows come out finite (-0.0: the 0*log0 clamp) and sit
    # behind the mask either way
    assert np.all(ent_once[64:] == 0.0)
    for _ in range(3):
        full = scoring.score_hc(freq, mask, k=7, tie_break="numpy")
        pre = scoring.score_hc_precomputed(ent_once, mask, k=7,
                                           tie_break="numpy")
        np.testing.assert_array_equal(np.asarray(pre.indices),
                                      np.asarray(full.indices))
        np.testing.assert_allclose(np.asarray(pre.values),
                                   np.asarray(full.values), rtol=1e-6)
        mask[np.asarray(pre.indices)] = False


def test_hc_query_removal_via_mask(rng):
    # Reference removes queried rows from the hc table (amg_test.py:455);
    # here that's a mask update, and re-scoring must pick the next tier.
    counts = rng.integers(1, 30, size=(50, 4))
    freq = counts / counts.sum(axis=1, keepdims=True)
    mask = np.ones(50, dtype=bool)
    r1 = scoring.score_hc(freq, mask, k=5, tie_break="numpy")
    mask2 = mask.copy()
    mask2[np.asarray(r1.indices)] = False
    r2 = scoring.score_hc(freq, mask2, k=5, tie_break="numpy")
    assert not set(np.asarray(r2.indices)) & set(np.asarray(r1.indices))
    ent1 = np.asarray(r1.entropy)
    remaining = np.argsort(ent1)[::-1][5:10]
    np.testing.assert_array_equal(np.sort(np.asarray(r2.indices)),
                                  np.sort(remaining))


def test_mix_parity(rng):
    # Oracle mirrors amg_test.py:473-481: stack mc consensus rows on top of
    # the remaining hc rows, entropy over all, top-q row indices.
    p = _probs(rng, 8, 60)
    counts = rng.integers(1, 25, size=(60, 4))
    hc = np.round(counts / counts.sum(axis=1, keepdims=True), 3)
    hc_mask = np.ones(60, dtype=bool)
    hc_mask[40:] = False  # songs already queried from hc in earlier iters
    pool_mask = np.ones(60, dtype=bool)

    res = scoring.score_mix(p, pool_mask, hc, hc_mask, k=9, tie_break="numpy")

    stacked = np.concatenate([np.mean(p, axis=0), hc], axis=0)
    ent_ref = scipy_entropy(stacked, axis=1)
    got_ent = np.asarray(res.entropy)  # (120,), -inf on masked hc rows
    np.testing.assert_allclose(got_ent[:100], np.concatenate(
        [ent_ref[:60], ent_ref[60:100]]), rtol=1e-4)
    assert np.all(np.isneginf(got_ent[100:]))
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.argsort(got_ent)[::-1][:9])
    is_hc, slot = scoring.split_mix_index(res.indices, 60)
    assert np.asarray(slot).max() < 60


def test_rand_uniform_over_valid(rng):
    mask = np.zeros(64, dtype=bool)
    mask[::2] = True
    key = jax.random.key(0)
    res = scoring.score_rand(key, mask, k=8)
    idx = np.asarray(res.indices)
    assert len(set(idx)) == 8
    assert all(mask[i] for i in idx)
    # different key → different draw (w.h.p.)
    res2 = scoring.score_rand(jax.random.key(1), mask, k=8)
    assert list(np.asarray(res2.indices)) != list(idx)


def test_jitted_fns_stable_shapes(rng):
    # the fns are process-shared (make_scoring_fns is lru_cached), so the
    # jit cache may already hold other tests' shapes — assert the DELTA:
    # one compile for this shape, zero for the same-shape second call
    fns = scoring.make_scoring_fns(k=4, tie_break="fast")
    p = _probs(rng, 3, 32).astype(np.float32)
    mask = np.ones(32, dtype=bool)
    before = fns["mc"]._cache_size()
    r1 = fns["mc"](p, mask)
    after_first = fns["mc"]._cache_size()
    assert after_first <= before + 1
    mask2 = mask.copy()
    mask2[np.asarray(r1.indices)] = False
    r2 = fns["mc"](p, mask2)  # same shapes → no retrace
    assert not set(np.asarray(r2.indices)) & set(np.asarray(r1.indices))
    assert fns["mc"]._cache_size() == after_first
