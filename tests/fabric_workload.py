"""Synthetic multi-host fabric workload.

Shared by the fabric tests (``tests/test_serve_fabric.py``), the worker
subprocess entrypoint (``tests/fabric_worker.py``) and
``bench.py --suite fabric``.  Deliberately self-contained (no pytest
import, deterministic from seeds): worker subprocesses must rebuild the
EXACT users the in-process sequential baselines were computed from, or
the bit-identical parity assertions would be comparing different
problems.  The generators mirror ``tests/test_fleet._user_data`` /
``_committee`` (3 songs' pools, GNB+SGD host committees, float32
checkpoints so resume replays bit-exactly).
"""

from __future__ import annotations

import json
import os

import numpy as np


def configure_jax() -> None:
    """Mirror ``tests/conftest.py``'s backend setup so worker subprocesses
    compute bit-identically to the in-process baselines (8 virtual CPU
    devices, partitionable threefry)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:  # this image's 0.4.37: XLA_FLAGS above applies
        pass
    jax.config.update("jax_threefry_partitionable", True)


def make_cfg(mode: str = "mc", epochs: int = 2, queries: int = 4):
    from consensus_entropy_tpu.config import ALConfig

    # float32 checkpoints: resume (failover included) replays bit-exactly
    return ALConfig(queries=queries, epochs=epochs, mode=mode, seed=7,
                    ckpt_dtype="float32", qbdc_k=6)


def tiny_cnn_configs():
    """The tiny CNN geometry the qbdc fabric rows run on (matches the CNN
    fleet/acquire tests; workers rebuild it from these constants, so the
    in-process baselines and the subprocess engines agree)."""
    from consensus_entropy_tpu.config import CNNConfig, TrainConfig

    return (CNNConfig(n_channels=4, n_mels=32, n_layers=5,
                      input_length=8192),
            TrainConfig(batch_size=2))


def retrain_epochs_for(mode: str):
    """CNN retrain epochs per AL iteration for the synthetic workload
    (qbdc only; host-committee modes have no CNN retrain)."""
    return 1 if mode == "qbdc" else None


def user_specs(n_users: int, n_songs: int = 30, sizes=None) -> list:
    """``[(seed, user_id, n_songs), ...]`` — the canonical workload.
    ``sizes`` (cycled over users) builds the SKEWED shape the elastic
    placement drills need: users land in different pool-width dispatch
    buckets, so bucket-aware placement has something to co-locate."""
    if sizes:
        return [(100 + i, f"u{i}", int(sizes[i % len(sizes)]))
                for i in range(int(n_users))]
    return [(100 + i, f"u{i}", n_songs) for i in range(int(n_users))]


def sizes_arg(specs) -> str:
    """The per-user size list as the comma-separated argv form
    ``tests/fabric_worker.py`` rebuilds specs from (workers MUST build
    the exact users the coordinator's baselines were computed from)."""
    return ",".join(str(n) for _, _, n in specs)


def make_data(seed: int, uid: str, n_songs: int = 30, f: int = 10,
              mode: str = "mc"):
    from consensus_entropy_tpu.al.loop import UserData
    from consensus_entropy_tpu.models.committee import FramePool

    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((4, f)).astype(np.float32) * 2.5
    rows, sids, labels = [], [], {}
    for i in range(n_songs):
        sid = f"song{i:03d}"
        c = int(rng.integers(0, 4))
        labels[sid] = c
        k = int(rng.integers(3, 7))
        rows.append(centers[c]
                    + rng.standard_normal((k, f)).astype(np.float32))
        sids += [sid] * k
    pool = FramePool(np.vstack(rows), sids)
    counts = rng.integers(1, 30, size=(n_songs, 4))
    hc = np.round(counts / counts.sum(1, keepdims=True),
                  3).astype(np.float32)
    data = UserData(uid, pool, labels, hc_rows=hc)
    if mode == "qbdc":
        # seeded waveform store for the dropout committee's CNN (both
        # processes rebuild identical waves from the spec seed)
        from consensus_entropy_tpu.data.audio import DeviceWaveformStore

        cnn_cfg, _ = tiny_cnn_configs()
        wrng = np.random.default_rng(seed + 7)
        waves = {s: wrng.standard_normal(9000).astype(np.float32)
                 for s in pool.song_ids}
        data.store = DeviceWaveformStore(waves, cnn_cfg.input_length)
    return data


def make_committee(data, sgd_name: str = "sgd.it_0", mode: str = "mc",
                   cnn_seed: int = 5):
    from consensus_entropy_tpu.models.committee import Committee
    from consensus_entropy_tpu.models.sklearn_members import (
        GNBMember,
        SGDMember,
    )

    if mode == "qbdc":
        import jax

        from consensus_entropy_tpu.models import short_cnn
        from consensus_entropy_tpu.models.committee import CNNMember

        cnn_cfg, tc = tiny_cnn_configs()
        member = CNNMember(
            "cnn0",
            short_cnn.init_variables(jax.random.key(cnn_seed), cnn_cfg),
            cnn_cfg, tc)
        return Committee([], [member], cnn_cfg, tc)
    X = data.pool.X
    y = np.array([data.labels[s] for s in np.repeat(
        data.pool.song_ids, data.pool.counts)], np.int32)
    return Committee([GNBMember("gnb.it_0").fit(X, y),
                      SGDMember(sgd_name, seed=0).fit(X, y)], [])


def load_workspace_committee(path: str, mode: str):
    """Reload a workspace committee with the mode's geometry (qbdc
    checkpoints are the tiny CNN and need its config at load)."""
    from consensus_entropy_tpu.al import workspace

    if mode == "qbdc":
        cnn_cfg, tc = tiny_cnn_configs()
        return workspace.load_committee(path, cnn_cfg, tc)
    return workspace.load_committee(path)


def build_entry_factory(ws_root: str, cfg, specs):
    """``build_entry(uid) -> FleetUser`` over persistent per-user
    workspaces under ``ws_root``: a fresh workspace gets a fresh
    committee, one holding mid-run state (the previous host's durable
    checkpoints) resumes from its own files — the fabric failover path."""
    from consensus_entropy_tpu.fleet import FleetUser

    by = {uid: (seed, uid, n) for seed, uid, n in specs}

    def build_entry(uid):
        seed, _, n = by[str(uid)]
        data = make_data(seed, str(uid), n_songs=n, mode=cfg.mode)
        fp = os.path.join(ws_root, f"fab_{uid}")
        os.makedirs(fp, exist_ok=True)
        if os.path.exists(os.path.join(fp, "al_state.json")):
            committee = load_workspace_committee(fp, cfg.mode)
        else:
            committee = make_committee(data, mode=cfg.mode)
        return FleetUser(
            str(uid), committee, data, fp, seed=cfg.seed,
            committee_factory=lambda fp=fp: load_workspace_committee(
                fp, cfg.mode))

    return build_entry


def force_low_water(coord, hosts: int = 3) -> None:
    """Deterministic drain trigger for scale-down drills (pass as — or
    call from — the coordinator's ``on_poll``, paired with a huge
    ``scale_down_s``): the low-water TIMER is forced the moment every
    joined host holds an in-flight user, so the drain victim has
    sessions to fence and the drill never races worker start-up against
    user completion."""
    if coord.drains:
        return
    st = coord.journal.state
    joined = [h for h in coord.hosts.values() if h.joined and h.alive]
    if len(joined) < hosts:
        return
    in_flight = set(st.in_flight)
    if all(any(st.assigned.get(u) == h.host_id for u in in_flight)
           for h in joined):
        coord._low_since = -1e18  # the mark has "held" long enough


def sequential_baselines(ws_root: str, cfg, specs) -> dict:
    """Uninterrupted single-host ground truth: ``{uid: result}`` from
    ``ALLoop.run_user`` over the identical users and seeds."""
    from consensus_entropy_tpu.al.loop import ALLoop

    out = {}
    loop = ALLoop(cfg, retrain_epochs=retrain_epochs_for(cfg.mode))
    for seed, uid, n in specs:
        data = make_data(seed, uid, n_songs=n, mode=cfg.mode)
        p = os.path.join(ws_root, f"seq_{uid}")
        os.makedirs(p)
        out[uid] = loop.run_user(make_committee(data, mode=cfg.mode),
                                 data, p)
    return out


def read_results(fabric_dir: str) -> dict:
    """``{uid: last result record}`` across every ``results_<host>.jsonl``
    the workers wrote (an idempotent re-finish appends a second record —
    the LAST one is the user's standing result)."""
    recs = []
    for fname in sorted(os.listdir(fabric_dir)):
        if not (fname.startswith("results_")
                and fname.endswith(".jsonl")):
            continue
        with open(os.path.join(fabric_dir, fname), "rb") as f:
            for raw in f:
                try:
                    rec = json.loads(raw.decode("utf-8"))
                except ValueError:
                    continue  # torn tail from a killed worker
                if isinstance(rec, dict) and "user" in rec:
                    recs.append(rec)
    recs.sort(key=lambda r: r.get("t", 0.0))
    return {r["user"]: r for r in recs}
