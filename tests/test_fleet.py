"""Fleet scheduler: interleaved multi-user runs vs sequential ground truth.

Tier-1 (un-marked) keeps only the 2-user scheduler smoke and the shared
checkpointer units, per the tier-1 budget; the full mode matrix, the
eviction+resume drill and the 4-user acceptance run are ``slow``
(``scripts/fleet_bench.sh`` exercises throughput).

Trajectory equality is exact (``==`` on float lists): the fleet drives the
SAME session generator as ``ALLoop.run_user`` and the batched scorers are
bit-identical to the single-user jitted fns, so there is no tolerance to
grant.  ``ckpt_dtype="float32"`` keeps resume-after-eviction bit-exact too.
"""

import json
import os

import numpy as np
import pytest

from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.al.loop import ALLoop, AsyncCheckpointer, UserData
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.models.committee import Committee, FramePool
from consensus_entropy_tpu.models.sklearn_members import GNBMember, SGDMember
from consensus_entropy_tpu.resilience import faults
from consensus_entropy_tpu.resilience.faults import FaultRule

pytestmark = pytest.mark.fleet


def _user_data(seed, uid, n_songs=30, f=10):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((4, f)).astype(np.float32) * 2.5
    rows, sids, labels = [], [], {}
    for i in range(n_songs):
        sid = f"song{i:03d}"
        c = int(rng.integers(0, 4))
        labels[sid] = c
        k = int(rng.integers(3, 7))
        rows.append(centers[c]
                    + rng.standard_normal((k, f)).astype(np.float32))
        sids += [sid] * k
    pool = FramePool(np.vstack(rows), sids)
    counts = rng.integers(1, 30, size=(n_songs, 4))
    hc = np.round(counts / counts.sum(1, keepdims=True),
                  3).astype(np.float32)
    return UserData(uid, pool, labels, hc_rows=hc)


def _committee(data, *, sgd_name="sgd.it_0", min_members=1):
    X = data.pool.X
    y = np.array([data.labels[s] for s in np.repeat(
        data.pool.song_ids, data.pool.counts)], np.int32)
    return Committee([GNBMember("gnb.it_0").fit(X, y),
                      SGDMember(sgd_name, seed=0).fit(X, y)], [],
                     min_members=min_members)


def _cfg(mode="mc", epochs=2, queries=4):
    # float32 checkpoints: resume (and resume-after-eviction) replays
    # bit-exactly, so faulted trajectories can be compared with ==
    return ALConfig(queries=queries, epochs=epochs, mode=mode, seed=7,
                    ckpt_dtype="float32")


def _run_pair(tmp_path, cfg, n_users, *, committee_fn=_committee,
              scheduler_kw=None, data_fn=_user_data):
    """Sequential baselines + a fleet cohort over identical inputs.
    Returns (sequential results, fleet records, scheduler)."""
    seq, entries = [], []
    for i in range(n_users):
        data = data_fn(100 + i, f"u{i}")
        p = tmp_path / f"seq_u{i}"
        p.mkdir()
        seq.append(ALLoop(cfg).run_user(committee_fn(data), data, str(p)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(
            f"u{i}", committee_fn(data), data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp))))
    sched = FleetScheduler(cfg, **(scheduler_kw or {}))
    recs = sched.run(entries)
    return seq, recs, sched


def test_fleet_two_user_smoke_matches_sequential(tmp_path):
    """2-user cohort: per-user trajectories identical to two sequential
    ``run_user`` runs; cohort telemetry lands in the fleet metrics.jsonl."""
    cfg = _cfg(mode="mc", epochs=2)
    jsonl = tmp_path / "fleet_metrics.jsonl"
    seq, recs, sched = _run_pair(
        tmp_path, cfg, 2,
        scheduler_kw={"report": FleetReport(str(jsonl))})
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]
    summary = sched.report.write_summary(cohort=2)
    assert summary["users_done"] == 2 and summary["users_failed"] == 0
    assert summary["score_dispatches"] >= cfg.epochs  # scoring happened
    assert 0 < summary["occupancy"] <= 1.0
    assert summary["users_per_sec"] > 0
    assert set(summary["phase_wall_s"]) >= {"select_s", "update_host_s",
                                            "evaluate_s"}
    events = [json.loads(l) for l in open(jsonl)]
    assert any(e["event"] == "user_done" for e in events)
    assert events[-1]["event"] == "fleet_summary"
    # per-user surfaces unchanged: workspace state + reports exist
    for i in range(2):
        d = str(tmp_path / f"fleet_u{i}")
        assert os.path.exists(os.path.join(d, "al_state.json"))
        assert os.path.exists(os.path.join(d, "metrics.jsonl"))
        assert os.path.exists(os.path.join(d, "timings.jsonl"))


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["mc", "hc", "mix", "rand"])
def test_fleet_matches_sequential_all_modes(tmp_path, mode):
    cfg = _cfg(mode=mode, epochs=3)
    seq, recs, _ = _run_pair(tmp_path, cfg, 3)
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]


@pytest.mark.slow
def test_fleet_four_user_acceptance(tmp_path):
    """Acceptance: a 4-user fleet on CPU-virtual devices produces per-user
    results identical to four sequential ``run_user`` runs (same seeds),
    with genuinely batched device dispatches."""
    cfg = _cfg(mode="mc", epochs=3, queries=5)
    seq, recs, sched = _run_pair(tmp_path, cfg, 4)
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]
    summary = sched.report.summary(cohort=4)
    assert summary["mean_device_batch"] > 1.0  # cross-user batching engaged
    # per-user metrics.jsonl matches the sequential run's records exactly
    for i in range(4):
        seq_recs = [json.loads(l) for l in
                    open(tmp_path / f"seq_u{i}" / "metrics.jsonl")]
        fleet_recs = [json.loads(l) for l in
                      open(tmp_path / f"fleet_u{i}" / "metrics.jsonl")]
        assert fleet_recs == seq_recs


@pytest.mark.slow
@pytest.mark.faults
def test_fleet_eviction_and_resume(tmp_path):
    """One user's committee exhausts mid-run (injected member failure under
    a min_members=2 floor): that session is evicted, resumed from its
    workspace, and every user — including the faulted one — finishes with
    the sequential unfaulted trajectory; the cohort never stalls."""
    cfg = _cfg(mode="mc", epochs=3)

    def committee_fn(data):
        if data.user_id == "u1":  # the victim: uniquely-named member
            return _committee(data, sgd_name="sgd.victim", min_members=2)
        return _committee(data)

    seq, entries = [], []
    for i in range(3):  # unfaulted sequential ground truth
        data = _user_data(100 + i, f"u{i}")
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg).run_user(committee_fn(data), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(
            f"u{i}", committee_fn(data), data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp))))
    jsonl = tmp_path / "fleet_metrics.jsonl"
    sched = FleetScheduler(cfg, report=FleetReport(str(jsonl)))
    # member-filtered rules count per-(point, member) hits: this fires on
    # the victim's FIRST retrain only, so the resumed session runs clean
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="sgd.victim")) as inj:
        recs = sched.run(entries)
    assert inj.fired, "the victim member's retrain fault never fired"
    events = [json.loads(l) for l in open(jsonl)]
    assert [e["user"] for e in events if e["event"] == "evict"] == ["u1"]
    assert [e["user"] for e in events if e["event"] == "resume"] == ["u1"]
    for s, r in zip(seq, recs):
        assert r["error"] is None, r
        assert r["result"]["trajectory"] == s["trajectory"]
    assert recs[1]["resumes"] == 1
    assert sched.report.users_failed == 0


@pytest.mark.slow
@pytest.mark.faults
def test_fleet_eviction_without_factory_fails_only_that_user(tmp_path):
    cfg = _cfg(mode="mc", epochs=2)
    entries, seq = [], []
    for i in range(2):
        data = _user_data(100 + i, f"u{i}")
        committee = (_committee(data, sgd_name="sgd.victim", min_members=2)
                     if i == 0 else _committee(data))
        p = tmp_path / f"fleet_u{i}"
        p.mkdir()
        entries.append(FleetUser(f"u{i}", committee, data, str(p),
                                 seed=cfg.seed))  # no committee_factory
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg).run_user(_committee(data), data, str(sp)))
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="sgd.victim")) as inj:
        recs = FleetScheduler(cfg).run(entries)
    assert inj.fired
    assert recs[0]["error"] is not None and recs[0]["result"] is None
    assert recs[1]["error"] is None
    assert recs[1]["result"]["trajectory"] == seq[1]["trajectory"]


@pytest.mark.slow
def test_fleet_preemption_leaves_all_workspaces_resumable(tmp_path):
    """A preemption request stops the WHOLE fleet at iteration boundaries;
    every workspace ends durable, and a rerun completes each user to the
    sequential trajectory."""
    from consensus_entropy_tpu.resilience.preemption import Preempted

    class CountingGuard:
        def __init__(self, after):
            self.checks, self.after = 0, after

        @property
        def requested(self):
            self.checks += 1
            return self.checks > self.after

    cfg = _cfg(mode="mc", epochs=3)
    seq, entries = [], []
    for i in range(2):
        data = _user_data(100 + i, f"u{i}")
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg).run_user(_committee(data), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(f"u{i}", _committee(data), data, str(fp),
                                 seed=cfg.seed))
    with pytest.raises(Preempted):
        FleetScheduler(cfg, preemption=CountingGuard(2)).run(entries)
    # rerun: resumed sessions complete to the sequential trajectories
    entries2 = [FleetUser(e.user_id, workspace.load_committee(e.user_path),
                          e.data, e.user_path, seed=cfg.seed)
                for e in entries]
    recs = FleetScheduler(cfg).run(entries2)
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]


@pytest.mark.slow
def test_fleet_cnn_committee_matches_sequential(tmp_path, rng):
    """Device committees ride the fleet too: CNN members' stacked-variable
    scoring/retraining runs inline on the scheduler thread (jax stays on
    the main thread), only the acquisition scoring batches across users —
    and the per-user trajectories still match the sequential run exactly."""
    import jax

    from consensus_entropy_tpu.config import CNNConfig, TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.models import short_cnn
    from consensus_entropy_tpu.models.committee import CNNMember

    tiny = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)
    tc = TrainConfig(batch_size=2)

    def data_fn(seed, uid):
        data = _user_data(seed, uid, n_songs=10)
        wrng = np.random.default_rng(seed + 7)
        waves = {s: wrng.standard_normal(9000).astype(np.float32)
                 for s in data.pool.song_ids}
        data.store = DeviceWaveformStore(waves, tiny.input_length)
        return data

    def committee_fn(data):
        X = data.pool.X
        y = np.array([data.labels[s] for s in np.repeat(
            data.pool.song_ids, data.pool.counts)], np.int32)
        cnns = [CNNMember(f"cnn{i}",
                          short_cnn.init_variables(jax.random.key(i), tiny),
                          tiny, tc)
                for i in range(2)]
        return Committee([GNBMember("gnb.it_0").fit(X, y)], cnns, tiny, tc)

    cfg = _cfg(mode="mc", epochs=2, queries=3)
    seq, entries = [], []
    for i in range(2):
        data = data_fn(100 + i, f"u{i}")
        sp = tmp_path / f"seq_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=2).run_user(
            committee_fn(data), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(f"u{i}", committee_fn(data), data, str(fp),
                                 seed=cfg.seed))
    recs = FleetScheduler(cfg, retrain_epochs=2).run(entries)
    for s, r in zip(seq, recs):
        assert r["error"] is None
        assert r["result"]["trajectory"] == s["trajectory"]


# -- occupancy accounting (active slots only) -----------------------------


@pytest.mark.faults
def test_fleet_occupancy_excludes_finished_and_evicted(tmp_path):
    """Regression: dispatch records grade occupancy against the slots
    still ACTIVE at dispatch time — a terminally-failed (or finished)
    session stops counting the moment its generator returns, instead of
    diluting every later dispatch for the remainder of the cohort."""
    cfg = _cfg(mode="mc", epochs=2)
    entries = []
    for i in range(3):
        data = _user_data(100 + i, f"u{i}")
        committee = (_committee(data, sgd_name="sgd.victim", min_members=2)
                     if i == 0 else _committee(data))
        p = tmp_path / f"fleet_u{i}"
        p.mkdir()
        entries.append(FleetUser(f"u{i}", committee, data, str(p),
                                 seed=cfg.seed))  # no factory: terminal
    sched = FleetScheduler(cfg)
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="sgd.victim")) as inj:
        recs = sched.run(entries)
    assert inj.fired
    assert recs[0]["error"] is not None
    ds = sched.report.dispatches
    # u0 died during epoch 0 (after its only select): no later dispatch
    # may grade itself against its dead slot
    assert all(d["active"] <= 3 for d in ds)
    assert ds[-1]["active"] <= 2
    assert ds[-1]["batch"] <= ds[-1]["active"]
    assert 0 < sched.report.occupancy <= 1.0


# -- engine teardown ordering ---------------------------------------------


@pytest.mark.faults
def test_abort_teardown_joins_checkpointers_before_pool_shutdown(tmp_path):
    """Scheduler teardown ordering: on the abort path (one session raises
    ``Preempted``), every OTHER live generator is closed — joining its
    session's ``AsyncCheckpointer`` even mid-commit (slowed here with a
    checkpoint-write delay fault) — BEFORE the shared checkpoint pool is
    shut down, so every workspace ends durable and resumable."""
    from consensus_entropy_tpu.al import state as al_state
    from consensus_entropy_tpu.resilience.preemption import Preempted

    class CountingGuard:
        def __init__(self, after):
            self.checks, self.after = 0, after

        @property
        def requested(self):
            self.checks += 1
            return self.checks > self.after

    cfg = _cfg(mode="mc", epochs=2)
    entries = []
    for i in range(2):
        data = _user_data(100 + i, f"u{i}")
        p = tmp_path / f"fleet_u{i}"
        p.mkdir()
        entries.append(FleetUser(f"u{i}", _committee(data), data, str(p),
                                 seed=cfg.seed))
    sched = FleetScheduler(cfg, preemption=CountingGuard(1))
    with faults.inject(FaultRule("checkpoint.write", "delay", at=1,
                                 times=16, delay_s=0.05)):
        with pytest.raises(Preempted):
            sched.run(entries)
    # the shared pool was reaped only after the joins: nothing pending
    assert sched._ckpt_pool._shutdown
    for i in range(2):
        # each workspace's last two-phase commit landed and is loadable
        st = al_state.ALState.load(str(tmp_path / f"fleet_u{i}"))
        assert st is not None


# -- AsyncCheckpointer concurrent-session fix (satellite) -----------------


def test_async_checkpointer_shared_executor_preserves_order():
    """Per-session job ordering holds on a shared pool, and ``close``
    leaves the shared pool running for its owner (the fleet scheduler)."""
    from concurrent.futures import ThreadPoolExecutor
    import threading

    pool = ThreadPoolExecutor(max_workers=4)
    try:
        log = []
        gate = threading.Event()
        a = AsyncCheckpointer(executor=pool)
        b = AsyncCheckpointer(executor=pool)
        a.submit(lambda: (gate.wait(2), log.append("a1")))
        b.submit(lambda: log.append("b1"))  # b runs while a's job blocks
        b.wait()
        assert log == ["b1"]
        gate.set()
        a.submit(lambda: log.append("a2"))  # joins a1 first
        a.wait()
        assert log == ["b1", "a1", "a2"]
        a.close()
        with pytest.raises(RuntimeError, match="closed"):
            a.submit(lambda: None)
        # the shared pool must survive a session's close
        b.submit(lambda: log.append("b2"))
        b.close()
        assert log[-1] == "b2"
    finally:
        pool.shutdown(wait=True)


def test_async_checkpointer_owned_pool_unchanged():
    done = []
    with AsyncCheckpointer() as ck:
        ck.submit(lambda: done.append(1))
    assert done == [1]
    with pytest.raises(RuntimeError):
        ck.submit(lambda: None)
