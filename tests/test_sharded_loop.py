"""Production AL path through the pool-sharded scorers.

The 8-virtual-device mesh run must reproduce the single-device trajectory
bit-for-bit (tie_break='fast'): the sharded mean/entropy are row-local (same
arithmetic per row), the top-k candidate merge is index-stable, and crop
sampling happens at the unpadded batch width — so sharding changes WHERE the
work runs, never the result.  Reference scoring chain: amg_test.py:425-447.
"""

import numpy as np
import pytest

import jax

from consensus_entropy_tpu.al import state as al_state
from consensus_entropy_tpu.al.loop import ALLoop, UserData
from consensus_entropy_tpu.config import ALConfig, CNNConfig, TrainConfig
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.models.committee import (
    CNNMember,
    Committee,
    FramePool,
)
from consensus_entropy_tpu.models.sklearn_members import GNBMember, SGDMember
from consensus_entropy_tpu.parallel.mesh import make_pool_mesh

TINY = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)


def _user_data(seed=3, n_songs=24, f=10, waves=False):
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((4, f)).astype(np.float32) * 2.0
    rows, sids, labels = [], [], {}
    for i in range(n_songs):
        sid = f"song{i:03d}"
        c = int(rng.integers(0, 4))
        labels[sid] = c
        k = int(rng.integers(3, 7))
        rows.append(centers[c]
                    + rng.standard_normal((k, f)).astype(np.float32))
        sids += [sid] * k
    pool = FramePool(np.vstack(rows), sids)
    counts = rng.integers(1, 30, size=(n_songs, 4))
    hc = np.round(counts / counts.sum(1, keepdims=True), 3).astype(np.float32)
    store = None
    if waves:
        store = DeviceWaveformStore(
            {s: rng.standard_normal(9000).astype(np.float32)
             for s in pool.song_ids}, TINY.input_length)
    return UserData("u0", pool, labels, hc_rows=hc, store=store)


def _host_members(seed=7, f=10):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((60, f)).astype(np.float32)
    y = np.tile(np.arange(4), 15)
    return [GNBMember().fit(X, y), SGDMember(seed=0).fit(X, y)]


def _run(path, mode, *, mesh=None, train_mesh=None, pad_to=None, cnn=False,
         n_songs=24, epochs=3, queries=4):
    path.mkdir(parents=True, exist_ok=True)
    data = _user_data(3, n_songs=n_songs, waves=cnn)
    cnns = []
    if cnn:
        cnns = [CNNMember(f"cnn{i}",
                          short_cnn.init_variables(jax.random.key(i), TINY),
                          TINY)
                for i in range(2)]
    com = Committee(_host_members(), cnns, TINY, TrainConfig(batch_size=2),
                    mesh=mesh, train_mesh=train_mesh)
    loop = ALLoop(ALConfig(queries=queries, epochs=epochs, mode=mode,
                           seed=11),
                  mesh=mesh, pad_pool_to=pad_to,
                  retrain_epochs=1 if cnn else None)
    res = loop.run_user(com, data, str(path))
    queried = al_state.ALState.load(str(path)).queried
    return res["trajectory"], queried


#: hc/mix rows slow-marked: see tests/test_resume.py's matrix note
@pytest.mark.parametrize("mode", [
    "mc",
    pytest.param("hc", marks=pytest.mark.slow),
    pytest.param("mix", marks=pytest.mark.slow),
    "rand",
])
def test_sharded_loop_bitwise_matches_single_device(tmp_path, mode):
    traj_a, q_a = _run(tmp_path / "a", mode)
    traj_b, q_b = _run(tmp_path / "b", mode, mesh=make_pool_mesh())
    assert q_a == q_b
    assert traj_a == traj_b  # exact float equality, not allclose


@pytest.mark.slow
def test_sharded_cnn_loop_matches_single_device(tmp_path):
    """Slow since ISSUE 6 (budget rebalance): tier-1 still covers the
    pool-sharded CNN scoring path end to end via
    ``test_cli.py::test_mesh_auto_cnn_committee_cli`` (--mesh auto with a
    CNN committee drives this same loop through the CLI, plus the
    training mesh), so this direct-API twin rides the slow lane."""
    traj_a, q_a = _run(tmp_path / "a", "mc", cnn=True, n_songs=10, epochs=2,
                       queries=3)
    traj_b, q_b = _run(tmp_path / "b", "mc", mesh=make_pool_mesh(), cnn=True,
                       n_songs=10, epochs=2, queries=3)
    assert q_a == q_b
    assert traj_a == traj_b


@pytest.mark.slow
def test_member_sharded_retrain_loop_matches_single_device(tmp_path):
    """Production retrain through a (dp=1, member=8) training mesh: the
    2-member committee is padded to 8 member slots inside fit_many, each
    chip trains one slot, and the full AL trajectory matches the
    single-device run (reference hot loop #2, amg_test.py:496-502).

    Slow since ISSUE 8 (budget rebalance — tier-1 was brushing the 870 s
    ceiling under wall-clock drift): at ~60 s this is the largest tier-1
    case, and the member-sharded fit_many MECHANISM stays tier-1 via
    ``test_cnn_trainer.py::test_fit_many_member_sharded_mesh`` while the
    mesh-driven AL loop stays tier-1 via the CLI mesh case
    (``test_cli.py::test_mesh_auto_cnn_committee_cli``); this end-to-end
    twin rides the slow lane."""
    from consensus_entropy_tpu.parallel.mesh import make_training_mesh

    traj_a, q_a = _run(tmp_path / "a", "mc", cnn=True, n_songs=10, epochs=2,
                       queries=3)
    traj_b, q_b = _run(tmp_path / "b", "mc", cnn=True, n_songs=10, epochs=2,
                       queries=3,
                       train_mesh=make_training_mesh(dp=1, member=8))
    assert q_a == q_b
    np.testing.assert_allclose(traj_a, traj_b, rtol=1e-5)


def test_pad_pool_to_does_not_change_selection(tmp_path):
    # mc entropy is mask-invariant to padding width (rand is not: its
    # uniform draw is shaped by the padded pool, documented behavior)
    traj_a, q_a = _run(tmp_path / "a", "mc")
    traj_b, q_b = _run(tmp_path / "b", "mc", pad_to=64)
    assert q_a == q_b
    assert traj_a == traj_b


def test_mesh_pad_width_is_shard_divisible(tmp_path):
    from consensus_entropy_tpu.al.acquisition import Acquirer

    acq = Acquirer([f"s{i}" for i in range(13)], None, queries=4, mode="mc",
                   mesh=make_pool_mesh(), pad_to=50)
    assert acq.n_pad % 8 == 0 and acq.n_pad >= 50
