"""Host committee members: partial_fit semantics, class preservation,
persistence round-trips."""

import numpy as np
import pytest

from consensus_entropy_tpu.config import NUM_CLASSES
from consensus_entropy_tpu.models.sklearn_members import (
    HAVE_XGBOOST,
    BoostedTreesMember,
    GNBMember,
    SGDMember,
    make_boosted_member,
)


def _data(rng, n=200, f=12):
    X = rng.standard_normal((n, f))
    centers = rng.standard_normal((NUM_CLASSES, f)) * 3
    y = rng.integers(0, NUM_CLASSES, size=n)
    X += centers[y]
    return X.astype(np.float32), y


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_fit_predict_proba(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X, y)
    p = m.predict_proba(X)
    assert p.shape == (len(X), NUM_CLASSES)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)
    assert (m.predict(X) == y).mean() > 0.8


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_partial_fit_update(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X[:150], y[:150])
    m.update(X[150:], y[150:])  # amg_test.py:509
    assert m.predict_proba(X[:5]).shape == (5, NUM_CLASSES)


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_update_with_missing_classes_keeps_4_columns(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X, y)
    sel = y == 0  # a query batch containing only class 0
    m.update(X[sel][:5], y[sel][:5])
    p = m.predict_proba(X[:10])
    assert p.shape == (10, NUM_CLASSES)
    np.testing.assert_array_equal(m.estimator.classes_, np.arange(4))


def test_boosted_fallback_class_preservation(rng):
    X, y = _data(rng)
    m = BoostedTreesMember(n_estimators=10, update_estimators=5, seed=0)
    m.fit(X, y)
    n0 = m.estimator.n_estimators_
    sel = y == 2
    m.update(X[sel][:6], y[sel][:6])  # single-class batch, like the AL loop
    assert m.estimator.n_estimators_ > n0  # boosting continued
    p = m.predict_proba(X[:7])
    assert p.shape == (7, NUM_CLASSES)
    np.testing.assert_array_equal(m.estimator.classes_, np.arange(4))


def test_make_boosted_member_gating():
    m = make_boosted_member(seed=0)
    if HAVE_XGBOOST:
        assert type(m).__name__ == "XGBMember"
    else:
        assert isinstance(m, BoostedTreesMember)
    assert m.kind == "xgb"


@pytest.mark.parametrize("factory", [
    lambda: GNBMember(), lambda: SGDMember(seed=1),
    lambda: BoostedTreesMember(n_estimators=5, seed=1)])
def test_save_load_roundtrip(factory, rng, tmp_path):
    X, y = _data(rng, n=80)
    m = factory().fit(X, y)
    path = str(tmp_path / "m.pkl")
    m.save(path)
    m2 = type(m).load(path)
    np.testing.assert_allclose(m2.predict_proba(X[:9]),
                               m.predict_proba(X[:9]), rtol=1e-6)
    m2.update(X[:10], y[:10])  # loaded member must still be updatable
