"""Host committee members: partial_fit semantics, class preservation,
persistence round-trips."""

import numpy as np
import pytest

from consensus_entropy_tpu.config import NUM_CLASSES
from consensus_entropy_tpu.models.sklearn_members import (
    HAVE_XGBOOST,
    BoostedTreesMember,
    GNBMember,
    SGDMember,
    make_boosted_member,
)


def _data(rng, n=200, f=12):
    X = rng.standard_normal((n, f))
    centers = rng.standard_normal((NUM_CLASSES, f)) * 3
    y = rng.integers(0, NUM_CLASSES, size=n)
    X += centers[y]
    return X.astype(np.float32), y


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_fit_predict_proba(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X, y)
    p = m.predict_proba(X)
    assert p.shape == (len(X), NUM_CLASSES)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)
    assert (m.predict(X) == y).mean() > 0.8


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_partial_fit_update(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X[:150], y[:150])
    m.update(X[150:], y[150:])  # amg_test.py:509
    assert m.predict_proba(X[:5]).shape == (5, NUM_CLASSES)


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_update_with_missing_classes_keeps_4_columns(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X, y)
    sel = y == 0  # a query batch containing only class 0
    m.update(X[sel][:5], y[sel][:5])
    p = m.predict_proba(X[:10])
    assert p.shape == (10, NUM_CLASSES)
    np.testing.assert_array_equal(m.estimator.classes_, np.arange(4))


def test_boosted_fallback_class_preservation(rng):
    X, y = _data(rng)
    m = BoostedTreesMember(n_estimators=10, update_estimators=5, seed=0)
    m.fit(X, y)
    n0 = m.estimator.n_estimators_
    sel = y == 2
    m.update(X[sel][:6], y[sel][:6])  # single-class batch, like the AL loop
    assert m.estimator.n_estimators_ > n0  # boosting continued
    p = m.predict_proba(X[:7])
    assert p.shape == (7, NUM_CLASSES)
    np.testing.assert_array_equal(m.estimator.classes_, np.arange(4))


def test_make_boosted_member_gating():
    from consensus_entropy_tpu.models.gbdt import NativeGBDTMember

    m = make_boosted_member(seed=0)
    if HAVE_XGBOOST:
        assert type(m).__name__ == "XGBMember"
    else:  # first-party GBDT beats the anchor-row approximation
        assert isinstance(m, NativeGBDTMember)
    assert m.kind == "xgb"
    assert isinstance(make_boosted_member(seed=0, impl="sklearn"),
                      BoostedTreesMember)
    assert isinstance(make_boosted_member(seed=0, impl="native"),
                      NativeGBDTMember)
    with pytest.raises(ValueError):
        make_boosted_member(impl="nope")


@pytest.mark.parametrize("factory", [
    lambda: GNBMember(), lambda: SGDMember(seed=1),
    lambda: BoostedTreesMember(n_estimators=5, seed=1)])
def test_save_load_roundtrip(factory, rng, tmp_path):
    X, y = _data(rng, n=80)
    m = factory().fit(X, y)
    path = str(tmp_path / "m.pkl")
    m.save(path)
    m2 = type(m).load(path)
    np.testing.assert_allclose(m2.predict_proba(X[:9]),
                               m.predict_proba(X[:9]), rtol=1e-6)
    m2.update(X[:10], y[:10])  # loaded member must still be updatable


def test_generic_member_roundtrip_and_frozen_update(rng, tmp_path):
    """rf/svc/... registry members: pickle round-trip preserves `kind`, and
    `update` is a no-op (the reference's AL dispatch, amg_test.py:503-509,
    leaves non-xgb/gnb/sgd members frozen rather than crashing)."""
    from sklearn.ensemble import RandomForestClassifier

    from consensus_entropy_tpu.models.sklearn_members import (
        GenericSklearnMember,
    )

    X, y = _data(rng)
    m = GenericSklearnMember("it_0", "rf",
                             RandomForestClassifier(n_estimators=5,
                                                    random_state=0))
    m.fit(X, y)
    before = m.predict_proba(X[:8])
    m.update(X[:4], y[:4])  # must not raise, must not change the model
    np.testing.assert_array_equal(before, m.predict_proba(X[:8]))

    path = str(tmp_path / "classifier_rf.it_0.pkl")
    m.save(path)
    m2 = GenericSklearnMember.load(path)
    assert m2.kind == "rf" and m2.name == "it_0"
    np.testing.assert_array_equal(before, m2.predict_proba(X[:8]))


def test_workspace_loads_generic_members(rng, tmp_path):
    """load_committee dispatches unknown kinds to GenericSklearnMember
    instead of the boosted-trees loader (which KeyErrors on their pickles)."""
    from sklearn.neighbors import KNeighborsClassifier

    from consensus_entropy_tpu.al.workspace import load_committee
    from consensus_entropy_tpu.models.sklearn_members import (
        GenericSklearnMember,
    )

    X, y = _data(rng)
    GNBMember("it_0").fit(X, y).save(str(tmp_path / "classifier_gnb.it_0.pkl"))
    GenericSklearnMember("it_0", "knn", KNeighborsClassifier(3)).fit(
        X, y).save(str(tmp_path / "classifier_knn.it_0.pkl"))
    committee = load_committee(str(tmp_path))
    kinds = sorted(m.kind for m in committee.host_members)
    assert kinds == ["gnb", "knn"]
    committee.update_host(X[:4], y[:4])  # knn stays frozen, gnb partial_fits


def test_grouped_folds_default_test_size():
    """Reference parity: GroupShuffleSplit with test_size unset holds out 20%
    of the groups (deam_classifier.py:199)."""
    from consensus_entropy_tpu.train.pretrain import grouped_folds

    song_ids = np.repeat(np.arange(50), 3)
    rng_ = np.random.default_rng(0)
    for tr, te in grouped_folds(song_ids, 3, rng_):
        test_songs = np.unique(song_ids[te])
        assert len(test_songs) == 10  # 20% of 50 groups
        assert not set(test_songs) & set(np.unique(song_ids[tr]))


# -- boosted-member contract, both paths (VERDICT r1 #5) -------------------
# Reference patch semantics (/root/reference/xgboost/sklearn.py:854-860,
# applied at :911-927): when a booster is passed to fit, classes_ and the
# multi:softprob objective are NOT recomputed, so the 4-class model survives
# query batches lacking classes.  The same contract table runs against the
# xgboost member (skip-marked where xgboost is absent) and the sklearn
# fallback.

def _xgb_factory():
    from consensus_entropy_tpu.models.sklearn_members import XGBMember

    return XGBMember(n_estimators=10, seed=0)


def _native_factory():
    from consensus_entropy_tpu.models.gbdt import NativeGBDTMember

    return NativeGBDTMember(n_estimators=10, update_estimators=5)


BOOSTED_FACTORIES = [
    pytest.param(lambda: BoostedTreesMember(n_estimators=10,
                                            update_estimators=5, seed=0),
                 id="fallback"),
    pytest.param(_native_factory, id="native"),
    pytest.param(_xgb_factory, id="xgboost",
                 marks=pytest.mark.skipif(not HAVE_XGBOOST,
                                          reason="xgboost not installed")),
]


@pytest.mark.parametrize("factory", BOOSTED_FACTORIES)
def test_boosted_contract_survives_deficient_batches(factory, rng):
    """Successive class-deficient updates (incl. single-class, as AL query
    batches are) keep the full 4-column softprob contract."""
    X, y = _data(rng)
    m = factory().fit(X, y)
    for cls_set in ([0], [1, 2], [3]):
        sel = np.isin(y, cls_set)
        m.update(X[sel][:8], y[sel][:8])
        p = m.predict_proba(X[:16])
        assert p.shape == (16, NUM_CLASSES)
        np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-4)
        assert (p > 0).all()  # every class still carries probability mass


@pytest.mark.parametrize("factory", BOOSTED_FACTORIES)
def test_boosted_contract_update_continues_not_refits(factory, rng):
    """update() must CONTINUE boosting (predictions change) without
    forgetting classes absent from the batch (held-out accuracy on those
    classes stays above chance)."""
    X, y = _data(rng, n=400)
    m = factory().fit(X[:300], y[:300])
    before = m.predict_proba(X[300:])
    sel = y[:300] == 0
    for _ in range(3):
        m.update(X[:300][sel][:10], y[:300][sel][:10])
    after = m.predict_proba(X[300:])
    assert not np.allclose(before, after)
    held = y[300:] != 0
    acc = (after[held].argmax(axis=1) == y[300:][held]).mean()
    assert acc > 0.3, acc  # classes outside the batch are not forgotten


@pytest.mark.parametrize("factory", BOOSTED_FACTORIES)
def test_boosted_contract_roundtrip_then_update(factory, rng, tmp_path):
    """save/load preserves predictions AND the ability to keep boosting
    class-deficient batches (the reference persists members per iteration,
    amg_test.py:511)."""
    X, y = _data(rng)
    m = factory().fit(X, y)
    path = str(tmp_path / "m.pkl")
    m.save(path)
    m2 = type(m).load(path)
    np.testing.assert_allclose(m.predict_proba(X[:9]),
                               m2.predict_proba(X[:9]), rtol=1e-6)
    sel = y == 1
    m2.update(X[sel][:5], y[sel][:5])
    p = m2.predict_proba(X[:9])
    assert p.shape == (9, NUM_CLASSES) and (p > 0).all()


def test_fallback_anchor_row_approximation_pinned(rng):
    """Pin the fallback's documented approximation: class-deficient batches
    are padded with ONE remembered anchor row per missing class, and the
    anchor memory refreshes from the latest batch containing the class."""
    X, y = _data(rng)
    m = BoostedTreesMember(n_estimators=5, update_estimators=5, seed=0)
    m.fit(X, y)
    assert sorted(m._class_rows) == [0, 1, 2, 3]
    Xm, ym = m._anchor_rows(np.array([1, 3]))
    assert Xm.shape == (2, X.shape[1]) and list(ym) == [1, 3]
    np.testing.assert_array_equal(Xm[0], X[y == 1][0])
    # anchors refresh: a later batch containing class 2 replaces its anchor
    Xb = (X[y == 2][:3] + 100.0).astype(np.float32)
    m.update(Xb, np.full(3, 2))
    np.testing.assert_array_equal(m._class_rows[2], Xb[0])


# -- make_boosted_member differential vs the first-party GBDT --------------
# VERDICT r4 #7: xgboost is NOT installable in this image (no pip installs;
# no wheels vendored), so the actual-xgboost wrapper path
# (XGBMember, mirroring /root/reference/xgboost/sklearn.py:854-860) can
# only run its contract table elsewhere (the skipif params above activate
# automatically in any image that has xgboost).  What CAN be pinned here is
# the DIFFERENTIAL between whatever make_boosted_member resolves to and the
# first-party NativeGBDTMember on an identical fit+update sequence — in an
# xgboost image this becomes the real xgboost-vs-first-party comparison
# with no test changes.


def _identical_sequence(member, X, y, rng):
    """fit + 3 class-deficient updates + 1 full-class update, fixed order."""
    member.fit(X[:150], y[:150])
    for cls_set in ([0], [2], [1, 3]):
        sel = np.isin(y[:150], cls_set)
        member.update(X[:150][sel][:8], y[:150][sel][:8])
    member.update(X[150:170], y[150:170])
    return member.predict_proba(X[170:])


def test_boosted_slot_tracks_first_party_gbdt(rng):
    """make_boosted_member('auto') and the first-party GBDT, driven through
    the identical continued-boosting sequence, must agree on the large
    majority of held-out argmax decisions (exact when auto resolves to the
    first-party impl; a real cross-library differential when xgboost is
    present)."""
    from consensus_entropy_tpu.models.gbdt import NativeGBDTMember

    X, y = _data(rng, n=220)
    p_auto = _identical_sequence(
        make_boosted_member("xgb", seed=0), X, y, rng)
    p_native = _identical_sequence(
        NativeGBDTMember("xgb", seed=0), X, y, rng)
    assert p_auto.shape == p_native.shape == (50, NUM_CLASSES)
    agree = (p_auto.argmax(axis=1) == p_native.argmax(axis=1)).mean()
    assert agree >= 0.9, agree
    # the sklearn anchor-row approximation is the loosest impl; even it
    # must stay decision-compatible on a separable task
    p_skl = _identical_sequence(
        BoostedTreesMember(n_estimators=50, update_estimators=10, seed=0),
        X, y, rng)
    agree_skl = (p_skl.argmax(axis=1) == p_native.argmax(axis=1)).mean()
    assert agree_skl >= 0.8, agree_skl


def test_boosted_impl_resolution_matches_image():
    """Document the environment: impl='auto' must resolve to the
    first-party GBDT exactly when xgboost is absent (this image), and to
    the true-warm-start xgboost wrapper when present."""
    from consensus_entropy_tpu.models.gbdt import NativeGBDTMember
    from consensus_entropy_tpu.models.sklearn_members import XGBMember

    m = make_boosted_member("xgb", seed=0)
    if HAVE_XGBOOST:
        assert isinstance(m, XGBMember)
    else:
        assert isinstance(m, NativeGBDTMember)
        with pytest.raises(ImportError, match="xgboost"):
            XGBMember("xgb")
