"""Host committee members: partial_fit semantics, class preservation,
persistence round-trips."""

import numpy as np
import pytest

from consensus_entropy_tpu.config import NUM_CLASSES
from consensus_entropy_tpu.models.sklearn_members import (
    HAVE_XGBOOST,
    BoostedTreesMember,
    GNBMember,
    SGDMember,
    make_boosted_member,
)


def _data(rng, n=200, f=12):
    X = rng.standard_normal((n, f))
    centers = rng.standard_normal((NUM_CLASSES, f)) * 3
    y = rng.integers(0, NUM_CLASSES, size=n)
    X += centers[y]
    return X.astype(np.float32), y


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_fit_predict_proba(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X, y)
    p = m.predict_proba(X)
    assert p.shape == (len(X), NUM_CLASSES)
    np.testing.assert_allclose(p.sum(axis=1), 1.0, atol=1e-5)
    assert (m.predict(X) == y).mean() > 0.8


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_partial_fit_update(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X[:150], y[:150])
    m.update(X[150:], y[150:])  # amg_test.py:509
    assert m.predict_proba(X[:5]).shape == (5, NUM_CLASSES)


@pytest.mark.parametrize("cls", [GNBMember, SGDMember])
def test_update_with_missing_classes_keeps_4_columns(cls, rng):
    X, y = _data(rng)
    m = cls().fit(X, y)
    sel = y == 0  # a query batch containing only class 0
    m.update(X[sel][:5], y[sel][:5])
    p = m.predict_proba(X[:10])
    assert p.shape == (10, NUM_CLASSES)
    np.testing.assert_array_equal(m.estimator.classes_, np.arange(4))


def test_boosted_fallback_class_preservation(rng):
    X, y = _data(rng)
    m = BoostedTreesMember(n_estimators=10, update_estimators=5, seed=0)
    m.fit(X, y)
    n0 = m.estimator.n_estimators_
    sel = y == 2
    m.update(X[sel][:6], y[sel][:6])  # single-class batch, like the AL loop
    assert m.estimator.n_estimators_ > n0  # boosting continued
    p = m.predict_proba(X[:7])
    assert p.shape == (7, NUM_CLASSES)
    np.testing.assert_array_equal(m.estimator.classes_, np.arange(4))


def test_make_boosted_member_gating():
    m = make_boosted_member(seed=0)
    if HAVE_XGBOOST:
        assert type(m).__name__ == "XGBMember"
    else:
        assert isinstance(m, BoostedTreesMember)
    assert m.kind == "xgb"


@pytest.mark.parametrize("factory", [
    lambda: GNBMember(), lambda: SGDMember(seed=1),
    lambda: BoostedTreesMember(n_estimators=5, seed=1)])
def test_save_load_roundtrip(factory, rng, tmp_path):
    X, y = _data(rng, n=80)
    m = factory().fit(X, y)
    path = str(tmp_path / "m.pkl")
    m.save(path)
    m2 = type(m).load(path)
    np.testing.assert_allclose(m2.predict_proba(X[:9]),
                               m.predict_proba(X[:9]), rtol=1e-6)
    m2.update(X[:10], y[:10])  # loaded member must still be updatable


def test_generic_member_roundtrip_and_frozen_update(rng, tmp_path):
    """rf/svc/... registry members: pickle round-trip preserves `kind`, and
    `update` is a no-op (the reference's AL dispatch, amg_test.py:503-509,
    leaves non-xgb/gnb/sgd members frozen rather than crashing)."""
    from sklearn.ensemble import RandomForestClassifier

    from consensus_entropy_tpu.models.sklearn_members import (
        GenericSklearnMember,
    )

    X, y = _data(rng)
    m = GenericSklearnMember("it_0", "rf",
                             RandomForestClassifier(n_estimators=5,
                                                    random_state=0))
    m.fit(X, y)
    before = m.predict_proba(X[:8])
    m.update(X[:4], y[:4])  # must not raise, must not change the model
    np.testing.assert_array_equal(before, m.predict_proba(X[:8]))

    path = str(tmp_path / "classifier_rf.it_0.pkl")
    m.save(path)
    m2 = GenericSklearnMember.load(path)
    assert m2.kind == "rf" and m2.name == "it_0"
    np.testing.assert_array_equal(before, m2.predict_proba(X[:8]))


def test_workspace_loads_generic_members(rng, tmp_path):
    """load_committee dispatches unknown kinds to GenericSklearnMember
    instead of the boosted-trees loader (which KeyErrors on their pickles)."""
    from sklearn.neighbors import KNeighborsClassifier

    from consensus_entropy_tpu.al.workspace import load_committee
    from consensus_entropy_tpu.models.sklearn_members import (
        GenericSklearnMember,
    )

    X, y = _data(rng)
    GNBMember("it_0").fit(X, y).save(str(tmp_path / "classifier_gnb.it_0.pkl"))
    GenericSklearnMember("it_0", "knn", KNeighborsClassifier(3)).fit(
        X, y).save(str(tmp_path / "classifier_knn.it_0.pkl"))
    committee = load_committee(str(tmp_path))
    kinds = sorted(m.kind for m in committee.host_members)
    assert kinds == ["gnb", "knn"]
    committee.update_host(X[:4], y[:4])  # knn stays frozen, gnb partial_fits


def test_grouped_folds_default_test_size():
    """Reference parity: GroupShuffleSplit with test_size unset holds out 20%
    of the groups (deam_classifier.py:199)."""
    from consensus_entropy_tpu.train.pretrain import grouped_folds

    song_ids = np.repeat(np.arange(50), 3)
    rng_ = np.random.default_rng(0)
    for tr, te in grouped_folds(song_ids, 3, rng_):
        test_songs = np.unique(song_ids[te])
        assert len(test_songs) == 10  # 20% of 50 groups
        assert not set(test_songs) & set(np.unique(song_ids[tr]))
