"""The live introspection plane (ISSUE 15): control-plane trace lane,
status snapshots, SLO burn-rate alerts, jit-compile telemetry, and the
``--drain-host`` operator command.

Tier-1 keeps the pure-host units (status atomic-rename/torn-read,
alert-threshold kernels with injected inputs, edge-triggered watcher,
``cetpu-top`` rendering, control-span id dedupe, ``planner_timeline``'s
journal-epoch leg, config validation) plus three deterministic drills:
the traced fake-fleet DRAIN drill (ctl.drain → ctl.fence → ctl.migrate
→ ctl.drain_done spans in the control lane, flow-linked to the migrated
user, continuity across a coordinator SIGKILL+replay), the operator
``--drain-host`` fake-fleet drill (same journaled machinery, operator
initiated), and a 2-user serve smoke pinning compile-event family
determinism across a serve restart.  The live 2-host subprocess leg
runs in ``scripts/obs_check.sh``.
"""

import json
import os

import pytest

from consensus_entropy_tpu.obs import alerts as alerts_mod
from consensus_entropy_tpu.obs import export, jit_telemetry
from consensus_entropy_tpu.obs.status import (
    StatusWriter,
    read_status,
    read_status_dir,
    status_path,
    validate_status,
)
from consensus_entropy_tpu.obs.trace import Tracer
from consensus_entropy_tpu.resilience.faults import InjectedKill
from consensus_entropy_tpu.serve import AdmissionJournal, FabricConfig
from tests.test_elastic import _drain_script, _fake_fleet

pytestmark = [pytest.mark.obs, pytest.mark.serve]


# -- status snapshots ------------------------------------------------------


class _Clock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t


def test_status_writer_atomic_rename_and_rate_limit(tmp_path):
    clock = _Clock()
    w = StatusWriter(str(tmp_path), "h0", interval_s=1.0, clock=clock)
    built = []

    def build():
        built.append(1)
        return {"live": 2, "queued": {"batch": 1}}

    assert w.maybe_write(build) is True
    snap = read_status(status_path(str(tmp_path), "h0"))
    assert snap["host"] == "h0" and snap["live"] == 2
    assert snap["t"] == 100.0 and snap["kind"] == "status"
    assert validate_status(snap) == []
    # inside the interval: no write, and build() not even called
    assert w.maybe_write(build) is False
    assert len(built) == 1
    clock.t += 1.5
    assert w.maybe_write(build) is True
    assert len(built) == 2
    # no .tmp litter (the rename completed)
    assert not os.path.exists(status_path(str(tmp_path), "h0") + ".tmp")


def test_status_maybe_write_is_best_effort(tmp_path):
    """The introspection plane must never take down the loop it
    observes: a failing payload builder (or a failing filesystem) is
    swallowed and counted, and the writer backs off to its interval
    instead of retrying at poll rate."""
    clock = _Clock()
    w = StatusWriter(str(tmp_path), "h0", interval_s=1.0, clock=clock)

    def boom():
        raise OSError("disk full")

    assert w.maybe_write(boom) is False
    assert w.errors == 1 and w.writes == 0
    assert w.maybe_write(boom) is False  # inside the backoff interval
    assert w.errors == 1
    clock.t += 1.5
    assert w.maybe_write(lambda: {"live": 1}) is True
    assert w.writes == 1
    # write() itself still raises (unit-test/diagnostic surface)
    with pytest.raises(TypeError):
        w.write(object())


def test_status_reader_tolerates_torn_and_foreign_files(tmp_path):
    StatusWriter(str(tmp_path), "h0", clock=_Clock()).write({"live": 1})
    # a torn copy (half a JSON object) and a non-dict file
    (tmp_path / "status_h1.json").write_text('{"kind": "status", "ho')
    (tmp_path / "status_h2.json").write_text("[1, 2, 3]")
    assert read_status(str(tmp_path / "status_h1.json")) is None
    assert read_status(str(tmp_path / "status_h2.json")) is None
    snaps = read_status_dir(str(tmp_path))
    assert list(snaps) == ["h0"]
    # schema-floor violations are named
    assert validate_status({"kind": "status", "host": "h0"})
    assert validate_status({"schema": 1, "kind": "status", "host": "h0",
                            "t": "late"})
    assert validate_status({"schema": 1, "kind": "status", "host": "h0",
                            "t": 1.0, "alerts": [{"no_kind": 1}]})


# -- alert kernels + watcher -----------------------------------------------


def test_alert_kernels_threshold_tables():
    slo = {"interactive": 60.0, "batch": 600.0}
    # below the burn fraction: quiet
    assert alerts_mod.slo_headroom_alerts(
        {"interactive": 40.0}, slo) == []
    fired = alerts_mod.slo_headroom_alerts(
        {"interactive": 50.0, "batch": 10.0}, slo)
    assert [a["cls"] for a in fired] == ["interactive"]
    assert fired[0]["kind"] == "slo_headroom" and fired[0]["burn"] > 0.8
    # unknown class target / None p95: quiet
    assert alerts_mod.slo_headroom_alerts({"vip": 99.0}, slo) == []
    assert alerts_mod.slo_headroom_alerts({"batch": None}, slo) == []

    assert alerts_mod.batch_aging_alerts({"batch": 31.0}, 0.0) == []
    assert alerts_mod.batch_aging_alerts({"batch": 29.0}, 30.0) == []
    assert alerts_mod.batch_aging_alerts(
        {"interactive": 99.0}, 30.0) == []  # the top class never ages
    fired = alerts_mod.batch_aging_alerts({"batch": 31.0}, 30.0)
    assert fired and fired[0]["kind"] == "batch_aging"

    assert alerts_mod.breaker_alerts(None) == []
    # a CLOSED width with recent failures rides along in
    # DispatchBreaker.summary() — it must NOT alert (stacked dispatch
    # is intact)
    fired = alerts_mod.breaker_alerts({512: "open", 64: "gave_up",
                                       128: "closed"})
    assert [(a["width"], a["state"]) for a in fired] \
        == [(64, "gave_up"), (512, "open")]

    assert alerts_mod.lease_alerts({"h0": None}, 5.0) == []
    assert alerts_mod.lease_alerts({"h0": 1.0}, 5.0) == []
    fired = alerts_mod.lease_alerts({"h0": 4.5, "h1": 0.1}, 5.0)
    assert [a["host"] for a in fired] == ["h0"]
    assert fired[0]["kind"] == "lease_expiry"


def test_alert_watcher_edge_triggers_and_schema(tmp_path):
    from consensus_entropy_tpu.fleet.report import FleetReport

    path = str(tmp_path / "fleet_metrics.jsonl")
    report = FleetReport(path)
    logged = []
    w = alerts_mod.AlertWatcher(report, log=logged.append)
    a = {"kind": "breaker_open", "key": "512", "width": 512,
         "state": "open"}
    assert w.update([a]) == [a]          # rises → fires
    assert w.update([a]) == []           # still active → silent
    assert w.active == [a]
    assert w.update([]) == []            # clears
    assert w.active == []
    assert w.update([a]) == [a]          # re-rises → re-fires
    assert w.fired == 2
    assert logged and "breaker_open" in logged[0]
    report.close()
    recs = export.read_jsonl_tolerant(path)
    alerts = [r for r in recs if r.get("event") == "alert"]
    assert len(alerts) == 2
    assert export.validate_metrics(recs) == []


# -- control-plane trace lane ----------------------------------------------


def test_control_event_ids_deterministic_and_dedupe(tmp_path):
    """The replay contract at unit level: two tracers (two coordinator
    incarnations) emitting the same decision under the same durable key
    produce ONE merged span; different keys stay distinct."""
    p1, p2 = str(tmp_path / "s1.jsonl"), str(tmp_path / "s2.jsonl")
    for path in (p1, p2):
        t = Tracer(path, run_id="mc-7", host="coordinator")
        t.control_event("ctl.fence", key=("h1", 184), flow_user="u3",
                        ok=True, gen=2)
        t.control_event("ctl.drain", key=41, host="h1")
        t.close()
    spans = export.load_spans([p1, p2])
    ctl = [s for s in spans if s.get("ctl")]
    assert sorted(s["name"] for s in ctl) == ["ctl.drain", "ctl.fence"]
    # and a DIFFERENT key forks a different id
    t = Tracer(p1, run_id="mc-7", host="coordinator")
    t.control_event("ctl.fence", key=("h1", 999), flow_user="u3")
    t.close()
    ctl2 = [s for s in export.load_spans([p1, p2]) if s.get("ctl")]
    assert len(ctl2) == 3


def test_chrome_trace_control_lane_and_flow_links():
    t = Tracer(None, run_id="mc-7", host="coordinator")
    t.open_user("u3")
    t.control_event("ctl.migrate", key=("i", "h1", 184), flow_user="u3",
                    host="h0", kind="inflight")
    t.control_event("ctl.spawn", key=7, host="h2")
    t.close_user("u3")
    t.close()
    trace = export.chrome_trace(t.records)
    procs = {e["args"]["name"]: e["pid"] for e in trace["traceEvents"]
             if e.get("name") == "process_name"}
    assert "control-plane" in procs
    ctl_x = [e for e in trace["traceEvents"] if e.get("ph") == "X"
             and e.get("pid") == procs["control-plane"]]
    assert sorted(e["name"] for e in ctl_x) \
        == ["ctl.migrate", "ctl.spawn"]
    starts = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    ends = [e for e in trace["traceEvents"] if e.get("ph") == "f"]
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["id"] == ends[0]["id"]
    assert starts[0]["pid"] == procs["control-plane"]
    assert ends[0]["pid"] == procs["host coordinator"]  # the user lane


def test_traced_drain_drill_control_spans_and_kill_replay(tmp_path):
    """The acceptance drill, fake-fleet shape: a traced elastic
    drain+migrate run lands ctl.drain → ctl.fence → ctl.migrate →
    ctl.drain_done in the control lane with flow links to the migrated
    user; a coordinator SIGKILL mid-drain + replay appends to the same
    span WAL and the merge keeps the pre-kill decisions exactly once."""
    users = [f"u{i}" for i in range(6)]
    pools = {u: (30 if i % 2 == 0 else 100)
             for i, u in enumerate(users)}
    spans_path = str(tmp_path / "spans.jsonl")

    def run(script, subdir):
        cfg = FabricConfig(hosts=2, min_hosts=1, max_hosts=2,
                           scale_down_s=0.05, poll_s=0.01,
                           drain_timeout_s=0.2)
        tracer = Tracer(spans_path, run_id="mc-7", host="coordinator")
        return _fake_fleet(tmp_path / subdir, cfg, users, pools, script,
                           tracer=tracer)

    # -- phase 1: kill the coordinator mid-drain (fences requested) --------
    def kill_mid_drain(rnd, coord, workers):
        _drain_script(rnd, coord, workers)
        if coord._fencing:
            raise InjectedKill("coordinator SIGKILL mid-drain")

    with pytest.raises(InjectedKill):
        run(kill_mid_drain, "run")
    pre_kill = [s for s in export.load_spans([spans_path])
                if s.get("ctl")]
    assert any(s["name"] == "ctl.drain" for s in pre_kill)

    # -- phase 2: replay the SAME journal dir to completion ----------------
    summary, coord, workers, fabric_dir = run(_drain_script, "run")
    assert sorted(summary["finished"]) == users
    spans = export.load_spans([spans_path])
    ctl = [s for s in spans if s.get("ctl")]
    names = {s["name"] for s in ctl}
    # the drain decision came from incarnation 1, the retirement from
    # incarnation 2's startup ledger-close — one merged timeline
    assert {"ctl.drain", "ctl.drain_done"} <= names
    drains = [s for s in ctl if s["name"] == "ctl.drain"]
    assert len(drains) == 1  # pre-kill decision survived, deduped
    assert any(s["name"] == "ctl.drain_done" and s.get("startup")
               for s in ctl)
    # every span id is unique post-merge (the dedupe invariant)
    ids = [(s["trace"], s["span"]) for s in spans]
    assert len(ids) == len(set(ids))
    assert export.validate_metrics([]) == []  # smoke: import path sane

    # -- phase 3: a clean, UNKILLED drill shows the full chain + flows -----
    summary2, _c, _w, _f = run(_drain_script, "clean")
    assert sorted(summary2["finished"]) == users
    assert summary2["drains"] == 1 and summary2["fences"] >= 1
    spans2 = export.load_spans([spans_path])
    ctl2 = [s for s in spans2 if s.get("ctl")]
    names2 = {s["name"] for s in ctl2}
    assert {"ctl.drain", "ctl.fence", "ctl.migrate",
            "ctl.drain_done"} <= names2
    migrated = [s for s in ctl2 if s["name"] == "ctl.migrate"]
    assert any(s.get("kind") == "inflight" for s in migrated)
    assert all(s.get("flow_user") for s in migrated)
    # user root spans for the flow targets (the serve layer writes them
    # in production; the drill emits them through the same tracer)
    t = Tracer(spans_path, run_id="mc-7", host="coordinator")
    for s in migrated:
        t.open_user(s["flow_user"])
        t.close_user(s["flow_user"])
    t.close()
    trace = export.chrome_trace(export.load_spans([spans_path]))
    starts = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    ends = {e["id"] for e in trace["traceEvents"] if e.get("ph") == "f"}
    assert starts and all(e["id"] in ends for e in starts)
    json.dumps(trace)  # export loads


# -- the operator drain command --------------------------------------------


def test_drain_host_requires_elastic():
    with pytest.raises(ValueError, match="drain_host requires"):
        FabricConfig(hosts=2, drain_host="h1")


def test_operator_drain_host_drill(tmp_path):
    """``--drain-host h1``: the named host drains through exactly the
    journaled scale-down machinery — no low-water mark involved — and
    retires with ``drain_done``; its in-flight user migrates via the
    fence."""
    users = [f"u{i}" for i in range(6)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=2, min_hosts=1, max_hosts=2,
                       drain_host="h1", poll_s=0.01,
                       drain_timeout_s=0.2)
    summary, coord, workers, fabric_dir = _fake_fleet(
        tmp_path, cfg, users, pools, _drain_script)
    assert sorted(summary["finished"]) == users
    assert summary["drains"] == 1
    assert summary["hosts"]["h1"] == "drained"
    jp = os.path.join(fabric_dir, "serve_journal.jsonl")
    st = AdmissionJournal(jp).state
    assert st.hosts["h1"] == "drain_done"
    assert st.fleet_hosts() == ["h0"]
    # the one-shot latch: the drill ended with the drain spent
    assert coord._operator_drained
    # the drain event carries the operator reason
    drains = [e for e in coord.report.events
              if e.get("event") == "host_drain"]
    assert drains and drains[0]["reason"] == "operator"


def test_operator_drain_host_unserviced_is_surfaced(tmp_path):
    """A typo'd --drain-host (the host never exists) must not read as a
    successful drain: the run completes, but the summary and the event
    stream carry the unserviced command."""
    users = [f"u{i}" for i in range(4)]
    pools = {u: 30 for u in users}
    cfg = FabricConfig(hosts=2, min_hosts=1, max_hosts=2,
                       drain_host="h9", poll_s=0.01,
                       drain_timeout_s=0.2)

    def script(rnd, coord, workers):
        if rnd > 2:
            for w in workers.values():
                for uid in list(w.admitted):
                    w.finish(uid)
                for uid in list(w.queued):
                    w.admit(uid)

    summary, coord, workers, _f = _fake_fleet(
        tmp_path, cfg, users, pools, script)
    assert sorted(summary["finished"]) == users
    assert summary["drains"] == 0
    assert summary["drain_host_unserviced"] == "h9"
    assert any(e.get("event") == "drain" and "never serviced"
               in (e.get("reason") or "")
               for e in coord.report.events)


# -- planner_timeline: the coordinator-epoch leg (report bugfix) -----------


def test_planner_timeline_includes_journal_epochs(tmp_path):
    users_dir = tmp_path / "users"
    users_dir.mkdir()
    journal = AdmissionJournal(str(users_dir / "serve_journal.jsonl"))
    journal.append("enqueue", "u1", pool=40)
    journal.append("planner", edges=[64, 128],
                   sketch={"n": 9, "buckets": {}})
    journal.append("planner", edges=[64, 256],
                   sketch={"n": 17, "buckets": {}}, fleet=True)
    journal.close()
    (users_dir / "fleet_metrics_h0.jsonl").write_text(json.dumps(
        {"schema": 2, "event": "fleet_edges", "t_s": 1.0,
         "edges": [64, 256], "observations": 17}) + "\n")
    timeline = export.planner_timeline(str(users_dir))
    assert [e["edges"] for e in timeline["journal_epochs"]] \
        == [[64, 128], [64, 256]]
    assert timeline["journal_epochs"][0]["observations"] == 9
    assert timeline["journal_epochs"][1]["fleet"] is True
    assert timeline["per_host"]["h0"]["fleet_edges"][0]["edges"] \
        == [64, 256]
    text = export.text_report(str(users_dir))
    assert "journal planner epochs" in text
    assert "fleet edges adopted [h0]" in text


# -- jit-compile telemetry -------------------------------------------------


def test_jit_telemetry_counters_and_events():
    from consensus_entropy_tpu.ops import scoring

    events = []
    jit_telemetry.subscribe(events.append)
    try:
        # a distinctive family key no other test builds
        scoring.fleet_scoring_fns_for_width(k=3, tie_break="numpy",
                                            width=48)
        scoring.fleet_scoring_fns_for_width(k=3, tie_break="numpy",
                                            width=48)
    finally:
        jit_telemetry.unsubscribe(events.append)
    snap = jit_telemetry.snapshot()
    fam = snap["per_family"]["fleet:k3:numpy@w48"]
    assert fam["builds"] == 1 and fam["lookups"] >= 2
    assert fam["hits"] == fam["lookups"] - 1
    builds = [e for e in events if e.get("phase") == "build"]
    assert len(builds) == 1
    assert builds[0]["fn"] == "fleet:k3:numpy" \
        and builds[0]["width"] == 48
    assert builds[0]["build_s"] >= 0.0
    assert jit_telemetry.family_labels().count("fleet:k3:numpy@w48") == 1


def test_compile_events_deterministic_across_serve_restart(tmp_path):
    """The family keys a serve run builds are a pure function of its
    workload geometry: a restarted run (same users, same journal dir)
    re-looks-up the SAME families and — the caches being process-wide —
    builds nothing new.  Compile events land schema-valid in the
    metrics stream."""
    from consensus_entropy_tpu.fleet import (
        FleetReport,
        FleetScheduler,
        FleetUser,
    )
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig
    from tests.fabric_workload import (
        make_cfg,
        make_committee,
        make_data,
    )

    cfg = make_cfg(mode="mc", epochs=2, queries=5)

    def serve_once(tag):
        report = FleetReport(str(tmp_path / f"metrics_{tag}.jsonl"))
        sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                               user_timings=False)
        server = FleetServer(
            sched, ServeConfig(target_live=2),
            journal=AdmissionJournal(str(tmp_path / "journal.jsonl")))
        entries = []
        for i in range(2):
            data = make_data(cfg.seed, f"u{i}", n_songs=30, mode="mc")
            ws = str(tmp_path / tag / f"u{i}")
            os.makedirs(ws)
            entries.append(FleetUser(
                data.user_id, make_committee(data, mode="mc"), data, ws,
                seed=cfg.seed))
        recs = server.serve(iter(entries))
        server.journal.close()
        report.write_summary(cohort=2)
        report.close()
        assert all(r["error"] is None for r in recs)
        evs = export.read_jsonl_tolerant(
            str(tmp_path / f"metrics_{tag}.jsonl"))
        assert export.validate_metrics(evs) == []
        return [e for e in evs if e.get("event") == "compile"]

    first = serve_once("a")
    again = serve_once("b")
    # run 1 built the (k=5) families for this workload's one bucket;
    # the "restart" re-uses every one of them — no new builds, and any
    # events it does emit (xla compiles of new shapes) name the same
    # family set or less
    built_first = {(e["fn"], e.get("width")) for e in first
                   if e.get("phase") == "build"}
    assert ("fleet:k5:fast", 32) in built_first
    assert [e for e in again if e.get("phase") == "build"] == []
    again_fns = {(e["fn"], e.get("width")) for e in again}
    assert again_fns <= {(e["fn"], e.get("width")) for e in first}


# -- cetpu-top -------------------------------------------------------------


def test_cetpu_top_renders_fleet_view(tmp_path, capsys):
    from consensus_entropy_tpu.cli.top import main, render

    clock = _Clock(200.0)
    StatusWriter(str(tmp_path / "status"), "coordinator",
                 clock=clock).write({
                     "hosts": {"h0": {"alive": True, "joined": True,
                                      "draining": False,
                                      "lease_age_s": 0.4, "load": 3},
                               "h1": {"alive": True, "joined": True,
                                      "draining": True,
                                      "lease_age_s": 1.2, "load": 1}},
                     "unresolved": 4, "queued": 2, "in_flight": 2,
                     "spawns": 2, "joins": 2, "migrations": 1,
                     "fences": 1, "drains": 1, "revocations": 0,
                     "draining_host": "h1", "edges": [64, 128],
                     "alerts": [{"kind": "lease_expiry", "key": "h1",
                                 "host": "h1", "age_s": 4.2,
                                 "lease_s": 5.0}]})
    StatusWriter(str(tmp_path / "status"), "h0", clock=clock).write({
        "queued": {"interactive": 1, "batch": 1}, "queue_total": 2,
        "live": 2, "live_cls": {"batch": 2}, "target_live": 2,
        "draining": False, "intake_open": True, "fences_pending": 0,
        "requeued": 0, "users_done": 3, "users_failed": 0,
        "planner": {"edges": [64, 128], "observations": 12,
                    "admission_hold_rounds": 1,
                    "dispatch_hold_rounds": 2},
        "buckets": {"64": {"occupancy": 1.0, "mean_batch": 2.0,
                           "dispatches": 7}},
        "jit": {"families": 3, "lookups": 9, "builds": 3, "hits": 6,
                "compiles": 4, "resident": 5}})
    frame = render(read_status_dir(str(tmp_path / "status")),
                   now=200.5)
    assert "[coordinator] fleet" in frame
    assert "h1     draining" in frame
    assert "! lease_expiry" in frame
    assert "[h0] live=2/2" in frame and "edges=[64, 128]" in frame
    assert "STALE" not in frame
    # a stale snapshot flags
    assert "STALE" in render(read_status_dir(str(tmp_path / "status")),
                             now=300.0)
    # the console entry, --once (resolves users_dir -> status/)
    assert main([str(tmp_path), "--once"]) == 0
    assert "[coordinator] fleet" in capsys.readouterr().out
    # empty dir: a calm message, not a crash
    assert main([str(tmp_path / "nowhere"), "--once"]) == 0
