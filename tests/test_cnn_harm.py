"""The harmonic-frontend CNN family (config.arch='harm'): filterbank
geometry, learnable-Q gradients, forward/training, committee vmap, registry.
Reference frontend semantics: the vendored (unused) ``HarmonicSTFT`` at
``/root/reference/short_cnn.py:166-275``."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.ops import harmonic

# semitone_scale=1 halves the note grid (level 64) so tiny inputs survive
# the pooling pyramid
TINY_HARM = CNNConfig(n_channels=4, n_layers=3, input_length=8192,
                      arch="harm", semitone_scale=1)


def test_note_grid_matches_reference_constants():
    """Defaults: C1 (midi 24) to the note whose 6th harmonic hits Nyquist
    (E6 = midi 88 at 16 kHz), 2 steps/semitone -> level 128 — the same
    height as the mel frontend's 128 bands."""
    centers, level = harmonic.harmonic_center_freqs(16000, 6, 2)
    assert level == (88 - 24) * 2 == 128
    assert centers.shape == (6 * 128,)
    # first center is C1; each harmonic block is an integer multiple
    np.testing.assert_allclose(centers[0], 32.7032, rtol=1e-4)
    np.testing.assert_allclose(centers[128], 2 * centers[0], rtol=1e-6)
    assert CNNConfig(arch="harm").harm_level == 128


def test_filterbank_triangles():
    fb = np.asarray(harmonic.harmonic_filterbank(jnp.asarray([1.0])))
    n_freqs = 512 // 2 + 1
    assert fb.shape == (n_freqs, 6 * 128)
    assert (fb >= 0).all() and fb.max() <= 1.0 + 1e-6
    # each band peaks at (or adjacent to) its center frequency bin
    centers, _ = harmonic.harmonic_center_freqs(16000, 6, 2)
    bins = np.linspace(0.0, 8000.0, n_freqs)
    band = 300  # an arbitrary mid-range band
    peak_hz = bins[np.argmax(fb[:, band])]
    bw = (harmonic.BW_ALPHA * centers[band] + harmonic.BW_BETA)
    assert abs(peak_hz - centers[band]) <= max(bw, bins[1] - bins[0])
    # larger Q narrows the bands: fewer nonzero bins per column
    fb_wide = np.asarray(harmonic.harmonic_filterbank(jnp.asarray([0.5])))
    fb_narrow = np.asarray(harmonic.harmonic_filterbank(jnp.asarray([4.0])))
    assert (fb_narrow > 0).sum() < (fb_wide > 0).sum()


def test_harmonic_spectrogram_shape(rng):
    x = rng.standard_normal((2, 4096)).astype(np.float32)
    out = np.asarray(harmonic.harmonic_spectrogram(
        x, jnp.asarray([1.0]), semitone_scale=1))
    from consensus_entropy_tpu.ops.mel import n_frames_for

    assert out.shape == (2, 6, 64, n_frames_for(4096))
    assert np.isfinite(out).all()


def test_harm_forward_and_param(rng):
    v = short_cnn.init_variables(jax.random.key(0), TINY_HARM)
    assert "bw_q" in v["params"]  # learnable frontend Q
    x = rng.standard_normal((3, TINY_HARM.input_length)).astype(np.float32)
    out = np.asarray(short_cnn.apply_infer(v, x, TINY_HARM))
    assert out.shape == (3, 4)
    assert np.isfinite(out).all()


def test_harm_frontend_gets_gradients(rng):
    """The whole point of the learnable frontend: dLoss/d(bw_q) != 0."""
    v = short_cnn.init_variables(jax.random.key(0), TINY_HARM)
    x = rng.standard_normal((4, TINY_HARM.input_length)).astype(np.float32)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 4)]

    def loss(params):
        out, _ = short_cnn.apply_train(
            {"params": params, "batch_stats": v["batch_stats"]}, x,
            jax.random.key(1), TINY_HARM)
        return jnp.mean((out - y) ** 2)

    g = jax.grad(loss)(v["params"])
    assert float(jnp.abs(g["bw_q"]).sum()) > 0.0


def test_harm_committee_vmap_and_trainer(rng):
    from consensus_entropy_tpu.config import TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

    members = [short_cnn.init_variables(jax.random.key(i), TINY_HARM)
               for i in range(2)]
    x = rng.standard_normal((3, TINY_HARM.input_length)).astype(np.float32)
    probs = np.asarray(short_cnn.committee_infer(
        short_cnn.stack_params(members), x, TINY_HARM))
    assert probs.shape == (2, 3, 4)

    waves = {f"s{i}": (rng.standard_normal(9000) * 0.05).astype(np.float32)
             for i in range(8)}
    store = DeviceWaveformStore(waves, TINY_HARM.input_length)
    ids = list(waves)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
    trainer = CNNTrainer(TINY_HARM, TrainConfig(batch_size=4))
    best, hist = trainer.fit(members[0], store, ids[:6], y[:6], ids[6:],
                             y[6:], jax.random.key(1), n_epochs=2)
    assert len(hist) == 2 and np.isfinite(
        [h["val_loss"] for h in hist]).all()
    # training moved the frontend Q (or at least kept it finite/positive)
    assert np.isfinite(np.asarray(best["params"]["bw_q"])).all()


def test_harm_checkpoint_and_registry(rng, tmp_path):
    from consensus_entropy_tpu.models.committee import CNNMember, Committee
    from consensus_entropy_tpu.train.pretrain import MODEL_CHOICES

    assert "cnn_harm_jax" in MODEL_CHOICES
    v = short_cnn.init_variables(jax.random.key(0), TINY_HARM)
    m = CNNMember("it_0", v, TINY_HARM)
    path = str(tmp_path / "classifier_cnn_harm.it_0.msgpack")
    m.save(path)
    # caller config differs in arch AND frontend geometry — the checkpoint
    # meta must win for every frontend-shaping field (a note-grid mismatch
    # restores cleanly but scores with a grid the weights never saw)
    other_cfg = dataclasses.replace(TINY_HARM, arch="vgg", n_mels=64,
                                    semitone_scale=2, n_harmonic=6)
    m2 = CNNMember.load(path, other_cfg)
    assert m2.config.arch == "harm"
    assert m2.config.semitone_scale == TINY_HARM.semitone_scale
    assert m2.config.n_harmonic == TINY_HARM.n_harmonic
    c = Committee([], [m2], other_cfg)
    assert c.config.arch == "harm"
    assert c.config.semitone_scale == TINY_HARM.semitone_scale
