"""The residual CNN family (config.arch='res'): geometry, training, committee
vmap, trainer integration, checkpoint arch round-trip, and the pretrain CLI
registry entry.  Reference block semantics: the vendored (unused) ``Res_2d``
at ``/root/reference/short_cnn.py:40-66``."""

import dataclasses

import jax
import numpy as np
import pytest

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.models import short_cnn

TINY_RES = CNNConfig(n_channels=4, n_mels=32, n_layers=3, input_length=8192,
                     arch="res")


@pytest.fixture(scope="module")
def res_vars():
    return short_cnn.init_variables(jax.random.key(0), TINY_RES)


def test_arch_validation():
    with pytest.raises(ValueError, match="arch"):
        CNNConfig(arch="transformer")


def test_res_geometry_never_collapses():
    # stride-2 convs ceil-halve; even deep stacks on small inputs are valid
    CNNConfig(n_channels=2, n_mels=8, n_layers=7, input_length=4096,
              arch="res")  # must not raise (vgg would collapse here)
    with pytest.raises(ValueError, match="collapses"):
        CNNConfig(n_channels=2, n_mels=8, n_layers=7, input_length=4096)


def test_res_forward_shape_and_range(res_vars, rng):
    x = rng.standard_normal((3, TINY_RES.input_length)).astype(np.float32)
    out = np.asarray(short_cnn.apply_infer(res_vars, x, TINY_RES))
    assert out.shape == (3, 4)
    # sigmoid head; at INIT the residual adds can push f32 sigmoid to
    # saturation (running BN stats haven't adapted), so bounds are closed
    assert np.isfinite(out).all()
    assert (out >= 0).all() and (out <= 1).all()


def test_res_params_differ_from_vgg():
    """The two trunks are distinct parameter trees (projection shortcut
    etc.) while sharing head parameter paths."""
    vgg_cfg = dataclasses.replace(TINY_RES, arch="vgg")
    res_p = short_cnn.init_variables(jax.random.key(0), TINY_RES)["params"]
    vgg_p = short_cnn.init_variables(jax.random.key(0), vgg_cfg)["params"]
    assert "dense1" in res_p and "dense1" in vgg_p  # shared head paths
    res_blocks = [k for k in res_p if k.startswith("ResBlock")]
    assert len(res_blocks) == TINY_RES.n_layers
    assert "conv_proj" in res_p[res_blocks[0]]  # projected shortcut
    assert not any(k.startswith("ResBlock") for k in vgg_p)


def test_res_train_step_and_committee_vmap(res_vars, rng):
    x = rng.standard_normal((4, TINY_RES.input_length)).astype(np.float32)
    out, new_stats = short_cnn.apply_train(
        res_vars, x, jax.random.key(1), TINY_RES)
    assert out.shape == (4, 4)
    assert any(not np.allclose(a, b) for a, b in zip(
        jax.tree.leaves(res_vars["batch_stats"]),
        jax.tree.leaves(new_stats)))
    members = [short_cnn.init_variables(jax.random.key(i), TINY_RES)
               for i in range(3)]
    stacked = short_cnn.stack_params(members)
    probs = np.asarray(short_cnn.committee_infer(stacked, x, TINY_RES))
    assert probs.shape == (3, 4, 4)
    # members differ (independent init) but each matches its solo forward
    np.testing.assert_allclose(
        probs[1], np.asarray(short_cnn.apply_infer(members[1], x, TINY_RES)),
        rtol=1e-5, atol=1e-6)


def test_res_trainer_fit(rng, tmp_path):
    """The shared CNNTrainer trains a res member end to end (jitted epochs,
    best-checkpoint gate) without any family-specific code."""
    from consensus_entropy_tpu.config import TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer

    waves = {f"s{i}": (rng.standard_normal(9000) * 0.05).astype(np.float32)
             for i in range(8)}
    store = DeviceWaveformStore(waves, TINY_RES.input_length)
    ids = list(waves)
    y = np.eye(4, dtype=np.float32)[rng.integers(0, 4, 8)]
    trainer = CNNTrainer(TINY_RES, TrainConfig(batch_size=4))
    v0 = short_cnn.init_variables(jax.random.key(0), TINY_RES)
    best, hist = trainer.fit(v0, store, ids[:6], y[:6], ids[6:], y[6:],
                             jax.random.key(1), n_epochs=2)
    assert len(hist) == 2
    assert np.isfinite([h["val_loss"] for h in hist]).all()


def test_res_member_checkpoint_arch_roundtrip(res_vars, tmp_path):
    """CNNMember checkpoints record their trunk family; load honors it even
    when the caller passes a vgg config, and the committee follows."""
    from consensus_entropy_tpu.models.committee import CNNMember, Committee

    m = CNNMember("it_0", res_vars, TINY_RES)
    path = str(tmp_path / "classifier_cnn.it_0.msgpack")
    m.save(path)
    vgg_cfg = dataclasses.replace(TINY_RES, arch="vgg")
    m2 = CNNMember.load(path, vgg_cfg)
    assert m2.config.arch == "res"
    c = Committee([], [m2], vgg_cfg)
    assert c.config.arch == "res"  # committee config follows the members


def test_committee_rejects_mixed_cnn_families(res_vars):
    from consensus_entropy_tpu.models.committee import CNNMember, Committee

    vgg_cfg = dataclasses.replace(TINY_RES, arch="vgg")
    vgg_vars = short_cnn.init_variables(jax.random.key(1), vgg_cfg)
    with pytest.raises(ValueError, match="trunk families"):
        Committee([], [CNNMember("a", res_vars, TINY_RES),
                       CNNMember("b", vgg_vars, vgg_cfg)], vgg_cfg)


def test_cnn_res_jax_registry_choice():
    from consensus_entropy_tpu.train.pretrain import MODEL_CHOICES

    assert "cnn_res_jax" in MODEL_CHOICES


def test_res_pretrain_artifacts_do_not_clobber_vgg(rng, tmp_path):
    """vgg and res pretrains in one pretrained dir coexist (arch-tagged
    filenames) and the metrics jsonl labels each family."""
    import json
    import os

    from consensus_entropy_tpu.config import TrainConfig
    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.train.pretrain import pretrain_cnn

    waves = {i: (rng.standard_normal(9000) * 0.05).astype(np.float32)
             for i in range(10)}
    labels = {i: i % 4 for i in waves}
    store = DeviceWaveformStore(waves, TINY_RES.input_length)
    out = str(tmp_path)
    vgg_cfg = dataclasses.replace(TINY_RES, arch="vgg")
    pretrain_cnn(labels, store, cv=1, out_dir=out, config=vgg_cfg,
                 train_config=TrainConfig(batch_size=4), n_epochs=1)
    pretrain_cnn(labels, store, cv=1, out_dir=out, config=TINY_RES,
                 train_config=TrainConfig(batch_size=4), n_epochs=1)
    files = sorted(f for f in os.listdir(out) if f.endswith(".msgpack"))
    assert files == ["classifier_cnn.it_0.msgpack",
                     "classifier_cnn_res.it_0.msgpack"]
    rows = [json.loads(l)
            for l in open(os.path.join(out, "pretrain_metrics.jsonl"))]
    assert [r["model"] for r in rows] == ["cnn_jax", "cnn_res_jax"]
