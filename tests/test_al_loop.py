"""End-to-end AL smoke tests on a synthetic pool (SURVEY.md §4c).

The synthetic task is separable, so mc acquisition with partial_fit updates
must lift committee F1 over iterations for the host-only committee.
"""

import os

import numpy as np
import pytest

from consensus_entropy_tpu.al.loop import ALLoop, UserData, grouped_split
from consensus_entropy_tpu.al import workspace
from consensus_entropy_tpu.config import ALConfig
from consensus_entropy_tpu.models.committee import Committee, FramePool
from consensus_entropy_tpu.models.sklearn_members import GNBMember, SGDMember


def _user_data(rng, n_songs=60, frames_per=(4, 9), f=16, uid="u0"):
    centers = rng.standard_normal((4, f)).astype(np.float32) * 2.5
    rows, sids, labels = [], [], {}
    for i in range(n_songs):
        sid = f"song{i:03d}"
        c = int(rng.integers(0, 4))
        labels[sid] = c
        k = int(rng.integers(*frames_per))
        rows.append(centers[c] + rng.standard_normal((k, f)).astype(np.float32))
        sids += [sid] * k
    pool = FramePool(np.vstack(rows), sids)
    counts = rng.integers(1, 30, size=(n_songs, 4))
    hc = np.round(counts / counts.sum(1, keepdims=True), 3).astype(np.float32)
    return UserData(uid, pool, labels, hc_rows=hc)


def _weak_committee(rng, data):
    # deliberately under-trained (one song per class) so AL has headroom
    X, y, picked = [], [], set()
    for s, c in data.labels.items():
        if c in picked:
            continue
        picked.add(c)
        rows = data.pool.rows_for_songs([s])
        X.append(data.pool.X[rows] + rng.standard_normal(
            (len(rows), data.pool.X.shape[1])).astype(np.float32) * 3)
        y += [c] * len(rows)
    X, y = np.vstack(X), np.asarray(y)
    return Committee([GNBMember().fit(X, y), SGDMember(seed=0).fit(X, y)], [])


def test_grouped_split_fractions(rng):
    data = _user_data(rng)
    split = grouped_split(data.pool, data.labels, 0.85,
                          np.random.default_rng(0))
    assert len(split.train_songs) == 51 and len(split.test_songs) == 9
    assert not set(split.train_songs) & set(split.test_songs)
    assert len(split.X_test) == len(split.y_test_frames)
    # frame labels repeat song labels
    assert set(np.unique(split.y_test_frames)) <= {0, 1, 2, 3}


#: hc/mix rows slow-marked: see tests/test_resume.py's matrix note
@pytest.mark.parametrize("mode", [
    "mc",
    pytest.param("hc", marks=pytest.mark.slow),
    pytest.param("mix", marks=pytest.mark.slow),
    "rand",
])
def test_al_loop_all_modes_run(rng, tmp_path, mode):
    data = _user_data(rng)
    com = _weak_committee(rng, data)
    loop = ALLoop(ALConfig(queries=5, epochs=3, mode=mode, seed=11))
    res = loop.run_user(com, data, str(tmp_path))
    assert len(res["trajectory"]) == 4  # epoch0 + 3
    assert os.path.exists(os.path.join(tmp_path, "metrics.jsonl"))
    txts = [f for f in os.listdir(tmp_path) if f.endswith(".txt")]
    assert len(txts) == 1
    body = open(os.path.join(tmp_path, txts[0])).read()
    assert "Summary: F1 mean score over all classifiers" in body
    assert "Epoch 2:" in body


def test_al_improves_on_separable_task(rng, tmp_path):
    data = _user_data(rng, n_songs=80)
    # committee that knows nothing: GNB fit on pure noise with random labels
    Xn = rng.standard_normal((40, data.pool.X.shape[1])).astype(np.float32)
    yn = np.tile(np.arange(4), 10)
    com = Committee([GNBMember().fit(Xn, yn)], [])
    loop = ALLoop(ALConfig(queries=10, epochs=5, mode="mc", seed=5))
    res = loop.run_user(com, data, str(tmp_path))
    traj = res["trajectory"]
    # 50 revealed songs of separable data must lift GNB well above chance
    assert traj[0] < 0.5 and traj[-1] > traj[0] + 0.2, traj


def test_workspace_resume(rng, tmp_path):
    data = _user_data(rng, n_songs=40)
    pre = tmp_path / "pretrained"
    os.makedirs(pre)
    _weak_committee(rng, data).save(str(pre))
    users = str(tmp_path / "users")

    path, skip = workspace.create_user(users, str(pre), "u0", "mc")
    assert not skip
    com = workspace.load_committee(path)
    assert com.size == 2
    loop = ALLoop(ALConfig(queries=5, epochs=2, mode="mc", seed=1))
    loop.run_user(com, data, path)
    com.save(path)
    workspace.mark_done(path)

    # second run skips the completed user (amg_test.py:152-159 semantics)
    _, skip2 = workspace.create_user(users, str(pre), "u0", "mc")
    assert skip2
    # a partially-run user (no DONE marker) is redone from pristine copies
    path_b, skip_b = workspace.create_user(users, str(pre), "u1", "mc")
    open(os.path.join(path_b, "junk.txt"), "w").write("partial")
    path_b2, skip_b2 = workspace.create_user(users, str(pre), "u1", "mc")
    assert not skip_b2
    assert not os.path.exists(os.path.join(path_b2, "junk.txt"))


def test_query_batch_label_alignment(rng):
    # Acquisition returns songs in entropy order; the frame batch must pair
    # each frame with ITS song's label even when that order differs from
    # pool order and frame counts differ per song.
    from consensus_entropy_tpu.al.loop import query_batch
    from consensus_entropy_tpu.models.committee import FramePool

    frame_song = ["a"] * 2 + ["b"] * 3 + ["c"] * 1 + ["d"] * 4
    X = np.arange(len(frame_song), dtype=np.float32)[:, None]
    pool = FramePool(X, frame_song)
    labels = {"a": 0, "b": 1, "c": 2, "d": 3}

    Xb, yb = query_batch(pool, labels, ["d", "b"])  # reversed vs pool order
    assert Xb.shape == (7, 1) and yb.shape == (7,)
    for x_row, y in zip(Xb[:, 0], yb):
        song = frame_song[int(x_row)]
        assert labels[song] == y, (x_row, y)


def test_non_coordinator_runs_lockstep_without_writes(rng, tmp_path,
                                                      monkeypatch):
    """Multi-host discipline: a non-coordinator process executes the full
    AL computation (it must stay in lockstep for collectives) but touches
    no workspace files; the returned trajectory matches the coordinator's
    bit-for-bit (same seed-derived streams)."""
    from consensus_entropy_tpu.parallel import multihost

    data = _user_data(rng, n_songs=30)
    committee = _weak_committee(np.random.default_rng(0), data)
    cfg = ALConfig(queries=4, epochs=2, mode="mc", seed=3)
    coord_dir = str(tmp_path / "coord")
    os.makedirs(coord_dir)
    ALLoop(cfg).run_user(committee, data, coord_dir, seed=3)
    assert os.path.exists(os.path.join(coord_dir, "metrics.jsonl"))

    monkeypatch.setattr(multihost, "is_coordinator", lambda: False)
    # identical inputs: rebuild data/committee with the same generators
    rng2 = np.random.default_rng(12345)
    dataA = _user_data(rng2, n_songs=30)
    committeeA = _weak_committee(np.random.default_rng(0), dataA)
    rng3 = np.random.default_rng(12345)
    dataB = _user_data(rng3, n_songs=30)
    committeeB = _weak_committee(np.random.default_rng(0), dataB)
    nc_dir = str(tmp_path / "nc")
    os.makedirs(nc_dir)
    res_nc = ALLoop(cfg).run_user(committeeB, dataB, nc_dir, seed=3)
    assert os.listdir(nc_dir) == []  # no reports, no state, no checkpoints
    monkeypatch.setattr(multihost, "is_coordinator", lambda: True)
    c_dir = str(tmp_path / "c2")
    os.makedirs(c_dir)
    res_c = ALLoop(cfg).run_user(committeeA, dataA, c_dir, seed=3)
    assert res_nc["trajectory"] == res_c["trajectory"]


def test_user_report_write_false_touches_nothing(tmp_path):
    from consensus_entropy_tpu.al.reporting import UserReport

    with UserReport(str(tmp_path), "mc", write=False) as rep:
        rep.epoch_header(0)
        f1 = rep.model_eval("m", [0, 1, 2, 3], [0, 1, 2, 2])
        rep.epoch_summary(0, [f1], queried=["s1"], pool_size=9)
    assert 0 < f1 < 1
    assert os.listdir(str(tmp_path)) == []
