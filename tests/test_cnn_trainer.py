"""CNN trainer: BCE parity, schedule transitions, learning on synthetic data."""

import jax
import numpy as np
import pytest
import torch

from consensus_entropy_tpu.config import CNNConfig, TrainConfig
from consensus_entropy_tpu.data.audio import DeviceWaveformStore
from consensus_entropy_tpu.labels import one_hot_np
from consensus_entropy_tpu.models import short_cnn
from consensus_entropy_tpu.models.cnn_trainer import CNNTrainer, bce_loss, make_tx

TINY = CNNConfig(n_channels=4, n_mels=32, n_layers=5, input_length=8192)


def test_bce_matches_torch(rng):
    p = rng.uniform(0.01, 0.99, size=(6, 4)).astype(np.float32)
    y = one_hot_np(rng.integers(0, 4, size=6))
    got = float(bce_loss(p, y))
    want = float(torch.nn.BCELoss()(torch.from_numpy(p), torch.from_numpy(y)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_bce_clamps_extremes():
    p = np.array([[0.0, 1.0, 0.5, 0.5]], np.float32)
    y = np.array([[1.0, 0.0, 1.0, 0.0]], np.float32)
    got = float(bce_loss(p, y))
    want = float(torch.nn.BCELoss()(torch.from_numpy(p), torch.from_numpy(y)))
    np.testing.assert_allclose(got, want, rtol=1e-4)  # both clamp log at -100


def test_make_tx_phases():
    cfg = TrainConfig()
    for phase in ("adam", "sgd_1", "sgd_2", "sgd_3"):
        tx = make_tx(phase, cfg)
        assert hasattr(tx, "init") and hasattr(tx, "update")


def _synthetic_pool(rng, n_songs, length_range=(9000, 12000)):
    # class-dependent tones so the task is learnable
    waves, classes = {}, {}
    for i in range(n_songs):
        c = i % 4
        n = int(rng.integers(*length_range))
        t = np.arange(n) / 16000.0
        freq = 400.0 * (c + 1)
        w = np.sin(2 * np.pi * freq * t) + 0.05 * rng.standard_normal(n)
        waves[f"song{i}"] = w.astype(np.float32)
        classes[f"song{i}"] = c
    return waves, classes


def test_fit_learns_and_tracks_best(rng):
    waves, classes = _synthetic_pool(rng, 8)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    variables = short_cnn.init_variables(jax.random.key(0), TINY)
    trainer = CNNTrainer(TINY, TrainConfig(batch_size=4, lr=1e-3))
    best, history = trainer.fit(
        variables, store, ids, y, ids, y, jax.random.key(1),
        n_epochs=12, adam_patience=100)
    assert len(history) == 12
    first, last = history[0]["train_loss"], history[-1]["train_loss"]
    assert last < first  # learning happened
    assert any(h["improved"] for h in history)
    preds = np.asarray(short_cnn.apply_infer(best, store.sample_crops(
        jax.random.key(2), store.row_of(ids)), TINY))
    assert preds.shape == (8, 4)


def test_schedule_transitions_and_best_reload(rng):
    waves, classes = _synthetic_pool(rng, 4)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    variables = short_cnn.init_variables(jax.random.key(0), TINY)
    cfg = TrainConfig(batch_size=4, adam_patience=2, sgd_patience=2)
    trainer = CNNTrainer(TINY, cfg)
    _, history = trainer.fit(variables, store, ids, y, ids, y,
                             jax.random.key(1), n_epochs=9)
    phases = [h["phase"] for h in history]
    # adam for 2 epochs, then sgd_1 ×2, sgd_2 ×2, then sgd_3 stays
    assert phases == ["adam", "adam", "sgd_1", "sgd_1", "sgd_2", "sgd_2",
                      "sgd_3", "sgd_3", "sgd_3"]


def test_pretrain_cnn_writes_tensorboard(tmp_path, rng):
    # Reference parity: Loss/train, Loss/valid scalars per epoch + fold F1
    # (deam_classifier.py:242,314-316), written only when tb_dir is given.
    import glob

    pytest.importorskip("torch.utils.tensorboard")

    import jax

    from consensus_entropy_tpu.data.audio import DeviceWaveformStore
    from consensus_entropy_tpu.train import pretrain

    waves = {i: (rng.standard_normal(TINY.input_length + 500) * 0.05
                 ).astype(np.float32) for i in range(8)}
    labels = {i: i % 4 for i in range(8)}
    store = DeviceWaveformStore(waves, TINY.input_length)
    out = pretrain.pretrain_cnn(
        labels, store, cv=1, out_dir=str(tmp_path / "models"),
        config=TINY, n_epochs=2, seed=0, tb_dir=str(tmp_path / "tb"))
    assert "f1" in out
    events = glob.glob(str(tmp_path / "tb" / "fold_0" / "events.out.*"))
    assert events, "no tensorboard event file written"


def test_fit_with_fewer_songs_than_batch_size(rng):
    # AL query batches can be smaller than TrainConfig.batch_size (q < 5);
    # the reference DataLoader yields a short batch (drop_last=False).
    waves, classes = _synthetic_pool(rng, 3)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    variables = short_cnn.init_variables(jax.random.key(0), TINY)
    trainer = CNNTrainer(TINY, TrainConfig(batch_size=5))
    _, history = trainer.fit(variables, store, ids, y, ids, y,
                             jax.random.key(1), n_epochs=2)
    assert len(history) == 2
    assert np.isfinite(history[-1]["train_loss"])


def test_all_songs_train_when_batch_does_not_divide(rng):
    # q=7 with batch_size=5: drop_last=False parity — every song must get
    # gradient every epoch (padded tail rows carry loss weight 0).
    waves, classes = _synthetic_pool(rng, 7)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    variables = short_cnn.init_variables(jax.random.key(0), TINY)
    trainer = CNNTrainer(TINY, TrainConfig(batch_size=5, lr=1e-3))
    _, history = trainer.fit(variables, store, ids, y, ids, y,
                             jax.random.key(1), n_epochs=3)
    assert all(np.isfinite(h["train_loss"]) for h in history)


def test_zero_retrain_epochs_respected(rng):
    # n_epochs=0 must mean "no training", not fall back to the default.
    waves, classes = _synthetic_pool(rng, 4)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    variables = short_cnn.init_variables(jax.random.key(0), TINY)
    trainer = CNNTrainer(TINY, TrainConfig(batch_size=4))
    best, history = trainer.fit(variables, store, ids, y, ids, y,
                                jax.random.key(1), n_epochs=0)
    assert history == []


# -- vmapped multi-member training (fit_many) ------------------------------


def test_fit_many_matches_sequential(rng):
    """Lockstep vmap over members computes the same training as M separate
    fit loops under the same fold_in key streams (the schedule is
    epoch-indexed, so lockstep is exact up to XLA's batched-op fusion —
    the vmapped conv/reduce kernels reassociate float math, so equality is
    to tolerance, not bitwise)."""
    waves, classes = _synthetic_pool(rng, 6)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    members = [short_cnn.init_variables(jax.random.key(i), TINY)
               for i in range(2)]
    key = jax.random.key(42)

    seq_best, seq_hist = [], []
    trainer_a = CNNTrainer(TINY, TrainConfig(batch_size=3))
    for i, v in enumerate(members):
        # fit donates its input buffers; keep `members` alive for fit_many
        v = jax.tree.map(lambda a: a.copy(), v)
        best, hist = trainer_a.fit(v, store, ids, y, ids[:2], y[:2],
                                   jax.random.fold_in(key, i), n_epochs=3)
        seq_best.append(best)
        seq_hist.append(hist)

    trainer_b = CNNTrainer(TINY, TrainConfig(batch_size=3))
    many_best, many_hist = trainer_b.fit_many(
        members, store, ids, y, ids[:2], y[:2], key, n_epochs=3)

    for m in range(2):
        for a, b in zip(seq_hist[m], many_hist[m]):
            np.testing.assert_allclose(a["val_loss"], b["val_loss"],
                                       rtol=1e-3)
            np.testing.assert_allclose(a["train_loss"], b["train_loss"],
                                       rtol=1e-3)
        # Adam normalizes each step to ~lr, so round-off in a near-zero
        # gradient can flip a step's sign; params therefore agree to the
        # accumulated-step scale (3 epochs x 2 batches x lr=1e-4), not rtol.
        flat_a = jax.tree.leaves(seq_best[m]["params"])
        flat_b = jax.tree.leaves(many_best[m]["params"])
        for la, lb in zip(flat_a, flat_b):
            np.testing.assert_allclose(np.asarray(la), np.asarray(lb),
                                       rtol=0, atol=2e-3)


def test_fit_many_member_sharded_mesh(rng):
    """fit_many over a (dp, member) training mesh: member axis sharded
    across chips, same results as the unsharded vmap."""
    from consensus_entropy_tpu.parallel.mesh import make_training_mesh

    waves, classes = _synthetic_pool(rng, 6)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    members = [short_cnn.init_variables(jax.random.key(i), TINY)
               for i in range(4)]
    key = jax.random.key(7)
    mesh = make_training_mesh(dp=2, member=4)

    plain_best, plain_hist = CNNTrainer(TINY, TrainConfig(batch_size=3)) \
        .fit_many(members, store, ids, y, ids[:2], y[:2], key, n_epochs=2)
    mesh_best, mesh_hist = CNNTrainer(TINY, TrainConfig(batch_size=3)) \
        .fit_many(members, store, ids, y, ids[:2], y[:2], key, n_epochs=2,
                  mesh=mesh)

    for m in range(4):
        for a, b in zip(plain_hist[m], mesh_hist[m]):
            # GSPMD-partitioned kernels reassociate float math; agreement
            # is to tolerance, not bitwise
            np.testing.assert_allclose(a["val_loss"], b["val_loss"],
                                       rtol=1e-3)


def test_bad_retrain_keeps_incoming_member(rng):
    """Best-checkpoint gate parity (amg_test.py:295): best_metric starts at
    0, so a retrain where every epoch has val_loss >= 1 (score <= 0) keeps
    the member's INCOMING weights."""
    waves, classes = _synthetic_pool(rng, 4)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    # a member biased to predict ~1 for every class ...
    variables = short_cnn.init_variables(jax.random.key(3), TINY)
    variables["params"]["dense2"]["bias"] = (
        variables["params"]["dense2"]["bias"] + 10.0)
    # ... evaluated against all-zero targets: val BCE ~= 10 >> 1 every epoch
    y_zero = np.zeros((len(ids), 4), np.float32)
    trainer = CNNTrainer(TINY, TrainConfig(batch_size=2))
    incoming = jax.tree.map(lambda a: np.asarray(a).copy(),
                            variables["params"])  # fit donates its input
    best, hist = trainer.fit(variables, store, ids, y_zero, ids, y_zero,
                             jax.random.key(0), n_epochs=2)
    assert all(h["val_loss"] > 1.0 for h in hist)
    assert not any(h["improved"] for h in hist)
    for la, lb in zip(jax.tree.leaves(incoming),
                      jax.tree.leaves(best["params"])):
        np.testing.assert_array_equal(la, np.asarray(lb))


def test_history_records_val_f1_per_epoch(rng):
    """Reference computes weighted F1 every validation pass (amg_test.py:264)
    and logs it per epoch (deam_classifier.py:314-316)."""
    waves, classes = _synthetic_pool(rng, 4)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    trainer = CNNTrainer(TINY, TrainConfig(batch_size=2))
    _, hist = trainer.fit(short_cnn.init_variables(jax.random.key(0), TINY),
                          store, ids, y, ids, y, jax.random.key(1),
                          n_epochs=2)
    assert all(0.0 <= h["val_f1"] <= 1.0 for h in hist)
    _, hists = trainer.fit_many(
        [short_cnn.init_variables(jax.random.key(i), TINY) for i in range(2)],
        store, ids, y, ids, y, jax.random.key(2), n_epochs=2)
    for h in hists:
        assert all(0.0 <= e["val_f1"] <= 1.0 for e in h)


def test_weighted_f1_in_graph_matches_sklearn():
    """In-graph validation F1 == sklearn f1_score(average='weighted',
    zero_division=0), including all-wrong/missing-class corners (the
    deferred-history refactor moved the reference's host-side per-epoch F1
    — amg_test.py:264 — into the epoch jit)."""
    import jax.numpy as jnp
    from sklearn.metrics import f1_score

    from consensus_entropy_tpu.models.cnn_trainer import weighted_f1_in_graph

    rng = np.random.default_rng(0)
    cases = [rng.integers(0, 4, 50) for _ in range(3)]
    cases.append(np.zeros(10, np.int64))        # single-class truth
    cases.append(np.full(10, 3, np.int64))      # never-predicted classes
    for y_true in cases:
        probs = rng.random((len(y_true), 4)).astype(np.float32)
        want = f1_score(y_true, probs.argmax(axis=1), average="weighted",
                        zero_division=0)
        got = float(weighted_f1_in_graph(jnp.asarray(probs),
                                         jnp.asarray(one_hot_np(y_true))))
        np.testing.assert_allclose(got, want, rtol=1e-6)


def test_bf16_training_quality_parity(rng):
    """Mixed-precision training (compute_dtype='bfloat16': bf16 convs, f32
    params/optimizer/loss) must learn the separable tone task to the same
    level as f32 — the quality gate behind the bench's bf16 retrain race
    (``bench.py --suite retrain``)."""
    import dataclasses

    waves, classes = _synthetic_pool(rng, 8)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    finals = {}
    for dt in ("float32", "bfloat16"):
        cfg = dataclasses.replace(TINY, compute_dtype=dt)
        store = DeviceWaveformStore(waves, cfg.input_length)
        trainer = CNNTrainer(cfg, TrainConfig(batch_size=4, lr=1e-3))
        variables = short_cnn.init_variables(jax.random.key(0), cfg)
        # 40 epochs (not 25): the tiny 8-sample run must CONVERGE under
        # any jax build's threefry stream for the parity gap to be
        # meaningful — at 25 epochs the gate measured luck-of-the-draw
        # (this image's 0.4.37 partitionable threefry lands bf16 at 0.67
        # mid-descent; by 40 both dtypes plateau and the gap is real)
        best, hist = trainer.fit(variables, store, ids, y, ids, y,
                                 jax.random.key(1), n_epochs=40)
        # params stay f32 regardless of compute dtype
        assert all(np.asarray(a).dtype == np.float32
                   for a in jax.tree.leaves(best["params"]))
        finals[dt] = max(h["val_f1"] for h in hist)
    assert finals["float32"] > 0.8, finals
    assert finals["bfloat16"] >= finals["float32"] - 0.15, finals


@pytest.mark.slow
def test_fit_many_production_shape_5_members_padded_to_8(rng):
    """The reference committee's exact shape: 5 CNN members on an 8-wide
    member axis (3 padded slots trained redundantly, sliced off) — the
    configuration the AL CLI builds under --mesh auto.  (Demoted to slow
    for the tier-1 budget: the member-mesh mechanism stays tier-1 via
    test_fit_many_member_sharded_mesh; this row adds only the padded
    5-on-8 width, while the PR 7 cross-user stacking parity cases took
    its tier-1 slot.)"""
    from consensus_entropy_tpu.parallel.mesh import make_training_mesh

    waves, classes = _synthetic_pool(rng, 6)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    members = [short_cnn.init_variables(jax.random.key(i), TINY)
               for i in range(5)]
    key = jax.random.key(3)
    plain_best, plain_hist = CNNTrainer(TINY, TrainConfig(batch_size=3)) \
        .fit_many(members, store, ids, y, ids[:2], y[:2], key, n_epochs=2)
    mesh_best, mesh_hist = CNNTrainer(TINY, TrainConfig(batch_size=3)) \
        .fit_many(members, store, ids, y, ids[:2], y[:2], key, n_epochs=2,
                  mesh=make_training_mesh(dp=1, member=8))
    assert len(mesh_best) == 5 and len(mesh_hist) == 5
    for m in range(5):
        for a, b in zip(plain_hist[m], mesh_hist[m]):
            np.testing.assert_allclose(a["val_loss"], b["val_loss"],
                                       rtol=1e-3)
            np.testing.assert_allclose(a["val_f1"], b["val_f1"], atol=1e-6)


@pytest.mark.slow  # ~50-65s numerical-parity pin; tier-1 budget (870s) excludes it — run via `pytest -m slow` or the full matrix
def test_fit_many_scanned_matches_per_epoch(rng):
    """The callback-free fit_many path scans each schedule phase as ONE
    jitted program (<=4 dispatches per retrain instead of one per epoch).
    It must compute the SAME trajectory as the per-epoch path: the scan
    body chains the identical vmap(split) key stream, so best params and
    every per-epoch metric agree."""
    waves, classes = _synthetic_pool(rng, 6)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    members = [short_cnn.init_variables(jax.random.key(i), TINY)
               for i in range(2)]
    cfg = TrainConfig(batch_size=4, adam_patience=3, sgd_patience=2)

    def run(callback):
        trainer = CNNTrainer(TINY, cfg)
        vs = [jax.tree.map(np.copy, v) for v in members]
        return trainer.fit_many(vs, store, ids, y, ids, y,
                                jax.random.key(5), n_epochs=9,
                                callback=callback)

    best_scan, hist_scan = run(None)           # scanned phases
    seen = []
    best_loop, hist_loop = run(lambda e, infos: seen.append(e))  # per-epoch
    assert seen == list(range(9))
    assert len(hist_scan) == len(hist_loop) == 2
    for hs, hl in zip(hist_scan, hist_loop):
        assert [h["phase"] for h in hs] == [h["phase"] for h in hl]
        assert [h["epoch"] for h in hs] == [h["epoch"] for h in hl]
        np.testing.assert_allclose([h["val_loss"] for h in hs],
                                   [h["val_loss"] for h in hl],
                                   rtol=1e-5, atol=1e-6)
        assert ([h["improved"] for h in hs]
                == [h["improved"] for h in hl])
    for bs, bl in zip(best_scan, best_loop):
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), bs, bl)


def test_phase_segments_match_run_schedule():
    cfg = TrainConfig(batch_size=4, adam_patience=2, sgd_patience=2)
    trainer = CNNTrainer(TINY, cfg)
    segs = trainer._phase_segments(9, 2)
    assert segs == [("adam", 0, 2), ("sgd_1", 2, 4), ("sgd_2", 4, 6),
                    ("sgd_3", 6, 9)]
    # schedule shorter than the first patience: one segment, no transition
    assert trainer._phase_segments(2, 5) == [("adam", 0, 2)]
    # and the expanded segments replay _run_schedule exactly
    ran = []
    trainer._run_schedule(9, 2, lambda e, p: ran.append((e, p)),
                          lambda p: None)
    flat = [(e, p) for p, s, t in segs for e in range(s, t)]
    assert ran == flat


@pytest.mark.slow  # ~50-65s numerical-parity pin; tier-1 budget (870s) excludes it — run via `pytest -m slow` or the full matrix
def test_fit_scanned_matches_per_epoch(rng):
    """fit's callback-free path scans schedule phases like fit_many's;
    trajectories and best params must match the per-epoch path exactly."""
    waves, classes = _synthetic_pool(rng, 6)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    variables = short_cnn.init_variables(jax.random.key(0), TINY)
    cfg = TrainConfig(batch_size=4, adam_patience=3, sgd_patience=2)

    def run(callback):
        trainer = CNNTrainer(TINY, cfg)
        v = jax.tree.map(np.copy, variables)
        return trainer.fit(v, store, ids, y, ids, y, jax.random.key(5),
                           n_epochs=9, callback=callback)

    best_scan, hist_scan = run(None)
    best_loop, hist_loop = run(lambda e, info, preds: None)
    assert [h["phase"] for h in hist_scan] == [h["phase"] for h in hist_loop]
    np.testing.assert_allclose([h["val_loss"] for h in hist_scan],
                               [h["val_loss"] for h in hist_loop],
                               rtol=1e-5, atol=1e-6)
    assert ([h["improved"] for h in hist_scan]
            == [h["improved"] for h in hist_loop])
    jax.tree.map(lambda a, b: np.testing.assert_allclose(
        np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        best_scan, best_loop)


@pytest.mark.slow  # ~50-65s numerical-parity pin; tier-1 budget (870s) excludes it — run via `pytest -m slow` or the full matrix
def test_fit_many_scanned_mesh_matches_per_epoch(rng):
    """``TrainConfig.scan_mesh_phases`` opts the member-sharded MESH retrain
    into the scanned per-phase program (<=4 dispatches instead of one per
    epoch on a real pod).  On a 1-device mesh — the simplest sharded
    construct, safe on the virtual-CPU validation backend — its trajectory
    and best params must match the per-epoch mesh path."""
    import dataclasses

    from consensus_entropy_tpu.parallel.mesh import make_training_mesh

    waves, classes = _synthetic_pool(rng, 6)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    members = [short_cnn.init_variables(jax.random.key(i), TINY)
               for i in range(2)]
    cfg = TrainConfig(batch_size=4, adam_patience=3, sgd_patience=2)
    mesh = make_training_mesh(dp=1, member=1, devices=jax.devices()[:1])

    def run(train_cfg):
        trainer = CNNTrainer(TINY, train_cfg)
        vs = [jax.tree.map(np.copy, v) for v in members]
        return trainer.fit_many(vs, store, ids, y, ids, y,
                                jax.random.key(5), n_epochs=9, mesh=mesh)

    best_loop, hist_loop = run(cfg)  # per-epoch mesh path (default)
    best_scan, hist_scan = run(
        dataclasses.replace(cfg, scan_mesh_phases=True))
    assert len(hist_scan) == len(hist_loop) == 2
    for hs, hl in zip(hist_scan, hist_loop):
        assert [h["phase"] for h in hs] == [h["phase"] for h in hl]
        np.testing.assert_allclose([h["val_loss"] for h in hs],
                                   [h["val_loss"] for h in hl],
                                   rtol=1e-5, atol=1e-6)
        assert ([h["improved"] for h in hs]
                == [h["improved"] for h in hl])
    for bs, bl in zip(best_scan, best_loop):
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6), bs, bl)


def test_epoch_fns_cache_bounded(rng, monkeypatch):
    """_EPOCH_FNS is a bounded LRU: in a production AL run n_train grows
    every iteration, so unbounded (phase, n_train)-keyed programs would
    leak for the process lifetime (round-4 advisor finding)."""
    from consensus_entropy_tpu.models import cnn_trainer as ct

    waves, classes = _synthetic_pool(rng, 8)
    store = DeviceWaveformStore(waves, TINY.input_length)
    ids = list(waves)
    y = one_hot_np([classes[s] for s in ids])
    trainer = CNNTrainer(TINY, TrainConfig(batch_size=4))
    monkeypatch.setattr(ct, "_EPOCH_FNS_MAX", 3)
    ct._EPOCH_FNS.clear()
    # growing n_train (the AL pool growth pattern) — 5 distinct keys
    for n in range(4, 9):
        trainer._epoch_fn("adam", n, len(ids), 4)
    assert len(ct._EPOCH_FNS) == 3
    kept = [k[3] for k in ct._EPOCH_FNS]  # n_train slot of the key
    assert kept == [6, 7, 8]  # least-recently-used evicted first
    # a cache hit refreshes recency instead of re-tracing
    fn = trainer._epoch_fn("adam", 6, len(ids), 4)
    trainer._epoch_fn("adam", 9, len(ids), 4)  # evicts 7, not 6
    assert trainer._epoch_fn("adam", 6, len(ids), 4) is fn
    assert [k[3] for k in ct._EPOCH_FNS] == [8, 9, 6]
    ct._EPOCH_FNS.clear()
