"""Full-song (window-grid) committee scoring vs naive oracles — the
deterministic replacement for the reference's one-random-crop-per-pass CNN
scoring (short_cnn.py:376-377)."""

import jax
import numpy as np
import pytest

from consensus_entropy_tpu.config import CNNConfig
from consensus_entropy_tpu.data.audio import (
    DeviceWaveformStore,
    HostWaveformStore,
)
from consensus_entropy_tpu.models.committee import CNNMember, Committee
from consensus_entropy_tpu.models.short_cnn import (
    apply_infer,
    init_variables,
)

TINY = CNNConfig(n_channels=4, n_fft=64, hop_length=32, n_mels=16,
                 n_layers=2, input_length=1024)


@pytest.fixture(scope="module")
def waves():
    rng = np.random.default_rng(5)
    return {f"s{i}": (rng.standard_normal(1024 + 700 * i) * 0.05
                      ).astype(np.float32) for i in range(5)}


@pytest.fixture(scope="module")
def store(waves):
    return DeviceWaveformStore(waves, TINY.input_length)


def _naive_windows(wave, hop, length, n_w):
    out, valid = np.zeros((n_w, length), np.float32), np.zeros(n_w, bool)
    for w in range(n_w):
        s = w * hop
        if s + length <= len(wave):
            out[w] = wave[s: s + length]
            valid[w] = True
    return out, valid


def test_window_batch_matches_naive(store, waves):
    hop = 512
    rows = store.row_of(["s0", "s3", "s4"])
    windows, valid = store.window_batch(rows, hop)
    n_w = store.n_windows(hop)
    assert windows.shape == (3, n_w, TINY.input_length)
    for j, sid in enumerate(["s0", "s3", "s4"]):
        want_w, want_v = _naive_windows(waves[sid], hop, TINY.input_length,
                                        n_w)
        np.testing.assert_array_equal(np.asarray(valid)[j], want_v)
        np.testing.assert_array_equal(
            np.asarray(windows)[j][want_v], want_w[want_v])
    assert np.asarray(valid)[:, 0].all()  # window 0 always valid


def test_host_store_window_batch_matches_device(tmp_path, waves, store):
    for sid, w in waves.items():
        np.save(tmp_path / f"{sid}.npy", w)
    host = HostWaveformStore(str(tmp_path), list(waves), TINY.input_length)
    rows_d = store.row_of(["s1", "s4"])
    rows_h = host.row_of(["s1", "s4"])
    wd, vd = store.window_batch(rows_d, 300)
    wh, vh = host.window_batch(rows_h, 300)
    np.testing.assert_array_equal(np.asarray(vd), np.asarray(vh))
    np.testing.assert_array_equal(
        np.asarray(wd)[np.asarray(vd)], np.asarray(wh)[np.asarray(vh)])


def _cnn_committee(hop, n_members=2):
    members = [CNNMember(f"it_{i}",
                         init_variables(jax.random.key(i), TINY, 2), TINY)
               for i in range(n_members)]
    return Committee([], members, TINY, full_song_hop=hop)


def test_full_song_scores_match_window_mean_oracle(store, waves):
    hop = 512
    committee = _cnn_committee(hop)
    got = np.asarray(committee.predict_songs_cnn(store, list(waves), None))
    assert got.shape == (2, 5, TINY.n_class)
    for mi, m in enumerate(committee.cnn_members):
        for j, sid in enumerate(waves):
            w, v = _naive_windows(waves[sid], hop, TINY.input_length,
                                  store.n_windows(hop))
            probs = np.asarray(apply_infer(m.variables, w[v], TINY))
            np.testing.assert_allclose(got[mi, j], probs.mean(axis=0),
                                       rtol=2e-4, atol=2e-6)


def test_full_song_chunking_is_invariant(store, waves):
    committee = _cnn_committee(512)
    a = np.asarray(committee.predict_songs_cnn(store, list(waves), None,
                                               chunk=2))
    b = np.asarray(committee.predict_songs_cnn(store, list(waves), None,
                                               chunk=100))
    np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_full_song_is_deterministic_and_flows_into_pool_probs(store, waves):
    committee = _cnn_committee(512)
    k1, k2 = jax.random.key(1), jax.random.key(2)
    a = np.asarray(committee.pool_probs(None, store, list(waves), k1))
    b = np.asarray(committee.pool_probs(None, store, list(waves), k2))
    np.testing.assert_array_equal(a, b)  # no crop randomness
    crops = _cnn_committee(None)
    c = np.asarray(crops.pool_probs(None, store, list(waves), k1))
    d = np.asarray(crops.pool_probs(None, store, list(waves), k2))
    assert not np.array_equal(c, d)  # reference behavior stays stochastic


def test_hop_validation_and_empty_song_list(store):
    with pytest.raises(ValueError, match="full_song_hop"):
        _cnn_committee(0)
    with pytest.raises(ValueError, match="full_song_hop"):
        _cnn_committee(TINY.input_length + 1)
    committee = _cnn_committee(512)
    out = committee.predict_songs_cnn(store, [], None)
    assert out.shape == (2, 0, TINY.n_class)
