"""The fused serve step: device-resident pool state, in-graph
select→reveal→mask, donated buffers — pinned bit-identical to the
host-round-trip arm.

Layers covered:

- **ops**: every ``*_fused`` scorer equals its unfused sibling's
  entropies/values/indices EXACTLY, and its returned masks equal the host
  bookkeeping the unfused arm performs (``Acquirer.finish_select``'s
  shrink + hc removal), for all six registered modes; the fleet vmapped
  fused fns are row-identical to the single-user fused fns (the stacked
  bucket dispatch), with the donated stacked mask buffers actually
  consumed.
- **acquirer**: the device mask twins are adopted from each fused step
  and stay in bitwise lockstep with the host mirrors across shrinking
  iterations; ``--no-fuse-step`` (``fuse_step=False``) selects
  identically.
- **loop/fleet/serve**: full AL runs — sequential, stacked fleet cohorts,
  and a serve-journal restart — produce bit-identical trajectories,
  reveal histories and reports across the two arms (tier-1 keeps the mc
  cases; the full mode matrix, the qbdc CNN case and the
  eviction+resume drill are ``slow``).

Eviction/resume and journal-restart correctness rest on one invariant the
unit here pins directly: ``DevicePoolState`` masks are built LAZILY from
the host mirrors (``device_masks``), so every rebuild path — which
constructs a fresh ``Acquirer`` at the pinned pad and replays
``ALState.queried`` — re-uploads post-replay mirrors bit-identical to
what an uninterrupted run holds on device.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_entropy_tpu.al.acquisition import Acquirer
from consensus_entropy_tpu.al.loop import ALLoop
from consensus_entropy_tpu.fleet import FleetReport, FleetScheduler, FleetUser
from consensus_entropy_tpu.ops import scoring
from consensus_entropy_tpu.ops.entropy import shannon_entropy
from consensus_entropy_tpu.ops.topk import reveal_mask_update
from tests.test_fleet import _cfg, _committee, _run_pair, _user_data

pytestmark = pytest.mark.fleet


def _probs(rng, m, n, c=4):
    p = rng.uniform(0.01, 1.0, size=(m, n, c)).astype(np.float32)
    return p / p.sum(axis=-1, keepdims=True)


def _host_shrink(mask, values, indices, n=None):
    """The unfused arm's host bookkeeping, verbatim: flip selected rows
    whose top-k value is real; mix-space indices fold mod n."""
    out = np.asarray(mask).copy()
    idx = np.asarray(indices)
    if n is not None:
        idx = idx % n
    out[idx[np.asarray(values) > -np.inf]] = False
    return out


def test_reveal_mask_update_drops_invalid_slots():
    mask = np.ones(10, bool)
    vals = jnp.asarray([1.0, 0.5, -jnp.inf])
    idx = jnp.asarray([3, 7, 2])  # slot 2 is a -inf filler: must survive
    out = np.asarray(reveal_mask_update(mask, vals, idx))
    expect = mask.copy()
    expect[[3, 7]] = False
    np.testing.assert_array_equal(out, expect)


def test_fused_ops_match_unfused_all_modes(rng):
    """Every fused scorer == its unfused sibling + the host mask update,
    bit for bit — the in-graph tail changes WHERE the bookkeeping runs,
    never what is selected."""
    m, n, k = 4, 96, 6
    p = _probs(rng, m, n)
    pool = np.zeros(n, bool)
    pool[:80] = True
    counts = rng.integers(1, 25, size=(n, 4))
    hc = np.round(counts / counts.sum(-1, keepdims=True),
                  3).astype(np.float32)
    hc_mask = pool.copy()
    hc_mask[50:] = False
    hc_ent = np.asarray(jax.jit(shannon_entropy)(hc))
    w = rng.uniform(0.2, 1.5, m).astype(np.float32)
    key = jax.random.key(11)
    fns = scoring.make_scoring_fns(k=k)

    cases = {
        "mc": ((p, pool), (p, jnp.asarray(pool))),
        "qbdc": ((p, pool), (p, jnp.asarray(pool))),
        "wmc": ((p, pool, w), (p, jnp.asarray(pool), w)),
        "rand": ((key, pool), (key, jnp.asarray(pool))),
        "hc_pre": ((hc_ent, hc_mask),
                   (hc_ent, jnp.asarray(hc_mask), jnp.asarray(pool))),
        "mix": ((p, pool, hc, hc_mask),
                (p, jnp.asarray(pool), hc, jnp.asarray(hc_mask))),
    }
    for mode, (plain_in, fused_in) in cases.items():
        plain = fns[mode](*plain_in)
        fused = fns[f"{mode}_fused"](*fused_in)
        for field in ("entropy", "values", "indices"):
            np.testing.assert_array_equal(
                np.asarray(getattr(fused, field)),
                np.asarray(getattr(plain, field)), err_msg=mode)
        v, i = np.asarray(plain.values), np.asarray(plain.indices)
        fold = n if mode == "mix" else None
        if mode == "hc_pre":
            # hc scores over the hc mask; both masks shrink at the slots
            np.testing.assert_array_equal(
                np.asarray(fused.hc_mask), _host_shrink(hc_mask, v, i))
            np.testing.assert_array_equal(
                np.asarray(fused.pool_mask), _host_shrink(pool, v, i))
        elif mode == "mix":
            np.testing.assert_array_equal(
                np.asarray(fused.pool_mask),
                _host_shrink(pool, v, i, n=fold))
            np.testing.assert_array_equal(
                np.asarray(fused.hc_mask),
                _host_shrink(hc_mask, v, i, n=fold))
        else:
            np.testing.assert_array_equal(
                np.asarray(fused.pool_mask), _host_shrink(pool, v, i))
            assert fused.hc_mask is None


def test_fleet_fused_rows_match_single_and_donate(rng):
    """The stacked bucket dispatch: every row of the vmapped fused fns is
    bit-identical to the single-user fused fn, and the STACKED mask
    operand is donated (consumed) — the in-place pool-state update the
    tentpole claims."""
    u, m, n, k = 3, 4, 64, 5
    p = _probs(rng, u * m, n).reshape(u, m, n, 4)
    mask = np.zeros((u, n), bool)
    mask[:, :50] = True
    fleet = scoring.make_fleet_scoring_fns(k=k)
    single = scoring.make_scoring_fns(k=k)
    stacked = jnp.asarray(mask)
    res = fleet["mc_fused"](jnp.asarray(p), stacked)
    for i in range(u):
        s = single["mc_fused"](p[i], jnp.asarray(mask[i]))
        for field in ("entropy", "values", "indices", "pool_mask"):
            np.testing.assert_array_equal(
                np.asarray(getattr(res, field)[i]),
                np.asarray(getattr(s, field)))
    with pytest.raises(RuntimeError):
        stacked.block_until_ready()  # donated: the buffer was consumed

    # bucketed (width-guarded) family: same graph, same rows, and the
    # guard still reads the fused mask operand's width
    bucket = scoring.fleet_scoring_fns_for_width(k=k, width=n)
    res2 = bucket["mc_fused"](jnp.asarray(p), jnp.asarray(mask))
    np.testing.assert_array_equal(np.asarray(res2.indices),
                                  np.asarray(res.indices))
    with pytest.raises(ValueError, match="bucket routing"):
        bucket["mc_fused"](jnp.asarray(p[:, :, :32]),
                           jnp.asarray(mask[:, :32]))


def test_acquirer_fused_masks_lockstep(rng):
    """Across shrinking iterations the device twins adopted from each
    fused step stay bitwise equal to the host mirrors — and a fused
    acquirer selects exactly what a ``fuse_step=False`` one does.  Also
    pins the lazy-upload rebuild contract: a THIRD acquirer replays the
    first acquirer's query history (the eviction/resume + journal-restart
    path) and its first ``device_masks()`` equals the live twins."""
    songs = [f"s{i:03d}" for i in range(37)]
    counts = rng.integers(1, 20, size=(37, 4))
    hc = np.round(counts / counts.sum(1, keepdims=True),
                  3).astype(np.float32)
    for mode in ("mc", "hc", "mix"):
        fused = Acquirer(songs, hc, queries=4, mode=mode, seed=1)
        plain = Acquirer(songs, hc, queries=4, mode=mode, seed=1,
                         fuse_step=False)
        assert fused.fuse_step and not plain.fuse_step
        hist = []
        for _ in range(3):
            live = fused.remaining_songs
            p = _probs(rng, 3, len(live))
            qf = fused.select(p)
            qp = plain.select(p)
            assert qf == qp
            hist.append(qf)
            np.testing.assert_array_equal(
                np.asarray(fused.device.pool_mask), fused.pool_mask)
            np.testing.assert_array_equal(fused.pool_mask, plain.pool_mask)
            if fused.strategy.uses_hc_table:
                np.testing.assert_array_equal(
                    np.asarray(fused.device.hc_mask), fused.hc_mask)
        assert fused.device.n_revealed == sum(len(b) for b in hist)
        rebuilt = Acquirer(songs, hc, queries=4, mode=mode, seed=1)
        rebuilt.replay(hist)
        d = rebuilt.device_masks()
        np.testing.assert_array_equal(np.asarray(d.pool_mask),
                                      np.asarray(fused.device.pool_mask))
        if rebuilt.strategy.uses_hc_table:
            np.testing.assert_array_equal(
                np.asarray(d.hc_mask), np.asarray(fused.device.hc_mask))


def _ab_run(tmp_path, cfg, tag, *, fuse, n_users=2):
    out = []
    loop = ALLoop(cfg, fuse_step=fuse)
    for i in range(n_users):
        data = _user_data(100 + i, f"u{i}")
        p = tmp_path / f"{tag}_u{i}"
        p.mkdir()
        out.append(loop.run_user(_committee(data), data, str(p)))
    return out


def test_sequential_loop_fused_parity_mc(tmp_path):
    """The tier-1 A/B pin: a sequential mc run under the fused step is
    bit-identical — trajectory AND reveal history — to the
    ``--no-fuse-step`` arm."""
    cfg = _cfg(mode="mc", epochs=3)
    a = _ab_run(tmp_path, cfg, "fused", fuse=True)
    b = _ab_run(tmp_path, cfg, "plain", fuse=False)
    assert [r["trajectory"] for r in a] == [r["trajectory"] for r in b]
    import json
    for i in range(2):
        fa = json.loads(
            (tmp_path / f"fused_u{i}" / "al_state.json").read_text())
        fb = json.loads(
            (tmp_path / f"plain_u{i}" / "al_state.json").read_text())
        assert fa["queried"] == fb["queried"]  # reveal trajectories


@pytest.mark.slow
def test_sequential_loop_fused_parity_matrix(tmp_path):
    """Full registered-mode matrix of the A/B pin (host modes; qbdc has
    its own CNN case below)."""
    import json

    for mode in ("hc", "mix", "rand", "wmc"):
        cfg = _cfg(mode=mode, epochs=3)
        a = _ab_run(tmp_path, cfg, f"{mode}_fused", fuse=True)
        b = _ab_run(tmp_path, cfg, f"{mode}_plain", fuse=False)
        assert [r["trajectory"] for r in a] == \
            [r["trajectory"] for r in b], mode
        for i in range(2):
            fa = json.loads((tmp_path / f"{mode}_fused_u{i}"
                             / "al_state.json").read_text())
            fb = json.loads((tmp_path / f"{mode}_plain_u{i}"
                             / "al_state.json").read_text())
            assert fa["queried"] == fb["queried"], mode


def test_fleet_fused_stacked_matches_sequential(tmp_path):
    """Cross-driver: a fused fleet cohort (stacked fused dispatches,
    donated stacks) reproduces sequential runs bit-for-bit (the
    sequential arm is fused too; the mc A/B pin above makes that
    transitively equal to the unfused arm), and the dispatch records
    carry the transfer grading the fused step is pinned by."""
    cfg = _cfg(mode="mc", epochs=3)
    report = FleetReport()
    seq, recs, sched = _run_pair(
        tmp_path, cfg, 2,
        scheduler_kw={"report": report, "fuse_step": True})
    assert all(r["error"] is None for r in recs)
    assert [r["result"]["trajectory"] for r in recs] == \
        [s["trajectory"] for s in seq]
    fused_fns = {d["fn"] for d in report.dispatches}
    assert "mc_fused" in fused_fns and "mc" not in fused_fns
    t = report.transfer_summary
    assert t is not None and t["selects"] == 2 * cfg.epochs
    # fused mc over a host committee: the probs block is each select's
    # ONLY steady-state host→device upload (masks live on device after
    # the one charged per-user admission upload)
    assert t["h2d_ops"] == t["selects"] + 2
    # strictly below the unfused arm's floor of 3 (probs + mask uploads
    # + the reduction dispatch per select); the exact value wiggles with
    # dispatch grouping, which is scheduling-timing dependent
    assert t["device_calls_per_select"] < 3.0


@pytest.mark.slow
def test_fleet_fused_eviction_resume_parity(tmp_path):
    """Eviction+resume under the fused step: the resumed session rebuilds
    its ``DevicePoolState`` from ``ALState`` at the pinned pad (lazy
    ``device_masks`` upload post-replay) and the user's trajectory stays
    bit-identical to an unfaulted UNFUSED sequential run."""
    from consensus_entropy_tpu.resilience import faults
    from consensus_entropy_tpu.resilience.faults import FaultRule

    from consensus_entropy_tpu.al import workspace

    cfg = _cfg(mode="mc", epochs=3)

    def committee_fn(data):
        if data.user_id == "u1":  # the victim: uniquely-named member
            return _committee(data, sgd_name="sgd.victim", min_members=2)
        return _committee(data)

    seq, entries = [], []
    for i in range(2):
        data = _user_data(100 + i, f"u{i}")
        sp = tmp_path / f"seqplain_u{i}"
        sp.mkdir()
        seq.append(ALLoop(cfg, fuse_step=False).run_user(
            committee_fn(data), data, str(sp)))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(
            f"u{i}", committee_fn(data), data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp))))
    rule = FaultRule("member.retrain", "raise", at=1, member="sgd.victim")
    with faults.inject(rule) as inj:
        sched = FleetScheduler(cfg, report=FleetReport(), fuse_step=True)
        recs = sched.run(entries)
        assert inj.fired
    assert all(r["error"] is None for r in recs)
    assert sum(r["resumes"] for r in recs) >= 1  # somebody was evicted
    assert [r["result"]["trajectory"] for r in recs] == \
        [s["trajectory"] for s in seq]


def test_serve_restart_fused_matches_unfused_sequential(tmp_path):
    """THE serve acceptance pin: a fused serve run SIGKILLed mid-run (at
    the first finish-journal append) and restarted from the journal
    finishes every user — the restarted sessions rebuild their
    ``DevicePoolState`` at the pinned pad from ``ALState`` — with results
    bit-identical to uninterrupted UNFUSED sequential runs."""
    from consensus_entropy_tpu.resilience.faults import FaultRule
    from tests.test_serve_faults import _restart_drill

    cfg = _cfg(mode="mc", epochs=2)
    specs = [(100 + i, f"u{i}", 30) for i in range(3)]
    seq = []
    loop = ALLoop(cfg, fuse_step=False)
    for seed, uid, n in specs:
        data = _user_data(seed, uid, n_songs=n)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(loop.run_user(_committee(data), data, str(p)))
    done, report = _restart_drill(
        tmp_path, cfg, specs,
        FaultRule("serve.journal.append", "kill", at=6),
        scheduler_kw={"fuse_step": True})
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]
    assert {d["fn"] for d in report.dispatches} <= {"mc_fused"}
    assert report.transfer_summary is not None


@pytest.mark.slow
def test_qbdc_fused_parity_and_serve_restart(tmp_path):
    """qbdc (the device-resident probs producer): fused vs unfused
    sequential parity, then a fused serve restart against the unfused
    baselines — the dropout committee's mask keys, the scatter buffer and
    the device pool masks all rebuild bit-identically."""
    from consensus_entropy_tpu.al import workspace
    from consensus_entropy_tpu.resilience.faults import FaultRule
    from tests.test_acquire import TINY_CNN, TINY_TC, _cnn_committee, \
        _cnn_data
    from tests.test_serve_faults import _restart_drill

    cfg = dataclasses.replace(_cfg(mode="qbdc", epochs=2, queries=3),
                              qbdc_k=6)
    specs = [(100 + i, f"u{i}", 8) for i in range(2)]
    seq = []
    for seed, uid, n in specs:
        data = _cnn_data(seed, uid, n_songs=n)
        p = tmp_path / f"seq_{uid}"
        p.mkdir()
        seq.append(ALLoop(cfg, retrain_epochs=1, fuse_step=False).run_user(
            _cnn_committee(data), data, str(p)))
    # fused sequential parity first
    for seed, uid, n in specs:
        data = _cnn_data(seed, uid, n_songs=n)
        p = tmp_path / f"fseq_{uid}"
        p.mkdir()
        r = ALLoop(cfg, retrain_epochs=1, fuse_step=True).run_user(
            _cnn_committee(data), data, str(p))
        assert r["trajectory"] == seq[
            [u for _, u, _ in specs].index(uid)]["trajectory"]

    def entries(tmp_path, cfg, specs):
        out = []
        for seed, uid, n in specs:
            data = _cnn_data(seed, uid, n_songs=n)
            fp = tmp_path / f"serve_{uid}"
            fp.mkdir(exist_ok=True)
            if (fp / "al_state.json").exists():
                committee = workspace.load_committee(str(fp), TINY_CNN,
                                                     TINY_TC)
            else:
                committee = _cnn_committee(data)
            out.append(FleetUser(
                uid, committee, data, str(fp), seed=cfg.seed,
                committee_factory=lambda fp=fp: workspace.load_committee(
                    str(fp), TINY_CNN, TINY_TC)))
        return out

    done, report = _restart_drill(
        tmp_path, cfg, specs, FaultRule("serve.collect", "kill", at=1),
        entries_fn=entries,
        scheduler_kw={"retrain_epochs": 1, "fuse_step": True})
    for s, (_, uid, _) in zip(seq, specs):
        assert done[uid]["error"] is None
        assert done[uid]["result"]["trajectory"] == s["trajectory"]
    # the restart may find every user already past its last epoch (the
    # kill landed after the work finished), so only pin that no UNFUSED
    # reduction ran — the fused-parity halves above carry the equality
    assert "qbdc" not in {d["fn"] for d in report.dispatches}
