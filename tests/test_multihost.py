"""Multi-host helpers on a single process (the multi-process path differs
only in jax.distributed.initialize, which auto-noops here)."""

import jax
import numpy as np

from consensus_entropy_tpu.ops.scoring import score_mc
from consensus_entropy_tpu.parallel import multihost


def test_initialize_is_noop_single_process():
    multihost.initialize()  # must not raise or hang
    assert jax.process_count() == 1


def test_host_slice_covers_everything():
    # Single process owns the whole row range (divisibility is trivially
    # satisfied; the guard only binds for process_count > 1).
    s = multihost.host_pool_slice(64)
    assert (s.start, s.stop) == (0, 64)


def test_distribute_pool_feeds_sharded_scoring(rng):
    # Host-local rows -> global sharded array -> fused scoring graph.
    mesh = multihost.global_pool_mesh()
    assert mesh.devices.size == 8  # conftest virtual mesh
    n = 64
    local = rng.uniform(0.01, 1.0, (n, 3, 4)).astype(np.float32)
    local /= local.sum(axis=-1, keepdims=True)
    probs_rows = local[multihost.host_pool_slice(n)]
    garr = multihost.distribute_pool(probs_rows, n)
    assert garr.shape == (n, 3, 4)
    assert len(garr.sharding.device_set) == 8

    member_major = np.moveaxis(np.asarray(garr), 1, 0)
    mask = np.ones(n, bool)
    res = score_mc(member_major, mask, k=5)
    want = score_mc(np.moveaxis(local, 1, 0), mask, k=5)
    np.testing.assert_array_equal(np.asarray(res.indices),
                                  np.asarray(want.indices))


def test_distribute_along_axis1_matches_device_put(rng):
    """The Acquirer's probs feed: (M, N, C) with pool on axis 1."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from consensus_entropy_tpu.parallel.mesh import POOL_AXIS

    probs = rng.standard_normal((3, 64, 4)).astype(np.float32)
    mesh = multihost.global_pool_mesh()
    got = multihost.distribute_along(
        probs[:, multihost.host_pool_slice(64)], probs.shape, mesh, axis=1)
    want = jax.device_put(probs, NamedSharding(mesh, P(None, POOL_AXIS,
                                                       None)))
    assert got.sharding == want.sharding
    np.testing.assert_array_equal(np.asarray(got), probs)


def test_feed_and_gather_round_trip(rng):
    """feed_pool_axis -> gather_to_host is the identity on a host-complete
    array (single-process: device_put + np.asarray equivalents)."""
    x = rng.standard_normal((32, 3)).astype(np.float32)
    mesh = multihost.global_pool_mesh()
    fed = multihost.feed_pool_axis(x, mesh, 0)
    np.testing.assert_array_equal(multihost.gather_to_host(fed), x)
