"""Masked top-k: reference tie semantics (np.argsort reversed) and masking."""

import jax.numpy as jnp
import numpy as np

from consensus_entropy_tpu.ops.topk import masked_top_k, valid_count


def _ref_rank(scores, q):
    # amg_test.py:445 is np.argsort(ent)[::-1][:q]; numpy's default introsort
    # makes its tie order implementation-defined, so our deterministic
    # analogue pins kind='stable'.
    return np.argsort(scores, kind="stable")[::-1][:q]


def test_numpy_tie_break_exact(rng):
    scores = rng.uniform(size=100).round(1)  # force ties
    mask = np.ones(100, dtype=bool)
    _, idx = masked_top_k(scores, mask, 10, tie_break="numpy")
    np.testing.assert_array_equal(np.asarray(idx), _ref_rank(scores, 10))


def test_all_ties_numpy_order():
    scores = np.zeros(16)
    mask = np.ones(16, dtype=bool)
    _, idx = masked_top_k(scores, mask, 5, tie_break="numpy")
    # reversed stable sort: highest index first
    np.testing.assert_array_equal(np.asarray(idx), [15, 14, 13, 12, 11])


def test_fast_matches_values(rng):
    scores = rng.uniform(size=257)
    mask = np.ones(257, dtype=bool)
    v_fast, _ = masked_top_k(scores, mask, 17, tie_break="fast")
    v_np, _ = masked_top_k(scores, mask, 17, tie_break="numpy")
    np.testing.assert_allclose(np.asarray(v_fast), np.asarray(v_np))
    np.testing.assert_allclose(np.asarray(v_fast), np.sort(scores)[::-1][:17])


def test_mask_excludes(rng):
    scores = rng.uniform(size=64)
    mask = np.zeros(64, dtype=bool)
    mask[10:20] = True
    for tb in ("fast", "numpy"):
        v, idx = masked_top_k(scores, mask, 5, tie_break=tb)
        assert set(np.asarray(idx)).issubset(set(range(10, 20)))
        assert int(valid_count(v)) == 5


def test_fewer_valid_than_k():
    scores = np.arange(8.0)
    mask = np.zeros(8, dtype=bool)
    mask[:3] = True
    v, idx = masked_top_k(scores, mask, 5, tie_break="fast")
    assert int(valid_count(v)) == 3
    np.testing.assert_array_equal(np.asarray(idx)[:3], [2, 1, 0])


def test_two_stage_matches_flat_top_k(rng):
    """two_stage_top_k must equal lax.top_k exactly — values AND indices,
    tie order included — on pools spanning the split threshold."""
    from jax import lax

    from consensus_entropy_tpu.ops.topk import two_stage_top_k

    for n in (100, 1024, 1025, 4096, 100_000):
        scores = rng.uniform(size=n).astype(np.float32)
        v2, i2 = two_stage_top_k(scores, 10)
        vf, if_ = lax.top_k(jnp.asarray(scores), 10)
        np.testing.assert_array_equal(np.asarray(v2), np.asarray(vf))
        np.testing.assert_array_equal(np.asarray(i2), np.asarray(if_))


def test_two_stage_tie_order_matches_flat(rng):
    """Exact ties are the hc regime (3-decimal-rounded tables → identical
    entropies): the candidate reduction must keep 'lowest index wins',
    byte-identical to the flat op — including ties straddling row
    boundaries and >k ties inside one row."""
    from jax import lax

    from consensus_entropy_tpu.ops.topk import two_stage_top_k

    n = 5000
    scores = np.round(rng.uniform(size=n), 2).astype(np.float32)  # ~100 ties/value
    scores[1020:1030] = 2.0  # >k block of ties straddling the 1024 boundary
    v2, i2 = two_stage_top_k(scores, 7)
    vf, if_ = lax.top_k(jnp.asarray(scores), 7)
    np.testing.assert_array_equal(np.asarray(v2), np.asarray(vf))
    np.testing.assert_array_equal(np.asarray(i2), np.asarray(if_))


def test_masked_fast_path_large_pool(rng):
    """masked_top_k 'fast' (which routes through the two-stage reduction at
    pool scale) vs a numpy oracle on a masked 50k pool."""
    n = 50_000
    scores = rng.uniform(size=n).astype(np.float32)
    mask = rng.uniform(size=n) < 0.7
    v, idx = masked_top_k(scores, mask, 10, tie_break="fast")
    masked = np.where(mask, scores, -np.inf)
    want_idx = np.argsort(masked, kind="stable")[::-1][:10]
    np.testing.assert_allclose(np.asarray(v), masked[want_idx])
    assert set(np.asarray(idx)) == set(want_idx)
