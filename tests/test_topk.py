"""Masked top-k: reference tie semantics (np.argsort reversed) and masking."""

import numpy as np

from consensus_entropy_tpu.ops.topk import masked_top_k, valid_count


def _ref_rank(scores, q):
    # amg_test.py:445 is np.argsort(ent)[::-1][:q]; numpy's default introsort
    # makes its tie order implementation-defined, so our deterministic
    # analogue pins kind='stable'.
    return np.argsort(scores, kind="stable")[::-1][:q]


def test_numpy_tie_break_exact(rng):
    scores = rng.uniform(size=100).round(1)  # force ties
    mask = np.ones(100, dtype=bool)
    _, idx = masked_top_k(scores, mask, 10, tie_break="numpy")
    np.testing.assert_array_equal(np.asarray(idx), _ref_rank(scores, 10))


def test_all_ties_numpy_order():
    scores = np.zeros(16)
    mask = np.ones(16, dtype=bool)
    _, idx = masked_top_k(scores, mask, 5, tie_break="numpy")
    # reversed stable sort: highest index first
    np.testing.assert_array_equal(np.asarray(idx), [15, 14, 13, 12, 11])


def test_fast_matches_values(rng):
    scores = rng.uniform(size=257)
    mask = np.ones(257, dtype=bool)
    v_fast, _ = masked_top_k(scores, mask, 17, tie_break="fast")
    v_np, _ = masked_top_k(scores, mask, 17, tie_break="numpy")
    np.testing.assert_allclose(np.asarray(v_fast), np.asarray(v_np))
    np.testing.assert_allclose(np.asarray(v_fast), np.sort(scores)[::-1][:17])


def test_mask_excludes(rng):
    scores = rng.uniform(size=64)
    mask = np.zeros(64, dtype=bool)
    mask[10:20] = True
    for tb in ("fast", "numpy"):
        v, idx = masked_top_k(scores, mask, 5, tie_break=tb)
        assert set(np.asarray(idx)).issubset(set(range(10, 20)))
        assert int(valid_count(v)) == 5


def test_fewer_valid_than_k():
    scores = np.arange(8.0)
    mask = np.zeros(8, dtype=bool)
    mask[:3] = True
    v, idx = masked_top_k(scores, mask, 5, tie_break="fast")
    assert int(valid_count(v)) == 3
    np.testing.assert_array_equal(np.asarray(idx)[:3], [2, 1, 0])
