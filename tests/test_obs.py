"""Observability subsystem: tracing, metrics registry, export (obs/).

Tier-1 (un-marked) keeps the pure-host units — histogram percentile
exactness vs numpy, schema-v2 validation, torn-tail-tolerant readers,
deterministic trace/span ids, span dedupe/orphan detection, Chrome-trace
export shape — plus ONE traced 2-user fleet eviction+resume drill (the
trace-continuity acceptance pin) and ONE traced 3-user serve run (span
nesting + the admission→finish latency histogram).  The fabric
worker-SIGKILL trace-continuity drill runs a real 2-host fabric and is
``slow``/``faults`` (``scripts/fault_matrix.sh`` runs it).
"""

import json
import os
import time

import numpy as np
import pytest

from consensus_entropy_tpu.obs import export
from consensus_entropy_tpu.obs.metrics import (
    EventWriter,
    Histogram,
    MetricsRegistry,
)
from consensus_entropy_tpu.obs.trace import NULL_TRACER, Tracer, trace_id

pytestmark = pytest.mark.obs


# -- metrics: histogram / registry / writer (no jax) -----------------------


def test_histogram_percentiles_exact_vs_numpy():
    """While the reservoir holds, percentiles are BIT-identical to
    np.percentile (linear interpolation, branch included) on known and
    random draws."""
    rng = np.random.default_rng(7)
    for draws in (np.arange(1.0, 101.0),
                  rng.exponential(0.3, size=257),
                  rng.uniform(0.001, 50.0, size=1000)):
        h = Histogram()
        for v in draws:
            h.add(v)
        assert h.exact
        for q in (0, 10, 50, 90, 95, 99, 99.9, 100):
            assert h.percentile(q) == float(np.percentile(draws, q)), \
                f"q={q} mismatch on n={len(draws)}"
        snap = h.snapshot()
        assert snap["p50"] == round(float(np.percentile(draws, 50)), 4)
        assert snap["n"] == len(draws) and "exact" not in snap


def test_histogram_bucket_fallback_is_conservative_upper_bound():
    """Past the reservoir the percentile comes from log-bucket upper
    edges: an UPPER bound on the true quantile, never below it, and the
    snapshot flags the loss of exactness."""
    rng = np.random.default_rng(3)
    draws = rng.exponential(1.0, size=500)
    h = Histogram(max_samples=100)
    for v in draws:
        h.add(v)
    assert not h.exact
    for q in (50, 95, 99):
        true = float(np.percentile(draws, q))
        est = h.percentile(q)
        assert est >= true * (1.0 - 1e-9)
        assert est <= max(true * h.growth, h.max)  # one bucket of slack
    assert h.snapshot()["exact"] is False
    assert h.n == 500 and h.min == draws.min() and h.max == draws.max()


def test_histogram_nonpositive_and_empty():
    h = Histogram()
    assert h.percentile(50) is None and h.snapshot() is None
    h.add(0.0)
    h.add(-1.0)
    h.add(2.0)
    assert h.n == 3
    assert h.percentile(0) == -1.0  # exact reservoir covers them


def test_metrics_registry_get_or_create_and_type_guard():
    r = MetricsRegistry()
    c = r.counter("dispatches")
    c.inc()
    c.inc(2)
    assert r.counter("dispatches") is c and c.value == 3
    r.gauge("depth").set(5)
    r.rolling("wait").add(1.5)
    r.histogram("lat").add(0.25)
    with pytest.raises(TypeError, match="is Counter"):
        r.gauge("dispatches")
    snap = r.snapshot()
    assert snap["dispatches"] == 3 and snap["depth"] == 5
    assert snap["wait"]["n"] == 1 and snap["lat"]["p50"] == 0.25


def test_event_writer_schema_tag_and_torn_tail_reader(tmp_path):
    """Every line the writer emits carries schema: 2; the tolerant reader
    skips a torn last line (the SIGKILL artifact) instead of raising —
    the same discipline serve.journal applies to its WALs."""
    path = str(tmp_path / "m.jsonl")
    w = EventWriter(path)
    w.emit({"event": "enqueue", "t_s": 0.1, "user": "u0", "depth": 1,
            "cls": "batch"})
    w.emit({"event": "admit", "t_s": 0.2, "user": "u0", "width": 32,
            "wait_s": 0.1, "depth": 0, "live": 1, "cls": "batch"})
    w.close()
    with open(path, "ab") as f:
        f.write(b'{"event": "user_done", "t_s": 0.3, "use')  # torn tail
    recs = export.read_jsonl_tolerant(path)
    assert [r["event"] for r in recs] == ["enqueue", "admit"]
    assert all(r["schema"] == 2 for r in recs)
    assert export.validate_metrics(recs) == []
    # missing file reads empty, never raises
    assert export.read_jsonl_tolerant(str(tmp_path / "absent.jsonl")) == []


def test_schema_validation_catches_violations():
    ok = {"schema": 2, "event": "enqueue", "t_s": 1.0, "user": "u",
          "depth": 0, "cls": "batch"}
    assert export.validate_metrics([ok]) == []
    errs = export.validate_metrics([
        {"event": "enqueue", "t_s": 1.0, "user": "u", "depth": 0,
         "cls": "batch"},  # no tag
        {"schema": 2, "event": "warp_core_breach", "t_s": 1.0},  # unknown
        {"schema": 2, "event": "admit", "t_s": 1.0, "user": "u"},  # fields
        {"schema": 2, "event": "enqueue", "user": "u", "depth": 0,
         "cls": "batch"},  # t_s
    ])
    assert len(errs) >= 4
    assert any("schema tag" in e for e in errs)
    assert any("unknown event" in e for e in errs)
    assert any("lacks 'width'" in e for e in errs)
    assert any("lacks numeric t_s" in e for e in errs)
    # summaries are exempt from t_s
    assert export.validate_metrics(
        [{"schema": 2, "event": "fleet_summary", "users_done": 1}]) == []


def test_profiling_aliases_are_the_obs_classes():
    """The utils.profiling import surface survives the migration as thin
    aliases over obs.metrics/obs.trace."""
    from consensus_entropy_tpu.obs import metrics as obs_metrics
    from consensus_entropy_tpu.obs import trace as obs_trace
    from consensus_entropy_tpu.utils import profiling

    assert profiling.StepTimer is obs_metrics.StepTimer
    assert profiling.RollingStat is obs_metrics.RollingStat
    assert profiling.trace is obs_trace.device_trace


# -- tracer: deterministic ids, dedupe, export (no jax) --------------------


def test_trace_and_span_ids_deterministic():
    """Ids are pure functions of (run_id, user, iteration): two tracer
    instances (a run and its restart, or two fabric hosts) derive the
    SAME ids — the mechanism that makes resumed users continue their
    trace."""
    a = Tracer(None, run_id="mc-7", host="h0")
    b = Tracer(None, run_id="mc-7", host="h1")
    assert trace_id("mc-7", "u0") == trace_id("mc-7", "u0")
    assert trace_id("mc-7", "u0") != trace_id("mc-7", "u1")
    assert trace_id("mc-7", "u0") != trace_id("mc-8", "u0")
    assert a.user_ctx("u0").span == b.user_ctx("u0").span
    assert a.run_ctx.span == b.run_ctx.span
    s1 = a.begin("al_iter", parent=a.user_ctx("u0"), key=("u0", 3))
    s2 = b.begin("al_iter", parent=b.user_ctx("u0"), key=("u0", 3))
    assert s1.ctx.span == s2.ctx.span
    assert s1.ctx.trace == trace_id("mc-7", "u0")
    # auto-keyed (dispatch) spans never collide across tracers
    d1 = a.begin("score_dispatch")
    d2 = b.begin("score_dispatch")
    assert d1.ctx.span != d2.ctx.span


def test_null_tracer_is_inert():
    assert NULL_TRACER.begin("x") is None
    NULL_TRACER.end(None)
    NULL_TRACER.open_user("u")
    NULL_TRACER.close_user("u")
    NULL_TRACER.span_at("x", 0.0, 1.0)
    with NULL_TRACER.span("x") as ctx:
        assert ctx is None
    assert NULL_TRACER.records == []


def test_span_dedupe_keeps_longest_and_orphan_detection(tmp_path):
    """The merge collapses duplicate span ids (resume re-runs, fabric
    transcription) keeping the longest duration; a parent id missing
    from the merged set is reported as an orphan."""
    p1, p2 = str(tmp_path / "a.jsonl"), str(tmp_path / "b.jsonl")
    t = Tracer(p1, run_id="r", host="h0")
    t.open_user("u0", t0=1.0)
    sp = t.begin("al_iter", parent=t.user_ctx("u0"), key=("u0", 0))
    t.end(sp)
    t.close_user("u0")
    t.close()
    # a second attempt re-emits the same iteration, longer
    t2 = Tracer(p2, run_id="r", host="h1")
    sp2 = t2.begin("al_iter", parent=t2.user_ctx("u0"), key=("u0", 0))
    time.sleep(0.02)
    t2.end(sp2)
    t2.close_user("u0")  # never opened on h1: no record, no crash
    t2.close()
    spans = export.load_spans([p1, p2])
    iters = [s for s in spans if s["name"] == "al_iter"]
    assert len(iters) == 1  # deduped by deterministic id
    assert iters[0]["host"] == "h1"  # the longer (completed) attempt won
    assert export.orphan_spans(spans) == []
    # drop the user record: its children become orphans
    broken = [s for s in spans if s["name"] != "user"]
    assert [o["name"] for o in export.orphan_spans(broken)] == ["al_iter"]


def test_chrome_trace_export_schema_and_lanes(tmp_path):
    """The export is valid Chrome trace-event JSON: complete events with
    int ts/dur, one process per host with metadata naming, one thread
    lane per user/bucket/run."""
    p = str(tmp_path / "s.jsonl")
    t = Tracer(p, run_id="r", host="h0")
    t.open_user("u0")
    sp = t.begin("al_iter", parent=t.user_ctx("u0"), key=("u0", 0),
                 user="u0", epoch=0)
    t.end(sp)
    t.span_at("score_dispatch", time.time() - 0.01, time.time(),
              parent=t.run_ctx, fn="mc_masked", width=32, batch=2)
    t.close_user("u0")
    t.close()
    trace = export.chrome_trace(export.load_spans([p]))
    blob = json.loads(json.dumps(trace))  # round-trips as plain JSON
    evs = blob["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    ms = [e for e in evs if e["ph"] == "M"]
    assert {e["name"] for e in xs} == {"run", "user", "al_iter",
                                       "score_dispatch"}
    for e in xs:
        assert isinstance(e["ts"], int) and isinstance(e["dur"], int)
        assert e["dur"] >= 1 and isinstance(e["pid"], int)
    lane_names = {e["args"]["name"] for e in ms}
    assert "host h0" in lane_names
    assert "user u0" in lane_names and "bucket 32" in lane_names
    assert "run" in lane_names


def _assert_strictly_nested(spans, eps=1e-6):
    by_id = {s["span"]: s for s in spans}
    for s in spans:
        parent = by_id.get(s.get("parent"))
        if parent is None:
            continue
        assert parent["t0"] <= s["t0"] + eps, (s["name"], parent["name"])
        assert s["t0"] + s["dur_s"] \
            <= parent["t0"] + parent["dur_s"] + eps, \
            (s["name"], parent["name"])


# -- traced fleet/serve runs (jax) -----------------------------------------


def _traced_fleet_eviction(tmp_path):
    from consensus_entropy_tpu.al import workspace
    from consensus_entropy_tpu.fleet import FleetScheduler, FleetUser
    from consensus_entropy_tpu.resilience import faults
    from consensus_entropy_tpu.resilience.faults import FaultRule
    from tests.test_fleet import _cfg, _committee, _user_data

    cfg = _cfg(epochs=2)
    entries = []
    for i in range(2):
        data = _user_data(100 + i, f"u{i}")
        committee = (_committee(data, sgd_name="sgd.victim", min_members=2)
                     if i == 0 else _committee(data))
        fp = tmp_path / f"fleet_u{i}"
        fp.mkdir()
        entries.append(FleetUser(
            f"u{i}", committee, data, str(fp), seed=cfg.seed,
            committee_factory=lambda fp=fp: workspace.load_committee(
                str(fp))))
    spans_path = str(tmp_path / "spans.jsonl")
    tracer = Tracer(spans_path, run_id=f"{cfg.mode}-{cfg.seed}")
    sched = FleetScheduler(cfg, tracer=tracer, max_resumes=1)
    with faults.inject(FaultRule("member.retrain", "raise", at=1,
                                 member="sgd.victim")) as inj:
        recs = sched.run(entries)
    tracer.close()
    return recs, inj, spans_path


@pytest.mark.faults
def test_fleet_tracing_eviction_resume_continues_trace(tmp_path):
    """THE trace-continuity pin: a session evicted mid-iteration and
    resumed from its workspace keeps ONE trace id, the re-run iteration's
    span id collapses with its interrupted attempt at merge, and no span
    in the merged set is orphaned."""
    recs, inj, spans_path = _traced_fleet_eviction(tmp_path)
    assert inj.fired
    assert [r["error"] for r in recs] == [None, None]
    assert recs[0]["resumes"] == 1  # the eviction+resume actually ran
    raw = [r for r in export.read_jsonl_tolerant(spans_path)
           if r.get("ev") == "span"]
    spans = export.load_spans([spans_path])
    assert len(raw) > len(spans)  # the re-run emitted duplicate ids...
    by_user = {}
    for s in spans:
        if "user" in s:
            by_user.setdefault(s["user"], set()).add(s["trace"])
    # ...and each user still owns exactly ONE trace id
    assert {u: len(t) for u, t in by_user.items()} == {"u0": 1, "u1": 1}
    assert by_user["u0"] == {trace_id("mc-7", "u0")}
    assert export.orphan_spans(spans) == []
    # the merged trace holds one al_iter per (user, epoch) — no forked
    # iteration spans from the two attempts
    iters = [(s["user"], s["epoch"]) for s in spans
             if s["name"] == "al_iter"]
    assert len(iters) == len(set(iters))
    assert sorted(e for u, e in iters if u == "u0") == [-1, 0, 1]


def test_serve_tracing_spans_nest_and_latency_histogram(tmp_path):
    """A traced 3-user serve run: spans strictly nest under
    run→user→al_iter, admission waits ride the user span, the summary
    (and bench line) carry the admission→finish latency histogram, and
    the metrics stream validates against schema v2."""
    from consensus_entropy_tpu.fleet import (
        FleetReport,
        FleetScheduler,
        FleetUser,
    )
    from consensus_entropy_tpu.fleet.report import bench_line
    from consensus_entropy_tpu.serve import FleetServer, ServeConfig
    from tests.test_fleet import _cfg, _committee, _user_data

    cfg = _cfg(epochs=2)
    entries = []
    for i in range(3):
        data = _user_data(100 + i, f"u{i}")
        fp = tmp_path / f"serve_u{i}"
        fp.mkdir()
        entries.append(FleetUser(f"u{i}", _committee(data), data, str(fp),
                                 seed=cfg.seed))
    spans_path = str(tmp_path / "spans.jsonl")
    metrics_path = str(tmp_path / "fleet_metrics.jsonl")
    tracer = Tracer(spans_path, run_id=f"{cfg.mode}-{cfg.seed}")
    report = FleetReport(metrics_path)
    sched = FleetScheduler(cfg, report=report, scoring_by_width=True,
                           tracer=tracer)
    server = FleetServer(sched, ServeConfig(target_live=2))
    recs = server.serve(iter(entries))
    tracer.close()
    summary = report.write_summary(cohort=2)
    report.close()
    assert all(r["error"] is None for r in recs)
    # the latency histogram is the SLO prerequisite: per-run p50/p99
    lat = summary["admission_to_finish_s"]
    assert lat["n"] == 3
    assert 0 < lat["p50"] <= lat["p95"] <= lat["p99"] <= lat["max"]
    assert bench_line(summary)["admission_to_finish_s"] == lat
    assert export.validate_metrics_file(metrics_path) == []
    spans = export.load_spans([spans_path])
    names = {s["name"] for s in spans}
    assert {"run", "user", "al_iter", "admission_wait", "host_step",
            "checkpoint", "score_dispatch"} <= names
    assert len([s for s in spans if s["name"] == "user"]) == 3
    assert len([s for s in spans if s["name"] == "admission_wait"]) == 3
    assert export.orphan_spans(spans) == []
    _assert_strictly_nested(spans)
    # every span of a user's trace hangs off that user's deterministic id
    for s in spans:
        if s["name"] in ("al_iter", "admission_wait"):
            assert s["parent"] == tracer.user_ctx(s["user"]).span
    # the Chrome export of the run loads and keeps one host lane
    trace = json.loads(json.dumps(export.chrome_trace(spans)))
    assert len([e for e in trace["traceEvents"] if e["ph"] == "X"]) \
        == len(spans)
    # the text report renders without a backend
    text = export.text_report(str(tmp_path))
    assert "admission→finish p50=" in text and "spans:" in text


# -- the fabric worker-SIGKILL trace drill (slow) --------------------------


@pytest.mark.slow
@pytest.mark.serve
@pytest.mark.faults
def test_fabric_worker_sigkill_trace_continuity(tmp_path):
    """A real 2-host fabric with h0 SIGKILLed mid-iteration: the
    failed-over users CONTINUE their traces on the survivor (one trace id
    per user, spans from both hosts), the coordinator's transcription +
    the per-worker WALs merge with no orphans, and the merged Chrome
    trace carries one process lane per host."""
    from consensus_entropy_tpu.fleet import FleetReport
    from consensus_entropy_tpu.serve import (
        AdmissionJournal,
        FabricConfig,
        FabricCoordinator,
    )
    from consensus_entropy_tpu.serve.hosts import fabric_paths
    from tests.fabric_workload import make_cfg, user_specs
    from tests.test_serve_fabric import _spawn_factory, _with_deadline

    cfg = make_cfg("mc", epochs=2)
    specs = user_specs(3)
    fabric_dir = str(tmp_path / "fabric")
    os.makedirs(fabric_dir)
    journal = AdmissionJournal(os.path.join(fabric_dir,
                                            "serve_journal.jsonl"))
    spans_path = str(tmp_path / "spans.jsonl")
    tracer = Tracer(spans_path, run_id=f"{cfg.mode}-{cfg.seed}",
                    host="coordinator")
    trace_env = {"CETPU_OBS_TRACE": "1"}
    h0_spans = fabric_paths(fabric_dir, "h0")["spans"]
    state = {"done": False}

    def kill_h0_after_first_span(coord):
        # kill only once h0 has admitted a user AND flushed at least one
        # span — so the drill exercises a trace interrupted MID-flight,
        # not a host that died before tracing anything
        if state["done"]:
            return
        st = coord.journal.state
        admitted = any(h == "h0" and st.last.get(u) == "admit"
                       for u, h in st.assigned.items())
        if admitted and os.path.exists(h0_spans) \
                and os.path.getsize(h0_spans) > 0:
            coord.hosts["h0"].proc.kill()
            state["done"] = True

    coord = FabricCoordinator(
        journal, fabric_dir, FabricConfig(hosts=2, lease_s=5.0),
        report=FleetReport(), tracer=tracer,
        on_poll=_with_deadline(kill_h0_after_first_span))
    try:
        summary = coord.run(
            [u for _, u, _ in specs],
            _spawn_factory(fabric_dir, str(tmp_path), cfg, 3,
                           env_extra={"h0": trace_env, "h1": trace_env}))
    finally:
        tracer.close()
        journal.close()
    assert sorted(summary["finished"]) == [u for _, u, _ in specs]
    assert summary["revocations"] == 1
    assert state["done"], "the drill never killed h0"
    # merge = coordinator transcription + the per-worker WALs (either
    # alone would do; together they exercise the dedupe)
    span_files = [spans_path] + [
        os.path.join(fabric_dir, f"spans_h{i}.jsonl") for i in (0, 1)]
    assert all(os.path.exists(p) for p in span_files)
    spans = export.load_spans(span_files)
    assert export.orphan_spans(spans) == []
    by_user = {}
    hosts_of = {}
    for s in spans:
        if "user" in s:
            by_user.setdefault(s["user"], set()).add(s["trace"])
            hosts_of.setdefault(s["user"], set()).add(s.get("host"))
    # every user: exactly one trace id, even across the failover
    assert all(len(t) == 1 for t in by_user.values())
    assert len(by_user) == 3
    # at least one failed-over user has spans from BOTH hosts
    assert any({"h0", "h1"} <= h for h in hosts_of.values()), hosts_of
    # the al_iter set is complete and unforked per user
    iters = [(s["user"], s["epoch"]) for s in spans
             if s["name"] == "al_iter"]
    assert len(iters) == len(set(iters))
    for _, uid, _ in specs:
        assert sorted(e for u, e in iters if u == uid) == [-1, 0, 1]
    trace = export.chrome_trace(spans)
    host_lanes = {e["args"]["name"] for e in trace["traceEvents"]
                  if e["ph"] == "M" and e["name"] == "process_name"}
    assert {"host h0", "host h1"} <= host_lanes


# -- the report CLI --------------------------------------------------------


def test_report_cli_validate_export_and_text(tmp_path):
    """python -m consensus_entropy_tpu.cli.report over a synthetic users
    dir: schema validation passes, the Chrome trace is written, the text
    report prints; an invalid metrics line flips the exit code."""
    users = tmp_path / "users"
    users.mkdir()
    w = EventWriter(str(users / "fleet_metrics.jsonl"))
    w.emit({"event": "enqueue", "t_s": 0.1, "user": "u0", "depth": 1,
            "cls": "batch"})
    w.emit({"event": "fleet_summary", "users_done": 1, "wall_s": 1.0,
            "users_per_sec": 1.0, "phase_wall_s": {"score_s": 0.5}})
    w.close()
    t = Tracer(str(users / "spans.jsonl"), run_id="r")
    t.open_user("u0")
    t.close_user("u0")
    t.close()
    from consensus_entropy_tpu.cli.report import main

    out = str(tmp_path / "trace.json")
    assert main([str(users), "--validate", "--out", out]) == 0
    blob = json.load(open(out))
    assert any(e["ph"] == "X" for e in blob["traceEvents"])
    with open(users / "fleet_metrics.jsonl", "ab") as f:
        f.write(json.dumps({"schema": 2, "event": "nonsense"}).encode()
                + b"\n")
    assert main([str(users), "--validate", "--no-text"]) == 1
